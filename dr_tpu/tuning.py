"""Persisted tuning database: measured config winners as DATA
(docs/SPEC.md §21.6).

Every per-op config in the package — stencil chunk caps, scan chunk
rows, SpMV formats, the join merge-route threshold, relational
capacity ratios — has a code default that was tuned on SOME machine
at SOME point.  Mapple (arXiv:2507.17087) and Mesh-TensorFlow
(arXiv:1811.02084) both argue those mapping decisions should be
declarative data, not code: this module is that store.
``tools/tune_tpu.py`` writes measured winners in (:func:`record`),
dispatch-time pickers read them out (:func:`lookup`) with the code
default as fallback — so the queued silicon ladders (ROADMAP item 7)
become DB entries the moment the relay returns, with zero code edits.

Keying is canon-portable like the compile cache: an entry is
``domain.param@backend=<cpu|tpu|...>,nshards=<p>,x64=<0|1>`` — the
mesh shape/backend CONTEXT is part of the key, so a CPU-mesh sweep
can never poison the TPU entry for the same knob (and vice versa).
Lookups match the CURRENT context exactly; no context = no entry =
code default.

Storage is ONE json file beside the compile cache:
``DR_TPU_TUNING_DB`` names it directly, else it lives at
``$DR_TPU_COMPILE_CACHE_DIR/tuning_db.json``; with neither set the
persisted layer is off (lookups fall through to the in-process
session overlay, then the default).  Writes are atomic
read-modify-write (tmp + rename) with last-writer-wins per key; a
missing or corrupt file degrades to code defaults with ONE
``warn_fallback`` — a broken DB must never take a dispatch down.

Two layers answer a lookup, freshest first:

1. **session overlay** (:func:`note`) — in-process observations
   (e.g. the §21.4 capinfer pass noting a measured rows/input ratio
   so the next auto op skips its probe); never persisted.
2. **persisted entries** — what ``tune_tpu.py`` recorded.

Precedence at the integration sites is uniform: an explicit env pin
(``DR_TPU_*``) beats the DB, the DB beats the code default.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from .utils.env import env_str
from .utils.fallback import warn_fallback

__all__ = ["lookup", "record", "note", "context", "context_key",
           "db_path", "enabled", "reload", "clear_session"]

_lock = threading.Lock()
_cache: Optional[dict] = None
_cache_path: Optional[str] = None
_cache_mtime: float = -1.0
_warned_paths: set = set()
_session: dict = {}


def db_path() -> str:
    """The persisted DB file, or "" when no store is armed."""
    p = env_str("DR_TPU_TUNING_DB")
    if p:
        return p
    cache = env_str("DR_TPU_COMPILE_CACHE_DIR")
    if cache:
        return os.path.join(cache, "tuning_db.json")
    return ""


def enabled() -> bool:
    """True when a persisted store is armed (lookups/records hit
    disk); the session overlay works either way."""
    return bool(db_path())


def context() -> dict:
    """Canon-portable tag of the current mesh/backend: ``backend``
    (device platform), ``nshards`` (mesh width), ``x64``.  NEVER
    initializes the runtime (a lookup must not claim devices): before
    ``dr_tpu.init()`` the context is the unmatched ``backend="none"``
    — entries only land/apply on a live mesh."""
    try:
        from .parallel import runtime as _rt
        if not _rt.is_initialized():
            return {"backend": "none", "nshards": 0, "x64": False}
        import jax
        r = _rt.runtime()
        devs = list(r.mesh.devices.reshape(-1))
        return {"backend": str(devs[0].platform),
                "nshards": len(devs),
                "x64": bool(jax.config.jax_enable_x64)}
    except Exception:  # pragma: no cover - defensive
        return {"backend": "none", "nshards": 0, "x64": False}


def context_key(domain: str, param: str, ctx: Optional[dict] = None) \
        -> str:
    c = context() if ctx is None else ctx
    return (f"{domain}.{param}@backend={c.get('backend', 'none')},"
            f"nshards={int(c.get('nshards', 0))},"
            f"x64={int(bool(c.get('x64', False)))}")


def _load() -> dict:
    """The persisted entries (mtime-checked reload so a sweep's write
    in another process is visible without a restart).  Tolerant: any
    read failure warns ONCE per path and applies code defaults."""
    global _cache, _cache_path, _cache_mtime
    path = db_path()
    with _lock:
        try:
            mtime = os.path.getmtime(path) if path else -1.0
        except OSError:
            mtime = -1.0
        if _cache is not None and _cache_path == path \
                and _cache_mtime == mtime:
            return _cache
        _cache_path, _cache_mtime = path, mtime
        _cache = {}
        if not path or mtime < 0:
            return _cache
        try:
            with open(path, encoding="utf-8") as fh:
                raw = json.load(fh)
            ent = raw.get("entries") if isinstance(raw, dict) else None
            if not isinstance(ent, dict):
                raise ValueError("no 'entries' table")
            _cache = ent
        except Exception as e:
            if path not in _warned_paths:
                _warned_paths.add(path)
                warn_fallback(
                    "tuning", f"tuning DB at {path!r} is unreadable "
                              f"({e!r}); code defaults apply")
            _cache = {}
        return _cache


def reload() -> None:
    """Drop the read cache (tests; long-lived daemons after a sweep)."""
    global _cache
    with _lock:
        _cache = None


def clear_session() -> None:
    """Drop the in-process overlay (between-test hygiene)."""
    _session.clear()


def lookup(domain: str, param: str, default=None,
           ctx: Optional[dict] = None):
    """The measured value for ``domain.param`` under the current (or
    given) context, or ``default``.  Session overlay first (fresher),
    then the persisted store; context mismatch = default."""
    key = context_key(domain, param, ctx)
    if key in _session:
        return _session[key]
    ent = _load().get(key)
    if isinstance(ent, dict):
        return ent.get("value", default)
    return default if ent is None else ent


def note(domain: str, param: str, value,
         ctx: Optional[dict] = None) -> str:
    """Record an in-process observation (session overlay only — the
    capinfer ratio path).  Returns the key."""
    key = context_key(domain, param, ctx)
    _session[key] = value
    return key


def record(domain: str, param: str, value,
           ctx: Optional[dict] = None, source: str = "") -> Optional[str]:
    """Persist a measured winner (the ``tune_tpu.py`` write path):
    atomic read-modify-write, last-writer-wins per key, the context
    tag baked into the key (a CPU sweep cannot poison a TPU row).
    With no store armed the value still lands in the session overlay.
    Returns the key written (None = overlay only)."""
    c = context() if ctx is None else ctx
    key = context_key(domain, param, c)
    _session[key] = value
    path = db_path()
    if not path:
        return None
    with _lock:
        try:
            raw = {}
            if os.path.exists(path):
                try:
                    with open(path, encoding="utf-8") as fh:
                        raw = json.load(fh)
                except Exception:
                    raw = {}  # corrupt store: rebuilt from here on
            ent = raw.get("entries") if isinstance(raw, dict) else None
            if not isinstance(ent, dict):
                ent = {}
            ent[key] = {"value": value, "domain": domain,
                        "param": param, "context": dict(c),
                        "source": source,
                        "recorded_at": round(time.time(), 3)}
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"version": 1, "entries": ent}, fh,
                          indent=1, sort_keys=True)
                fh.write("\n")
            os.replace(tmp, path)
            global _cache
            _cache = None
        except OSError as e:
            warn_fallback(
                "tuning", f"tuning DB write to {path!r} failed "
                          f"({e!r}); winner kept in-process only")
            return None
    return key
