"""In-process span/event recorder — the tracing half of ``dr_tpu.obs``.

One bounded ring buffer of trace events (``collections.deque`` with an
env-capped ``maxlen`` — memory stays bounded under a 300-iteration fuzz
crank), monotonic clocks (``time.perf_counter_ns`` for every timestamp,
so spans survive wall-clock steps), and thread-aware nesting: each
thread carries its own span stack (implicit parents), while
cross-thread structure — the serving daemon's batch-flush span linking
back to each client request's span recorded on a reader thread — uses
EXPLICIT span ids (``begin``/``end``/``complete`` with ``parent=``,
plus Chrome flow events via :func:`flow`).

Overhead contract (docs/SPEC.md §15): with tracing OFF (the default)
every entry point is one module-global check and allocates NOTHING —
``span()`` returns a shared null context manager, ``begin`` returns 0,
``event``/``complete``/``end`` return immediately, and the hot-path
hooks in ``spmd_guard``/``faults`` stay ``None`` so the per-dispatch
cost is one ``is not None`` test.  :func:`events_recorded` is the
pin for that contract: a dispatch-count-style monotonic counter that
must not move while tracing is off.

Arming: :func:`install` (called at ``import dr_tpu``) arms when
``DR_TPU_TRACE=1`` and registers the process-exit Chrome-trace export
into ``DR_TPU_TRACE_DIR``; :func:`arm` is the programmatic switch
(tests, the serving daemon's stats sampling does NOT need it — the
metrics registry is always live for explicit handles).
"""

from __future__ import annotations

import atexit
import sys
import threading
import time
from collections import deque
from typing import List, Optional

from ..utils.env import env_flag, env_int

__all__ = ["armed", "arm", "install", "span", "begin", "end", "complete",
           "event", "flow", "now", "current", "tail", "events", "size",
           "events_recorded", "reset", "thread_names"]

#: THE module-level guard — every entry point checks it first.
_armed = False
_installed = False

_lock = threading.Lock()
#: the bounded event ring; maxlen re-read from DR_TPU_TRACE_BUF at arm()
_ring: deque = deque(maxlen=65536)
#: monotonic count of events ever recorded (ring may have dropped some)
_recorded = 0
_next_id = 1
#: open cross-thread spans: id -> (name, cat, tid, t0_ns, parent, attrs)
_open: dict = {}
#: tid -> thread name, for the exporter's metadata events
_tid_names: dict = {}

_tls = threading.local()


def armed() -> bool:
    return _armed


def now() -> int:
    """Recorder clock (perf_counter ns) when armed, else 0 — callers
    stash it to later emit a :func:`complete` span retroactively."""
    return time.perf_counter_ns() if _armed else 0


def events_recorded() -> int:
    """Monotonic count of trace events recorded in this process — the
    tracing-off no-op pin (must not move while tracing is off), in the
    mold of ``spmd_guard.dispatch_count``."""
    return _recorded


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current() -> int:
    """Span id at the top of THIS thread's span stack (0 = none)."""
    st = getattr(_tls, "stack", None)
    return st[-1][0] if st else 0


def _alloc_id() -> int:
    global _next_id
    with _lock:
        sid = _next_id
        _next_id += 1
    return sid


def _tid() -> int:
    t = threading.get_ident()
    if t not in _tid_names:
        _tid_names[t] = threading.current_thread().name
    return t


def _record(ev: dict) -> None:
    # the ONE choke point onto the ring, and the backstop for the
    # tracing-off no-op pin: a span begun while armed whose end()/
    # __exit__ lands after a disarm (an in-flight serve request across
    # the test fixture teardown) must not move the counter or the ring
    if not _armed:
        return
    global _recorded
    with _lock:
        _recorded += 1
        _ring.append(ev)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class _NullSpan:
    """Shared no-op context manager handed out while tracing is off —
    no per-call allocation on the disarmed path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NULL = _NullSpan()


class Span:
    """An armed context-manager span: nested via the thread-local
    stack (implicit parent), recorded as one completed ("X") event on
    exit.  ``set(**attrs)`` adds attributes before the record."""

    __slots__ = ("name", "cat", "attrs", "sid", "parent", "t0")

    def __init__(self, name: str, cat: str, parent: int, attrs: dict):
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.parent = parent
        self.sid = _alloc_id()
        self.t0 = 0

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        if self.parent == 0:
            self.parent = current()
        _stack().append((self.sid, self))
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, etype, evalue, tb) -> bool:
        t1 = time.perf_counter_ns()
        st = _stack()
        if st and st[-1][0] == self.sid:
            st.pop()
        if etype is not None:
            self.attrs.setdefault("error", etype.__name__)
        if self.parent:
            self.attrs.setdefault("parent", self.parent)
        _record({"ph": "X", "name": self.name, "cat": self.cat,
                 "id": self.sid, "tid": _tid(),
                 "ts": self.t0 // 1000, "dur": (t1 - self.t0) // 1000,
                 "args": self.attrs})
        return False


def span(name: str, cat: str = "", parent: int = 0, **attrs):
    """Context-manager span; returns a shared no-op when tracing is
    off.  ``parent=0`` nests under this thread's current span."""
    if not _armed:
        return _NULL
    return Span(name, cat, parent, attrs)


def begin(name: str, cat: str = "", parent: int = 0, **attrs) -> int:
    """Open a cross-thread span and return its id (0 when off).  The
    span does NOT join the caller's thread stack — it is closed by
    :func:`end` (any thread), which records the completed event."""
    if not _armed:
        return 0
    sid = _alloc_id()
    with _lock:
        _open[sid] = (name, cat, _tid(), time.perf_counter_ns(),
                      parent or current(), attrs)
    return sid


def end(sid: int, **attrs) -> None:
    """Close a :func:`begin` span (no-op for id 0 / unknown ids — a
    span begun before a disarm, or double-ended, must not raise)."""
    if sid == 0:
        return
    with _lock:
        entry = _open.pop(sid, None)
    if entry is None:
        return
    name, cat, tid, t0, parent, a = entry
    a.update(attrs)
    if parent:
        a.setdefault("parent", parent)
    t1 = time.perf_counter_ns()
    _record({"ph": "X", "name": name, "cat": cat, "id": sid, "tid": tid,
             "ts": t0 // 1000, "dur": (t1 - t0) // 1000, "args": a})


def complete(name: str, t0_ns: int, cat: str = "", parent: int = 0,
             t1_ns: Optional[int] = None, **attrs) -> None:
    """Record an already-elapsed span from a stashed :func:`now`
    timestamp (the serve queue-wait shape: start time known at submit,
    emitted at dispatch).  No-op when off or when ``t0_ns`` is 0 (the
    value :func:`now` hands out while disarmed)."""
    if not _armed or not t0_ns:
        return
    if parent:
        attrs.setdefault("parent", parent)
    t1 = t1_ns if t1_ns is not None else time.perf_counter_ns()
    _record({"ph": "X", "name": name, "cat": cat, "id": _alloc_id(),
             "tid": _tid(), "ts": t0_ns // 1000,
             "dur": max(0, (t1 - t0_ns) // 1000), "args": attrs})


def event(name: str, cat: str = "", **attrs) -> None:
    """Instant event (Chrome "i" phase)."""
    if not _armed:
        return
    _record({"ph": "i", "name": name, "cat": cat, "tid": _tid(),
             "ts": time.perf_counter_ns() // 1000, "s": "t",
             "args": attrs})


def flow(fid: int, phase: str, name: str = "serve.request") -> None:
    """Chrome flow event ("s" start / "f" finish) binding two slices —
    e.g. a request span on a reader thread to the batch-flush span on
    the dispatch thread.  ``fid`` is the linking id (use the source
    span's id)."""
    if not _armed or fid == 0 or phase not in ("s", "t", "f"):
        return
    ev = {"ph": phase, "name": name, "cat": "flow", "id": fid,
          "tid": _tid(), "ts": time.perf_counter_ns() // 1000}
    if phase == "f":
        ev["bp"] = "e"  # bind to the enclosing slice
    _record(ev)


# ---------------------------------------------------------------------------
# inspection
# ---------------------------------------------------------------------------

def events() -> List[dict]:
    """Snapshot (shallow copy) of the ring's current contents."""
    with _lock:
        return list(_ring)


def size() -> int:
    """Current ring occupancy — O(1), no copy (snapshots want the
    count without paying a full-ring materialization under the
    lock)."""
    with _lock:
        return len(_ring)


def tail(n: Optional[int] = None) -> List[dict]:
    """The last ``n`` recorded events (default ``DR_TPU_TRACE_TAIL``,
    40) — the postmortem classified errors attach.  islice from the
    computed offset, NOT ``list(_ring)[-n:]``: every classified error
    constructed while traced pays this under the recorder lock, and a
    full-ring copy per retried transient would stall concurrent
    event recording."""
    if n is None:
        n = env_int("DR_TPU_TRACE_TAIL", 40)
    from itertools import islice
    with _lock:
        return list(islice(_ring, max(0, len(_ring) - n), None))


def thread_names() -> dict:
    return dict(_tid_names)


def reset() -> None:
    """Drop every recorded event and open span (tests; the monotonic
    :func:`events_recorded` counter is NOT reset)."""
    with _lock:
        _ring.clear()
        _open.clear()


# ---------------------------------------------------------------------------
# hooks into the hot-path modules (spmd_guard / faults)
# ---------------------------------------------------------------------------

def _key_label(key) -> str:
    """Cheap, allocation-light label for a dispatch key: the leading
    tag string of the conventional tuple keys, else the type name —
    NOT repr (container-sized splice keys would be slow to format)."""
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        return key[0]
    return type(key).__name__


def _on_dispatch(key) -> None:
    event("dispatch", cat="dispatch", key=_key_label(key))


def _on_compile(key) -> None:
    event("compile", cat="dispatch", key=_key_label(key))


def _on_site(site: str, ctx: dict) -> None:
    # dispatch.cache (and device.lost, which rides the same tap) visits
    # are already on the trace through the spmd_guard dispatch hook —
    # the site echo would double every entry
    if site in ("dispatch.cache", "device.lost"):
        return
    # ctx keys are site-chosen and may collide with event()'s own
    # parameters (a site firing with name=... must not TypeError the
    # traced hot path) — prefix the reserved ones
    event(site, cat="site",
          **{(f"ctx_{k}" if k in ("name", "cat") else k): str(v)[:80]
             for k, v in ctx.items()})


def _on_fault(site: str, kind: str) -> None:
    event("fault", cat="fault", site=site, kind=kind)


def arm(on: bool = True) -> None:
    """Flip the module guard and (un)install the spmd_guard/faults
    hooks.  Arming re-reads ``DR_TPU_TRACE_BUF`` so tests can pin a
    small ring; the existing contents are kept (tail-truncated)."""
    global _armed, _ring
    from ..utils import faults, spmd_guard
    if on:
        cap = env_int("DR_TPU_TRACE_BUF", 65536, floor=16)
        with _lock:
            if _ring.maxlen != cap:
                _ring = deque(_ring, maxlen=cap)
        _armed = True
        spmd_guard._obs_dispatch_hook = _on_dispatch
        spmd_guard._obs_compile_hook = _on_compile
        faults._obs_site_hook = _on_site
        faults._obs_fault_hook = _on_fault
    else:
        _armed = False
        spmd_guard._obs_dispatch_hook = None
        spmd_guard._obs_compile_hook = None
        faults._obs_site_hook = None
        faults._obs_fault_hook = None


def _atexit_export() -> None:  # pragma: no cover - process teardown
    from . import export
    try:
        path = export.write()
        print(f"dr_tpu.obs: trace written to {path}", file=sys.stderr)
    except OSError as e:
        print(f"dr_tpu.obs: trace export failed: {e!r}", file=sys.stderr)


def install() -> bool:
    """Arm from the environment (``DR_TPU_TRACE=1``) at import time and
    register the process-exit Chrome-trace export; idempotent; returns
    whether tracing is armed."""
    global _installed
    if _installed or not env_flag("DR_TPU_TRACE"):
        return _armed
    arm(True)
    atexit.register(_atexit_export)
    _installed = True
    return True
