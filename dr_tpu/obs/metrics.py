"""Metrics registry — counters, gauges, bucketed histograms.

Two usage tiers, matching the overhead contract (docs/SPEC.md §15):

* **Handles** (:func:`counter` / :func:`gauge` / :func:`histogram`)
  are ALWAYS live: a caller that holds one (the serving daemon's
  per-request queue-wait/service/flush samples) records regardless of
  ``DR_TPU_TRACE`` — those sites are request-rate, not dispatch-rate,
  and their numbers feed ``bench.py --serve`` / the ``stats`` wire op
  on every run.
* The **guarded conveniences** live in ``dr_tpu.obs``
  (``count``/``gauge_set``/``observe``): one armed-check no-ops them
  while tracing is off, for instrumentation on hotter paths
  (plan flushes, retries, fallbacks).

:func:`snapshot` renders the whole registry as a compact,
JSON-serializable dict — the ``detail.obs`` bench artifact block and
the serve ``stats`` op's ``obs`` field.  Histograms report count /
sum / min / max, fixed log-spaced bucket counts, and p50/p95/p99
estimated from a bounded reservoir of recent samples.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "counter", "gauge",
           "histogram", "snapshot", "reset", "DEFAULT_BUCKETS"]

#: log-spaced bucket upper bounds (unit-agnostic; the serve histograms
#: record milliseconds).  An implicit +inf bucket catches the rest.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
    1000.0, 5000.0)

#: bounded per-histogram sample reservoir for percentile estimates
_RESERVOIR = 512


class Counter:
    """Locked add: counters are bumped from multiple threads (the
    serve dispatch thread next to host-thread plan flushes), and an
    unguarded ``value += n`` read-add-store can drop increments across
    a GIL switch — silently corrupting the very diagnostics these
    exist to report."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        # a plain store is atomic under the GIL — no lock needed
        self.value = float(v)


class Histogram:
    """Bucketed histogram + bounded recent-sample reservoir.  One lock
    per observe — these sit on request-rate paths, not dispatch-rate."""

    __slots__ = ("name", "bounds", "bucket_counts", "count", "total",
                 "vmin", "vmax", "_samples", "_lock")

    def __init__(self, name: str, buckets: Optional[Tuple[float, ...]]
                 = None):
        self.name = name
        self.bounds = tuple(buckets) if buckets else DEFAULT_BUCKETS
        self.bucket_counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None
        self._samples: deque = deque(maxlen=_RESERVOIR)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if self.vmin is None or v < self.vmin:
                self.vmin = v
            if self.vmax is None or v > self.vmax:
                self.vmax = v
            i = 0
            for b in self.bounds:
                if v <= b:
                    break
                i += 1
            self.bucket_counts[i] += 1
            self._samples.append(v)

    def snapshot(self) -> dict:
        with self._lock:
            s = sorted(self._samples)
            out = {"count": self.count,
                   "sum": round(self.total, 6),
                   "min": (None if self.vmin is None
                           else round(self.vmin, 6)),
                   "max": (None if self.vmax is None
                           else round(self.vmax, 6)),
                   "buckets": {("le_%g" % b): c for b, c in
                               zip(self.bounds, self.bucket_counts)
                               if c},
                   }
            if self.bucket_counts[-1]:
                out["buckets"]["le_inf"] = self.bucket_counts[-1]
        for p, q in (("p50", 50), ("p95", 95), ("p99", 99)):
            out[p] = (round(s[min(len(s) - 1,
                                  int(round(q / 100.0 * (len(s) - 1))))],
                            6) if s else None)
        return out


_lock = threading.Lock()
_counters: Dict[str, Counter] = {}
_gauges: Dict[str, Gauge] = {}
_hists: Dict[str, Histogram] = {}


def counter(name: str) -> Counter:
    c = _counters.get(name)
    if c is None:
        with _lock:
            c = _counters.setdefault(name, Counter(name))
    return c


def gauge(name: str) -> Gauge:
    g = _gauges.get(name)
    if g is None:
        with _lock:
            g = _gauges.setdefault(name, Gauge(name))
    return g


def histogram(name: str, buckets: Optional[Tuple[float, ...]] = None
              ) -> Histogram:
    h = _hists.get(name)
    if h is None:
        with _lock:
            h = _hists.setdefault(name, Histogram(name, buckets))
    return h


def snapshot() -> dict:
    """Compact JSON-serializable registry dump (empty sections are
    omitted so an idle process snapshots to nearly nothing)."""
    out: dict = {}
    with _lock:
        cs = {n: c.value for n, c in _counters.items() if c.value}
        gs = {n: g.value for n, g in _gauges.items()}
        hs = list(_hists.values())
    if cs:
        out["counters"] = cs
    if gs:
        out["gauges"] = gs
    rendered = {h.name: h.snapshot() for h in hs if h.count}
    if rendered:
        out["histograms"] = rendered
    return out


def reset() -> None:
    """Zero every registered metric IN PLACE (tests).  Registrations
    are kept: modules hold handles at import time (the serve daemon's
    histograms) — dropping the registry entries would orphan those
    handles and silently stop their numbers reaching snapshots."""
    with _lock:
        cs = list(_counters.values())
        for g in _gauges.values():
            g.value = 0.0  # plain store: atomic under the GIL
        hs = list(_hists.values())
    for c in cs:
        # under the counter's OWN lock: an unlocked zero racing a
        # concurrent locked add() could resurrect the pre-reset count
        with c._lock:
            c.value = 0
    for h in hs:
        with h._lock:
            h.bucket_counts = [0] * (len(h.bounds) + 1)
            h.count = 0
            h.total = 0.0
            h.vmin = h.vmax = None
            h._samples.clear()
