"""Exporters: Chrome trace-event JSON + the compact metrics snapshot.

The trace file is the standard Chrome ``traceEvents`` object format —
open it in ``chrome://tracing`` or https://ui.perfetto.dev.  Recorded
events are already one dict per Chrome event (recorder.py), so export
only adds the shared ``pid`` and thread-name metadata events.  Flow
start/finish pairs (the Perfetto cross-thread arrows for the serve
batch-flush → request linkage) are recorded at INSTRUMENTATION time
via ``recorder.flow`` — they pass through here untouched, and the
span-level ``args.links`` lists exist for tools/trace_view.py, which
joins on them instead of the flow events.

The metrics snapshot (:func:`metrics_snapshot`) is the ``detail.obs``
block ``bench.py`` embeds in every artifact and the ``obs`` field of
the serve ``stats`` wire op: the metrics registry plus the
process-lifetime dispatch/compile counters and the recorder's
ring-buffer accounting.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import List, Optional

from ..utils.env import env_str
from . import metrics, recorder

__all__ = ["trace_dir", "chrome_trace", "write", "metrics_snapshot"]


def trace_dir() -> str:
    """``DR_TPU_TRACE_DIR``, or the system temp dir (exports must land
    somewhere writable without polluting the working tree)."""
    return env_str("DR_TPU_TRACE_DIR") or tempfile.gettempdir()


def chrome_trace(events: Optional[List[dict]] = None) -> dict:
    """Render recorded events as a Chrome ``traceEvents`` object."""
    if events is None:
        events = recorder.events()
    pid = os.getpid()
    out = []
    for tid, name in sorted(recorder.thread_names().items()):
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "args": {"name": name}})
    for ev in events:
        e = dict(ev)
        e["pid"] = pid
        out.append(e)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"producer": "dr_tpu.obs",
                          "events_recorded": recorder.events_recorded()}}


def write(path: Optional[str] = None,
          events: Optional[List[dict]] = None) -> str:
    """Write the Chrome trace JSON; default path is
    ``<trace_dir>/dr_tpu_trace_<pid>.json``.  Returns the path."""
    if path is None:
        path = os.path.join(trace_dir(),
                            f"dr_tpu_trace_{os.getpid()}.json")
    doc = chrome_trace(events)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
        fh.write("\n")
    return path


def metrics_snapshot() -> dict:
    """The compact observability snapshot: metrics registry + the
    always-on dispatch/compile counters + ring accounting."""
    from ..utils import spmd_guard
    snap = metrics.snapshot()
    snap["dispatches"] = spmd_guard.dispatch_count()
    snap["compiles"] = spmd_guard.compile_count()
    snap["trace_armed"] = recorder.armed()
    if recorder.armed():
        snap["events_recorded"] = recorder.events_recorded()
        snap["events_buffered"] = recorder.size()
    return snap
