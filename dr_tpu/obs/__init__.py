"""``dr_tpu.obs`` — unified tracing & metrics (docs/SPEC.md §15).

The repo had five disjoint observability signals (profiling phase
breakdowns, the spmd_guard dispatch/compile counters, degradation-story
markers, ``plan.explain()``, the serve ``stats`` op) and no way to see
one request's life end-to-end.  This package is the one spine they all
feed:

* **spans & events** (``recorder``): a thread-aware in-process span
  recorder over a bounded ring buffer, armed by ``DR_TPU_TRACE=1``.
  Instrumentation rides the existing hook points — every TappedCache
  dispatch/compile (``spmd_guard``), every fault-registry site visit
  AND every injected fault (``utils/faults`` — a ``DR_TPU_FAULT_SPEC``
  injection appears *in* the trace), plan record/flush, retry/deadline
  attempts, fallback warns, serve request lifecycles, the elastic
  re-layout spans (``mesh.shrink`` with the device-loss event inside
  it, ``mesh.grow`` with the recovery event — docs/SPEC.md §16/§16.6),
  and ``drlog`` debug lines as instant events.
* **metrics** (``metrics``): counters, gauges, bucketed histograms.
  Handles are always live (the serve daemon samples queue-wait /
  service / flush time per request on every run); the module-level
  conveniences here (:func:`count` / :func:`gauge_set` /
  :func:`observe`) are armed-gated for hotter paths.
* **exporters** (``export``): Chrome trace-event JSON into
  ``DR_TPU_TRACE_DIR`` (Perfetto-openable; auto-written at process
  exit when env-armed) and the compact :func:`snapshot` that
  ``bench.py`` embeds as ``detail.obs`` and the serve ``stats`` wire
  op returns.

Overhead contract: tracing off = one module-global check per entry
point, zero per-event allocation (pinned by
``recorder.events_recorded``), and ``None`` hot-path hooks.
"""

from __future__ import annotations

from . import export, metrics, recorder
from .export import chrome_trace, metrics_snapshot, trace_dir, write
from .recorder import (arm, armed, begin, complete, current, end, event,
                       events, events_recorded, flow, install, now,
                       reset as _reset_ring, size, span, tail)

__all__ = ["arm", "armed", "begin", "complete", "count", "current",
           "end", "event", "events", "events_recorded", "export",
           "export_chrome_trace", "flow", "gauge_set", "install",
           "metrics", "now", "observe", "recorder", "reset", "size",
           "snapshot", "span", "tail", "trace_dir", "chrome_trace",
           "metrics_snapshot", "write"]


# ------------------------------------------------------- armed-gated metrics

def count(name: str, n: int = 1) -> None:
    """Armed-gated counter bump (one check when tracing is off)."""
    if recorder._armed:
        metrics.counter(name).add(n)


def gauge_set(name: str, v: float) -> None:
    if recorder._armed:
        metrics.gauge(name).set(v)


def observe(name: str, v: float) -> None:
    """Armed-gated histogram observation."""
    if recorder._armed:
        metrics.histogram(name).observe(v)


def snapshot() -> dict:
    """The compact observability snapshot (``detail.obs`` /
    serve ``stats.obs``): metrics registry + dispatch/compile counts +
    trace-ring accounting.  Always available — cheap when idle."""
    return export.metrics_snapshot()


def export_chrome_trace(path=None) -> str:
    """Write the Chrome trace JSON (default into :func:`trace_dir`);
    returns the written path."""
    return export.write(path)


def reset() -> None:
    """Clear the trace ring AND the metrics registry (tests)."""
    _reset_ring()
    metrics.reset()
