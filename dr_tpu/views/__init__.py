from . import views
from .views import (take, drop, subrange, slice_view, transform, zip_view,
                    enumerate_view, iota_view, aligned, local_segments,
                    take_segments, drop_segments, ranked_view,
                    segment_id, segment_range, segment_ranges)
