"""Segment-preserving views over distributed ranges.

TPU re-design of the reference's view stack:

* ``take_segments`` / ``drop_segments`` / subrange recomputation
  (``include/dr/details/segments_tools.hpp:38-94,149-223``),
* ``zip_view`` with aligned segmentation (``include/dr/shp/zip_view.hpp``;
  misaligned zip yields EMPTY segments — segments_tools.hpp:117-121 — which
  is exactly the ``aligned()`` signal, mhp/alignment.hpp:8-28),
* segment-preserving ``transform_view`` (``include/dr/views/transform.hpp``),
* ``views::slice`` / ``take`` / ``drop`` / ``enumerate`` adaptors
  (``shp/views/standard_views.hpp``, ``shp/views/enumerate.hpp``),
* ``local_segments`` (``mhp/views.hpp:9-21``) and the debug ``ranked_view``
  (``views/views.hpp:7-11``).

Views are lazy metadata: they recompute ``segments()`` and know how to
produce their logical value as a jax expression (``to_array``), so whole
view pipelines (zip | transform | reduce) can be fused into one XLA program
by the algorithm layer.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.segment import Segment, ZipSegment
from ..core.vocabulary import local, rank, segments

__all__ = [
    "take", "drop", "subrange", "slice_view", "transform", "zip_view",
    "zip", "enumerate_view", "enumerate", "iota_view", "counted",
    "take_segments", "drop_segments", "aligned", "local_segments",
    "ranked_view", "BoundOp",
]


class BoundOp:
    """``op`` with trailing scalar arguments bound: calling it behaves
    exactly like ``lambda *a: op(*a, *scalars)``, but it keeps the op
    and the scalars inspectable — the algorithm layer's program caches
    key on the OP identity plus the scalar COUNT and feed the values as
    traced operands, so a loop streaming coefficients through a view
    pipeline (``reduce(views.transform(r, f, mu))`` per step) reuses
    one compiled program instead of recompiling per value."""

    __slots__ = ("op", "scalars")

    def __init__(self, op: Callable, scalars: Sequence):
        self.op = op
        self.scalars = tuple(scalars)

    def __call__(self, *args):
        return self.op(*args, *self.scalars)


# ---------------------------------------------------------------------------
# segment recomputation tools (segments_tools.hpp:38-94)
# ---------------------------------------------------------------------------

def take_segments(segs: Sequence, n: int):
    """First ``n`` elements of a segment list, trimming the cut segment."""
    out, remaining = [], n
    for s in segs:
        if remaining <= 0:
            break
        k = min(len(s), remaining)
        out.append(s[:k] if k != len(s) else s)
        remaining -= k
    return out

def drop_segments(segs: Sequence, n: int):
    """Drop the first ``n`` elements of a segment list."""
    out, todrop = [], n
    for s in segs:
        if todrop >= len(s):
            todrop -= len(s)
            continue
        out.append(s[todrop:] if todrop else s)
        todrop = 0
    return out


# ---------------------------------------------------------------------------
# view classes
# ---------------------------------------------------------------------------

class _ViewBase:
    base: Any

    def __len__(self) -> int:
        raise NotImplementedError

    def __dr_segments__(self):
        raise NotImplementedError

    def to_array(self):
        raise NotImplementedError

    def materialize(self):
        from ..utils.host import to_host
        arr = self.to_array()
        if isinstance(arr, tuple):
            return tuple(to_host(a) for a in arr)
        return to_host(arr)

    def __iter__(self):
        m = self.materialize()
        if isinstance(m, tuple):
            return iter(builtin_zip(*m))
        return iter(m)

    def __getitem__(self, key):
        if isinstance(key, slice):
            start, stop, step = key.indices(len(self))
            assert step == 1
            return subrange(self, start, stop)
        m = self.to_array()
        if isinstance(m, tuple):
            return tuple(a[key].item() for a in m)
        return m[key].item()


builtin_zip = zip
builtin_enumerate = enumerate


class subrange(_ViewBase):
    """Window [start, stop) over a distributed range (take/drop/subrange)."""

    def __init__(self, base: Any, start: int, stop: int):
        n = len(base)
        start = max(0, min(start, n))
        stop = max(start, min(stop, n))
        # collapse nested windows so ``base`` stays close to the container
        if isinstance(base, subrange):
            start += base.start
            stop += base.start
            base = base.base
        self.base = base
        self.start = start
        self.stop = stop

    def __len__(self):
        return self.stop - self.start

    def __dr_segments__(self):
        segs = segments(self.base)
        return take_segments(drop_segments(segs, self.start), len(self))

    def to_array(self):
        arr = self.base.to_array()
        if isinstance(arr, tuple):
            return tuple(a[self.start:self.stop] for a in arr)
        return arr[self.start:self.stop]


def take(r, n=None):
    if n is None:
        return _Pipe(lambda rr: subrange(rr, 0, r))
    return subrange(r, 0, n)


def drop(r, n=None):
    if n is None:
        return _Pipe(lambda rr: subrange(rr, r, len(rr)))
    return subrange(r, n, len(r))


def slice_view(r, bounds=None):
    """``views::slice(r, (a, b))`` (shp/views/standard_views.hpp:19-44)."""
    if bounds is None:
        a, b = r
        return _Pipe(lambda rr: subrange(rr, a, b))
    a, b = bounds
    return subrange(r, a, b)


def counted(it_range, n):
    """rng::views::counted analog over our ranges."""
    return subrange(it_range, 0, n)


class transform(_ViewBase):
    """Lazy elementwise transform that stays distributed
    (views/transform.hpp:9-43).  ``op`` must be jax-traceable; over a zip
    base it receives one argument per component.  Trailing ``*scalars``
    bind extra arguments (:class:`BoundOp`): the fused algorithm
    programs receive them TRACED, so per-call coefficient streams reuse
    one compiled program."""

    def __init__(self, base: Any, op: Callable = None, *scalars):
        if op is None:
            # the adaptor form transform(op) is handled in __new__; reaching
            # here means a single non-callable argument
            raise TypeError("transform(range, op) or transform(op) | range")
        if not callable(op):
            # fail at the misuse site: the adaptor form takes NO scalars
            # (transform(op, 0.5) | r would land here with op=0.5)
            raise TypeError(
                "transform op must be callable; the pipe-adaptor form "
                "does not take scalars — use transform(range, op, *scalars)")
        self.base = base
        self.op = BoundOp(op, scalars) if scalars else op

    def __new__(cls, base=None, op=None, *scalars):
        if op is None and callable(base) and not hasattr(base, "__dr_segments__") \
                and not hasattr(base, "to_array"):
            return _Pipe(lambda rr: cls(rr, base))
        return super().__new__(cls)

    def __len__(self):
        return len(self.base)

    def __dr_segments__(self):
        out = []
        for s in segments(self.base):
            if isinstance(s, Segment):
                out.append(s.with_op(self.op))
            elif isinstance(s, ZipSegment):
                out.append(_MappedZipSegment(s, self.op))
            else:
                out.append(_MappedZipSegment(s, self.op))
        return out

    def to_array(self):
        arr = self.base.to_array()
        if isinstance(arr, tuple):
            return self.op(*arr)
        return self.op(arr)


class _MappedZipSegment:
    """ZipSegment with an elementwise op over the component tuple."""

    __slots__ = ("inner", "op")

    def __init__(self, inner, op):
        self.inner = inner
        self.op = op

    def __dr_rank__(self):
        return rank(self.inner)

    def __dr_local__(self):
        vals = local(self.inner)
        return self.op(*vals) if isinstance(vals, tuple) else self.op(vals)

    def __len__(self):
        return len(self.inner)

    def __getitem__(self, key):
        if isinstance(key, slice):
            return _MappedZipSegment(self.inner[key], self.op)
        return self.materialize()[key]

    def __iter__(self):
        return iter(self.materialize())

    def materialize(self):
        vals = self.inner.materialize()
        if isinstance(vals, tuple):
            return np.asarray(self.op(*[jnp.asarray(v) for v in vals]))
        return np.asarray(self.op(jnp.asarray(vals)))


class zip_view(_ViewBase):
    """Rank-aware zip (shp/zip_view.hpp).  Misaligned inputs yield empty
    ``segments()`` — the ``aligned()`` signal — while ``to_array`` still
    works (the slow path resharding is XLA's job, not serial RMA)."""

    def __init__(self, *ranges):
        assert ranges
        self.components = tuple(ranges)
        self.base = ranges[0]

    def __len__(self):
        return min(len(r) for r in self.components)

    def __dr_segments__(self):
        n = len(self)
        seg_lists = []
        for r in self.components:
            try:
                segs = segments(r)
            except TypeError:
                return []  # zipping with a non-distributed range
            seg_lists.append(take_segments(segs, n))
        first = seg_lists[0]
        shape = [(rank(s), len(s)) for s in first]
        for other in seg_lists[1:]:
            if [(rank(s), len(s)) for s in other] != shape:
                return []  # misaligned (segments_tools.hpp:117-121)
        return [ZipSegment(*parts) for parts in builtin_zip(*seg_lists)]

    def zipped_segments(self):
        return self.__dr_segments__()

    def to_array(self):
        n = len(self)
        arrs = []
        for r in self.components:
            a = r.to_array()
            assert not isinstance(a, tuple), "nested zip: flatten first"
            arrs.append(a[:n])
        return tuple(arrs)


zip = zip_view


class iota_view(_ViewBase):
    """Counting range whose segmentation mirrors ``like`` — the building
    block of ``enumerate`` (details/enumerate.hpp:27-58)."""

    def __init__(self, start: int, n: int, like: Any = None, dtype=jnp.int32):
        self.start = start
        self._n = n
        self.like = like
        self.dtype = dtype
        self.base = None

    def __len__(self):
        return self._n

    def __dr_segments__(self):
        if self.like is None:
            return [Segment(self, 0, 0, self._n)]
        out = []
        for s in take_segments(segments(self.like), self._n):
            out.append(Segment(self, rank(s), s.begin, s.end))
        return out

    # acts as its own "container" for Segment plumbing
    def _host_values(self, begin, end):
        return np.arange(self.start + begin, self.start + end,
                         dtype=np.dtype(self.dtype))

    def _local_values(self, rank_, begin, end):
        return jnp.arange(self.start + begin, self.start + end,
                          dtype=self.dtype)

    def to_array(self):
        return jnp.arange(self.start, self.start + self._n, dtype=self.dtype)


class segment_id:
    """A position inside one segment: (segment, local_id, global id) —
    ``shp::id<1>`` (shp/range.hpp:12-33).  Converts to the global index."""

    __slots__ = ("segment", "local_id", "global_id")

    def __init__(self, segment: int, local_id: int, global_id: int):
        self.segment = segment
        self.local_id = local_id
        self.global_id = global_id

    def __index__(self):
        return self.global_id

    def __int__(self):
        return self.global_id

    def __eq__(self, other):
        if isinstance(other, segment_id):
            return (self.segment, self.local_id, self.global_id) == \
                (other.segment, other.local_id, other.global_id)
        return self.global_id == other

    def __hash__(self):
        # consistent with the int-comparison branch of __eq__
        return hash(self.global_id)

    def __repr__(self):
        return (f"segment_id(segment={self.segment}, "
                f"local={self.local_id}, global={self.global_id})")


class segment_range:
    """Range of :class:`segment_id` values for one segment
    (shp/range.hpp:97-130): ``segment_range(seg_id, size, global_offset)``
    yields ids (seg_id, 0..size-1, global_offset + local)."""

    def __init__(self, seg_id: int, segment_size: int, global_offset: int):
        self.segment_id = seg_id
        self.segment_size = segment_size
        self.global_offset = global_offset

    def __len__(self):
        return self.segment_size

    def __getitem__(self, idx: int):
        if idx < 0:
            idx += self.segment_size
        if not 0 <= idx < self.segment_size:
            raise IndexError(idx)
        return segment_id(self.segment_id, idx, self.global_offset + idx)

    def __iter__(self):
        return (self[i] for i in range(self.segment_size))

    def rank(self):  # reference: always 0 (shp/range.hpp:124)
        return 0


def segment_ranges(r):
    """One :class:`segment_range` per segment of ``r`` — the natural use
    of the reference's utility: segment-local ids with global offsets."""
    out, pos = [], 0
    for i, s in builtin_enumerate(segments(r)):
        out.append(segment_range(i, len(s), pos))
        pos += len(s)
    return out


class enumerate_view(zip_view):
    """zip(iota, r) (shp/views/enumerate.hpp:27-52)."""

    def __init__(self, r):
        super().__init__(iota_view(0, len(r), like=r), r)


def enumerate(r=None):
    if r is None:
        return _Pipe(enumerate_view)
    return enumerate_view(r)


class ranked_view(zip_view):
    """(owning-rank, value) pairs for debugging (views/views.hpp:7-11)."""

    def __init__(self, r):
        ranks = _rank_of_view(r)
        super().__init__(ranks, r)


class _rank_of_view(_ViewBase):
    """Per-element owning rank of ``like``; positions derive from segment
    ORDER (cumulative lengths), so any segment type works (zips included)."""

    def __init__(self, like):
        self.like = like
        self.base = None
        segs = segments(like)
        if not segs:
            raise ValueError("ranked_view: range has no segments "
                             "(misaligned zip?)")
        self._bounds = []
        pos = 0
        for s in segs:
            self._bounds.append((pos, pos + len(s), rank(s)))
            pos += len(s)

    def __len__(self):
        return len(self.like)

    def __dr_segments__(self):
        return [Segment(self, r, lo, hi) for lo, hi, r in self._bounds]

    def _host_values(self, begin, end):
        vals = np.empty(end - begin, dtype=np.int32)
        for lo, hi, r in self._bounds:
            a, b = max(lo, begin), min(hi, end)
            if a < b:
                vals[a - begin:b - begin] = r
        return vals

    def _local_values(self, rank_, begin, end):
        return jnp.full((end - begin,), rank_, dtype=jnp.int32)

    def to_array(self):
        return jnp.asarray(self._host_values(0, len(self)))


class _Pipe:
    """Pipeable view adaptor: ``dv | views.take(3) | views.transform(f)``."""

    def __init__(self, fn):
        self.fn = fn

    def __ror__(self, r):
        return self.fn(r)

    def __call__(self, r):
        return self.fn(r)


# ---------------------------------------------------------------------------
# alignment + local segments
# ---------------------------------------------------------------------------

def aligned(*ranges) -> bool:
    """True iff all ranges have pairwise rank/size-equal segment lists
    (mhp/alignment.hpp:13-28).  An empty segment list (misaligned zip)
    is not aligned (mhp/alignment.hpp:8-10)."""
    shapes = []
    for r in ranges:
        if hasattr(r, "__iter__") and not hasattr(r, "__dr_segments__") \
                and not hasattr(r, "to_array"):
            continue  # plain local iterables are skipped (alignment.hpp:20)
        try:
            segs = segments(r)
        except TypeError:
            return False
        if not segs:
            return False
        shapes.append([(rank(s), len(s)) for s in segs])
    return all(s == shapes[0] for s in shapes[1:]) if shapes else True


def local_segments(r):
    """Device-local values of each segment (mhp/views.hpp:9-21).  On the
    single-controller TPU runtime every shard is addressable, so this yields
    one jax array (or tuple for zips) per segment."""
    return [local(s) for s in segments(r)]
