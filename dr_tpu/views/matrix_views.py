"""Matrix views: submatrix / row / column slices of a dense_matrix.

TPU re-design of the reference's matrix view family
(``shp/views/dense_matrix_view.hpp``, ``dense_row_view.hpp``,
``dense_column_view.hpp``): lazy (rows x cols) windows that still expose
``segments()`` (clipped tiles, with ranks) and evaluate as jax arrays.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ..core.vocabulary import rank, segments

__all__ = ["dense_matrix_view", "matrix_row_view", "matrix_column_view"]


class dense_matrix_view:
    """Window rows [rb, re) x cols [cb, ce) over a dense_matrix
    (dense_matrix_view.hpp:108-163)."""

    def __init__(self, base, rb, re, cb, ce):
        m, n = base.shape
        self.base = base
        self.rb, self.re = max(0, rb), min(re, m)
        self.cb, self.ce = max(0, cb), min(ce, n)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.re - self.rb, self.ce - self.cb)

    def __len__(self):
        return self.shape[0] * self.shape[1]

    def __dr_segments__(self):
        out = []
        for t in segments(self.base):
            rb, re = max(t.rb, self.rb), min(t.re, self.re)
            cb, ce = max(t.cb, self.cb), min(t.ce, self.ce)
            if rb < re and cb < ce:
                from ..containers.dense_matrix import MatrixTileSegment
                out.append(MatrixTileSegment(self.base, rank(t),
                                             rb, re, cb, ce))
        return out

    def to_array(self):
        return self.base.to_array()[self.rb:self.re, self.cb:self.ce]

    def materialize(self) -> np.ndarray:
        from ..utils.host import to_host
        return to_host(self.to_array())

    def row(self, i: int) -> "matrix_row_view":
        return matrix_row_view(self.base, self.rb + i, self.cb, self.ce)

    def column(self, j: int) -> "matrix_column_view":
        return matrix_column_view(self.base, self.cb + j, self.rb, self.re)

    def __repr__(self):
        return (f"dense_matrix_view(rows=[{self.rb},{self.re}), "
                f"cols=[{self.cb},{self.ce}))")


class matrix_row_view:
    """One matrix row as a 1-D range (dense_row_view.hpp:76-102)."""

    def __init__(self, base, i, cb=0, ce=None):
        self.base = base
        self.i = i
        self.cb = cb
        self.ce = base.shape[1] if ce is None else ce

    def __len__(self):
        return self.ce - self.cb

    def to_array(self):
        return self.base.to_array()[self.i, self.cb:self.ce]

    def materialize(self):
        from ..utils.host import to_host
        return to_host(self.to_array())

    def __iter__(self):
        return iter(self.materialize())

    def __getitem__(self, j):
        return self.base[self.i, self.cb + j]


class matrix_column_view:
    """One matrix column as a 1-D range (dense_column_view.hpp:77-105)."""

    def __init__(self, base, j, rb=0, re=None):
        self.base = base
        self.j = j
        self.rb = rb
        self.re = base.shape[0] if re is None else re

    def __len__(self):
        return self.re - self.rb

    def to_array(self):
        return self.base.to_array()[self.rb:self.re, self.j]

    def materialize(self):
        from ..utils.host import to_host
        return to_host(self.to_array())

    def __iter__(self):
        return iter(self.materialize())

    def __getitem__(self, i):
        return self.base[self.rb + i, self.j]
