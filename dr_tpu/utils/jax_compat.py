"""jax version portability shims.

The package is written against the current jax surface (top-level
``jax.shard_map`` with the ``check_vma`` kwarg).  Older jax releases
(<= 0.4.x, the toolchain this container bakes in) ship the same
functionality as ``jax.experimental.shard_map.shard_map`` with the
kwarg spelled ``check_rep``.  :func:`install` bridges the gap in one
place — every module keeps calling ``jax.shard_map(...)`` — and is a
no-op on a jax that already has the attribute.

Imported for its side effect at the top of ``dr_tpu/__init__``; safe
to call repeatedly.
"""

from __future__ import annotations

import jax


def install() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  **kwargs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma,
                          **kwargs)

    jax.shard_map = shard_map


install()
