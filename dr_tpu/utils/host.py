"""Host materialization that works in multi-process (multi-host) runs.

Single-controller: shards are all addressable and ``np.asarray`` works.
Under ``jax.distributed`` each process holds only its shards, so global
reads go through ``process_allgather`` — the analog of the reference's
gather-to-root, except the result is valid on every process.
"""

from __future__ import annotations

import numpy as np

__all__ = ["to_host"]


def to_host(arr) -> np.ndarray:
    import jax
    if not hasattr(arr, "is_fully_addressable"):
        return np.asarray(arr)
    if jax.process_count() == 1 or arr.is_fully_addressable:
        return np.asarray(arr)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
