"""Profiling and timing helpers.

The reference has NO tracing/profiling subsystem (SURVEY.md §5:
"Tracing/profiling: none"); on TPU the right tool is the JAX/XLA
profiler, so this module is a thin, dependency-light veneer over it
plus device-honest timers for the tunneled single-chip environment
(see docs/PERF.md "measurement lesson"):

- ``trace(logdir)``: context manager around ``jax.profiler.trace`` —
  XLA op-level traces viewable in TensorBoard/Perfetto; annotations via
  ``annotate``.
- ``annotate(name)``: ``jax.profiler.TraceAnnotation`` passthrough.
- ``device_timer(run_sync, r1, r2, samples)``: the marginal method as
  a library utility — per-op device seconds for a fused ``*_n``-style
  callable, with the per-dispatch constant cancelled.
- ``marginal(...)``: the jitter-proof adaptive variant (bench.py's
  measurement core as a library API): widens the loop count until the
  measured delta dominates the tunneled dispatch drift, and raises
  :class:`JitterError` instead of returning noise.
- ``profile_phases(make_run, names)``: PHASE-LEVEL breakdown of a fused
  shard_map program from prefix-truncated variants (round 6).  The
  program family exposes a ``stop_after`` knob (e.g. the sample-sort's
  ``_sort_program``) building the same program cut after a named phase;
  ``make_run(i)`` returns a fused-loop ``run_sync`` for the prefix
  ending at ``names[i]``.  Each prefix is timed by the marginal method
  and phase ``i``'s cost is the difference of consecutive prefix
  times — the per-dispatch constant AND the shared earlier-phase work
  cancel.  Caveat: truncation changes what XLA can fuse across the cut,
  so per-phase figures are estimates, not an exact partition.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

__all__ = ["trace", "annotate", "device_timer", "marginal",
           "JitterError", "PhaseBreakdown", "profile_phases"]


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a JAX profiler trace of the enclosed block into
    ``logdir`` (inspect with TensorBoard's profile plugin)."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a :func:`trace` capture."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def _interleaved_delta(run_sync, ra: int, rb: int,
                       samples: int) -> float:
    """The marginal method's measurement core: interleave ``samples``
    timings of the ra-round and rb-round fused loops and divide the
    median difference by rb - ra (the per-dispatch constant cancels).
    Shared by :func:`device_timer` and :func:`marginal` — ONE copy of
    the discipline."""
    t1s, t2s = [], []
    for _ in range(samples):
        t0 = time.perf_counter()
        run_sync(ra)
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_sync(rb)
        t2s.append(time.perf_counter() - t0)
    return (float(np.median(t2s)) - float(np.median(t1s))) / (rb - ra)


def device_timer(run_sync, r1: int = 4, r2: int = 36,
                 samples: int = 5) -> float:
    """Per-op device seconds for a fused-loop callable by the marginal
    method: ``run_sync(r)`` must execute ``r`` chained ops in ONE
    dispatched program and hard-sync (read a device scalar).  The
    host-dispatch constant — large and drifting on tunneled backends —
    cancels in the r2-r1 difference.  See the ``*_n`` family
    (``dot_n``, ``inclusive_scan_n``, ``ring_attention_n``, ``gemv_n``,
    ``span_halo.exchange_n``) for ready-made fused loops.  For a
    jitter-proof adaptive variant use :func:`marginal`."""
    for r in (r1, r2):
        run_sync(r)  # compile + warm
    return _interleaved_delta(run_sync, r1, r2, samples)


class JitterError(RuntimeError):
    """Measurement (not kernel) failure from :func:`marginal`: the
    widened delta still drowned in the per-dispatch jitter."""


def marginal(run_sync, r1: int = 4, r2: int = 36, samples: int = 5,
             min_spread: float = 0.3, rmax: int = 4096) -> float:
    """Device-side per-op seconds by the MARGINAL method (the library
    form of ``bench._marginal`` — docs/PERF.md "measurement lesson"):
    time a fused loop of r1 ops and one of r2 ops (each dispatched once
    and synced once), interleaved, and divide the median difference by
    r2 - r1.  The tunneled per-dispatch constant — large and drifting
    (tens of ms) — cancels in the difference.

    ADAPTIVE: the difference only means anything once it dominates the
    dispatch jitter.  After a pilot estimate, if (r2-r1) * dt falls
    under ``min_spread`` seconds the loop count is widened (one extra
    compile — fori_loop compile time is iteration-count independent)
    until the measured delta is jitter-proof; a delta that STILL stays
    an order of magnitude under the threshold raises
    :class:`JitterError` instead of returning noise."""
    def once(ra, rb):
        return _interleaved_delta(run_sync, ra, rb, samples)

    run_sync(r1)  # compile + warm
    run_sync(r2)
    dt = once(r1, r2)
    # min_spread <= 0 disables the adaptive widening entirely (test
    # harnesses pin the loop counts for determinism)
    if min_spread > 0 and (r2 - r1) * dt < min_spread:
        # pilot was noise-level (possibly <= 0): widen so the true delta
        # would exceed min_spread even if the op is ~10x faster than the
        # noisy pilot suggests.  t_warm/r2 overestimates per-op time (it
        # still contains the dispatch constant), so the ~3 s budget cap
        # it implies is conservative.
        t0 = time.perf_counter()
        run_sync(r2)
        t_warm = time.perf_counter() - t0
        per = max(dt, min_spread / 10.0 / rmax)
        cap = max(r2, int(3.0 * r2 / max(t_warm, 1e-3)))
        r2w = min(rmax, cap, r1 + max(2 * (r2 - r1),
                                      int(np.ceil(min_spread / per))))
        if r2w > r2:
            run_sync(r2w)  # compile + warm the widened loop
            dt = once(r1, r2w)
            r2 = r2w
    if dt <= 0 or (r2 - r1) * dt < min_spread / 10.0:
        raise JitterError("marginal measurement drowned in dispatch "
                          f"jitter (dt={dt:.3e} s/op over "
                          f"{r2 - r1} ops)")
    return dt


class PhaseBreakdown:
    """Per-phase seconds of a fused program, from cumulative prefix
    timings (:func:`profile_phases`).  ``seconds`` maps phase name to
    its marginal cost (clamped at 0 — timing noise can order two
    near-identical prefixes backwards); ``total`` is the LAST prefix's
    cumulative per-op time (the full program)."""

    def __init__(self, names, cumulative):
        assert len(names) == len(cumulative) and names
        self.names = tuple(names)
        self.cumulative = tuple(float(c) for c in cumulative)
        per = []
        prev = 0.0
        for c in self.cumulative:
            per.append(max(0.0, c - prev))
            prev = max(prev, c)
        self.seconds = dict(zip(self.names, per))
        self.total = self.cumulative[-1]

    @property
    def dominant(self) -> str:
        """The costliest phase's name."""
        return max(self.names, key=lambda nm: self.seconds[nm])

    def fractions(self) -> dict:
        """Phase share of the total (0 when the total itself is 0)."""
        tot = sum(self.seconds.values())
        return {nm: (self.seconds[nm] / tot if tot > 0 else 0.0)
                for nm in self.names}

    def detail(self, bytes_per_op: float, digits: int = 3) -> dict:
        """Bench-JSON form: per-phase effective giga-units/s for a
        program moving ``bytes_per_op`` logical units per fused
        iteration — bytes give GB/s, FLOPs give GFLOP/s (the round-9
        spmv ladder) — phases that measured ~0 report 0.0, not inf."""
        out = {}
        for nm in self.names:
            s = self.seconds[nm]
            out[nm] = round(bytes_per_op / s / 1e9, digits) if s > 0 \
                else 0.0
        return out

    def table(self, bytes_per_op: float = None,
              unit: str = "GB/s") -> str:
        """Human-readable per-phase table (tune_tpu.py output);
        ``unit`` labels the rate column (``bytes_per_op`` in FLOPs +
        unit="GFLOP/s" for the spmv ladder)."""
        tot = sum(self.seconds.values()) or 1.0
        lines = []
        for nm in self.names:
            s = self.seconds[nm]
            line = f"  {nm:<12s} {s * 1e3:9.3f} ms  {s / tot:6.1%}"
            if bytes_per_op is not None and s > 0:
                line += f"  {bytes_per_op / s / 1e9:8.2f} {unit}"
            lines.append(line)
        lines.append(f"  {'total':<12s} {self.total * 1e3:9.3f} ms")
        return "\n".join(lines)


def profile_phases(make_run, names, r1: int = 2, r2: int = 10,
                   samples: int = 5, min_spread: float = 0.3,
                   rmax: int = 4096) -> PhaseBreakdown:
    """Phase breakdown of a fused program from prefix truncations.

    ``make_run(i)`` must return a ``run_sync(r)`` callable executing
    ``r`` fused iterations of the program truncated after phase
    ``names[i]`` (the last name being the FULL program) and hard-sync.
    Each prefix is timed by :func:`marginal`; per-phase cost is the
    difference of consecutive prefixes.  A prefix whose measurement
    drowns in jitter (:class:`JitterError`) is recorded at its
    predecessor's cumulative time (phase cost 0) rather than failing
    the whole breakdown."""
    cum = []
    for i in range(len(names)):
        run = make_run(i)
        try:
            dt = marginal(run, r1=r1, r2=r2, samples=samples,
                          min_spread=min_spread, rmax=rmax)
        except JitterError:
            dt = cum[-1] if cum else 0.0
        cum.append(dt)
    return PhaseBreakdown(names, cum)
