"""Profiling and timing helpers.

The reference has NO tracing/profiling subsystem (SURVEY.md §5:
"Tracing/profiling: none"); on TPU the right tool is the JAX/XLA
profiler, so this module is a thin, dependency-light veneer over it
plus a device-honest timer for the tunneled single-chip environment
(see docs/PERF.md "measurement lesson"):

- ``trace(logdir)``: context manager around ``jax.profiler.trace`` —
  XLA op-level traces viewable in TensorBoard/Perfetto; annotations via
  ``annotate``.
- ``annotate(name)``: ``jax.profiler.TraceAnnotation`` passthrough.
- ``device_timer(run_sync, r1, r2, samples)``: the marginal method as
  a library utility — per-op device seconds for a fused ``*_n``-style
  callable, with the per-dispatch constant cancelled.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

__all__ = ["trace", "annotate", "device_timer"]


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a JAX profiler trace of the enclosed block into
    ``logdir`` (inspect with TensorBoard's profile plugin)."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a :func:`trace` capture."""
    import jax
    return jax.profiler.TraceAnnotation(name)


def device_timer(run_sync, r1: int = 4, r2: int = 36,
                 samples: int = 5) -> float:
    """Per-op device seconds for a fused-loop callable by the marginal
    method: ``run_sync(r)`` must execute ``r`` chained ops in ONE
    dispatched program and hard-sync (read a device scalar).  The
    host-dispatch constant — large and drifting on tunneled backends —
    cancels in the r2-r1 difference.  See the ``*_n`` family
    (``dot_n``, ``inclusive_scan_n``, ``ring_attention_n``, ``gemv_n``,
    ``span_halo.exchange_n``) for ready-made fused loops."""
    for r in (r1, r2):
        run_sync(r)  # compile + warm
    t1s, t2s = [], []
    for _ in range(samples):
        t0 = time.perf_counter()
        run_sync(r1)
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_sync(r2)
        t2s.append(time.perf_counter() - t0)
    return (float(np.median(t2s)) - float(np.median(t1s))) / (r2 - r1)
