"""Checkpoint / restore for distributed containers.

The reference has NO serialization at all (SURVEY.md §5 "Checkpoint /
resume: none").  A framework needs one, so this ships beyond parity:
containers round-trip through a single ``.npz`` per object (logical value
+ layout metadata).  In multi-process runs every process calls save()
(collective: materialization gathers), only process 0 writes, and load()
rebuilds the same sharded layout on every process.

Failure model (docs/SPEC.md "Failure model & recovery"):

* save() is ATOMIC: the archive is written to a same-directory temp
  file, fsync'd, and ``os.replace``'d into place — a process killed
  mid-write leaves either the previous checkpoint or nothing, never a
  torn file.  ``meta`` carries a ``format_version`` so future layout
  changes stay detectable.
* load() raises :class:`~.resilience.CheckpointCorruptError` (a
  classified ProgramError) on truncated/corrupt/newer-format files —
  never a raw zipfile traceback.
* Injection sites ``checkpoint.write`` / ``checkpoint.read``
  (utils/faults) exercise both paths on the CPU mesh; the behavioral
  ``truncate`` kind leaves the torn file a NON-atomic writer would
  have, so the corrupt-load leg has a live regression test.
"""

from __future__ import annotations

import json
import os
import zlib
import zipfile

import numpy as np

from . import faults as _faults
from .resilience import CheckpointCorruptError

__all__ = ["save", "load", "read", "snapshot", "rebuild",
           "FORMAT_VERSION"]

#: bump on any incompatible meta/arrays layout change; load() accepts
#: anything <= this (absent = 0, the pre-versioned round-6 format).
FORMAT_VERSION = 1


def _member(f, fname: str, name: str):
    """Read one archive member, classifying corruption NARROWLY: the
    surrounding load() body raises intentional ValueErrors (mesh/layout
    mismatches) that must keep their class, so only the member read
    itself maps onto CheckpointCorruptError (a zip-intact archive whose
    .npy bytes were overwritten raises ValueError from np.lib.format)."""
    try:
        return f[name]
    except KeyError as e:
        raise CheckpointCorruptError(
            f"checkpoint {fname} is missing member {name!r}",
            site="checkpoint.read") from e
    except (zipfile.BadZipFile, zlib.error, EOFError, OSError,
            ValueError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {fname} member {name!r} is corrupt: {e}",
            site="checkpoint.read") from e


def _final_path(path) -> str:
    """np.savez appends .npz to bare paths; with the atomic temp-file
    protocol WE control the name, so normalize once here (load accepts
    both spellings, as before)."""
    p = str(path)
    return p if p.endswith(".npz") else p + ".npz"


def _write_atomic(final: str, meta: dict, arrays: dict) -> None:
    """Write the archive to ``final`` via temp file + fsync + rename.
    The ``checkpoint.write`` injection site fires between the write and
    the rename: exception kinds abort with the destination untouched
    (what atomicity buys); the behavioral ``truncate`` kind installs a
    torn file — the state a mid-stream kill leaves a NON-atomic writer
    in — so load()'s corrupt-file classification stays regression-
    tested."""
    tmp = final + ".tmp"
    try:
        with open(tmp, "wb") as fh:
            np.savez(fh, meta=json.dumps(meta), **arrays)
            fh.flush()
            os.fsync(fh.fileno())
        kind = _faults.fire("checkpoint.write", path=final)
        if kind == "truncate":
            with open(tmp, "r+b") as fh:
                fh.truncate(max(1, os.path.getsize(tmp) // 2))
        os.replace(tmp, final)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def snapshot(container):
    """Host-staged ``(meta, arrays)`` state capture — the shared front
    half of :func:`save` and the elastic rescue path
    (utils/elastic.py, docs/SPEC.md §16).  Materialization gathers, so
    in multi-process runs every process must call it collectively."""
    from ..containers.distributed_vector import distributed_vector
    from ..containers.dense_matrix import dense_matrix
    from ..containers.sparse_matrix import sparse_matrix
    from ..containers.mdarray import distributed_mdarray

    if isinstance(container, distributed_vector):
        hb = container.halo_bounds
        meta = {"kind": "vector", "halo": [hb.prev, hb.next, hb.periodic]}
        dist = container.distribution
        if dist is not None:
            meta["sizes"] = list(dist.sizes)
        arrays = {"data": container.materialize()}
    elif isinstance(container, dense_matrix):
        meta = {"kind": "dense_matrix",
                "grid": list(container.grid_shape),
                "tile": list(container.partition.tile)}
        arrays = {"data": container.materialize()}
    elif isinstance(container, distributed_mdarray):
        meta = {"kind": "mdarray", "grid": list(container.grid)}
        arrays = {"data": container.materialize()}
    elif isinstance(container, sparse_matrix):
        rows, cols, vals = [], [], []
        for seg in container.__dr_segments__():
            r, c, v = seg.triples()
            rows.append(r)
            cols.append(c)
            vals.append(v)
        meta = {"kind": "sparse_matrix", "shape": list(container.shape),
                "grid": list(container.grid_shape)}
        arrays = {
            "rows": np.concatenate(rows) if rows else np.zeros(0, np.int64),
            "cols": np.concatenate(cols) if cols else np.zeros(0, np.int64),
            "vals": np.concatenate(vals) if vals else np.zeros(0),
        }
    else:
        raise TypeError(f"cannot checkpoint {type(container).__name__}")
    meta["format_version"] = FORMAT_VERSION
    return meta, arrays


def save(path: str, container) -> None:
    import jax
    meta, arrays = snapshot(container)

    err = None
    if jax.process_index() == 0:
        try:
            _write_atomic(_final_path(path), meta, arrays)
        except Exception as e:  # must still reach the collective below
            err = e
    if jax.process_count() > 1:
        # one collective does double duty: save() returns only once the
        # file is durable from every process's point of view (a later
        # load() must never race the write), AND rank 0's write status
        # propagates so a failed write raises on EVERY rank instead of
        # hanging the others in a rendezvous rank 0 never reached
        from jax.experimental import multihost_utils
        flags = np.asarray(multihost_utils.process_allgather(
            np.asarray([err is None], np.int32))).reshape(-1)
        if err is None and not flags[0]:
            raise RuntimeError(
                "checkpoint save failed on process 0; see its log")
    if err is not None:
        raise err
    # a durable checkpoint is the elastic restore source (SPEC §16):
    # a container whose segments die with a device restores from the
    # last path saved here — registered on every process (load is
    # collective, so every survivor can rebuild)
    from . import elastic
    elastic.note_checkpoint(container, _final_path(path))


#: archive members each kind carries beyond ``meta`` (pre-read by
#: load() so member corruption classifies before rebuild runs)
_ARRAY_MEMBERS = {
    "vector": ("data",),
    "dense_matrix": ("data",),
    "mdarray": ("data",),
    "sparse_matrix": ("rows", "cols", "vals"),
}


def rebuild(meta, arrays, *, runtime=None, reblock=False):
    """Reconstruct a container from a ``(meta, arrays)`` snapshot —
    the shared back half of :func:`load` and the elastic
    rescue/restore path (utils/elastic.py, docs/SPEC.md §16).

    ``reblock=True`` drops mesh-shape constraints (a vector's explicit
    block distribution) so state restores onto a DIFFERENT-sized mesh
    with the default even block layout — what a shrink rescue needs;
    plain loads keep the strict-mismatch errors."""
    from ..containers.distributed_vector import distributed_vector
    from ..containers.dense_matrix import dense_matrix
    from ..containers.sparse_matrix import sparse_matrix
    from ..containers.mdarray import distributed_mdarray
    from ..parallel.halo import halo_bounds

    kind = meta["kind"]
    if kind == "vector":
        prev, nxt, periodic = meta["halo"]
        hb = halo_bounds(int(prev), int(nxt), bool(periodic)) \
            if (prev or nxt) else None
        sizes = None if reblock else meta.get("sizes")
        if sizes is not None:
            from ..parallel import runtime as _rt
            P = (runtime or _rt.runtime()).nprocs
            if len(sizes) != P:
                raise ValueError(
                    f"checkpointed block_distribution has "
                    f"{len(sizes)} blocks but the current mesh "
                    f"has {P} shards; re-save without an "
                    "explicit distribution to re-block on load")
        return distributed_vector.from_array(
            arrays["data"], halo=hb, distribution=sizes,
            runtime=runtime)
    if kind == "dense_matrix":
        part = _matrix_partition(meta, runtime, cyclic_ok=True)
        return dense_matrix.from_array(arrays["data"], part,
                                       runtime=runtime)
    if kind == "mdarray":
        return distributed_mdarray.from_array(arrays["data"],
                                              runtime=runtime)
    if kind == "sparse_matrix":
        part = _matrix_partition(meta, runtime, cyclic_ok=False)
        return sparse_matrix.from_coo(
            tuple(meta["shape"]), arrays["rows"], arrays["cols"],
            arrays["vals"], partition=part, runtime=runtime)
    raise ValueError(f"unknown checkpoint kind: {kind}")


def read(path: str):
    """Read a checkpoint's raw ``(meta, arrays)`` snapshot WITHOUT
    rebuilding a container — the elastic per-segment restore merges
    checkpointed values for dead segments with live survivor state
    (SPEC §16).  Same classification contract as :func:`load`."""
    fname = _final_path(path)
    _faults.fire("checkpoint.read", path=fname)
    try:
        f = np.load(fname, allow_pickle=False)
    except (zipfile.BadZipFile, zlib.error, EOFError, ValueError) as e:
        # a truncated/torn archive; FileNotFoundError stays itself
        raise CheckpointCorruptError(
            f"unreadable checkpoint {fname}: {e}",
            site="checkpoint.read") from e
    with f:
        try:
            meta = json.loads(str(_member(f, fname, "meta")))
            kind = meta["kind"]
            version = int(meta.get("format_version", 0))
        except (KeyError, ValueError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"checkpoint {fname} has no readable meta record: {e}",
                site="checkpoint.read") from e
        if version > FORMAT_VERSION:
            raise CheckpointCorruptError(
                f"checkpoint {fname} written by a newer dr_tpu "
                f"(format_version={version} > {FORMAT_VERSION}); "
                "upgrade to load it", site="checkpoint.read")
        if kind not in _ARRAY_MEMBERS:
            raise ValueError(f"unknown checkpoint kind: {kind}")
        # pre-read every member INSIDE the archive context: a torn
        # member (a non-atomic writer's legacy, or the injected
        # 'truncate' kind) classifies through _member before rebuild
        # touches the mesh
        arrays = {name: _member(f, fname, name)
                  for name in _ARRAY_MEMBERS[kind]}
    return meta, arrays


def load(path: str, *, runtime=None, reblock=False):
    meta, arrays = read(path)
    return rebuild(meta, arrays, runtime=runtime, reblock=reblock)


def _matrix_partition(meta, runtime, *, cyclic_ok):
    """Rebuild the checkpointed partition: exact when the saved grid fits
    the current mesh; re-blocked (default grid) when a plain block layout
    moved to a different mesh size; error when a non-default layout
    cannot be represented there."""
    from ..containers.partition import block_cyclic, tile as _tile
    from ..parallel import runtime as _rt

    grid = meta.get("grid")
    tile = meta.get("tile", [_tile.div, _tile.div])
    if grid is None:
        return None
    P = (runtime or _rt.runtime()).nprocs
    gp, gq = int(grid[0]), int(grid[1])
    is_div = tuple(tile) == (_tile.div, _tile.div)
    if gp * gq == P:
        if is_div and not cyclic_ok and gq == 1:
            return None  # default row tiling: let the container choose
        return block_cyclic(tile=tuple(tile), grid=(gp, gq))
    if is_div:
        return None  # plain block layout: re-block on the current mesh
    raise ValueError(
        f"checkpointed cyclic partition (grid {gp}x{gq}, tile {tile}) "
        f"does not fit the current {P}-device mesh; re-save with a "
        "block (tile.div) layout to re-block on load")
