"""Checkpoint / restore for distributed containers.

The reference has NO serialization at all (SURVEY.md §5 "Checkpoint /
resume: none").  A framework needs one, so this ships beyond parity:
containers round-trip through a single ``.npz`` per object (logical value
+ layout metadata).  In multi-process runs every process calls save()
(collective: materialization gathers), only process 0 writes, and load()
rebuilds the same sharded layout on every process.
"""

from __future__ import annotations

import json

import numpy as np

__all__ = ["save", "load"]


def save(path: str, container) -> None:
    import jax
    from ..containers.distributed_vector import distributed_vector
    from ..containers.dense_matrix import dense_matrix
    from ..containers.sparse_matrix import sparse_matrix
    from ..containers.mdarray import distributed_mdarray

    if isinstance(container, distributed_vector):
        hb = container.halo_bounds
        meta = {"kind": "vector", "halo": [hb.prev, hb.next, hb.periodic]}
        dist = container.distribution
        if dist is not None:
            meta["sizes"] = list(dist.sizes)
        arrays = {"data": container.materialize()}
    elif isinstance(container, dense_matrix):
        meta = {"kind": "dense_matrix",
                "grid": list(container.grid_shape),
                "tile": list(container.partition.tile)}
        arrays = {"data": container.materialize()}
    elif isinstance(container, distributed_mdarray):
        meta = {"kind": "mdarray", "grid": list(container.grid)}
        arrays = {"data": container.materialize()}
    elif isinstance(container, sparse_matrix):
        rows, cols, vals = [], [], []
        for seg in container.__dr_segments__():
            r, c, v = seg.triples()
            rows.append(r)
            cols.append(c)
            vals.append(v)
        meta = {"kind": "sparse_matrix", "shape": list(container.shape),
                "grid": list(container.grid_shape)}
        arrays = {
            "rows": np.concatenate(rows) if rows else np.zeros(0, np.int64),
            "cols": np.concatenate(cols) if cols else np.zeros(0, np.int64),
            "vals": np.concatenate(vals) if vals else np.zeros(0),
        }
    else:
        raise TypeError(f"cannot checkpoint {type(container).__name__}")

    err = None
    if jax.process_index() == 0:
        try:
            np.savez(path, meta=json.dumps(meta), **arrays)
        except Exception as e:  # must still reach the collective below
            err = e
    if jax.process_count() > 1:
        # one collective does double duty: save() returns only once the
        # file is durable from every process's point of view (a later
        # load() must never race the write), AND rank 0's write status
        # propagates so a failed write raises on EVERY rank instead of
        # hanging the others in a rendezvous rank 0 never reached
        from jax.experimental import multihost_utils
        flags = np.asarray(multihost_utils.process_allgather(
            np.asarray([err is None], np.int32))).reshape(-1)
        if err is None and not flags[0]:
            raise RuntimeError(
                "checkpoint save failed on process 0; see its log")
    if err is not None:
        raise err


def load(path: str, *, runtime=None):
    from ..containers.distributed_vector import distributed_vector
    from ..containers.dense_matrix import dense_matrix
    from ..containers.sparse_matrix import sparse_matrix
    from ..containers.mdarray import distributed_mdarray
    from ..parallel.halo import halo_bounds

    with np.load(path if str(path).endswith(".npz") else f"{path}.npz",
                 allow_pickle=False) as f:
        meta = json.loads(str(f["meta"]))
        kind = meta["kind"]
        if kind == "vector":
            prev, nxt, periodic = meta["halo"]
            hb = halo_bounds(int(prev), int(nxt), bool(periodic)) \
                if (prev or nxt) else None
            sizes = meta.get("sizes")
            if sizes is not None:
                from ..parallel import runtime as _rt
                P = (runtime or _rt.runtime()).nprocs
                if len(sizes) != P:
                    raise ValueError(
                        f"checkpointed block_distribution has {len(sizes)} "
                        f"blocks but the current mesh has {P} shards; "
                        "re-save without an explicit distribution to "
                        "re-block on load")
            return distributed_vector.from_array(f["data"], halo=hb,
                                                 distribution=sizes,
                                                 runtime=runtime)
        if kind == "dense_matrix":
            part = _matrix_partition(meta, runtime, cyclic_ok=True)
            return dense_matrix.from_array(f["data"], part,
                                           runtime=runtime)
        if kind == "mdarray":
            return distributed_mdarray.from_array(f["data"],
                                                  runtime=runtime)
        if kind == "sparse_matrix":
            part = _matrix_partition(meta, runtime, cyclic_ok=False)
            return sparse_matrix.from_coo(tuple(meta["shape"]), f["rows"],
                                          f["cols"], f["vals"],
                                          partition=part, runtime=runtime)
    raise ValueError(f"unknown checkpoint kind: {kind}")


def _matrix_partition(meta, runtime, *, cyclic_ok):
    """Rebuild the checkpointed partition: exact when the saved grid fits
    the current mesh; re-blocked (default grid) when a plain block layout
    moved to a different mesh size; error when a non-default layout
    cannot be represented there."""
    from ..containers.partition import block_cyclic, tile as _tile
    from ..parallel import runtime as _rt

    grid = meta.get("grid")
    tile = meta.get("tile", [_tile.div, _tile.div])
    if grid is None:
        return None
    P = (runtime or _rt.runtime()).nprocs
    gp, gq = int(grid[0]), int(grid[1])
    is_div = tuple(tile) == (_tile.div, _tile.div)
    if gp * gq == P:
        if is_div and not cyclic_ok and gq == 1:
            return None  # default row tiling: let the container choose
        return block_cyclic(tile=tuple(tile), grid=(gp, gq))
    if is_div:
        return None  # plain block layout: re-block on the current mesh
    raise ValueError(
        f"checkpointed cyclic partition (grid {gp}x{gq}, tile {tile}) "
        f"does not fit the current {P}-device mesh; re-save with a "
        "block (tile.div) layout to re-block on load")
