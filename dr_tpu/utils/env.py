"""Env-var registry: the ONE place dr_tpu code reads the environment.

Every on-device tuning variable (``DR_TPU_MM_CHUNK_CAP``,
``DR_TPU_SCAN_CHUNK``, ``DR_TPU_FLASH_BQ/BK``) is a power-of-two cap
read per call (so sweeps work in-process) and keyed into the relevant
program caches.  Parsing is TOLERANT: a malformed value falls back to
the default instead of taking down every caller at trace time — a typo
in a tuning sweep must not brick unrelated programs.

Raw ``os.environ`` reads of ``DR_TPU_*`` vars anywhere else in the
package are a lint error (tools/drlint.py rule R2): routing every read
through these helpers keeps parsing tolerant everywhere and gives the
SPEC.md env table one mechanical source of truth to drift-check
against (docs/SPEC.md §13).
"""

from __future__ import annotations

import contextlib
import os

__all__ = ["env_int", "env_pow2", "env_float", "env_str", "env_flag",
           "env_raw", "env_override"]


@contextlib.contextmanager
def env_override(**vars_):
    """Scoped env-var override with exact restore: sets each ``VAR=value``
    (``None`` deletes the var for the scope) and puts every var back on
    exit — to its prior value if it had one, else removed.  ONE home for
    the save/force/finally-restore dance the format/schedule sweeps
    (bench ladder, tune ladders, fuzz arms, chaos battery) all need; a
    hand-copied restore that mixes up the None-vs-set cases leaks a
    forced format into whatever measures next."""
    prior = {v: os.environ.get(v) for v in vars_}
    try:
        for v, val in vars_.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val
        yield
    finally:
        for v, val in prior.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val


def env_int(name: str, default: int, floor: int = 1) -> int:
    """``max(floor, int($name))``; ``default`` on a missing or
    malformed value."""
    raw = os.environ.get(name)
    try:
        v = int(raw) if raw is not None else default
    except ValueError:
        v = default
    return max(floor, v)


def env_float(name: str, default: float) -> float:
    """``float($name)``; ``default`` on a missing or malformed value."""
    raw = os.environ.get(name)
    try:
        return float(raw) if raw not in (None, "") else default
    except ValueError:
        return default


def env_str(name: str, default: str = "") -> str:
    """``$name`` stripped of surrounding whitespace; ``default`` when
    unset.  Mode/choice knobs (``DR_TPU_SPMV_FORMAT`` etc.) lowercase
    the result at the call site — the raw case is preserved here for
    path-valued vars (``DR_TPU_COMPILE_CACHE_DIR``)."""
    raw = os.environ.get(name)
    return default if raw is None else raw.strip()


def env_raw(name: str):
    """``os.environ.get($name)`` — None when unset.  For the few call
    sites where None-vs-set matters (save/restore of an operator pin,
    re-exec relay markers); everything with a usable default belongs on
    the typed helpers above."""
    return os.environ.get(name)


def env_flag(name: str) -> bool:
    """True iff ``$name`` is set to ``1`` (whitespace-tolerant) — the
    package-wide convention for boolean switches."""
    return env_str(name) == "1"


def env_pow2(name: str, default: int, floor: int = 1) -> int:
    """``max(floor, int($name))`` rounded DOWN to a power of two;
    ``default`` on a missing or malformed value.  The floor is re-applied
    AFTER the round-down so a non-power-of-two floor can't be undershot
    (floor=100, value=100 must not yield 64)."""
    v = env_int(name, default, floor)
    return max(floor, 1 << (v.bit_length() - 1))
