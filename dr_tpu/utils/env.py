"""Tuning-knob env parsing shared by the kernels.

Every on-device tuning variable (``DR_TPU_MM_CHUNK_CAP``,
``DR_TPU_SCAN_CHUNK``, ``DR_TPU_FLASH_BQ/BK``) is a power-of-two cap
read per call (so sweeps work in-process) and keyed into the relevant
program caches.  Parsing is TOLERANT: a malformed value falls back to
the default instead of taking down every caller at trace time — a typo
in a tuning sweep must not brick unrelated programs.
"""

from __future__ import annotations

import contextlib
import os

__all__ = ["env_int", "env_pow2", "env_override"]


@contextlib.contextmanager
def env_override(**vars_):
    """Scoped env-var override with exact restore: sets each ``VAR=value``
    (``None`` deletes the var for the scope) and puts every var back on
    exit — to its prior value if it had one, else removed.  ONE home for
    the save/force/finally-restore dance the format/schedule sweeps
    (bench ladder, tune ladders, fuzz arms, chaos battery) all need; a
    hand-copied restore that mixes up the None-vs-set cases leaks a
    forced format into whatever measures next."""
    prior = {v: os.environ.get(v) for v in vars_}
    try:
        for v, val in vars_.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val
        yield
    finally:
        for v, val in prior.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val


def env_int(name: str, default: int, floor: int = 1) -> int:
    """``max(floor, int($name))``; ``default`` on a missing or
    malformed value."""
    raw = os.environ.get(name)
    try:
        v = int(raw) if raw is not None else default
    except ValueError:
        v = default
    return max(floor, v)


def env_pow2(name: str, default: int, floor: int = 1) -> int:
    """``max(floor, int($name))`` rounded DOWN to a power of two;
    ``default`` on a missing or malformed value.  The floor is re-applied
    AFTER the round-down so a non-power-of-two floor can't be undershot
    (floor=100, value=100 must not yield 64)."""
    v = env_int(name, default, floor)
    return max(floor, 1 << (v.bit_length() - 1))
