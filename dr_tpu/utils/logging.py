"""``drlog``: the framework logger.

TPU re-design of ``lib::drlog`` (``include/dr/details/logger.hpp:7-49``):
a global logger with a per-process file sink (the reference writes
``dr.{rank}.log`` per MPI rank; a single-controller TPU process writes one
file, multi-host writes one per process index), ``debug(fmt, ...)`` with
call-site prefixes, and a zero-cost disabled mode (the reference compiles
the subsystem away without DR_FORMAT; here the module no-ops unless
enabled, and the comm layers guard calls on ``enabled()``).
"""

from __future__ import annotations

import os
import sys
from .env import env_str
from typing import Optional, TextIO

__all__ = ["drlog", "Logger"]


class Logger:
    def __init__(self):
        self._sink: Optional[TextIO] = None
        self._enabled = bool(env_str("DR_TPU_LOG"))

    def set_file(self, path: str) -> None:
        """Open the per-process sink (README.rst:101-107 usage shape);
        multi-host appends the process index like the reference's rank."""
        import jax
        if jax.process_count() > 1:
            root, ext = os.path.splitext(path)
            path = f"{root}.{jax.process_index()}{ext}"
        self._sink = open(path, "a")
        self._enabled = True

    def enabled(self) -> bool:
        return self._enabled

    def debug(self, fmt: str, *args, **kw) -> None:
        """debug(fmt, ...) with source-location prefix
        (logger.hpp:13-28).

        When the tracing layer is armed (``DR_TPU_TRACE=1``), every
        debug line ALSO lands in the obs trace as an instant event —
        whether or not the file/stderr sink is enabled — so the two
        debug channels cannot tell divergent stories about one run
        (docs/SPEC.md §15)."""
        from ..obs import recorder as _obs
        traced = _obs._armed
        if not self._enabled and not traced:
            return
        # sys._getframe beats inspect.stack(): the latter materializes
        # FrameSummary objects (source reads included) for the WHOLE
        # stack just to yield one filename:lineno — with tracing armed
        # that cost would land on every debug call
        frame = sys._getframe(1)
        loc = (f"{os.path.basename(frame.f_code.co_filename)}:"
               f"{frame.f_lineno}")
        msg = fmt.format(*args, **kw) if (args or kw) else fmt
        if traced:
            _obs.event("log.debug", cat="log", loc=loc, msg=msg[:200])
        if not self._enabled:
            return
        line = f"[{loc}] {msg}\n"
        if self._sink is not None:
            self._sink.write(line)
            self._sink.flush()
        else:
            sys.stderr.write("drlog " + line)

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None


#: global logger instance (the reference's ``lib::drlog`` global)
drlog = Logger()
