"""SPMD dispatch-order guard: detect divergent collective ordering.

The multi-process (MHP/DCN) dimension has one protocol invariant: every
process must enqueue the same sharded programs in the same order
(SURVEY.md §7 hard-part 6 — the discipline the reference gets for free
from MPI's matched collectives).  A violation does not crash; it
DEADLOCKS or silently mismatches data.  The reference ships no tool for
this class of bug (its §5 race-detection row is empty); this guard is
the TPU build's answer.

Usage::

    from dr_tpu.utils import spmd_guard
    with spmd_guard.guard() as g:
        ... run the SPMD section on every process ...
        g.verify()          # collective: raises on divergence

While active, every program dispatch in the package — the algorithm
layer's shared cache AND the per-module caches (halo, collectives,
matrices, mdarray, ring attention) are all :class:`TappedCache`\\ s —
records a canonicalized form of its cache key.  ``verify()`` allgathers a digest across processes; on
mismatch it allgathers the full traces and reports the first divergent
dispatch index with both sides' entries — the information a deadlock
postmortem cannot give you.

Canonicalization: cache keys embed ``pinned_id`` values (process-local
object identities, typed ``core.pinning.PinnedId``), which legitimately
differ across processes; exactly those are replaced by a placeholder —
every other int is structural and compared verbatim.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from contextlib import contextmanager
from typing import List, Optional

from ..core.pinning import PinnedId, _pins
from . import faults

__all__ = ["guard", "active", "DivergenceError", "TappedCache",
           "first_divergence", "dispatch_count", "compile_count"]


class DivergenceError(RuntimeError):
    pass


def _hash_code(h, code) -> None:
    """Feed a code object into ``h`` process-portably: bytecode,
    referenced NAMES (sin vs cos differ only here — bytecode alone
    merges them), and constants, RECURSING into nested code objects
    (their repr embeds a process-local 0x address — hashing it would
    make identical nested lambdas diverge across processes, a false
    positive)."""
    h.update(code.co_code)
    h.update(repr(code.co_names).encode())
    h.update(repr((code.co_varnames, code.co_argcount)).encode())
    for c in code.co_consts:
        if hasattr(c, "co_code"):
            _hash_code(h, c)
        else:
            h.update(repr(c).encode())
        h.update(b"\0")


def _canon_callable(obj) -> str:
    """Process-portable identity of a callable: qualname plus a hash of
    its compiled code — two different lambdas share the qualname
    '<lambda>' but not their bytecode/constants, so a rank-dependent op
    choice still diverges the trace."""
    name = getattr(obj, "__qualname__", getattr(obj, "__name__", "fn"))
    code = getattr(obj, "__code__", None)
    if code is None:
        return name
    h = hashlib.sha1()
    _hash_code(h, code)
    return f"{name}#{h.hexdigest()[:8]}"


def _canon(x) -> str:
    if isinstance(x, tuple):
        return "(" + ",".join(_canon(e) for e in x) + ")"
    if isinstance(x, PinnedId):
        # resolve the pinned object: a user op's code identity is
        # process-portable and keeps "same geometry, different op"
        # divergences visible; non-callable identities (meshes)
        # canonicalize away
        obj = _pins.get(int(x))
        if callable(obj):
            return _canon_callable(obj)
        return "ptr"
    if callable(x):
        return _canon_callable(x)
    return repr(x)


def _cache_cap() -> int:
    """Per-cache entry bound (``DR_TPU_PROG_CACHE_CAP``, default 512).

    Compiled executables pin JIT code for the process lifetime; an
    unbounded cache let a 400-iteration fuzz run segfault XLA's CPU
    compiler after a few thousand live programs (the compile itself
    crashed, not our code).  Normal workloads reuse a handful of
    layouts and never approach the bound."""
    from .env import env_int
    return env_int("DR_TPU_PROG_CACHE_CAP", 512, floor=8)


class TappedCache(OrderedDict):
    """Program-cache whose lookups double as the guard's dispatch tap:
    every algorithm dispatch does a ``get``/``setdefault`` on its
    module's cache FIRST (hit or miss), so converting a module cache to
    a TappedCache puts its dispatches on the verified trace.  The tap
    itself is a no-op when no guard is active; the LRU bookkeeping
    below costs one extra dict operation per dispatch — noise next to
    a program launch.

    Also a bounded LRU (:func:`_cache_cap`): hits refresh recency and
    inserts evict the oldest entries.  Eviction is DETERMINISTIC given
    the dispatch sequence, so SPMD processes running the same program
    order evict identically — the guard's own invariant keeps the
    caches coherent across the mesh.  Instances register with
    ``core.pinning`` so that when a PIN is evicted, the entries whose
    keys reference that identity are purged here (id-reuse soundness,
    see pinning's module docstring)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        from ..core.pinning import register_cache
        register_cache(self)

    def get(self, key, default=None):
        # the dispatch moment doubles as the 'dispatch.cache' injection
        # site (utils/faults): a per-process fault here drops exactly
        # one dispatch from the trace — the divergence class the guard
        # exists to catch.  fire() precedes record(): a faulted
        # dispatch never reached the backend, so it must not appear on
        # the verified trace either.  'device.lost' rides the same
        # moment (SPEC §16): a device death surfaces at whatever
        # dispatch touches the dead mesh next — mid-eager-op, mid-plan-
        # flush, or mid-serve-batch alike.
        faults.fire("dispatch.cache")
        faults.fire("device.lost")
        record(key)
        try:
            self.move_to_end(key)  # hit-refresh in ONE lookup
        except KeyError:
            pass
        return super().get(key, default)

    def setdefault(self, key, default=None):
        faults.fire("dispatch.cache")
        faults.fire("device.lost")
        record(key)
        # inline rather than super().setdefault(): OrderedDict routes
        # that through the overridden __setitem__, double-counting the
        # insert on the sanitizer's compile counter
        if key in self:
            val = super().__getitem__(key)
        else:
            _note_insert(key)
            super().__setitem__(key, default)
            val = default
        self.move_to_end(key)
        self._evict()
        return val

    def __setitem__(self, key, value):
        if key not in self:
            _note_insert(key)
        super().__setitem__(key, value)
        self.move_to_end(key)
        self._evict()

    def _evict(self) -> None:
        cap = _cache_cap()
        while len(self) > cap:
            self.popitem(last=False)


def first_divergence(base, other):
    """Locate the first divergent dispatch between two traces:
    ``(index, base_entry, other_entry)``; a pure length mismatch after a
    matching prefix returns ``(min_len, None, None)``; identical traces
    return None.  Shared by ``verify()`` and the resilience tests (a
    per-process injected fault drops a dispatch — this is the tool that
    names it)."""
    n = min(len(base), len(other))
    for i in range(n):
        if base[i] != other[i]:
            return i, base[i], other[i]
    if len(base) != len(other):
        return n, None, None
    return None


class SpmdGuard:
    def __init__(self):
        self.trace: List[str] = []

    def record(self, key) -> None:
        c = _canon(key)
        if _canon_check_hook is not None:
            _canon_check_hook(key, c)
        self.trace.append(c)

    def digest(self) -> str:
        h = hashlib.sha1()
        for t in self.trace:
            h.update(t.encode())
            h.update(b"\0")
        return h.hexdigest()

    def verify(self) -> None:
        """Collective check (every process must call it at the same
        point — it is itself a dispatch in the protocol).  No-op in
        single-process runs beyond freezing the trace."""
        import jax
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils
        import numpy as np
        me = jax.process_index()
        # phase 1: fixed-size digest + count from every process
        digest_bytes = np.frombuffer(
            bytes.fromhex(self.digest()), dtype=np.uint8)
        mine = np.concatenate(
            [digest_bytes.astype(np.int64), [len(self.trace)]])
        allv = np.asarray(multihost_utils.process_allgather(mine))
        if (allv == allv[0]).all():
            return
        # phase 2 (all processes reach here together — everyone saw the
        # same mismatching gather): ship the traces and locate the
        # first divergence against process 0
        import json
        payload = json.dumps(self.trace).encode()
        # pad to the max length so the gather has one static shape
        lens = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(payload)], np.int64))).reshape(-1)
        buf = np.zeros(int(lens.max()), np.uint8)
        buf[:len(payload)] = np.frombuffer(payload, np.uint8)
        traces_raw = np.asarray(
            multihost_utils.process_allgather(buf))
        traces = [json.loads(bytes(traces_raw[p][:int(lens[p])]
                                   ).decode())
                  for p in range(traces_raw.shape[0])]
        base = traces[0]
        for p, tr in enumerate(traces[1:], start=1):
            div = first_divergence(base, tr)
            if div is None:
                continue
            i, be, te = div
            if be is not None:
                raise DivergenceError(
                    f"SPMD dispatch divergence at index {i}: "
                    f"process 0 dispatched {be} but process "
                    f"{p} dispatched {te} (I am process {me})")
            raise DivergenceError(
                f"SPMD dispatch-count divergence: process 0 made "
                f"{len(base)} dispatches, process {p} made "
                f"{len(tr)} (first {i} agree; I am process {me})")
        raise DivergenceError(
            "SPMD digest mismatch with identical traces — "
            "canonicalization bug, please report")


_active: Optional[SpmdGuard] = None

#: process-lifetime dispatch counter: every TappedCache lookup (= every
#: algorithm/plan dispatch) increments it, guard active or not.  One
#: int add on the hot path; bench.py's ``detail.dispatch_counts`` and
#: plan.explain()'s per-run figures are diffs of this counter.
_dispatches: int = 0


def dispatch_count() -> int:
    """Monotonic count of tapped dispatches in this process."""
    return _dispatches


#: process-lifetime count of tapped-cache INSERTS.  A cache insert is
#: the compile moment (every module stores its freshly-jitted program
#: into its TappedCache), so this counter is the recompile detector's
#: raw signal: ``utils.sanitize.zero_recompile`` diffs it, and the
#: armed sanitizer's hook canonicalizes each inserted key to catch
#: value-keyed recompile storms (docs/SPEC.md §13.4).
_compiles: int = 0

#: set by utils.sanitize.install() when DR_TPU_SANITIZE=1 — receives
#: every inserted key.  None keeps the insert path one int add.
_compile_hook = None

#: set by utils.sanitize.install() — receives (key, canon) for every
#: dispatch recorded under an active guard (canon-portability check).
_canon_check_hook = None

#: set by dr_tpu.obs when DR_TPU_TRACE=1 — receive every tapped
#: dispatch / cache insert (= compile) as a trace event.  None keeps
#: the tracing-off hot path one ``is not None`` test (SPEC §15).
_obs_dispatch_hook = None
_obs_compile_hook = None


def compile_count() -> int:
    """Monotonic count of tapped-cache inserts (= program compiles)."""
    return _compiles


def _note_insert(key) -> None:
    global _compiles
    _compiles += 1
    if _compile_hook is not None:
        _compile_hook(key)
    if _obs_compile_hook is not None:
        _obs_compile_hook(key)


def note_compile(key) -> None:
    """Report a compile the insert tap cannot see: a two-level cache
    (stencil's per-step-count inner dicts) stores jitted programs in a
    PLAIN inner dict under one tapped outer key — call this at each
    inner store so the sanitizer's recompile budget covers them too."""
    _note_insert(key)


def active() -> Optional[SpmdGuard]:
    return _active


def record(key) -> None:
    """Called by the shared program cache on every dispatch lookup."""
    global _dispatches
    _dispatches += 1
    if _obs_dispatch_hook is not None:
        _obs_dispatch_hook(key)
    if _active is not None:
        _active.record(key)


@contextmanager
def guard():
    global _active
    prev = _active
    g = SpmdGuard()
    _active = g
    try:
        yield g
    finally:
        _active = prev
