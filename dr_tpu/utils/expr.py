"""Expression-DSL compiler for the native (C++) bridge.

The reference's algorithms take arbitrary C++ callables (e.g. the
stencil lambda at ``examples/mhp/stencil-1d.cpp:16-19`` or the
``transform_reduce`` multiply at ``examples/shp/dot_product.cpp:11-18``).
JAX cannot trace a C++ lambda, so the native API ships an arithmetic
expression DSL instead (SURVEY.md §7 hard-part 2, option (a)): the C++
side (``native/bridge/thp_bridge.hpp`` ``thp::expr``) serializes an
expression tree over placeholders ``x0..x7`` to a canonical string, and
this module compiles that string ONCE into a jax-traceable callable.

Caching by string is load-bearing, not a nicety: the algorithm layer's
program caches key user ops by IDENTITY (``core/pinning.pinned_id``),
so the same expression must map to the SAME function object for
repeated bridge calls to reuse their compiled XLA programs.

The grammar is validated before ``eval``: only whitelisted function
names, placeholders, numeric literals, and arithmetic punctuation may
appear — a malformed or adversarial string raises instead of reaching
the interpreter with any usable namespace.
"""

from __future__ import annotations

import ast
import functools
import re

import jax.numpy as jnp

__all__ = ["op_from_expr", "op_from_source", "FUNCTIONS"]

# the callable surface the C++ DSL can name (thp::sqrt & co.)
FUNCTIONS = {
    "sqrt": jnp.sqrt,
    "exp": jnp.exp,
    "log": jnp.log,
    "tanh": jnp.tanh,
    "abs": jnp.abs,
    "minimum": jnp.minimum,
    "maximum": jnp.maximum,
    "power": jnp.power,
}

_MAX_ARGS = 8
# validator-side arity for each whitelisted function (the structural
# AST gate rejects wrong-arity calls at the trust boundary)
_ARITY = {"sqrt": 1, "exp": 1, "log": 1, "tanh": 1, "abs": 1,
          "minimum": 2, "maximum": 2, "power": 2}
assert set(_ARITY) == set(FUNCTIONS), "every DSL function needs an arity"
_NAME = re.compile(r"[A-Za-z_][A-Za-z_0-9]*")
# everything a serialized expression may contain besides names:
# numbers (incl. scientific notation), arithmetic, parens, commas
_PUNCT = re.compile(r"^[\d\s\.\+\-\*/%\(\),eE]*$")


def _validate(expr: str, nargs: int) -> None:
    names = set(_NAME.findall(expr))
    allowed = set(FUNCTIONS) | {f"x{i}" for i in range(nargs)}
    # exponent suffixes of numeric literals ("1e-3", "2.5e2") tokenize
    # as the pseudo-names "e"/"e2" since the literal's digits precede
    # them; they can never resolve to anything (globals carry no such
    # names), so they are grammar, not identifiers
    bad = sorted(n for n in names if n not in allowed
                 and not re.fullmatch(r"[eE]\d*", n))
    if bad:
        raise ValueError(f"expr names outside the DSL surface: {bad} "
                         f"(allowed: x0..x{nargs - 1} + {sorted(FUNCTIONS)})")
    rest = _NAME.sub("", expr)
    if not _PUNCT.match(rest):
        raise ValueError(f"expr contains non-DSL characters: {expr!r}")
    if "__" in expr:
        raise ValueError("double underscore is not part of the DSL")
    # structural gate (round-5 fuzz finding: the character classes
    # alone admit "x0, x1" — a TUPLE — and similar shapes): the string
    # must parse as ONE scalar expression whose AST contains only DSL
    # nodes.  Commas are legal solely as whitelisted-call argument
    # separators, which this walk enforces for free.
    try:
        tree = ast.parse(expr.strip(), mode="eval")
    except SyntaxError:
        raise ValueError(f"expr does not parse as one expression: "
                         f"{expr!r}") from None
    for node in ast.walk(tree):
        if isinstance(node, (ast.Expression, ast.operator, ast.unaryop,
                             ast.expr_context)):
            continue
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div,
                          ast.Mod, ast.Pow)):
            continue
        if isinstance(node, ast.UnaryOp) and isinstance(
                node.op, (ast.UAdd, ast.USub)):
            continue
        if isinstance(node, ast.Call):
            if (not isinstance(node.func, ast.Name)
                    or node.func.id not in FUNCTIONS or node.keywords):
                raise ValueError(
                    f"expr call outside the DSL surface: {expr!r}")
            want = _ARITY[node.func.id]
            if len(node.args) != want:
                # arity belongs to the validator: a wrong-arity call
                # must fail HERE with ValueError, not as a TypeError
                # when the op first runs inside a jitted algorithm
                raise ValueError(
                    f"{node.func.id} takes {want} argument(s), got "
                    f"{len(node.args)} in {expr!r}")
            continue
        if isinstance(node, ast.Name):  # membership checked above
            continue
        if isinstance(node, ast.Constant) and isinstance(
                node.value, (int, float)):
            continue
        raise ValueError(f"expr node outside the DSL: "
                         f"{type(node).__name__} in {expr!r}")


@functools.lru_cache(maxsize=512)
def op_from_source(src: str, nargs: int):
    """Compile arbitrary jax-traceable Python source into an op — the
    native bridge's ESCAPE HATCH (SURVEY.md §7 hard-part 2, option b)
    for ops the arithmetic DSL cannot express: conditionals
    (``jnp.where``), comparisons, clipping, casts, or anything else
    traceable.  ``src`` must evaluate to a callable of ``nargs``
    positional arguments, e.g. ``"lambda x0: jnp.where(x0 > 0, x0,
    0.01 * x0)"``; ``jnp``, ``lax`` and ``np`` are in scope.

    .. warning:: UNSAFE BY DESIGN — ``src`` is ``eval``'d with full
       builtins.  Unlike :func:`op_from_expr` there is NO grammar
       validation: this is deliberate full Python, the same trust
       boundary as ``thp::session::exec`` (the C++ caller already owns
       the embedded interpreter).  It must ONLY ever receive
       embedder-authored source — never strings from config files,
       serialized programs, or any other less-trusted channel; route
       those through :func:`op_from_expr`'s validated grammar instead.
       Caching by (source, nargs) keeps the identity-keyed program
       caches effective across bridge calls."""
    nargs = int(nargs)
    if not (1 <= nargs <= _MAX_ARGS):
        raise ValueError(f"nargs must be 1..{_MAX_ARGS}")
    import builtins

    import numpy as np
    from jax import lax
    fn = eval(compile(src, f"<thp-custom-op:{src[:60]}>", "eval"),
              {"__builtins__": builtins, "jnp": jnp, "np": np,
               "lax": lax})
    if not callable(fn):
        raise TypeError(f"custom op source is not callable: {src!r}")
    import inspect
    try:
        params = inspect.signature(fn).parameters.values()
    except (TypeError, ValueError):
        params = None  # builtins/ufuncs: trust the declared arity
    if params is not None:
        # the op is CALLED with exactly nargs positionals: reject only
        # genuinely incompatible signatures (required > nargs, or more
        # positionals than accepted without *args)
        required = sum(
            p.default is p.empty
            and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            for p in params)
        max_pos = sum(p.kind in (p.POSITIONAL_ONLY,
                                 p.POSITIONAL_OR_KEYWORD)
                      for p in params)
        var_pos = any(p.kind == p.VAR_POSITIONAL for p in params)
        if required > nargs or (not var_pos and max_pos < nargs):
            raise ValueError(
                f"custom op signature incompatible with {nargs} "
                f"positional args: {src!r}")
    try:
        fn.__name__ = f"thp_custom_{abs(hash((src, nargs))) % 10 ** 8}"
    except (AttributeError, TypeError):
        pass  # ufuncs and some builtins have read-only names
    return fn


@functools.lru_cache(maxsize=512)
def op_from_expr(expr: str, nargs: int):
    """Compile a DSL string into a jax-traceable callable of ``nargs``
    positional arguments.  Cached by (string, nargs) so equal
    expressions share one function object (see module docstring)."""
    nargs = int(nargs)
    if not (1 <= nargs <= _MAX_ARGS):
        raise ValueError(f"nargs must be 1..{_MAX_ARGS}")
    _validate(expr, nargs)
    args = ", ".join(f"x{i}" for i in range(nargs))
    code = compile(f"lambda {args}: ({expr})", f"<thp-expr:{expr}>", "eval")
    # the lambda resolves free names from its __globals__ (the globals
    # dict passed to eval), not from eval's locals — FUNCTIONS must live
    # in globals.  __import__ stays available because jnp functions lazy-
    # import submodules at call time; the validated grammar cannot name
    # it (names are whitelisted above).
    fn = eval(code, {"__builtins__": {"__import__": __import__},
                     **FUNCTIONS})  # noqa: S307
    fn.__name__ = f"thp_expr_{abs(hash((expr, nargs))) % 10 ** 8}"
    return fn
