"""Debug printers: ``print_range`` / ``print_matrix`` / ``range_details``.

TPU re-design of the reference's debug helpers (``shp/util.hpp:138-222``):
human-readable dumps of a distributed range's values and per-segment
placement (rank, origin, size, device), for interactive inspection.
"""

from __future__ import annotations

import sys

import numpy as np

from ..core.vocabulary import local, rank, segments

__all__ = ["print_range", "print_matrix", "range_details"]


def range_details(r, name: str = "range", file=None) -> str:
    """Per-segment placement summary (shp/util.hpp:186-205)."""
    out = [f"{name}: n={len(r)}"]
    try:
        segs = segments(r)
    except TypeError:
        segs = []
    for i, s in enumerate(segs):
        origin = getattr(s, "begin", None)
        origin = "" if origin is None else f" origin={origin}"
        dev = ""
        loc = local(s)
        devs = getattr(loc, "devices", None)
        if callable(devs):
            try:
                dev = f" device={list(devs())[0]}"
            # drlint: ok[R5] best-effort device tag in a debug printout — absence degrades nothing
            except Exception:
                pass
        out.append(f"  segment {i}: rank={rank(s)} size={len(s)}"
                   f"{origin}{dev}")
    text = "\n".join(out)
    print(text, file=file or sys.stdout)
    return text


def print_range(r, name: str = "range", limit: int = 64, file=None) -> str:
    """Values + segmentation (shp/util.hpp:138-160)."""
    vals = np.asarray(r.materialize() if hasattr(r, "materialize")
                      else np.asarray(r))
    shown = np.array2string(vals[:limit], threshold=limit)
    suffix = " ..." if vals.size > limit else ""
    text = f"{name}: {shown}{suffix}"
    print(text, file=file or sys.stdout)
    range_details(r, name, file=file)
    return text


def print_matrix(m, name: str = "matrix", limit: int = 8, file=None) -> str:
    """2-D dump with tile grid info (shp/util.hpp:162-184)."""
    vals = np.asarray(m.materialize())
    shown = np.array2string(vals[:limit, :limit], threshold=limit * limit)
    text = (f"{name}: shape={m.shape} grid={getattr(m, 'grid_shape', '?')}"
            f"\n{shown}")
    print(text, file=file or sys.stdout)
    return text
