"""Elastic mesh degradation: survive device loss by SHRINKING the mesh
and rescuing live state (docs/SPEC.md §16).

The failure model (§10) classifies faults and routes the FIRST backend
touch, but before this module a device or host dying mid-session still
killed the job: every live container, deferred plan, and serve claim
died with it.  ROADMAP item 5 names the goal — "the degradation router
extended so a lost host downgrades the mesh instead of the job" — and
the re-placement recipe comes from the array-redistribution literature
(arXiv:2112.01075: any src→dst sharding change decomposes into
portable collective steps) plus Mesh-TensorFlow's topology-aware
layouts (arXiv:1811.02084).  This module is the session-level recovery
manager:

* **Detection** — a classified
  :class:`~.resilience.DeviceLostError`: raised by the ``device.lost``
  fault site (riding every TappedCache dispatch tap, so a device can
  die mid-eager-op, mid-plan-flush, or mid-serve-batch), by
  :func:`~.resilience.classify` on raw backend device-loss text, or by
  :func:`attribute` pinning a collective failure on a mesh rank.
* **Shrink** — :func:`rescue_session` computes the surviving-device
  mesh, rebuilds the global :class:`~..parallel.runtime.Runtime` on
  it, and walks the old runtime's live containers applying the
  rescue/restore/lost matrix:

  ========  =====================================================
  fate      when / how
  ========  =====================================================
  rescued   no segment lived on a lost rank: state moves through
            :func:`redistribute` (host-staged gather/scatter v1 —
            the API is the contract; the collective lowering is
            ROADMAP item 2's follow-on) onto the shrunken mesh,
            bit-equal to the pre-fault value
  restored  segments died with the device but the container has a
            durable atomic checkpoint (utils/checkpoint.save
            registers every successful write here): reloaded onto
            the new mesh with ``reblock=True``
  lost      segments died and no checkpoint exists: the container
            is POISONED — any further use raises a classified
            ``DeviceLostError`` naming the loss, never a silent
            wrong answer
  ========  =====================================================

* **Automatic hooks** — armed by ``DR_TPU_ELASTIC=1``:
  :func:`~.resilience.retry` turns a ``DeviceLostError`` into
  shrink-and-retry (the serve daemon's batch dispatch already runs
  under it, so a resident claim degrades to the shrunken mesh without
  dropping clients); ``plan.flush`` re-records its unexecuted queue
  against the new mesh and re-flushes (the fresh mesh re-keys every
  program, so spmd_guard sees a fresh canonical digest).
  :func:`rescue_session` itself always works when called explicitly —
  the flag gates only the automatic recovery.

Every shrink publishes ``_DR_TPU_ELASTIC_*`` env markers;
``resilience.degradation_story`` folds them into the ``shrink``
chapter of ``detail.degraded`` (they ride re-exec environments like
the serve markers), and obs records a ``mesh.shrink`` span with the
device-loss event inside it.  ``DR_TPU_ELASTIC_MIN_DEVICES`` floors
the shrink — below it the rescue refuses classified.
"""

from __future__ import annotations

import os
import time
import weakref

import numpy as np
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from . import faults as _faults
from . import resilience as _resilience
from .env import env_flag, env_int
from .fallback import warn_fallback

__all__ = ["enabled", "redistribute", "rescue_session", "try_rescue",
           "attribute", "ShrinkReport", "note_checkpoint",
           "checkpoint_path", "shrink_count", "last_report", "is_lost",
           "reset", "MARKERS"]

#: env markers the shrink publishes for resilience.degradation_story
MARKERS = ("_DR_TPU_ELASTIC_REASON", "_DR_TPU_ELASTIC_SHRINKS",
           "_DR_TPU_ELASTIC_LOST_RANKS", "_DR_TPU_ELASTIC_RESCUED",
           "_DR_TPU_ELASTIC_RESTORED", "_DR_TPU_ELASTIC_LOST",
           "_DR_TPU_ELASTIC_NPROCS", "_DR_TPU_ELASTIC_WALL_S")

#: id(container) -> (weakref, checkpoint path); ids are recycled, so
#: the weakref is the liveness check (a dead ref invalidates the row)
_ckpts: dict = {}

_shrinks = 0
_rescued = 0
_restored = 0
_lost = 0
_wall_s = 0.0
_last_report: Optional["ShrinkReport"] = None
#: reentrancy latch: a device "dying" during an active rescue must not
#: recurse into a second shrink under the first one's feet
_rescuing = False


def enabled() -> bool:
    """True when ``DR_TPU_ELASTIC=1`` arms the AUTOMATIC recovery
    hooks (retry / plan flush / serve batch).  Explicit
    :func:`rescue_session` calls work either way."""
    return env_flag("DR_TPU_ELASTIC")


def shrink_count() -> int:
    """Completed shrinks this process (the serve daemon diffs it to
    notice a mid-batch shrink)."""
    return _shrinks


def last_report() -> Optional["ShrinkReport"]:
    return _last_report


@dataclass
class ShrinkReport:
    """One completed shrink: what died, what survived, what it cost."""

    reason: str
    lost_ranks: List[int]
    nprocs_before: int
    nprocs_after: int
    rescued: int = 0
    restored: int = 0
    lost: int = 0
    wall_s: float = 0.0
    #: container fates for postmortems: (kind, repr, detail)
    fates: list = field(default_factory=list)


# ---------------------------------------------------------------------------
# checkpoint registry (restore source)
# ---------------------------------------------------------------------------

def note_checkpoint(container, path: str) -> None:
    """Record ``path`` as ``container``'s durable restore source —
    ``utils.checkpoint.save`` calls this after every successful atomic
    write, so a later shrink can restore segments that died with a
    device.  A death callback prunes the row when the container is
    collected (guarded against id reuse by a newer registration), so
    a long-lived daemon checkpointing short-lived containers does not
    grow the registry without bound."""
    key = id(container)

    def _drop(ref, _key=key):
        row = _ckpts.get(_key)
        if row is not None and row[0] is ref:
            _ckpts.pop(_key, None)

    _ckpts[key] = (weakref.ref(container, _drop), str(path))


def checkpoint_path(container) -> Optional[str]:
    """The last checkpoint registered for ``container`` (and still
    on disk), or None."""
    row = _ckpts.get(id(container))
    if row is None:
        return None
    ref, path = row
    if ref() is not container:
        # the id was recycled by a different object: stale row
        _ckpts.pop(id(container), None)
        return None
    return path if os.path.exists(path) else None


# ---------------------------------------------------------------------------
# rank attribution
# ---------------------------------------------------------------------------

def attribute(err, rank: int) -> _resilience.DeviceLostError:
    """Attribute a collective/backend failure to a mesh rank: the
    classified :class:`DeviceLostError` the rescue hooks act on.  The
    multihost leg uses this when a peer process dies mid-collective —
    the failure names no rank by itself, the survivor's topology
    knowledge does."""
    de = _resilience.DeviceLostError(
        f"rank {rank} presumed lost: {type(err).__name__}: {err}",
        site=getattr(err, "site", "") or "device.lost", rank=int(rank))
    if isinstance(err, BaseException):
        de.__cause__ = err
    return de


# ---------------------------------------------------------------------------
# redistribute: public v1 (host-staged gather/scatter)
# ---------------------------------------------------------------------------

def redistribute(container, new_dist=None, *, runtime=None):
    """Re-lay ``container`` out IN PLACE under ``new_dist`` on
    ``runtime`` (default: the current global runtime) and return it.

    v1 is host-staged: the logical value gathers to the host and
    scatters through the target layout's pack program — the API is the
    contract, the collective lowering (arXiv:2112.01075's
    all-to-all/permute decomposition on the shared ring machinery) is
    ROADMAP item 2's follow-on.  In-place on purpose: every existing
    reference to the container (views, recorded plan ops, the elastic
    rescue walking a live session) stays valid across the move.

    ``new_dist`` (a ``block_distribution``, a sizes sequence, or None
    for the default even layout) is a ``distributed_vector`` contract;
    matrices re-block with their default partition on the target
    runtime.  Pending deferred work on the container flushes first
    (the gather is a host materialization)."""
    from ..containers.distributed_vector import distributed_vector
    from ..parallel import runtime as _rt

    rt = runtime or _rt.runtime()
    if isinstance(container, distributed_vector):
        values = container.materialize()
        container._rebind(rt, new_dist)
        container.assign_array(values)
        return container
    if new_dist is not None:
        raise ValueError(
            "explicit block distributions are a distributed_vector "
            "contract; matrices re-block with their default partition "
            "on the target runtime")
    from . import checkpoint as _ck
    meta, arrays = _ck.snapshot(container)
    fresh = _ck.rebuild(meta, arrays, runtime=rt, reblock=True)
    _swap_state(container, fresh, rt)
    return container


def _swap_state(container, fresh, rt) -> None:
    """Adopt ``fresh``'s state into ``container`` in place (same
    logical value, new mesh/layout) and fix the self-references the
    dict swap cannot carry (a vector's halo controller binds its
    owner)."""
    container.__dict__.clear()
    container.__dict__.update(fresh.__dict__)
    from ..containers.distributed_vector import distributed_vector
    if isinstance(container, distributed_vector) and container._hb.width:
        from ..parallel.halo import span_halo
        container._halo = span_halo(container)
    rt.register(container)


# ---------------------------------------------------------------------------
# poisoning (the 'lost' fate)
# ---------------------------------------------------------------------------

_poison_classes: dict = {}


def _poison(container, why: str) -> None:
    """Mark ``container`` LOST: its segments died with a device and no
    checkpoint exists.  Any further attribute access raises the
    classified ``DeviceLostError`` — a lost container must never feed
    a silent wrong answer into a surviving computation."""
    cls = type(container)
    pc = _poison_classes.get(cls)
    if pc is None:
        def __getattribute__(self, name):
            if name.startswith("__") or name == "_elastic_lost_reason":
                return object.__getattribute__(self, name)
            raise _resilience.DeviceLostError(
                f"{cls.__name__} state was lost with the failed "
                "device(s) "
                f"({object.__getattribute__(self, '_elastic_lost_reason')}); "
                "only a checkpoint that predates the loss can restore "
                "it", site="device.lost")

        pc = type("Lost" + cls.__name__, (cls,),
                  {"__getattribute__": __getattribute__})
        _poison_classes[cls] = pc
    container._elastic_lost_reason = why
    container.__class__ = pc


def is_lost(container) -> bool:
    """True when a shrink poisoned ``container`` (its class carries
    the loss marker)."""
    return type(container) in _poison_classes.values()


# ---------------------------------------------------------------------------
# the shrink itself
# ---------------------------------------------------------------------------

def _owned_ranks(container, P: int) -> set:
    """Mesh ranks holding any of ``container``'s segments.  Vectors
    read their block windows (a zero-size block owns nothing — the
    'team' case survives a loss elsewhere untouched); matrices tile
    over a grid PREFIX of the device list; unknown kinds
    conservatively claim every rank."""
    from ..containers.distributed_vector import distributed_vector

    if isinstance(container, distributed_vector):
        owned = set()
        for r in range(container.nshards):
            b, e = container._rank_window(r)
            if b < e:
                owned.add(r)
        return owned
    grid = getattr(container, "grid_shape", None) \
        or getattr(container, "grid", None)
    if grid is not None:
        tiles = 1
        for g in tuple(grid):
            tiles *= int(g)
        return set(range(min(P, tiles)))
    return set(range(P))


def _plan_fate(c, lost_set: set, P: int, reason: str):
    """Decide one container's fate on the OLD mesh and capture the host
    state the apply step needs:

    * untouched by the loss → ``("rescue", (meta, arrays))`` — full
      host snapshot, bit-equal to the pre-fault value;
    * a vector with segments on dead ranks AND a checkpoint →
      ``("restore", ("merge", values))`` — PER-SEGMENT hybrid: live
      survivor segments read from the device, dead segments from the
      last atomic checkpoint (the documented consistency contract:
      dead segments rewind to the checkpoint, survivors do not);
    * a matrix with a checkpoint → ``("restore", ("ckpt", path))`` —
      whole-container reload (v1);
    * no checkpoint → ``("lost", reason)``.
    """
    from ..containers.distributed_vector import distributed_vector

    if not (_owned_ranks(c, P) & lost_set):
        from . import checkpoint as _ck
        return "rescue", _ck.snapshot(c)
    path = checkpoint_path(c)
    if path is None:
        return "lost", reason
    if isinstance(c, distributed_vector):
        return "restore", ("merge", _merge_vector_values(c, lost_set,
                                                         path))
    return "restore", ("ckpt", path)


def _merge_vector_values(c, lost_set: set, path: str):
    """The per-segment hybrid value: start from the checkpoint's
    logical array, overwrite every SURVIVING rank's window with its
    live device values (read shard-local — nothing is read from a dead
    rank)."""
    from . import checkpoint as _ck

    meta, arrays = _ck.read(path)
    if meta.get("kind") != "vector":
        raise ValueError(
            f"checkpoint at {path} holds a {meta.get('kind')!r}, not "
            "this vector")
    base = np.array(arrays["data"])
    if base.shape != (len(c),):
        raise ValueError(
            f"checkpoint length {base.shape} != live vector ({len(c)},)")
    for r in range(c.nshards):
        if r in lost_set:
            continue
        b, e = c._rank_window(r)
        if b < e:
            base[b:e] = np.asarray(c._local_values(r, b, e))
    return base.astype(np.dtype(c.dtype), copy=False)


def _apply_restore(c, payload, new_rt) -> None:
    kind, data = payload
    if kind == "merge":
        c._rebind(new_rt, None)
        c.assign_array(data)
    else:
        from . import checkpoint as _ck
        _swap_state(c, _ck.load(data, runtime=new_rt, reblock=True),
                    new_rt)


def rescue_session(err=None, *, lost_ranks: Optional[Sequence[int]] = None,
                   reason: str = "") -> ShrinkReport:
    """Shrink the session onto the surviving devices and rescue live
    state.  ``lost_ranks`` overrides the rank attribution carried by
    ``err`` (an env-injected loss names no rank: the LAST rank is
    presumed — deterministic, and on the tunneled topology the highest
    rank is the farthest hop).  Raises classified on an impossible
    rescue (below ``DR_TPU_ELASTIC_MIN_DEVICES``, reentrant loss, or a
    ``mesh.shrink`` fault); on success the global runtime IS the
    shrunken mesh and the report says what happened to every
    container."""
    global _shrinks, _rescued, _restored, _lost, _wall_s, _rescuing
    global _last_report
    from .. import obs as _obs
    from ..parallel import runtime as _rt

    if _rescuing:
        raise _resilience.ProgramError(
            "elastic: device loss during an active rescue — a nested "
            "shrink cannot run under the first one", site="mesh.shrink")
    if not _rt.is_initialized():
        raise _resilience.ProgramError(
            "elastic: no runtime to shrink (init() first)",
            site="mesh.shrink")
    rt = _rt.runtime()
    P = rt.nprocs
    if lost_ranks is not None:
        ranks = sorted({int(r) for r in lost_ranks})
    else:
        rank = getattr(err, "rank", None)
        # an unattributed loss presumes the LAST rank (deterministic;
        # the farthest hop on the tunneled topology)
        ranks = [int(rank)] if rank is not None else [P - 1]
    if not ranks or any(not 0 <= r < P for r in ranks):
        # a stale attribution (a rank id from the PRE-shrink topology)
        # must fail loudly: silently remapping it would rescue the
        # wrong rank's data and leave the dead device in the mesh
        raise _resilience.ProgramError(
            f"elastic: lost-rank attribution {ranks} is invalid for "
            f"the current {P}-rank mesh (stale topology?)",
            site="mesh.shrink")
    reason = reason or (f"{type(err).__name__}: {err}" if err is not None
                        else "requested shrink")
    min_dev = env_int("DR_TPU_ELASTIC_MIN_DEVICES", 1)
    survivors = [d for r, d in enumerate(rt.devices)
                 if r not in set(ranks)]
    t0 = time.perf_counter()
    sid = _obs.begin("mesh.shrink", cat="elastic",
                     lost=",".join(map(str, ranks)), nprocs=P)
    _rescuing = True
    report = ShrinkReport(reason=reason, lost_ranks=ranks,
                          nprocs_before=P, nprocs_after=len(survivors))
    try:
        # the device-loss event sits INSIDE the shrink span: a trace
        # reader sees what died and the rescue that answered, together
        _obs.event("device.lost", cat="elastic",
                   ranks=",".join(map(str, ranks)),
                   error=type(err).__name__ if err is not None
                   else "requested")
        _faults.fire("mesh.shrink", lost=tuple(ranks))
        if len(survivors) < max(1, min_dev):
            raise _resilience.DeviceLostError(
                f"elastic: cannot shrink below "
                f"DR_TPU_ELASTIC_MIN_DEVICES={min_dev} "
                f"({len(survivors)} survivor(s) of {P}); original "
                f"loss: {reason}", site="mesh.shrink")
        lost_set = set(ranks)
        # fates + host snapshots are decided on the OLD mesh, before
        # the runtime flips: a rescue gather reads only segments the
        # survivors still hold (host-staged v1), and a partially-dead
        # VECTOR merges its survivors' live segments with the
        # checkpointed values of the dead ones (per-segment restore;
        # matrices restore whole-container v1)
        fates = []
        for c in rt.live_containers():
            try:
                fates.append((c,) + _plan_fate(c, lost_set, P, reason))
            except Exception as e:  # fate/gather failed (including a
                # second classified fault riding the dispatches): the
                # rescue of the REST of the session must not die with
                # one container.  A registered checkpoint still
                # restores it (whole-container — the live gather
                # already failed); only a checkpoint-less container
                # degrades to lost (§16.3's matrix).
                path = checkpoint_path(c)
                if path is not None:
                    fates.append((c, "restore", ("ckpt", path)))
                else:
                    fates.append(
                        (c, "lost",
                         f"{reason}; rescue gather failed: {e!r}"))
        new_rt = _rt.init(survivors)
        for c, fate, payload in fates:
            name = type(c).__name__
            try:
                if fate == "rescue":
                    meta, arrays = payload
                    from . import checkpoint as _ck
                    _swap_state(c, _ck.rebuild(meta, arrays,
                                               runtime=new_rt,
                                               reblock=True), new_rt)
                elif fate == "restore":
                    _apply_restore(c, payload, new_rt)
            except Exception as e:
                # a container whose rebuild cannot land on the small
                # mesh (halo radius > new segment, unfittable cyclic
                # grid, corrupt checkpoint) degrades to LOST — the
                # session survives, the container fails loudly
                fate, payload = "lost", f"{reason}; {fate} failed: {e!r}"
            if fate == "lost":
                _poison(c, payload)
                report.lost += 1
                detail = payload
            elif fate == "restore":
                report.restored += 1
                # postmortem tag only — never the merged array itself
                detail = payload[0] if isinstance(payload, tuple) \
                    else str(payload)
            else:
                report.rescued += 1
                detail = ""
            report.fates.append((fate, name, detail))
        report.wall_s = round(time.perf_counter() - t0, 4)
        _shrinks += 1
        _rescued += report.rescued
        _restored += report.restored
        _lost += report.lost
        _wall_s += report.wall_s
        _last_report = report
        _publish(report)
        warn_fallback(
            "elastic",
            f"mesh shrank {P} -> {len(survivors)} device(s) (lost "
            f"rank(s) {ranks}): {report.rescued} rescued, "
            f"{report.restored} restored, {report.lost} lost; {reason}")
        return report
    except _resilience.ResilienceError as e:
        # even a FAILED rescue leaves a chapter: the classified error
        # the caller surfaces must be explainable from the artifact
        os.environ["_DR_TPU_ELASTIC_REASON"] = \
            f"shrink failed: {e}"[:200]
        raise
    finally:
        _rescuing = False
        _obs.end(sid, survivors=len(survivors), rescued=report.rescued,
                 restored=report.restored, lost=report.lost)


def try_rescue(err) -> bool:
    """The guarded form the automatic hooks use (retry, plan flush):
    attempt a shrink for ``err``; False when a rescue is impossible
    (reentrant, no runtime, floor reached, or a fault inside the
    shrink) — the caller then surfaces the ORIGINAL classified loss.
    Never raises."""
    try:
        rescue_session(err)
        return True
    except _resilience.ResilienceError as e:
        warn_fallback("elastic", f"rescue failed ({e}); surfacing the "
                                 "original device loss")
        return False


def _publish(report: ShrinkReport) -> None:
    """Publish the cumulative shrink chapter as env markers —
    ``resilience.degradation_story`` folds them into
    ``detail.degraded`` and they ride re-exec environments like the
    serve markers."""
    os.environ["_DR_TPU_ELASTIC_REASON"] = report.reason[:200]
    os.environ["_DR_TPU_ELASTIC_SHRINKS"] = str(_shrinks)
    os.environ["_DR_TPU_ELASTIC_LOST_RANKS"] = \
        ",".join(map(str, report.lost_ranks))
    os.environ["_DR_TPU_ELASTIC_RESCUED"] = str(_rescued)
    os.environ["_DR_TPU_ELASTIC_RESTORED"] = str(_restored)
    os.environ["_DR_TPU_ELASTIC_LOST"] = str(_lost)
    os.environ["_DR_TPU_ELASTIC_NPROCS"] = str(report.nprocs_after)
    os.environ["_DR_TPU_ELASTIC_WALL_S"] = f"{_wall_s:.4f}"


def reset() -> None:
    """Between-test hygiene (the conftest disarm fixture): clear the
    markers, the checkpoint registry, and the counters so one test's
    shrunken-mesh story cannot leak into the next."""
    global _shrinks, _rescued, _restored, _lost, _wall_s, _last_report
    global _rescuing
    _shrinks = _rescued = _restored = _lost = 0
    _wall_s = 0.0
    _last_report = None
    _rescuing = False
    _ckpts.clear()
    for m in MARKERS:
        os.environ.pop(m, None)
