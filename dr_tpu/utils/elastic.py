"""Elastic mesh degradation: survive device loss by SHRINKING the mesh
and rescuing live state (docs/SPEC.md §16).

The failure model (§10) classifies faults and routes the FIRST backend
touch, but before this module a device or host dying mid-session still
killed the job: every live container, deferred plan, and serve claim
died with it.  ROADMAP item 5 names the goal — "the degradation router
extended so a lost host downgrades the mesh instead of the job" — and
the re-placement recipe comes from the array-redistribution literature
(arXiv:2112.01075: any src→dst sharding change decomposes into
portable collective steps) plus Mesh-TensorFlow's topology-aware
layouts (arXiv:1811.02084).  This module is the session-level recovery
manager:

* **Detection** — a classified
  :class:`~.resilience.DeviceLostError`: raised by the ``device.lost``
  fault site (riding every TappedCache dispatch tap, so a device can
  die mid-eager-op, mid-plan-flush, or mid-serve-batch), by
  :func:`~.resilience.classify` on raw backend device-loss text, or by
  :func:`attribute` pinning a collective failure on a mesh rank.
* **Shrink** — :func:`rescue_session` computes the surviving-device
  mesh, rebuilds the global :class:`~..parallel.runtime.Runtime` on
  it, and walks the old runtime's live containers applying the
  rescue/restore/lost matrix:

  ========  =====================================================
  fate      when / how
  ========  =====================================================
  rescued   no segment lived on a lost rank: state moves through
            the host-staged gather/scatter route (the cross-mesh
            arm of :func:`redistribute` — the collective lowering,
            docs/SPEC.md §18, needs src and dst on ONE mesh, which
            a shrink never has) onto the shrunken mesh, bit-equal
            to the pre-fault value
  restored  segments died with the device but the container has a
            durable atomic checkpoint (utils/checkpoint.save
            registers every successful write here): reloaded onto
            the new mesh with ``reblock=True``
  lost      segments died and no checkpoint exists: the container
            is POISONED — any further use raises a classified
            ``DeviceLostError`` naming the loss, never a silent
            wrong answer
  ========  =====================================================

* **Automatic hooks** — armed by ``DR_TPU_ELASTIC=1``:
  :func:`~.resilience.retry` turns a ``DeviceLostError`` into
  shrink-and-retry (the serve daemon's batch dispatch already runs
  under it, so a resident claim degrades to the shrunken mesh without
  dropping clients); ``plan.flush`` re-records its unexecuted queue
  against the new mesh and re-flushes (the fresh mesh re-keys every
  program, so spmd_guard sees a fresh canonical digest).
  :func:`rescue_session` itself always works when called explicitly —
  the flag gates only the automatic recovery.

Every shrink publishes ``_DR_TPU_ELASTIC_*`` env markers;
``resilience.degradation_story`` folds them into the ``shrink``
chapter of ``detail.degraded`` (they ride re-exec environments like
the serve markers), and obs records a ``mesh.shrink`` span with the
device-loss event inside it.  ``DR_TPU_ELASTIC_MIN_DEVICES`` floors
the shrink — below it the rescue refuses classified.

**Grow-back (round 15, docs/SPEC.md §16.6)** makes elasticity
symmetric: shrink was the availability story, :func:`grow_session` is
the capacity story.  A recovered device (or a relay that comes back —
the serve daemon's route re-promotion, dr_tpu/serve) is RE-ADMITTED:
the runtime re-inits on the larger mesh and every live container
moves through ``redistribute()`` onto the grown layout between
batches/flushes.
Detection is a bounded, seeded-backoff recovery probe
(:class:`GrowSupervisor` riding ``resilience.backoff_schedule``;
PASSIVE — owners poll it between batches, never concurrent with a
live claim) over ``runtime.probe_recovered`` (fault site
``device.recover``).  The grow itself fires ``mesh.grow`` before the
runtime flips, so an injected fault fails the re-admission CLASSIFIED
with the session still serving correctly on the small mesh — a grow
must never make things worse.  Re-admission fates:

  ========  =====================================================
  fate      when / how
  ========  =====================================================
  moved     the container redistributes onto the grown mesh
            (in place, bit-equal — fresh dispatch keys, zero
            value-keyed recompiles under ``DR_TPU_SANITIZE=1``)
  kept      the move failed (a second fault mid-redistribute):
            the container STAYS on the old, still-live small
            mesh, value intact — never worse than not growing
  poisoned  a container the preceding shrink LOST stays poisoned
            — a grow never resurrects dead state as a silent
            wrong answer
  ========  =====================================================

``DR_TPU_ELASTIC_GROW=1`` arms the automatic polls (plan region exit,
serve dispatch loop); explicit :func:`grow_session` calls work either
way.  Every grow publishes ``_DR_TPU_ELASTIC_GROW_*`` markers —
``degradation_story`` folds them into a ``grow`` chapter — and obs
records a ``mesh.grow`` span.
"""

from __future__ import annotations

import os
import threading
import time
import weakref

import numpy as np
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from . import faults as _faults
from . import resilience as _resilience
from .env import env_flag, env_float, env_int
from .fallback import warn_fallback

__all__ = ["enabled", "redistribute", "rescue_session", "try_rescue",
           "attribute", "ShrinkReport", "note_checkpoint",
           "checkpoint_path", "shrink_count", "last_report", "is_lost",
           "reset", "MARKERS", "grow_enabled", "grow_session",
           "maybe_grow", "grow_count", "last_grow_report", "GrowReport",
           "GrowSupervisor", "GROW_MARKERS"]

#: env markers the shrink publishes for resilience.degradation_story
MARKERS = ("_DR_TPU_ELASTIC_REASON", "_DR_TPU_ELASTIC_SHRINKS",
           "_DR_TPU_ELASTIC_LOST_RANKS", "_DR_TPU_ELASTIC_RESCUED",
           "_DR_TPU_ELASTIC_RESTORED", "_DR_TPU_ELASTIC_LOST",
           "_DR_TPU_ELASTIC_NPROCS", "_DR_TPU_ELASTIC_WALL_S")

#: env markers a grow-back publishes (the ``grow`` story chapter)
GROW_MARKERS = ("_DR_TPU_ELASTIC_GROW_REASON", "_DR_TPU_ELASTIC_GROWS",
                "_DR_TPU_ELASTIC_GROW_NPROCS",
                "_DR_TPU_ELASTIC_GROW_MOVED",
                "_DR_TPU_ELASTIC_GROW_KEPT",
                "_DR_TPU_ELASTIC_GROW_WALL_S")

#: id(container) -> (weakref, checkpoint path); ids are recycled, so
#: the weakref is the liveness check (a dead ref invalidates the row)
_ckpts: dict = {}

_shrinks = 0
_rescued = 0
_restored = 0
_lost = 0
_wall_s = 0.0
_last_report: Optional["ShrinkReport"] = None
#: reentrancy latch: a device "dying" during an active rescue — or a
#: recovery probe landing mid-rescue — must not recurse into a second
#: re-layout under the first one's feet (shrink and grow share it)
_rescuing = False

# grow-back state (docs/SPEC.md §16.6)
_grows = 0
_moved = 0
_kept = 0
_grow_wall_s = 0.0
_last_grow: Optional["GrowReport"] = None
#: the automatic-poll supervisor (plan region exit / serve dispatch
#: loop share it through maybe_grow); re-armed per shrink epoch
_grow_sup: Optional["GrowSupervisor"] = None
_grow_sup_epoch = -1
#: two polling threads (a serve dispatch thread next to the host
#: thread's deferred regions) must not race one grow — the loser of
#: the non-blocking acquire just skips its poll
_grow_lock = threading.Lock()


def enabled() -> bool:
    """True when ``DR_TPU_ELASTIC=1`` arms the AUTOMATIC recovery
    hooks (retry / plan flush / serve batch).  Explicit
    :func:`rescue_session` calls work either way."""
    return env_flag("DR_TPU_ELASTIC")


def grow_enabled() -> bool:
    """True when ``DR_TPU_ELASTIC_GROW=1`` arms the AUTOMATIC grow-back
    polls (plan region exit, serve dispatch loop / route re-promotion).
    Explicit :func:`grow_session` calls work either way."""
    return env_flag("DR_TPU_ELASTIC_GROW")


def shrink_count() -> int:
    """Completed shrinks this process (the serve daemon diffs it to
    notice a mid-batch shrink)."""
    return _shrinks


def grow_count() -> int:
    """Completed grows this process (the serve daemon diffs it to
    notice a mid-batch grow-back, mirroring :func:`shrink_count`)."""
    return _grows


def last_report() -> Optional["ShrinkReport"]:
    return _last_report


def last_grow_report() -> Optional["GrowReport"]:
    return _last_grow


@dataclass
class ShrinkReport:
    """One completed shrink: what died, what survived, what it cost."""

    reason: str
    lost_ranks: List[int]
    nprocs_before: int
    nprocs_after: int
    rescued: int = 0
    restored: int = 0
    lost: int = 0
    wall_s: float = 0.0
    #: container fates for postmortems: (kind, repr, detail)
    fates: list = field(default_factory=list)


@dataclass
class GrowReport:
    """One completed grow-back: what was re-admitted, what moved."""

    reason: str
    nprocs_before: int
    nprocs_after: int
    moved: int = 0
    kept: int = 0
    poisoned: int = 0
    wall_s: float = 0.0
    #: container fates for postmortems: (kind, repr, detail)
    fates: list = field(default_factory=list)


# ---------------------------------------------------------------------------
# checkpoint registry (restore source)
# ---------------------------------------------------------------------------

def note_checkpoint(container, path: str) -> None:
    """Record ``path`` as ``container``'s durable restore source —
    ``utils.checkpoint.save`` calls this after every successful atomic
    write, so a later shrink can restore segments that died with a
    device.  A death callback prunes the row when the container is
    collected (guarded against id reuse by a newer registration), so
    a long-lived daemon checkpointing short-lived containers does not
    grow the registry without bound."""
    key = id(container)

    def _drop(ref, _key=key):
        row = _ckpts.get(_key)
        if row is not None and row[0] is ref:
            _ckpts.pop(_key, None)

    _ckpts[key] = (weakref.ref(container, _drop), str(path))


def checkpoint_path(container) -> Optional[str]:
    """The last checkpoint registered for ``container`` (and still
    on disk), or None."""
    row = _ckpts.get(id(container))
    if row is None:
        return None
    ref, path = row
    if ref() is not container:
        # the id was recycled by a different object: stale row
        _ckpts.pop(id(container), None)
        return None
    return path if os.path.exists(path) else None


# ---------------------------------------------------------------------------
# rank attribution
# ---------------------------------------------------------------------------

def attribute(err, rank: int) -> _resilience.DeviceLostError:
    """Attribute a collective/backend failure to a mesh rank: the
    classified :class:`DeviceLostError` the rescue hooks act on.  The
    multihost leg uses this when a peer process dies mid-collective —
    the failure names no rank by itself, the survivor's topology
    knowledge does."""
    de = _resilience.DeviceLostError(
        f"rank {rank} presumed lost: {type(err).__name__}: {err}",
        site=getattr(err, "site", "") or "device.lost", rank=int(rank))
    if isinstance(err, BaseException):
        de.__cause__ = err
    return de


# ---------------------------------------------------------------------------
# redistribute: the public re-layout API (docs/SPEC.md §18)
# ---------------------------------------------------------------------------

def redistribute(container, new_dist=None, *, runtime=None):
    """Re-lay ``container`` out IN PLACE under ``new_dist`` on
    ``runtime`` (default: the current global runtime) and return it.

    Vectors route through the collective redistribution engine
    (``parallel/redistribute``, docs/SPEC.md §18): when src and dst
    share a mesh the re-layout is ONE device-side exchange program
    (masked ppermute sequence on the shared ring machinery, peak
    extra memory bounded by the largest transfer bucket) that
    RECORDS into deferred plans; cross-runtime hops — and matrices —
    keep the host-staged v1 route (gather to the host, scatter
    through the target pack program), which is also the elastic
    rescue/grow fallback.  ``DR_TPU_REDISTRIBUTE`` overrides the
    autoselect; the two impls are bit-identical (the fuzz arm's
    contract).  In-place on purpose: every existing reference to the
    container (views, recorded plan ops, the elastic rescue walking a
    live session) stays valid across the move.

    ``new_dist`` (a ``block_distribution``, a sizes sequence, or None
    for the default even layout) is a ``distributed_vector`` contract;
    matrices re-block with their default partition on the target
    runtime.  Pending deferred work on the container flushes first
    (host-staged routes materialize; the collective route records or
    runs after the plan's queue in record order)."""
    from ..containers.distributed_vector import distributed_vector
    from ..parallel import runtime as _rt

    rt = runtime or _rt.runtime()
    if isinstance(container, distributed_vector):
        from ..parallel import redistribute as _rdx
        return _rdx.redistribute_vector(container, new_dist, rt)
    if new_dist is not None:
        raise ValueError(
            "explicit block distributions are a distributed_vector "
            "contract; matrices re-block with their default partition "
            "on the target runtime")
    from . import checkpoint as _ck
    meta, arrays = _ck.snapshot(container)
    fresh = _ck.rebuild(meta, arrays, runtime=rt, reblock=True)
    _swap_state(container, fresh, rt)
    return container


def _swap_state(container, fresh, rt) -> None:
    """Adopt ``fresh``'s state into ``container`` in place (same
    logical value, new mesh/layout) and fix the self-references the
    dict swap cannot carry (a vector's halo controller binds its
    owner)."""
    container.__dict__.clear()
    container.__dict__.update(fresh.__dict__)
    from ..containers.distributed_vector import distributed_vector
    if isinstance(container, distributed_vector) and container._hb.width:
        from ..parallel.halo import span_halo
        container._halo = span_halo(container)
    rt.register(container)


# ---------------------------------------------------------------------------
# poisoning (the 'lost' fate)
# ---------------------------------------------------------------------------

_poison_classes: dict = {}


def _poison(container, why: str) -> None:
    """Mark ``container`` LOST: its segments died with a device and no
    checkpoint exists.  Any further attribute access raises the
    classified ``DeviceLostError`` — a lost container must never feed
    a silent wrong answer into a surviving computation."""
    cls = type(container)
    pc = _poison_classes.get(cls)
    if pc is None:
        def __getattribute__(self, name):
            if name.startswith("__") or name == "_elastic_lost_reason":
                return object.__getattribute__(self, name)
            raise _resilience.DeviceLostError(
                f"{cls.__name__} state was lost with the failed "
                "device(s) "
                f"({object.__getattribute__(self, '_elastic_lost_reason')}); "
                "only a checkpoint that predates the loss can restore "
                "it", site="device.lost")

        pc = type("Lost" + cls.__name__, (cls,),
                  {"__getattribute__": __getattribute__})
        _poison_classes[cls] = pc
    container._elastic_lost_reason = why
    container.__class__ = pc


def is_lost(container) -> bool:
    """True when a shrink poisoned ``container`` (its class carries
    the loss marker)."""
    return type(container) in _poison_classes.values()


# ---------------------------------------------------------------------------
# the shrink itself
# ---------------------------------------------------------------------------

def _owned_ranks(container, P: int) -> set:
    """Mesh ranks holding any of ``container``'s segments.  Vectors
    read their block windows (a zero-size block owns nothing — the
    'team' case survives a loss elsewhere untouched); matrices tile
    over a grid PREFIX of the device list; unknown kinds
    conservatively claim every rank."""
    from ..containers.distributed_vector import distributed_vector

    if isinstance(container, distributed_vector):
        owned = set()
        for r in range(container.nshards):
            b, e = container._rank_window(r)
            if b < e:
                owned.add(r)
        return owned
    grid = getattr(container, "grid_shape", None) \
        or getattr(container, "grid", None)
    if grid is not None:
        tiles = 1
        for g in tuple(grid):
            tiles *= int(g)
        return set(range(min(P, tiles)))
    return set(range(P))


def _plan_fate(c, lost_set: set, P: int, reason: str):
    """Decide one container's fate on the OLD mesh and capture the host
    state the apply step needs:

    * untouched by the loss → ``("rescue", (meta, arrays))`` — full
      host snapshot, bit-equal to the pre-fault value;
    * a vector with segments on dead ranks AND a checkpoint →
      ``("restore", ("merge", values))`` — PER-SEGMENT hybrid: live
      survivor segments read from the device, dead segments from the
      last atomic checkpoint (the documented consistency contract:
      dead segments rewind to the checkpoint, survivors do not);
    * a dense/sparse matrix with a checkpoint →
      ``("restore", ("snap", (meta, arrays)))`` — PER-TILE hybrid
      (the vector contract extended, round 15): survivor tiles keep
      their live values, only tiles on dead ranks rewind;
    * any other checkpointed kind → ``("restore", ("ckpt", path))`` —
      whole-container reload (v1);
    * no checkpoint → ``("lost", reason)``.
    """
    from ..containers.distributed_vector import distributed_vector
    from ..containers.dense_matrix import dense_matrix
    from ..containers.sparse_matrix import sparse_matrix

    if not (_owned_ranks(c, P) & lost_set):
        from . import checkpoint as _ck
        return "rescue", _ck.snapshot(c)
    path = checkpoint_path(c)
    if path is None:
        return "lost", reason
    if isinstance(c, distributed_vector):
        return "restore", ("merge", _merge_vector_values(c, lost_set,
                                                         path))
    if isinstance(c, (dense_matrix, sparse_matrix)):
        return "restore", ("snap", _merge_matrix_snapshot(c, lost_set,
                                                          path))
    return "restore", ("ckpt", path)


def _merge_vector_values(c, lost_set: set, path: str):
    """The per-segment hybrid value: start from the checkpoint's
    logical array, overwrite every SURVIVING rank's window with its
    live device values (read shard-local — nothing is read from a dead
    rank)."""
    from . import checkpoint as _ck

    meta, arrays = _ck.read(path)
    if meta.get("kind") != "vector":
        raise ValueError(
            f"checkpoint at {path} holds a {meta.get('kind')!r}, not "
            "this vector")
    base = np.array(arrays["data"])
    if base.shape != (len(c),):
        raise ValueError(
            f"checkpoint length {base.shape} != live vector ({len(c)},)")
    for r in range(c.nshards):
        if r in lost_set:
            continue
        b, e = c._rank_window(r)
        if b < e:
            base[b:e] = np.asarray(c._local_values(r, b, e))
    return base.astype(np.dtype(c.dtype), copy=False)


def _merge_matrix_snapshot(c, lost_set: set, path: str):
    """The per-tile hybrid ``(meta, arrays)`` snapshot for tiled
    matrices (the vector per-segment contract, §16.3, extended): start
    from the checkpoint's logical state, overwrite every tile owned by
    a SURVIVING rank with its live values (tile segments read
    shard-local — nothing is read from a dead rank); tiles on dead
    ranks rewind to the checkpoint."""
    from . import checkpoint as _ck
    from ..containers.dense_matrix import dense_matrix

    meta, arrays = _ck.read(path)
    want = "dense_matrix" if isinstance(c, dense_matrix) \
        else "sparse_matrix"
    if meta.get("kind") != want:
        raise ValueError(
            f"checkpoint at {path} holds a {meta.get('kind')!r}, not "
            f"this {want}")
    if tuple(int(s) for s in meta.get("shape", c.shape)) \
            != tuple(c.shape):
        raise ValueError(
            f"checkpoint shape {meta.get('shape')} != live matrix "
            f"{tuple(c.shape)}")
    if isinstance(c, dense_matrix):
        base = np.array(arrays["data"])
        if base.shape != tuple(c.shape):
            raise ValueError(
                f"checkpoint shape {base.shape} != live matrix "
                f"{tuple(c.shape)}")
        for seg in c.__dr_segments__():
            if seg.__dr_rank__() in lost_set:
                continue
            # __dr_local__ (not materialize): the shard-local tile
            # read — materialize() unfolds the WHOLE matrix, which
            # both reads through the dead rank and pays P-1 full
            # gathers; the contract is "nothing is read from a dead
            # rank", same as the vector's _local_values
            base[seg.rb:seg.re, seg.cb:seg.ce] = \
                np.asarray(seg.__dr_local__())
        meta = dict(meta)
        return meta, {"data": base.astype(np.dtype(c.dtype), copy=False)}
    # sparse: survivors contribute their live tile triples; the
    # checkpoint contributes only the entries inside DEAD tiles'
    # row/col windows (entries nowhere near a dead tile are exactly the
    # survivors' — live wins everywhere it can)
    ck_rows = np.asarray(arrays["rows"])
    ck_cols = np.asarray(arrays["cols"])
    ck_vals = np.asarray(arrays["vals"])
    dead = np.zeros(ck_rows.shape, bool)
    rows, cols, vals = [], [], []
    for seg in c.__dr_segments__():
        inside = ((ck_rows >= seg.rb) & (ck_rows < seg.re)
                  & (ck_cols >= seg.cb) & (ck_cols < seg.ce))
        if seg.__dr_rank__() in lost_set:
            dead |= inside
        else:
            r, cc, v = seg.triples()
            rows.append(np.asarray(r))
            cols.append(np.asarray(cc))
            vals.append(np.asarray(v))
    rows.append(ck_rows[dead])
    cols.append(ck_cols[dead])
    vals.append(ck_vals[dead])
    meta = dict(meta)
    return meta, {
        "rows": np.concatenate(rows) if rows else np.zeros(0, np.int64),
        "cols": np.concatenate(cols) if cols else np.zeros(0, np.int64),
        "vals": np.concatenate(vals) if vals else np.zeros(0),
    }


def _apply_restore(c, payload, new_rt) -> None:
    kind, data = payload
    if kind == "merge":
        c._rebind(new_rt, None)
        c.assign_array(data)
    elif kind == "snap":
        from . import checkpoint as _ck
        meta, arrays = data
        _swap_state(c, _ck.rebuild(meta, arrays, runtime=new_rt,
                                   reblock=True), new_rt)
    else:
        from . import checkpoint as _ck
        _swap_state(c, _ck.load(data, runtime=new_rt, reblock=True),
                    new_rt)


def rescue_session(err=None, *, lost_ranks: Optional[Sequence[int]] = None,
                   reason: str = "") -> ShrinkReport:
    """Shrink the session onto the surviving devices and rescue live
    state.  ``lost_ranks`` overrides the rank attribution carried by
    ``err`` (an env-injected loss names no rank: the LAST rank is
    presumed — deterministic, and on the tunneled topology the highest
    rank is the farthest hop).  Raises classified on an impossible
    rescue (below ``DR_TPU_ELASTIC_MIN_DEVICES``, reentrant loss, or a
    ``mesh.shrink`` fault); on success the global runtime IS the
    shrunken mesh and the report says what happened to every
    container."""
    global _shrinks, _rescued, _restored, _lost, _wall_s, _rescuing
    global _last_report
    from .. import obs as _obs
    from ..parallel import runtime as _rt

    if _rescuing:
        raise _resilience.ProgramError(
            "elastic: device loss during an active rescue — a nested "
            "shrink cannot run under the first one", site="mesh.shrink")
    if not _rt.is_initialized():
        raise _resilience.ProgramError(
            "elastic: no runtime to shrink (init() first)",
            site="mesh.shrink")
    rt = _rt.runtime()
    P = rt.nprocs
    if lost_ranks is not None:
        ranks = sorted({int(r) for r in lost_ranks})
    else:
        rank = getattr(err, "rank", None)
        # an unattributed loss presumes the LAST rank (deterministic;
        # the farthest hop on the tunneled topology)
        ranks = [int(rank)] if rank is not None else [P - 1]
    if not ranks or any(not 0 <= r < P for r in ranks):
        # a stale attribution (a rank id from the PRE-shrink topology)
        # must fail loudly: silently remapping it would rescue the
        # wrong rank's data and leave the dead device in the mesh
        raise _resilience.ProgramError(
            f"elastic: lost-rank attribution {ranks} is invalid for "
            f"the current {P}-rank mesh (stale topology?)",
            site="mesh.shrink")
    reason = reason or (f"{type(err).__name__}: {err}" if err is not None
                        else "requested shrink")
    min_dev = env_int("DR_TPU_ELASTIC_MIN_DEVICES", 1)
    survivors = [d for r, d in enumerate(rt.devices)
                 if r not in set(ranks)]
    t0 = time.perf_counter()
    sid = _obs.begin("mesh.shrink", cat="elastic",
                     lost=",".join(map(str, ranks)), nprocs=P)
    _rescuing = True
    report = ShrinkReport(reason=reason, lost_ranks=ranks,
                          nprocs_before=P, nprocs_after=len(survivors))
    try:
        # the device-loss event sits INSIDE the shrink span: a trace
        # reader sees what died and the rescue that answered, together
        _obs.event("device.lost", cat="elastic",
                   ranks=",".join(map(str, ranks)),
                   error=type(err).__name__ if err is not None
                   else "requested")
        _faults.fire("mesh.shrink", lost=tuple(ranks))
        if len(survivors) < max(1, min_dev):
            raise _resilience.DeviceLostError(
                f"elastic: cannot shrink below "
                f"DR_TPU_ELASTIC_MIN_DEVICES={min_dev} "
                f"({len(survivors)} survivor(s) of {P}); original "
                f"loss: {reason}", site="mesh.shrink")
        lost_set = set(ranks)
        # fates + host snapshots are decided on the OLD mesh, before
        # the runtime flips: a rescue gather reads only segments the
        # survivors still hold (host-staged v1), and a partially-dead
        # VECTOR merges its survivors' live segments with the
        # checkpointed values of the dead ones (per-segment restore;
        # matrices restore whole-container v1)
        fates = []
        for c in rt.live_containers():
            try:
                fates.append((c,) + _plan_fate(c, lost_set, P, reason))
            except Exception as e:  # fate/gather failed (including a
                # second classified fault riding the dispatches): the
                # rescue of the REST of the session must not die with
                # one container.  A registered checkpoint still
                # restores it (whole-container — the live gather
                # already failed); only a checkpoint-less container
                # degrades to lost (§16.3's matrix).
                path = checkpoint_path(c)
                if path is not None:
                    fates.append((c, "restore", ("ckpt", path)))
                else:
                    fates.append(
                        (c, "lost",
                         f"{reason}; rescue gather failed: {e!r}"))
        new_rt = _rt.init(survivors)
        for c, fate, payload in fates:
            name = type(c).__name__
            try:
                if fate == "rescue":
                    meta, arrays = payload
                    from . import checkpoint as _ck
                    _swap_state(c, _ck.rebuild(meta, arrays,
                                               runtime=new_rt,
                                               reblock=True), new_rt)
                elif fate == "restore":
                    _apply_restore(c, payload, new_rt)
            except Exception as e:
                # a container whose rebuild cannot land on the small
                # mesh (halo radius > new segment, unfittable cyclic
                # grid, corrupt checkpoint) degrades to LOST — the
                # session survives, the container fails loudly
                fate, payload = "lost", f"{reason}; {fate} failed: {e!r}"
            if fate == "lost":
                _poison(c, payload)
                report.lost += 1
                detail = payload
            elif fate == "restore":
                report.restored += 1
                # postmortem tag only — never the merged array itself
                detail = payload[0] if isinstance(payload, tuple) \
                    else str(payload)
            else:
                report.rescued += 1
                detail = ""
            report.fates.append((fate, name, detail))
        report.wall_s = round(time.perf_counter() - t0, 4)
        _shrinks += 1
        _rescued += report.rescued
        _restored += report.restored
        _lost += report.lost
        _wall_s += report.wall_s
        _last_report = report
        _publish(report)
        warn_fallback(
            "elastic",
            f"mesh shrank {P} -> {len(survivors)} device(s) (lost "
            f"rank(s) {ranks}): {report.rescued} rescued, "
            f"{report.restored} restored, {report.lost} lost; {reason}")
        return report
    except _resilience.ResilienceError as e:
        # even a FAILED rescue leaves a chapter: the classified error
        # the caller surfaces must be explainable from the artifact
        os.environ["_DR_TPU_ELASTIC_REASON"] = \
            f"shrink failed: {e}"[:200]
        raise
    finally:
        _rescuing = False
        _obs.end(sid, survivors=len(survivors), rescued=report.rescued,
                 restored=report.restored, lost=report.lost)


def try_rescue(err) -> bool:
    """The guarded form the automatic hooks use (retry, plan flush):
    attempt a shrink for ``err``; False when a rescue is impossible
    (reentrant, no runtime, floor reached, or a fault inside the
    shrink) — the caller then surfaces the ORIGINAL classified loss.
    Never raises."""
    try:
        rescue_session(err)
        return True
    except _resilience.ResilienceError as e:
        warn_fallback("elastic", f"rescue failed ({e}); surfacing the "
                                 "original device loss")
        return False


def _publish(report: ShrinkReport) -> None:
    """Publish the cumulative shrink chapter as env markers —
    ``resilience.degradation_story`` folds them into
    ``detail.degraded`` and they ride re-exec environments like the
    serve markers."""
    os.environ["_DR_TPU_ELASTIC_REASON"] = report.reason[:200]
    os.environ["_DR_TPU_ELASTIC_SHRINKS"] = str(_shrinks)
    os.environ["_DR_TPU_ELASTIC_LOST_RANKS"] = \
        ",".join(map(str, report.lost_ranks))
    os.environ["_DR_TPU_ELASTIC_RESCUED"] = str(_rescued)
    os.environ["_DR_TPU_ELASTIC_RESTORED"] = str(_restored)
    os.environ["_DR_TPU_ELASTIC_LOST"] = str(_lost)
    os.environ["_DR_TPU_ELASTIC_NPROCS"] = str(report.nprocs_after)
    os.environ["_DR_TPU_ELASTIC_WALL_S"] = f"{_wall_s:.4f}"


# ---------------------------------------------------------------------------
# grow-back: re-admit recovered devices (docs/SPEC.md §16.6)
# ---------------------------------------------------------------------------

def grow_session(devices=None, *, reason: str = "",
                 require_growth: bool = True) -> "GrowReport":
    """Re-admit recovered capacity: re-init the runtime on ``devices``
    (default: the current mesh plus whatever ``runtime.probe_recovered``
    finds — the fault-injectable recovery probe) and
    ``redistribute()`` every live container onto the grown layout, in
    place.  The symmetric half of :func:`rescue_session`.

    ``require_growth=False`` admits a SAME-SIZE target — the serve
    daemon's route re-promotion (a claim degraded to the CPU route
    re-claiming the device route) is a capacity change the device
    COUNT cannot see.

    Failure contract ("grow must never make things worse"):

    * the ``mesh.grow`` fault site fires BEFORE the runtime rebuild —
      a fault there raises classified with the session untouched,
      still serving on the small mesh;
    * a per-container move failure degrades that container to
      ``kept`` — it stays on the old (still-live) runtime, value
      intact, announced through the fallback registry;
    * containers the preceding shrink POISONED stay poisoned — a grow
      never resurrects lost state as a silent wrong answer.

    On success the global runtime IS the grown mesh, the cumulative
    ``_DR_TPU_ELASTIC_GROW_*`` markers are published (the ``grow``
    chapter of ``resilience.degradation_story``), and obs records a
    ``mesh.grow`` span."""
    global _grows, _moved, _kept, _grow_wall_s, _rescuing, _last_grow
    from .. import obs as _obs
    from ..parallel import runtime as _rt

    if _rescuing:
        raise _resilience.ProgramError(
            "elastic: grow during an active rescue/grow — a second "
            "re-layout cannot run under the first one", site="mesh.grow")
    if not _rt.is_initialized():
        raise _resilience.ProgramError(
            "elastic: no runtime to grow (init() first)",
            site="mesh.grow")
    rt = _rt.runtime()
    P = rt.nprocs
    if devices is None:
        recovered = _rt.probe_recovered()
        if not recovered:
            raise _resilience.ProgramError(
                "elastic: recovery probe found no devices beyond the "
                f"current {P}-rank mesh — nothing to re-admit",
                site="mesh.grow")
        devices = rt.devices + list(recovered)
    devices = list(devices)
    if require_growth and len(devices) <= P:
        raise _resilience.ProgramError(
            f"elastic: grow target has {len(devices)} device(s), no "
            f"more than the current {P}-rank mesh — nothing to "
            "re-admit", site="mesh.grow")
    reason = reason or (f"re-admitting {len(devices) - P} recovered "
                        "device(s)")
    t0 = time.perf_counter()
    sid = _obs.begin("mesh.grow", cat="elastic", nprocs=P,
                     target=len(devices))
    _rescuing = True
    report = GrowReport(reason=reason, nprocs_before=P,
                        nprocs_after=len(devices))
    try:
        # the recovery event sits INSIDE the grow span, mirroring the
        # device-loss event inside mesh.shrink
        _obs.event("device.recover", cat="elastic",
                   admitted=len(devices) - P)
        _faults.fire("mesh.grow", target=len(devices))
        _validate_admitted(devices, rt)
        live = rt.live_containers()
        new_rt = _rt.init(devices)
        for c in live:
            name = type(c).__name__
            if is_lost(c):
                # the shrink's loss verdict survives the grow: only a
                # checkpoint that predates the loss can restore it
                report.poisoned += 1
                report.fates.append(("poisoned", name, ""))
                continue
            try:
                redistribute(c, None, runtime=new_rt)
                report.moved += 1
                report.fates.append(("moved", name, ""))
            except Exception as e:
                # never worse than not growing: the container stays on
                # the old (still-live) small runtime, value intact
                report.kept += 1
                report.fates.append(("kept", name, repr(e)))
                warn_fallback(
                    "elastic",
                    f"grow: {name} stays on the {P}-device mesh "
                    f"(move failed: {e!r})")
        report.wall_s = round(time.perf_counter() - t0, 4)
        _grows += 1
        _moved += report.moved
        _kept += report.kept
        _grow_wall_s += report.wall_s
        _last_grow = report
        _publish_grow(report)
        warn_fallback(
            "elastic",
            f"mesh grew {P} -> {len(devices)} device(s): "
            f"{report.moved} moved, {report.kept} kept, "
            f"{report.poisoned} left poisoned; {reason}")
        return report
    finally:
        _rescuing = False
        _obs.end(sid, nprocs=report.nprocs_after, moved=report.moved,
                 kept=report.kept, poisoned=report.poisoned)


def _validate_admitted(devices, rt) -> None:
    """A device LISTED is not a device ALIVE: PJRT enumeration is
    fixed at client init, so after a real mid-session loss the dead
    chip is still in ``jax.devices()`` — re-admitting it untested
    would oscillate shrink→grow→shrink, rewinding checkpointed
    segments every cycle.  Touch every device being ADMITTED — not
    already in the current mesh, keyed by (platform, id) so a serve
    route promotion validates its whole target — with a scalar round
    trip under the deadline watchdog.  A dead or wedged device fails
    the grow CLASSIFIED here, before the runtime flips and before
    anything moves (the supervisor then backs off; the session stays
    on the small mesh)."""
    import jax

    have = {(getattr(d, "platform", ""), d.id) for d in rt.devices}
    fresh = [d for d in devices
             if (getattr(d, "platform", ""), d.id) not in have]
    if not fresh:
        return

    def touch():
        for d in fresh:
            np.asarray(jax.device_put(np.float32(1.0), d))

    try:
        _resilience.with_deadline(touch, 30.0, site="mesh.grow",
                                  dump=False)
    except _resilience.ResilienceError:
        raise
    except Exception as e:
        raise _resilience.classified(
            f"elastic: re-admission validation failed — a listed "
            f"device did not answer the scalar touch ({e!r})",
            site="mesh.grow") from e


def _publish_grow(report: "GrowReport") -> None:
    """Publish the cumulative grow chapter as env markers —
    ``resilience.degradation_story`` folds them into
    ``detail.degraded.grow`` and they ride re-exec environments like
    the shrink markers."""
    os.environ["_DR_TPU_ELASTIC_GROW_REASON"] = report.reason[:200]
    os.environ["_DR_TPU_ELASTIC_GROWS"] = str(_grows)
    os.environ["_DR_TPU_ELASTIC_GROW_NPROCS"] = str(report.nprocs_after)
    os.environ["_DR_TPU_ELASTIC_GROW_MOVED"] = str(_moved)
    os.environ["_DR_TPU_ELASTIC_GROW_KEPT"] = str(_kept)
    os.environ["_DR_TPU_ELASTIC_GROW_WALL_S"] = f"{_grow_wall_s:.4f}"


class GrowSupervisor(_resilience.ProbeTimer):
    """Bounded, seeded-backoff recovery supervisor (SPEC §16.6).

    PASSIVE on purpose — it owns no thread: the claim holder polls it
    between batches/plan flushes (the one-TPU-process rule: a recovery
    probe must never run concurrent with a live claim, and the moment
    between batches is the only time the dispatch thread provably owns
    nothing in flight).  The pacing is the shared
    :class:`resilience.ProbeTimer` — deterministic seeded jitter, so
    tests reproduce every probe time — starting at
    ``DR_TPU_ELASTIC_GROW_PROBE_S``, doubling to the
    ``DR_TPU_ELASTIC_GROW_PROBE_CAP_S`` cap, and BOUNDED at
    ``DR_TPU_ELASTIC_GROW_PROBES`` total probes: a capacity that never
    comes back must not be probed forever."""

    def __init__(self, *, seed: int = 0):
        super().__init__(
            env_float("DR_TPU_ELASTIC_GROW_PROBE_S", 1.0),
            env_float("DR_TPU_ELASTIC_GROW_PROBE_CAP_S", 60.0),
            env_int("DR_TPU_ELASTIC_GROW_PROBES", 64), seed=seed)
        self.failures = 0
        self.grows = 0

    def poll(self, attempt) -> Optional["GrowReport"]:
        """Run ``attempt()`` if a probe is due.  ``attempt`` returns a
        :class:`GrowReport` on a completed grow, None when nothing has
        recovered yet; a CLASSIFIED failure (an injected
        ``device.recover``/``mesh.grow`` fault, a wedged probe) is
        caught, warned, and counted — the session stays exactly where
        it was and the backoff continues.  Never raises."""
        now = time.monotonic()
        if not self.due(now):
            return None
        self.advance(now)
        try:
            rep = attempt()
        except Exception as e:
            self.failures += 1
            warn_fallback(
                "elastic",
                f"grow probe {self.probes}/{self.budget} failed "
                f"({_resilience.classified(e)}); staying on the "
                "current mesh/route")
            return None
        if rep is not None:
            self.grows += 1
        return rep


def _probe_and_grow() -> Optional["GrowReport"]:
    """The default supervisor attempt: probe for returned devices
    (fault site ``device.recover``) and re-admit them."""
    from ..parallel import runtime as _rt
    recovered = _rt.probe_recovered()
    if not recovered:
        return None
    rt = _rt.runtime()
    return grow_session(
        devices=rt.devices + list(recovered),
        reason=f"recovery probe: {len(recovered)} device(s) returned")


def maybe_grow() -> Optional["GrowReport"]:
    """The between-flushes polling hook (plan region exit, serve
    dispatch loop): with ``DR_TPU_ELASTIC_GROW=1`` and a SHRUNKEN
    session, poll the bounded-backoff supervisor for returned devices
    and grow back when one is found.  One env check when disarmed; a
    full mesh (no shrink yet, or already grown back) never probes.
    Never raises — a failed probe/grow is warned and the session stays
    where it was."""
    global _grow_sup, _grow_sup_epoch
    if not grow_enabled() or _rescuing or _shrinks == 0:
        return None
    from ..parallel import runtime as _rt
    if not _rt.is_initialized():
        return None
    if not _grow_lock.acquire(blocking=False):
        return None  # another thread's poll is already in flight
    try:
        if _grow_sup is None or _grow_sup_epoch != _shrinks:
            # a NEW shrink re-arms the full probe budget
            _grow_sup = GrowSupervisor()
            _grow_sup_epoch = _shrinks
        rep = _grow_sup.poll(_probe_and_grow)
        if rep is not None:
            # a grow landed: RESET the backoff, don't exhaust — a
            # PARTIAL recovery (one of two lost devices returned)
            # must keep probing for the stragglers.  A fully
            # re-admitted mesh just runs the fresh budget dry
            # (probe_recovered returns []), still bounded.
            _grow_sup = GrowSupervisor()
            _grow_sup_epoch = _shrinks
        return rep
    finally:
        _grow_lock.release()


def reset() -> None:
    """Between-test hygiene (the conftest disarm fixture): clear the
    markers, the checkpoint registry, the counters, and the grow
    supervisor so one test's shrunken-mesh story (or its pending probe
    schedule) cannot leak into the next.  The supervisor is passive —
    polled, never a thread — so disarming it is just dropping it."""
    global _shrinks, _rescued, _restored, _lost, _wall_s, _last_report
    global _rescuing, _grows, _moved, _kept, _grow_wall_s, _last_grow
    global _grow_sup, _grow_sup_epoch
    _shrinks = _rescued = _restored = _lost = 0
    _wall_s = 0.0
    _last_report = None
    _rescuing = False
    _grows = _moved = _kept = 0
    _grow_wall_s = 0.0
    _last_grow = None
    _grow_sup = None
    _grow_sup_epoch = -1
    _ckpts.clear()
    for m in MARKERS + GROW_MARKERS:
        os.environ.pop(m, None)
