"""Classified failure taxonomy + retry/deadline substrate + the shared
graceful-degradation router for the first backend touch.

Before this module every failure path in the package was ad hoc:
``bench.py`` hand-rolled its relay TCP probe, probe retry, and tagged
CPU-fallback re-exec; ``__graft_entry__.py`` carried a second copy;
``tools/tune_tpu.py`` had none and could hang on a wedged relay.  The
multi-controller failure literature this build leans on (Mesh-TensorFlow
arxiv 1811.02084; array-redistribution collectives arxiv 2112.01075)
assumes exactly one classified-error + retry substrate under every
collective program — this module is it.

Three layers:

* **Taxonomy** — every backend failure is classified into
  :class:`TransientBackendError` (a second attempt may land),
  :class:`RelayDownError` (nothing is listening; retrying burns the
  caller's budget), :class:`DeviceOOM` (back off the problem size, not
  the clock), or :class:`ProgramError` (deterministic; retrying is
  futile).  :func:`classify` maps raw backend error text onto the
  taxonomy; :func:`classified` wraps an error into its class.
* **retry / with_deadline** — :func:`retry` runs a callable with
  exponential backoff and DETERMINISTIC seeded jitter
  (:func:`backoff_schedule` is a pure function of its arguments, so
  tests and SPMD processes agree on every delay).  :func:`with_deadline`
  bounds a possibly-hanging call (first touch, compile) with a watchdog
  thread; on expiry it dumps the active spmd_guard dispatch trace —
  the postmortem a silent hang can never give you — and raises
  :class:`DeadlineExpired`.
* **Degradation router** — :func:`relay_listening` /
  :func:`dead_relay` (the claim-free TCP reachability check, moved
  here from bench.py), :func:`route_first_touch` (the probe/retry/CPU
  decision bench.py's re-exec chain executes), and
  :func:`first_touch_or_cpu` (the in-process variant ``entry()`` and
  ``tools/tune_tpu.py`` share: dead relay -> switch to CPU before
  backend init; probe failure -> classified error, never a hang).

Fault injection (utils/faults.py) raises these classes at registered
sites, so every path here is exercisable on the 8-device CPU mesh.
See docs/SPEC.md "Failure model & recovery".
"""

from __future__ import annotations

import os
from .env import env_int, env_str
import random
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

__all__ = [
    "ResilienceError", "TransientBackendError", "RelayDownError",
    "DeviceOOM", "ProgramError", "CheckpointCorruptError",
    "DeadlineExpired", "ServerOverloaded", "ServerDraining",
    "DeviceLostError",
    "classify", "classified",
    "backoff_schedule", "ProbeTimer", "TokenBudget",
    "retry", "with_deadline", "dump_dispatch_trace", "dump_obs_tail",
    "relay_listening",
    "dead_relay", "route_first_touch", "first_touch_or_cpu",
    "FirstTouch", "degradation_story",
]


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

def _obs_tail():
    """Last-N trace events when tracing (dr_tpu/obs) is armed — the
    classified-error postmortem payload; None while tracing is off
    (one module-global check, no allocation)."""
    from ..obs import recorder as _rec
    if not _rec._armed:
        return None
    return _rec.tail()


class ResilienceError(RuntimeError):
    """Base of the classified failure taxonomy.  ``site`` names the
    injection/dispatch site that raised (empty when classified from a
    raw backend error with no site context).

    ``trace_tail``: when the tracing layer is armed (``DR_TPU_TRACE=1``,
    dr_tpu/obs) every classified error carries the last-N trace events
    as a POSTMORTEM — the generalization of :func:`with_deadline`'s
    dispatch-trace tail dump to every failure class (N =
    ``DR_TPU_TRACE_TAIL``); None while tracing is off."""

    def __init__(self, message: str, *, site: str = ""):
        super().__init__(message)
        self.site = site
        self.trace_tail = _obs_tail()


class TransientBackendError(ResilienceError):
    """The backend hiccuped (UNAVAILABLE / reset / wedged claim): a
    later attempt may land — the retryable class."""


class RelayDownError(ResilienceError):
    """The tunnel relay is not even listening: no claim can be served,
    retrying only burns the caller's timeout budget.  Degrade (CPU
    fallback) instead of retrying."""


class DeviceOOM(ResilienceError):
    """RESOURCE_EXHAUSTED: back off the problem size, not the clock."""


class ProgramError(ResilienceError):
    """Deterministic program/user error: retrying is futile; surface."""


class CheckpointCorruptError(ProgramError):
    """A checkpoint file is truncated/corrupt/foreign — the classified
    answer to a torn write (utils/checkpoint.py)."""


class DeadlineExpired(ResilienceError):
    """A watchdogged call overran its deadline (hung first touch /
    compile).  Raised by :func:`with_deadline` after the dispatch-trace
    dump; the hung worker thread is left behind (daemon)."""


class ServerOverloaded(ResilienceError):
    """The serving daemon's admission control rejected the request
    (queue depth or per-tenant in-flight cap exceeded — dr_tpu/serve).
    A classified rejection, never a hang: back off and resubmit, or
    spread the load — retrying immediately just re-trips the cap."""


class ServerDraining(ResilienceError):
    """The serving daemon is DRAINING (docs/SPEC.md §20.3): it stops
    admitting new work, finishes what it holds, flushes its journal,
    and exits.  A planned handoff, not a failure: a routed client
    re-hashes the tenant onto a live replica BEFORE the daemon dies
    (the whole point of announcing the drain), a single-daemon caller
    should reconnect elsewhere — retrying the draining daemon only
    burns the drain window."""


class DeviceLostError(ResilienceError):
    """A device (or the host behind it) died MID-SESSION: retrying the
    same program on the same mesh cannot succeed, and falling all the
    way back to CPU throws away the surviving devices.  The policy is
    ELASTIC DEGRADATION (utils/elastic.py, docs/SPEC.md §16): shrink
    the mesh to the survivors, rescue live container state, and carry
    on.  ``rank`` attributes the loss to a mesh rank when known (fault
    injection and collective attribution set it; a raw backend error
    classified here leaves it None and the rescue presumes the last
    rank)."""

    def __init__(self, message: str, *, site: str = "",
                 rank: Optional[int] = None):
        super().__init__(message, site=site)
        self.rank = rank


# substring evidence for each class (matched case-insensitively),
# checked in order: OOM first (its messages often also contain
# transient-looking words), then relay-down, then the transient bucket;
# anything else is a program error.  The OOM tokens are ANCHORED
# ("out of memory", not bench._measure's looser "emory" net): as a
# global classifier gating retry decisions, a transient error that
# merely MENTIONS memory must stay retryable.
_OOM_TOKENS = ("resource_exhausted", "out of memory")
# device-loss evidence is checked BEFORE the transient bucket: the raw
# backend text for a dead chip often also carries transient-looking
# words ("unavailable"), and retrying on the dead mesh cannot land —
# the policy is an elastic shrink, not backoff
_DEVICE_LOST_TOKENS = ("device_lost", "device lost", "data_loss",
                       "device failure", "hardware failure")
_RELAY_TOKENS = ("relay not listening", "connection refused",
                 "econnrefused", "failed to connect")
# no bare "exceeded": deterministic errors phrase limits that way too
# ("maximum recursion depth exceeded") and must NOT become retryable;
# the probe-timeout message matches via "wedged"/"timeout" instead
_TRANSIENT_TOKENS = ("unavailable", "deadline_exceeded", "aborted",
                     "socket closed", "connection reset", "wedged",
                     "timed out", "timeout")


def classify(err) -> type:
    """Map an exception or raw error text onto the taxonomy.  Already
    classified errors keep their class."""
    if isinstance(err, ResilienceError):
        return type(err)
    text = (err if isinstance(err, str)
            else f"{type(err).__name__}: {err}").lower()
    for tokens, cls in ((_OOM_TOKENS, DeviceOOM),
                        (_DEVICE_LOST_TOKENS, DeviceLostError),
                        (_RELAY_TOKENS, RelayDownError),
                        (_TRANSIENT_TOKENS, TransientBackendError)):
        if any(t in text for t in tokens):
            return cls
    return ProgramError


def classified(err, *, site: str = "") -> ResilienceError:
    """Return ``err`` as a taxonomy instance: pass-through when already
    classified, else wrap (keeping the original as ``__cause__``)."""
    if isinstance(err, ResilienceError):
        if site and not err.site:
            err.site = site
        return err
    cls = classify(err)
    msg = err if isinstance(err, str) else f"{type(err).__name__}: {err}"
    out = cls(msg, site=site)
    if isinstance(err, BaseException):
        out.__cause__ = err
    return out


# ---------------------------------------------------------------------------
# retry with deterministic backoff
# ---------------------------------------------------------------------------

def backoff_schedule(attempts: int, *, base: float = 0.05,
                     factor: float = 2.0, max_delay: float = 30.0,
                     jitter: float = 0.25, seed: int = 0) -> list:
    """Exponential backoff delays with DETERMINISTIC jitter: a pure
    function of its arguments (seeded ``random.Random``), so tests — and
    SPMD processes sharing a seed — reproduce every delay exactly.
    Jitter multiplies each delay by a factor in [1-jitter, 1+jitter]."""
    rng = random.Random(seed)
    out = []
    for i in range(max(0, attempts)):
        d = min(max_delay, base * (factor ** i))
        out.append(d * (1.0 + jitter * (2.0 * rng.random() - 1.0)))
    return out


class ProbeTimer:
    """Bounded seeded-backoff probe timer — the pacing core shared by
    the elastic recovery supervisor (§16.6) and the serve circuit
    breakers / respawn supervisor (§20.1): delays ride
    :func:`backoff_schedule` (deterministic jitter, so tests
    reproduce every probe time) from ``base`` doubling to ``cap``,
    BOUNDED at ``budget`` total probes — a capacity/replica that
    never comes back is not probed forever."""

    def __init__(self, base: float, cap: float, budget: int, *,
                 seed: int = 0):
        self.budget = int(budget)
        self._delays = backoff_schedule(
            self.budget, base=max(0.0, float(base)), factor=2.0,
            max_delay=max(0.0, float(cap)), seed=seed)
        self.probes = 0
        self._next = time.monotonic() + (self._delays[0]
                                         if self._delays else 0.0)

    def exhausted(self) -> bool:
        return self.probes >= self.budget

    def due(self, now: Optional[float] = None) -> bool:
        return not self.exhausted() and \
            (time.monotonic() if now is None else now) >= self._next

    def advance(self, now: Optional[float] = None) -> None:
        """One probe taken: schedule the next."""
        now = time.monotonic() if now is None else now
        self.probes += 1
        if self.probes < self.budget:
            self._next = now + self._delays[self.probes]


class TokenBudget:
    """Shared retry token bucket (docs/SPEC.md §20.2).

    Per-call retry loops compose multiplicatively: N clients x R
    attempts each x M replicas re-hashed is N*R*M connection storms
    against a fleet that is ALREADY failing — the retry amplification
    the control plane exists to stop.  One bucket is shared by every
    retry loop in the process: a retry SPENDS a token
    (:meth:`spend`), a successful request REFILLS a fraction of one
    (:meth:`note_success`, ``ratio`` per success, capped at
    ``capacity``).  While the fleet is healthy the bucket stays full
    and retries behave exactly as before; when everything is failing
    the bucket drains in ``capacity`` retries total — fleet-wide —
    and every later failure surfaces classified in one attempt, fast,
    instead of a backoff storm amplifying the overload.

    Thread-safe; ``capacity=0`` disarms retries outright.  Pass one
    to :func:`retry` via ``budget=`` — an exhausted bucket makes the
    loop re-raise the classified error instead of sleeping."""

    def __init__(self, capacity: float, ratio: float = 0.1):
        self.capacity = max(0.0, float(capacity))
        self.ratio = max(0.0, float(ratio))
        self._tokens = self.capacity
        self._lock = threading.Lock()
        self.spent = 0
        self.denied = 0

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def spend(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens; False (and nothing taken) when the
        bucket cannot cover them — the caller must NOT retry."""
        with self._lock:
            if self._tokens < n:
                self.denied += 1
                return False
            self._tokens -= n
            self.spent += 1
            return True

    def note_success(self) -> None:
        """A request landed: bank ``ratio`` of a token (capped)."""
        with self._lock:
            self._tokens = min(self.capacity, self._tokens + self.ratio)

    def snapshot(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "capacity": self.capacity, "ratio": self.ratio,
                    "spent": self.spent, "denied": self.denied}


def retry(fn: Callable, *, attempts: int = 3, base: float = 0.05,
          factor: float = 2.0, max_delay: float = 30.0,
          jitter: float = 0.25, seed: int = 0,
          retry_on: Sequence[type] = (TransientBackendError,),
          sleep: Callable = time.sleep, on_retry: Callable = None,
          deadline_s: Optional[float] = None,
          budget: Optional[TokenBudget] = None):
    """Run ``fn()`` with classified retries.

    Every raised error is classified first; only instances of
    ``retry_on`` classes are retried (default: transients only — a dead
    relay or an OOM must be routed, not hammered).  Delays come from
    :func:`backoff_schedule`, so the whole timing story is deterministic
    given ``seed``.  ``on_retry(attempt_index, error, delay)`` observes
    each retry.  The final failure is re-raised CLASSIFIED.

    ``deadline_s`` makes the loop deadline-aware: a retry whose backoff
    delay would land past the budget (measured from the first attempt)
    is not taken — the classified error surfaces instead of a retry
    nobody is still waiting on (the serve client's policy, SPEC §14.6).

    ``budget`` threads a shared :class:`TokenBudget` through the loop
    (SPEC §20.2): each retry spends one token first, and an exhausted
    bucket re-raises the classified error immediately — no backoff
    sleep, no attempt — so a fleet-wide failure degrades into fast
    classified errors instead of a process-wide retry storm.

    Elastic degradation (docs/SPEC.md §16): when ``DR_TPU_ELASTIC=1``,
    a :class:`DeviceLostError` raised by the protected call triggers a
    mesh SHRINK (``utils.elastic.try_rescue`` — survivors re-meshed,
    live containers rescued) and the call is retried on the shrunken
    mesh — device loss becomes a degraded retry instead of a dead job.
    With elastic off (or the rescue impossible) the loss surfaces
    classified as before."""
    if attempts < 1:
        # a config-derived attempts=0 must fail loudly, not silently
        # skip the protected call and hand back None
        raise ValueError(f"retry needs attempts >= 1, got {attempts}")
    delays = backoff_schedule(attempts - 1, base=base, factor=factor,
                              max_delay=max_delay, jitter=jitter, seed=seed)
    t0 = time.monotonic()
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:
            ce = classified(e)
            recoverable = isinstance(ce, tuple(retry_on))
            shrunk = False
            if (not recoverable and isinstance(ce, DeviceLostError)
                    and i < attempts - 1):
                from . import elastic
                shrunk = recoverable = (elastic.enabled()
                                        and elastic.try_rescue(ce))
            if i == attempts - 1 or not recoverable:
                if ce is e:
                    raise  # already classified: keep its cause chain
                raise ce from e
            if deadline_s is not None and not shrunk and \
                    time.monotonic() - t0 + delays[i] > deadline_s:
                if ce is e:
                    raise
                raise ce from e
            if budget is not None and not shrunk \
                    and not budget.spend():
                # shared retry budget exhausted (SPEC §20.2): surface
                # the classified error NOW — fast, no backoff — rather
                # than join a fleet-wide retry storm
                from .. import obs as _obs
                _obs.count("resilience.retry_budget_denied")
                if ce is e:
                    raise
                raise ce from e
            if on_retry is not None:
                on_retry(i, ce, delays[i])
            from .. import obs as _obs
            _obs.event("retry", cat="resilience", attempt=i,
                       error=type(ce).__name__, site=ce.site,
                       delay_s=round(delays[i], 4), shrunk=shrunk)
            _obs.count("resilience.retries")
            if not shrunk:
                # the shrink already paid the recovery cost; a backoff
                # delay on top would just stall the rescued mesh
                sleep(delays[i])


# ---------------------------------------------------------------------------
# deadline watchdog + dispatch-trace escalation
# ---------------------------------------------------------------------------

def dump_dispatch_trace(file=None, limit: int = 40) -> int:
    """Print the tail of the active spmd_guard dispatch trace — the
    information a hang postmortem cannot give you (which program the
    process was enqueueing when it stopped making progress).  Returns
    the number of entries printed (0 when no guard is active)."""
    from . import spmd_guard
    file = file or sys.stderr
    g = spmd_guard.active()
    if g is None or not g.trace:
        print("resilience: no active spmd_guard dispatch trace "
              "(run inside spmd_guard.guard() for a dispatch postmortem)",
              file=file)
        return 0
    tail = g.trace[-limit:]
    start = len(g.trace) - len(tail)
    print(f"resilience: last {len(tail)} of {len(g.trace)} recorded "
          "dispatches before the deadline expired:", file=file)
    for i, entry in enumerate(tail, start=start):
        print(f"  [{i}] {entry}", file=file)
    return len(tail)


def dump_obs_tail(file=None) -> int:
    """Print the tail of the obs trace ring (when ``DR_TPU_TRACE=1``)
    — the unified-trace sibling of :func:`dump_dispatch_trace`: spans,
    site visits, and injected faults leading up to the failure.
    Returns the number of events printed (0 while tracing is off)."""
    tail = _obs_tail()
    if not tail:
        return 0
    file = file or sys.stderr
    print(f"resilience: last {len(tail)} obs trace event(s) before "
          "the failure:", file=file)
    for ev in tail:
        args = ev.get("args") or {}
        extra = " ".join(f"{k}={v}" for k, v in args.items())
        dur = f" dur={ev['dur']}us" if "dur" in ev else ""
        print(f"  [{ev.get('ts', 0)}] {ev.get('name')}"
              f" ({ev.get('cat', '')}){dur} {extra}".rstrip(),
              file=file)
    return len(tail)


def with_deadline(fn: Callable, timeout_s: float, *, site: str = "",
                  dump: bool = True, file=None):
    """Run ``fn()`` under a watchdog: its value (or its exception) when
    it finishes within ``timeout_s``; :class:`DeadlineExpired` — after
    an spmd_guard dispatch-trace dump — when it hangs.  The worker is a
    daemon thread, so a truly wedged call (a PJRT claim against a dead
    relay) cannot pin process exit."""
    box = {}

    def run():
        try:
            box["value"] = fn()
        except BaseException as e:  # must cross the thread boundary
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        from .. import obs as _obs
        _obs.event("deadline.expired", cat="resilience", site=site,
                   timeout_s=timeout_s)
        if dump:
            dump_dispatch_trace(file)
            dump_obs_tail(file)
        name = site or getattr(fn, "__name__", "call")
        raise DeadlineExpired(
            f"{name} exceeded its {timeout_s:.1f}s deadline "
            "(hung first touch / compile?)", site=site)
    if "error" in box:
        raise box["error"]
    return box.get("value")


# ---------------------------------------------------------------------------
# relay reachability (moved here from bench.py — one copy, three callers)
# ---------------------------------------------------------------------------

def relay_listening() -> bool:
    """Claim-free reachability check of the loopback tunnel relay: a TCP
    connect costs nothing server-side, unlike a jax claim.  Gates the
    retry leg — when the relay is not even listening (a down/restarting
    relay, vs a wedged claim path), a second claim cannot succeed and
    the CPU fallback should run immediately.  A connect TIMEOUT (a
    SYN-dropping/firewalled relay — the half-dead state rounds 2/3
    hit) also counts as not-listening, since a claim against it would
    just burn the probe watchdog; truly unknown errors still count as
    listening so an unusual relay config never disables the retry.
    ``DR_TPU_RELAY_UNKNOWN=down`` flips that last policy for ops use."""
    import socket
    port = env_int("DR_TPU_RELAY_PROBE_PORT", 8082)
    s = socket.socket()
    s.settimeout(3)
    try:
        s.connect(("127.0.0.1", port))
        return True
    except (ConnectionRefusedError, socket.timeout, TimeoutError):
        return False
    except Exception:
        return env_str("DR_TPU_RELAY_UNKNOWN", "up") != "down"
    finally:
        s.close()


def dead_relay(listening: Optional[Callable] = None) -> bool:
    """True when the tunneled (axon) platform is in play but its relay
    is not even listening — a state where no claim can be served and
    probing only burns the caller's timeout budget.  ``listening``
    overrides the reachability check (bench.py threads its
    monkeypatchable module global through here)."""
    import jax
    return ("axon" in str(getattr(jax.config, "jax_platforms", ""))
            and not (listening or relay_listening)())


# ---------------------------------------------------------------------------
# first-touch degradation router
# ---------------------------------------------------------------------------

@dataclass
class FirstTouch:
    """Decision record of one first-backend-touch attempt.

    ``decision``:

    * ``"ok"``    — devices probed; carry on.
    * ``"retry"`` — probe failed but the relay still listens (wedged
      claim path): retry once in a FRESH process (an in-process retry
      would join the hang on jax's singleton init lock).
    * ``"cpu"``   — unrecoverable here (dead relay, or the retry leg
      failed too): degrade to a tagged CPU run.
    """

    decision: str
    devices: Optional[list] = None
    err: Optional[str] = None
    probe_wall_s: float = 0.0
    probe_skipped: bool = False


#: the degradation reason used whenever the dead-relay fast path fires
RELAY_DOWN_REASON = "relay not listening (TCP check)"


def route_first_touch(timeout_s: float, *, retried: bool = False,
                      probe: Optional[Callable] = None,
                      is_dead: Optional[Callable] = None,
                      listening: Optional[Callable] = None) -> FirstTouch:
    """ONE probe/degradation decision, shared by bench.py (which maps it
    onto its re-exec chain), ``entry()`` and ``tools/tune_tpu.py``
    (which map it onto in-process CPU fallback / classified errors).

    * Dead relay and not yet retried -> ``"cpu"`` without spending the
      probe timeout (the watchdog would burn the whole budget for a
      claim that cannot be served).
    * Probe success -> ``"ok"`` (with the probe wall time recorded for
      the degradation story).
    * First failure with the relay still listening -> ``"retry"``.
    * Anything else -> ``"cpu"``.
    """
    if probe is None:
        from ..parallel import runtime as _rt
        probe = _rt.probe_devices
    is_dead = is_dead or (lambda: dead_relay(listening))
    if not retried and is_dead():
        return FirstTouch(
            "cpu", err=f"{RELAY_DOWN_REASON}; probe skipped, retry skipped",
            probe_skipped=True)
    t0 = time.perf_counter()
    devs, err = probe(timeout_s)
    wall = round(time.perf_counter() - t0, 3)
    if devs is not None:
        return FirstTouch("ok", devices=devs, probe_wall_s=wall)
    if not retried and (listening or relay_listening)():
        return FirstTouch("retry", err=err, probe_wall_s=wall)
    return FirstTouch("cpu", err=err, probe_wall_s=wall)


def first_touch_or_cpu(timeout_s: float, *, tag: str = "first_touch",
                       file=None):
    """In-process first touch for tools that cannot re-exec (``entry()``,
    ``tools/tune_tpu.py``): returns ``(devices, degraded_reason|None)``.

    A dead relay switches the platform to CPU BEFORE backend init (the
    jittable work is platform-agnostic; an in-process retry after a HUNG
    probe would deadlock on jax's backend-init lock, which is why
    bench.py re-execs instead) and reports the degradation reason.  A
    probe failure raises the CLASSIFIED error — a recorded, typed
    failure always beats the eternal hang a wedged relay produces."""
    import jax
    degraded = None
    ft = route_first_touch(timeout_s, probe=None)
    if ft.decision == "cpu" and ft.probe_skipped:
        degraded = RELAY_DOWN_REASON
        print(f"{tag}: {degraded}; falling back to CPU", file=file or
              sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        ft = route_first_touch(timeout_s, retried=True)
    if ft.decision != "ok":
        raise classified(f"device init failed: {ft.err}", site=tag)
    return ft.devices, degraded


def degradation_story(env=None) -> Optional[dict]:
    """Assemble the degradation story a tagged CPU fallback run must
    carry into its JSON artifact (fallback reason, ORIGINAL probe error,
    retry count, probe wall time) from the ``_DR_TPU_BENCH_*`` markers
    the re-exec chain threads through the environment.  Served runs
    (dr_tpu/serve) add their own ``_DR_TPU_SERVE_*`` markers — queue
    depth high-water, shed count, daemon restarts — published by the
    daemon when it degrades or stops, so ``detail.degraded`` tells the
    FULL story of a served session, not just the first-touch leg.
    Elastic shrinks (utils/elastic.py, SPEC §16) publish
    ``_DR_TPU_ELASTIC_*`` markers the same way — a run whose mesh
    shrank mid-session carries a ``shrink`` chapter (lost ranks,
    rescued/restored/lost container counts, shrink wall time) in every
    artifact, re-execs included (the markers ride the inherited
    environment like the serve ones).  Grow-backs (SPEC §16.6) add a
    ``grow`` chapter from the ``_DR_TPU_ELASTIC_GROW_*`` markers —
    grow count, moved/kept container counts, the re-admitted mesh size
    — so an artifact whose session shrank AND recovered tells the
    whole arc.  None when the run is not degraded."""
    env = os.environ if env is None else env
    reason = env.get("_DR_TPU_BENCH_DEGRADED")
    serve_reason = env.get("_DR_TPU_SERVE_DEGRADED")
    shrink_reason = env.get("_DR_TPU_ELASTIC_REASON")
    grow_reason = env.get("_DR_TPU_ELASTIC_GROW_REASON")
    # dead-replica rehash marker (serve/router.py, SPEC §19.3): a
    # fleet that lost a replica is a degraded run even when every
    # surviving daemon is healthy
    router_reason = env.get("_DR_TPU_SERVE_ROUTER_REASON")
    # control-plane markers (SPEC §20): a respawned replica or a
    # truncated journal tail means a death/corruption happened this
    # session — a story even when the fleet has fully recovered
    # (drains alone are planned maintenance and only ride along)
    respawns = env.get("_DR_TPU_SERVE_RESPAWNS")
    journal_cut = env.get("_DR_TPU_SERVE_JOURNAL_TRUNCATED")
    if not reason and not serve_reason and not shrink_reason \
            and not grow_reason and not router_reason \
            and not respawns and not journal_cut:
        return None
    story = {"reason": reason or serve_reason or shrink_reason
             or grow_reason or router_reason
             or (respawns and f"{respawns} serve replica(s) respawned")
             or f"journal tail truncated ({journal_cut} bytes)",
             "retries": int(env.get("_DR_TPU_BENCH_RETRIES", "0") or 0),
             "probe_wall_s": float(env.get("_DR_TPU_BENCH_PROBE_S", "0")
                                   or 0.0)}
    first = env.get("_DR_TPU_BENCH_FIRST_ERR")
    if first:
        story["first_error"] = first
    serve = {}
    for key, marker in (("reason", "_DR_TPU_SERVE_DEGRADED"),
                        ("queue_depth", "_DR_TPU_SERVE_QUEUE_DEPTH"),
                        ("shed", "_DR_TPU_SERVE_SHED"),
                        ("restarts", "_DR_TPU_SERVE_RESTARTS"),
                        ("router_dead", "_DR_TPU_SERVE_ROUTER_DEAD"),
                        ("router_reason",
                         "_DR_TPU_SERVE_ROUTER_REASON"),
                        # control plane (SPEC §20): planned drains,
                        # supervisor respawns, breaker re-admissions,
                        # and the journal-recovery counts
                        ("drains", "_DR_TPU_SERVE_DRAINS"),
                        ("drained_rehashes",
                         "_DR_TPU_SERVE_ROUTER_DRAINED"),
                        ("respawns", "_DR_TPU_SERVE_RESPAWNS"),
                        ("router_recovered",
                         "_DR_TPU_SERVE_ROUTER_RECOVERED"),
                        ("journal_recovered",
                         "_DR_TPU_SERVE_JOURNAL_RECOVERED"),
                        ("journal_truncated",
                         "_DR_TPU_SERVE_JOURNAL_TRUNCATED")):
        raw = env.get(marker)
        if raw not in (None, ""):
            serve[key] = raw if key in ("reason", "router_reason") \
                else int(raw)
    if serve:
        story["serve"] = serve
    shrink = {}
    for key, marker, conv in (
            ("reason", "_DR_TPU_ELASTIC_REASON", str),
            ("shrinks", "_DR_TPU_ELASTIC_SHRINKS", int),
            ("lost_ranks", "_DR_TPU_ELASTIC_LOST_RANKS", str),
            ("rescued", "_DR_TPU_ELASTIC_RESCUED", int),
            ("restored", "_DR_TPU_ELASTIC_RESTORED", int),
            ("lost", "_DR_TPU_ELASTIC_LOST", int),
            ("nprocs", "_DR_TPU_ELASTIC_NPROCS", int),
            ("wall_s", "_DR_TPU_ELASTIC_WALL_S", float)):
        raw = env.get(marker)
        if raw not in (None, ""):
            shrink[key] = conv(raw)
    if shrink:
        story["shrink"] = shrink
    grow = {}
    for key, marker, conv in (
            ("reason", "_DR_TPU_ELASTIC_GROW_REASON", str),
            ("grows", "_DR_TPU_ELASTIC_GROWS", int),
            ("moved", "_DR_TPU_ELASTIC_GROW_MOVED", int),
            ("kept", "_DR_TPU_ELASTIC_GROW_KEPT", int),
            ("nprocs", "_DR_TPU_ELASTIC_GROW_NPROCS", int),
            ("wall_s", "_DR_TPU_ELASTIC_GROW_WALL_S", float)):
        raw = env.get(marker)
        if raw not in (None, ""):
            grow[key] = conv(raw)
    if grow:
        story["grow"] = grow
    return story
