"""Loud (once-per-site) materialize-fallback warnings.

Some algorithm configurations leave the fused shard_map fast paths and
run through a materialized logical array instead (device-side gather →
global op → re-scatter).  After the round-5 burn-down, no
SINGLE-component distributed shape materializes; the warned routes
left are the scan catch-all (multi-component or host, non-distributed,
inputs), reduce's multi-component custom-op range (a transform over
a zip with an unclassified op — round 6), and the deferred-plan
``"plan"`` route (round 8): a non-fusible op (sort, gemv, a
materialize-route transform) forcing a recorded region to flush — the
dispatch-fusion cliff made audible.
Each is correct but collective-suboptimal, and VERDICT r3 item 5 calls
the silent version a perf cliff: this module makes every such fallback
announce itself ONCE per (operation, reason) pair so users see the
cliff without drowning in repeats.  ``DR_TPU_SILENCE_FALLBACKS=1``
disables the warnings (for tests and users who accepted the cost).
"""

from __future__ import annotations

from .env import env_flag
import warnings

from . import faults as _faults

_seen: set = set()


class MaterializeFallbackWarning(UserWarning):
    """An operation left its fused fast path for a materialized run."""


def reset() -> None:
    """Clear the once-per-site memory so tests (and long-lived servers
    that want a fresh warning epoch) see each fallback announce itself
    again."""
    _seen.clear()


def warn_fallback(op: str, reason: str) -> None:
    """Warn (once per site) that ``op`` is materializing because of
    ``reason``.  Cheap on the hot path: a set lookup after the first.
    Every call — silenced or repeated — routes through the
    ``fallback.warn`` fault-registry site first, so a chaos run counts
    materialize fallbacks (a degraded-but-correct outcome) instead of
    losing them to the once-per-site budget — and, when tracing is
    armed, that fire lands each fallback as a ``site`` trace event
    (dr_tpu/obs), with the ``fallback.warns`` counter alongside."""
    _faults.fire("fallback.warn", op=op, reason=reason)
    from .. import obs as _obs
    _obs.count("fallback.warns")
    key = (op, reason)
    if key in _seen:
        return
    if env_flag("DR_TPU_SILENCE_FALLBACKS"):
        return  # silenced calls don't consume the once-per-site budget
    _seen.add(key)
    warnings.warn(
        f"dr_tpu.{op}: taking the materialize fallback ({reason}) — "
        "correct but collective-suboptimal; see docs/SPEC.md",
        MaterializeFallbackWarning, stacklevel=3)
