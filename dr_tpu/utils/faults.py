"""Deterministic fault-injection registry.

Every failure path the resilience layer (utils/resilience.py) routes is
exercisable on the 8-device CPU mesh: instrumented modules call
:func:`fire` at NAMED injection sites; when an injection is armed for
that site, the registered fault class is raised (or, for behavioral
kinds like ``truncate``, returned for the site to act on).  With
nothing armed, ``fire`` is a single module-global check — noise next to
a program dispatch.

Registered sites (the chaos sweep — tests/test_chaos.py,
``tools/fuzz_crank.sh`` chaos arm — iterates this table):

===================  ============================  =======================
site                 where it fires                kinds
===================  ============================  =======================
runtime.probe        runtime.probe_devices          transient, relay_down
runtime.init         runtime.init                   transient, program
dispatch.cache       every TappedCache lookup       transient, program
                     (the algorithm dispatch
                     cache + all module caches)
collectives.shift    communicator shift_*           transient, oom, program
collectives.alltoall communicator.alltoall          transient, oom, program
collectives.ppermute ring-pipeline dispatchers      transient, oom, program
                     (parallel/pipeline.
                     fire_ppermute — the gemv ring
                     family, ring attention, the
                     2-D ring combine)
halo.exchange        span_halo exchange/exchange_n  transient, oom, program
halo.reduce          span_halo.reduce               transient, oom, program
checkpoint.write     checkpoint.save (pre-replace)  transient, truncate,
                                                    program
checkpoint.read      checkpoint.load                transient, program
plan.flush           deferred-plan flush boundary   transient, program
                     (dr_tpu/plan.py — fires
                     before any queued dispatch;
                     a faulted flush drops the
                     unexecuted queue cleanly)
serve.accept         serving-daemon accept loop     transient, program
                     (dr_tpu/serve/daemon.py —
                     fires per accepted client
                     connection; a faulted accept
                     drops that connection, the
                     daemon keeps serving)
serve.request        serving-daemon request intake  transient, oom, program
                     (per decoded request frame,
                     before admission; the error
                     is serialized back to the
                     client, never kills the
                     daemon)
serve.flush          serving-daemon batch dispatch  transient, relay_down,
                     (inside the retried batch      program
                     body, before the deferred
                     flush; relay_down triggers
                     the watchdog CPU degrade)
device.lost          every TappedCache dispatch     device_lost
                     tap (a device can die mid-
                     eager-op, mid-plan-flush, or
                     mid-serve-batch; rank rides
                     the fire ctx when known)
mesh.shrink          utils/elastic.rescue_session   transient, program
                     (the shrink boundary, before
                     the runtime rebuild — a fault
                     fails the rescue classified,
                     containers untouched)
device.recover       every grow-back recovery       transient, program
                     probe (runtime.
                     probe_recovered and the serve
                     route re-promotion probe —
                     one failed probe, supervisor
                     backs off, session unchanged)
mesh.grow            utils/elastic.grow_session     transient, program
                     (the grow boundary, before
                     the larger runtime is built —
                     a fault fails the re-admission
                     classified; the session keeps
                     serving on the small mesh)
redistribute.        every redistribution-engine    transient, oom, program
exchange             dispatch (parallel/
                     redistribute — collective
                     exchange, host-staged and
                     cross-mesh reshard transports,
                     the deferred-plan pre hook;
                     fires before the program-cache
                     lookup, container untouched)
arena.map            serving-daemon shared-memory   transient, program
                     arena map/alloc (dr_tpu/serve/
                     arena.py — a bad handle is the
                     client's deterministic bug; an
                     exhausted arena is a transient
                     the client absorbs by falling
                     back to the inline wire)
arena.release        arena slot refcount drop       transient, program
router.route         replica-router lookup          transient, program
                     (dr_tpu/serve/router.py —
                     fires before any replica is
                     touched)
router.probe         circuit-breaker half-open      transient, program
                     probe of an OPEN replica
                     (serve/router.py — a faulted
                     probe counts as failed, the
                     breaker backs off, traffic
                     stays on the survivors)
serve.drain          graceful-drain entry           transient, program
                     (serve/daemon.py Server.drain
                     — before admission closes; a
                     fault fails the drain
                     classified with the daemon
                     still serving)
serve.journal        resident-state journal ops     transient, program
                     (serve/journal.py — fires at
                     replay/append/compact; an
                     append fault degrades
                     durability warned, never the
                     request; a replay fault starts
                     the daemon on an empty cache)
sanitize.verify      plansan verification per       transient, program
                     flush (plan/__init__.flush —
                     after plan.flush, before the
                     oracle and any dispatch; a
                     fault fails the flush
                     classified with nothing
                     executed)
fallback.warn        utils/fallback.warn_fallback   (counting only)
===================  ============================  =======================

Exception kinds map onto the taxonomy: ``transient`` ->
TransientBackendError, ``relay_down`` -> RelayDownError, ``oom`` ->
DeviceOOM (message carries RESOURCE_EXHAUSTED so string-matching
backoff paths treat it like the real thing), ``program`` ->
ProgramError, ``device_lost`` -> DeviceLostError (message carries
DEVICE_LOST; the elastic layer shrinks the mesh on it, SPEC §16).
``truncate`` is behavioral: checkpoint.save truncates
the written file — the torn write a mid-stream kill leaves behind.

Spec grammar (``DR_TPU_FAULT_SPEC``, parsed at import; call
:func:`reload_env` after changing the variable in-process)::

    spec  := entry (';' entry)*            (',' also splits)
    entry := site ':' kind ['*' times] ['@' after]
    site  := registered site name, '*' globs allowed
    times := int or 'inf'   (default 1 — fire once, then pass clean)
    after := int            (clean passes before the first firing)

Example::

    DR_TPU_FAULT_SPEC="halo.exchange:transient*2;checkpoint.write:truncate@1"

Programmatic API: :func:`inject` / :func:`injected` (context manager) /
:func:`clear`.  While ANY injection is armed the registry also counts
site visits (:func:`stats`) — the chaos arm uses this to assert the
battery actually reached every site, and ``fallback.warn`` exists only
to be counted.  See docs/SPEC.md "Failure model & recovery".
"""

from __future__ import annotations

import warnings
from contextlib import contextmanager
from fnmatch import fnmatchcase
from typing import Dict, List, Optional, Tuple

from .env import env_flag, env_str

__all__ = ["fire", "inject", "injected", "clear", "sites", "stats",
           "parse_spec", "reload_env", "arm_counting", "pending",
           "EXCEPTION_KINDS", "BEHAVIORAL_KINDS", "SITES"]

#: site -> fault kinds it supports (exception kinds raise at the site;
#: behavioral kinds are returned from fire() for the site to act on).
SITES: Dict[str, Tuple[str, ...]] = {
    "runtime.probe": ("transient", "relay_down"),
    "runtime.init": ("transient", "program"),
    "dispatch.cache": ("transient", "program"),
    "collectives.shift": ("transient", "oom", "program"),
    "collectives.alltoall": ("transient", "oom", "program"),
    "collectives.ppermute": ("transient", "oom", "program"),
    "halo.exchange": ("transient", "oom", "program"),
    "halo.reduce": ("transient", "oom", "program"),
    "checkpoint.write": ("transient", "truncate", "program"),
    "checkpoint.read": ("transient", "program"),
    "plan.flush": ("transient", "program"),
    "serve.accept": ("transient", "program"),
    "serve.request": ("transient", "oom", "program"),
    "serve.flush": ("transient", "relay_down", "program"),
    # elastic degradation (docs/SPEC.md §16): device.lost rides EVERY
    # tapped dispatch (spmd_guard.TappedCache — the same choke point as
    # dispatch.cache, so a device can "die" mid-eager-op, mid-plan-
    # flush, or mid-serve-batch); mesh.shrink fires inside
    # utils/elastic.rescue_session at the shrink boundary, before the
    # runtime is rebuilt — a fault there fails the rescue classified
    # with the session's containers untouched.
    "device.lost": ("device_lost",),
    "mesh.shrink": ("transient", "program"),
    # elastic grow-back (docs/SPEC.md §16.6): device.recover fires at
    # every recovery probe (runtime.probe_recovered and the serve
    # daemon's route re-promotion probe) — a fault there fails ONE
    # probe classified and the supervisor backs off, session unchanged;
    # mesh.grow fires inside utils/elastic.grow_session at the grow
    # boundary, before the larger runtime is built — a fault there
    # fails the re-admission classified with the session still serving
    # correctly on the small mesh (grow must never make things worse).
    "device.recover": ("transient", "program"),
    "mesh.grow": ("transient", "program"),
    # collective redistribution (docs/SPEC.md §18): fires at every
    # engine dispatch (the collective exchange, the host-staged and
    # cross-mesh reshard transports, the deferred-plan pre-dispatch
    # hook), BEFORE the program-cache lookup — a faulted re-layout
    # surfaces classified with the container exactly as it was (the
    # metadata rebind rolls back).
    "redistribute.exchange": ("transient", "oom", "program"),
    # serving data plane (docs/SPEC.md §19): arena.map fires at every
    # shared-memory handle map/alloc on the daemon (a bad handle —
    # stale generation, unknown slot — is a deterministic ProgramError;
    # arena exhaustion is a transient the client absorbs by falling
    # back to the inline wire); arena.release fires at every slot
    # refcount drop; router.route fires at every replica-router lookup
    # (a faulted route surfaces classified before any replica is
    # touched).
    "arena.map": ("transient", "program"),
    "arena.release": ("transient", "program"),
    "router.route": ("transient", "program"),
    # serving control plane (docs/SPEC.md §20): router.probe fires at
    # every circuit-breaker half-open probe of an open replica (a
    # faulted probe counts as a failed probe — the breaker backs off
    # and traffic stays on the survivors); serve.drain fires at
    # Server.drain entry, before admission closes (a faulted drain
    # surfaces classified with the daemon still serving normally);
    # serve.journal fires at every resident-state journal operation
    # (replay at start, append per put/drop, compact) — an append
    # fault degrades durability (warned, counted), never the request,
    # and a replay fault starts the daemon on an empty resident cache.
    "router.probe": ("transient", "program"),
    "serve.drain": ("transient", "program"),
    "serve.journal": ("transient", "program"),
    # on-chip kernel tier (docs/SPEC.md §22): fires at EVERY kernel-arm
    # decision (ops/kernels.use_kernel — sort_local/segred/hist/scan),
    # before the arm's program is built or fetched; a fault there
    # degrades that dispatch to the portable XLA route (warned,
    # counted), never a crash — the kernels are an optimization tier.
    "kernel.build": ("transient", "program"),
    # plansan footprint verifier (docs/SPEC.md §23): fires on EVERY
    # plan flush right after plan.flush, before the serializability
    # oracle runs and before any dispatch — a faulted verification
    # surfaces classified with nothing executed and containers exactly
    # as recorded (the same "faulted flush executes nothing" contract
    # as plan.flush); the verifier itself only checks under
    # DR_TPU_SANITIZE=1 but the site fires unconditionally so the
    # chaos battery reaches it unarmed.
    "sanitize.verify": ("transient", "program"),
    "fallback.warn": (),
}

EXCEPTION_KINDS = ("transient", "relay_down", "oom", "program",
                   "device_lost")
BEHAVIORAL_KINDS = ("truncate",)
_ALL_KINDS = EXCEPTION_KINDS + BEHAVIORAL_KINDS


class _Injection:
    __slots__ = ("site", "kind", "remaining", "skip", "fired")

    def __init__(self, site: str, kind: str, times, after: int):
        self.site = site
        self.kind = kind
        self.remaining = times  # int or None (= unbounded)
        self.skip = after
        self.fired = 0

    def __repr__(self):  # pragma: no cover - debugging aid
        times = "inf" if self.remaining is None else self.remaining
        return (f"_Injection({self.site}:{self.kind}*{times}"
                f"@{self.skip}, fired={self.fired})")


_specs: List[_Injection] = []
_counts: Dict[str, int] = {}
_counting = False
#: hot-path gate: fire() returns immediately unless something is armed
_armed = False

#: set by dr_tpu.obs when DR_TPU_TRACE=1: every fire() visit becomes a
#: trace event (site hook) and every TRIGGERED injection is stamped
#: into the trace (fault hook) — an injected fault appears *in* the
#: trace next to the dispatch it poisoned (SPEC §15).  None keeps the
#: untraced fire() one extra ``is not None`` test.
_obs_site_hook = None
_obs_fault_hook = None


def _rearm() -> None:
    global _armed
    _armed = bool(_specs) or _counting


def sites() -> Dict[str, Tuple[str, ...]]:
    """The registered injection-site table (copy)."""
    return dict(SITES)


def stats() -> Dict[str, int]:
    """Per-site visit counts since the last :func:`clear` (collected
    only while armed — chaos runs, not production dispatch)."""
    return dict(_counts)


def pending() -> List[str]:
    """Human-readable list of injections that have not exhausted."""
    return [repr(s) for s in _specs
            if s.remaining is None or s.remaining > 0]


def arm_counting(on: bool = True) -> None:
    """Count site visits even with no injection armed (the chaos arm's
    coverage assertion; ``DR_TPU_FAULT_COUNT=1`` sets this at import)."""
    global _counting
    _counting = on
    _rearm()


def inject(site: str, kind: str, *, times: Optional[int] = 1,
           after: int = 0) -> None:
    """Arm ``kind`` at ``site`` (glob patterns allowed): the next
    ``after`` matching visits pass clean, then ``times`` visits fault
    (``times=None`` = every visit).  Unknown sites/kinds — and kinds no
    matched site SUPPORTS (e.g. ``truncate`` anywhere but
    checkpoint.write) — are errors: a typo in a chaos spec must not
    read as a clean sweep."""
    if kind not in _ALL_KINDS:
        raise ValueError(f"unknown fault kind {kind!r}; "
                         f"known: {', '.join(_ALL_KINDS)}")
    matched = [s for s in SITES if fnmatchcase(s, site)]
    if not matched:
        raise ValueError(f"fault site {site!r} matches no registered "
                         f"site; known: {', '.join(sorted(SITES))}")
    if not any(kind in SITES[s] for s in matched):
        raise ValueError(
            f"fault kind {kind!r} is unsupported at every site matching "
            f"{site!r} (supported there: "
            f"{', '.join(sorted(set().union(*(SITES[s] for s in matched))) or ['none'])})")
    _specs.append(_Injection(site, kind, times, int(after)))
    _rearm()


@contextmanager
def injected(site: str, kind: str, *, times: Optional[int] = 1,
             after: int = 0):
    """Scoped :func:`inject`: the injection is removed on exit (other
    armed injections are untouched)."""
    inject(site, kind, times=times, after=after)
    sp = _specs[-1]
    try:
        yield sp
    finally:
        try:
            _specs.remove(sp)
        except ValueError:  # a clear() inside the block already took it
            pass
        _rearm()


def clear() -> None:
    """Disarm every injection and zero the visit counters."""
    global _counting
    _specs.clear()
    _counts.clear()
    _counting = False
    _rearm()


def fire(site: str, **ctx) -> Optional[str]:
    """Hot-path hook at a named injection site.

    No-op (one global check) when nothing is armed.  Armed: counts the
    visit, and if an injection matches, raises its classified exception
    — or returns the behavioral kind string (e.g. ``"truncate"``) for
    the site to act on.  Returns None on a clean pass."""
    if _obs_site_hook is not None:
        _obs_site_hook(site, ctx)
    if not _armed:
        return None
    _counts[site] = _counts.get(site, 0) + 1
    for sp in _specs:
        if sp.remaining is not None and sp.remaining <= 0:
            continue
        if not fnmatchcase(site, sp.site):
            continue
        if sp.kind not in SITES.get(site, ()):
            continue  # glob spec: fire only where the kind is supported
        if sp.skip > 0:
            sp.skip -= 1
            continue
        if sp.remaining is not None:
            sp.remaining -= 1
        sp.fired += 1
        return _trigger(site, sp.kind, ctx)
    return None


def _trigger(site: str, kind: str, ctx: dict) -> Optional[str]:
    if _obs_fault_hook is not None:
        _obs_fault_hook(site, kind)
    from . import resilience as R
    tag = f"injected fault '{kind}' at site {site}"
    if ctx:
        tag += f" ({', '.join(f'{k}={v!r}' for k, v in sorted(ctx.items()))})"
    if kind == "transient":
        raise R.TransientBackendError(f"UNAVAILABLE: {tag}", site=site)
    if kind == "relay_down":
        raise R.RelayDownError(f"relay not listening: {tag}", site=site)
    if kind == "oom":
        raise R.DeviceOOM(f"RESOURCE_EXHAUSTED: {tag}", site=site)
    if kind == "device_lost":
        # rank attribution rides the fire() ctx (DR_TPU_FAULT_SPEC has
        # no rank field; env-injected losses leave rank None and the
        # elastic rescue presumes the last rank)
        rank = ctx.get("rank")
        raise R.DeviceLostError(f"DEVICE_LOST: {tag}", site=site,
                                rank=rank if isinstance(rank, int)
                                else None)
    if kind == "program":
        raise R.ProgramError(tag, site=site)
    return kind  # behavioral: the site acts on it


# ---------------------------------------------------------------------------
# env spec
# ---------------------------------------------------------------------------

def parse_spec(text: str) -> List[Tuple[str, str, Optional[int], int]]:
    """Parse the ``DR_TPU_FAULT_SPEC`` grammar into
    ``(site, kind, times, after)`` tuples.  Raises ValueError on a
    malformed ENTRY (reload_env downgrades that to a warning so a typo
    cannot brick an unrelated run, but never silently arms nothing)."""
    out = []
    for raw in text.replace(",", ";").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ValueError(f"fault spec entry {entry!r}: expected "
                             "site:kind[*times][@after]")
        site, rest = entry.split(":", 1)
        after = 0
        if "@" in rest:
            rest, a = rest.rsplit("@", 1)
            after = int(a)
        times: Optional[int] = 1
        if "*" in rest:
            rest, t = rest.split("*", 1)
            times = None if t.strip() == "inf" else int(t)
        out.append((site.strip(), rest.strip(), times, after))
    return out


def reload_env() -> int:
    """(Re)install injections from ``DR_TPU_FAULT_SPEC`` (clears any
    previously armed set first).  Returns the number installed.
    Malformed entries warn and are skipped — but a spec that arms
    NOTHING despite being nonempty also warns, so a typo'd chaos run
    cannot read as a clean sweep."""
    clear()
    if env_flag("DR_TPU_FAULT_COUNT"):
        arm_counting()
    text = env_str("DR_TPU_FAULT_SPEC")
    if not text.strip():
        return 0
    installed = 0
    try:
        entries = parse_spec(text)
    except ValueError as e:
        warnings.warn(f"DR_TPU_FAULT_SPEC ignored: {e}", stacklevel=2)
        return 0
    for site, kind, times, after in entries:
        try:
            inject(site, kind, times=times, after=after)
            installed += 1
        except ValueError as e:
            warnings.warn(f"DR_TPU_FAULT_SPEC entry skipped: {e}",
                          stacklevel=2)
    if installed == 0:
        warnings.warn("DR_TPU_FAULT_SPEC set but armed no injections",
                      stacklevel=2)
    return installed


reload_env()
