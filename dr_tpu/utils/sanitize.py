"""``DR_TPU_SANITIZE=1`` — the runtime half of drlint (SPEC.md §13.4).

What ``tools/drlint.py`` proves statically, this module asserts
dynamically while real programs run:

* **Recompile detection** (rule R1's runtime complement).  Every
  TappedCache insert is a compile; ``spmd_guard.compile_count()``
  counts them unconditionally (one int add).  Armed, each inserted key
  is canonicalized (``spmd_guard._canon`` — pin identities neutralized,
  so two meshes with the same geometry collide, exactly like the
  cross-rank digest) and a test epoch in which the SAME canonical
  program compiles more than ``DR_TPU_SANITIZE_RECOMPILE_LIMIT``
  (default 2) times fails: that is the value-keyed recompile storm.
  :func:`zero_recompile` is the strict region form — no cache insert at
  all may occur inside (the test_plan/test_pipeline pins ride it).

* **Finite flush** (``check_finite``): immediately after each fused
  run of a deferred-plan flush executes, every inexact container it
  touched must be NaN/Inf-free — per run, not per flush, so a later
  run overwriting a container can neither hide an earlier run's NaN
  nor be blamed for its own on the earlier run's ops.  Plan path
  ONLY: sort/attention tests legitimately push NaN sentinels through
  eager ops, but a fused elementwise chain has no sentinel semantics
  — a non-finite state there is an emitted-program bug (or a
  deliberate overflow, which belongs on the eager path).  A run any
  of whose containers was ALREADY non-finite immediately before it
  executed is exempt: the eager chain would propagate the same NaN,
  so there is nothing to attribute to the emitted program.

* **Canon portability** (strict ``spmd_guard`` digest verification):
  every dispatch key recorded under an active guard must canonicalize
  WITHOUT a process-local ``0x…`` address — an address in the canon
  means ``verify()`` would false-positive across ranks (the exact
  canonicalization-bug class its phase-2 error message punts on).
  Checked at every cache INSERT (each distinct key passes there first)
  and again at record time under any active guard, so the sanitized
  tier-1 suite sweeps every dispatch key it makes.

Arming: :func:`install` is called at ``import dr_tpu`` (cheap env
check, no-op unless ``DR_TPU_SANITIZE=1``); the conftest fixture then
gives every test its own epoch (``reset_epoch`` / ``check_recompiles``).
"""

from __future__ import annotations

import re
import threading
from collections import Counter
from contextlib import contextmanager
from typing import Dict, Optional

from .env import env_flag, env_int

__all__ = ["SanitizeError", "enabled", "install", "installed",
           "reset_epoch", "check_recompiles", "zero_recompile",
           "check_finite", "is_finite", "recompile_counts",
           "watch_containers"]


class SanitizeError(AssertionError):
    """A runtime invariant the static rules mirror was violated."""


def enabled() -> bool:
    return env_flag("DR_TPU_SANITIZE")


_installed = False
_epoch: Counter = Counter()          # canonical key -> compiles this epoch

#: canon strings are process-portable by construction; a hex address
#: can only leak in through repr() of an unpinned rich object in a
#: cache key — the divergence-false-positive class this check names.
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]{6,}")


def _canon(key) -> str:
    from . import spmd_guard
    return spmd_guard._canon(key)


def _on_compile(key) -> None:
    canon = _canon(key)
    _on_record(key, canon)   # every key is canonicalized here anyway
    _epoch[canon] += 1


def _on_record(key, canon: str) -> None:
    m = _ADDR_RE.search(canon)
    if m:
        raise SanitizeError(
            "dispatch key canonicalizes with a process-local address "
            f"({m.group(0)}): {canon[:200]!r} — spmd_guard.verify() "
            "would report a false divergence across ranks; pin the "
            "object (core.pinning) or key on portable structure")


def install() -> bool:
    """Arm the hooks when ``DR_TPU_SANITIZE=1``; idempotent, returns
    whether the sanitizer is armed."""
    global _installed
    if _installed or not enabled():
        return _installed
    from . import spmd_guard
    spmd_guard._compile_hook = _on_compile
    spmd_guard._canon_check_hook = _on_record
    _installed = True
    return True


def installed() -> bool:
    return _installed


def reset_epoch() -> None:
    """Start a fresh recompile-counting epoch (one per test)."""
    _epoch.clear()


def recompile_counts() -> Dict[str, int]:
    """Canonical-key compile counts for the current epoch."""
    return dict(_epoch)


def check_recompiles(limit: Optional[int] = None) -> None:
    """Fail the epoch if any canonical program compiled more than
    ``limit`` times (default ``DR_TPU_SANITIZE_RECOMPILE_LIMIT``, 2 —
    one benign duplicate allowed for two-mesh tests; a storm is
    many)."""
    if limit is None:
        limit = env_int("DR_TPU_SANITIZE_RECOMPILE_LIMIT", 2)
    bad = {k: c for k, c in _epoch.items() if c > limit}
    if bad:
        worst = sorted(bad.items(), key=lambda kv: -kv[1])[:3]
        lines = "; ".join(f"{c}x {k[:160]}" for k, c in worst)
        raise SanitizeError(
            f"recompile storm: {len(bad)} canonical program(s) "
            f"compiled more than {limit}x in one epoch — value-keyed "
            "cache keys (rule R1); ride a traced operand instead.  "
            f"Worst: {lines}")


@contextmanager
def zero_recompile(what: str = "region"):
    """Assert that NO program-cache insert happens inside the region —
    the strict re-record contract: a second pass over already-compiled
    work must hit every cache.  Works unarmed too (the raw counter is
    always on)."""
    from . import spmd_guard
    c0 = spmd_guard.compile_count()
    yield
    grew = spmd_guard.compile_count() - c0
    if grew:
        raise SanitizeError(
            f"{what}: {grew} program compile(s) inside a "
            "zero-recompile region — the re-record path misses its "
            "cache (value-keyed key or drifting key structure)")


# ---------------------------------------------------------------------------
# container-access watching (the plansan opaque-footprint verifier,
# docs/SPEC.md §23.3)
# ---------------------------------------------------------------------------

#: module-global fast gates for the instrumented containers: a
#: ``_data`` property pays ONE None check while no watcher is armed
#: anywhere; armed, the dispatchers below route to the PER-THREAD
#: watcher (the serve daemon's dispatch thread must not observe the
#: host thread's opaque thunk, and vice versa).
_access_hook = None
_born_hook = None
_watch_tls = threading.local()
_watch_lock = threading.Lock()
_watchers = 0


def _dispatch_access(kind: str, cont) -> None:
    h = getattr(_watch_tls, "access", None)
    if h is not None:
        h(kind, cont)


def _dispatch_born(cont) -> None:
    h = getattr(_watch_tls, "born", None)
    if h is not None:
        h(cont)


@contextmanager
def watch_containers(access, born=None):
    """Arm a container-access watcher ON THIS THREAD for the enclosed
    block: instrumented containers report every ``_data`` read
    (``access("r", cont)``), every rebind (``access("w", cont)``), and
    every container CREATION (``born(cont)``) — the plansan opaque
    verifier's observation channel.  Nests (the previous watcher is
    restored); other threads stay unobserved."""
    global _access_hook, _born_hook, _watchers
    prev = (getattr(_watch_tls, "access", None),
            getattr(_watch_tls, "born", None))
    _watch_tls.access, _watch_tls.born = access, born
    with _watch_lock:
        _watchers += 1
        _access_hook = _dispatch_access
        _born_hook = _dispatch_born
    try:
        yield
    finally:
        _watch_tls.access, _watch_tls.born = prev
        with _watch_lock:
            _watchers -= 1
            if not _watchers:
                _access_hook = None
                _born_hook = None


def is_finite(arr) -> bool:
    """True when ``arr`` has no NaN/Inf (non-inexact dtypes vacuously).
    Forces a device sync — callers gate on :func:`installed`."""
    import jax.numpy as jnp
    if not jnp.issubdtype(jnp.result_type(arr), jnp.inexact):
        return True
    return bool(jnp.isfinite(arr).all())


def check_finite(arr, what: str) -> None:
    """Raise unless every element of ``arr`` is finite.  Callers gate
    on :func:`installed` — this forces a device sync."""
    if not is_finite(arr):
        raise SanitizeError(
            f"non-finite values in {what} after a plan-flush run — an "
            "emitted-program bug, or an overflow/NaN the chain mints "
            "from finite inputs; the deferred plan path has no "
            "NaN-sentinel semantics (run such chains eagerly)")
