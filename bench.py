#!/usr/bin/env python
"""Driver benchmark: 1-D 5-point stencil over a large distributed_vector.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

Workload (BASELINE.json north star): iterated 1-D 5-point stencil (radius
2) with halo exchange per step over a ~1B-element vector, target >= 70% of
HBM bandwidth per chip.  The whole multi-step loop runs inside one jitted
program (``stencil_iterate``: fused ppermute halo exchange + shifted
weighted sum + lax.fori_loop double buffering), so the measured rate is
pure device-side HBM traffic.

vs_baseline: achieved GB/s divided by the north-star target (0.7 x the
chip's peak HBM bandwidth).  The reference publishes no numbers
(BASELINE.md), so the target is the hardware-derived bar.
"""

import json
import os
import sys
import time

import numpy as np


# per-chip peak HBM bandwidth, GB/s (public spec sheets)
_PEAK_HBM = {
    "v2": 700.0, "v3": 900.0, "v4": 1228.0,
    "v5e": 819.0, "v5 lite": 819.0, "v5p": 2765.0,
    "v6e": 1640.0, "v6 lite": 1640.0,
}


def _peak_for(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for k, v in sorted(_PEAK_HBM.items(), key=lambda kv: -len(kv[0])):
        if k in kind:
            return v
    if device.platform == "cpu":
        return 50.0  # rough DDR figure so CPU smoke runs stay meaningful
    return 819.0


def main():
    n = int(os.environ.get("DR_TPU_BENCH_N", str(2 ** 30)))
    steps = int(os.environ.get("DR_TPU_BENCH_STEPS", "16"))
    impl = os.environ.get("DR_TPU_BENCH_IMPL", "xla")  # xla | pallas
    tblock = int(os.environ.get("DR_TPU_BENCH_TBLOCK", "8"))

    import jax
    import dr_tpu
    from dr_tpu.algorithms.stencil import (stencil_iterate,
                                           stencil_iterate_blocked)

    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    if on_cpu and "DR_TPU_BENCH_N" not in os.environ:
        n = 2 ** 24  # keep CPU smoke runs fast

    dr_tpu.init(jax.devices())
    w = [0.05, 0.25, 0.4, 0.25, 0.05]
    radius = 2
    halo_w = radius if impl == "xla" else tblock * radius
    # periodic ring: every element computed every step on both paths
    hb = dr_tpu.halo_bounds(halo_w, halo_w, periodic=True)
    nshards = dr_tpu.nprocs()
    n -= n % (nshards * 2 ** 17 if impl == "pallas" else nshards) or 0

    dtype = np.float32
    for attempt in range(3):
        try:
            a = dr_tpu.distributed_vector(n, dtype, halo=hb)
            b = dr_tpu.distributed_vector(n, dtype, halo=hb)
            dr_tpu.fill(a, 1.0)
            dr_tpu.fill(b, 1.0)
            a.block_until_ready()
            b.block_until_ready()
            break
        except Exception:
            if attempt == 2:
                raise
            n //= 4  # back off on OOM
            n -= n % (nshards * 2 ** 17 if impl == "pallas" else nshards)

    def run(nsteps):
        if impl == "pallas":
            return stencil_iterate_blocked(a, w, nsteps,
                                           time_block=tblock,
                                           chunk=2 ** 17)
        return stencil_iterate(a, b, w, steps=nsteps)

    # warmup / compile (same step count as the timed run so the timed
    # region never compiles)
    run(steps)
    a.block_until_ready()
    b.block_until_ready()

    t0 = time.perf_counter()
    out = run(steps)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    # minimal HBM traffic per step: read n + write n elements
    bytes_moved = 2.0 * n * np.dtype(dtype).itemsize * steps
    gbps = bytes_moved / dt / 1e9
    nchips = 1  # single-controller measurement is per chip
    peak = _peak_for(dev)
    target = 0.7 * peak

    print(json.dumps({
        "metric": "stencil1d_5pt_hbm_bandwidth_per_chip",
        "value": round(gbps / nchips, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / nchips / target, 4),
        "detail": {
            "n": n, "steps": steps, "seconds": round(dt, 4),
            "impl": impl, "device": str(dev), "peak_hbm_gbps": peak,
            "target_gbps": round(target, 1),
        },
    }))


if __name__ == "__main__":
    main()
