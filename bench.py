#!/usr/bin/env python
"""Driver benchmark: 1-D 5-point stencil over a large distributed_vector.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

Workload (BASELINE.json north star): iterated 1-D 5-point stencil (radius
2) with halo exchange over a ~1B-element vector, target >= 70% of HBM
bandwidth per chip.  Three implementations (TPU tries matmul -> pallas ->
xla, falling back on failure so the driver always records a number):

- ``xla`` — one jitted program per run (fused ppermute halo exchange +
  shifted weighted sum + lax.fori_loop double buffering); each step reads
  and writes the whole vector, so the rate is physical HBM traffic.
- ``pallas`` — the temporally-blocked VMEM kernel fuses ``tblock`` steps
  per HBM pass; VPU compute-bound near 0.9 TB/s effective on v5e.
- ``matmul`` (TPU default) — composes ``tblock`` steps into one banded
  Toeplitz operator applied as lane-column matmuls on the MXU
  (ops/stencil_matmul.py); ~5x the pallas path's effective rate.

For the blocked paths the reported *effective* bandwidth (2 x 4 bytes x
n x steps / time) can exceed physical peak by up to ``tblock``-fold:
that headroom over the bandwidth bound is the point of temporal
blocking.  ``detail.phys_gbps`` estimates the physical traffic rate.

vs_baseline: achieved effective GB/s divided by the north-star target
(0.7 x the chip's peak HBM bandwidth).  The reference publishes no
numbers (BASELINE.md), so the target is the hardware-derived bar.

``--phases`` (or DR_TPU_BENCH_PHASES=1) additionally emits the
key-value sort phase ladder into detail; the keys-only sort phase
breakdown (``detail.sort_phases_gbps``) is always on (round 6 —
utils/profiling.profile_phases over the sample-sort truncations).

Round 8: ``detail.pipeline_gbps`` (eager-vs-deferred 5-op chain through
``dr_tpu.deferred()``, marginal method) and ``detail.dispatch_counts``
(spmd_guard tap counts for the headline timed run and one pipeline
chain per arm) are always on; ``--pipeline`` (or
DR_TPU_BENCH_PIPELINE=1 — survives the CPU-fallback re-execs) adds the
deferred chain-length ladder.

Round 9: the sparse family gets the sort treatment —
``detail.spmv_format``/``spmm_format`` (the layout the measurement
actually dispatched: autoselect or env override, fallback-resolved) and ``detail.spmv_phases_gflops`` (ring-schedule truncation
ladder: local_compute / rotate / combine) are always on; ``--spmv``
(or DR_TPU_BENCH_SPMV=1, surviving both re-exec legs) adds the
per-format gemv_n ladder.

Round 11: ``--serve`` (or DR_TPU_BENCH_SERVE=1 — argv and env both
survive the CPU-fallback re-execs) runs the closed-loop serving load
generator: an in-process ``dr_tpu.serve`` daemon (one resident claim,
request batching into deferred-plan flushes) driven by
DR_TPU_BENCH_SERVE_CLIENTS concurrent client connections issuing
back-to-back requests; ``detail.serve_latency_ms`` (p50/p95/p99),
``detail.serve_rps``, and ``detail.serve_batch`` make "heavy traffic"
a measured number.  A daemon that degraded mid-run reports through
``detail.degraded.serve`` (resilience.degradation_story markers).

Round 12: ``detail.obs`` (the dr_tpu/obs metrics snapshot — counters,
the daemon-side serve latency histograms, dispatch/compile counts) is
always on; ``--serve`` adds ``detail.serve_daemon_ms`` (queue-wait vs
service vs batch-flush split) next to the client percentiles, and
under ``DR_TPU_TRACE=1`` the run exports a Chrome trace
(``detail.obs.trace_file``, Perfetto-openable; docs/SPEC.md §15).

Round 14: ``--relational`` (or DR_TPU_BENCH_RELATIONAL=1 — argv and
env both survive the CPU-fallback re-execs) runs the TPC-style
relational pipeline (docs/SPEC.md §17): fact-table join -> groupby
sum -> top_k, emitting ``detail.relational_rows``, per-stage
``detail.relational_*_ms``, ``detail.relational_pipeline_krows_s``,
and ``detail.relational_deferred_dispatches`` (the static-shape
histogram/top_k pair fused into ONE plan flush).

Round 13: a run whose mesh SHRANK mid-session (elastic degradation,
docs/SPEC.md §16) is self-describing — the ``_DR_TPU_ELASTIC_*``
markers the shrink publishes ride the re-exec environment like the
``_DR_TPU_SERVE_*`` ones, so ``detail.degraded.shrink`` (lost ranks,
rescued/restored/lost container counts, shrink wall time) lands in
EVERY artifact the run emits, CPU-fallback re-exec legs included.

Round 15: the recovery half rides the same markers — a session that
GREW BACK (elastic grow-back, docs/SPEC.md §16.6: a recovered device
re-admitted, or the serve claim re-promoted from the CPU route to the
device route after a relay returned) carries
``detail.degraded.grow`` — grow count, moved/kept container counts,
the re-admitted mesh size, grow wall time — next to the ``shrink``
chapter, so one artifact tells the whole degrade-and-recover arc.

Round 17 (the serving data plane, docs/SPEC.md §19): ``--serve``
additionally measures the plane itself — ``detail.serve_arena_ms``
(arena vs inline-wire p50 A/B at a ≥ 1 MiB payload: the zero-copy
acceptance number), ``detail.serve_router`` (closed-loop rps at
replica counts 1 and 2 behind the consistent-hash front, CPU-route
replicas on this host), and ``detail.serve_tenants`` (per-tenant
queue-wait/service p50/p95 under a skewed heavy/light load — the
weighted-fair no-starvation evidence).

Round 18 (the serving control plane, docs/SPEC.md §20): ``--serve``
adds ``detail.serve_restart`` — the classified-error count and p99 a
closed-loop client sees while the 2-replica fleet restarts, once
through the graceful drain protocol (``rolling_restart``: zero
errors expected — tenants re-hash BEFORE each replica dies) and once
through an abrupt replica crash + respawn (the breaker re-hash
absorbs it; the resident journal brings tenant state back).  Argv
and env survive the CPU-fallback re-execs, as with every serve leg.

Round 16: ``--redistribute`` (or DR_TPU_BENCH_REDISTRIBUTE=1 — argv
and env both survive the CPU-fallback re-execs) races the two
re-layout impls (docs/SPEC.md §18) over a layout ping-pong, emitting
``detail.redistribute_gbps`` (host-staged vs collective, marginal
method); the always-on relational config additionally records
``detail.relational_join_route`` — the merge route the join took
(broadcast vs repartition) with its per-device gathered-channel rows,
the peak-memory proxy — and ``--relational`` adds the forced
repartition timing next to the broadcast one.
"""

import json
import os
import sys
import time

import numpy as np

# the user's apply-implementation override, captured before any
# fallback step mutates the variable (None-vs-set matters: an explicit
# empty value must not read as a pin).  Raw on purpose: the capture
# must run at the top of module load, before the dr_tpu imports below
# (and any env_override dance they enable) can touch the variable.
# drlint: ok[R2] earliest-possible capture, before any package import
_USER_MM_IMPL = os.environ.get("DR_TPU_MM_IMPL")

# per-chip peak HBM bandwidth, GB/s (public spec sheets)
_PEAK_HBM = {
    "v2": 700.0, "v3": 900.0, "v4": 1228.0,
    "v5e": 819.0, "v5 lite": 819.0, "v5p": 2765.0,
    "v6e": 1640.0, "v6 lite": 1640.0,
}


def _peak_for(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for k, v in sorted(_PEAK_HBM.items(), key=lambda kv: -len(kv[0])):
        if k in kind:
            return v
    if device.platform == "cpu":
        return 50.0  # rough DDR figure so CPU smoke runs stay meaningful
    return 819.0


def _sync(cont):
    # block_until_ready can be a no-op on tunneled backends (axon); a host
    # read of one element is a hard completion barrier.  Slice device-side
    # so only a scalar crosses the wire, and read a local shard so
    # multi-process SPMD runs stay legal.
    shard = cont._data.addressable_shards[0].data
    return float(shard.reshape(-1)[0])


def _measure(impl: str, n: int, steps: int, tblock: int):
    """Allocate, warm up, and time one implementation; returns a result
    dict.  Raises on any non-OOM failure (caller decides the fallback)."""
    import dr_tpu
    from dr_tpu.utils.env import env_raw
    from dr_tpu.algorithms.stencil import (stencil_iterate,
                                           stencil_iterate_blocked,
                                           stencil_iterate_matmul)
    from dr_tpu.ops import stencil_pallas

    pallas = impl == "pallas"
    matmul = impl in ("matmul", "matmul_xla")
    # matmul_xla: the composed-operator path with the XLA P-form apply —
    # the fallback when the fused Pallas apply fails on this backend.
    # Other impls restore whatever the USER set (bench must not eat a
    # DR_TPU_MM_IMPL override).
    if impl == "matmul_xla":
        os.environ["DR_TPU_MM_IMPL"] = "xla"
    elif _USER_MM_IMPL is None:
        os.environ.pop("DR_TPU_MM_IMPL", None)
    else:
        os.environ["DR_TPU_MM_IMPL"] = _USER_MM_IMPL
    blocked = pallas or matmul
    w = [0.05, 0.25, 0.4, 0.25, 0.05]
    radius = 2
    if matmul:
        from dr_tpu.ops import stencil_matmul
        # composed band may reach four lane columns each side (default)
        la = stencil_matmul.LANES
        tblock = min(tblock, stencil_matmul.max_ksteps(radius))
        halo_w = max(la, -(-tblock * radius // la) * la)
        # the chunked apply peaks near 3x the row (input copy + stacked
        # chunk outputs + output); cap so it fits 16 GB HBM with margin
        n = min(n, 2 ** 29)
    elif pallas:
        # VPU path: its per-step roll/select cost scales with tblock;
        # 64 was the measured knee — don't inherit the matmul default,
        # but honor an explicit user override
        if env_raw("DR_TPU_BENCH_TBLOCK") is None:
            tblock = min(tblock, 64)
        # Mosaic tile alignment: halo is whole (8, 128) f32 tiles
        ra = stencil_pallas.ROW_ALIGN
        halo_w = max(ra, -(-tblock * radius // ra) * ra)
    else:
        halo_w = radius
    # periodic ring: every element computed every step on both paths
    hb = dr_tpu.halo_bounds(halo_w, halo_w, periodic=True)
    nshards = dr_tpu.nprocs()
    # blocked paths: shards must be whole aligned chunks; never below one
    align = nshards * 2 ** 17 if blocked else nshards
    n = max(align, n - n % align)

    dtype = np.float32
    a = b = None

    def run(nsteps):
        if matmul:
            return stencil_iterate_matmul(a, w, nsteps, k_block=tblock)
        if pallas:
            return stencil_iterate_blocked(a, w, nsteps,
                                           time_block=tblock,
                                           chunk=2 ** 17)
        return stencil_iterate(a, b, w, steps=nsteps)

    for attempt in range(3):
        try:
            a = dr_tpu.distributed_vector(n, dtype, halo=hb)
            dr_tpu.fill(a, 1.0)
            if not blocked:  # blocked paths step in place, no 2nd buffer
                b = dr_tpu.distributed_vector(n, dtype, halo=hb)
                dr_tpu.fill(b, 1.0)
            # warmup / compile; also surfaces OOM for backoff.  XLA path:
            # same step count as the timed run (steps is in the jit key).
            # Pallas path: one full block + the remainder block compiles
            # both cached programs without paying the full timed run.
            nfull, rest = divmod(steps, tblock)
            warm = steps if not blocked else \
                min(steps, tblock * min(nfull, 1) + rest)
            _sync(run(warm))
            break
        except Exception as e:
            oom = "RESOURCE_EXHAUSTED" in str(e) or "emory" in str(e)
            if attempt == 2 or not oom:
                raise
            a = b = None  # release this attempt's buffers before retrying
        # backoff OUTSIDE the except block: while it is live, the
        # exception's traceback pins callee frames (and their buffers),
        # so collecting/sleeping inside would wait for nothing
        _settle(2.0)
        n //= 4  # back off on OOM
        n = max(align, n - n % align)

    # best-of-3: the timed run is ~0.3 s, the tunneled dispatch constant
    # drifts by tens of ms — a single sample can be inflated ~25%
    from dr_tpu.utils.spmd_guard import dispatch_count
    d0 = dispatch_count()
    dt = _time_best(lambda: _sync(run(steps)), iters=3)
    # tap dispatches per timed run (round 8): dispatch-count regressions
    # become visible in every BENCH_r*.json
    dpr = (dispatch_count() - d0) / 3.0
    dispatches = int(dpr) if dpr == int(dpr) else round(dpr, 2)

    # effective traffic: the per-step XLA path would read n + write n
    bytes_eff = 2.0 * n * np.dtype(dtype).itemsize * steps
    gbps = bytes_eff / dt / 1e9
    # physical traffic: the pallas path touches HBM once per tblock steps
    nfull, rest = divmod(steps, tblock)
    passes = steps if not blocked else nfull + (1 if rest else 0)
    phys_gbps = 2.0 * n * np.dtype(dtype).itemsize * passes / dt / 1e9
    return {"n": n, "steps": steps, "seconds": round(dt, 4), "impl": impl,
            "gbps": gbps, "phys_gbps": phys_gbps, "dispatches": dispatches}


def _settle(seconds):
    """gc + pause so asynchronous (tunneled) device deallocs land before
    the next allocation.  Call with no exception in flight: a live
    traceback pins the failed frames' buffers and defeats the wait."""
    import gc
    gc.collect()
    time.sleep(seconds)


def _time_best(fn, iters=3):
    """Best-of-N wall time of fn(); fn must block until complete."""
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


# The MARGINAL measurement core lives in utils/profiling (round 6 —
# it used to be defined here; one implementation, library-importable):
# time a fused loop of r1 ops and one of r2 ops, divide the median
# difference by r2 - r1 (the tunneled per-dispatch constant cancels),
# adaptively widening the loop count until the delta dominates the
# dispatch jitter, and raising JitterError instead of returning noise.
# Fused loops come from the *_n program family (dot_n,
# inclusive_scan_n, ring_attention_n, exchange_n, sort_n).
from dr_tpu.utils.profiling import JitterError as _JitterError  # noqa: E402
from dr_tpu.utils.profiling import marginal as _marginal  # noqa: E402


def _marginal_with_fallback(run_sync, kernel_possible, env_var, err_key,
                            out, **kw):
    """_marginal, but when a TPU Pallas kernel path may have been taken
    and fails, record the error and retry once with ``env_var=xla``
    forcing the XLA path.  Off-TPU the kernel was never selected, so
    failures propagate undisguised (no pointless identical retry).
    Jitter failures are the MEASUREMENT's, not the kernel's: re-raise
    (an xla retry would silently publish the slower path's rate under
    a false kernel-error label)."""
    try:
        return _marginal(run_sync, **kw)
    except _JitterError:
        raise
    except Exception as e:
        if not kernel_possible:
            raise
        out[err_key] = repr(e)[:120]
        os.environ[env_var] = "xla"
        try:
            return _marginal(run_sync, **kw)
        finally:
            os.environ.pop(env_var, None)


def _kernel_ab(arm, run_sync, on_tpu, **kw):
    """Pallas-vs-XLA A/B of ONE registered kernel arm (docs/SPEC.md
    §22.5): the same fused run timed under each env pin of the arm's
    override var (ops/kernels.ARMS), per-leg guarded so a kernel
    failure records an error string instead of eating the column.
    Off-TPU the pallas pin means interpret mode — hours at bench
    sizes — so the column carries the honest skip tag instead of a
    meaningless number.  Callers pick operands INSIDE the arm's
    eligibility cap; at the headline sizes the pin silently no-ops
    and the A/B would time XLA against itself."""
    from dr_tpu.ops import kernels
    from dr_tpu.utils.env import env_override
    if not on_tpu:
        return {"note": "cpu mesh: pallas arm = interpret; A/B skipped"}
    env_var = dict((a, e) for a, e, _, _, _ in kernels.ARMS)[arm]
    res = {}
    for mode in ("xla", "pallas"):
        with env_override(**{env_var: mode}):
            try:
                dt = _marginal(run_sync, **kw)
                res[f"{mode}_ms"] = round(dt * 1e3, 3)
            except _JitterError as e:
                res[f"{mode}_error"] = f"JitterError: {e}"[:120]
            except Exception as e:  # pragma: no cover - defensive
                res[f"{mode}_error"] = repr(e)[:120]
    if "xla_ms" in res and "pallas_ms" in res:
        res["winner"] = ("pallas" if res["pallas_ms"] < res["xla_ms"]
                         else "xla")
    return res


def _time_amortized(dispatch, sync, calls=16, batches=3):
    """Median per-call time of ``calls`` async dispatches + ONE sync.

    The host<->device control link (a tunneled RPC under axon) costs tens
    of milliseconds per round trip; syncing every call would measure the
    link, not the device.  Dispatches queue on the device, so batch-time /
    calls is the genuine per-op device time once calls >> 1."""
    times = []
    for _ in range(batches):
        t0 = time.perf_counter()
        last = None
        for _ in range(calls):
            last = dispatch()
        sync(last)
        times.append((time.perf_counter() - t0) / calls)
    return float(np.median(times))


def _pl_scale(x, c):
    return x * c


def _pl_shift(x, c):
    return x + c


def _pipeline_chain(a, b, coef):
    """One 5-op cross-algorithm chain (fill -> for_each -> halo exchange
    -> transform -> reduce) — the deferred-plan workload.  Module-level
    ops keep the program-cache keys stable across calls; ``coef`` rides
    as a traced scalar, so streaming values reuse one compiled plan."""
    import dr_tpu
    dr_tpu.fill(a, 0.5)
    dr_tpu.for_each(a, _pl_scale, coef)
    a.halo().exchange()
    dr_tpu.transform(a, b, _pl_shift, 1.0)
    return dr_tpu.reduce(b)


def _pipeline_runners(a, b):
    """(run_eager, run_deferred) over the shared chain — ONE home for
    the measurement protocol (bench's pipeline config and
    tune_tpu.py's on-chip ladder must time the identical workload).
    ``run(r)`` executes r chains and hard-syncs; the streamed
    coefficient keeps the program caches hot across r."""
    import dr_tpu

    def run_eager(r):
        for i in range(r):
            _pipeline_chain(a, b, 1.0 + i * 1e-7)
        _sync(b)

    def run_deferred(r):
        with dr_tpu.deferred():
            vals = [_pipeline_chain(a, b, 1.0 + i * 1e-7)
                    for i in range(r)]
        float(vals[-1])  # ONE host sync for the whole region

    return run_eager, run_deferred


def _pipeline_metrics(on_cpu: bool, ladder: bool = False) -> dict:
    """Eager-vs-deferred per-chain rate of the 5-op pipeline chain by
    the marginal method (``run(r)`` = r chains; the per-measurement
    constant cancels, while the per-op dispatch cost — the thing
    deferred mode erases — properly scales with r on the eager arm).
    Also reports the tap dispatch count of ONE chain on each arm.
    ``ladder=True`` (--pipeline) adds a raw chain-length ladder for the
    next chip session (per-chain wall ms at r = 1..16)."""
    import dr_tpu
    from dr_tpu.utils.spmd_guard import dispatch_count
    out = {}
    P = dr_tpu.nprocs()
    itemsize = 4
    # CPU smoke size stays small: the config measures DISPATCH
    # amortization, which dominates regardless of n off-chip
    n = (2 ** 18 if on_cpu else 2 ** 24) // P * P
    hb = dr_tpu.halo_bounds(2, 2, periodic=True)
    a = dr_tpu.distributed_vector(n, np.float32, halo=hb)
    b = dr_tpu.distributed_vector(n, np.float32, halo=hb)
    # fill n + for_each 2n + transform 2n + reduce n (exchange moves
    # ghost widths — noise): the chain's logical traffic
    bytes_chain = 6.0 * n * itemsize
    run_eager, run_deferred = _pipeline_runners(a, b)

    try:
        run_eager(1)
        run_deferred(1)  # warm both arms (compile the r=1 plan)
        d0 = dispatch_count()
        run_eager(1)
        eager_d = dispatch_count() - d0
        d0 = dispatch_count()
        run_deferred(1)
        deferred_d = dispatch_count() - d0
        out["dispatch_counts"] = {"pipeline_chain_eager": eager_d,
                                  "pipeline_chain_deferred": deferred_d}
        # rmax bounds the adaptive widening: a deferred r-chain plan
        # traces 5*r ops, so unbounded widening would compile a
        # monster program just to beat the jitter guard
        dt_e = _marginal(run_eager, r1=2, r2=8, samples=3,
                         min_spread=0.05 if on_cpu else 0.3, rmax=32)
        dt_d = _marginal(run_deferred, r1=2, r2=8, samples=3,
                         min_spread=0.05 if on_cpu else 0.3, rmax=32)
        out["pipeline_gbps"] = {
            "eager": round(bytes_chain / dt_e / 1e9, 3),
            "deferred": round(bytes_chain / dt_d / 1e9, 3)}
        out["pipeline_chain_us"] = {"eager": round(dt_e * 1e6, 1),
                                    "deferred": round(dt_d * 1e6, 1)}
        if ladder:
            lad = {}
            for r in (1, 2, 4, 8, 16):
                run_deferred(r)  # compile the r-chain plan
                t0 = time.perf_counter()
                run_deferred(r)
                lad[str(r)] = round((time.perf_counter() - t0) / r * 1e6,
                                    1)
            out["pipeline_deferred_ladder_us_per_chain"] = lad
    except _JitterError as e:
        out["pipeline_error"] = f"JitterError: {e}"[:160]
    except Exception as e:  # pragma: no cover - defensive
        out["pipeline_error"] = repr(e)[:160]
    return out


def _secondary_metrics(on_cpu: bool, on_tpu: bool,
                       phases: bool = False,
                       spmv_ladder: bool = False) -> dict:
    """The remaining BASELINE.json configs, each as one number in detail:
    transform_reduce dot (GB/s), inclusive_scan (GB/s), halo-exchange
    p50 latency (us), 2-D heat stencil (GB/s), CSR SpMV (GFLOP/s).
    Every config is independently guarded — a failure records an error
    string instead of killing the headline metric.

    The sort config additionally emits its PHASE BREAKDOWN
    (``sort_phases_gbps``: per-phase effective GB/s over the
    sample-sort truncation ladder, ``sort_phase_dominant``) — round 6;
    ``phases=True`` (``--phases`` / ``DR_TPU_BENCH_PHASES=1``) adds the
    key-value ladder (``sortkv_phases_gbps``).  On a single-device mesh
    the collective phases collapse into ``local_sort`` (the program has
    no exchange to run), which is itself the honest story: the CPU
    fallback's sort cost IS the local XLA sort."""
    import dr_tpu
    out = {}
    P = dr_tpu.nprocs()
    itemsize = 4

    # config 1: transform_reduce dot-product (dot_product.cpp:11-18).
    # dot_n fuses the reductions device-side (VERDICT r1 item 4): the
    # metric no longer pays the tunneled dispatch overhead.
    try:
        n = (2 ** 22 if on_cpu else 2 ** 27) // P * P
        a = dr_tpu.distributed_vector(n, np.float32)
        b = dr_tpu.distributed_vector(n, np.float32)
        dr_tpu.fill(a, 1.5)
        dr_tpu.fill(b, 2.0)
        from dr_tpu.algorithms.reduce import dot_kernel_eligible, dot_n
        kern = dot_kernel_eligible(a, b)
        dt = _marginal_with_fallback(lambda r: float(dot_n(a, b, r)),
                                     kern, "DR_TPU_DOT_IMPL",
                                     "dot_kernel_error", out)
        out["dot_gbps"] = round(2.0 * n * itemsize / dt / 1e9, 2)
        # the FULL gate, not just the env ask: report what actually ran
        out["dot_impl"] = ("pallas" if kern and
                           "dot_kernel_error" not in out else "xla")
    except Exception as e:  # pragma: no cover - defensive
        out["dot_error"] = repr(e)[:160]
    finally:
        a = b = None  # free the buffers even when a step raised

    # config 3: inclusive_scan prefix sum (inclusive_scan.hpp:25-148),
    # fused-loop measurement (inclusive_scan_n)
    try:
        n = (2 ** 22 if on_cpu else 2 ** 27) // P * P
        a = dr_tpu.distributed_vector(n, np.float32)
        s = dr_tpu.distributed_vector(n, np.float32)
        dr_tpu.iota(a, 0)
        from dr_tpu.algorithms.scan import inclusive_scan_n

        def run_scan(r):
            inclusive_scan_n(a, s, r)
            _sync(s)
        dt = _marginal_with_fallback(run_scan, on_tpu, "DR_TPU_SCAN_IMPL",
                                     "scan_kernel_error", out)
        out["scan_gbps"] = round(2.0 * n * itemsize / dt / 1e9, 2)
        from dr_tpu.algorithms.scan import _kernel_variant
        kern, pipe, cap, passes = _kernel_variant()
        out["scan_cfg"] = (f"{kern or 'mxu'}/{pipe or 'manual'}"
                           f"/R{cap}/p{passes}")
    except Exception as e:  # pragma: no cover - defensive
        out["scan_error"] = repr(e)[:160]
    finally:
        a = s = None

    # halo-exchange p50 latency (the BASELINE.json metric's third term;
    # halo.hpp:273-387 exchange over the ppermute ring)
    try:
        hw = 1024
        n = P * (2 ** 18 if on_cpu else 2 ** 22)
        hb = dr_tpu.halo_bounds(hw, hw, periodic=True)
        v = dr_tpu.distributed_vector(n, np.float32, halo=hb)
        dr_tpu.fill(v, 1.0)
        h = v.halo()
        rounds = 64
        h.exchange_n(rounds)  # warm/compile
        _sync(v)
        # device-side p50: each timed call fuses `rounds` exchanges in one
        # program (lax.fori_loop), so per-exchange time excludes the
        # tunneled per-dispatch overhead entirely
        dt = _time_amortized(lambda: h.exchange_n(rounds),
                             lambda _: _sync(v), calls=4, batches=5)
        out["halo_exchange_amortized_p50_us"] = round(dt / rounds * 1e6, 1)
    except Exception as e:  # pragma: no cover - defensive
        out["halo_error"] = repr(e)[:160]
    finally:
        v = h = None  # span_halo holds the vector; clear both

    # config 4: 2-D heat stencil on the tiled dense matrix.  On TPU the
    # temporally-blocked Pallas kernel (VMEM row bands, T steps per HBM
    # pass) runs first; any failure falls back to the XLA path.
    A = B = M = None
    try:
        m = 1024 if on_cpu else 8192
        w = dr_tpu.heat_step_weights(0.25)
        src = np.zeros((m, m), dtype=np.float32)
        src[m // 2, m // 2] = 1000.0
        dt = steps = None
        if on_tpu:  # the blocked kernel compiles on TPU only
            try:
                from dr_tpu.algorithms.stencil2d import stencil2d_n
                tb = 16
                M = dr_tpu.dense_matrix.from_array(src)

                def run_heat(r):
                    stencil2d_n(M, w, r, time_block=tb)
                    _sync(M)
                # marginal per-block time (dispatch constant cancelled)
                steps = tb
                dt = _marginal(run_heat, r1=2, r2=10)
                out["heat2d_impl"] = "pallas2d"
            except Exception as e:
                out["heat2d_blocked_error"] = repr(e)[:120]
                dt = None
            finally:
                M = None
        if dt is None:
            steps = 10
            A = dr_tpu.dense_matrix.from_array(src)
            B = dr_tpu.dense_matrix.from_array(src)
            dr_tpu.stencil2d_iterate(A, B, w, steps=steps)  # warm
            dt = _time_amortized(
                lambda: dr_tpu.stencil2d_iterate(A, B, w, steps=steps),
                _sync, calls=8)
            out["heat2d_impl"] = "xla"
        out["heat2d_gbps"] = round(
            2.0 * m * m * itemsize * steps / dt / 1e9, 2)
    except Exception as e:  # pragma: no cover - defensive
        out["heat2d_error"] = repr(e)[:160]
    finally:
        A = B = M = None

    # beyond-parity: distributed sample sort (sort_n fused loop; the
    # reference has no sort — the repo's own perf bar needs a recorded
    # number for the surfaces it advertises, VERDICT r4 missing #3)
    try:
        n = (2 ** 20 if on_cpu else 2 ** 24) // P * P
        rng = np.random.default_rng(3)
        v = dr_tpu.distributed_vector(n, np.float32)
        v.assign_array(rng.standard_normal(n).astype(np.float32))
        from dr_tpu.algorithms.sort import sort_by_key_n, sort_n

        def run_sort(r):
            sort_n(v, r)
            _sync(v)
        dt = _marginal(run_sort, r1=2, r2=10, samples=5)
        out["sort_gbps"] = round(n * itemsize / dt / 1e9, 2)
        out["sort_mkeys"] = round(n / dt / 1e6, 1)

        # keys-only per-phase breakdown over the truncation ladder
        # (round 6): consecutive stop_after prefixes timed by the
        # marginal method; differences are the phase costs.
        # Independently guarded (like every config) and BEFORE the
        # key-value leg, so a kv failure cannot eat the breakdown that
        # rides sort_gbps.  v's content is scrap afterwards — this is
        # the last keys-only use of it.
        spread = 0.1 if on_cpu else 0.3
        try:
            if P == 1:
                # no collective phases exist at p=1 (every truncation
                # IS the full program, so ladder differences would be
                # pure noise): the whole sort is the local XLA sort —
                # the honest, platform-bound breakdown (docs/PERF.md
                # round 6)
                out["sort_phases_gbps"] = {
                    "local_sort": out["sort_gbps"]}
                out["sort_phase_dominant"] = "local_sort"
                out["sort_phases_note"] = \
                    "p=1: collective phases collapse; sort IS the " \
                    "local XLA sort"
            else:
                from dr_tpu.algorithms.sort import (SORT_PHASES,
                                                    sort_phases_n)
                from dr_tpu.utils.profiling import profile_phases

                def mk_sort(i):
                    def run(r):
                        sort_phases_n(v, SORT_PHASES[i], r)
                        _sync(v)
                    return run
                bd = profile_phases(mk_sort, SORT_PHASES, r1=2, r2=6,
                                    samples=3, min_spread=spread)
                out["sort_phases_gbps"] = bd.detail(n * itemsize)
                out["sort_phase_dominant"] = bd.dominant
        except Exception as e:  # pragma: no cover - defensive
            out["sort_phases_error"] = repr(e)[:160]

        kd = dr_tpu.distributed_vector(n, np.float32)
        kd.assign_array(rng.standard_normal(n).astype(np.float32))
        pd = dr_tpu.distributed_vector(n, np.int32)
        dr_tpu.iota(pd, 0)

        def run_kv(r):
            sort_by_key_n(kd, pd, r)
            _sync(kd)
        dt = _marginal(run_kv, r1=2, r2=10, samples=5)
        out["sortkv_gbps"] = round(2.0 * n * itemsize / dt / 1e9, 2)
        if phases:
            try:
                if P == 1:
                    out["sortkv_phases_gbps"] = {
                        "local_sort": out["sortkv_gbps"]}
                    out["sortkv_phase_dominant"] = "local_sort"
                else:
                    from dr_tpu.algorithms.sort import (
                        SORTKV_PHASES, sort_by_key_phases_n)
                    from dr_tpu.utils.profiling import profile_phases

                    def mk_kv(i):
                        def run(r):
                            sort_by_key_phases_n(kd, pd,
                                                 SORTKV_PHASES[i], r)
                            _sync(kd)
                        return run
                    bdk = profile_phases(mk_kv, SORTKV_PHASES,
                                         r1=2, r2=6, samples=3,
                                         min_spread=spread)
                    out["sortkv_phases_gbps"] = bdk.detail(
                        2.0 * n * itemsize)
                    out["sortkv_phase_dominant"] = bdk.dominant
            except Exception as e:  # pragma: no cover - defensive
                out["sortkv_phases_error"] = repr(e)[:160]

        # --phases also grows the sort_local kernel-arm A/B
        # (docs/SPEC.md §22.5) — at a per-shard size INSIDE the
        # bitonic eligibility cap (the headline n is far above it,
        # where the pallas pin silently no-ops)
        if phases:
            va = None
            try:
                n_ab = 16384 * P
                va = dr_tpu.distributed_vector(n_ab, np.float32)
                va.assign_array(
                    rng.standard_normal(n_ab).astype(np.float32))

                def run_ab(r):
                    sort_n(va, r)
                    _sync(va)
                out.setdefault("kernels", {})["sort_local"] = \
                    _kernel_ab("sort_local", run_ab, on_tpu,
                               r1=2, r2=6, samples=3)
            except Exception as e:  # pragma: no cover - defensive
                out["sort_kernel_ab_error"] = repr(e)[:120]
            finally:
                va = None
    except Exception as e:  # pragma: no cover - defensive
        out["sort_error"] = repr(e)[:160]
    finally:
        v = kd = pd = None

    # long-context: causal ring attention (sequence-parallel over the
    # same ppermute ring as the halo subsystem; SURVEY §5).  bf16
    # inputs take the fused Pallas flash kernel (f32 accumulation);
    # ring_attention_n chains steps device-side for the measurement.
    try:
        B, S, h, hd = 1, (1024 if on_cpu else 8192), (2 if on_cpu else 8), \
            (64 if on_cpu else 128)
        S = S // P * P
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        # stage on device once: numpy operands would re-cross the host
        # link every call and the transfer would dominate the timing
        q, kk, vv = (jnp.asarray(
            rng.standard_normal((B, S, h, hd)).astype(np.float32),
            dtype=jnp.bfloat16) for _ in range(3))

        def run_attn(r):
            res = dr_tpu.ring_attention_n(q, kk, vv, r, causal=True)
            float(res[0, 0, 0, 0].astype(jnp.float32))
        dt = _marginal_with_fallback(run_attn, on_tpu,
                                     "DR_TPU_RING_IMPL",
                                     "ring_attn_kernel_error", out,
                                     r1=2, r2=18, samples=5)
        flops = 2.0 * B * h * S * S * hd  # causal: half of 4*B*h*S^2*d
        out["ring_attn_tflops"] = round(flops / dt / 1e12, 3)
    except Exception as e:  # pragma: no cover - defensive
        out["ring_attn_error"] = repr(e)[:160]
    finally:
        q = kk = vv = None

    # config 5: CSR SpMV (gemv_example.cpp:18-41), fused-loop (gemv_n).
    # Round 9: the artifact carries the container's chosen-format tag,
    # the ring-schedule PHASE breakdown (gemv_phases_n truncations over
    # SPMV_PHASES — the sort round's profiling discipline), and, under
    # --spmv, a format ladder (gemv_n per forced format).
    try:
        m = 2 ** 14 if on_cpu else 2 ** 17
        k = 32  # nnz per row
        rng = np.random.default_rng(0)
        rows = np.repeat(np.arange(m), k)
        cols = rng.integers(0, m, size=m * k)
        vals = rng.standard_normal(m * k).astype(np.float32)
        A = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols, vals)
        c = dr_tpu.distributed_vector(m, np.float32)
        bv = dr_tpu.distributed_vector(m, np.float32)
        dr_tpu.fill(bv, 1.0)
        dr_tpu.fill(c, 0.0)
        from dr_tpu.algorithms.gemv import (SPMV_PHASES, gemv_n,
                                            gemv_phases_n,
                                            resolved_format)

        def run_spmv(r):
            gemv_n(c, A, bv, r)
            _sync(c)
        dt = _marginal(run_spmv, r1=2, r2=18)
        out["spmv_gflops"] = round(2.0 * m * k / dt / 1e9, 2)
        # the format the measurement actually DISPATCHED: a session-
        # pinned DR_TPU_SPMV_FORMAT routes the number, so it must route
        # the label too (A.format alone would tag a forced-csr run ell)
        out["spmv_format"] = resolved_format(A)
        flops = 2.0 * m * k
        spread = 0.1 if on_cpu else 0.3
        try:
            if P == 1 or not A.ensure_ring():
                # no ring to cut: the whole SpMV is the local
                # contraction — the honest collapse, like the p=1 sort
                out["spmv_phases_gflops"] = {
                    "local_compute": out["spmv_gflops"]}
                out["spmv_phase_dominant"] = "local_compute"
                out["spmv_phases_note"] = \
                    "p=1 or ring-ineligible: no ring phases; SpMV IS " \
                    "the local contraction"
            else:
                from dr_tpu.utils.profiling import profile_phases

                def mk_spmv(i):
                    def run(r):
                        gemv_phases_n(c, A, bv, SPMV_PHASES[i], r)
                        _sync(c)
                    return run
                bd = profile_phases(mk_spmv, SPMV_PHASES, r1=2, r2=10,
                                    samples=3, min_spread=spread)
                out["spmv_phases_gflops"] = bd.detail(flops)
                out["spmv_phase_dominant"] = bd.dominant
        except Exception as e:  # pragma: no cover - defensive
            out["spmv_phases_error"] = repr(e)[:160]
        if spmv_ladder:
            lad = {}
            # a forced-but-ineligible format silently falls back down
            # the dispatch chain (SPEC §12.2) — tag those rungs instead
            # of recording the fallback arm's number under the forced
            # label (two rungs could secretly be the same program)
            from dr_tpu.algorithms.gemv import viable_formats
            from dr_tpu.utils.env import env_override
            viable = viable_formats(A)
            with env_override(DR_TPU_SPMV_FORMAT=None):
                for fmt in ("csr", "ell", "bcsr", "ring"):
                    if not viable[fmt]:
                        lad[fmt] = "ineligible (would fall back)"
                        continue
                    os.environ["DR_TPU_SPMV_FORMAT"] = fmt
                    try:
                        dtf = _marginal(run_spmv, r1=2, r2=10,
                                        samples=3, min_spread=spread)
                        lad[fmt] = round(flops / dtf / 1e9, 2)
                    except Exception as e:
                        lad[fmt] = repr(e)[:80]
            out["spmv_format_ladder_gflops"] = lad
    except Exception as e:  # pragma: no cover - defensive
        out["spmv_error"] = repr(e)[:160]
    finally:
        A = c = bv = None

    # config 5a': multi-vector SpMM on the SAME random pattern as
    # config 5.  Single-vector random SpMV is gather-ISSUE-bound at
    # ~2.6 cycles/entry (docs/PERF.md roofline) — here each gathered
    # slice feeds nv MACs, so aggregate GFLOP/s amortizes the bound.
    try:
        m = 2 ** 14 if on_cpu else 2 ** 17
        k, nv = 32, 8
        rng = np.random.default_rng(0)
        rows = np.repeat(np.arange(m), k)
        cols = rng.integers(0, m, size=m * k)
        vals = rng.standard_normal(m * k).astype(np.float32)
        A = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols, vals)
        import jax.numpy as jnp
        Bm = jnp.asarray(rng.standard_normal((m, nv)).astype(np.float32))

        def run_spmm(r):
            y = dr_tpu.spmm_n(A, Bm, r)
            float(y[0, 0])
        dt = _marginal(run_spmm, r1=2, r2=18)
        out["spmm8_gflops"] = round(2.0 * m * k * nv / dt / 1e9, 2)
        # the arm spmm_n actually RAN (only the grouped ELL/BCSR
        # programs exist; forced csr/ring resolve to ELL)
        from dr_tpu.algorithms.gemv import resolved_spmm_format
        out["spmm_format"] = resolved_spmm_format(A)
    except Exception as e:  # pragma: no cover - defensive
        out["spmm_error"] = repr(e)[:160]
    finally:
        A = Bm = None

    # config 5b: block-banded SpMV — the BCSR dense-tile MXU path
    # (structured sparsity: one 128-slice gather per (8,128) tile)
    try:
        m = 2 ** 12 if on_cpu else 2 ** 15
        half = 128
        rng = np.random.default_rng(1)
        ii = np.repeat(np.arange(m), 2 * half + 1)
        jj = ii + np.tile(np.arange(-half, half + 1), m)
        keep = (jj >= 0) & (jj < m)
        ii, jj = ii[keep], jj[keep]
        vv = rng.standard_normal(len(ii)).astype(np.float32)
        A = dr_tpu.sparse_matrix.from_coo((m, m), ii, jj, vv)
        assert A.ensure_bcsr(), "banded matrix must take the BCSR path"
        c = dr_tpu.distributed_vector(m, np.float32)
        bv = dr_tpu.distributed_vector(m, np.float32)
        dr_tpu.fill(bv, 1.0)
        dr_tpu.fill(c, 0.0)
        from dr_tpu.algorithms.gemv import gemv_n

        def run_bspmv(r):
            gemv_n(c, A, bv, r)
            _sync(c)
        dt = _marginal(run_bspmv, r1=2, r2=18)
        out["spmv_block_gflops"] = round(2.0 * len(ii) / dt / 1e9, 2)
    except Exception as e:  # pragma: no cover - defensive
        out["spmv_block_error"] = repr(e)[:160]
    finally:
        A = c = bv = None
    return out


def _relational_runner(n_fact: int, ncard: int):
    """Build the TPC-style relational pipeline workload — fact table
    (``n_fact`` rows over ``ncard`` keys) joining a one-row-per-key
    dimension table, groupby sum, top_k of the heaviest groups.  ONE
    home shared with ``tools/tune_tpu.py relational`` (the
    ``_pipeline_runners`` precedent: the on-chip ladder must time the
    identical workload the bench rows record).  Returns ``(stage,
    conts)``: ``stage()`` runs join -> groupby -> top_k and returns
    ``(m, ng, per_stage_seconds)``; ``conts`` holds the live
    containers (``jl`` feeds the deferred-fusion probe)."""
    import dr_tpu
    rng = np.random.default_rng(14)
    fk = rng.integers(0, ncard, n_fact).astype(np.float32)
    fv = rng.standard_normal(n_fact).astype(np.float32)
    dk = rng.permutation(ncard).astype(np.float32)
    dv = rng.standard_normal(ncard).astype(np.float32)
    conts = {
        "fkv": dr_tpu.distributed_vector.from_array(fk),
        "fvv": dr_tpu.distributed_vector.from_array(fv),
        "dkv": dr_tpu.distributed_vector.from_array(dk),
        "dvv": dr_tpu.distributed_vector.from_array(dv),
    }
    cap = 2 * n_fact  # dim keys are unique: <= 1 match per fact row
    for nm in ("jk", "jl", "jr", "gk", "gv"):
        conts[nm] = dr_tpu.distributed_vector(cap, np.float32)
    conts["tv"] = dr_tpu.distributed_vector(8, np.float32)
    conts["ti"] = dr_tpu.distributed_vector(8, np.int32)

    def stage():
        c = conts
        ts = {}
        t0 = time.perf_counter()
        m = int(dr_tpu.join(c["fkv"], c["fvv"], c["dkv"], c["dvv"],
                            c["jk"], c["jl"], c["jr"]))
        ts["join"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        # aggregate only the real joined rows (the capacity tail is
        # zeros); m is deterministic, so the window program compiles
        # once across the warm and timed runs
        ng = int(dr_tpu.groupby_aggregate(c["jk"][0:m], c["jl"][0:m],
                                          c["gk"], c["gv"],
                                          agg="sum"))
        ts["groupby"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        # only the LIVE groups: the capacity tail is zeros and must
        # neither enter the candidate pool nor the timing
        dr_tpu.top_k(c["gv"][0:ng], c["tv"], c["ti"])
        _sync(c["tv"])
        ts["topk"] = time.perf_counter() - t0
        return m, ng, ts

    return stage, conts


def _relational_metrics(on_cpu: bool) -> dict:
    """--relational / DR_TPU_BENCH_RELATIONAL=1 (round 14): a small
    TPC-style end-to-end pipeline over the relational layer
    (docs/SPEC.md §17) — a fact table joins a dimension table
    (feature-join shape), the joined product aggregates per key
    (groupby sum), and top_k takes the heaviest groups — the log
    analytics / feature-join composite no single-primitive number can
    fake.  Emits per-stage wall times, end-to-end row throughput, and
    the deferred-fusion dispatch count of the static-shape ops."""
    import dr_tpu
    from dr_tpu.utils.spmd_guard import dispatch_count
    out = {}
    n_fact = 2 ** 14 if on_cpu else 2 ** 18
    ncard = max(n_fact // 16, 4)  # key cardinality (fan-in ~16)
    try:
        from dr_tpu.algorithms import relational as _rel
        from dr_tpu.utils.env import env_override
        stage, conts = _relational_runner(n_fact, ncard)
        stage()  # warm the programs (compiles)
        m, ng, ts = stage()
        total = sum(ts.values())
        out["relational_rows"] = {"fact": n_fact, "dim": ncard,
                                  "joined": m, "groups": ng}
        # the merge route the join took + its per-device
        # gathered-channel rows — the peak-memory proxy that decides
        # broadcast vs repartition on real row counts (SPEC §18.4)
        out["relational_join_route"] = _rel.last_join_route()
        # forced-repartition A/B: the same join through the
        # bounded-memory exchange (threshold 0), so the artifact
        # carries the small/large-side routing gap
        try:
            with env_override(DR_TPU_JOIN_BROADCAST_MAX="0"):
                stage()  # warm the partition programs
                _m2, _ng2, ts2 = stage()
            out["relational_join_partition_ms"] = round(
                ts2["join"] * 1e3, 2)
            out["relational_join_partition_route"] = \
                _rel.last_join_route()
        except Exception as e:  # pragma: no cover - defensive
            out["relational_join_partition_error"] = repr(e)[:120]
        out["relational_join_ms"] = round(ts["join"] * 1e3, 2)
        out["relational_groupby_ms"] = round(ts["groupby"] * 1e3, 2)
        out["relational_topk_ms"] = round(ts["topk"] * 1e3, 2)
        out["relational_pipeline_krows_s"] = round(
            n_fact / total / 1e3, 1)
        # deferred fusion of the static-shape ops: histogram + top_k
        # over the joined values flush as ONE dispatch (dr_tpu/plan.py)
        jl, tv, ti = conts["jl"], conts["tv"], conts["ti"]
        hb = dr_tpu.distributed_vector(16, np.int32)
        with dr_tpu.deferred():  # warm the fused program
            dr_tpu.histogram(jl[0:m], hb, -3.0, 3.0)
            dr_tpu.top_k(jl[0:m], tv, ti)
        d0 = dispatch_count()
        with dr_tpu.deferred():
            dr_tpu.histogram(jl[0:m], hb, -3.0, 3.0)
            dr_tpu.top_k(jl[0:m], tv, ti)
        out["relational_deferred_dispatches"] = dispatch_count() - d0
    except Exception as e:  # pragma: no cover - defensive
        out["relational_error"] = repr(e)[:160]

    # kernel-arm A/Bs (docs/SPEC.md §22.5): the segred monoid core and
    # the histogram scatter-add, each under both env pins at a
    # kernel-eligible per-shard size (the pipeline's joined product is
    # far above the §22 caps, where the pin silently no-ops).
    # Independently guarded like every config here.
    try:
        P = dr_tpu.nprocs()
        rng = np.random.default_rng(8)
        nk = 8192 * P
        gk = dr_tpu.distributed_vector.from_array(
            rng.integers(0, 512, nk).astype(np.int32))
        gv = dr_tpu.distributed_vector.from_array(
            rng.integers(0, 99, nk).astype(np.int32))
        ok = dr_tpu.distributed_vector(1024, np.int32)
        ov = dr_tpu.distributed_vector(1024, np.int32)

        def run_segred(r):
            for _ in range(r):
                dr_tpu.groupby_aggregate(gk, gv, ok, ov, agg="sum")
            _sync(ov)
        hv = dr_tpu.distributed_vector.from_array(
            rng.standard_normal(nk).astype(np.float32))
        hb = dr_tpu.distributed_vector(256, np.int32)

        def run_hist(r):
            for _ in range(r):
                dr_tpu.histogram(hv, hb, -4.0, 4.0)
            _sync(hb)
        kerns = out.setdefault("kernels", {})
        kerns["segred"] = _kernel_ab("segred", run_segred, not on_cpu,
                                     r1=2, r2=6, samples=3)
        kerns["hist"] = _kernel_ab("hist", run_hist, not on_cpu,
                                   r1=2, r2=6, samples=3)
    except Exception as e:  # pragma: no cover - defensive
        out["kernels_error"] = repr(e)[:160]
    finally:
        gk = gv = ok = ov = hv = hb = None
    return out


def _redistribute_metrics(on_cpu: bool) -> dict:
    """--redistribute / DR_TPU_BENCH_REDISTRIBUTE=1 (round 16,
    docs/SPEC.md §18): per-hop GB/s of a layout ping-pong (default
    even <-> uneven rotated cut — every shard's window moves) through
    BOTH impls forced via the override, marginal method.  The
    host-vs-collective gap is the number that justifies the engine."""
    import dr_tpu
    from dr_tpu.utils.env import env_override
    out = {}
    P = dr_tpu.nprocs()
    n = max((2 ** 20 if on_cpu else 2 ** 24) // P * P, P)
    try:
        src = np.arange(n, dtype=np.float32)
        v = dr_tpu.distributed_vector.from_array(src)
        base = n // P
        rot = [base] * P
        rot[0] = base // 2
        rot[-1] = n - sum(rot[:-1])  # uneven: first half-shard, fat tail

        def mk_run(impl):
            def run(r):
                with env_override(DR_TPU_REDISTRIBUTE=impl):
                    for _ in range(r):
                        dr_tpu.redistribute(v, rot)
                        dr_tpu.redistribute(v, None)
                _sync(v)
            return run

        gbps = {}
        for impl in ("host", "collective"):
            dt = _marginal(mk_run(impl), r1=1, r2=5, samples=3,
                           min_spread=0.0)
            # 2 hops per iteration, n float32 elements each
            gbps[impl] = round(2 * n * 4 / dt / 1e9, 3)
        out["redistribute_gbps"] = gbps
        out["redistribute_shape"] = {"n": n, "hops_per_iter": 2,
                                     "dtype": "float32"}
    except Exception as e:  # pragma: no cover - defensive
        out["redistribute_error"] = repr(e)[:160]
    return out


def _serve_metrics(on_cpu: bool) -> dict:
    """--serve / DR_TPU_BENCH_SERVE=1: closed-loop serving load
    generator (round 11).  One in-process ``dr_tpu.serve`` daemon —
    the resident-claim architecture — driven by N concurrent client
    connections, each issuing back-to-back scale/reduce/dot requests
    (closed loop: a client's next request waits for its reply).
    Reports per-request latency percentiles, aggregate request
    throughput, and the daemon's batching story (fused flushes,
    batched-request count, batch high-water) — with batching ON, the
    depth-N arrival window coalesces concurrent clients' ops into one
    deferred-plan flush each."""
    import tempfile
    import threading

    from dr_tpu import serve
    from dr_tpu.utils.env import env_int
    out = {}
    nclients = env_int("DR_TPU_BENCH_SERVE_CLIENTS", 4)
    nreqs = env_int("DR_TPU_BENCH_SERVE_REQS", 24)
    n = 2 ** 12 if on_cpu else 2 ** 16
    tmpdir = tempfile.mkdtemp(prefix="dr_tpu_bench_serve_")
    sock = os.path.join(tmpdir, "daemon.sock")
    srv = serve.Server(sock)
    # client sockets must outlive the daemon's flush watchdog: the
    # warm-up pays the first compiles, which on the tunneled backend
    # can take minutes — a 40 s default timeout would kill the whole
    # serve config before the daemon could answer
    cto = srv.flush_deadline + 60.0
    try:
        srv.start()
        rng = np.random.default_rng(11)
        x = rng.standard_normal(n).astype(np.float32)
        y = rng.standard_normal(n).astype(np.float32)
        with serve.Client(sock, timeout=cto) as c:  # compile once
            c.scale(x, a=1.0)
            c.reduce(x)
            c.dot(x, y)
        lat = [[] for _ in range(nclients)]
        errs = []

        def worker(i):
            try:
                with serve.Client(sock, timeout=cto,
                                  tenant=f"client{i}") as c:
                    for r in range(nreqs):
                        op = ("scale", "reduce", "dot")[r % 3]
                        t0 = time.perf_counter()
                        if op == "scale":
                            # streamed coefficient: one cached program
                            c.scale(x, a=1.0 + r * 1e-6)
                        elif op == "reduce":
                            c.reduce(x)
                        else:
                            c.dot(x, y)
                        lat[i].append(time.perf_counter() - t0)
            except Exception as e:
                errs.append(repr(e)[:120])

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(nclients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        all_lat = np.sort(np.array([t for l in lat for t in l]))
        if errs:
            out["serve_errors"] = errs[:3]
        if all_lat.size:
            out["serve_latency_ms"] = {
                p: round(float(np.percentile(all_lat, q)) * 1e3, 2)
                for p, q in (("p50", 50), ("p95", 95), ("p99", 99))}
            out["serve_clients"] = nclients
            out["serve_requests"] = int(all_lat.size)
            out["serve_rps"] = round(all_lat.size / wall, 1)
        st = srv.stats()
        out["serve_batch"] = {
            "flushes": st["flushes"],
            "batched_requests": st["batched_requests"],
            "batch_hw": st["batch_hw"],
            "queue_depth_hw": st["depth_hw"],
            "shed": st["shed"], "rejected": st["rejected"]}
        # daemon-side latency split (round 12, dr_tpu/obs): where each
        # request's time went — queue-wait vs service vs the shared
        # batch-flush — next to the client-side percentiles above.
        # Sampled by the daemon's always-live histograms, so this
        # works traced or not.
        hists = (st.get("obs") or {}).get("histograms", {})
        split = {}
        for key, label in (("serve.queue_wait_ms", "queue_wait"),
                           ("serve.service_ms", "service"),
                           ("serve.flush_ms", "flush")):
            h = hists.get(key)
            if h and h.get("count"):
                split[label] = {"p50": h.get("p50"),
                                "p95": h.get("p95"),
                                "count": h["count"]}
        if split:
            out["serve_daemon_ms"] = split
        if st["degraded"]:
            out["serve_degraded"] = st["degraded"]

        # ---- round 17: the serving data plane (docs/SPEC.md §19)
        # arena vs inline-wire p50 A/B at a >= 1 MiB payload — the
        # zero-copy acceptance number (same op, same daemon, one
        # closed-loop client; only the transport differs)
        nbig = 2 ** 18  # 1 MiB of f32 — the acceptance floor
        xb = rng.standard_normal(nbig).astype(np.float32)
        ab = {"payload_mib": round(nbig * 4 / 2 ** 20, 2)}
        for label, use_arena in (("inline", False), ("arena", True)):
            lat2 = []
            with serve.Client(sock, timeout=cto,
                              arena=use_arena) as c:
                c.scale(xb, a=1.0)  # warm: compile + arena attach
                for r in range(12):
                    t0 = time.perf_counter()
                    c.scale(xb, a=1.0 + r * 1e-6)
                    lat2.append(time.perf_counter() - t0)
            ab[f"{label}_p50"] = round(
                float(np.percentile(lat2, 50)) * 1e3, 3)
        if ab["arena_p50"] > 0:
            ab["speedup"] = round(ab["inline_p50"] / ab["arena_p50"],
                                  3)
        out["serve_arena_ms"] = ab

        # skewed heavy/light load: per-tenant latency breakdown — the
        # weighted-fair no-starvation evidence (client-side per-tenant
        # percentiles next to the daemon's per-tenant queue-wait)
        xs = rng.standard_normal(2 ** 12).astype(np.float32)
        tlat = {"heavy": [], "light": []}

        def tenant_worker(tenant, reqs):
            try:
                with serve.Client(sock, timeout=cto,
                                  tenant=tenant) as c:
                    for r in range(reqs):
                        t0 = time.perf_counter()
                        c.scale(xs, a=1.0 + r * 1e-6)
                        tlat[tenant].append(time.perf_counter() - t0)
            except Exception as e:  # pragma: no cover - defensive
                out.setdefault("serve_tenant_errors", []) \
                    .append(repr(e)[:120])

        tthreads = [threading.Thread(target=tenant_worker,
                                     args=("heavy", 16))
                    for _ in range(3)]
        tthreads.append(threading.Thread(target=tenant_worker,
                                         args=("light", 8)))
        for t in tthreads:
            t.start()
        for t in tthreads:
            t.join()
        tenants = {}
        hists2 = (srv.stats().get("obs") or {}).get("histograms", {})
        for tenant, lats in tlat.items():
            if not lats:
                continue
            arr = np.sort(np.array(lats))
            row = {"requests": int(arr.size),
                   "p50_ms": round(float(np.percentile(arr, 50))
                                   * 1e3, 2),
                   "p95_ms": round(float(np.percentile(arr, 95))
                                   * 1e3, 2)}
            qw = hists2.get(f"serve.queue_wait_ms.t.{tenant}")
            if qw:
                row["queue_wait_p95_ms"] = qw.get("p95")
            tenants[tenant] = row
        if tenants:
            out["serve_tenants"] = tenants

        # replica scale-out: closed-loop rps at 1 vs 2 replicas
        # behind the consistent-hash front.  CPU-route replicas only
        # (the primary daemon holds the one claim on this host), so
        # the leg runs on CPU sessions and is skipped on silicon —
        # tune_tpu.py serve ladders it for the queued chip session.
        if on_cpu:
            router = {}
            for nrep in (1, 2):
                fleet = serve.Router(
                    os.path.join(tmpdir, f"fleet{nrep}"),
                    replicas=nrep, cpu=True, batch_window=0.0)
                try:
                    fleet.start()
                    rlat = [[] for _ in range(4)]

                    def rworker(i):
                        try:
                            with serve.RouterClient(
                                    fleet.paths(), tenant=f"rt{i}",
                                    timeout=cto) as rc:
                                rc.scale(xs, a=1.0)  # warm
                                for r in range(12):
                                    t0 = time.perf_counter()
                                    rc.scale(xs, a=1.0 + r * 1e-6)
                                    rlat[i].append(
                                        time.perf_counter() - t0)
                        except Exception as e:  # pragma: no cover
                            out.setdefault("serve_router_errors", []) \
                                .append(repr(e)[:120])

                    rthreads = [threading.Thread(target=rworker,
                                                 args=(i,))
                                for i in range(4)]
                    t0 = time.perf_counter()
                    for t in rthreads:
                        t.start()
                    for t in rthreads:
                        t.join()
                    wall2 = time.perf_counter() - t0
                    alat = np.sort(np.array(
                        [v for l in rlat for v in l]))
                    if alat.size:
                        router[f"replicas_{nrep}"] = {
                            "rps": round(alat.size / wall2, 1),
                            "p50_ms": round(
                                float(np.percentile(alat, 50)) * 1e3,
                                2)}
                finally:
                    fleet.stop()
            if router:
                out["serve_router"] = router

            # rolling-restart availability (ISSUE 14, SPEC §20.6):
            # classified-error count + p99 seen by a closed-loop
            # client while the 2-replica fleet restarts — once via
            # the graceful drain protocol (rolling_restart: zero
            # errors expected) and once via an abrupt replica crash +
            # respawn (the breaker re-hash absorbs it; the journal
            # brings resident state back).  CPU sessions only, like
            # the router leg above.
            from dr_tpu.utils.env import env_override
            from dr_tpu.utils import resilience as _res
            restart = {}
            for label in ("drain", "crash"):
                fleet = serve.Router(
                    os.path.join(tmpdir, f"cp_{label}"), replicas=2,
                    cpu=True, batch_window=0.0,
                    state_dir=os.path.join(tmpdir, f"cps_{label}"))
                errors, rlat2 = [], []
                stop_evt = threading.Event()

                def aworker(fleet=fleet, errors=errors, rlat2=rlat2,
                            stop_evt=stop_evt):
                    try:
                        with serve.RouterClient(fleet.paths(),
                                                tenant="avail",
                                                timeout=cto) as rc:
                            rc.scale(xs, a=1.0)  # warm
                            while not stop_evt.is_set():
                                t0 = time.perf_counter()
                                try:
                                    rc.scale(xs, a=1.0)
                                    rlat2.append(
                                        time.perf_counter() - t0)
                                except _res.ResilienceError as e:
                                    errors.append(
                                        type(e).__name__)
                    except Exception as e:  # pragma: no cover
                        errors.append(repr(e)[:80])

                try:
                    fleet.start()
                    # paced probes, NOT 0.0: zero delays let a tight
                    # client loop burn the whole probe budget inside
                    # one restart's downtime (the replica would never
                    # re-admit)
                    with env_override(DR_TPU_SERVE_PROBE_S="0.01"):
                        t = threading.Thread(target=aworker)
                        t.start()
                        time.sleep(0.2)
                        if label == "drain":
                            fleet.rolling_restart()
                        else:
                            # abrupt stop = the crash; restart = the
                            # supervisor's respawn step.  Kill the
                            # replica the tenant actually hashes to —
                            # killing the other one would measure an
                            # undisturbed fleet.
                            from dr_tpu.serve.router import HashRing
                            victim = fleet.paths().index(
                                HashRing(fleet.paths())
                                .lookup("avail"))
                            fleet._servers[victim].stop()
                            time.sleep(0.1)
                            fleet.restart_replica(victim)
                        time.sleep(0.3)
                        stop_evt.set()
                        t.join(timeout=60.0)
                    row = {"classified_errors": len(errors),
                           "requests": len(rlat2)}
                    if rlat2:
                        row["p99_ms"] = round(float(
                            np.percentile(np.array(rlat2), 99)) * 1e3,
                            2)
                    if errors:
                        row["error_classes"] = sorted(set(errors))[:4]
                    restart[label] = row
                finally:
                    stop_evt.set()
                    fleet.stop()
            out["serve_restart"] = restart
    except Exception as e:  # pragma: no cover - defensive
        out["serve_error"] = repr(e)[:160]
    finally:
        try:
            srv.stop()
        except Exception:  # pragma: no cover - teardown best effort
            out.setdefault("serve_error", "daemon stop failed")
        import shutil
        shutil.rmtree(tmpdir, ignore_errors=True)
    return out


def _plan_opt_chain(n_fact: int, ncard: int, mode: str):
    """One deferred relational pipeline flush under
    ``DR_TPU_PLAN_OPT=mode`` on fresh containers: fusible elementwise
    work interleaved with the opaque relational ops (join_auto ->
    groupby_auto) and the fusible histogram/top_k tail — exactly the
    shape whose recording-order run splits the §21 merge pass erases.
    Returns ``(dispatches_in_flush, wall_seconds, opt_note,
    results)``."""
    import dr_tpu
    from dr_tpu.utils.env import env_override
    from dr_tpu.utils.spmd_guard import dispatch_count

    rng = np.random.default_rng(19)
    fk = rng.integers(0, ncard, n_fact).astype(np.float32)
    fv = rng.standard_normal(n_fact).astype(np.float32)
    dk = rng.permutation(ncard).astype(np.float32)
    dv = rng.standard_normal(ncard).astype(np.float32)
    aux = rng.standard_normal(n_fact).astype(np.float32)
    fkv = dr_tpu.distributed_vector.from_array(fk)
    fvv = dr_tpu.distributed_vector.from_array(fv)
    dkv = dr_tpu.distributed_vector.from_array(dk)
    dvv = dr_tpu.distributed_vector.from_array(dv)
    a1 = dr_tpu.distributed_vector.from_array(aux)
    a2 = dr_tpu.distributed_vector.from_array(aux)
    hb = dr_tpu.distributed_vector(16, np.int32)
    tv = dr_tpu.distributed_vector(8, np.float32)
    ti = dr_tpu.distributed_vector(8, np.int32)
    with env_override(DR_TPU_PLAN_OPT=mode):
        d0 = dispatch_count()
        t0 = time.perf_counter()
        with dr_tpu.deferred() as p:
            dr_tpu.for_each(a1, _pl_scale, 2.0)       # fusible run 1
            j = dr_tpu.join_auto(fkv, fvv, dkv, dvv)  # opaque
            dr_tpu.for_each(a2, _pl_shift, 1.0)       # fusible run 2
            g = dr_tpu.groupby_auto(fkv, fvv, agg="sum")  # opaque
            dr_tpu.histogram(a1, hb, -6.0, 6.0)       # fusible run 3
            dr_tpu.top_k(a1, tv, ti)
        wall = time.perf_counter() - t0
        used = dispatch_count() - d0
        results = (j.count, g.count, dr_tpu.to_numpy(hb).tolist())
    return used, wall, (p.log[-1].get("opt") or {}), results


def _plan_metrics(on_cpu: bool) -> dict:
    """--plan / DR_TPU_BENCH_PLAN=1 (round 19, docs/SPEC.md §21): the
    plan-optimizer A/B — the deferred relational pipeline (join_auto
    -> groupby_auto -> histogram/top_k with interleaved elementwise
    runs) and the serve batched flush, each measured with
    ``DR_TPU_PLAN_OPT=0`` vs ``all``: dispatch counts per flush
    (STRICTLY fewer with the optimizer on is the acceptance bar on
    the relational pipeline) and wall time, plus the per-flush pass
    note (runs merged / ops eliminated / pushdowns)."""
    import tempfile
    import threading

    from dr_tpu import serve
    from dr_tpu.utils.env import env_override
    from dr_tpu.utils.spmd_guard import dispatch_count
    out = {}
    n_fact = 2 ** 12 if on_cpu else 2 ** 16
    ncard = max(n_fact // 16, 4)
    try:
        leg = {}
        for mode in ("0", "all"):
            _plan_opt_chain(n_fact, ncard, mode)   # warm the compiles
            used, wall, note, res = _plan_opt_chain(n_fact, ncard,
                                                    mode)
            leg[mode] = {"dispatches": used,
                         "wall_ms": round(wall * 1e3, 2)}
            if mode == "all":
                leg["opt_note"] = {k: note.get(k) for k in
                                   ("passes", "merged_runs",
                                    "dce_ops", "pushdowns")}
                leg["results"] = {"joined": res[0], "groups": res[1]}
        leg["fewer_dispatches"] = \
            leg["all"]["dispatches"] < leg["0"]["dispatches"]
        out["plan_opt_relational"] = leg
    except Exception as e:  # pragma: no cover - defensive
        out["plan_opt_relational_error"] = repr(e)[:160]

    # ---- serve leg: concurrent clients batched into ONE deferred
    # flush ride the optimizer — scale runs split by opaque scans
    # coalesce when the §21 merge pass is armed
    try:
        rng = np.random.default_rng(20)
        x = rng.standard_normal(2 ** 12 if on_cpu
                                else 2 ** 16).astype(np.float32)
        sleg = {}
        for mode in ("0", "all"):
            tmpdir = tempfile.mkdtemp(prefix="dr_tpu_bench_plan_")
            with env_override(DR_TPU_PLAN_OPT=mode):
                srv = serve.Server(os.path.join(tmpdir, "p.sock"),
                                   batch_window=0.01).start()
                try:
                    def burst():
                        errs = []

                        # TWO tenants, one op class each: the DRR
                        # admission queue round-robins across tenants
                        # (FIFO within), so the batch's recorded
                        # queue DETERMINISTICALLY interleaves scale
                        # runs with opaque scans — the recording-
                        # order split the merge pass erases
                        def worker(i):
                            try:
                                tenant = "scans" if i % 2 else "scales"
                                with serve.Client(srv.path,
                                                  timeout=120.0,
                                                  tenant=tenant) as c:
                                    if i % 2:
                                        c.scan(x)
                                    else:
                                        c.scale(x, a=1.0 + i)
                            except Exception as e:
                                errs.append(repr(e)[:120])
                        ths = [threading.Thread(target=worker,
                                                args=(i,))
                               for i in range(4)]
                        t0 = time.perf_counter()
                        for t in ths:
                            t.start()
                        for t in ths:
                            t.join()
                        return errs, time.perf_counter() - t0
                    burst()  # warm the per-shape compiles
                    d0 = dispatch_count()
                    errs, wall = burst()
                    sleg[mode] = {
                        "dispatches": dispatch_count() - d0,
                        "wall_ms": round(wall * 1e3, 2)}
                    if errs:
                        sleg[mode]["errors"] = errs[:2]
                finally:
                    srv.stop()
                    import shutil
                    shutil.rmtree(tmpdir, ignore_errors=True)
        out["plan_opt_serve_batch"] = sleg
    except Exception as e:  # pragma: no cover - defensive
        out["plan_opt_serve_error"] = repr(e)[:160]
    return out


def _relay_listening() -> bool:
    """Claim-free reachability check of the loopback tunnel relay (ONE
    copy for the whole repo: utils/resilience.relay_listening — shared
    with ``entry()``/``dryrun_multichip`` and ``tools/tune_tpu.py``).
    Kept as a module global so tests monkeypatch bench's policy alone."""
    from dr_tpu.utils import resilience
    return resilience.relay_listening()


def _dead_relay() -> bool:
    """True when the tunneled (axon) platform is in play but its relay
    is not even listening — a state where no claim can be served and
    probing only burns the caller's timeout budget."""
    from dr_tpu.utils import resilience
    return resilience.dead_relay(listening=_relay_listening)


def _exec_cpu_fallback(err: str, *, probe_s: float = 0.0,
                       retries: int = 0):
    """Re-exec this benchmark with the CPU platform forced and the
    degradation STORY recorded — the single exit ramp for every
    dead-relay / failed-probe path.  The story (fallback reason,
    original probe error, retry count, probe wall time) rides the env
    into the child so the tagged CPU run's JSON carries it
    (resilience.degradation_story), not only stderr."""
    print(f"device init failed ({err}); re-running on CPU",
          file=sys.stderr)
    env = dict(os.environ)
    env["_DR_TPU_BENCH_CPU_FALLBACK"] = "1"
    env["_DR_TPU_BENCH_DEGRADED"] = err
    env["_DR_TPU_BENCH_RETRIES"] = str(retries)
    env["_DR_TPU_BENCH_PROBE_S"] = f"{probe_s:.3f}"
    env["JAX_PLATFORMS"] = "cpu"
    # keep the CLI (--phases) across the re-exec
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)]
              + sys.argv[1:], env)


def _devices_or_die(timeout_s: float):
    """First backend touch through the SHARED degradation router
    (resilience.route_first_touch over runtime.probe_devices): a
    recorded result beats the eternal hang a wedged tunnel relay
    produces.  The router owns the policy; bench owns the exec
    mechanics its decisions map onto:

    * ``"ok"``    -> return the probed devices.
    * ``"retry"`` -> probe failed with the relay still LISTENING
      (wedged claim path): retry ONCE in a fresh process after a
      cool-down (round-3 probe tallies show single claims failing
      where a later one lands instantly; a hung claim blocks the
      singleton PJRT init lock, so an in-process retry would just
      join the hang).
    * ``"cpu"``   -> dead relay, or the retry leg failed too: re-exec
      with the CPU platform forced — an honest smoke number with
      ``detail.device = cpu`` and ``detail.degraded`` naming the cause
      still beats a zero.  The child sets the platform before backend
      init, so its probe returns immediately; if even that fails,
      record the error and exit.

    Worst-case init time stays bounded: timeout_s + cooldown +
    min(timeout_s, retry timeout) — defaults 420 + 45 + 240 s.  The
    cool-down runs in the RETRY child (after the exec that killed the
    first, possibly mid-claim, client), so the server-side grant gets
    the whole gap to expire before the fresh claim.
    """
    from dr_tpu.parallel.runtime import probe_devices
    from dr_tpu.utils.env import env_float, env_raw, env_str
    from dr_tpu.utils.resilience import (degradation_story,
                                         route_first_touch)

    retried = bool(env_raw("_DR_TPU_BENCH_RETRY"))
    cpu_child = bool(env_raw("_DR_TPU_BENCH_CPU_FALLBACK"))
    if cpu_child:
        import jax
        jax.config.update("jax_platforms", "cpu")
    elif retried:
        # Cool down HERE, in the fresh child, before its first claim:
        # the exec that spawned this process killed the first probe's
        # (possibly mid-claim) client, and the server-side grant needs
        # the gap AFTER that death — sleeping in the parent before the
        # exec would give it zero post-death expiry time.
        time.sleep(env_float("DR_TPU_BENCH_RETRY_COOLDOWN", 45.0))
        timeout_s = min(timeout_s,
                        env_float("DR_TPU_BENCH_RETRY_TIMEOUT", 240.0))
    ft = route_first_touch(timeout_s, retried=retried or cpu_child,
                           probe=probe_devices, is_dead=_dead_relay,
                           listening=_relay_listening)
    if ft.decision == "ok":
        return ft.devices
    prior_s = env_float("_DR_TPU_BENCH_PROBE_S", 0.0)
    if ft.decision == "retry":
        print(f"device init failed ({ft.err}); retrying once in a "
              "fresh process after a cool-down", file=sys.stderr)
        env = dict(os.environ)
        env["_DR_TPU_BENCH_RETRY"] = "1"
        env["_DR_TPU_BENCH_FIRST_ERR"] = ft.err
        env["_DR_TPU_BENCH_PROBE_S"] = f"{ft.probe_wall_s:.3f}"
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)]
                  + sys.argv[1:], env)
    if not cpu_child:
        err = ft.err
        if retried:
            first = env_str("_DR_TPU_BENCH_FIRST_ERR")
            if first and first != err:
                err = f"{err}; first attempt: {first}"
            err = f"retry failed: {err}"
        elif not ft.probe_skipped:
            err = f"{err}; relay not listening, retry skipped"
        _exec_cpu_fallback(err, probe_s=prior_s + ft.probe_wall_s,
                           retries=1 if retried else 0)
    # even the CPU child could not init: record the error and exit
    detail = {"error": ft.err}
    story = degradation_story()
    if story:
        # keep the original TPU-side cause alongside the child's error
        detail["degraded"] = story
    print(json.dumps({
        "metric": "stencil1d_5pt_effective_bandwidth_per_chip",
        "value": 0.0, "unit": "GB/s", "vs_baseline": 0.0,
        "detail": detail,
    }))
    sys.stdout.flush()
    os._exit(1)


def main():
    from dr_tpu.utils.env import (env_flag, env_float, env_int, env_raw,
                                  env_str)
    n = env_int("DR_TPU_BENCH_N", 2 ** 30)

    # healthy claims complete in seconds; a wedged relay otherwise eats
    # the driver's whole bench budget before the CPU fallback can run
    _devices_or_die(env_float("DR_TPU_BENCH_INIT_TIMEOUT", 420.0))
    import jax
    import dr_tpu
    from dr_tpu.ops import stencil_pallas

    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    on_tpu = dev.platform == "tpu"
    # default chain on TPU: MXU composed-operator matmul path, then the
    # Pallas VMEM kernel, then plain XLA; elsewhere XLA only (interpret-
    # mode pallas is far too slow for a benchmark)
    if env_raw("DR_TPU_BENCH_IMPL") is not None:
        chain = [env_str("DR_TPU_BENCH_IMPL").lower()]
    elif on_tpu:
        chain = ["matmul", "matmul_xla"] + \
            (["pallas"] if stencil_pallas.supported() else []) + ["xla"]
    else:
        chain = ["xla"]
    # 256 composed steps per HBM pass on the matmul path (band spans
    # four lane columns each side at radius 2 — the round-3 measured
    # winner, tools/tune_stencil.log); the pallas VPU path clamps per
    # its own budget
    tblock = env_int("DR_TPU_BENCH_TBLOCK", 256)
    if on_cpu and env_raw("DR_TPU_BENCH_N") is None:
        n = 2 ** 24  # keep CPU smoke runs fast

    dr_tpu.init(jax.devices())
    res = None
    for i, impl in enumerate(chain):
        blocked = impl in ("pallas", "matmul", "matmul_xla")
        steps = env_int("DR_TPU_BENCH_STEPS", 512 if blocked else 16,
                        floor=0)
        try:
            res = _measure(impl, n, steps, tblock)
            break
        except Exception:
            if i + 1 == len(chain):
                raise
            # report the failure and fall back so the driver still
            # records a number for the round
            import traceback
            traceback.print_exc(file=sys.stderr)
            print(f"{impl} path failed; falling back to {chain[i + 1]}",
                  file=sys.stderr)
        # settle OUTSIDE the except block (the live traceback pins the
        # failed attempt's device buffers) before the next impl allocates
        _settle(3.0)

    nchips = 1  # single-controller measurement is per chip
    peak = _peak_for(dev)
    target = 0.7 * peak

    secondary = {}
    if env_str("DR_TPU_BENCH_SECONDARY", "1") != "0":
        # --phases (or DR_TPU_BENCH_PHASES=1): add the key-value sort
        # phase ladder on top of the always-on keys-only breakdown
        phases = "--phases" in sys.argv[1:] or env_flag("DR_TPU_BENCH_PHASES")
        # --spmv (or DR_TPU_BENCH_SPMV=1 — both survive the two
        # CPU-fallback re-execs, like --pipeline): add the spmv format
        # ladder on top of the always-on phase breakdown + format tag
        spmv_ladder = ("--spmv" in sys.argv[1:]
                       or env_flag("DR_TPU_BENCH_SPMV"))
        secondary = _secondary_metrics(on_cpu, on_tpu, phases=phases,
                                       spmv_ladder=spmv_ladder)
        # pipeline config (round 8): eager-vs-deferred 5-op chain.
        # Always on; --pipeline (or DR_TPU_BENCH_PIPELINE=1 — the flag
        # survives both CPU-fallback re-execs like --phases) adds the
        # chain-length ladder for the next chip session.
        ladder = ("--pipeline" in sys.argv[1:]
                  or env_flag("DR_TPU_BENCH_PIPELINE"))
        secondary.update(_pipeline_metrics(on_cpu, ladder=ladder))
        # serving config (round 11): the closed-loop load generator
        # is opt-in (--serve / DR_TPU_BENCH_SERVE=1 — argv and env
        # both survive the CPU-fallback re-execs) and, like every
        # other config here, honors DR_TPU_BENCH_SECONDARY=0; it
        # spins a resident daemon and measures multi-client latency
        # percentiles with batching on
        if "--serve" in sys.argv[1:] or env_flag("DR_TPU_BENCH_SERVE"):
            secondary.update(_serve_metrics(on_cpu))
        # relational config (round 14): the TPC-style join -> groupby
        # -> top_k pipeline is opt-in (--relational /
        # DR_TPU_BENCH_RELATIONAL=1 — argv and env both survive the
        # CPU-fallback re-execs) and honors DR_TPU_BENCH_SECONDARY=0
        if "--relational" in sys.argv[1:] \
                or env_flag("DR_TPU_BENCH_RELATIONAL"):
            rel = _relational_metrics(on_cpu)
            # detail.kernels is shared with the --phases sort_local
            # A/B — merge the sub-dict instead of clobbering it
            if "kernels" in rel and "kernels" in secondary:
                secondary["kernels"].update(rel.pop("kernels"))
            secondary.update(rel)
        # redistribute config (round 16): host vs collective re-layout
        # ladder, opt-in (--redistribute / DR_TPU_BENCH_REDISTRIBUTE=1
        # — argv and env both survive the CPU-fallback re-execs) and
        # honoring DR_TPU_BENCH_SECONDARY=0 like every config here
        if "--redistribute" in sys.argv[1:] \
                or env_flag("DR_TPU_BENCH_REDISTRIBUTE"):
            secondary.update(_redistribute_metrics(on_cpu))
        # plan-optimizer config (round 19, docs/SPEC.md §21): the
        # DR_TPU_PLAN_OPT=0-vs-all A/B over the deferred relational
        # pipeline and the serve batched flush, opt-in (--plan /
        # DR_TPU_BENCH_PLAN=1 — argv and env both survive the
        # CPU-fallback re-execs) and honoring DR_TPU_BENCH_SECONDARY=0
        if "--plan" in sys.argv[1:] or env_flag("DR_TPU_BENCH_PLAN"):
            secondary.update(_plan_metrics(on_cpu))

    # tagged CPU fallback: the full degradation story (reason, original
    # probe error, retry count, probe wall time — and, AFTER the serve
    # config above has run, the daemon's serve markers) survives into
    # the artifact, not only stderr
    from dr_tpu.utils.resilience import degradation_story
    story = degradation_story()

    # tap dispatch counts (round 8): the headline timed run's count
    # joins the pipeline arms so dispatch regressions show in every
    # BENCH_r*.json artifact
    dispatch_counts = {"headline_timed_run": res.get("dispatches")}
    dispatch_counts.update(secondary.pop("dispatch_counts", {}))

    # observability snapshot (round 12, dr_tpu/obs — SPEC §15): the
    # compact metrics snapshot rides EVERY artifact as detail.obs;
    # under DR_TPU_TRACE=1 the Chrome trace is exported and its path
    # recorded so a bench run's trace is one click from its number
    from dr_tpu import obs
    obs_detail = obs.snapshot()
    if obs.armed():
        try:
            obs_detail["trace_file"] = obs.export_chrome_trace()
        except OSError as e:
            obs_detail["trace_error"] = repr(e)[:120]

    print(json.dumps({
        "metric": "stencil1d_5pt_effective_bandwidth_per_chip",
        "value": round(res["gbps"] / nchips, 2),
        "unit": "GB/s",
        "vs_baseline": round(res["gbps"] / nchips / target, 4),
        "detail": {
            "n": res["n"], "steps": res["steps"],
            "seconds": res["seconds"], "impl": res["impl"],
            "device": str(dev), "peak_hbm_gbps": peak,
            "phys_gbps": round(res["phys_gbps"] / nchips, 2),
            "target_gbps": round(target, 1),
            "dispatch_counts": dispatch_counts,
            "obs": obs_detail,
            **({"degraded": story} if story else {}),
            **secondary,
        },
    }))


if __name__ == "__main__":
    main()
