#!/bin/bash
# Patient background watcher for the two sweeps outage 3 swallowed
# (stencil at DEFAULT precision, physbw).  One patient probe at a time
# (clean exits; a failing probe burns its ~25-min client retry budget,
# so the effective cadence is ~40 min); on the first success, waits out
# the claim gap and runs ONLY the two leftover sweeps.
# Log: tools/watch_leftovers.log
set -u
cd "$(dirname "$0")/.."
log() { echo "[watch_leftovers $(date +%H:%M:%S)] $*" >> tools/watch_leftovers.log; }

log "watcher started"
for attempt in $(seq 1 12); do
  log "probe attempt $attempt"
  python -u - > tools/probe_leftover.log 2>&1 <<'PY'
import time, sys
t0 = time.time()
import jax
try:
    devs = jax.devices()
    print(f"PATIENT PROBE OK after {time.time()-t0:.0f}s:", devs)
    import jax.numpy as jnp
    print("sum:", float(jnp.ones((64,)).sum()))
    sys.exit(0)
except Exception as e:
    print(f"PATIENT PROBE FAIL after {time.time()-t0:.0f}s:", repr(e)[:200])
    sys.exit(3)
PY
  rc=$?
  if [ "$rc" -eq 0 ]; then
    log "CHIP ALIVE (attempt $attempt) — claim gap, then the two sweeps"
    sleep 300
    log "stencil at DEFAULT precision"
    DR_TPU_MM_PRECISION=default python -u tools/tune_tpu.py stencil \
      > tools/tune_stencil_default.log 2>&1
    log "stencil-default exit=$?"
    sleep 300
    log "physbw"
    python -u tools/tune_tpu.py physbw > tools/tune_physbw.log 2>&1
    log "physbw exit=$?"
    log "leftover sweeps complete"
    exit 0
  fi
  log "probe failed (rc=$rc); sleeping 15 min"
  sleep 900
done
log "watcher exhausted its attempts"
exit 1
