#!/usr/bin/env python
"""drlint — dr_tpu-specific static invariant checker.

Four rounds of PRs each re-fixed instances of the same bug classes;
this pass encodes them as permanent rules over ``dr_tpu/``, ``tools/``,
``tests/`` (+ ``bench.py``, ``__graft_entry__.py``):

====  =====================================================================
rule  invariant
====  =====================================================================
R1    traced-operand: a runtime scalar (``.item()`` result, ``float()`` of
      a subscript/attribute) must not be baked into a jitted program body
      via closure, nor keyed BY VALUE into a program cache — route it
      through a traced operand (``_traced_op_key``/BoundOp).  Value-keyed
      scalars are the recompile-storm class; every new value compiles a
      new program.
R2    env-registry: every ``DR_TPU_*`` / ``_DR_TPU_*`` value READ goes
      through ``dr_tpu/utils/env`` (tolerant parsing, one registry), and
      every ``DR_TPU_*`` var referenced anywhere must have a row in the
      docs/SPEC.md §13 env table (both drift directions are checked;
      writes — sweeps, ``env_override`` — are allowed raw).
R3    fault-sites: every ``faults.fire``/``inject``/``injected`` site
      literal must name (or glob onto) a ``faults.SITES`` entry, every
      SITES entry must actually be fired somewhere in ``dr_tpu/``, and
      the ``tests/test_chaos.py`` battery must sweep the registry.
R4    collective-divergence: a collective (``ppermute``/``psum``/
      ``all_gather``/``all_to_all``/shift/…) lexically under an ``if``/
      ``while``/``for`` whose condition reads runtime DATA (subscripts,
      ``.item()``, ``.any()``-family reductions) diverges dispatch order
      across ranks — the class ``spmd_guard.first_divergence`` only
      names at runtime, after the hang.  Mesh-static conditions (names,
      ``.shape[…]``, literals) are fine.
R5    fallback-warn: degradation paths announce themselves through
      ``utils.fallback.warn_fallback`` (the chaos-countable registry) —
      bare ``warnings.warn`` in package code and broad ``except: pass``
      swallows are findings.
R6    tapped-cache: ``jax.jit`` in ``dr_tpu/`` must live in a module on
      the TappedCache discipline (so dispatches ride the spmd_guard
      tap); immediately-invoked ``jax.jit(f)(…)`` (compile-per-call) and
      plain-dict program caches are findings anywhere.
R7    plan-opt registry: every optimizer pass registered in
      ``dr_tpu/plan/opt.py``'s ``PASSES`` must have a docs/SPEC.md
      §21.2 pass-table row (semantics + bit-identity argument) and
      bit-identity fuzz coverage (``test_fuzz_plan_opt`` sweeps
      ``PASS_NAMES``, or names each pass) — both drift directions;
      registration itself is the per-pass disable flag
      (``DR_TPU_PLAN_OPT_DISABLE`` keys on the registered name).
R9    footprint-closure: every plan-item record site in ``dr_tpu/``
      declares its footprint — a ``_FusedOp(…)`` construction passes
      ``reads=``/``writes=`` whose slot positions are DERIVED from the
      op's actual traced operands (``run.slot(…)`` results, chased
      through local assignments — the R1 machinery pointed at
      footprints), and a ``record_opaque(…)`` call provides BOTH
      ``reads`` and ``writes`` (an explicit ``None`` is the documented
      barrier opt-in).  Whole-repo closure à la R3/R7/R8: the
      ``plansan.FAMILIES`` registry ↔ the ``Plan.record_*`` methods ↔
      the SPEC §23.2 family table ↔ the mutation battery
      (``tests/test_plansan.py``) ↔ the ``test_fuzz_plansan`` arm,
      both drift directions, plus the ``sanitize.verify`` fault site.
R10   serialization-dependency: code under ``dr_tpu/plan/`` must not
      interpret ``.reads``/``.writes`` footprints itself — every
      aliasing/ordering decision routes through
      ``plan/interference.py`` (the one interference-graph helper),
      so no future pass can hand-roll its own aliasing logic.
====  =====================================================================

Suppressions: ``# drlint: ok[R2] <reason>`` on the finding's line, or on
a dedicated comment line directly above it.  Multiple rules:
``ok[R2,R5]``.  Stacked comment-line waivers above one statement merge.
The reason is REQUIRED — a bare ``ok[Rn]`` is itself a finding (rule
R0).

Scope pragma: ``# drlint: scope=package`` in a file's first lines makes
the package-scoped rules (R5, the R6 module checks) apply to it even
outside ``dr_tpu/`` — fixture twins declare it so a direct CLI scan
judges them exactly as the faked-relpath test scan does.  A path value
(``# drlint: scope=dr_tpu/plan/x.py``) additionally gives the file that
EFFECTIVE relpath for the path-scoped rules (R10) — how the R10
fixture twins opt into the ``dr_tpu/plan/`` discipline from
``tests/drlint_fixtures/``.

Baseline: ``tools/drlint_baseline.json`` holds accepted pre-existing
findings (keyed file::rule::message, line-number free so they survive
drift).  ``--check`` exits non-zero on any non-baselined finding;
``--write-baseline`` records the current findings for burn-down.  A
baseline entry that no longer matches any finding is STALE and fails
the run (a dead suppression could mask a reintroduced bug);
``--prune`` rewrites the baseline down to the entries that still
fire.

Usage::

    python tools/drlint.py --check            # CI gate (make lint)
    python tools/drlint.py --json report.json # machine-readable report
    python tools/drlint.py --rules R4 path.py # one rule, some files

The runtime companion is ``DR_TPU_SANITIZE=1``
(``dr_tpu/utils/sanitize.py``): what these rules prove statically, the
sanitizer asserts dynamically (recompile detection, NaN/Inf at plan
flush, canon-portability of every dispatch key).  docs/SPEC.md §13.
"""

from __future__ import annotations

import argparse
import ast
import fnmatch
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULES = {
    "R0": "malformed suppression (reason required) / unparseable file",
    "R1": "runtime scalar baked into a program builder",
    "R2": "env read outside utils/env or SPEC env-table drift",
    "R3": "fault-site registry drift",
    "R4": "collective under a data-dependent branch",
    "R5": "degradation path outside the fallback registry",
    "R6": "program compilation outside the TappedCache discipline",
    "R7": "plan-optimizer pass registry drift",
    "R8": "kernel-arm registry drift",
    "R9": "plan-item record site without a derived footprint",
    "R10": "footprint interpreted outside plan/interference.py",
}

DEFAULT_ROOTS = ("dr_tpu", "tools", "tests", "bench.py",
                 "__graft_entry__.py")
EXCLUDE_DIRS = {"__pycache__", "drlint_fixtures"}

ENV_VAR_RE = re.compile(r"^_?DR_TPU_[A-Z0-9_]+$")
ENV_HELPERS = {"env_int", "env_pow2", "env_float", "env_str", "env_flag",
               "env_raw"}
COLLECTIVES = {"ppermute", "psum", "psum_scatter", "all_gather",
               "all_to_all", "pshuffle", "shift_left", "shift_right",
               "alltoall"}
#: reductions of runtime data that taint a branch condition (R4)
DATA_REDUCERS = {"item", "any", "all", "sum", "min", "max", "mean",
                 "nonzero", "tolist"}
CACHE_NAME_RE = re.compile(r"^_\w*cache\w*$|^\w*_cache$")

SUPPRESS_RE = re.compile(
    r"#\s*drlint:\s*ok\[(R[0-9]+(?:\s*,\s*R[0-9]+)*)\]\s*(.*)")
#: opts a file outside dr_tpu/ into the package-scoped rules (R5/R6
#: module checks); must appear in the first few lines.  A path value
#: (``scope=dr_tpu/plan/x.py``) also gives the file that EFFECTIVE
#: relpath for the path-scoped rules (R10).
SCOPE_PACKAGE_RE = re.compile(
    r"#\s*drlint:\s*scope=(package\b|[\w./-]+)")


@dataclass
class Finding:
    file: str          # repo-relative path
    line: int
    rule: str
    msg: str
    status: str = "active"      # active | suppressed | baselined
    reason: str = ""            # suppression reason, when suppressed

    @property
    def key(self) -> str:
        return f"{self.file}::{self.rule}::{self.msg}"

    def __str__(self) -> str:
        tag = "" if self.status == "active" else f" [{self.status}]"
        return f"{self.file}:{self.line}: {self.rule}{tag} {self.msg}"


def _dotted(node: ast.AST) -> str:
    """Dotted name of a call target ('jax.jit', 'os.environ.get', …);
    '' when the target is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _const_strs(node: ast.AST) -> List[str]:
    """String constants an expression can evaluate to: a Constant is
    itself; an IfExp contributes both branches (halo fires
    ``"halo.reduce" if kind == "reduce" else "halo.exchange"``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        return _const_strs(node.body) + _const_strs(node.orelse)
    return []


# ---------------------------------------------------------------------------
# suppression handling
# ---------------------------------------------------------------------------

class Suppressions:
    """Per-file map line -> {rule: reason}.  A suppression on a bare
    comment line covers the next non-comment line; stacked comment
    lines merge; reasons are tracked PER RULE, so a reasonless waiver
    for one rule cannot disarm a reasoned waiver for another."""

    def __init__(self, src_lines: List[str], relpath: str,
                 findings: List[Finding]):
        self.by_line: Dict[int, Dict[str, str]] = {}
        pending: Optional[Dict[str, str]] = None
        for i, text in enumerate(src_lines, start=1):
            m = SUPPRESS_RE.search(text)
            stripped = text.strip()
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                reason = m.group(2).strip()
                if not reason:
                    findings.append(Finding(
                        relpath, i, "R0",
                        f"suppression ok[{','.join(sorted(rules))}] "
                        "has no reason — say why the finding is fine"))
                entry = {r: reason for r in rules}
                if stripped.startswith("#"):
                    if pending is None:       # stacked waivers merge
                        pending = {}
                    self._merge(pending, entry)
                else:
                    # an inline-suppressed line still CONSUMES a
                    # pending line-above waiver — it must not leak
                    # onto the next statement
                    if pending is not None:
                        self._merge(
                            self.by_line.setdefault(i, {}), pending)
                        pending = None
                    self._merge(self.by_line.setdefault(i, {}), entry)
                continue
            if pending is not None and stripped and \
                    not stripped.startswith("#"):
                self._merge(self.by_line.setdefault(i, {}), pending)
                pending = None

    @staticmethod
    def _merge(into: Dict[str, str], entry: Dict[str, str]) -> None:
        for rule, reason in entry.items():
            if rule not in into or (not into[rule] and reason):
                into[rule] = reason

    def apply(self, f: Finding) -> None:
        hit = self.by_line.get(f.line)
        if hit and hit.get(f.rule):
            f.status = "suppressed"
            f.reason = hit[f.rule]


# ---------------------------------------------------------------------------
# per-file analysis
# ---------------------------------------------------------------------------

class FileInfo:
    """One parsed file plus the module-level context the rules need."""

    def __init__(self, path: str, relpath: str):
        self.path = path
        self.relpath = relpath
        with open(path, encoding="utf-8") as fh:
            self.src = fh.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=relpath)
        scope = None
        for ln in self.lines[:5]:
            m = SCOPE_PACKAGE_RE.search(ln)
            if m:
                scope = m.group(1)
                break
        #: the relpath the PATH-scoped rules judge the file by: its
        #: real location, unless a scope pragma fakes one (fixtures)
        self.effective = relpath if scope in (None, "package") else scope
        self.in_pkg = (relpath.startswith("dr_tpu/") or
                       self.effective.startswith("dr_tpu/") or
                       scope == "package")
        # parent links for ancestor walks
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        # module context: tapped caches and imported cache names
        self.tapped_caches: Set[str] = set()
        self.dict_caches: Dict[str, int] = {}
        self.imported_caches: Set[str] = set()
        for node in self.tree.body:
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                tgt, val = node.target.id, node.value
            if tgt is None or not CACHE_NAME_RE.match(tgt):
                continue
            if isinstance(val, ast.Call) and \
                    _dotted(val.func).endswith("TappedCache"):
                self.tapped_caches.add(tgt)
            elif isinstance(val, (ast.Dict,)) or (
                    isinstance(val, ast.Call) and
                    _dotted(val.func) == "dict"):
                self.dict_caches[tgt] = node.lineno
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    name = alias.asname or alias.name
                    if CACHE_NAME_RE.match(name):
                        self.imported_caches.add(name)

    def ancestors(self, node: ast.AST):
        cur = self.parent.get(node)
        while cur is not None:
            yield cur
            cur = self.parent.get(cur)


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------

class Linter:
    def __init__(self, files: List[FileInfo], rules: Set[str],
                 full_scan: bool):
        self.files = files
        self.rules = rules
        #: cross-file checks (stale SPEC rows, unfired SITES, chaos
        #: coverage) only make sense over the default whole-repo scan —
        #: a fixture-scoped run must not report the world as stale.
        self.full_scan = full_scan
        self.findings: List[Finding] = []
        self.env_refs: Dict[str, Tuple[str, int]] = {}
        self._fired: Set[str] = set()
        self._sites: Optional[Dict[str, int]] = None

    def emit(self, rule: str, fi: FileInfo, node_or_line, msg: str):
        if rule not in self.rules:
            return
        line = node_or_line if isinstance(node_or_line, int) \
            else getattr(node_or_line, "lineno", 1)
        self.findings.append(Finding(fi.relpath, line, rule, msg))

    def run(self) -> List[Finding]:
        for fi in self.files:
            self.check_file(fi)
        self.check_env_table()
        self.check_fault_registry()
        self.check_plan_opt_registry()
        self.check_kernel_registry()
        self.check_plansan_registry()
        # suppressions apply last (and R0 findings ride along)
        for fi in self.files:
            sup = Suppressions(fi.lines, fi.relpath, self.findings)
            for f in self.findings:
                if f.file == fi.relpath and f.status == "active":
                    sup.apply(f)
        self.findings.sort(key=lambda f: (f.file, f.line, f.rule))
        return self.findings

    # ------------------------------------------------------------- per file
    def check_file(self, fi: FileInfo) -> None:
        is_env_py = fi.relpath == "dr_tpu/utils/env.py"
        if fi.in_pkg and fi.dict_caches and any(
                isinstance(n, ast.Call) and _dotted(n.func) == "jax.jit"
                for n in ast.walk(fi.tree)):
            cname, lineno = next(iter(fi.dict_caches.items()))
            self.emit("R6", fi, lineno,
                      f"program cache {cname!r} is a plain dict — use "
                      "spmd_guard.TappedCache so dispatches ride the "
                      "guard tap")
        check_r10 = ("R10" in self.rules and
                     fi.effective.startswith("dr_tpu/plan/") and
                     os.path.basename(fi.effective) != "interference.py")
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Call):
                self.visit_call(fi, node, is_env_py)
                if fi.in_pkg:
                    self.check_record_site(fi, node)
            elif isinstance(node, ast.Subscript):
                self.visit_subscript(fi, node, is_env_py)
            elif isinstance(node, ast.Compare):
                self.visit_compare(fi, node, is_env_py)
            elif isinstance(node, ast.ExceptHandler):
                self.visit_except(fi, node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.check_builder(fi, node)
            if check_r10 and isinstance(node, ast.Attribute) and \
                    node.attr in ("reads", "writes") and \
                    isinstance(node.ctx, ast.Load):
                self.emit("R10", fi, node,
                          f"footprint attribute .{node.attr} read "
                          "inside dr_tpu/plan/ — every aliasing/"
                          "ordering decision routes through "
                          "plan/interference.py (the one "
                          "interference-graph helper)")

    def note_env(self, var: str, fi: FileInfo, line: int) -> None:
        self.env_refs.setdefault(var, (fi.relpath, line))

    def visit_call(self, fi: FileInfo, node: ast.Call,
                   is_env_py: bool) -> None:
        name = _dotted(node.func)
        short = name.rsplit(".", 1)[-1]
        args0 = _const_strs(node.args[0]) if node.args else []

        # ---- R2: env reads + reference inventory
        if name in ("os.environ.get", "environ.get", "os.getenv",
                    "getenv") and args0:
            for var in args0:
                if ENV_VAR_RE.match(var):
                    self.note_env(var, fi, node.lineno)
                    if not is_env_py:
                        self.emit("R2", fi, node,
                                  f"raw os.environ read of {var!r} — "
                                  "route it through utils/env "
                                  "(env_str/env_int/env_flag/…)")
        elif short in ENV_HELPERS and args0:
            for var in args0:
                if ENV_VAR_RE.match(var):
                    self.note_env(var, fi, node.lineno)
        elif short == "env_override":
            for kw in node.keywords:
                if kw.arg and ENV_VAR_RE.match(kw.arg):
                    self.note_env(kw.arg, fi, node.lineno)
        elif name.endswith("environ.pop") or \
                name.endswith("environ.setdefault") or \
                short in ("setenv", "delenv"):
            for var in args0:
                if ENV_VAR_RE.match(var):
                    self.note_env(var, fi, node.lineno)

        # ---- R3: fire/inject site names
        if short in ("fire", "inject", "injected") and (
                name.split(".")[0] in ("faults", "_faults") or
                name in ("fire", "inject", "injected")):
            self.check_fault_call(fi, node, short)

        # ---- R4: collectives under data-dependent control flow
        if short in COLLECTIVES:
            self.check_collective(fi, node, short)

        # ---- R5: bare warnings.warn in package code
        if name == "warnings.warn" and fi.in_pkg and \
                not fi.relpath.startswith(
                    ("dr_tpu/utils/fallback", "dr_tpu/utils/faults",
                     "dr_tpu/utils/env")):
            self.emit("R5", fi, node,
                      "bare warnings.warn in package code — degradations "
                      "go through utils.fallback.warn_fallback (the "
                      "chaos-countable registry)")

        # ---- R6: jit discipline
        if name == "jax.jit" and fi.in_pkg and not (
                fi.tapped_caches or fi.imported_caches):
            self.emit("R6", fi, node,
                      "jax.jit in a module with no TappedCache program "
                      "cache — compiles are off the spmd_guard "
                      "dispatch tap")
        if isinstance(node.func, ast.Call) and \
                _dotted(node.func.func) == "jax.jit":
            self.emit("R6", fi, node,
                      "immediately-invoked jax.jit(f)(…) compiles on "
                      "every call — cache the program")

    def visit_subscript(self, fi: FileInfo, node: ast.Subscript,
                        is_env_py: bool) -> None:
        if not isinstance(node.ctx, ast.Load):
            return  # writes (sweep overrides) are allowed raw
        if _dotted(node.value) not in ("os.environ", "environ"):
            return
        for var in _const_strs(node.slice):
            if ENV_VAR_RE.match(var):
                self.note_env(var, fi, node.lineno)
                if not is_env_py:
                    self.emit("R2", fi, node,
                              f"raw os.environ[{var!r}] read — route "
                              "it through utils/env")

    def visit_compare(self, fi: FileInfo, node: ast.Compare,
                      is_env_py: bool) -> None:
        """R2: a membership test (``"DR_TPU_X" in os.environ``) is a
        read too — the None-vs-set shape ``env_raw`` exists for."""
        if len(node.ops) != 1 or \
                not isinstance(node.ops[0], (ast.In, ast.NotIn)):
            return
        if _dotted(node.comparators[0]) not in ("os.environ", "environ"):
            return
        for var in _const_strs(node.left):
            if ENV_VAR_RE.match(var):
                self.note_env(var, fi, node.lineno)
                if not is_env_py:
                    self.emit("R2", fi, node,
                              f"raw membership test of {var!r} in "
                              "os.environ — use utils/env "
                              "(env_raw(...) is not None)")

    def visit_except(self, fi: FileInfo, node: ast.ExceptHandler):
        if not fi.in_pkg:
            return
        broad = node.type is None or (
            isinstance(node.type, ast.Name) and
            node.type.id in ("Exception", "BaseException"))
        if broad and len(node.body) == 1 and \
                isinstance(node.body[0], ast.Pass):
            self.emit("R5", fi, node,
                      "broad except swallowed with pass — a silent "
                      "degradation path must warn_fallback (or narrow "
                      "the catch)")

    # --------------------------------------------------------------- R3
    def check_fault_call(self, fi: FileInfo, node: ast.Call,
                         kind: str) -> None:
        sites = self.fault_sites()
        if sites is None or not node.args:
            return
        for site in _const_strs(node.args[0]):
            if any(ch in site for ch in "*?["):
                if not any(fnmatch.fnmatchcase(s, site) for s in sites):
                    self.emit("R3", fi, node,
                              f"fault-site glob {site!r} matches no "
                              "faults.SITES entry")
            elif site not in sites:
                self.emit("R3", fi, node,
                          f"fault site {site!r} is not registered in "
                          "faults.SITES — a chaos sweep will never "
                          "reach it")
            elif kind == "fire" and fi.relpath.startswith("dr_tpu/"):
                # only PACKAGE fires count toward registry coverage:
                # a fire() in a test must not keep a dead SITES row
                # looking reachable
                self._fired.add(site)

    def fault_sites(self) -> Optional[Dict[str, int]]:
        """SITES names -> line, parsed from utils/faults.py (AST, no
        package import — the linter must run without jax)."""
        if self._sites is not None:
            return self._sites
        path = os.path.join(REPO, "dr_tpu", "utils", "faults.py")
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        out: Dict[str, int] = {}
        for node in tree.body:
            tgt = node.target if isinstance(node, ast.AnnAssign) else (
                node.targets[0] if isinstance(node, ast.Assign) and
                node.targets else None)
            if isinstance(tgt, ast.Name) and tgt.id == "SITES" and \
                    isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant):
                        out[k.value] = k.lineno
        self._sites = out
        return out

    def check_fault_registry(self) -> None:
        """Whole-repo R3 closure: every SITES entry fired somewhere,
        and the chaos battery sweeps the registry."""
        if not self.full_scan or "R3" not in self.rules:
            return
        sites = self.fault_sites() or {}
        faults_fi = next((f for f in self.files
                          if f.relpath == "dr_tpu/utils/faults.py"), None)
        for site, line in sites.items():
            if site not in self._fired and faults_fi is not None:
                self.emit("R3", faults_fi, line,
                          f"SITES entry {site!r} is never fired in "
                          "dr_tpu/ — dead registry row")
        chaos = os.path.join(REPO, "tests", "test_chaos.py")
        chaos_fi = next((f for f in self.files
                         if f.relpath == "tests/test_chaos.py"), None)
        if os.path.exists(chaos) and chaos_fi is not None:
            src = chaos_fi.src
            if not re.search(r"\bSITES\b|\bsites\(\)", src):
                missing = [s for s in sites if s not in src]
                if missing:
                    self.emit("R3", chaos_fi, 1,
                              "test_chaos does not sweep faults.SITES "
                              f"and never names: {', '.join(missing)}")

    # --------------------------------------------------------------- R7
    def check_plan_opt_registry(self) -> None:
        """Whole-repo R7 closure: every ``PASSES`` entry in
        dr_tpu/plan/opt.py has a docs/SPEC.md §21.2 pass-table row and
        bit-identity fuzz coverage, and every §21.2 row names a
        registered pass — the R3 fault-registry discipline applied to
        the optimizer's pass pipeline."""
        if not self.full_scan or "R7" not in self.rules:
            return
        opt_fi = next((f for f in self.files
                       if f.relpath == "dr_tpu/plan/opt.py"), None)
        if opt_fi is None:
            return
        passes: Dict[str, int] = {}
        for node in opt_fi.tree.body:
            tgt = node.targets[0] if isinstance(node, ast.Assign) \
                and node.targets else None
            if isinstance(tgt, ast.Name) and tgt.id == "PASSES" and \
                    isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Tuple) and elt.elts and \
                            isinstance(elt.elts[0], ast.Constant):
                        passes[elt.elts[0].value] = elt.lineno
        if not passes:
            self.emit("R7", opt_fi, 1,
                      "no PASSES registry found — the §21 pass "
                      "pipeline must register every pass")
            return
        # SPEC §21.2 pass-table rows (first backticked cell of each
        # table row inside the subsection)
        spec_rows: Dict[str, int] = {}
        spec_path = os.path.join(REPO, "docs", "SPEC.md")
        if os.path.exists(spec_path):
            in_sect = False
            with open(spec_path, encoding="utf-8") as fh:
                for i, text in enumerate(fh.read().splitlines(), 1):
                    if re.match(r"###\s*21\.2\b", text):
                        in_sect = True
                        continue
                    if in_sect and re.match(r"##", text):
                        break
                    if in_sect:
                        m = re.match(r"\|\s*`([a-z][a-z_]*)`", text)
                        if m:
                            spec_rows[m.group(1)] = i
        for name, line in sorted(passes.items()):
            if name not in spec_rows:
                self.emit("R7", opt_fi, line,
                          f"optimizer pass {name!r} has no docs/"
                          "SPEC.md §21.2 pass-table row — document "
                          "its semantics and bit-identity argument")
        for name, line in sorted(spec_rows.items()):
            if name not in passes:
                self.findings.append(Finding(
                    "docs/SPEC.md", line, "R7",
                    f"§21.2 pass-table row {name!r} matches no "
                    "registered pass in plan/opt.py — stale "
                    "documentation"))
        # bit-identity fuzz coverage: the arm sweeps the registry
        # (PASS_NAMES) or names every pass explicitly
        fuzz = next((f for f in self.files
                     if f.relpath == "tests/test_fuzz.py"), None)
        if fuzz is not None:
            if "def test_fuzz_plan_opt" not in fuzz.src:
                self.emit("R7", fuzz, 1,
                          "tests/test_fuzz.py has no "
                          "test_fuzz_plan_opt — every optimizer pass "
                          "needs the bit-identity fuzz arm")
            elif not re.search(r"\bPASS_NAMES\b", fuzz.src):
                missing = [p for p in sorted(passes)
                           if p not in fuzz.src]
                if missing:
                    self.emit("R7", fuzz, 1,
                              "test_fuzz_plan_opt does not sweep "
                              "plan_opt.PASS_NAMES and never names: "
                              f"{', '.join(missing)}")

    # --------------------------------------------------------------- R8
    def check_kernel_registry(self) -> None:
        """Whole-repo R8 closure: every ``ARMS`` row in
        dr_tpu/ops/kernels.py declares an env override the inventory
        actually reads, a kernel module that exists and exports
        ``supported()``, a portable-fallback declaration, a fault site
        registered in faults.SITES, and a docs/SPEC.md §22.1 arm-table
        row (both drift directions) — plus pallas-vs-xla parity fuzz
        coverage.  The R3/R7 registry discipline applied to the
        on-chip kernel tier."""
        if not self.full_scan or "R8" not in self.rules:
            return
        k_fi = next((f for f in self.files
                     if f.relpath == "dr_tpu/ops/kernels.py"), None)
        if k_fi is None:
            return
        arms: Dict[str, Tuple[int, str, str, str, str]] = {}
        for node in k_fi.tree.body:
            tgt = node.targets[0] if isinstance(node, ast.Assign) \
                and node.targets else None
            if isinstance(tgt, ast.Name) and tgt.id == "ARMS" and \
                    isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Tuple) and \
                            len(elt.elts) == 5 and all(
                                isinstance(e, ast.Constant)
                                for e in elt.elts):
                        arms[elt.elts[0].value] = (
                            elt.lineno, elt.elts[1].value,
                            elt.elts[2].value, elt.elts[3].value,
                            elt.elts[4].value)
        if not arms:
            self.emit("R8", k_fi, 1,
                      "no ARMS registry found — the §22 kernel tier "
                      "must register every arm as a literal 5-tuple "
                      "(arm, env, module, xla fallback, fault site)")
            return
        sites = self.fault_sites() or {}
        for name, (line, env, module, fallback, site) in \
                sorted(arms.items()):
            if not ENV_VAR_RE.match(env) or env not in self.env_refs:
                self.emit("R8", k_fi, line,
                          f"kernel arm {name!r} override {env!r} is "
                          "never read through the env registry — "
                          "register a literal env_str read")
            mod_fi = next((f for f in self.files
                           if f.relpath == f"dr_tpu/ops/{module}.py"),
                          None)
            if mod_fi is None:
                self.emit("R8", k_fi, line,
                          f"kernel arm {name!r} names module "
                          f"{module!r} but dr_tpu/ops/{module}.py "
                          "does not exist")
            elif not re.search(r"^def supported\(", mod_fi.src,
                               re.MULTILINE):
                self.emit("R8", mod_fi, 1,
                          f"kernel module {module!r} exports no "
                          "supported() availability probe — the arm "
                          "cannot degrade gracefully without one")
            if not fallback:
                self.emit("R8", k_fi, line,
                          f"kernel arm {name!r} declares no portable "
                          "XLA fallback — kernels are an optimization "
                          "tier, the portable lowering is the "
                          "contract")
            if sites and site not in sites:
                self.emit("R8", k_fi, line,
                          f"kernel arm {name!r} fault site {site!r} "
                          "is not registered in faults.SITES")
        # SPEC §22.1 arm-table rows (first backticked cell), both
        # drift directions — the R7 pass-table pattern
        spec_rows: Dict[str, int] = {}
        spec_path = os.path.join(REPO, "docs", "SPEC.md")
        if os.path.exists(spec_path):
            in_sect = False
            with open(spec_path, encoding="utf-8") as fh:
                for i, text in enumerate(fh.read().splitlines(), 1):
                    if re.match(r"###\s*22\.1\b", text):
                        in_sect = True
                        continue
                    if in_sect and re.match(r"##", text):
                        break
                    if in_sect:
                        m = re.match(r"\|\s*`([a-z][a-z_]*)`", text)
                        if m:
                            spec_rows[m.group(1)] = i
        for name, (line, *_rest) in sorted(arms.items()):
            if name not in spec_rows:
                self.emit("R8", k_fi, line,
                          f"kernel arm {name!r} has no docs/SPEC.md "
                          "§22.1 arm-table row — document its scope, "
                          "eligibility, and bit-identity contract")
        for name, line in sorted(spec_rows.items()):
            if name not in arms:
                self.findings.append(Finding(
                    "docs/SPEC.md", line, "R8",
                    f"§22.1 arm-table row {name!r} matches no "
                    "registered arm in ops/kernels.py — stale "
                    "documentation"))
        # parity fuzz coverage: the arm battery sweeps the registry
        # (ARM_NAMES) or names every arm explicitly
        fuzz = next((f for f in self.files
                     if f.relpath == "tests/test_fuzz.py"), None)
        if fuzz is not None:
            if "def test_fuzz_kernel_parity" not in fuzz.src:
                self.emit("R8", fuzz, 1,
                          "tests/test_fuzz.py has no "
                          "test_fuzz_kernel_parity — every kernel arm "
                          "needs the pallas-vs-xla parity fuzz arm")
            elif not re.search(r"\bARM_NAMES\b", fuzz.src):
                missing = [a for a in sorted(arms)
                           if a not in fuzz.src]
                if missing:
                    self.emit("R8", fuzz, 1,
                              "test_fuzz_kernel_parity does not sweep "
                              "kernels.ARM_NAMES and never names: "
                              f"{', '.join(missing)}")

    # --------------------------------------------------------------- R9
    #: interference helpers whose results ARE footprints — a Name
    #: chased onto one of these calls is derived by construction
    _R9_HELPERS = {"remap"}

    def check_record_site(self, fi: FileInfo, node: ast.Call) -> None:
        """R9 per-site half: a ``record_opaque(…)`` call must provide
        BOTH ``reads`` and ``writes`` (an explicit ``None`` is the
        documented barrier opt-in); a ``_FusedOp(…)`` construction
        must declare at least one of ``reads=``/``writes=`` and every
        slot position in them must be DERIVED from the run's actual
        operands (``run.slot(…)`` results chased through local
        assignments — the R1 taint machinery pointed at footprints)."""
        if "R9" not in self.rules:
            return
        name = _dotted(node.func)
        short = name.rsplit(".", 1)[-1]
        if short == "record_opaque":
            provided = {kw.arg for kw in node.keywords
                        if kw.arg in ("reads", "writes")}
            if len(node.args) >= 3:
                provided.add("reads")
            if len(node.args) >= 4:
                provided.add("writes")
            missing = sorted({"reads", "writes"} - provided)
            if missing:
                self.emit("R9", fi, node,
                          "record_opaque without "
                          f"{' / '.join(missing)} — declare the "
                          "containers the thunk touches, or opt into "
                          "the barrier explicitly (reads=None/"
                          "writes=None)")
            return
        if short != "_FusedOp":
            return
        kws = {kw.arg: kw.value for kw in node.keywords}
        if "reads" not in kws and "writes" not in kws:
            self.emit("R9", fi, node,
                      "_FusedOp constructed with no reads=/writes= "
                      "footprint — every §21 pass and the flush-cliff "
                      "skip would treat the op as touching nothing")
            return
        names = self._r9_names(fi, node)
        rd = kws.get("reads")
        if rd is not None and not self._r9_tuple(
                rd, names, set(), self._r9_slot, 0):
            self.emit("R9", fi, rd,
                      "_FusedOp reads= is not derived from the run's "
                      "operands — every slot position must chase to a "
                      ".slot(…) result (or an interference helper)")
        wr = kws.get("writes")
        if wr is not None and not self._r9_tuple(
                wr, names, set(), self._r9_write_elem, 0):
            self.emit("R9", fi, wr,
                      "_FusedOp writes= is not derived from the run's "
                      "operands — every window's slot must chase to a "
                      ".slot(…) result (or an interference helper)")

    def _r9_names(self, fi: FileInfo,
                  node: ast.AST) -> Dict[str, List[ast.AST]]:
        """Name -> RHS expressions bound in the call's enclosing
        function (tuple-unpacking spreads a tuple RHS elementwise; a
        non-tuple RHS maps onto every target — ``a, b = helper()``
        chases both names to the call)."""
        fn = None
        for anc in fi.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                fn = anc
                break
        if fn is None:
            fn = fi.tree
        out: Dict[str, List[ast.AST]] = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, []).append(n.value)
                    elif isinstance(t, ast.Tuple):
                        vals = (n.value.elts if isinstance(
                            n.value, ast.Tuple) and
                            len(n.value.elts) == len(t.elts)
                            else [n.value] * len(t.elts))
                        for te, ve in zip(t.elts, vals):
                            if isinstance(te, ast.Name):
                                out.setdefault(te.id, []).append(ve)
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name):
                out.setdefault(n.target.id, []).append(n.value)
        return out

    def _r9_slot(self, e: ast.AST, names, assumed,
                 depth: int) -> bool:
        """One slot POSITION: a ``.slot(…)`` result, a literal, or a
        name that chases to one through the local assignment map."""
        if depth > 8:
            return False
        if isinstance(e, ast.Constant):
            return True              # literal slot / None (absent pair)
        if isinstance(e, ast.Call):
            d = _dotted(e.func)
            return d == "slot" or d.endswith(".slot")
        if isinstance(e, ast.IfExp):
            return (self._r9_slot(e.body, names, assumed, depth + 1) and
                    self._r9_slot(e.orelse, names, assumed, depth + 1))
        if isinstance(e, ast.Name):
            if e.id in assumed:
                return True
            rhss = names.get(e.id)
            return bool(rhss) and all(
                self._r9_slot(r, names, assumed, depth + 1)
                for r in rhss)
        return False

    def _r9_write_elem(self, e: ast.AST, names, assumed,
                       depth: int) -> bool:
        """One writes= element: a ``(slot, …)`` window tuple whose
        FIRST position is slot-derived (extents are the fuzz
        battery's problem, not the lint's)."""
        if depth > 8:
            return False
        if isinstance(e, ast.Tuple):
            return bool(e.elts) and self._r9_slot(
                e.elts[0], names, assumed, depth + 1)
        if isinstance(e, ast.IfExp):
            return (self._r9_write_elem(e.body, names, assumed,
                                        depth + 1) and
                    self._r9_write_elem(e.orelse, names, assumed,
                                        depth + 1))
        if isinstance(e, ast.Name):
            if e.id in assumed:
                return True
            rhss = names.get(e.id)
            return bool(rhss) and all(
                self._r9_write_elem(r, names, assumed, depth + 1)
                for r in rhss)
        return False

    def _r9_tuple(self, e: ast.AST, names, assumed, elem,
                  depth: int) -> bool:
        """A whole footprint expression: a tuple of ``elem``-valid
        entries, chased through names, concatenation, conditional
        branches, ``tuple(genexp)`` comprehension (targets assumed
        derived), or an interference-helper call."""
        if depth > 8:
            return False
        if isinstance(e, ast.Constant):
            return e.value is None   # explicit barrier / empty default
        if isinstance(e, ast.Tuple):
            return all(elem(x, names, assumed, depth + 1)
                       for x in e.elts)
        if isinstance(e, ast.IfExp):
            return (self._r9_tuple(e.body, names, assumed, elem,
                                   depth + 1) and
                    self._r9_tuple(e.orelse, names, assumed, elem,
                                   depth + 1))
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
            return (self._r9_tuple(e.left, names, assumed, elem,
                                   depth + 1) and
                    self._r9_tuple(e.right, names, assumed, elem,
                                   depth + 1))
        if isinstance(e, ast.Name):
            if e.id in assumed:
                return True
            rhss = names.get(e.id)
            return bool(rhss) and all(
                self._r9_tuple(r, names, assumed, elem, depth + 1)
                for r in rhss)
        if isinstance(e, ast.Call):
            d = _dotted(e.func)
            short = d.rsplit(".", 1)[-1]
            if short in self._R9_HELPERS:
                return True
            if short == "tuple" and e.args and isinstance(
                    e.args[0], (ast.GeneratorExp, ast.ListComp)):
                g = e.args[0]
                assumed2 = set(assumed)
                for gen in g.generators:
                    for t in ast.walk(gen.target):
                        if isinstance(t, ast.Name):
                            assumed2.add(t.id)
                return elem(g.elt, names, assumed2, depth + 1)
        return False

    def check_plansan_registry(self) -> None:
        """Whole-repo R9 closure: the ``plansan.FAMILIES`` registry ↔
        the ``Plan.record_*`` methods ↔ the SPEC §23.2 family table ↔
        the mutation battery ↔ the fuzz arm, both drift directions,
        plus the ``sanitize.verify`` fault site — the R3/R7/R8
        registry discipline applied to footprint kinds."""
        if not self.full_scan or "R9" not in self.rules:
            return
        ps_fi = next((f for f in self.files
                      if f.relpath == "dr_tpu/plan/plansan.py"), None)
        if ps_fi is None:
            return
        fams: Dict[str, Tuple[int, str]] = {}
        for node in ps_fi.tree.body:
            tgt = node.targets[0] if isinstance(node, ast.Assign) \
                and node.targets else None
            if isinstance(tgt, ast.Name) and tgt.id == "FAMILIES" and \
                    isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Tuple) and \
                            len(elt.elts) == 2 and all(
                                isinstance(e, ast.Constant)
                                for e in elt.elts):
                        fams[elt.elts[0].value] = (
                            elt.lineno, elt.elts[1].value)
        if not fams:
            self.emit("R9", ps_fi, 1,
                      "no FAMILIES registry found — plansan must "
                      "register every footprint kind as a literal "
                      "(family, record_method) pair")
            return
        # families ↔ Plan.record_* methods, both directions
        plan_fi = next((f for f in self.files
                        if f.relpath == "dr_tpu/plan/__init__.py"),
                       None)
        if plan_fi is not None:
            methods: Dict[str, int] = {}
            for m in re.finditer(r"^\s+def (record_[a-z_]+)\(",
                                 plan_fi.src, re.MULTILINE):
                methods[m.group(1)] = \
                    plan_fi.src[:m.start()].count("\n") + 1
            for fam, (line, meth) in sorted(fams.items()):
                if meth not in methods:
                    self.emit("R9", ps_fi, line,
                              f"family {fam!r} names {meth!r} but "
                              "plan/__init__.py defines no such "
                              "record method")
            for meth, line in sorted(methods.items()):
                if meth not in {m for _l, m in fams.values()}:
                    self.emit("R9", plan_fi, line,
                              f"record method {meth!r} is missing "
                              "from plansan.FAMILIES — unregistered "
                              "footprint kinds escape the mutation "
                              "battery and the fuzz arm")
        # SPEC §23.2 family-table rows, both directions
        spec_rows: Dict[str, int] = {}
        spec_path = os.path.join(REPO, "docs", "SPEC.md")
        if os.path.exists(spec_path):
            in_sect = False
            with open(spec_path, encoding="utf-8") as fh:
                for i, text in enumerate(fh.read().splitlines(), 1):
                    if re.match(r"###\s*23\.2\b", text):
                        in_sect = True
                        continue
                    if in_sect and re.match(r"##", text):
                        break
                    if in_sect:
                        m = re.match(r"\|\s*`([a-z][a-z_]*)`", text)
                        if m:
                            spec_rows[m.group(1)] = i
        for fam, (line, _meth) in sorted(fams.items()):
            if fam not in spec_rows:
                self.emit("R9", ps_fi, line,
                          f"footprint family {fam!r} has no docs/"
                          "SPEC.md §23.2 family-table row — document "
                          "its declared footprint shape")
        for fam, line in sorted(spec_rows.items()):
            if fam not in fams:
                self.findings.append(Finding(
                    "docs/SPEC.md", line, "R9",
                    f"§23.2 family-table row {fam!r} matches no "
                    "plansan.FAMILIES entry — stale documentation"))
        # mutation battery sweeps the registry
        bat = next((f for f in self.files
                    if f.relpath == "tests/test_plansan.py"), None)
        if bat is None:
            self.emit("R9", ps_fi, 1,
                      "tests/test_plansan.py does not exist — every "
                      "footprint family needs a seeded "
                      "under-declaration the shadow verifier catches")
        elif not re.search(r"\bFAMILY_NAMES\b", bat.src):
            missing = [f for f in sorted(fams) if f not in bat.src]
            if missing:
                self.emit("R9", bat, 1,
                          "test_plansan does not sweep "
                          "plansan.FAMILY_NAMES and never names: "
                          f"{', '.join(missing)}")
        # oracle fuzz arm exists
        fuzz = next((f for f in self.files
                     if f.relpath == "tests/test_fuzz.py"), None)
        if fuzz is not None and \
                "def test_fuzz_plansan" not in fuzz.src:
            self.emit("R9", fuzz, 1,
                      "tests/test_fuzz.py has no test_fuzz_plansan — "
                      "the serializability oracle needs the random-"
                      "plan random-pass-subset fuzz arm")
        # the runtime verifier's fault site is registered
        sites = self.fault_sites() or {}
        if sites and "sanitize.verify" not in sites:
            self.emit("R9", ps_fi, 1,
                      "fault site 'sanitize.verify' is not registered "
                      "in faults.SITES — the verifier's failure path "
                      "is outside the chaos sweep")

    # --------------------------------------------------------------- R4
    def check_collective(self, fi: FileInfo, node: ast.Call,
                         short: str) -> None:
        for anc in fi.ancestors(node):
            test = None
            if isinstance(anc, (ast.If, ast.While, ast.IfExp)):
                test = anc.test
            elif isinstance(anc, ast.For):
                test = anc.iter
            if test is None or not self.data_tainted(test):
                continue
            what = "loop over" if isinstance(anc, ast.For) else "branch on"
            self.emit("R4", fi, node,
                      f"collective {short!r} under a data-dependent "
                      f"{what} runtime values (line {anc.lineno}) — "
                      "ranks can diverge in dispatch order; hoist the "
                      "decision to mesh-static state (the static "
                      "complement of spmd_guard.first_divergence)")
            return  # one finding per call is enough

    @staticmethod
    def data_tainted(expr: ast.AST) -> bool:
        """A branch condition is data-tainted when it READS runtime
        array contents: subscripts (except static ``.shape[i]``),
        ``.item()``-family reductions, or np/jnp reductions."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Subscript):
                v = n.value
                if not (isinstance(v, ast.Attribute) and
                        v.attr == "shape"):
                    return True
            elif isinstance(n, ast.Call):
                d = _dotted(n.func)
                short = d.rsplit(".", 1)[-1]
                if short in DATA_REDUCERS:
                    return True
                if d.startswith(("np.", "jnp.", "numpy.",
                                 "jax.numpy.")):
                    return True
        return False

    # --------------------------------------------------------------- R1
    def check_builder(self, fi: FileInfo, fn: ast.FunctionDef) -> None:
        """R1 over one program-builder function (one that stores into a
        ``*cache*`` or returns ``jax.jit(…)``)."""
        if "R1" not in self.rules:
            return
        cache_stores = []
        returns_jit = False
        for n in ast.walk(fn):
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.ctx, ast.Store) and \
                    CACHE_NAME_RE.match(_dotted(n.value) or ""):
                cache_stores.append(n)
            elif isinstance(n, ast.Return) and \
                    isinstance(n.value, ast.Call) and \
                    _dotted(n.value.func) == "jax.jit":
                returns_jit = True
        if not cache_stores and not returns_jit:
            return

        # taint: names bound to runtime-scalar pulls in THIS function
        tainted: Dict[str, int] = {}
        nested = [n for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.Lambda))
                  and n is not fn]

        def scalar_pull(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if isinstance(n, ast.Call):
                    d = _dotted(n.func)
                    if d.endswith(".item"):
                        return True
                    if d == "float" and n.args and isinstance(
                            n.args[0], (ast.Subscript, ast.Attribute)):
                        return True
            return False

        in_nested = set()
        for nf in nested:
            in_nested.update(ast.walk(nf))
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and n not in in_nested and \
                    scalar_pull(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        tainted[t.id] = n.lineno

        # key expressions: RHS of assignments to the names used as the
        # cache-store index
        key_names = set()
        for st in cache_stores:
            for n in ast.walk(st.slice):
                if isinstance(n, ast.Name):
                    key_names.add(n.id)
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Assign) and n not in in_nested):
                continue
            is_key = any(isinstance(t, ast.Name) and t.id in key_names
                         for t in n.targets)
            if not is_key:
                continue
            if scalar_pull(n.value):
                self.emit("R1", fi, n,
                          "runtime scalar (.item()/float(…)) keyed BY "
                          "VALUE into a program cache — every new value "
                          "recompiles; pass it as a traced operand "
                          "(_traced_op_key/BoundOp)")
                continue
            for sub in ast.walk(n.value):
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    self.emit("R1", fi, n,
                              f"runtime scalar {sub.id!r} (pulled at "
                              f"line {tainted[sub.id]}) keyed BY VALUE "
                              "into a program cache — recompile storm; "
                              "ride a traced operand instead")
                    break
                if isinstance(sub, ast.JoinedStr) and any(
                        isinstance(v, ast.FormattedValue)
                        for v in sub.values):
                    self.emit("R1", fi, n,
                              "f-string interpolation in a program "
                              "cache key — key on structure, trace "
                              "values (_traced_op_key)")
                    break

        # closure capture of a tainted scalar inside the jitted body
        for nf in nested:
            params = {a.arg for a in nf.args.args}
            for n in ast.walk(nf):
                if isinstance(n, ast.Name) and n.id in tainted and \
                        n.id not in params:
                    self.emit("R1", fi, n,
                              f"runtime scalar {n.id!r} (pulled at line "
                              f"{tainted[n.id]}) closed over by the "
                              "program body — it bakes into the "
                              "compiled program; pass it as a traced "
                              "operand")
                    break

    # --------------------------------------------------------------- R2b
    def check_env_table(self) -> None:
        """SPEC.md env-table drift, both directions."""
        if "R2" not in self.rules:
            return
        spec_path = os.path.join(REPO, "docs", "SPEC.md")
        if not os.path.exists(spec_path):
            return
        with open(spec_path, encoding="utf-8") as fh:
            spec_lines = fh.read().splitlines()
        table: Dict[str, int] = {}
        for i, text in enumerate(spec_lines, start=1):
            m = re.match(r"\|\s*`(_?DR_TPU_[A-Z0-9_]+)`", text)
            if m:
                table[m.group(1)] = i
        for var, (relpath, line) in sorted(self.env_refs.items()):
            if var.startswith("_DR_TPU_"):
                continue  # process-internal relay markers: §13 exempts
            if var not in table:
                self.findings.append(Finding(
                    relpath, line, "R2",
                    f"{var} has no row in the docs/SPEC.md §13 env "
                    "table — document it"))
        if not self.full_scan:
            return
        # shell/tooling refs count for the reverse (stale-row) check
        shell_refs: Set[str] = set()
        for root, dirs, names in os.walk(os.path.join(REPO, "tools")):
            for nm in names:
                if nm.endswith(".sh"):
                    with open(os.path.join(root, nm),
                              encoding="utf-8", errors="replace") as fh:
                        shell_refs.update(re.findall(
                            r"_?DR_TPU_[A-Z0-9_]+", fh.read()))
        for var, line in sorted(table.items()):
            if var not in self.env_refs and var not in shell_refs:
                self.findings.append(Finding(
                    "docs/SPEC.md", line, "R2",
                    f"env-table row {var} matches no reference in the "
                    "code — stale documentation"))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def collect_files(paths: List[str]) -> Tuple[List[FileInfo],
                                             List[Finding]]:
    """Parse the scan set.  An unparseable file is returned as an
    ACTIVE finding, never silently dropped — a CI gate that skips a
    broken file would report the world clean while scanning none of
    it."""
    out: List[FileInfo] = []
    errors: List[Finding] = []
    seen: Set[str] = set()

    def add(p: str) -> None:
        ap = os.path.abspath(p)
        if ap in seen or not ap.endswith(".py"):
            return
        seen.add(ap)
        rel = os.path.relpath(ap, REPO).replace(os.sep, "/")
        try:
            out.append(FileInfo(ap, rel))
        except SyntaxError as e:
            errors.append(Finding(
                rel, e.lineno or 1, "R0",
                f"cannot parse file ({e.msg}) — the scan is skipping "
                "it entirely"))

    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs if d not in EXCLUDE_DIRS]
                for nm in sorted(names):
                    add(os.path.join(root, nm))
        else:
            add(p)
    return out, errors


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="dr_tpu static invariant checker (docs/SPEC.md §13)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the repo)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: exit non-zero on non-baselined "
                    "findings (this is also the default behavior)")
    ap.add_argument("--rules", default=",".join(sorted(RULES)),
                    help="comma-separated rule subset, e.g. R2,R4")
    ap.add_argument("--json", metavar="PATH",
                    help="write the machine-readable report ( - = stdout)")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "tools",
                                         "drlint_baseline.json"))
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current findings into the baseline")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file (report everything)")
    ap.add_argument("--prune", action="store_true",
                    help="rewrite the baseline down to the entries "
                    "that still fire (stale suppressions otherwise "
                    "FAIL the run)")
    args = ap.parse_args(argv)

    full_scan = not args.paths
    roots = args.paths or [os.path.join(REPO, r) for r in DEFAULT_ROOTS]
    rules = {r.strip().upper() for r in args.rules.split(",")} | {"R0"}
    unknown = rules - set(RULES)
    if unknown:
        ap.error(f"unknown rules: {', '.join(sorted(unknown))}")

    files, parse_errors = collect_files(roots)
    findings = Linter(files, rules, full_scan).run()
    findings.extend(parse_errors)

    baseline: Dict[str, int] = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        with open(args.baseline, encoding="utf-8") as fh:
            baseline = json.load(fh).get("findings", {})
    budget = dict(baseline)
    for f in findings:
        if f.status == "active" and budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            f.status = "baselined"

    active = [f for f in findings if f.status == "active"]
    if args.write_baseline:
        counts: Dict[str, int] = {}
        for f in findings:
            if f.status in ("active", "baselined"):
                counts[f.key] = counts.get(f.key, 0) + 1
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump({"findings": counts}, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"drlint: baseline written — {sum(counts.values())} "
              f"finding(s) in {args.baseline}")
        return 0

    # with the JSON report on stdout, the human-readable text moves to
    # stderr so `--json -` stays machine-parseable
    out = sys.stderr if args.json == "-" else sys.stdout
    for f in findings:
        if f.status != "active":
            continue
        print(f, file=out)
    n_sup = sum(1 for f in findings if f.status == "suppressed")
    n_base = sum(1 for f in findings if f.status == "baselined")
    stale = {k: v for k, v in budget.items() if v > 0}
    summary = (f"drlint: {len(active)} finding(s) "
               f"({n_base} baselined, {n_sup} suppressed) over "
               f"{len(files)} file(s)")
    print(summary, file=out)
    stale_fail = False
    if stale and args.prune:
        # rewrite the baseline down to what still fires
        kept = {k: v - stale.get(k, 0) for k, v in baseline.items()}
        kept = {k: v for k, v in kept.items() if v > 0}
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump({"findings": kept}, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"drlint: pruned {sum(stale.values())} stale baseline "
              f"entr(ies); {sum(kept.values())} remain in "
              f"{args.baseline}", file=out)
    elif stale and full_scan:
        # a suppression matching no finding could silently re-admit
        # the bug it once excused — stale entries fail the gate
        stale_fail = True
        for k in sorted(stale):
            print(f"drlint: STALE baseline entry ({stale[k]}x): {k}",
                  file=out)
        print(f"drlint: {sum(stale.values())} stale baseline "
              "entr(ies) no longer match any finding — run --prune "
              "(or --write-baseline) to burn them down", file=out)
    elif stale:
        # a partial scan can't tell dead from out-of-scope — note only
        print(f"drlint: note — {sum(stale.values())} baseline "
              "entr(ies) did not fire in this partial scan", file=out)

    if args.json:
        report = {
            "summary": {"active": len(active), "baselined": n_base,
                        "suppressed": n_sup, "files": len(files),
                        "rules": sorted(rules - {"R0"}),
                        "stale_baseline": stale},
            "findings": [vars(f) for f in findings],
        }
        text = json.dumps(report, indent=1, sort_keys=True) + "\n"
        if args.json == "-":
            sys.stdout.write(text)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(text)
    return 1 if (active or stale_fail) else 0


if __name__ == "__main__":
    sys.exit(main())
