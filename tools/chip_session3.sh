#!/bin/bash
# Round-4 on-chip session: the ROUND3_NOTES queue in priority order.
# Discipline (docs/ROUND3_NOTES.md, memory: the claim path wedges after
# some number of claims per VM session and only a relay restart brings
# it back): bench FIRST, everything after ~5 claims is best-effort; one
# TPU process at a time, clean exits, 5-minute claim gaps.
set -u
cd "$(dirname "$0")/.."
log() { echo "[chip_session3 $(date +%H:%M:%S)] $*"; }

log "1/4 bench.py (the BENCH artifact; dot should now show ~760 GB/s)"
python -u bench.py > tools/bench_r4_dev.json 2> tools/bench_r4_dev.err
log "bench exit=$? $(tail -c 300 tools/bench_r4_dev.json)"
sleep 300

log "2/4 stencil at DEFAULT precision (phys bar >= 200 GB/s)"
DR_TPU_MM_PRECISION=default python -u tools/tune_tpu.py stencil \
  > tools/tune_stencil_default.log 2>&1
log "stencil-default exit=$?"
sleep 300

log "3/4 physbw (VPU blocked kernel at T=1-8: the pure-DMA ceiling)"
python -u tools/tune_tpu.py physbw > tools/tune_physbw.log 2>&1
log "physbw exit=$?"
sleep 300

log "4/4 attn (regenerate the lost resident bq/bk + streaming log)"
python -u tools/tune_tpu.py attn > tools/tune_attn.log 2>&1
log "attn exit=$?"
log "session complete — COMMIT THE LOGS IMMEDIATELY (uncommitted sweep"
log "logs died with the VM twice this round)"
