#!/bin/bash
# Round-3 session-3 on-chip run: the chip_session.sh steps whose logs
# were lost with the previous VM (docs/ROUND3_NOTES.md chip session 2
# ran bench + attn; the rest never ran).  Same discipline: one TPU
# process at a time, clean exits, 5-minute gaps between claims.
set -u
cd "$(dirname "$0")/.."
log() { echo "[chip_session2 $(date +%H:%M:%S)] $*"; }

log "1/7 bench.py (regenerate the BENCH_r03 rehearsal artifact)"
python -u bench.py > tools/bench_r3_dev.json 2> tools/bench_r3_dev.err
log "bench exit=$? $(tail -c 300 tools/bench_r3_dev.json)"
sleep 300

log "2/7 spmv (BCSR GFLOP/s)"
python -u tools/tune_tpu.py spmv > tools/tune_spmv.log 2>&1
log "spmv exit=$?"
sleep 300

log "3/7 dot (XLA vs pallas kernel)"
python -u tools/tune_tpu.py dot > tools/tune_dot.log 2>&1
log "dot exit=$?"
sleep 300

log "4/7 heat (time blocks)"
python -u tools/tune_tpu.py heat > tools/tune_heat.log 2>&1
log "heat exit=$?"
sleep 300

log "5/7 scan (grid-vs-manual A/B + carry-seeded path)"
python -u tools/tune_tpu.py scan > tools/tune_scan5.log 2>&1
log "scan exit=$?"
sleep 300

log "6/7 stencil at DEFAULT precision (phys bar)"
DR_TPU_MM_PRECISION=default python -u tools/tune_tpu.py stencil \
  > tools/tune_stencil_default.log 2>&1
log "stencil-default exit=$?"
sleep 300

log "7/7 physbw (VPU blocked kernel at small T)"
python -u tools/tune_tpu.py physbw > tools/tune_physbw.log 2>&1
log "physbw exit=$?"
log "session complete"
