#!/bin/bash
# Long fuzz cranks, one pytest PROCESS per fuzz-test function.
#
# Why not one big `DR_TPU_FUZZ_ITERS=N pytest tests/test_fuzz.py`?
# Each random geometry compiles a fresh XLA CPU executable; a
# 300-iteration all-arms crank accumulates tens of thousands of
# compiled programs in one process, and the XLA CPU compiler was
# observed to SEGFAULT under that load (round 5: crash inside
# backend_compile_and_load after ~30 min; the same arm at 400
# iterations in its own process passes).  Per-function processes
# bound the compile count and make a crash attributable to ONE arm.
#
# Usage: tools/fuzz_crank.sh [iters] [filter]    (default 300, all arms)
#
# [filter] cranks only arms whose node id matches the substring — e.g.
# `tools/fuzz_crank.sh 300 sort_family` runs the round-6 sort-family
# arm (sort / sort_by_key / argsort / is_sorted, the restructured
# single-exchange plan included) at the full 300-iteration discipline.
#
# CHAOS arm (round 7): tests/test_chaos.py sweeps every registered
# fault-injection site x kind under the sort/scan/halo battery and
# asserts "classified error or clean degraded result, never a hang"
# (utils/faults + utils/resilience).  It collects alongside the fuzz
# arms (filter `chaos` to crank it alone); DR_TPU_CHAOS_ROUNDS scales
# its per-combo repetitions off the iteration budget.
#
# PLAN arm (round 8): test_fuzz_plan_chains cranks seeded random
# fusible op chains through `dr_tpu.deferred()` (dr_tpu/plan.py) and
# bit-compares the deferred flush against the eager sequence (filter
# `plan_chains`).  The chaos sweep covers the plan.flush fault site.
#
# SPARSE-FORMAT arm (round 9): test_fuzz_sparse_formats cranks every
# SpMV layout (csr/ell/bcsr/ring) over random densities, 1-D and 2-D
# grids, and the adversarial shapes (all-rows-empty, one-dense-row,
# banded) against a dense float64 oracle, and bit-compares the ring
# schedule's serial vs pipelined issue orders (filter
# `sparse_formats`).  The chaos sweep covers collectives.ppermute.
#
# CROSS-MESH arm (round 11): test_fuzz_cross_mesh drives random
# second runtimes over random device subsets through the two-runtime
# reshard routes (cross-mesh sort_by_key windows, cross-mesh scans)
# vs numpy oracles with the materialize fallback disarmed (filter
# `cross_mesh`).
#
# SERVE arm (round 11): tests/test_serve.py runs at the end against a
# LIVE `python -m dr_tpu.serve` daemon subprocess — with the crank's
# DR_TPU_CHAOS_ROUNDS > 1 it sweeps every serve.* site x kind combo
# there (plus all the in-process lifecycle edges); the in-battery
# serve leg rides the chaos arm above.
#
# REDISTRIBUTE arm (round 13): test_fuzz_redistribute cranks random
# src->dst redistributions (explicit distributions x random target
# runtimes over device subsets) against numpy oracles (filter
# `redistribute`) — collected automatically with the fuzz arms.
#
# ELASTIC arm (round 13): test_elastic.py's kill-a-rank fuzz runs at
# the end — random container populations, a random lost rank, one
# elastic rescue per pass; every container must match its pre-fault
# oracle or raise classified (docs/SPEC.md SS16).  The chaos arm above
# sweeps the device.lost / mesh.shrink site rows.
# RELATIONAL arm (round 14): test_fuzz_relational cranks random key
# distributions (uniform / skewed / all-equal / distinct / float) x
# uneven layouts through join / groupby / unique / histogram / top_k
# vs pandas/numpy oracles (filter `relational`) — collected
# automatically with the fuzz arms; the chaos battery grew a
# join -> groupby -> deferred top_k/histogram leg (docs/SPEC.md SS17).
#
# COLLECTIVE-REDISTRIBUTE arm (round 16): test_fuzz_redistribute_impls
# cranks random same-mesh src->dst re-layouts (uneven cuts, zero-size
# team blocks, halo vectors, several dtypes) through BOTH impls forced
# via DR_TPU_REDISTRIBUTE and bit-compares the physical padded rows
# (filter `redistribute_impls`); test_fuzz_join_partition cranks the
# join's broadcast vs bounded-memory repartition merge routes
# (DR_TPU_JOIN_BROADCAST_MAX=0 forces the exchange) over random key
# distributions x layouts, bit-equal on every channel (filter
# `join_partition`).  Both collect automatically with the fuzz arms;
# the chaos battery grew a redistribute leg sweeping the
# redistribute.exchange site rows (docs/SPEC.md SS18).
#
# GROW arm (round 15): test_fuzz_elastic_kill_and_revive (collected
# with the fuzz arms — random kill -> grow_session revive vs pre-fault
# oracles) plus the shrink->grow->shrink soak cranked below; the chaos
# battery grew a grow-back leg sweeping the device.recover / mesh.grow
# site rows (docs/SPEC.md SS16.6).
#
# RESPAWN arm (ISSUE 14, docs/SPEC.md SS20): the serving control
# plane under churn — crank-budgeted rounds of the full control-plane
# suite (circuit-breaker units, retry-budget exhaustion, journal
# torn-tail/fence recovery, drain-with-inflight) plus the slow-marked
# subprocess legs: replica SIGKILL -> supervisor respawn -> journal
# recovery verified bit-equal each round, and the rolling-restart
# soak (zero classified client errors).  The chaos battery above
# sweeps the router.probe / serve.drain / serve.journal site rows.
# PLAN-OPT arm (ISSUE 15, docs/SPEC.md SS21): test_fuzz_plan_opt
# cranks random recorded chains (fusible/opaque/relational/
# redistribute mix, random per-pass DR_TPU_PLAN_OPT_DISABLE
# bisection, a mid-flush elastic-shrink slice) and bit-compares
# DR_TPU_PLAN_OPT=all vs =0 — collected automatically with the fuzz
# arms above, plus a dedicated DR_TPU_SANITIZE=1 crank below (the
# recompile budget and finite-flush sweep over every optimized
# chain).  drlint R7 keys the pass registry on this arm.
#
# KERNEL arm (docs/SPEC.md SS22): test_fuzz_kernel_parity cranks every
# registered kernel arm (ops/kernels.ARM_NAMES) pallas-PINNED (Pallas
# interpret mode on the CPU mesh — the real kernel bodies, no silicon)
# vs xla-PINNED on identical inputs, bit-equal everywhere but the scan
# arm's tolerance carve-out, with a mid-sort elastic-shrink slice
# (filter `kernel_parity`); the slow-marked kernel_interpret variant
# (test_fuzz_kernel_parity_deep) collects here too — geometries past
# one bitonic stage boundary and a >2-tile segred groupby.  The chaos
# battery sweeps the kernel.build site rows.  drlint R8 keys the arm
# registry on this battery.
#
# PLANSAN arm (docs/SPEC.md SS23): test_fuzz_plansan cranks random
# recorded chains with the plansan layer armed in-process — shadow
# verifier over every fused run, container watcher over every opaque
# thunk, serializability oracle over every optimized queue under
# RANDOM DR_TPU_PLAN_OPT_DISABLE pass subsets — bit-compared against
# an unarmed control (filter `plansan`; collected automatically with
# the fuzz arms).  A dedicated DR_TPU_SANITIZE=1 crank below re-runs
# it through the env-armed install() route, and the MAKE-SANITIZE
# gate runs the whole tier-1 suite armed plus drlint (= `make
# sanitize`, the SS23.5 soundness gate).  drlint R9 keys the
# footprint family registry on the test_plansan.py mutation battery.
set -u
cd "$(dirname "$0")/.."
ITERS=${1:-300}
FILTER=${2:-}
CHAOS_ROUNDS=$(( ITERS / 60 + 1 ))
rc=0
# DRLINT arm (round 10): the static invariant gate runs FIRST — a rule
# violation fails the crank before any compile time is spent
# (docs/SPEC.md SS13; suppressions/baseline are the escape hatches)
echo "=== drlint --check (static invariants) ==="
if ! python tools/drlint.py --check; then
  echo "FAILED: drlint --check"
  rc=1
fi
# a broken collection (import/syntax error) must NOT read as a clean
# crank — with TWO files collected, one broken file still leaves nodes
# non-empty, so the pytest exit status is the guard, not just emptiness
collect_out=$(python -m pytest tests/test_fuzz.py tests/test_chaos.py \
              --collect-only -q 2>&1)
collect_rc=$?
nodes=$(printf '%s\n' "$collect_out" | grep "::" | cut -d"[" -f1 | sort -u)
if [ "$collect_rc" -ne 0 ] || [ -z "$nodes" ]; then
  echo "FAILED: broken test collection (rc=$collect_rc)" >&2
  printf '%s\n' "$collect_out" | tail -5 >&2
  exit 2
fi
if [ -n "$FILTER" ]; then
  nodes=$(printf '%s\n' $nodes | grep -- "$FILTER")
  if [ -z "$nodes" ]; then
    # collection was fine — the FILTER just matched nothing (typo?)
    echo "FAILED: no fuzz arm matches filter '$FILTER'" >&2
    exit 2
  fi
fi
for nd in $nodes; do
  echo "=== $nd (DR_TPU_FUZZ_ITERS=$ITERS DR_TPU_CHAOS_ROUNDS=$CHAOS_ROUNDS) ==="
  DR_TPU_FUZZ_ITERS=$ITERS DR_TPU_CHAOS_ROUNDS=$CHAOS_ROUNDS \
    python -m pytest "$nd" -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): $nd"
    rc=1
  fi
done
# SANITIZE arm (round 10): one crank of the plan-chain arm with the
# runtime sanitizer armed — recompile budget, finite flush sweep, and
# canon-portability checked over every random chain (docs/SPEC.md
# SS13.4).  Skipped when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  nd="tests/test_fuzz.py::test_fuzz_plan_chains"
  echo "=== $nd (DR_TPU_SANITIZE=1 DR_TPU_FUZZ_ITERS=$ITERS) ==="
  DR_TPU_SANITIZE=1 DR_TPU_FUZZ_ITERS=$ITERS \
    python -m pytest "$nd" -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): $nd under DR_TPU_SANITIZE=1"
    rc=1
  fi
fi
# TRACED arm (round 12): one crank of the plan-chain arm with the
# unified tracing layer armed (DR_TPU_TRACE=1, docs/SPEC.md SS15) —
# every dispatch/flush/fault rides the obs ring for the whole crank
# (the ring-buffer cap is the memory guarantee under test), the
# process-exit exporter writes a Chrome trace, and trace_view must
# parse and summarize it.  Skipped when a filter narrowed the crank.
if [ -z "$FILTER" ]; then
  nd="tests/test_fuzz.py::test_fuzz_plan_chains"
  TDIR=$(mktemp -d)
  echo "=== $nd (DR_TPU_TRACE=1 DR_TPU_FUZZ_ITERS=$ITERS) ==="
  DR_TPU_TRACE=1 DR_TPU_TRACE_DIR="$TDIR" DR_TPU_FUZZ_ITERS=$ITERS \
    python -m pytest "$nd" -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): $nd under DR_TPU_TRACE=1"
    rc=1
  fi
  if ls "$TDIR"/dr_tpu_trace_*.json >/dev/null 2>&1 \
      && python tools/trace_view.py "$TDIR"/dr_tpu_trace_*.json \
         > /dev/null; then
    echo "trace_view: traced-arm trace parsed OK"
  else
    echo "FAILED: traced arm produced no parseable trace"
    rc=1
  fi
  rm -rf "$TDIR"
fi
# PLAN-OPT arm (ISSUE 15): the bit-identity battery with the runtime
# sanitizer armed — recompile budget, finite flush sweep, and
# canon-portable dispatch keys over every OPTIMIZED chain (merged
# runs re-key their programs; a sanitize finding here is an optimizer
# bug).  Skipped when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  nd="tests/test_fuzz.py::test_fuzz_plan_opt"
  echo "=== $nd (DR_TPU_SANITIZE=1 DR_TPU_FUZZ_ITERS=$ITERS) ==="
  DR_TPU_SANITIZE=1 DR_TPU_FUZZ_ITERS=$ITERS \
    python -m pytest "$nd" -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): $nd under DR_TPU_SANITIZE=1"
    rc=1
  fi
fi
# PLANSAN arm (docs/SPEC.md SS23): the plansan battery through the
# ENV-armed route — DR_TPU_SANITIZE=1 makes runtime init call
# sanitize.install(), so the verifier/watcher/oracle ride every flush
# the way a production sanitize run arms them (the in-process arming
# inside the test covered the hook mechanics; this covers install()).
# Skipped when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  nd="tests/test_fuzz.py::test_fuzz_plansan"
  echo "=== $nd (DR_TPU_SANITIZE=1 DR_TPU_FUZZ_ITERS=$ITERS) ==="
  DR_TPU_SANITIZE=1 DR_TPU_FUZZ_ITERS=$ITERS \
    python -m pytest "$nd" -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): $nd under DR_TPU_SANITIZE=1"
    rc=1
  fi
fi
# MAKE-SANITIZE gate (docs/SPEC.md SS23.5): the full soundness gate —
# tier-1 under the armed runtime sanitizer (recompile budget, finite
# sweep, canon keys, plansan verifier + oracle on every deferred
# flush in the suite) plus the static half (drlint R0-R10).  Skipped
# when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  echo "=== make sanitize (armed tier-1 + drlint) ==="
  if ! make sanitize; then
    echo "FAILED: make sanitize"
    rc=1
  fi
fi
# ELASTIC arm (round 13): random kill-a-rank sweeps over random
# container populations, crank-budgeted (each pass inits a fresh mesh,
# loses a random rank, and audits the rescue/restore/lost matrix).
# Skipped when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  nd="tests/test_elastic.py::test_fuzz_elastic_kill_a_rank"
  echo "=== $nd (DR_TPU_FUZZ_ITERS=$ITERS) ==="
  DR_TPU_FUZZ_ITERS=$ITERS \
    python -m pytest "$nd" -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): $nd elastic arm"
    rc=1
  fi
fi
# GROW arm (round 15): the shrink->grow->shrink roundtrip soak,
# crank-budgeted — kill a rank, revive it through grow_session, kill
# another, asserting bit-equal container state vs the never-failed
# oracle at every step (docs/SPEC.md SS16.6; the kill-and-revive fuzz
# in test_fuzz.py is collected with the fuzz arms above).  Skipped
# when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  nd="tests/test_elastic.py::test_fuzz_elastic_shrink_grow_shrink"
  echo "=== $nd (DR_TPU_FUZZ_ITERS=$ITERS) ==="
  DR_TPU_FUZZ_ITERS=$ITERS \
    python -m pytest "$nd" -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): $nd grow arm"
    rc=1
  fi
fi
# SERVE arm (round 11): chaos against a live daemon subprocess —
# DR_TPU_CHAOS_ROUNDS > 1 expands test_serve_subprocess_chaos to the
# full serve.* site x kind sweep (plus every in-process lifecycle
# edge).  Skipped when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  echo "=== tests/test_serve.py (serve arm, DR_TPU_CHAOS_ROUNDS=$CHAOS_ROUNDS) ==="
  DR_TPU_CHAOS_ROUNDS=$CHAOS_ROUNDS \
    python -m pytest tests/test_serve.py -q 2>&1 | tail -2
  st=${PIPESTATUS[0]}
  if [ "$st" -ne 0 ]; then
    echo "FAILED ($st): tests/test_serve.py serve arm"
    rc=1
  fi
fi
# ARENA arm (ISSUE 13, docs/SPEC.md SS19): the serving data plane
# under churn — parallel-client arena stress against a SMALL segment
# (slot recycling + exhaustion fallbacks), the full in-process
# dataplane suite, and the subprocess fleet churn x replica-kill leg
# (crank-budgeted via DR_TPU_CHAOS_ROUNDS rounds of the whole file).
# Skipped when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  echo "=== tests/test_serve_dataplane.py (arena arm, rounds=$CHAOS_ROUNDS) ==="
  r=0
  while [ "$r" -lt "$CHAOS_ROUNDS" ]; do
    DR_TPU_SERVE_ARENA_BYTES=$((1 << 20)) \
      python -m pytest tests/test_serve_dataplane.py -q 2>&1 | tail -2
    st=${PIPESTATUS[0]}
    if [ "$st" -ne 0 ]; then
      echo "FAILED ($st): tests/test_serve_dataplane.py arena arm (round $r)"
      rc=1
      break
    fi
    r=$((r + 1))
  done
fi
# RESPAWN arm (ISSUE 14, docs/SPEC.md SS20): replica kill-and-respawn
# churn — each round runs the whole control-plane suite, slow
# subprocess legs included (SIGKILL -> respawn with journal recovery
# verified bit-equal, rolling restart with zero classified errors).
# Skipped when a filter already narrowed the crank.
if [ -z "$FILTER" ]; then
  echo "=== tests/test_serve_controlplane.py (respawn arm, rounds=$CHAOS_ROUNDS) ==="
  r=0
  while [ "$r" -lt "$CHAOS_ROUNDS" ]; do
    python -m pytest tests/test_serve_controlplane.py -q 2>&1 | tail -2
    st=${PIPESTATUS[0]}
    if [ "$st" -ne 0 ]; then
      echo "FAILED ($st): tests/test_serve_controlplane.py respawn arm (round $r)"
      rc=1
      break
    fi
    r=$((r + 1))
  done
fi
exit $rc
