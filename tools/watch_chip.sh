#!/bin/bash
# Autonomous chip watcher: patient probes on a 15-minute cadence; on
# the first success, waits out the claim-gap and runs the full
# measurement session (tools/chip_session.sh).  One TPU client at a
# time by construction — the prober exits cleanly before the session
# starts.  Log: tools/watch_chip.log
set -u
cd "$(dirname "$0")/.."
log() { echo "[watch_chip $(date +%H:%M:%S)] $*" >> tools/watch_chip.log; }

log "watcher started"
for attempt in $(seq 1 40); do
  log "probe attempt $attempt"
  python -u - > tools/probe_watch.log 2>&1 <<'PY'
import time, sys
t0 = time.time()
import jax
try:
    devs = jax.devices()
    print(f"PATIENT PROBE OK after {time.time()-t0:.0f}s:", devs)
    import jax.numpy as jnp
    print("sum:", float(jnp.ones((64,)).sum()))
    sys.exit(0)
except Exception as e:
    print(f"PATIENT PROBE FAIL after {time.time()-t0:.0f}s:", repr(e)[:200])
    sys.exit(3)
PY
  rc=$?
  if [ "$rc" -eq 0 ]; then
    log "CHIP ALIVE (attempt $attempt) — claim gap, then chip_session"
    sleep 300
    bash tools/chip_session.sh >> tools/watch_chip.log 2>&1
    log "chip_session finished"
    exit 0
  fi
  log "probe failed (rc=$rc); sleeping 15 min"
  sleep 900
done
log "watcher exhausted its attempts"
exit 1
