#!/bin/bash
# Round-5 relay-recovery watcher (re-armed; round 4's exited at its
# claim cutoff without the relay ever listening).
#
# Round 5 ALSO started with the relay down: every loopback relay port
# (see /root/.relay.py PORTS) refuses connections — the round-3 wedge
# has now outlived TWO round boundaries.
#
# Detection is CLAIM-FREE: a TCP connect to the relay's first port costs
# nothing on the server side, unlike a jax claim whose failure burns the
# client's ~25-minute internal retry budget and (per the round-2/3
# postmortems) may add to the server-side wedge tally.  Only when the
# port actually LISTENS again (the host restarted the relay) do we spend
# real claims — and we spend as few as possible: the observed budget is
# ~4-5 client processes per relay lifetime, and the driver's own
# end-of-round bench must land inside it (VERDICT r3 item 1).
#
#   recovery with >5h of round left: bench.py, then the one named
#     VERDICT sweep with a bar (stencil at DEFAULT precision), then STOP.
#   recovery later than that: bench.py ONLY, then STOP.
#
# Every artifact is committed the moment it lands (uncommitted sweep
# logs died with the VM twice in round 3).
set -u
cd "$(dirname "$0")/.."
log() { echo "[relay_watch $(date +%H:%M:%S)] $*" >> tools/relay_watch.log; }

port_open() {  # same knob as bench.py's _relay_listening
  python - <<'PY'
import os, socket, sys
s = socket.socket()
s.settimeout(3)
try:
    s.connect(("127.0.0.1",
               int(os.environ.get("DR_TPU_RELAY_PROBE_PORT", "8082"))))
    sys.exit(0)
except Exception:
    sys.exit(1)
finally:
    s.close()
PY
}

commit_logs() {  # $1 = message, rest = paths
  msg="$1"; shift
  for i in 1 2 3; do
    git add -- "$@" 2>>tools/relay_watch.log \
      && git commit -m "$msg" >> tools/relay_watch.log 2>&1 && return 0
    sleep 7  # index.lock race with foreground work: retry
  done
  log "COMMIT FAILED for: $msg"
  return 1
}

DEADLINE=$(( $(date +%s) + 5 * 3600 ))  # "early recovery" cutoff
# HARD claim cutoff: near end of round the driver's own bench is
# imminent — a watcher bench started on late recovery could run
# CONCURRENTLY with it (two TPU clients, the one thing the relay
# rules forbid).  After the cutoff the watcher only logs.
# Margin math: the round is ~12 h and the driver bench lands after it.
# A claim started at the cutoff runs bench only (~7-17 min; the sweep
# leg is gated behind the 5 h DEADLINE), so a 9 h cutoff leaves ~2.5 h
# of slack before any driver claim — never two TPU clients at once.
STOP=${DR_TPU_WATCH_STOP_EPOCH:-$(( $(date +%s) + 32400 ))}  # ~9 h

log "watcher started: TCP-checking 127.0.0.1:8082 every 120 s (claim-free)"
n=0
while true; do
  n=$((n + 1))
  if [ "$(date +%s)" -ge "$STOP" ]; then
    log "claim cutoff reached (driver bench imminent) — exiting" \
        "without claiming; the driver owns any recovered relay"
    exit 0
  fi
  if port_open; then
    log "RELAY PORT OPEN (check $n) — settling 60 s"
    sleep 60
    break
  fi
  [ $((n % 15)) -eq 0 ] && log "check $n: port still refusing"
  sleep 120
done

log "claim 1: bench.py (the rehearsal; dot should show ~760 GB/s pallas)"
python -u bench.py > tools/bench_r5_dev.json 2> tools/bench_r5_dev.err
log "bench exit=$? $(tail -c 200 tools/bench_r5_dev.json)"
commit_logs "Record the round-5 on-chip bench rehearsal" \
  tools/bench_r5_dev.json tools/bench_r5_dev.err tools/relay_watch.log

if [ "$(date +%s)" -lt "$DEADLINE" ]; then
  sleep 300
  log "claim 2: stencil at DEFAULT precision (phys bar >= 200 GB/s)"
  DR_TPU_MM_PRECISION=default python -u tools/tune_tpu.py stencil \
    > tools/tune_stencil_default.log 2>&1
  log "stencil-default exit=$?"
  commit_logs "Record the DEFAULT-precision stencil sweep" \
    tools/tune_stencil_default.log tools/relay_watch.log
  sleep 300
  # claim 3 is long (three sweeps); it may START only with >= 2 h of
  # slack before the STOP cutoff so even a slow run cannot straddle
  # the driver's own bench window (the margin math above assumes only
  # a short bench can ever run near STOP)
  if [ "$(date +%s)" -lt $(( STOP - 7200 )) ]; then
    # no timeout wrapper: SIGTERM-killing a TPU client mid-claim is
    # the one forbidden operation (relay wedge postmortems); the 2 h
    # slack gate bounds the exposure instead (sweeps historically run
    # 30-60 min)
    log "claim 3: halo carry A/B + attn honest re-rank + sort ladder" \
        "(one process = one claim)"
    python -u tools/tune_tpu.py halo attn sort \
      > tools/tune_r5_sweeps.log 2>&1
    log "halo/attn/sort exit=$?"
    commit_logs "Record the round-5 halo/attn/sort on-chip sweeps" \
      tools/tune_r5_sweeps.log tools/relay_watch.log
  else
    log "skipping claim 3: < 2 h before the claim cutoff"
  fi
else
  log "late recovery: bench only, preserving the driver's claim budget"
fi

log "watcher done — NO further claims this session (driver bench next)"
