#!/usr/bin/env python
"""trace_view — terminal summarizer for dr_tpu Chrome trace files.

A `DR_TPU_TRACE=1` run exports Chrome trace-event JSON (dr_tpu/obs,
docs/SPEC.md §15).  Perfetto renders it beautifully, but a fuzz crank
or CI log needs the story without a browser; this tool prints:

* **top spans by self-time** — per span-name aggregate of duration
  minus nested-child duration (same-thread time nesting, the Chrome
  model), so a flush span's cost is not double-counted against the
  runs inside it;
* **events by site/category** — instant-event counts grouped by
  category then name (fault-registry site visits, injected faults,
  dispatches/compiles, log lines);
* **per-request serve breakdown** — for each `serve.request` span,
  queue-wait (its retroactive child span), the batch-flush span it
  links into, and total latency, with aggregate mean/max;
* **per-tenant serve rollup** — p50/p95 queue-wait and service per
  tenant, so weighted-fair isolation (docs/SPEC.md §19.4) is visible
  straight from a trace: a heavy tenant's queue-wait dilates while a
  light tenant's stays flat;
* **serve control-plane rollup** — drain, breaker-probe (with the
  ok/failed split), replica-respawn, drain-rehash, and
  journal-replay event counts (docs/SPEC.md §20), so a traced
  rolling restart or kill-and-respawn session tells its story
  without a browser.

Usage::

    python tools/trace_view.py TRACE.json [...]  [--top N]

Exit status: 0 on a parseable trace (even an empty one prints a
summary); 2 on unreadable/malformed input — the fuzz-crank traced arm
uses this as its post-run sanity gate.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import List, Optional


def load_events(path: str) -> List[dict]:
    """Chrome trace events from ``path`` — accepts both the object
    form (``{"traceEvents": [...]}`` — what dr_tpu/obs writes) and the
    bare JSON-array form."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        evs = doc.get("traceEvents")
        if not isinstance(evs, list):
            raise ValueError(f"{path}: no traceEvents array")
        return evs
    if isinstance(doc, list):
        return doc
    raise ValueError(f"{path}: not a Chrome trace document")


def self_times(spans: List[dict]) -> dict:
    """Per-name ``{"total": us, "self": us, "count": n}`` aggregates.
    Self-time subtracts DIRECTLY nested same-thread child spans
    (stack sweep over spans sorted by start, longest first on ties)."""
    agg: dict = defaultdict(lambda: {"total": 0, "self": 0, "count": 0})
    by_tid: dict = defaultdict(list)
    for s in spans:
        by_tid[s.get("tid", 0)].append(s)
    for tid, group in by_tid.items():
        group.sort(key=lambda s: (s.get("ts", 0), -s.get("dur", 0)))
        stack: list = []  # (end_ts, span, child_time_accum)
        for s in group:
            ts, dur = s.get("ts", 0), s.get("dur", 0)
            while stack and stack[-1][0] <= ts:
                _close(stack, agg)
            if stack:
                stack[-1][2] += dur
            stack.append([ts + dur, s, 0])
        while stack:
            _close(stack, agg)
    return dict(agg)


def _close(stack: list, agg: dict) -> None:
    _, s, child = stack.pop()
    a = agg[s.get("name", "?")]
    dur = s.get("dur", 0)
    a["total"] += dur
    a["self"] += max(0, dur - child)
    a["count"] += 1


def _pct(vals, q) -> float:
    """Nearest-rank percentile over a small sample list (0 when
    empty) — no numpy dependency for a log-summarizer."""
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1))))]


def fmt_us(us) -> str:
    us = float(us)
    if us >= 1e6:
        return f"{us / 1e6:.3f} s"
    if us >= 1e3:
        return f"{us / 1e3:.3f} ms"
    return f"{us:.0f} us"


def summarize(events: List[dict], top: int = 15,
              out=None) -> None:
    out = out or sys.stdout
    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    flows = [e for e in events if e.get("ph") in ("s", "f")]
    print(f"trace: {len(events)} event(s) — {len(spans)} span(s), "
          f"{len(instants)} instant(s), {len(flows)} flow(s)",
          file=out)

    # ---- top spans by self-time
    agg = self_times(spans)
    print(f"\ntop {min(top, len(agg))} spans by self-time:", file=out)
    print(f"  {'name':<24} {'count':>6} {'total':>12} {'self':>12} "
          f"{'mean':>12}", file=out)
    for name, a in sorted(agg.items(),
                          key=lambda kv: -kv[1]["self"])[:top]:
        mean = a["total"] / a["count"] if a["count"] else 0
        print(f"  {name:<24} {a['count']:>6} {fmt_us(a['total']):>12} "
              f"{fmt_us(a['self']):>12} {fmt_us(mean):>12}", file=out)

    # ---- instant events grouped by category / name
    if instants:
        groups: dict = defaultdict(int)
        for e in instants:
            groups[(e.get("cat", ""), e.get("name", "?"))] += 1
        print("\nevents by site:", file=out)
        for (cat, name), n in sorted(groups.items(),
                                     key=lambda kv: (kv[0][0], -kv[1])):
            print(f"  {cat or '-':<10} {name:<28} {n:>8}", file=out)

    # ---- plan optimizer rollup (docs/SPEC.md §21.5): per-flush
    # optimizer spans plus the per-pass breakdown — what the pass
    # pipeline did (runs merged, dead ops eliminated, pushdowns) and
    # what it cost, straight from a traced run
    opt_spans = [s for s in spans if s.get("name") == "plan.opt"]
    if opt_spans:
        tot = {"merged_runs": 0, "dce_ops": 0, "pushdowns": 0}
        for s in opt_spans:
            a = s.get("args") or {}
            for k in tot:
                try:
                    tot[k] += int(a.get(k, 0) or 0)
                except (TypeError, ValueError):
                    pass
        cost = sum(s.get("dur", 0) for s in opt_spans)
        print(f"\nplan optimizer: {len(opt_spans)} optimized "
              f"flush(es), {fmt_us(cost)} total — "
              f"{tot['merged_runs']} run(s) merged, "
              f"{tot['dce_ops']} dead op(s) eliminated, "
              f"{tot['pushdowns']} pushdown(s)", file=out)
        per = [(name, a) for name, a in sorted(agg.items())
               if name.startswith("plan.opt.")]
        for name, a in per:
            print(f"  {name:<22} {a['count']:>6} runs  "
                  f"{fmt_us(a['total']):>12} total", file=out)

    # ---- serve control-plane rollup (docs/SPEC.md §20): drains,
    # breaker probes, respawns, drain-rehashes, journal replays
    cp: dict = defaultdict(int)
    probe_ok = 0
    for e in instants:
        name = e.get("name", "")
        # cat gates out the fault-site echo instants (cat="site"),
        # which share these names and would double every count
        if e.get("cat") == "serve" and \
                name in ("serve.drain", "router.probe",
                         "router.respawn", "router.drain_rehash",
                         "serve.journal.replay"):
            cp[name] += 1
            if name == "router.probe" and (e.get("args") or {}).get("ok"):
                probe_ok += 1
    if cp:
        print("\nserve control plane:", file=out)
        for name in ("serve.drain", "router.drain_rehash",
                     "router.probe", "router.respawn",
                     "serve.journal.replay"):
            if not cp.get(name):
                continue
            extra = (f" (ok={probe_ok}, failed={cp[name] - probe_ok})"
                     if name == "router.probe" else "")
            print(f"  {name:<22} {cp[name]:>6}{extra}", file=out)

    # ---- per-request serve latency breakdown
    reqs = [s for s in spans if s.get("name") == "serve.request"]
    if reqs:
        qw_by_parent: dict = {}
        for s in spans:
            if s.get("name") == "serve.queue_wait":
                p = (s.get("args") or {}).get("parent")
                if p is not None:
                    qw_by_parent[p] = s.get("dur", 0)
        flush_of: dict = {}
        for s in spans:
            if s.get("name") == "serve.batch_flush":
                for link in (s.get("args") or {}).get("links", []):
                    flush_of[link] = s.get("dur", 0)
        print(f"\nserve: {len(reqs)} request(s)", file=out)
        print(f"  {'op':<8} {'tenant':<10} {'rid':>6} "
              f"{'queue-wait':>12} {'flush':>12} {'total':>12} "
              f"{'outcome':<10}", file=out)
        tot = qws = 0
        worst = 0
        for s in sorted(reqs, key=lambda s: s.get("ts", 0)):
            a = s.get("args") or {}
            sid = s.get("id")
            qw = qw_by_parent.get(sid, 0)
            fl = flush_of.get(sid, 0)
            dur = s.get("dur", 0)
            tot += dur
            qws += qw
            worst = max(worst, dur)
            print(f"  {a.get('op', '?'):<8} {a.get('tenant', '?'):<10} "
                  f"{a.get('rid', '?'):>6} {fmt_us(qw):>12} "
                  f"{fmt_us(fl):>12} {fmt_us(dur):>12} "
                  f"{a.get('error', 'ok'):<10}", file=out)
        n = len(reqs)
        print(f"  mean total {fmt_us(tot / n)}, mean queue-wait "
              f"{fmt_us(qws / n)}, worst {fmt_us(worst)}", file=out)

        # ---- per-tenant rollup (weighted-fair isolation, SPEC §19.4)
        by_tenant: dict = defaultdict(lambda: {"qw": [], "sv": []})
        for s in reqs:
            a = s.get("args") or {}
            qw = qw_by_parent.get(s.get("id"), 0)
            row = by_tenant[a.get("tenant", "?")]
            row["qw"].append(qw)
            # service = the span's remainder once queue-wait is out
            row["sv"].append(max(0, s.get("dur", 0) - qw))
        if len(by_tenant) >= 1:
            print("\nserve per-tenant rollup (queue-wait / service):",
                  file=out)
            print(f"  {'tenant':<12} {'n':>5} {'qw p50':>12} "
                  f"{'qw p95':>12} {'sv p50':>12} {'sv p95':>12}",
                  file=out)
            for tenant in sorted(by_tenant):
                row = by_tenant[tenant]
                print(f"  {tenant:<12} {len(row['qw']):>5} "
                      f"{fmt_us(_pct(row['qw'], 50)):>12} "
                      f"{fmt_us(_pct(row['qw'], 95)):>12} "
                      f"{fmt_us(_pct(row['sv'], 50)):>12} "
                      f"{fmt_us(_pct(row['sv'], 95)):>12}", file=out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize dr_tpu Chrome trace files "
                    "(docs/SPEC.md §15)")
    ap.add_argument("traces", nargs="+", help="trace JSON file(s)")
    ap.add_argument("--top", type=int, default=15,
                    help="span rows to show (default 15)")
    args = ap.parse_args(argv)
    rc = 0
    for i, path in enumerate(args.traces):
        if len(args.traces) > 1 or i:
            print(f"\n=== {path} ===")
        try:
            events = load_events(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"trace_view: cannot read {path}: {e}",
                  file=sys.stderr)
            rc = 2
            continue
        summarize(events, top=args.top)
    return rc


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `trace_view … | head` is normal usage
        sys.exit(0)
