#!/usr/bin/env python
"""On-device tuning sweep for the hot kernels (run on the real TPU).

Measures everything by the marginal method with a hard scalar-read sync
(docs/PERF.md "measurement lesson"): block_until_ready can be a no-op
on tunneled backends, so each timed call returns one device scalar.

Usage:  python tools/tune_tpu.py
        [stencil|scan|dot|spmv|heat|attn|halo|sort|kernels|pipeline|
         relational|redistribute|serve|all]

Prints one line per configuration; safe to re-run (all programs cached
per process).  This is a developer tool, not part of the bench contract.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _errline(e):
    return (str(e).splitlines() or [repr(e)])[0][:90]


def _record_winner(domain, param, value, source):
    """Persist a measured winner into the tuning DB (docs/SPEC.md
    §21.6) under the LIVE mesh's backend/shape context — dispatch and
    the plan-optimizer config passes read it back with code defaults
    as fallback, so the sweep's result applies in every later process
    with zero code edits.  The context tag (backend, nshards, x64) is
    baked into the key, so a CPU-mesh sweep can never poison the TPU
    entry (and vice versa); a DEGRADED sweep therefore records a CPU
    row, which a TPU dispatch will never match.  With no store armed
    (DR_TPU_TUNING_DB / DR_TPU_COMPILE_CACHE_DIR both unset) the
    winner lands in the in-process overlay only and the line says
    so."""
    import dr_tpu
    from dr_tpu import tuning
    from dr_tpu.parallel import runtime as _rt
    if not _rt.is_initialized():
        dr_tpu.init()  # the context tag needs the live mesh
    key = tuning.record(domain, param, value,
                        source=f"tune_tpu:{source}")
    if key is None:
        print(f"tuning: {domain}.{param} = {value!r} noted in-process "
              "only (no DR_TPU_TUNING_DB / compile-cache dir armed)",
              flush=True)
    else:
        print(f"tuning: recorded {key} = {value!r}", flush=True)


def _marginal(run_sync, r1=2, r2=10, samples=5):
    """bench._marginal: the jitter-proof variant.  The plain median
    difference this tool used through round 3 had NO minimum-spread
    guard, so a fast op over few rounds (16 x ~1 ms for the attn sweep)
    measured a difference SMALLER than the tunnel's per-dispatch drift
    (tens of ms) — that is how round-3 sweep figures exceeded the
    chip's bf16 peak (VERDICT r3 item 7).  bench._marginal widens the
    loop count until the delta dominates the jitter and raises
    _JitterError instead of returning noise."""
    from bench import _marginal as _bench_marginal
    return _bench_marginal(run_sync, r1=r1, r2=r2, samples=samples)


def tune_stencil():
    """Sweep the fused-apply chunk cap and band width on the headline
    geometry (n = 2^29, f32)."""
    import jax
    import jax.numpy as jnp

    from dr_tpu.ops import stencil_matmul as sm

    n = 2 ** 29
    w = (0.05, 0.25, 0.4, 0.25, 0.05)  # radius 2
    for k, halo in ((64, 128), (128, 256), (256, 512)):
        seg = n
        row = jnp.zeros((1, 2 * halo + seg), jnp.float32) + 0.5
        GB = seg * 4 * 2 / 1e9
        for cap in (4096, 8192, 16384):
            os.environ["DR_TPU_MM_CHUNK_CAP"] = str(cap)
            try:
                @jax.jit
                def run(row, r, salt):
                    row = row.at[0, 0].add(salt * 1e-9)

                    def body(i, acc):
                        return sm.matmul_stencil_row(acc, seg, halo, w, k,
                                                     impl="pallas")
                    out = jax.lax.fori_loop(0, r, body, row)
                    return out[0, seg // 2]

                s = [0]

                def sync(r):
                    s[0] += 1
                    return float(run(row, r, s[0]))
                dt = _marginal(sync)
                print(f"stencil k={k} cap={cap}: {dt * 1e3:.2f} ms/apply "
                      f"phys {GB / dt:.1f} GB/s "
                      f"eff {GB * k / dt / 2:.0f} GB/s", flush=True)
            except Exception as e:
                print(f"stencil k={k} cap={cap}: FAIL "
                      f"{_errline(e)}", flush=True)
    os.environ.pop("DR_TPU_MM_CHUNK_CAP", None)


def tune_physbw():
    """PHYSICAL-bandwidth sweep of the VPU blocked kernel at small T:
    at T=1 the ~20 vector-ops/element-step sit well under the 2-pass
    DMA floor, so the per-pass rate should approach HBM peak — the
    datapoint for the >= 200 GB/s physical-bandwidth bar, docs/PERF.md
    (the MXU composed apply is
    MXU-bound near 180 GB/s; heat2d proves 91% of peak is reachable)."""
    import jax
    import jax.numpy as jnp

    from dr_tpu.ops import stencil_pallas as sp

    n = 2 ** 29
    w = (0.05, 0.25, 0.4, 0.25, 0.05)  # radius 2
    halo = 1024  # whole (8, 128) f32 tiles (kernel row alignment)
    row = jnp.zeros((1, 2 * halo + n), jnp.float32) + 0.5
    GB = n * 4 * 2 / 1e9
    for T in (1, 2, 4, 8):
        try:
            @jax.jit
            def run(row, r, salt):
                row = row.at[0, 0].add(salt * 1e-9)

                def body(i, acc):
                    return sp.blocked_stencil_row(acc, n, halo, w, T)
                out = jax.lax.fori_loop(0, r, body, row)
                return out[0, n // 2]

            s = [0]

            def sync(r):
                s[0] += 1
                return float(run(row, r, s[0]))
            dt = _marginal(sync)
            print(f"physbw T={T}: {dt * 1e3:.2f} ms/pass "
                  f"phys {GB / dt:.1f} GB/s "
                  f"eff {GB * T / dt:.0f} GB/s", flush=True)
        except Exception as e:
            print(f"physbw T={T}: FAIL {_errline(e)}", flush=True)


def tune_scan():
    import jax
    import jax.numpy as jnp

    from dr_tpu.ops import scan_pallas

    n = 2 ** 27
    x = jnp.ones((n,), jnp.float32)
    print("pick_chunk:", scan_pallas.pick_chunk(n), flush=True)

    # manual-pipeline entries first: the auto-grid form has hung the
    # remote compiler before (round-3 notes), so the provable numbers
    # must land before any grid attempt can stall the sweep
    sweep = [("mxu0", 8192, "manual"), ("mxu3", 8192, "manual"),
             ("mxu0", 16384, "manual"), ("mxu3", 16384, "manual"),
             ("mxu0", 4096, "manual"), ("vpu", 8192, "manual"),
             ("mxu0", 8192, "grid"), ("mxu3", 8192, "grid")]
    results = []
    for variant, cap, pipe in sweep:
        if variant == "vpu":
            os.environ["DR_TPU_SCAN_KERNEL"] = "vpu"
            os.environ.pop("DR_TPU_SCAN_PASSES", None)
        else:
            os.environ.pop("DR_TPU_SCAN_KERNEL", None)
            os.environ["DR_TPU_SCAN_PASSES"] = variant[-1]
        os.environ["DR_TPU_SCAN_PIPE"] = pipe
        os.environ["DR_TPU_SCAN_CHUNK"] = str(cap)

        @jax.jit
        def run(x, r, salt):
            # chain scans DIRECTLY (scan of the previous output): a
            # rescale between rounds would add a whole extra HBM pass
            # to every round and undercount the kernel by ~2x.  Values
            # blow up to inf; inf arithmetic runs at full speed and
            # the inclusive_scan_n bench measures the same way.
            x = x.at[0].add(salt * 1e-9)

            def body(i, acc):
                return scan_pallas.chunked_cumsum(acc)
            out = jax.lax.fori_loop(0, r, body, x)
            return out[n // 2]

        s = [0]

        def sync(r):
            s[0] += 1
            return float(run(x, r, s[0]))
        try:
            dt = _marginal(sync)
            # only rungs measured under the DEFAULT kernel family and
            # pipe feed the recorded winner: chunk_cap() applies the
            # DB entry with no env pins, so a vpu- or grid-tuned
            # chunk would be a cross-config confound
            if variant != "vpu" and pipe == "manual":
                results.append((dt, cap))
            print(f"scan kernel [{variant} {pipe} R={cap}]: "
                  f"{dt * 1e3:.3f} ms "
                  f"-> {2 * n * 4 / dt / 1e9:.1f} GB/s", flush=True)
        except Exception as e:
            print(f"scan kernel [{variant} {pipe} R={cap}]: FAIL "
                  f"{_errline(e)}", flush=True)
    os.environ.pop("DR_TPU_SCAN_KERNEL", None)
    os.environ.pop("DR_TPU_SCAN_CHUNK", None)
    os.environ.pop("DR_TPU_SCAN_PASSES", None)
    os.environ.pop("DR_TPU_SCAN_PIPE", None)
    if results:
        # the chunk of the fastest rung becomes the DB winner the
        # chunk_cap() picker reads back (env pin still beats it)
        _record_winner("scan", "chunk", min(results)[1], "scan")


def tune_container(name):
    """dot / spmv / heat / attn through the public *_n programs."""
    import jax.numpy as jnp

    import dr_tpu

    dr_tpu.init()
    if name == "dot":
        n = 2 ** 27
        a = dr_tpu.distributed_vector(n, np.float32)
        b = dr_tpu.distributed_vector(n, np.float32)
        dr_tpu.fill(a, 1.5)
        dr_tpu.fill(b, 2.0)
        for impl in ("xla", "pallas"):
            # explicit on BOTH arms: the kernel is the default when the
            # var is unset, so popping would compare pallas vs pallas
            os.environ["DR_TPU_DOT_IMPL"] = impl
            for r2 in (36, 150, 600):
                try:
                    dt = _marginal(
                        lambda r: float(dr_tpu.dot_n(a, b, r)), 4, r2)
                    print(f"dot [{impl}] r2={r2}: "
                          f"{2.0 * n * 4 / dt / 1e9:.1f} GB/s",
                          flush=True)
                except Exception as e:
                    print(f"dot [{impl}] r2={r2}: FAIL "
                          f"{_errline(e)}", flush=True)
        os.environ.pop("DR_TPU_DOT_IMPL", None)
    elif name == "heat":
        m = 8192
        w = dr_tpu.heat_step_weights(0.25)
        src = np.zeros((m, m), dtype=np.float32)
        src[m // 2, m // 2] = 1000.0
        M = dr_tpu.dense_matrix.from_array(src)

        def _sync(c):
            return float(c._data.addressable_shards[0].data.reshape(-1)[0])

        for tb in (8, 16, 32, 64):
            def run(r):
                dr_tpu.stencil2d_n(M, w, r, time_block=tb)
                _sync(M)
            try:
                dt = _marginal(run, 2, 10)
                print(f"heat2d tb={tb}: "
                      f"{2.0 * m * m * 4 * tb / dt / 1e9:.1f} GB/s eff",
                      flush=True)
            except Exception as e:
                print(f"heat2d tb={tb}: FAIL "
                      f"{_errline(e)}", flush=True)
    elif name == "attn":
        B, S, h, hd = 1, 8192, 8, 128
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(
            rng.standard_normal((B, S, h, hd)).astype(np.float32),
            dtype=jnp.bfloat16) for _ in range(3))

        from dr_tpu.ops.flash_attention import causal_computed_flops

        def run(r):
            res = dr_tpu.ring_attention_n(q, k, v, r, causal=True)
            float(res[0, 0, 0, 0].astype(jnp.float32))
        # ideal causal triangle (the cross-round comparison number) AND
        # the exact block-granular flops the kernel runs (utilization):
        # dividing the triangle by an honest time can never exceed peak,
        # so any figure above ~197 TFLOP/s flags a measurement bug
        fl = 2.0 * B * h * S * S * hd

        def report(tag, bq, bk, dt):
            actual = B * h * causal_computed_flops(S, S, hd, bq, bk)
            print(f"ring attn {tag}: {fl / dt / 1e12:.1f} TFLOP/s eff "
                  f"(ideal-causal), {actual / dt / 1e12:.1f} mxu "
                  f"(exact computed)", flush=True)
        for bq, bk in ((2048, 1024), (1024, 1024), (2048, 512),
                       (512, 512), (1024, 2048)):
            os.environ["DR_TPU_FLASH_BQ"] = str(bq)
            os.environ["DR_TPU_FLASH_BK"] = str(bk)
            try:
                dt = _marginal(run, 2, 18)
                report(f"bq={bq} bk={bk}", bq, bk, dt)
            except Exception as e:
                print(f"ring attn bq={bq} bk={bk}: FAIL "
                      f"{_errline(e)}", flush=True)
        os.environ.pop("DR_TPU_FLASH_BQ", None)
        os.environ.pop("DR_TPU_FLASH_BK", None)
        # streaming kernel on the same (resident-eligible) config:
        # compile check + the cost of HBM-streamed K/V tiles
        os.environ["DR_TPU_FLASH_STREAM"] = "1"
        try:
            dt = _marginal(run, 2, 18)
            from dr_tpu.ops.flash_attention import pick_blocks
            bq, bk = pick_blocks(S, S, hd)
            report(f"STREAMING bq={bq} bk={bk}", bq, bk, dt)
        except Exception as e:
            print(f"ring attn STREAMING: FAIL {_errline(e)}", flush=True)
        finally:
            os.environ.pop("DR_TPU_FLASH_STREAM", None)
    elif name == "halo":
        # The driver metric's third term (halo p50) drifted 273 -> 462 us
        # across rounds 1-3 on the same config; the round-4 ghost-carry
        # exchange_n (halo.py:_exchange_n_program) removes the two
        # full-row copies per round the row carry paid.  A/B both
        # carries x ghost widths; bar: ghost-carry p50 <= the r1 273 us.
        rounds = 64
        for hw in (2, 1024):
            n = dr_tpu.nprocs() * 2 ** 22
            hb = dr_tpu.halo_bounds(hw, hw, periodic=True)
            v = dr_tpu.distributed_vector(n, np.float32, halo=hb)
            dr_tpu.fill(v, 1.0)
            h = v.halo()

            def _sync(_=None):
                return float(
                    v._data.addressable_shards[0].data.reshape(-1)[0])

            for carry in ("ghost", "row"):
                os.environ["DR_TPU_HALO_NCARRY"] = carry

                def run(r):
                    h.exchange_n(rounds * r)
                    _sync()
                try:
                    dt = _marginal(run, 2, 10)
                    print(f"halo hw={hw} carry={carry}: "
                          f"{dt / rounds * 1e6:.1f} us/exchange",
                          flush=True)
                except Exception as e:
                    print(f"halo hw={hw} carry={carry}: FAIL "
                          f"{_errline(e)}", flush=True)
            os.environ.pop("DR_TPU_HALO_NCARRY", None)
            v = h = None
    elif name == "spmv":
        m, half = 2 ** 15, 128
        rng = np.random.default_rng(1)
        ii = np.repeat(np.arange(m), 2 * half + 1)
        jj = ii + np.tile(np.arange(-half, half + 1), m)
        keep = (jj >= 0) & (jj < m)
        ii, jj = ii[keep], jj[keep]
        vv = rng.standard_normal(len(ii)).astype(np.float32)
        A = dr_tpu.sparse_matrix.from_coo((m, m), ii, jj, vv)
        assert A.ensure_bcsr()
        c = dr_tpu.distributed_vector(m, np.float32)
        bv = dr_tpu.distributed_vector(m, np.float32)
        dr_tpu.fill(bv, 1.0)
        dr_tpu.fill(c, 0.0)

        def _sync(cc):
            return float(cc._data.addressable_shards[0].data.reshape(-1)[0])

        for r2 in (18, 600, 3000):
            def run(r):
                dr_tpu.gemv_n(c, A, bv, r)
                _sync(c)
            try:
                dt = _marginal(run, 2, r2)
                print(f"bcsr spmv r2={r2}: "
                      f"{2.0 * len(ii) / dt / 1e9:.2f} GFLOP/s",
                      flush=True)
            except Exception as e:
                print(f"bcsr spmv r2={r2}: FAIL {_errline(e)}",
                      flush=True)
        # random pattern x multiple vectors: the gather-amortization
        # surface (nv slices of work per gather issue; PERF.md roofline)
        mr, kr = 2 ** 17, 32
        rng = np.random.default_rng(0)
        rrows = np.repeat(np.arange(mr), kr)
        rcols = rng.integers(0, mr, size=mr * kr)
        rvals = rng.standard_normal(mr * kr).astype(np.float32)
        Ar = dr_tpu.sparse_matrix.from_coo((mr, mr), rrows, rcols, rvals)
        for nv in (1, 4, 8, 16):
            Bm = jnp.asarray(
                rng.standard_normal((mr, nv)).astype(np.float32))

            def run_mm(r):
                y = dr_tpu.spmm_n(Ar, Bm, r)
                float(y[0, 0])
            try:
                dt = _marginal(run_mm, 2, 18)
                print(f"random spmm nv={nv}: "
                      f"{2.0 * mr * kr * nv / dt / 1e9:.2f} GFLOP/s "
                      "aggregate", flush=True)
            except Exception as e:
                print(f"random spmm nv={nv}: FAIL {_errline(e)}",
                      flush=True)
        tune_spmv_ladder()


def tune_spmv_ladder():
    """Round-9 spmv LADDER: format x density x n sweep through gemv_n
    (every arm of the dispatch — csr segment-sum, ELL, BCSR, ring) plus
    the ring-schedule A/B (DR_TPU_RING_SCHEDULE serial vs pipelined)
    and the ring phase table (gemv_phases_n truncations) at each ring-
    eligible point — the on-chip datapoints docs/PERF.md round 9 needs
    before the autoselect thresholds can be called tuned."""
    import dr_tpu
    from dr_tpu.algorithms.gemv import (SPMV_PHASES, gemv_n,
                                        gemv_phases_n, viable_formats)
    from dr_tpu.utils import profiling

    P = dr_tpu.nprocs()
    rng = np.random.default_rng(2)

    def _sync(cc):
        return float(cc._data.addressable_shards[0].data.reshape(-1)[0])

    # restore any operator-pinned values on exit (the sweep forces its
    # own per-rung settings; a session-level pin must survive it)
    from dr_tpu.utils.env import env_override, env_raw
    fmt_wins: dict = {}
    with env_override(
            DR_TPU_SPMV_FORMAT=env_raw("DR_TPU_SPMV_FORMAT"),
            DR_TPU_RING_SCHEDULE=env_raw("DR_TPU_RING_SCHEDULE")):
        for logn in (14, 17):
            for k in (4, 32):
                m = 2 ** logn
                rows = np.repeat(np.arange(m), k)
                cols = rng.integers(0, m, size=m * k)
                vals = rng.standard_normal(m * k).astype(np.float32)
                A = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols,
                                                  vals)
                c = dr_tpu.distributed_vector(m, np.float32)
                bv = dr_tpu.distributed_vector(m, np.float32)
                dr_tpu.fill(bv, 1.0)
                dr_tpu.fill(c, 0.0)
                flops = 2.0 * m * k
                tag = f"n=2^{logn} k={k} auto={A.format}"

                def run(r):
                    gemv_n(c, A, bv, r)
                    _sync(c)
                # forced-but-ineligible formats fall back down the
                # dispatch chain (SPEC §12.2): tag those rungs rather
                # than printing the fallback arm's number under the
                # forced label.  The ring arm is measured ONLY by the
                # schedule A/B below — a [ring] rung here would repeat
                # the [ring/pipelined] measurement verbatim.
                viable = viable_formats(A)
                rung_best = None
                for fmt in ("csr", "ell", "bcsr"):
                    if not viable[fmt]:
                        print(f"spmv {tag} [{fmt}]: ineligible "
                              "(would fall back)", flush=True)
                        continue
                    os.environ["DR_TPU_SPMV_FORMAT"] = fmt
                    try:
                        dt = _marginal(run, 2, 18)
                        if rung_best is None or dt < rung_best[0]:
                            rung_best = (dt, fmt)
                        print(f"spmv {tag} [{fmt}]: "
                              f"{flops / dt / 1e9:.2f} GFLOP/s",
                              flush=True)
                    except Exception as e:
                        print(f"spmv {tag} [{fmt}]: FAIL {_errline(e)}",
                              flush=True)
                if rung_best is not None:
                    fmt_wins[rung_best[1]] = \
                        fmt_wins.get(rung_best[1], 0) + 1
                os.environ["DR_TPU_SPMV_FORMAT"] = "ring"
                try:
                    if P > 1 and viable["ring"]:
                        for sched in ("serial", "pipelined"):
                            os.environ["DR_TPU_RING_SCHEDULE"] = sched
                            try:
                                dt = _marginal(run, 2, 18)
                                print(f"spmv {tag} [ring/{sched}]: "
                                      f"{flops / dt / 1e9:.2f} GFLOP/s",
                                      flush=True)
                            except Exception as e:
                                print(f"spmv {tag} [ring/{sched}]: "
                                      f"FAIL {_errline(e)}", flush=True)
                        os.environ.pop("DR_TPU_RING_SCHEDULE", None)

                        def mk(i):
                            def runp(r):
                                gemv_phases_n(c, A, bv, SPMV_PHASES[i],
                                              r)
                                _sync(c)
                            return runp
                        bd = profiling.profile_phases(mk, SPMV_PHASES,
                                                      r1=2, r2=10)
                        print(f"spmv {tag} phase ladder:\n"
                              + bd.table(flops, unit="GFLOP/s"),
                              flush=True)
                    else:
                        print(f"spmv {tag}: ring ineligible (p=1 or "
                              "bucket-skew gate) — phases collapse",
                              flush=True)
                except Exception as e:
                    print(f"spmv {tag} ring ladder: FAIL {_errline(e)}",
                          flush=True)
                finally:
                    os.environ.pop("DR_TPU_SPMV_FORMAT", None)
                    os.environ.pop("DR_TPU_RING_SCHEDULE", None)
                A = c = bv = None
    if fmt_wins:
        # majority winner across the ladder's rungs: the _pick_format
        # tier between the env pin and the build-time autoselect.
        # The ring arm is deliberately absent — its eligibility is
        # per-matrix (bucket-skew gate), so a ring row would force
        # the fallback chain on ineligible matrices for nothing.
        best = max(sorted(fmt_wins), key=lambda f: fmt_wins[f])
        _record_winner("spmv", "format", best, "spmv")


def tune_sort():
    """Size ladder for the sample-sort family (sort_n / sort_by_key_n
    fused loops): records where the collective phases amortize — the
    on-chip row for docs/PERF.md (the reference has no sort to compare
    against; the bar is the repo's own advertised surface).

    Round 6: each size also prints the PHASE LADDER (per-phase ms and
    share via the stop_after truncations + utils.profiling), an A/B of
    the stable-comparator override (DR_TPU_SORT_STABLE), and the
    key-value ladder at the top size — so the staged TPU tuning starts
    from an understood shape instead of one opaque number."""
    import jax
    import dr_tpu
    dr_tpu.init()
    P = dr_tpu.nprocs()
    from dr_tpu.algorithms.sort import (SORT_PHASES, SORTKV_PHASES,
                                        sort_by_key_n,
                                        sort_by_key_phases_n, sort_n,
                                        sort_phases_n)
    from dr_tpu.utils import profiling
    rng = np.random.default_rng(3)
    sizes = (18, 20, 22, 24)
    for logn in sizes:
        n = (2 ** logn) // P * P
        try:
            v = dr_tpu.distributed_vector(n, np.float32)
            v.assign_array(rng.standard_normal(n).astype(np.float32))

            def run(r):
                sort_n(v, r)
                float(v[0])
            dt = _marginal(run, 2, 10)
            print(f"sort n=2^{logn}: {n / dt / 1e6:.1f} Mkeys/s "
                  f"({n * 4 / dt / 1e9:.2f} GB/s)", flush=True)

            # stable-comparator A/B (the unstable default won round 6
            # on sorted/structured inputs; re-confirm on each chip).
            # Restore the operator's own setting afterwards — a sweep
            # run entirely under DR_TPU_SORT_STABLE=1 must stay stable.
            from dr_tpu.utils.env import env_override
            with env_override(DR_TPU_SORT_STABLE="1"):
                try:
                    dt_s = _marginal(run, 2, 10)
                    print(f"sort n=2^{logn} [stable]: "
                          f"{n / dt_s / 1e6:.1f} Mkeys/s", flush=True)
                except Exception as e:
                    print(f"sort n=2^{logn} [stable]: FAIL {_errline(e)}",
                          flush=True)

            if P == 1:
                # the single-chip deployment: no collective phases —
                # every truncation IS the full program, so a ladder
                # would print pure dispatch noise (bench.py makes the
                # same collapse)
                print(f"sort n=2^{logn} phase ladder: p=1 — "
                      "collective phases collapse; sort IS the local "
                      "XLA sort", flush=True)
            else:
                def mk(i):
                    def runp(r):
                        sort_phases_n(v, SORT_PHASES[i], r)
                        float(v[0])
                    return runp
                bd = profiling.profile_phases(mk, SORT_PHASES,
                                              r1=2, r2=10)
                print(f"sort n=2^{logn} phase ladder:\n"
                      + bd.table(n * 4.0), flush=True)

            kd = dr_tpu.distributed_vector(n, np.float32)
            kd.assign_array(rng.standard_normal(n).astype(np.float32))
            pd = dr_tpu.distributed_vector(n, np.int32)
            dr_tpu.iota(pd, 0)

            def run_kv(r):
                sort_by_key_n(kd, pd, r)
                float(kd[0])
            dt = _marginal(run_kv, 2, 10)
            print(f"sort_by_key n=2^{logn}: {n / dt / 1e6:.1f} Mpairs/s "
                  f"({2 * n * 4 / dt / 1e9:.2f} GB/s)", flush=True)
            if logn == sizes[-1] and P > 1:
                def mkv(i):
                    def runp(r):
                        sort_by_key_phases_n(kd, pd, SORTKV_PHASES[i],
                                             r)
                        float(kd[0])
                    return runp
                bdk = profiling.profile_phases(mkv, SORTKV_PHASES,
                                               r1=2, r2=10)
                print(f"sort_by_key n=2^{logn} phase ladder:\n"
                      + bdk.table(2 * n * 4.0), flush=True)
        except Exception as e:
            print(f"sort n=2^{logn}: FAIL {_errline(e)}", flush=True)
        finally:
            v = kd = pd = None


def tune_kernels():
    """On-chip kernel-arm ladder (docs/SPEC.md §22): every registered
    arm (ops/kernels.ARM_NAMES) A/B'd pallas vs xla over dtype x size
    rungs, the winner per arm recorded into the tuning DB as
    ``kernels.<arm>`` — the §22.2 pickers read it back with env pins
    still beating it.  On this host's CPU mesh the pallas rung runs in
    interpret mode (uselessly slow — the recorded CPU-context row can
    never poison the TPU entry, §21.6), so the ladder only MEANS
    something on silicon; it still runs everywhere as a correctness
    smoke."""
    import dr_tpu
    from dr_tpu.ops import kernels
    from dr_tpu.utils.env import env_override

    dr_tpu.init()
    P = dr_tpu.nprocs()
    rng = np.random.default_rng(22)
    wins = {}

    def ab(arm, label, run_sync):
        """One rung: time run_sync under each pin; returns the winner
        mode or None when either leg failed."""
        env = dict((e, None) for _, e, _, _, _ in kernels.ARMS)
        out = {}
        for mode in ("xla", "pallas"):
            env[dict((a, e) for a, e, _, _, _ in kernels.ARMS)[arm]] \
                = mode
            with env_override(**env):
                try:
                    out[mode] = _marginal(run_sync, 2, 10)
                    print(f"kernels {arm} [{label} {mode}]: "
                          f"{out[mode] * 1e3:.3f} ms", flush=True)
                except Exception as e:
                    print(f"kernels {arm} [{label} {mode}]: FAIL "
                          f"{_errline(e)}", flush=True)
        if len(out) == 2:
            return min(out, key=out.get)
        return None

    # --- sort_local: the fused sort_n loop at kernel-eligible shard
    # sizes (padded bitonic cap is 2^15 elements per shard)
    from dr_tpu.algorithms.sort import sort_by_key_n, sort_n
    for dt_name, dt in (("f32", np.float32), ("i32", np.int32)):
        for spp in (4096, 16384):
            n = spp * P
            v = dr_tpu.distributed_vector(n, dt)
            src = (rng.standard_normal(n).astype(dt) if dt == np.float32
                   else rng.integers(-9999, 9999, n).astype(dt))
            v.assign_array(src)

            def run(r, v=v):
                sort_n(v, r)
                float(v[0])
            w = ab("sort_local", f"{dt_name} n={n}", run)
            if w:
                wins.setdefault("sort_local", []).append(w)
    kd = dr_tpu.distributed_vector(8192 * P, np.float32)
    kd.assign_array(rng.standard_normal(8192 * P).astype(np.float32))
    pd = dr_tpu.distributed_vector(8192 * P, np.int32)
    dr_tpu.iota(pd, 0)

    def run_kv(r):
        sort_by_key_n(kd, pd, r)
        float(kd[0])
    w = ab("sort_local", f"kv n={8192 * P}", run_kv)
    if w:
        wins.setdefault("sort_local", []).append(w)

    # --- segred: groupby (the monoid core) + the plain reduce route
    for agg, vdt in (("sum", np.int32), ("min", np.float32)):
        nk = 4096 * P
        gk = dr_tpu.distributed_vector.from_array(
            rng.integers(0, 500, nk).astype(np.int32))
        gv = dr_tpu.distributed_vector.from_array(
            rng.integers(0, 99, nk).astype(vdt) if vdt == np.int32
            else rng.standard_normal(nk).astype(vdt))
        ok = dr_tpu.distributed_vector(512, np.int32)
        ov = dr_tpu.distributed_vector(512, vdt)

        def run(r, gk=gk, gv=gv, ok=ok, ov=ov, agg=agg):
            for _ in range(r):
                dr_tpu.groupby_aggregate(gk, gv, ok, ov, agg=agg)
            float(ov[0])
        w = ab("segred", f"groupby-{agg}-{np.dtype(vdt).name}", run)
        if w:
            wins.setdefault("segred", []).append(w)
    ri = dr_tpu.distributed_vector.from_array(
        rng.integers(-99, 99, 8192 * P).astype(np.int32))

    def run_red(r):
        acc = 0
        for _ in range(r):
            acc = dr_tpu.reduce(ri)
        float(acc)
    w = ab("segred", "reduce-add-int32", run_red)
    if w:
        wins.setdefault("segred", []).append(w)

    # --- hist: the bincount scatter-add over bin-count rungs
    hv = dr_tpu.distributed_vector.from_array(
        rng.standard_normal(8192 * P).astype(np.float32))
    for bins in (64, 1024):
        hb = dr_tpu.distributed_vector(bins, np.int32)

        def run(r, hb=hb):
            for _ in range(r):
                dr_tpu.histogram(hv, hb, -4.0, 4.0)
            float(hb[0])
        w = ab("hist", f"bins={bins}", run)
        if w:
            wins.setdefault("hist", []).append(w)

    # --- scan: the fused inclusive_scan_n loop at a chunkable size
    ns = 128 * 128 * max(1, 2 ** 27 // (128 * 128 * P)) * P
    sv = dr_tpu.distributed_vector(ns, np.float32)
    dr_tpu.fill(sv, 1.0)
    so = dr_tpu.distributed_vector(ns, np.float32)

    def run_scan(r):
        dr_tpu.inclusive_scan_n(sv, so, r)
        float(so[0])
    w = ab("scan", f"f32 n={ns}", run_scan)
    if w:
        wins.setdefault("scan", []).append(w)

    for arm in kernels.ARM_NAMES:
        got = wins.get(arm)
        if not got:
            print(f"kernels {arm}: no complete A/B rung — nothing "
                  "recorded", flush=True)
            continue
        # majority across rungs (the spmv-format discipline): the
        # picker applies ONE mode per arm, so the rung vote is the
        # honest aggregate
        best = max(set(got), key=got.count)
        _record_winner("kernels", arm, best, "kernels")


def tune_pipeline():
    """Chain-length ladder for the deferred execution plan (round 8,
    dr_tpu/plan.py): per-chain time of the 5-op pipeline chain
    (fill -> for_each -> halo exchange -> transform -> reduce), eager
    vs deferred, at growing chain lengths.  Eager pays the tunneled
    per-dispatch constant 5x per chain plus one sync; a deferred
    region of r chains is ONE dispatch + ONE sync however long the
    chain — the ladder shows where the amortization saturates, the
    datapoint for docs/PERF.md's pipeline rows on the next chip
    session."""
    import dr_tpu
    from bench import _pipeline_runners

    dr_tpu.init()
    P = dr_tpu.nprocs()
    on_cpu = dr_tpu.devices()[0].platform == "cpu"
    n = (2 ** 20 if on_cpu else 2 ** 24) // P * P
    hb = dr_tpu.halo_bounds(2, 2, periodic=True)
    a = dr_tpu.distributed_vector(n, np.float32, halo=hb)
    b = dr_tpu.distributed_vector(n, np.float32, halo=hb)
    # the SAME runner pair as bench's pipeline config: the on-chip
    # ladder must time the identical workload the PERF.md rows record
    run_eager, run_deferred = _pipeline_runners(a, b)

    from dr_tpu.utils.spmd_guard import dispatch_count
    for r in (1, 2, 4, 8, 16, 32):
        for tag, run in (("eager", run_eager), ("deferred", run_deferred)):
            try:
                run(r)  # warm/compile (each deferred r is a new program)
                ts = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    run(r)
                    ts.append(time.perf_counter() - t0)
                d0 = dispatch_count()
                run(r)
                disp = dispatch_count() - d0
                per = float(np.median(ts)) / r
                print(f"pipeline r={r:<2d} [{tag:8s}]: "
                      f"{per * 1e3:8.3f} ms/chain  "
                      f"{disp} dispatch(es)/region", flush=True)
            except Exception as e:
                print(f"pipeline r={r} [{tag}]: FAIL {_errline(e)}",
                      flush=True)


def tune_relational():
    """Relational-layer ladder (round 14, docs/SPEC.md §17) for the
    queued silicon session: per-stage wall time of the TPC-style
    pipeline (join -> groupby sum -> top_k) at growing fact-table
    sizes x key cardinalities — the numbers that decide whether the
    broadcast sorted-merge join needs the bounded-memory repartition
    exchange (ROADMAP item 2) on real chips."""
    import dr_tpu
    # the SAME runner as bench's relational config: the on-chip
    # ladder must time the identical workload the PERF.md rows record
    from bench import _relational_runner
    from dr_tpu.utils.env import env_override

    dr_tpu.init()
    on_cpu = dr_tpu.devices()[0].platform == "cpu"
    ratios = None
    crossover = []  # (combined_rows, t_broadcast, t_partition)
    for logn in ((12, 14) if on_cpu else (16, 18, 20)):
        n = 2 ** logn
        for card in (max(n // 64, 4), max(n // 8, 4)):
            stage = conts = None
            try:
                stage, conts = _relational_runner(n, card)
                # broadcast-vs-repartition A/B at every rung: the
                # crossover row count is the §21.4 joinroute winner
                with env_override(
                        DR_TPU_JOIN_BROADCAST_MAX=str(1 << 62)):
                    stage()  # warm/compile the broadcast programs
                    m, ng, ts = stage()
                with env_override(DR_TPU_JOIN_BROADCAST_MAX="0"):
                    stage()  # warm the partition programs
                    _m2, _ng2, ts_p = stage()
                crossover.append((n + card, ts["join"], ts_p["join"]))
                # observed output/input ratios: the capinfer pass's
                # probe-skipping hints (join base = both sorted sides,
                # groupby base = its input rows)
                ratios = (m / max(n + card, 1), ng / max(m, 1))
                total = sum(ts.values())
                print(f"relational n=2^{logn} card={card:<7d}: "
                      f"join {ts['join'] * 1e3:8.2f} ms "
                      f"(part {ts_p['join'] * 1e3:8.2f} ms)  "
                      f"groupby {ts['groupby'] * 1e3:8.2f} ms  "
                      f"topk {ts['topk'] * 1e3:8.2f} ms  "
                      f"({n / total / 1e3:8.1f} krows/s)",
                      flush=True)
            except Exception as e:
                print(f"relational n=2^{logn} card={card}: FAIL "
                      f"{_errline(e)}", flush=True)
            finally:
                stage = conts = None
    if ratios is not None:
        _record_winner("relational", "cap_ratio_join_inner",
                       round(ratios[0], 6), "relational")
        _record_winner("relational", "cap_ratio_groupby",
                       round(ratios[1], 6), "relational")
    wins = [c for c, tb, tp in crossover if tp < tb]
    if wins and dr_tpu.nprocs() > 1:
        # repartition first wins at `min(wins)` combined rows: route
        # broadcast strictly below it (join keeps broadcast while
        # combined <= broadcast_max)
        _record_winner("join", "broadcast_max", min(wins) - 1,
                       "relational")
    elif crossover:
        print("tuning: no repartition crossover to record (single "
              "shard, or broadcast wins every measured rung) — "
              "join.broadcast_max keeps the code default", flush=True)


def tune_redistribute():
    """Round-16 re-layout ladder (docs/SPEC.md §18) for the queued
    silicon session: per-hop GB/s of host-staged vs collective
    ``redistribute()`` at growing n over the layout kinds that shape
    the exchange plan — ``rotate`` (every shard's window shifts: p-1
    short hops) and ``team`` (gather-to-one/scatter-from-one: the
    largest single buckets).  The host-vs-collective gap on real ICI
    is the number that retires the host-staged default everywhere the
    meshes align."""
    import dr_tpu
    from dr_tpu.utils.env import env_override

    dr_tpu.init()
    P = dr_tpu.nprocs()
    on_cpu = dr_tpu.devices()[0].platform == "cpu"
    for logn in ((16, 18) if on_cpu else (20, 22, 24)):
        n = max((1 << logn) // P * P, P)
        for kind in ("rotate", "team"):
            if kind == "team":
                alt = [n] + [0] * (P - 1)
            else:
                base = n // P
                alt = [base] * P
                alt[0] = base // 2
                alt[-1] = n - sum(alt[:-1])
            v = None
            try:
                v = dr_tpu.distributed_vector.from_array(
                    np.arange(n, dtype=np.float32))
                for impl in ("host", "collective"):
                    def run(r, impl=impl, alt=alt, v=v):
                        with env_override(DR_TPU_REDISTRIBUTE=impl):
                            for _ in range(r):
                                dr_tpu.redistribute(v, alt)
                                dr_tpu.redistribute(v, None)
                        float(np.asarray(v._data)[0, 0])  # sync

                    dt = _marginal(run, r1=1, r2=5, samples=3)
                    print(f"redistribute n=2^{logn} [{kind:6s}] "
                          f"{impl:10s}: {2 * n * 4 / dt / 1e9:8.3f} "
                          "GB/s/hop-pair", flush=True)
            except Exception as e:
                print(f"redistribute n=2^{logn} [{kind}]: FAIL "
                      f"{_errline(e)}", flush=True)
            finally:
                v = None


def tune_serve():
    """Serving data-plane ladder (ISSUE 13, docs/SPEC.md §19) for the
    queued silicon session: closed-loop p50 / rps over batch window x
    arena x replica count.  On a real-TPU session the PRIMARY daemon
    holds the device claim and the replica rungs stay CPU-route (the
    one-claim rule) — the numbers that matter on chip are the batch
    window and the arena A/B against the device daemon."""
    import tempfile
    import threading

    import dr_tpu
    from dr_tpu import serve
    from dr_tpu.utils.env import env_override

    dr_tpu.init()
    tmpdir = tempfile.mkdtemp(prefix="dr_tpu_tune_serve_")
    rng = np.random.default_rng(19)
    xb = rng.standard_normal(2 ** 18).astype(np.float32)  # 1 MiB
    nreqs = 16

    def closed_loop(path, arena, nclients=2):
        lat = [[] for _ in range(nclients)]

        def worker(i):
            with serve.Client(path, timeout=240.0,
                              tenant=f"t{i}", arena=arena) as c:
                c.scale(xb, a=1.0)  # warm
                for r in range(nreqs):
                    t0 = time.perf_counter()
                    c.scale(xb, a=1.0 + r * 1e-6)
                    lat[i].append(time.perf_counter() - t0)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(nclients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        flat = sorted(v for l in lat for v in l)
        p50 = flat[len(flat) // 2] if flat else float("nan")
        return p50 * 1e3, (len(flat) / wall if wall else 0.0)

    for window in (0.0, 0.002, 0.01):
        for arena in (False, True):
            srv = None
            try:
                srv = serve.Server(
                    os.path.join(tmpdir,
                                 f"s{int(window * 1e4)}_{arena}.sock"),
                    batch_window=window).start()
                p50, rps = closed_loop(srv.path, arena)
                print(f"serve window={window * 1e3:5.1f} ms "
                      f"arena={'on ' if arena else 'off'}: "
                      f"p50 {p50:8.2f} ms  {rps:8.1f} req/s",
                      flush=True)
            except Exception as e:
                print(f"serve window={window} arena={arena}: FAIL "
                      f"{_errline(e)}", flush=True)
            finally:
                if srv is not None:
                    srv.stop()

    for nrep in (1, 2, 4):
        fleet = None
        try:
            with env_override(DR_TPU_SERVE_ARENA="1"):
                fleet = serve.Router(
                    os.path.join(tmpdir, f"fleet{nrep}"),
                    replicas=nrep, cpu=True,
                    batch_window=0.0).start()
            lat: list = []
            nclients = 4

            def rworker(i):
                with serve.RouterClient(fleet.paths(),
                                        tenant=f"rt{i}",
                                        timeout=240.0) as rc:
                    rc.scale(xb, a=1.0)
                    for r in range(nreqs):
                        t0 = time.perf_counter()
                        rc.scale(xb, a=1.0 + r * 1e-6)
                        lat.append(time.perf_counter() - t0)

            threads = [threading.Thread(target=rworker, args=(i,))
                       for i in range(nclients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            flat = sorted(lat)
            p50 = flat[len(flat) // 2] * 1e3 if flat else float("nan")
            print(f"serve replicas={nrep}: p50 {p50:8.2f} ms  "
                  f"{len(flat) / wall:8.1f} req/s", flush=True)
        except Exception as e:
            print(f"serve replicas={nrep}: FAIL {_errline(e)}",
                  flush=True)
        finally:
            if fleet is not None:
                fleet.stop()


if __name__ == "__main__":
    # Guarded first backend touch through the SAME degradation router
    # as bench.py and entry() (utils/resilience): a dead relay degrades
    # to a tagged CPU run instead of hanging the sweep; a wedged claim
    # raises a CLASSIFIED error within its deadline.  Before this the
    # tool had no guard at all and a wedged relay ate the session.
    from dr_tpu.utils import resilience as _resilience
    try:
        from dr_tpu.utils.env import env_float
        _devs, _degraded = _resilience.first_touch_or_cpu(
            env_float("DR_TPU_TUNE_INIT_TIMEOUT", 420.0),
            tag="tune_tpu")
    except _resilience.ResilienceError as e:
        print(f"tune_tpu: device init failed "
              f"({type(e).__name__}: {e}); aborting sweep", flush=True)
        sys.exit(1)
    if _degraded:
        print(f"tune_tpu: DEGRADED run ({_degraded}) — numbers below "
              "are CPU-bound, not TPU tuning data", flush=True)

    # several modes may share ONE process (= one relay claim):
    # `tune_tpu.py halo attn sort` runs all three back to back
    whats = sys.argv[1:] or ["all"]
    for what in whats:
        if what in ("stencil", "all"):
            tune_stencil()
        if what in ("physbw", "all"):
            tune_physbw()
        if what in ("scan", "all"):
            tune_scan()
        if what in ("sort", "all"):
            tune_sort()
        if what in ("kernels", "all"):
            tune_kernels()
        if what in ("pipeline", "all"):
            tune_pipeline()
        if what in ("relational", "all"):
            tune_relational()
        if what in ("redistribute", "all"):
            tune_redistribute()
        if what in ("serve", "all"):
            tune_serve()
        for nm in ("dot", "heat", "attn", "halo", "spmv"):
            if what in (nm, "all"):
                tune_container(nm)
