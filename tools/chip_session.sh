#!/bin/bash
# Round-3 on-chip session (historical; superseded by chip_session2.sh).
# Sweep bars and dispositions are recorded in docs/PERF.md.
# One TPU process at a time; 5-minute gaps between claims (the round-3
# second outage followed a 90 s gap — docs/ROUND3_NOTES.md).
set -u
cd "$(dirname "$0")/.."
log() { echo "[chip_session $(date +%H:%M:%S)] $*"; }

log "1/7 bench.py (the BENCH_r03 artifact rehearsal)"
python -u bench.py > tools/bench_r3_dev.json 2> tools/bench_r3_dev.err
log "bench exit=$? $(tail -c 300 tools/bench_r3_dev.json)"
sleep 300

log "2/7 attn sweep (ends with the streaming-kernel hardware compile)"
python -u tools/tune_tpu.py attn > tools/tune_attn.log 2>&1
log "attn exit=$?"
sleep 300

log "3/7 spmv (BCSR GFLOP/s)"
python -u tools/tune_tpu.py spmv > tools/tune_spmv.log 2>&1
log "spmv exit=$?"
sleep 300

log "4/7 dot (XLA vs pallas kernel)"
python -u tools/tune_tpu.py dot > tools/tune_dot.log 2>&1
log "dot exit=$?"
sleep 300

log "5/7 heat (time blocks)"
python -u tools/tune_tpu.py heat > tools/tune_heat.log 2>&1
log "heat exit=$?"
sleep 300

log "6/7 scan (grid-vs-manual A/B + carry-seeded path)"
python -u tools/tune_tpu.py scan > tools/tune_scan5.log 2>&1
log "scan exit=$?"
sleep 300

log "7/8 stencil at DEFAULT precision (phys bar)"
DR_TPU_MM_PRECISION=default python -u tools/tune_tpu.py stencil \
  > tools/tune_stencil_default.log 2>&1
log "stencil-default exit=$?"
sleep 300

log "8/8 physbw (VPU blocked kernel at small T)"
python -u tools/tune_tpu.py physbw > tools/tune_physbw.log 2>&1
log "physbw exit=$?"
log "session complete"
