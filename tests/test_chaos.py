"""Chaos sweep over the fault-injection registry (ISSUE 2 acceptance):
for EVERY registered injection site, each supported fault class must
yield either a CLASSIFIED exception or a successful degraded run within
its deadline — zero hangs, zero unclassified tracebacks.

Driven standalone by ``tools/fuzz_crank.sh``'s chaos arm
(``DR_TPU_CHAOS_ROUNDS`` cranks repetitions); in the tier-1 suite each
(site, kind) combo runs once.  The battery is the sort/scan/halo fuzz
programs plus checkpoint IO and the probe/init path — small shapes, so
programs compile once and the sweep stays cheap on the 8-device CPU
mesh.
"""

import os

import numpy as np
import pytest

import dr_tpu
from dr_tpu.utils import fallback, faults, resilience
from dr_tpu.utils.env import env_float, env_int

ROUNDS = env_int("DR_TPU_CHAOS_ROUNDS", 1, floor=0)  # 0 = skip the sweep
DEADLINE = env_float("DR_TPU_CHAOS_DEADLINE", 180.0)


def _half(x):
    return x * 0.5


def _battery(tmpdir: str, tag: str) -> None:
    """One pass through the programs the resilience layer protects,
    visiting EVERY registered injection site (asserted by
    test_battery_reaches_every_site): probe -> init -> dispatch cache ->
    halo exchange/reduce -> collectives shift/alltoall -> sort -> scan
    -> deferred-plan flush -> serving daemon (accept/request/flush) ->
    relational join/groupby/top_k/histogram (round 14) ->
    collective redistribute (round 16: redistribute.exchange fires at
    the engine dispatch) -> checkpoint write/read -> fallback.warn ->
    elastic shrink
    (device.lost rides every dispatch tap; mesh.shrink fires inside
    the rescue) -> elastic grow-back (round 15: device.recover fires
    at the recovery probe, mesh.grow inside the re-admission)."""
    from dr_tpu.parallel.runtime import probe_devices
    devs, err = probe_devices(30.0)
    if err is not None:
        raise resilience.classified(err, site="runtime.probe")
    dr_tpu.init(devs)
    P = dr_tpu.nprocs()
    n = 16 * P
    rng = np.random.default_rng(7)
    src = rng.standard_normal(n).astype(np.float32)

    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    v = dr_tpu.distributed_vector.from_array(src, halo=hb)
    h = dr_tpu.halo(v)
    h.exchange()
    h.reduce_plus()

    comm = dr_tpu.default_comm()
    comm.shift_forward(v._data, periodic=True)
    comm.alltoall(comm.scatter(np.zeros((P, P, 4), np.float32)))

    # ring-scheduled SpMV (round 9): the collectives.ppermute site fires
    # at the ring dispatcher.  Columns spread one-per-block so the ring
    # bucket gate admits the layout; format forced so the autoselect
    # cannot route around the site.
    gm = 8 * P
    gbw = -(-gm // P)
    grows = np.repeat(np.arange(gm), 2)
    gcols = np.minimum(np.tile(np.arange(2), gm) * gbw
                       + rng.integers(0, gbw, 2 * gm), gm - 1)
    gvals = rng.standard_normal(2 * gm).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo((gm, gm), grows, gcols, gvals)
    gc = dr_tpu.distributed_vector(gm)
    dr_tpu.fill(gc, 0.0)
    from dr_tpu.utils.env import env_override
    with env_override(DR_TPU_SPMV_FORMAT="ring"):
        assert A.ensure_ring(), "battery ring matrix must be eligible"
        dr_tpu.gemv(gc, A, np.ones(gm, np.float32))
    ref = np.zeros((gm,), np.float64)
    np.add.at(ref, grows, gvals.astype(np.float64)
              * np.ones(gm)[gcols])
    np.testing.assert_allclose(dr_tpu.to_numpy(gc), ref, rtol=1e-4,
                               atol=1e-5)

    sv = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort(sv)
    got = dr_tpu.to_numpy(sv)
    np.testing.assert_array_equal(got, np.sort(src))

    out = dr_tpu.distributed_vector(n)
    dr_tpu.inclusive_scan(dr_tpu.distributed_vector.from_array(src), out)
    np.testing.assert_allclose(dr_tpu.to_numpy(out),
                               np.cumsum(src, dtype=np.float32),
                               rtol=1e-4, atol=1e-5)

    # deferred-plan flush (round 8): the plan.flush site fires at the
    # region-exit flush boundary; a fault there must surface classified
    # with the container untouched — never a hang
    pv = dr_tpu.distributed_vector.from_array(src)
    with dr_tpu.deferred():
        dr_tpu.fill(pv, 2.0)
        dr_tpu.for_each(pv, _half)
        tot = dr_tpu.reduce(pv)
    assert abs(float(tot) - n) < 1e-3

    # serving daemon (round 11): serve.accept fires per accepted
    # connection, serve.request per compute-request intake, serve.flush
    # inside the retried batch body.  A fault must surface CLASSIFIED
    # at the client (transients recover on the in-process retry leg,
    # relay_down degrades the resident claim to the CPU route and the
    # leg still SUCCEEDS) — the daemon itself never dies and never
    # hangs the battery.  The data-plane legs (docs/SPEC.md §19) ride
    # the same daemon: a payload above the arena floor drives
    # arena.map (lease + map) and arena.release (the intake-side slot
    # recycle) — an arena fault either serializes classified or the
    # client falls back to the inline wire and the request still
    # SUCCEEDS; a RouterClient lookup drives router.route.
    from dr_tpu import serve
    ssrv = serve.Server(os.path.join(tmpdir, f"chaos_{tag}.sock"),
                        batch_window=0.0,
                        state_dir=os.path.join(tmpdir, f"state_{tag}"))
    s2 = None
    try:
        ssrv.start()  # serve.journal fires at the (empty) replay
        with serve.Client(ssrv.path, timeout=60.0) as sc:
            sx = src[:8 * P].copy()
            np.testing.assert_allclose(sc.scale(sx, a=2.0, b=1.0),
                                       sx * 2.0 + 1.0, rtol=1e-6)
            assert abs(sc.reduce(np.ones(4 * P, np.float32)) - 4 * P) \
                < 1e-3
            # arena leg: a payload above DR_TPU_SERVE_ARENA_MIN_BYTES
            # stages through shared memory (alloc+map+release fire);
            # an exhaustion/fault falls back to the inline wire
            ax = np.arange(
                env_int("DR_TPU_SERVE_ARENA_MIN_BYTES", 1 << 16) // 4
                + 8, dtype=np.float32)
            np.testing.assert_allclose(sc.scale(ax, a=0.5),
                                       ax * 0.5, rtol=1e-6)
            # journal leg (SPEC §20.4): put/drop append durable
            # records (serve.journal fires per append; a faulted
            # append degrades durability warned, the request SUCCEEDS)
            sc.put("chaos", sx)
            assert abs(sc.reduce(serve.Ref("chaos")) - sx.sum()) < 1e-2
            sc.drop("chaos")
        # control-plane leg (SPEC §20): a second replica drains
        # gracefully (serve.drain fires), its tenant re-hashes onto
        # the survivor with no client-visible error, and — once a
        # fresh daemon holds the socket again — the open breaker's
        # half-open probe (router.probe fires) re-admits it.
        s2 = serve.Server(os.path.join(tmpdir, f"chaos2_{tag}.sock"),
                          batch_window=0.0).start()
        with env_override(DR_TPU_SERVE_PROBE_S="0.0"):
            with serve.RouterClient([ssrv.path, s2.path],
                                    timeout=60.0) as rc:
                # router leg: the consistent-hash lookup (router.route
                # fires before the replica is touched)
                assert abs(rc.reduce(np.ones(4 * P, np.float32))
                           - 4 * P) < 1e-3
                t2 = next(t for t in (f"t{i}" for i in range(64))
                          if rc.route(t) == s2.path)
                s2.drain()
                # the drained replica's tenant re-hashes and SUCCEEDS
                assert abs(rc.reduce(np.ones(2 * P, np.float32),
                                     tenant=t2) - 2 * P) < 1e-3
                # restart the replica; the due probe re-admits it
                s2 = serve.Server(s2.path, batch_window=0.0).start()
                assert abs(rc.reduce(np.ones(P, np.float32),
                                     tenant=t2) - P) < 1e-3
                assert s2.path in rc.live_replicas() or \
                    rc.breaker_states().get(s2.path) == "open"
    finally:
        if s2 is not None:
            s2.stop()
        ssrv.stop()

    # relational composite (round 14): join -> groupby -> top_k over a
    # tiny table rides the same dispatch taps (dispatch.cache /
    # device.lost fire on every cached program) — a fault anywhere in
    # the sort-scratch, merge, or fused-flush path must surface
    # classified or degrade clean, like every other leg
    rn = 8 * P
    rkeys = rng.integers(0, 4, rn).astype(np.float32)
    rvals = rng.standard_normal(rn).astype(np.float32)
    rkv = dr_tpu.distributed_vector.from_array(rkeys)
    rvv = dr_tpu.distributed_vector.from_array(rvals)
    jcap = rn * rn  # self-join worst case
    jk = dr_tpu.distributed_vector(jcap)
    jl = dr_tpu.distributed_vector(jcap)
    jr = dr_tpu.distributed_vector(jcap)
    jm = dr_tpu.join(rkv, rvv, rkv, rvv, jk, jl, jr)
    import pandas as pd
    jref = pd.merge(pd.DataFrame({"k": rkeys, "a": rvals}),
                    pd.DataFrame({"k": rkeys, "b": rvals}), on="k")
    assert jm == len(jref), (jm, len(jref))
    gk = dr_tpu.distributed_vector(rn)
    gv = dr_tpu.distributed_vector(rn)
    ngr = dr_tpu.groupby_aggregate(rkv, rvv, gk, gv, agg="sum")
    gref = pd.DataFrame({"k": rkeys, "v": rvals}).groupby("k")["v"] \
        .sum()
    assert ngr == len(gref)
    np.testing.assert_allclose(dr_tpu.to_numpy(gv)[:ngr],
                               gref.values.astype(np.float32),
                               rtol=1e-4, atol=1e-5)
    with dr_tpu.deferred():  # fusible leg through the plan.flush site
        tk = dr_tpu.distributed_vector(3)
        dr_tpu.top_k(rvv, tk)
        hh = dr_tpu.distributed_vector(4, np.int32)
        dr_tpu.histogram(rvv, hh, -2.0, 2.0)
    np.testing.assert_allclose(dr_tpu.to_numpy(tk),
                               np.sort(rvals)[::-1][:3])

    # redistribute leg (round 16, docs/SPEC.md §18): the collective
    # re-layout engine — same mesh, so the autoselect takes the
    # device-side exchange program and redistribute.exchange fires at
    # its dispatch (before the program-cache lookup: a fault here must
    # surface classified with the vector EXACTLY as it was).  Team ->
    # uneven -> even hops so the offset-permute planner emits real
    # buckets, value bit-equal throughout.
    rdv = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.redistribute(rdv, [n] + [0] * (P - 1))
    dr_tpu.redistribute(rdv, [1] * (P - 1) + [n - (P - 1)])
    dr_tpu.redistribute(rdv, None)
    np.testing.assert_array_equal(dr_tpu.to_numpy(rdv), src)

    ck = os.path.join(tmpdir, f"chaos_{tag}.npz")
    dr_tpu.checkpoint.save(ck, dr_tpu.distributed_vector.from_array(src))
    back = dr_tpu.checkpoint.load(ck)
    np.testing.assert_allclose(np.asarray(back.materialize()), src,
                               rtol=1e-6)

    fallback.warn_fallback("chaos", "battery sweep")

    # elastic leg (round 13, LAST — it shrinks the mesh): a simulated
    # device loss must shrink the session and rescue live state
    # (docs/SPEC.md §16).  mesh.shrink fires inside the rescue;
    # device.lost rides every dispatch tap above, so both new sites
    # are visited.  A team vector dodging the dead rank is RESCUED
    # bit-equal; an uncheckpointed full-span vector is LOST and must
    # raise classified, never answer wrong.
    from dr_tpu.utils import elastic
    esrc = src[:4 * P]
    team = dr_tpu.distributed_vector.from_array(
        esrc, distribution=[len(esrc)] + [0] * (P - 1))
    gone = dr_tpu.distributed_vector.from_array(esrc)
    er = elastic.rescue_session(
        resilience.DeviceLostError("battery: simulated device loss",
                                   rank=P - 1))
    assert er.nprocs_after == P - 1 and dr_tpu.nprocs() == P - 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(team), esrc)
    try:
        dr_tpu.to_numpy(gone)
        raise AssertionError("lost container must raise classified")
    except resilience.DeviceLostError:
        pass

    # grow-back leg (round 15, docs/SPEC.md §16.6): the lost rank
    # "returns" — device.recover fires at the recovery probe,
    # mesh.grow inside grow_session.  Rescued state must ride the
    # re-admission bit-equal, and the poisoned container must STAY
    # classified — a grow never resurrects lost state as a silent
    # wrong answer.
    gr = elastic.grow_session(reason="battery: lost rank returned")
    assert gr.nprocs_after == P and dr_tpu.nprocs() == P
    np.testing.assert_array_equal(dr_tpu.to_numpy(team), esrc)
    try:
        dr_tpu.to_numpy(gone)
        raise AssertionError("poisoned container must stay classified "
                             "across a grow")
    except resilience.DeviceLostError:
        pass


def _combos():
    return [(site, kind) for site, kinds in sorted(faults.sites().items())
            for kind in kinds]


#: first hang seen — later combos skip instead of interleaving with the
#: orphaned battery thread still running against the shared mesh (the
#: spurious follow-on failures would bury the one-line hang signal)
_hang_seen: list = []


@pytest.mark.parametrize("site,kind", _combos())
def test_chaos_every_site_and_kind(site, kind, tmp_path):
    """Inject one fault at (site, kind); the battery must finish clean
    (degraded-but-correct) or die with a CLASSIFIED error — within the
    deadline either way.  An unclassified traceback or a hang is the
    bug this sweep exists to catch."""
    if _hang_seen:
        pytest.skip(f"prior hang at {_hang_seen[0]}: its orphaned "
                    "battery thread may still interleave")
    for r in range(ROUNDS):
        with faults.injected(site, kind, times=1) as sp:
            try:
                resilience.with_deadline(
                    lambda: _battery(str(tmp_path), f"{r}"),
                    DEADLINE, site=f"chaos:{site}:{kind}", dump=False)
            except resilience.DeadlineExpired:
                _hang_seen.append(f"{site}:{kind}")
                raise AssertionError(
                    f"HANG: {site}:{kind} exceeded the {DEADLINE}s "
                    "chaos deadline")
            except resilience.ResilienceError:
                pass  # classified failure: an acceptable outcome
            # (any OTHER exception propagates = unclassified = failure)
            assert sp.fired == 1, \
                f"battery never reached site {site} (vacuous sweep)"


def test_battery_reaches_every_site(tmp_path):
    """Coverage guard for the sweep itself: the battery must VISIT every
    registered site, else a combo above could pass without testing
    anything (and fallback.warn — counting-only — is asserted here)."""
    faults.clear()
    faults.arm_counting()
    _battery(str(tmp_path), "coverage")
    visits = faults.stats()
    missing = [s for s in faults.sites() if visits.get(s, 0) == 0]
    assert not missing, f"battery misses injection sites: {missing}"


def test_transient_retry_recovers_midstream(tmp_path):
    """Acceptance: a transient fault inside the battery recovers via
    retry() IN PROCESS — no re-exec, same mesh, correct results."""
    with faults.injected("halo.exchange", "transient", times=1) as sp:
        resilience.retry(lambda: _battery(str(tmp_path), "retry"),
                         attempts=2, sleep=lambda s: None)
        assert sp.fired == 1
