"""The driver artifact contract: bench.py must print ONE JSON line with
the agreed keys whatever the backend state (the round's BENCH_r{N}.json
is produced by exactly this invocation)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_bench_json_contract():
    env = {k: v for k, v in os.environ.items()
           # drop the suite's own platform/mesh env so the child's
           # configuration is the test's, not conftest's
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update({
        "DR_TPU_BENCH_N": str(2 ** 18),
        "DR_TPU_BENCH_STEPS": "8",
        "DR_TPU_BENCH_INIT_TIMEOUT": "30",
        "DR_TPU_BENCH_SECONDARY": "1",
    })
    # Force the child onto CPU BEFORE any backend init (the env var
    # alone is frozen by site customization on the axon box, and a
    # child that claimed the real TPU could be killed mid-compile by
    # the subprocess timeout — the exact kill the relay postmortems
    # forbid).  The degraded TPU->CPU re-exec branch is exercised
    # separately on the real box (docs/ROUND3_NOTES.md); this test
    # pins the JSON contract itself.
    code = ("import jax, runpy; "
            "jax.config.update('jax_platforms', 'cpu'); "
            "runpy.run_path('bench.py', run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line, got: {out.stdout!r}"
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "detail"):
        assert key in rec, f"missing {key}"
    assert rec["metric"] == "stencil1d_5pt_effective_bandwidth_per_chip"
    assert rec["unit"] == "GB/s"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    d = rec["detail"]
    for key in ("n", "steps", "impl", "device", "peak_hbm_gbps",
                "phys_gbps", "target_gbps"):
        assert key in d, f"missing detail.{key}"
    # secondary configs must each report a number or a tagged error
    for cfg in ("dot", "scan", "heat2d", "spmv", "sort"):
        assert any(k.startswith(cfg) for k in d), f"no {cfg} field"
    # the sort phase breakdown (round 6) rides the sort config: either
    # the ladder (p>1), the honest p=1 collapse, or its own tagged
    # error (independently guarded like every config)
    if "sort_gbps" in d:
        assert "sort_phases_gbps" in d or "sort_phases_error" in d, \
            "missing detail.sort_phases_gbps"
        if "sort_phases_gbps" in d:
            assert "sort_phase_dominant" in d
            assert all(vv >= 0
                       for vv in d["sort_phases_gbps"].values())
    # round 8: the deferred-pipeline config and the tap dispatch counts
    # ride every artifact (ISSUE 3 acceptance: the CPU-fallback bench
    # emits pipeline_gbps + dispatch_counts)
    # round 9: the sparse family's phase breakdown + chosen-format tag
    # ride every artifact (either the ring ladder, the honest
    # p=1/ring-ineligible collapse, or a tagged error)
    if "spmv_gflops" in d:
        assert "spmv_format" in d and d["spmv_format"] in (
            "csr", "ell", "bcsr", "ring"), "missing detail.spmv_format"
        assert "spmv_phases_gflops" in d or "spmv_phases_error" in d, \
            "missing detail.spmv_phases_gflops"
        if "spmv_phases_gflops" in d:
            assert "spmv_phase_dominant" in d
            assert all(vv >= 0
                       for vv in d["spmv_phases_gflops"].values())
    if "spmm8_gflops" in d:
        assert "spmm_format" in d
    assert "pipeline_gbps" in d or "pipeline_error" in d, \
        "missing detail.pipeline_gbps"
    assert "dispatch_counts" in d
    dc = d["dispatch_counts"]
    assert dc.get("headline_timed_run", 0) >= 1
    if "pipeline_gbps" in d:
        assert set(d["pipeline_gbps"]) == {"eager", "deferred"}
        assert all(v > 0 for v in d["pipeline_gbps"].values())
        # the whole point: a deferred chain costs (far) fewer dispatches
        assert dc["pipeline_chain_deferred"] < dc["pipeline_chain_eager"]
        assert dc["pipeline_chain_deferred"] <= 2
