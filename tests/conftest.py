"""Test harness: fake an 8-device TPU mesh on CPU.

The reference tests multi-device behavior without a cluster by duplicating
real devices (``test/gtest/shp/shp-tests.cpp:34-39``) and by running the
same gtest binary under mpiexec at 1-4 ranks (``test/gtest/mhp/
CMakeLists.txt:27-33``).  The JAX analog is
``--xla_force_host_platform_device_count``: one process, N virtual CPU
devices, identical SPMD semantics.  Parametrized fixtures re-run suites at
several mesh sizes, mirroring the reference's rank sweep.
"""

import os

# XLA flags are read at (lazy) backend init, so setting them here is early
# enough even if jax was already imported by site customization.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
# fallback-path tests exercise materialize routes on purpose; the
# loud-once warning stays covered by test_fallbacks_warn_once, which
# clears this
os.environ.setdefault("DR_TPU_SILENCE_FALLBACKS", "1")

import jax  # noqa: E402

# The environment may have imported jax already (e.g. a TPU plugin's
# sitecustomize), freezing JAX_PLATFORMS from env — override via config.
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402

import dr_tpu  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_runtime():
    """Every test starts with a full 8-device mesh runtime."""
    dr_tpu.init()
    yield
    dr_tpu.final()


@pytest.fixture(autouse=True)
def _sanitize_epoch():
    """DR_TPU_SANITIZE=1 (docs/SPEC.md §13.4): every test is its own
    recompile-counting epoch — a canonical program compiling more than
    the per-epoch budget inside one test is the value-keyed recompile
    storm drlint's R1 flags statically.  Canon-portability of every
    dispatch key is checked by the armed insert hook as the test runs;
    unarmed, this fixture is a no-op."""
    from dr_tpu.utils import sanitize
    if not sanitize.installed():
        yield
        return
    sanitize.reset_epoch()
    yield
    sanitize.check_recompiles()


@pytest.fixture(autouse=True)
def _disarm_faults():
    """A leaked fault injection (utils/faults) must not outlive its
    test: the next test's dr_tpu.init() would trip it.  reload_env()
    (not clear()) so a suite deliberately run under DR_TPU_FAULT_SPEC /
    DR_TPU_FAULT_COUNT keeps its env-declared arming across tests.

    The same hygiene covers serve state (round 11): a leaked in-process
    serving daemon (tests/test_serve, the chaos battery's serve leg)
    must not keep holding its socket — and its published degradation
    markers must not bleed a 'degraded' story — into the next test.
    Lazy via sys.modules: tests that never touched dr_tpu.serve pay
    nothing.

    Elastic shrink state (round 13) gets the same treatment: a test
    that shrank the mesh must not leak its _DR_TPU_ELASTIC_* markers,
    checkpoint registry, or shrink counters into the next test (the
    _fresh_runtime fixture already restores the full 8-device mesh).

    Grow-back state (round 15) rides the same elastic.reset(): the
    _DR_TPU_ELASTIC_GROW_* markers, grow counters, and the recovery
    SUPERVISOR are all dropped — the supervisor is passive (polled
    between batches, never a thread), so disarming it here guarantees
    no probe schedule (let alone a probe thread) leaks between tests;
    serve.reset() stops any daemon whose own route supervisor could
    otherwise still be polled by a live dispatch loop.

    Control-plane state (ISSUE 14, SPEC §20) rides serve.reset() too:
    spawned Router fleets stop (a leaked respawn supervisor must not
    keep resurrecting daemon subprocesses into the next test), the
    shared retry token budget drops (re-read from env lazily), and
    the resident-state journal files this process touched are
    unlinked — one test's durable residents must not replay into the
    next test's daemon."""
    yield
    from dr_tpu.utils import elastic, faults
    faults.reload_env()
    elastic.reset()
    import sys as _sys
    serve = _sys.modules.get("dr_tpu.serve")
    if serve is not None:
        serve.reset()


@pytest.fixture(autouse=True)
def _clear_tuning_knobs(monkeypatch):
    """Tests run at the DEFAULT kernel configuration: an ambient tuning
    sweep's env (tools/tune_tpu.py exports these) must not shift chunk
    sizes, tiles, or variants under geometry-sensitive assertions."""
    for var in ("DR_TPU_SCAN_CHUNK", "DR_TPU_SCAN_KERNEL",
                "DR_TPU_SCAN_PIPE", "DR_TPU_SCAN_PASSES",
                "DR_TPU_MM_CHUNK_CAP", "DR_TPU_MM_BAND_COLS",
                "DR_TPU_FLASH_BQ", "DR_TPU_FLASH_BK",
                "DR_TPU_FLASH_STREAM", "DR_TPU_MM_PRECISION",
                "DR_TPU_GATHER_W", "DR_TPU_DOT_IMPL",
                "DR_TPU_SORT_STABLE",
                "DR_TPU_SORT_LOCAL", "DR_TPU_SEGRED_IMPL",
                "DR_TPU_HIST_IMPL", "DR_TPU_SCAN_IMPL",
                "DR_TPU_PLAN_OPT", "DR_TPU_PLAN_OPT_DISABLE",
                "DR_TPU_TUNING_DB"):
        monkeypatch.delenv(var, raising=False)
    yield
    # the persisted tuning DB's in-process overlay (a noted capacity
    # ratio, a recorded sweep winner) must not shift the NEXT test's
    # picked configs — same hygiene as the env knobs above
    from dr_tpu import tuning
    tuning.clear_session()
    tuning.reload()


def pytest_collection_modifyitems(config, items):
    """``kernel_interpret``-marked tests run Pallas kernels in interpret
    mode at crank depth (the unrolled bitonic network traces slowly on
    CPU): promote them to ``slow`` so tier-1's ``-m 'not slow'`` keeps
    its budget while ``tools/fuzz_crank.sh`` (unfiltered) still runs
    them."""
    for item in items:
        if item.get_closest_marker("kernel_interpret") is not None:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(params=[1, 2, 3, 4, 8])
def mesh_size(request):
    """Rank sweep, mirroring the reference's mpiexec -n {1,2,3,4} runs.
    Skips sizes beyond the host's (virtual) device count, so the suite
    stays valid under any --xla_force_host_platform_device_count."""
    n = request.param
    if n > len(jax.devices()):
        pytest.skip(f"host exposes {len(jax.devices())} devices < {n}")
    dr_tpu.init(jax.devices()[:n])
    return n


# ---------------------------------------------------------------------------
# Oracle helpers (reference test/gtest/include/common-tests.hpp)
# ---------------------------------------------------------------------------

def check_segments(r):
    """join(segments(r)) == r elementwise (common-tests.hpp:31-50)."""
    segs = dr_tpu.segments(r)
    joined = np.concatenate([np.asarray(s.materialize()) for s in segs]) \
        if segs else np.array([])
    ref = np.asarray(dr_tpu.to_numpy(r))
    np.testing.assert_allclose(joined, ref, rtol=1e-6)
    # segments tile the range in order without gaps or overlap
    assert sum(len(s) for s in segs) == len(r)
    for a, b in zip(segs, segs[1:]):
        if hasattr(a, "end") and hasattr(b, "begin"):
            assert a.end == b.begin


def equal(r, expected):
    """Distributed result vs serial reference (common-tests.hpp:52-81)."""
    np.testing.assert_allclose(np.asarray(dr_tpu.to_numpy(r)),
                               np.asarray(expected), rtol=1e-5, atol=1e-6)


@pytest.fixture
def oracle():
    class _O:
        check_segments = staticmethod(check_segments)
        equal = staticmethod(equal)
    return _O
