"""Worker for the multi-process (MHP-dimension) smoke test.

Each process initializes jax.distributed, joins the global mesh, and runs
the same collective program — the SPMD discipline of the reference's
MPI backend (every rank calls every collective in the same order).
Usage: python multihost_worker.py <pid> <nproc> <port>
"""

import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

import jax

jax.config.update("jax_platforms", "cpu")

import dr_tpu  # noqa: E402
import numpy as np  # noqa: E402

dr_tpu.init_distributed(f"localhost:{port}", nproc, pid)
assert dr_tpu.nprocs() == nproc

n = 4 * nproc
dv = dr_tpu.distributed_vector(n, dtype=np.float32)
dr_tpu.iota(dv, 1)

total = dr_tpu.reduce(dv)
assert total == n * (n + 1) / 2, total

out = dr_tpu.distributed_vector(n)
dr_tpu.inclusive_scan(dv, out)
got = dr_tpu.to_numpy(out)
np.testing.assert_allclose(got, np.cumsum(np.arange(1, n + 1)), rtol=1e-5)

hb = dr_tpu.halo_bounds(1, 1, periodic=True)
sv = dr_tpu.distributed_vector(n, dtype=np.float32, halo=hb)
w = dr_tpu.distributed_vector(n, dtype=np.float32, halo=hb)
src = np.arange(n, dtype=np.float32)
sv.assign_array(src)
w.assign_array(src)
res = dr_tpu.stencil_iterate(sv, w, [0.25, 0.5, 0.25], steps=2)
vals = dr_tpu.to_numpy(res)
assert np.isfinite(vals).all()

# iteration and matrix materialization must also be valid on every process
assert list(dv)[0] == 1.0
mat = dr_tpu.dense_matrix((2 * nproc, 3), dtype=np.float32,
                          partition=dr_tpu.row_tiles())
m_host = mat.materialize()
assert m_host.shape == (2 * nproc, 3)

# fused zip|transform|reduce dot (single-pass multi-chain program)
other = dr_tpu.distributed_vector(n, dtype=np.float32)
dr_tpu.fill(other, 2.0)
d = dr_tpu.dot(dv, other)
assert d == 2.0 * total, d

# halo exchange + ghost->owner reduction across process boundaries
sv.halo().exchange()
sv.halo().reduce_plus()
sv.block_until_ready()

# SpMV: multi-process runs must stay on the sharded segment_sum path
# (the ELL regroup needs fully-addressable shards)
m = 2 * nproc
rows = np.arange(m, dtype=np.int64)
cols = np.zeros(m, dtype=np.int64)
vals = np.ones(m, dtype=np.float32)
A = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols, vals)
if nproc > 1:  # single-process shards are addressable; ELL is fine there
    assert not A.ensure_ell()
c = dr_tpu.distributed_vector(m, dtype=np.float32)
bv = dr_tpu.distributed_vector(m, dtype=np.float32)
dr_tpu.fill(bv, 3.0)
dr_tpu.fill(c, 0.0)
dr_tpu.gemv(c, A, bv)
np.testing.assert_allclose(dr_tpu.to_numpy(c), np.full(m, 3.0), rtol=1e-6)

# multi-vector SpMM (round 4): each row of A holds a single 1, so the
# product replicates B's rows — valid on every process
Bmm = np.tile(np.array([1.0, 2.0], np.float32), (m, 1))
Ymm = np.asarray(dr_tpu.spmm(A, Bmm))
np.testing.assert_allclose(Ymm, Bmm, rtol=1e-6)

# fused measurement family must be SPMD-safe (every process runs the
# same chained program; psum keeps results identical everywhere)
from dr_tpu.algorithms.reduce import dot_n  # noqa: E402
dn = float(dot_n(dv, other, 3))
assert abs(dn - d) < 1e-3 * abs(d), (dn, d)

from dr_tpu.algorithms.scan import inclusive_scan_n  # noqa: E402
sn = dr_tpu.distributed_vector(n, dtype=np.float32)
inclusive_scan_n(dv, sn, 1)
np.testing.assert_allclose(dr_tpu.to_numpy(sn),
                           np.cumsum(np.arange(1, n + 1)), rtol=1e-5)

# ring attention over the two-process mesh (XLA path on CPU)
rng = np.random.default_rng(3)
S, h, hd = 4 * nproc, 2, 8
q, k2, v2 = (rng.standard_normal((1, S, h, hd)).astype(np.float32)
             for _ in range(3))
att1 = dr_tpu.ring_attention(q, k2, v2, causal=True)
attn = dr_tpu.ring_attention_n(q, k2, v2, 1, causal=True)
# global arrays span both processes: compare the LOCAL shards
np.testing.assert_allclose(
    np.asarray(attn.addressable_shards[0].data),
    np.asarray(att1.addressable_shards[0].data), rtol=1e-5, atol=1e-6)

# checkpoint across processes: save() is collective (materialization
# gathers), only process 0 writes, load() re-shards on every process
import tempfile  # noqa: E402
# the rendezvous port is unique per run and SHARED by all ranks (a
# pid would differ per rank) — concurrent suites can't race the file
ck = f"{tempfile.gettempdir()}/dr_tpu_mh_ckpt_{port}_{nproc}.npz"
dr_tpu.checkpoint.save(ck, dv)
# no explicit barrier: save()'s OWN contract is that the write has
# landed on every process's view when it returns — this load tests it
lv = dr_tpu.checkpoint.load(ck)
np.testing.assert_allclose(dr_tpu.to_numpy(lv), np.arange(1, n + 1))
dr_tpu.barrier()  # all loads done before rank 0 removes the file
if pid == 0:
    import os as _os
    _os.remove(ck)

# SPMD dispatch-order guard: both processes ran the same collective
# sequence above — verify() must agree (and is itself collective)
from dr_tpu.utils import spmd_guard  # noqa: E402
with spmd_guard.guard() as _g:
    _gv = dr_tpu.distributed_vector(n)
    dr_tpu.iota(_gv, 0)
    dr_tpu.fill(_gv, 1.0)
    dr_tpu.dot(_gv, _gv)
    _g.verify()
assert len(_g.trace) >= 3

# communicator gather/allgather must be valid on EVERY process: the
# global logical array is not fully addressable here, so this exercises
# the process_allgather route (utils/host.to_host) — np.asarray alone
# raises on non-addressable shards (VERDICT r2 weak item 5)
comm = dr_tpu.default_comm()
g = comm.allgather(dv.to_array())
np.testing.assert_allclose(g, np.arange(1, n + 1))

# distributed sample sort: the all_to_all bucket exchange crosses the
# process boundary (every process runs the same collective program)
srt_src = np.asarray(
    np.random.default_rng(7).standard_normal(n), dtype=np.float32)
srt = dr_tpu.distributed_vector(n, dtype=np.float32)
srt.assign_array(srt_src)
dr_tpu.sort(srt)
np.testing.assert_allclose(dr_tpu.to_numpy(srt), np.sort(srt_src),
                           rtol=0, atol=0)
srt_pay = np.arange(n, dtype=np.float32)
srt_k = dr_tpu.distributed_vector(n, dtype=np.float32)
srt_k.assign_array(srt_src)
srt_v = dr_tpu.distributed_vector(n, dtype=np.float32)
srt_v.assign_array(srt_pay)
dr_tpu.sort_by_key(srt_k, srt_v)
np.testing.assert_allclose(
    dr_tpu.to_numpy(srt_v),
    srt_pay[np.argsort(srt_src, kind="stable")], rtol=0, atol=0)

# uneven block distribution ACROSS PROCESSES (one shard per process,
# different sizes): scan and sort run their native geometry-general
# programs over the DCN mesh
usizes = [3 + 2 * r for r in range(nproc)]
un = sum(usizes)
usrc = np.random.default_rng(17).standard_normal(un).astype(np.float32)
ud = dr_tpu.distributed_vector(un, dtype=np.float32,
                               distribution=usizes)
ud.assign_array(usrc)
us = dr_tpu.distributed_vector(un, dtype=np.float32,
                               distribution=usizes)
dr_tpu.inclusive_scan(ud, us)
np.testing.assert_allclose(dr_tpu.to_numpy(us), np.cumsum(usrc),
                           rtol=1e-4)
dr_tpu.sort(ud)
np.testing.assert_allclose(dr_tpu.to_numpy(ud), np.sort(usrc),
                           rtol=0, atol=0)
assert dr_tpu.is_sorted(ud)

# 2-D matrix op across processes: mdarray transpose (all-to-all route)
src2 = np.arange(4 * nproc * 8, dtype=np.float32).reshape(4 * nproc, 8)
M = dr_tpu.distributed_mdarray.from_array(src2)
T = dr_tpu.distributed_mdarray((8, 4 * nproc))
dr_tpu.transpose(T, M)
np.testing.assert_allclose(T.materialize(), src2.T)

# 2-D-partitioned sparse gemv over a (nproc, 1)->factor grid
gp, gq = dr_tpu.factor(nproc)
if gq > 1:
    d2 = np.zeros((2 * nproc, 2 * nproc), dtype=np.float32)
    d2[0, -1] = 5.0
    sp2 = dr_tpu.sparse_matrix.from_dense(
        d2, partition=dr_tpu.block_cyclic(grid=(gp, gq)))
    c2 = dr_tpu.distributed_vector(2 * nproc, dtype=np.float32)
    dr_tpu.fill(c2, 0.0)
    dr_tpu.gemv(c2, sp2, np.ones(2 * nproc, dtype=np.float32))
    np.testing.assert_allclose(dr_tpu.to_numpy(c2), d2.sum(axis=1))

# round-5 surfaces across process boundaries: windowed sort (window-
# coordinate geometry), a mismatched-window scan (the realign
# all_to_all), and an overlapping same-container sort_by_key (aliased
# payload-last blend)
r5 = np.random.default_rng(50).standard_normal(n).astype(np.float32)
wv5 = dr_tpu.distributed_vector(n, dtype=np.float32)
wv5.assign_array(r5)
wb5, we5 = 1, n - 2
dr_tpu.sort(wv5[wb5:we5])
wref5 = r5.copy()
wref5[wb5:we5] = np.sort(r5[wb5:we5])
np.testing.assert_allclose(dr_tpu.to_numpy(wv5), wref5, rtol=0, atol=0)

ms5 = dr_tpu.distributed_vector(n, dtype=np.float32)
dr_tpu.fill(ms5, 0.0)
dr_tpu.inclusive_scan(wv5[0:n - 3], ms5[3:n])
msg5 = dr_tpu.to_numpy(ms5)
np.testing.assert_allclose(msg5[3:n], np.cumsum(wref5[0:n - 3]),
                           rtol=1e-4, atol=1e-4)

ov5 = dr_tpu.distributed_vector(n, dtype=np.float32)
ov5.assign_array(r5)
ka, kb = 0, max(2, n // 2)
va, vb = max(1, n // 4), max(1, n // 4) + (kb - ka)
assert vb <= n, "overlap coverage must never silently vanish"
dr_tpu.sort_by_key(ov5[ka:kb], ov5[va:vb])
oref5 = r5.copy()
oo5 = np.argsort(r5[ka:kb], kind="stable")
oref5[ka:kb] = r5[ka:kb][oo5]
oref5[va:vb] = r5[va:vb][oo5]
np.testing.assert_allclose(dr_tpu.to_numpy(ov5), oref5, rtol=0,
                           atol=0)

print(f"MULTIHOST-OK pid={pid} reduce={total} scan_last={got[-1]}",
      flush=True)
