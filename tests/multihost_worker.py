"""Worker for the multi-process (MHP-dimension) smoke test.

Each process initializes jax.distributed, joins the global mesh, and runs
the same collective program — the SPMD discipline of the reference's
MPI backend (every rank calls every collective in the same order).
Usage: python multihost_worker.py <pid> <nproc> <port>
"""

import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

import jax

jax.config.update("jax_platforms", "cpu")

import dr_tpu  # noqa: E402
import numpy as np  # noqa: E402

dr_tpu.init_distributed(f"localhost:{port}", nproc, pid)
assert dr_tpu.nprocs() == nproc

n = 4 * nproc
dv = dr_tpu.distributed_vector(n, dtype=np.float32)
dr_tpu.iota(dv, 1)

total = dr_tpu.reduce(dv)
assert total == n * (n + 1) / 2, total

out = dr_tpu.distributed_vector(n)
dr_tpu.inclusive_scan(dv, out)
got = dr_tpu.to_numpy(out)
np.testing.assert_allclose(got, np.cumsum(np.arange(1, n + 1)), rtol=1e-5)

hb = dr_tpu.halo_bounds(1, 1, periodic=True)
sv = dr_tpu.distributed_vector(n, dtype=np.float32, halo=hb)
w = dr_tpu.distributed_vector(n, dtype=np.float32, halo=hb)
src = np.arange(n, dtype=np.float32)
sv.assign_array(src)
w.assign_array(src)
res = dr_tpu.stencil_iterate(sv, w, [0.25, 0.5, 0.25], steps=2)
vals = dr_tpu.to_numpy(res)
assert np.isfinite(vals).all()

# iteration and matrix materialization must also be valid on every process
assert list(dv)[0] == 1.0
mat = dr_tpu.dense_matrix((2 * nproc, 3), dtype=np.float32,
                          partition=dr_tpu.row_tiles())
m_host = mat.materialize()
assert m_host.shape == (2 * nproc, 3)

# fused zip|transform|reduce dot (single-pass multi-chain program)
other = dr_tpu.distributed_vector(n, dtype=np.float32)
dr_tpu.fill(other, 2.0)
d = dr_tpu.dot(dv, other)
assert d == 2.0 * total, d

# halo exchange + ghost->owner reduction across process boundaries
sv.halo().exchange()
sv.halo().reduce_plus()
sv.block_until_ready()

# SpMV: multi-process runs must stay on the sharded segment_sum path
# (the ELL regroup needs fully-addressable shards)
m = 2 * nproc
rows = np.arange(m, dtype=np.int64)
cols = np.zeros(m, dtype=np.int64)
vals = np.ones(m, dtype=np.float32)
A = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols, vals)
if nproc > 1:  # single-process shards are addressable; ELL is fine there
    assert not A.ensure_ell()
c = dr_tpu.distributed_vector(m, dtype=np.float32)
bv = dr_tpu.distributed_vector(m, dtype=np.float32)
dr_tpu.fill(bv, 3.0)
dr_tpu.fill(c, 0.0)
dr_tpu.gemv(c, A, bv)
np.testing.assert_allclose(dr_tpu.to_numpy(c), np.full(m, 3.0), rtol=1e-6)

print(f"MULTIHOST-OK pid={pid} reduce={total} scan_last={got[-1]}",
      flush=True)
