"""Fused stencil tests vs serial oracle (reference
examples/mhp/stencil-1d.cpp:21-45 — the example's built-in check())."""

import numpy as np
import pytest

import dr_tpu
from dr_tpu.algorithms.stencil import stencil_iterate, stencil_transform


def _serial_stencil(x, w, steps, periodic=False):
    r = (len(w) - 1) // 2
    x = x.astype(np.float64).copy()
    for _ in range(steps):
        if periodic:
            acc = np.zeros_like(x)
            for d in range(-r, r + 1):
                acc += np.roll(x, -d) * w[d + r]
            x = acc
        else:
            y = x.copy()
            n = len(x)
            acc = np.zeros(n - 2 * r)
            for d in range(-r, r + 1):
                acc += x[r + d: n - r + d] * w[d + r]
            y[r:n - r] = acc
            x = y
    return x


@pytest.mark.parametrize("n", [32, 61])
def test_stencil_3pt_single_step(n, mesh_size):
    if n // mesh_size == 0:
        pytest.skip("degenerate")
    w = [1 / 3, 1 / 3, 1 / 3]
    src = np.random.default_rng(0).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(1, 1)
    try:
        a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    except ValueError:
        pytest.skip("layout too small for halo")
    b = dr_tpu.distributed_vector(n, halo=hb)
    dr_tpu.copy(src, b)  # edges preserved in output
    stencil_transform(a, b, w)
    ref = _serial_stencil(src, w, 1)
    np.testing.assert_allclose(dr_tpu.to_numpy(b), ref, rtol=1e-5,
                               atol=1e-6)


def test_stencil_5pt_iterated():
    n = 96
    w = [0.1, 0.2, 0.4, 0.2, 0.1]
    src = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(2, 2)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate(a, b, w, steps=5)
    ref = _serial_stencil(src, w, 5)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-4,
                               atol=1e-5)


def test_stencil_periodic_ring():
    n = 64
    w = [0.25, 0.5, 0.25]
    src = np.random.default_rng(2).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate(a, b, w, steps=3)
    ref = _serial_stencil(src, w, 3, periodic=True)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-4,
                               atol=1e-5)


def test_stencil_periodic_short_tail():
    # last shard shorter than the others: ghost placement after valid tail
    n = 59  # 8 shards * seg 8 = 64 > 59, tail = 3 >= radius 1
    w = [0.25, 0.5, 0.25]
    src = np.random.default_rng(3).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate(a, b, w, steps=2)
    ref = _serial_stencil(src, w, 2, periodic=True)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-4,
                               atol=1e-5)


def test_stencil_nonlinear_fn():
    n = 48
    src = np.abs(np.random.default_rng(4).standard_normal(n)
                 ).astype(np.float32) + 0.1
    hb = dr_tpu.halo_bounds(1, 1)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)

    import jax.numpy as jnp

    def op(xm, x, xp):
        return jnp.sqrt(xm * xp) + x

    stencil_transform(a, b, op)
    ref = src.copy()
    ref[1:-1] = np.sqrt(src[:-2] * src[2:]) + src[1:-1]
    np.testing.assert_allclose(dr_tpu.to_numpy(b), ref, rtol=1e-5)


def test_stencil_odd_steps_returns_other_buffer():
    n = 32
    w = [0.5, 0.0, 0.5]
    src = np.random.default_rng(5).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(1, 1)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate(a, b, w, steps=3)
    ref = _serial_stencil(src, w, 3)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-4,
                               atol=1e-5)
