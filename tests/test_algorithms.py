"""Distributed algorithm tests vs serial oracle
(reference test/gtest/mhp/algorithms.cpp, test/gtest/shp/algorithms.cpp)."""

import operator

import jax.numpy as jnp
import numpy as np
import pytest

import dr_tpu
from dr_tpu import views


def test_fill(mesh_size, oracle):
    dv = dr_tpu.distributed_vector(25)
    dr_tpu.fill(dv, 3.5)
    oracle.equal(dv, np.full(25, 3.5))


def test_fill_subrange(oracle):
    dv = dr_tpu.distributed_vector(20)
    dr_tpu.fill(dv[4:9], 2.0)
    ref = np.zeros(20)
    ref[4:9] = 2.0
    oracle.equal(dv, ref)


def test_iota(mesh_size, oracle):
    dv = dr_tpu.distributed_vector(23, dtype=np.int32)
    dr_tpu.iota(dv, 10)
    oracle.equal(dv, np.arange(10, 33))


def test_iota_subrange(oracle):
    dv = dr_tpu.distributed_vector(12, dtype=np.int32)
    dr_tpu.iota(dv[3:7], 100)
    ref = np.zeros(12, dtype=np.int32)
    ref[3:7] = np.arange(100, 104)
    oracle.equal(dv, ref)


def test_copy_aligned(mesh_size, oracle):
    a = dr_tpu.distributed_vector(31)
    b = dr_tpu.distributed_vector(31)
    dr_tpu.iota(a, 0)
    dr_tpu.copy(a, b)
    oracle.equal(b, np.arange(31, dtype=np.float32))


def test_copy_host_to_distributed(oracle):
    ref = np.random.default_rng(0).standard_normal(40).astype(np.float32)
    dv = dr_tpu.distributed_vector(40)
    dr_tpu.copy(ref, dv)
    oracle.equal(dv, ref)


def test_copy_misaligned_windows(oracle):
    # shifted windows are misaligned -> XLA-reshard fallback
    a = dr_tpu.distributed_vector(20)
    b = dr_tpu.distributed_vector(20)
    dr_tpu.iota(a, 0)
    assert not dr_tpu.aligned(a[1:11], b[5:15])
    dr_tpu.copy(a[1:11], b[5:15])
    ref = np.zeros(20, dtype=np.float32)
    ref[5:15] = np.arange(1, 11)
    oracle.equal(b, ref)


def test_transform(mesh_size, oracle):
    a = dr_tpu.distributed_vector(27)
    b = dr_tpu.distributed_vector(27)
    dr_tpu.iota(a, 0)
    dr_tpu.transform(a, b, lambda x: 2 * x + 1)
    oracle.equal(b, 2 * np.arange(27, dtype=np.float32) + 1)


def test_transform_zip(oracle):
    n = 24
    a = dr_tpu.distributed_vector.from_array(np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector.from_array(np.ones(n, dtype=np.float32))
    c = dr_tpu.distributed_vector(n)
    z = views.zip_view(a, b)
    dr_tpu.transform(z, c, lambda x, y: x + y)
    oracle.equal(c, np.arange(n) + 1.0)


def test_for_each(mesh_size, oracle):
    dv = dr_tpu.distributed_vector(18)
    dr_tpu.iota(dv, 0)
    dr_tpu.for_each(dv, lambda x: x * x)
    oracle.equal(dv, np.arange(18, dtype=np.float32) ** 2)


def test_for_each_zip_writeback(oracle):
    n = 16
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector.from_array(
        np.full(n, 10, dtype=np.float32))
    z = views.zip_view(a, b)
    dr_tpu.for_each(z, lambda x, y: (x + y, y - x))
    oracle.equal(a, np.arange(n) + 10.0)
    oracle.equal(b, 10.0 - np.arange(n))


def test_reduce_sum(mesh_size):
    dv = dr_tpu.distributed_vector(100)
    dr_tpu.iota(dv, 1)
    assert dr_tpu.reduce(dv) == pytest.approx(5050.0)


def test_reduce_with_init_and_ops():
    dv = dr_tpu.distributed_vector(10)
    dr_tpu.iota(dv, 1)
    assert dr_tpu.reduce(dv, init=100.0) == pytest.approx(155.0)
    assert dr_tpu.reduce(dv, op=jnp.maximum) == pytest.approx(10.0)
    assert dr_tpu.reduce(dv, op=jnp.minimum) == pytest.approx(1.0)


def test_reduce_generic_op():
    dv = dr_tpu.distributed_vector(8)
    dr_tpu.fill(dv, 2.0)
    got = dr_tpu.reduce(dv, op=lambda a, b: a * b)
    assert got == pytest.approx(256.0)


def test_reduce_subrange():
    dv = dr_tpu.distributed_vector(50)
    dr_tpu.iota(dv, 0)
    assert dr_tpu.reduce(dv[10:20]) == pytest.approx(sum(range(10, 20)))


def test_transform_reduce_dot(mesh_size):
    n = 1000
    rng = np.random.default_rng(42)
    x = rng.standard_normal(n).astype(np.float32)
    y = rng.standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(x)
    b = dr_tpu.distributed_vector.from_array(y)
    got = dr_tpu.dot(a, b)
    assert got == pytest.approx(float(np.dot(x, y)), rel=1e-4)


def test_transform_reduce_explicit():
    dv = dr_tpu.distributed_vector(9)
    dr_tpu.iota(dv, 1)
    got = dr_tpu.transform_reduce(dv, transform_op=lambda x: x * x)
    assert got == pytest.approx(float((np.arange(1, 10) ** 2).sum()))


def test_async_reductions():
    # reduce_async/dot_async return device scalars (reference SHP's
    # oneDPL reduce_async surface, shp/algorithms/reduce.hpp:42-88)
    src = np.arange(33, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    b = dr_tpu.distributed_vector.from_array(src * 0 + 2)
    v = dr_tpu.reduce_async(a)
    assert float(v) == pytest.approx(src.sum())
    d = dr_tpu.dot_async(a, b)
    assert float(d) == pytest.approx(2 * src.sum())
    t = dr_tpu.transform_reduce_async(a, transform_op=lambda x: x * x)
    assert float(t) == pytest.approx((src * src).sum())


def test_dot_n_matches_dot():
    from dr_tpu.algorithms.reduce import dot_n
    n = 64 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector(n)
    b = dr_tpu.distributed_vector(n)
    dr_tpu.iota(a, 1)
    dr_tpu.fill(b, 0.5)
    want = dr_tpu.dot(a, b)
    got = float(dot_n(a, b, 3))
    assert abs(got - want) < 1e-3 * abs(want)


def test_inclusive_scan_n_runs_chained():
    from dr_tpu.algorithms.scan import inclusive_scan_n
    n = 32 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector(n)
    s = dr_tpu.distributed_vector(n)
    dr_tpu.fill(a, 1.0)
    inclusive_scan_n(a, s, 1)
    # one round == a plain inclusive scan
    np.testing.assert_allclose(dr_tpu.to_numpy(s),
                               np.arange(1, n + 1, dtype=np.float32))
    inclusive_scan_n(a, s, 2)  # chained round compiles and runs
    got = dr_tpu.to_numpy(s)
    np.testing.assert_allclose(got, np.cumsum(np.arange(1, n + 1)))


def test_profiling_device_timer_and_annotate():
    """utils.profiling: the marginal timer measures a fused loop and
    annotate/trace wrap without error (CPU backend)."""
    from dr_tpu.algorithms.reduce import dot_n
    from dr_tpu.utils import profiling
    n = 64 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector(n)
    b = dr_tpu.distributed_vector(n)
    dr_tpu.fill(a, 1.0)
    dr_tpu.fill(b, 2.0)
    dt = profiling.device_timer(lambda r: float(dot_n(a, b, r)),
                                r1=1, r2=5, samples=2)
    assert np.isfinite(dt)
    with profiling.annotate("dot"):
        float(dot_n(a, b, 1))


def test_profiling_marginal_widens_and_raises(monkeypatch):
    """utils.profiling.marginal (the bench measurement core as a
    library API): fast ops widen their loop count; a measurement with
    zero marginal cost raises the typed JitterError."""
    from dr_tpu.utils import profiling

    class _FakeOp:
        def __init__(self, per_op, constant=0.01):
            self.per_op, self.constant = per_op, constant
            self.clock = [0.0]
            self.calls = []

        def __call__(self, r):
            self.calls.append(r)
            self.clock[0] += self.constant + self.per_op * r

    op = _FakeOp(per_op=1e-4)
    monkeypatch.setattr(profiling.time, "perf_counter",
                        lambda: op.clock[0])
    dt = profiling.marginal(op, r1=4, r2=36, samples=3,
                            min_spread=0.3, rmax=4096)
    assert dt == pytest.approx(1e-4, rel=1e-6)
    assert max(op.calls) > 36  # widened beyond the pilot loop count
    noise = _FakeOp(per_op=0.0)
    monkeypatch.setattr(profiling.time, "perf_counter",
                        lambda: noise.clock[0])
    with pytest.raises(profiling.JitterError):
        profiling.marginal(noise, r1=4, r2=36, samples=3,
                           min_spread=0.3, rmax=4096)


def test_profiling_phase_breakdown_math(monkeypatch):
    """profile_phases: cumulative prefix times become per-phase costs
    (clamped at 0 on noise inversions); jitter-drowned prefixes record
    a zero-cost phase instead of failing the breakdown."""
    from dr_tpu.utils import profiling
    cums = [0.010, 0.014, 0.013, None, 0.040]  # None -> JitterError
    names = ("a", "b", "c", "d", "e")

    def fake_marginal(run, **kw):
        v = cums[run]
        if v is None:
            raise profiling.JitterError("noise")
        return v

    monkeypatch.setattr(profiling, "marginal", fake_marginal)
    bd = profiling.profile_phases(lambda i: i, names, r1=2, r2=6)
    assert bd.total == pytest.approx(0.040)
    assert bd.seconds["a"] == pytest.approx(0.010)
    assert bd.seconds["b"] == pytest.approx(0.004)
    assert bd.seconds["c"] == 0.0            # inversion clamps to 0
    assert bd.seconds["d"] == 0.0            # jitter-drowned prefix
    assert bd.seconds["e"] == pytest.approx(0.040 - 0.014)
    assert bd.dominant == "e"
    det = bd.detail(bytes_per_op=4e9)
    assert det["a"] == pytest.approx(400.0)  # 4 GB / 10 ms
    assert det["c"] == 0.0
    assert "total" in bd.table(4e9)
    fr = bd.fractions()
    assert sum(fr.values()) == pytest.approx(1.0)


def test_profiling_phases_on_sort_program():
    """End-to-end: profile_phases over the sample-sort truncation
    ladder on the CPU mesh returns a breakdown with every phase named
    (timings themselves are noise at this scale — min_spread=0 keeps
    the harness deterministic)."""
    from dr_tpu.algorithms.sort import SORT_PHASES, sort_phases_n
    from dr_tpu.utils import profiling
    n = 64 * dr_tpu.nprocs()
    rng = np.random.default_rng(3)
    v = dr_tpu.distributed_vector.from_array(
        rng.standard_normal(n).astype(np.float32))

    def mk(i):
        def run(r):
            sort_phases_n(v, SORT_PHASES[i], r)
            float(dr_tpu.to_numpy(v)[0])
        return run

    bd = profiling.profile_phases(mk, SORT_PHASES, r1=1, r2=3,
                                  samples=1, min_spread=0.0)
    assert bd.names == SORT_PHASES
    assert all(s >= 0 for s in bd.seconds.values())


def test_transform_scalar_args_reuse_program():
    """Trailing transform scalars are traced: two calls with different
    values share ONE cached program (the CG-loop pattern)."""
    from dr_tpu.algorithms.elementwise import _prog_cache

    def axpy(x, p, alpha):
        return x + alpha * p

    n = 256
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.iota(a, 0)
    dr_tpu.fill(b, 1.0)
    dr_tpu.transform(dr_tpu.views.zip(a, b), a, axpy, 2.0)
    n_progs = len(_prog_cache)
    dr_tpu.transform(dr_tpu.views.zip(a, b), a, axpy, 5.0)
    assert len(_prog_cache) == n_progs  # same program, new scalar
    ref = np.arange(n) + 2.0 + 5.0
    np.testing.assert_allclose(dr_tpu.to_numpy(a), ref, rtol=1e-6)


def test_for_each_scalar_args():
    """for_each mirrors transform's trailing traced scalars, including
    over zips (tuple write-back)."""

    def scale2(x, y, c):
        return x * c, y + c

    n = 128
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.iota(a, 0)
    dr_tpu.fill(b, 1.0)
    from dr_tpu.algorithms.elementwise import _prog_cache

    dr_tpu.for_each(dr_tpu.views.zip(a, b), scale2, 3.0)
    np.testing.assert_allclose(dr_tpu.to_numpy(a), np.arange(n) * 3.0)
    np.testing.assert_allclose(dr_tpu.to_numpy(b), np.full(n, 4.0))
    n_progs = len(_prog_cache)
    dr_tpu.for_each(dr_tpu.views.zip(a, b), scale2, 0.5)
    assert len(_prog_cache) == n_progs  # scalar traced, program reused
    np.testing.assert_allclose(dr_tpu.to_numpy(a), np.arange(n) * 1.5)

    def shift(x, c):
        return x + c

    dr_tpu.for_each(a, shift, 2.0)
    np.testing.assert_allclose(dr_tpu.to_numpy(a), np.arange(n) * 1.5 + 2.0)


def test_transform_reduce_streamed_coefficient():
    """transform_args bind TRACED scalars into the fused reduce pipeline:
    a streaming coefficient reuses one compiled program."""
    from dr_tpu.algorithms.elementwise import _prog_cache

    def sqdiff(x, mu):
        return (x - mu) ** 2

    n = 500
    rng = np.random.default_rng(3)
    src = rng.standard_normal(n).astype(np.float32)
    dv = dr_tpu.distributed_vector.from_array(src)
    got = dr_tpu.transform_reduce(dv, transform_op=sqdiff,
                                  transform_args=(0.5,))
    ref = float(((src.astype(np.float64) - 0.5) ** 2).sum())
    assert got == pytest.approx(ref, rel=1e-4)
    n_progs = len(_prog_cache)
    got2 = dr_tpu.transform_reduce(dv, transform_op=sqdiff,
                                   transform_args=(-1.25,))
    assert len(_prog_cache) == n_progs  # scalar traced, program reused
    ref2 = float(((src.astype(np.float64) + 1.25) ** 2).sum())
    assert got2 == pytest.approx(ref2, rel=1e-4)

    # the same through an explicit views.transform pipeline over a zip
    def wdot(x, y, w):
        return w * x * y

    b = dr_tpu.distributed_vector.from_array(2.0 - src)
    z = dr_tpu.views.zip(dv, b)
    r1 = dr_tpu.reduce(dr_tpu.views.transform(z, wdot, 2.0))
    r2 = dr_tpu.reduce(dr_tpu.views.transform(z, wdot, -3.0))
    ref1 = float((2.0 * src.astype(np.float64) * (2.0 - src)).sum())
    assert r1 == pytest.approx(ref1, rel=1e-4)
    assert r2 == pytest.approx(-1.5 * r1, rel=1e-4)


def test_nested_bound_ops_in_reduce_pipeline():
    """BoundOp at BOTH levels: bound component transforms inside a zip
    whose combine is also bound — scalar ordering (chain-major, then
    zip op) through one fused program."""
    from dr_tpu.algorithms.elementwise import _prog_cache

    def shift(x, c):
        return x + c

    def wmul(x, y, w):
        return w * x * y

    n = 320
    rng = np.random.default_rng(9)
    xs = rng.standard_normal(n).astype(np.float32)
    ys = rng.standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(xs)
    b = dr_tpu.distributed_vector.from_array(ys)

    def pipeline(c1, c2, w):
        z = dr_tpu.views.zip(dr_tpu.views.transform(a, shift, c1),
                             dr_tpu.views.transform(b, shift, c2))
        return dr_tpu.reduce(dr_tpu.views.transform(z, wmul, w))

    got = pipeline(0.5, -1.0, 2.0)
    ref = float((2.0 * (xs.astype(np.float64) + 0.5) * (ys - 1.0)).sum())
    assert got == pytest.approx(ref, rel=1e-3)
    n_progs = len(_prog_cache)
    got2 = pipeline(-2.0, 3.0, 0.25)
    assert len(_prog_cache) == n_progs  # all five scalars traced
    ref2 = float((0.25 * (xs.astype(np.float64) - 2.0) * (ys + 3.0)).sum())
    assert got2 == pytest.approx(ref2, rel=1e-3)


def test_dot_n_kernel_path_interpret(monkeypatch):
    """dot_n's Pallas kernel path (the TPU default since the round-3
    A/B; DR_TPU_DOT_IMPL=xla opts out): per-shard streamed kernel +
    psum on the multi-device mesh, interpret mode."""
    import functools
    import importlib
    reduce_mod = importlib.import_module("dr_tpu.algorithms.reduce")
    from dr_tpu.ops import reduce_pallas

    monkeypatch.setenv("DR_TPU_DOT_IMPL", "pallas")
    monkeypatch.setattr(reduce_mod, "_dot_kernel_platform_ok",
                        lambda rt: True)
    monkeypatch.setattr(
        reduce_pallas, "chunked_dot",
        functools.partial(reduce_pallas.chunked_dot, interpret=True))
    P = dr_tpu.nprocs()
    n = 128 * 128 * P  # exact uniform lane-chunkable layout
    rng = np.random.default_rng(13)
    xs = rng.standard_normal(n).astype(np.float32)
    ys = rng.standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(xs)
    b = dr_tpu.distributed_vector.from_array(ys)
    got = float(dr_tpu.dot_n(a, b, 3))
    ref = float(xs.astype(np.float64) @ ys.astype(np.float64))
    assert abs(got - ref) < 1e-4 * abs(ref) + 1e-2


def test_reduce_custom_op_native(monkeypatch):
    """Unclassified (identityless) reduce ops run a fused shard_map
    program — per-shard associative fold + empty-shard-skipping total
    walk — instead of the silent materialize (round 5).  Windows,
    view chains, and uneven distributions included."""
    from dr_tpu import views

    # std::reduce requires an ASSOCIATIVE op; multiplication disguised
    # as a lambda defeats the monoid classifier while keeping an exact
    # numpy oracle
    op = lambda a, b: a * b * 1.0

    n = 97
    rng = np.random.default_rng(12)
    src = (rng.uniform(0.9, 1.1, n)).astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src)

    def boom(self):
        raise AssertionError("custom reduce materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    got = dr_tpu.reduce(v, op=op)
    monkeypatch.undo()
    np.testing.assert_allclose(got, float(np.prod(src.astype(np.float64))),
                               rtol=1e-4)

    # window + view chain
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    got2 = dr_tpu.reduce(views.transform(v[10:60], lambda x: x * x),
                         op=op)
    monkeypatch.undo()
    np.testing.assert_allclose(
        got2, float(np.prod((src[10:60] ** 2).astype(np.float64))),
        rtol=1e-3)

    # uneven distribution with an empty team shard
    P = dr_tpu.nprocs()
    if P >= 3:
        sizes = [7, 0] + [0] * (P - 3) + [n - 7]
        u = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
        u.assign_array(src)
        monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
        got3 = dr_tpu.reduce(u, op=op)
        monkeypatch.undo()
        np.testing.assert_allclose(
            got3, float(np.prod(src.astype(np.float64))), rtol=1e-4)


def test_reduce_custom_op_streaming_scalar_reuses_program():
    """BoundOp coefficients feed the custom-reduce program as TRACED
    operands: streaming a new value must NOT compile a new program
    (the _fused_reduce_program convention; round-5 review finding)."""
    from dr_tpu import views
    from dr_tpu.algorithms.elementwise import _prog_cache
    op = lambda a, b: a * b * 1.0
    src = np.random.default_rng(13).uniform(0.9, 1.1, 40).astype(
        np.float32)
    v = dr_tpu.distributed_vector.from_array(src)

    shift = lambda x, m: x + m  # defined ONCE (the documented contract)

    def run(mu):
        return dr_tpu.reduce(views.transform(v, shift, mu), op=op)

    got1 = run(0.01)
    ncached = len(_prog_cache)
    got2 = run(0.02)  # same op identity, new scalar value
    assert len(_prog_cache) == ncached, "scalar stream recompiled"
    np.testing.assert_allclose(
        got1, float(np.prod((src + 0.01).astype(np.float64))), rtol=1e-4)
    np.testing.assert_allclose(
        got2, float(np.prod((src + 0.02).astype(np.float64))), rtol=1e-4)


def test_reduce_custom_op_trailing_empty_nominal_shard(monkeypatch):
    """n=33 on 8 shards: the uniform ceil layout leaves shard 7's
    nominal window entirely beyond n.  Its pad cells must never enter
    the identityless fold (round-5 fuzz finding: the product came
    back 0.0)."""
    P = dr_tpu.nprocs()
    n = 4 * P + 1  # forces a trailing all-beyond-n nominal shard
    pos = (np.abs(np.random.default_rng(3).standard_normal(n)) * 0.2
           + 0.9).astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(pos)

    def boom(self):
        raise AssertionError("materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    got = dr_tpu.reduce(v, op=lambda a, b: a * b * 1.0)
    # a PROPER sub-window so the window_geometry branch actually runs
    # against the trailing-empty geometry (v[0:n] normalizes to the
    # non-window program)
    got_w = dr_tpu.reduce(v[1:n], op=lambda a, b: a * b * 1.0)
    monkeypatch.undo()
    want = float(np.prod(pos.astype(np.float64)))
    np.testing.assert_allclose(got, want, rtol=1e-4)
    np.testing.assert_allclose(
        got_w, float(np.prod(pos[1:].astype(np.float64))), rtol=1e-4)
