"""Serving control plane (docs/SPEC.md §20): health-checked replica
fleet (circuit breakers + respawn supervisor), shared retry budgets,
graceful drain, and crash-safe resident-state recovery.

In-process daemons under tmp_path sockets carry tier-1 (the
test_serve.py conventions); the subprocess SIGKILL→respawn soak and
the spawn-mode rolling restart are slow-marked and cranked by the
fuzz-crank RESPAWN arm.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

import dr_tpu
from dr_tpu import serve
from dr_tpu.serve import journal as journal_mod
from dr_tpu.serve.router import _ProbeSchedule
from dr_tpu.utils import faults, resilience
from dr_tpu.utils.env import env_int, env_override

X = np.arange(48, dtype=np.float32)


@pytest.fixture
def server(tmp_path):
    srv = serve.Server(str(tmp_path / "cp.sock"),
                       state_dir=str(tmp_path / "state"))
    srv.start()
    yield srv
    srv.stop()


def _client(srv, **kw):
    kw.setdefault("timeout", 60.0)
    return serve.Client(srv.path, **kw)


# ---------------------------------------------------------------------------
# circuit breaker + probe schedule units (no daemon)
# ---------------------------------------------------------------------------

def test_breaker_state_machine():
    with env_override(DR_TPU_SERVE_PROBE_S="1.0",
                      DR_TPU_SERVE_PROBES="3"):
        br = serve.CircuitBreaker("/tmp/x.sock", seed=0)
        assert br.state == "closed" and not br.due()
        br.trip()
        assert br.state == "open" and br.trips == 1
        now = time.monotonic()
        # first probe lands one backoff-base out, not immediately
        assert not br.due(now)
        assert br.due(now + 2.0)
        # a failed probe advances the schedule: the next due time
        # doubles (seeded jitter, deterministic)
        br.sched.advance(now + 2.0)
        assert not br.due(now + 2.1)
        assert br.due(now + 2.0 + 4.0)
        # budget bounds the probing: after 3 probes, never due again
        br.sched.advance(now + 6.0)
        br.sched.advance(now + 6.0)
        assert br.exhausted() and not br.due(now + 1e6)
        # a healthy probe closes the breaker and drops the schedule
        br.reset()
        assert br.state == "closed" and br.sched is None


def test_probe_schedule_deterministic_and_bounded():
    with env_override(DR_TPU_SERVE_PROBE_S="0.25",
                      DR_TPU_SERVE_PROBE_CAP_S="2.0",
                      DR_TPU_SERVE_PROBES="5"):
        a, b = _ProbeSchedule(seed=3), _ProbeSchedule(seed=3)
        assert a._delays == b._delays  # seeded: reproducible
        assert len(a._delays) == 5
        assert max(a._delays) <= 2.0 * 1.25  # cap (+jitter)
        for _ in range(5):
            assert not a.exhausted()
            a.advance()
        assert a.exhausted() and not a.due()


# ---------------------------------------------------------------------------
# retry budget (SPEC §20.2)
# ---------------------------------------------------------------------------

def test_token_budget_spend_refill():
    b = resilience.TokenBudget(2, ratio=0.5)
    assert b.spend() and b.spend() and not b.spend()
    b.note_success()
    assert not b.spend()  # half a token is not a whole one
    b.note_success()
    assert b.spend()
    snap = b.snapshot()
    assert snap["spent"] == 3 and snap["denied"] == 2


def test_retry_budget_exhausted_fails_fast():
    calls = []

    def boom():
        calls.append(1)
        raise resilience.TransientBackendError("UNAVAILABLE: synthetic")

    t0 = time.perf_counter()
    with pytest.raises(resilience.TransientBackendError):
        resilience.retry(boom, attempts=8, base=5.0,
                         budget=resilience.TokenBudget(0))
    # one attempt, NO backoff sleep: the 5 s base never ran
    assert len(calls) == 1
    assert time.perf_counter() - t0 < 1.0


def test_client_retries_draw_shared_budget(server):
    # the intake fault serializes a retryable transient on EVERY
    # request; a budget of one token allows exactly one resubmission
    # fleet-wide, then the failure surfaces fast
    budget = resilience.TokenBudget(1, ratio=0.0)
    with faults.injected("serve.request", "transient",
                         times=None) as sp:
        with _client(server, retries=5, budget=budget) as c:
            with pytest.raises(resilience.TransientBackendError):
                c.reduce(X)
            first = sp.fired
            assert first == 2  # initial attempt + the one budgeted retry
            # the bucket is dry: the next request gets ONE attempt
            with pytest.raises(resilience.TransientBackendError):
                c.reduce(X)
            assert sp.fired == first + 1
    # successful requests refill the bucket at the configured ratio
    with _client(server, retries=5,
                 budget=resilience.TokenBudget(4, ratio=1.0)) as c:
        assert abs(c.reduce(np.ones(8, np.float32)) - 8.0) < 1e-3


def test_router_and_clients_share_one_budget(tmp_path):
    # the satellite bugfix: RouterClient's per-replica Clients draw
    # from ONE bucket, so fleet-level retries cannot multiply
    fleet = serve.Router(str(tmp_path / "b"), replicas=2, cpu=True,
                         batch_window=0.0).start()
    try:
        budget = resilience.TokenBudget(1, ratio=0.0)
        with serve.RouterClient(fleet.paths(), timeout=60.0,
                                retries=4, budget=budget) as rc:
            with faults.injected("serve.request", "transient",
                                 times=None) as sp:
                with pytest.raises(resilience.TransientBackendError):
                    rc.reduce(X)
                total_after_first = sp.fired
                assert total_after_first == 2  # 1 try + 1 budgeted
                with pytest.raises(resilience.TransientBackendError):
                    rc.reduce(X, tenant="other")
                # the other tenant (possibly the other replica) got
                # NO budgeted retry: the bucket is shared and dry
                assert sp.fired == total_after_first + 1
    finally:
        fleet.stop()


def test_dead_fleet_fails_fast_classified(tmp_path):
    # acceptance: with the budget exhausted and every breaker open, a
    # dead fleet costs < 1 RTT per request — no backoff storm
    with env_override(DR_TPU_SERVE_PROBE_S="30.0"):
        fleet = serve.Router(str(tmp_path / "dead"), replicas=2,
                             cpu=True, batch_window=0.0).start()
        try:
            rc = serve.RouterClient(fleet.paths(), timeout=60.0,
                                    budget=resilience.TokenBudget(0))
            assert abs(rc.reduce(np.ones(8, np.float32)) - 8.0) < 1e-3
            for s in list(fleet._servers):
                s.stop()
            t0 = time.perf_counter()
            for i in range(10):
                with pytest.raises(resilience.RelayDownError):
                    rc.reduce(X, tenant=f"t{i}")
            assert time.perf_counter() - t0 < 1.0
            assert set(rc.breaker_states().values()) == {"open"}
            rc.close()
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# graceful drain (SPEC §20.3)
# ---------------------------------------------------------------------------

def test_drain_completes_inflight_then_stops(tmp_path):
    srv = serve.Server(str(tmp_path / "d.sock"), batch_window=0.0)
    srv.start()
    try:
        srv.hold()  # park the dispatcher: the request stays in flight
        res = {}

        def worker():
            with _client(srv) as c:
                res["got"] = c.reduce(np.ones(32, np.float32))

        t = threading.Thread(target=worker)
        t.start()
        deadline = time.monotonic() + 10.0
        while len(srv._queue) == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(srv._queue) == 1
        dt = threading.Thread(target=srv.drain)
        dt.start()
        deadline = time.monotonic() + 10.0
        while not srv.draining() and time.monotonic() < deadline:
            time.sleep(0.005)
        # admission is closed: a new compute request gets the typed
        # drain rejection (ping still answers, and says so)
        with _client(srv) as c2:
            assert c2.ping().get("draining") is True
            with pytest.raises(resilience.ServerDraining):
                c2.reduce(np.ones(8, np.float32))
        assert not srv._stopped.is_set()  # waiting on the in-flight
        srv.release()
        dt.join(timeout=30.0)
        t.join(timeout=30.0)
        assert abs(res["got"] - 32.0) < 1e-3  # in-flight COMPLETED
        assert srv._stopped.is_set()
        assert srv._drains == 1 and srv._drain_rejects == 1
        assert env_int("_DR_TPU_SERVE_DRAINS", 0, floor=0) >= 1
    finally:
        srv.release()
        srv.stop()


def test_drain_wire_op_stops_daemon(server):
    with _client(server) as c:
        ack = c.drain()
        assert ack.get("draining") is True
    deadline = time.monotonic() + 10.0
    while not server._stopped.is_set() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert server._stopped.is_set()
    with pytest.raises(resilience.RelayDownError):
        serve.Client(server.path, timeout=5.0)


def test_drain_fault_site_classified(server):
    with faults.injected("serve.drain", "program") as sp:
        with pytest.raises(resilience.ProgramError):
            server.drain()
        assert sp.fired == 1
    # the faulted drain left the daemon serving normally
    assert not server.draining()
    with _client(server) as c:
        assert abs(c.reduce(np.ones(8, np.float32)) - 8.0) < 1e-3


def test_drain_wire_op_fault_classified(server):
    # the WIRE drain fires the site BEFORE the ack: a faulted drain
    # reaches the caller classified (§20.5) — never a positive ack
    # followed by a helper thread dying with the error
    with faults.injected("serve.drain", "program") as sp:
        with _client(server) as c:
            with pytest.raises(resilience.ProgramError):
                c.drain()
            assert sp.fired == 1
    assert not server.draining()  # and the daemon serves on
    with _client(server) as c:
        assert abs(c.reduce(np.ones(8, np.float32)) - 8.0) < 1e-3


def test_router_drain_rehash_no_client_error(tmp_path):
    with env_override(DR_TPU_SERVE_PROBE_S="30.0"):
        fleet = serve.Router(str(tmp_path / "dr"), replicas=2,
                             cpu=True, batch_window=0.0).start()
        try:
            with serve.RouterClient(fleet.paths(),
                                    timeout=60.0) as rc:
                t2 = next(t for t in (f"t{i}" for i in range(64))
                          if rc.route(t) == fleet.paths()[1])
                assert abs(rc.reduce(np.ones(8, np.float32),
                                     tenant=t2) - 8.0) < 1e-3
                # park one in-flight request on the replica (held
                # dispatcher) so the drain STAYS in its announcing
                # phase — an idle drain completes instantly and the
                # client would only see the connect-refused corpse
                fleet._servers[1].hold()
                res = {}

                def inflight():
                    with _client(fleet._servers[1]) as c:
                        res["got"] = c.reduce(np.ones(32, np.float32))

                it = threading.Thread(target=inflight)
                it.start()
                deadline = time.monotonic() + 10.0
                while len(fleet._servers[1]._queue) == 0 \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
                dt = threading.Thread(
                    target=fleet._servers[1].drain)
                dt.start()
                deadline = time.monotonic() + 10.0
                while not fleet._servers[1].draining() \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
                # mid-drain: the tenant's next op succeeds with NO
                # classified error (the drain announcement re-hashes)
                assert abs(rc.reduce(np.ones(16, np.float32),
                                     tenant=t2) - 16.0) < 1e-3
                fleet._servers[1].release()
                dt.join(timeout=30.0)
                it.join(timeout=30.0)
                assert abs(res["got"] - 32.0) < 1e-3  # drain finished it
                assert rc.drain_rehashes == 1 and rc.rehashes == 0
                assert rc.breaker_states()[fleet.paths()[1]] == "open"
                assert env_int("_DR_TPU_SERVE_ROUTER_DRAINED", 0,
                               floor=0) >= 1
        finally:
            fleet._servers[1].release()
            fleet.stop()


def test_rolling_restart_zero_classified_errors(tmp_path):
    # acceptance: rolling_restart over 2 replicas, traffic running,
    # ZERO classified client errors, resident state intact (journal).
    # NOT probe base 0.0: zero delays make the 16-probe budget
    # burnable within one restart's downtime by the tight traffic
    # loop — paced probes are the production shape
    with env_override(DR_TPU_SERVE_PROBE_S="0.01"):
        fleet = serve.Router(str(tmp_path / "rr"), replicas=2,
                             cpu=True, batch_window=0.0,
                             state_dir=str(tmp_path / "state")).start()
        try:
            rc = serve.RouterClient(fleet.paths(), tenant="alice",
                                    timeout=60.0)
            rc.put("feat", X)
            errs, done = [], threading.Event()

            def traffic():
                while not done.is_set():
                    try:
                        rc.reduce(X, tenant="alice")
                        rc.reduce(X, tenant="bob")
                    except resilience.ResilienceError as e:
                        errs.append(repr(e))

            th = threading.Thread(target=traffic)
            th.start()
            try:
                time.sleep(0.1)
                restarted = fleet.rolling_restart()
                time.sleep(0.2)
            finally:
                done.set()
                th.join(timeout=60.0)
            assert len(restarted) == 2
            assert not errs, errs[:3]
            # breakers re-close as paced probes land: the fleet is
            # whole again (and only THEN does the tenant's home
            # replica answer for its journal-replayed residents)
            deadline = time.monotonic() + 10.0
            while len(rc.live_replicas()) < 2 \
                    and time.monotonic() < deadline:
                rc.reduce(np.ones(4, np.float32), tenant="carol")
            assert len(rc.live_replicas()) == 2
            # resident state survived the full roll via the journal
            np.testing.assert_array_equal(rc.get("feat"), X)
            rc.close()
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# breaker probes re-admit a returned replica (SPEC §20.1)
# ---------------------------------------------------------------------------

def test_breaker_probe_readmits_replica(tmp_path):
    with env_override(DR_TPU_SERVE_PROBE_S="0.0"):
        fleet = serve.Router(str(tmp_path / "pr"), replicas=2,
                             cpu=True, batch_window=0.0).start()
        try:
            with serve.RouterClient(fleet.paths(),
                                    timeout=60.0) as rc:
                t2 = next(t for t in (f"t{i}" for i in range(64))
                          if rc.route(t) == fleet.paths()[0])
                fleet._servers[0].stop()  # abrupt death, no drain
                # the dead replica's tenant re-hashes (classified
                # story marker) and the op still succeeds
                assert abs(rc.reduce(np.ones(8, np.float32),
                                     tenant=t2) - 8.0) < 1e-3
                assert rc.rehashes == 1
                assert len(rc.live_replicas()) == 1
                # a fresh daemon takes the socket back; the due probe
                # (router.probe fires) re-admits it to the ring
                fleet.restart_replica(0)
                with faults.injected("router.probe", "transient") \
                        as sp:
                    # the FAULTED probe backs off — replica stays out
                    rc.reduce(np.ones(4, np.float32), tenant=t2)
                    assert sp.fired == 1
                    assert len(rc.live_replicas()) == 1
                rc.reduce(np.ones(4, np.float32), tenant=t2)
                assert fleet.paths()[0] in rc.live_replicas()
                assert rc.recoveries == 1
                assert rc.breaker_states()[fleet.paths()[0]] \
                    == "closed"
                assert env_int("_DR_TPU_SERVE_ROUTER_RECOVERED", 0,
                               floor=0) >= 1
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# crash-safe resident journal (SPEC §20.4)
# ---------------------------------------------------------------------------

def test_journal_replay_restores_residents(tmp_path, server):
    with _client(server) as c:
        c.put("a", X)
        c.put("b", X * 2)
        c.put("gone", X * 3)
        c.drop("gone")
        # an identical re-put appends nothing (content-tag fast path)
        appends = server._journal.appends
        c.put("a", X)
        assert server._journal.appends == appends
    server.stop()
    srv2 = serve.Server(server.path,
                        state_dir=str(tmp_path / "state")).start()
    try:
        with _client(srv2) as c:
            np.testing.assert_array_equal(c.get("a"), X)
            np.testing.assert_array_equal(c.get("b"), X * 2)
            with pytest.raises(resilience.ProgramError):
                c.get("gone")  # the drop was journaled too
            # refs resolve against the replayed containers
            assert abs(c.reduce(serve.Ref("b")) - 2 * X.sum()) < 1e-2
        st = srv2.stats()["journal"]
        assert st["replayed"] == 2 and st["live"] == 2
        assert env_int("_DR_TPU_SERVE_JOURNAL_RECOVERED", 0,
                       floor=0) == 2
        story = resilience.degradation_story()
        # a clean replay after a clean stop is NOT a degradation story
        assert story is None or "journal_recovered" in story["serve"]
    finally:
        srv2.stop()


def test_journal_torn_tail_truncates_cleanly(tmp_path, server):
    with _client(server) as c:
        c.put("keep", X)
    server.stop()
    jr = journal_mod.Journal(str(tmp_path / "state"), server.path)
    good = os.path.getsize(jr.path)
    with open(jr.path, "ab") as fh:
        fh.write(b"\x20\x00\x00\x00\x10")  # half a record prefix
    # strict scan classifies the tear
    with pytest.raises(resilience.CheckpointCorruptError):
        jr.scan()
    srv2 = serve.Server(server.path,
                        state_dir=str(tmp_path / "state")).start()
    try:
        with _client(srv2) as c:
            np.testing.assert_array_equal(c.get("keep"), X)
        assert os.path.getsize(jr.path) >= good  # compacted, whole
        assert env_int("_DR_TPU_SERVE_JOURNAL_TRUNCATED", 0,
                       floor=0) == 5
        story = resilience.degradation_story()
        assert story is not None
        assert story["serve"]["journal_truncated"] == 5
    finally:
        srv2.stop()


def test_journal_corrupt_payload_classified(tmp_path):
    jr = journal_mod.Journal(str(tmp_path / "jc"), "/tmp/x.sock")
    jr.claim()
    jr.append("put", "t", "n", "tag",
              np.arange(8, dtype=np.float32).tobytes())
    with open(jr.path, "r+b") as fh:
        fh.seek(-2, os.SEEK_END)
        fh.write(b"\xff\xff")  # flip payload bytes: crc must catch it
    with pytest.raises(resilience.CheckpointCorruptError):
        jr.scan()
    # replay truncates the corrupt record away — clean, empty
    assert jr.replay() == {}
    assert jr.truncated_bytes > 0


def test_journal_stale_generation_fenced(tmp_path, server):
    with _client(server) as c:
        c.put("a", X)
        # a NEWER daemon claims the state behind this one's back —
        # the socket-takeover race's loser must never serve again
        journal_mod.Journal(str(tmp_path / "state"),
                            server.path).claim()
        with pytest.raises(resilience.ProgramError):
            c.put("b", X * 2)
    deadline = time.monotonic() + 10.0
    while not server._stopped.is_set() \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert server._stopped.is_set()  # the stale daemon took itself out
    assert server._journal.fenced
    assert "fenced" in (server.degraded or "")


def test_journal_append_fault_degrades_durability_only(server):
    # a journal IO fault must not fail the put — durability degrades,
    # warned and counted; the entry still serves from memory
    with faults.injected("serve.journal", "transient") as sp:
        with _client(server) as c:
            c.put("soft", X)
            assert sp.fired == 1
            np.testing.assert_array_equal(c.get("soft"), X)
    assert server._journal_errors == 1


def test_journal_replay_fault_starts_empty(tmp_path, server):
    with _client(server) as c:
        c.put("a", X)
    server.stop()
    with faults.injected("serve.journal", "program") as sp:
        srv2 = serve.Server(server.path,
                            state_dir=str(tmp_path / "state")).start()
        try:
            assert sp.fired >= 1
            with _client(srv2) as c:
                with pytest.raises(resilience.ProgramError):
                    c.get("a")  # empty cache — but the daemon SERVES
                assert abs(c.reduce(np.ones(8, np.float32)) - 8.0) \
                    < 1e-3
        finally:
            srv2.stop()


def test_journal_compact_fault_keeps_replayed_residents(tmp_path,
                                                        server):
    # a classified compaction failure AFTER a whole replay must not
    # wipe the correctly-replayed residents: compact is atomic
    # temp+replace, the old journal is intact on disk
    with _client(server) as c:
        c.put("a", X)
    server.stop()
    # replay fires serve.journal first (op="replay"); after=1 lands
    # the fault on the compaction that follows the whole replay
    with faults.injected("serve.journal", "transient", after=1) as sp:
        srv2 = serve.Server(server.path,
                            state_dir=str(tmp_path / "state")).start()
        try:
            assert sp.fired == 1
            with _client(srv2) as c:
                np.testing.assert_array_equal(c.get("a"), X)
        finally:
            srv2.stop()


def test_journal_append_oserror_degrades_durability_only(server):
    # a raw filesystem error (ENOENT/ENOSPC-shaped) on append follows
    # the same contract as a classified one: durability degrades,
    # the put still serves from memory
    with _client(server) as c:
        server._journal.path = os.path.join(
            os.path.dirname(server._journal.path), "missing-dir",
            "j.journal")
        c.put("soft", X)
        np.testing.assert_array_equal(c.get("soft"), X)
    assert server._journal_errors == 1


def test_journal_replay_oserror_starts_empty(tmp_path, server):
    # an unreadable journal (OSError, not a classified corruption)
    # must not brick the daemon: it starts with an EMPTY cache
    with _client(server) as c:
        c.put("a", X)
    server.stop()
    jr = journal_mod.Journal(str(tmp_path / "state"), server.path)
    os.unlink(jr.path)
    os.makedirs(jr.path)  # open("rb") now raises IsADirectoryError
    srv2 = serve.Server(server.path,
                        state_dir=str(tmp_path / "state")).start()
    try:
        with _client(srv2) as c:
            with pytest.raises(resilience.ProgramError):
                c.get("a")  # empty cache — but the daemon serves
            assert abs(c.reduce(np.ones(8, np.float32)) - 8.0) < 1e-3
    finally:
        srv2.stop()


def test_journal_unavailable_state_dir_serves_without_durability(
        tmp_path):
    # a state dir that cannot be created degrades DURABILITY at
    # start, never the daemon
    bad = tmp_path / "statefile"
    bad.write_text("not a dir")
    srv = serve.Server(str(tmp_path / "cp2.sock"), state_dir=str(bad))
    srv.start()
    try:
        assert srv._journal is None
        with _client(srv) as c:
            c.put("a", X)
            np.testing.assert_array_equal(c.get("a"), X)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# story + trace_view satellites
# ---------------------------------------------------------------------------

def test_degradation_story_controlplane_counters(monkeypatch):
    for m, v in (("_DR_TPU_SERVE_RESPAWNS", "2"),
                 ("_DR_TPU_SERVE_DRAINS", "3"),
                 ("_DR_TPU_SERVE_ROUTER_RECOVERED", "1"),
                 ("_DR_TPU_SERVE_JOURNAL_RECOVERED", "4")):
        monkeypatch.setenv(m, v)
    story = resilience.degradation_story()
    assert story is not None  # respawns alone make it a story
    assert story["serve"]["respawns"] == 2
    assert story["serve"]["drains"] == 3
    assert story["serve"]["router_recovered"] == 1
    assert story["serve"]["journal_recovered"] == 4
    assert "respawned" in story["reason"]


def test_trace_view_controlplane_rollup(capsys):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "trace_view", os.path.join(repo, "tools", "trace_view.py"))
    tv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tv)
    events = [
        {"ph": "i", "name": "serve.drain", "cat": "serve", "ts": 1},
        {"ph": "i", "name": "router.probe", "cat": "serve", "ts": 2,
         "args": {"ok": False}},
        {"ph": "i", "name": "router.probe", "cat": "serve", "ts": 3,
         "args": {"ok": True}},
        {"ph": "i", "name": "router.respawn", "cat": "serve", "ts": 4},
        {"ph": "i", "name": "serve.journal.replay", "cat": "serve",
         "ts": 5},
    ]
    tv.summarize(events)
    out = capsys.readouterr().out
    assert "serve control plane" in out
    probe = next(l for l in out.splitlines()
                 if l.strip().startswith("router.probe"))
    assert "ok=1" in probe and "failed=1" in probe
    assert "router.respawn" in out and "serve.drain" in out
    assert "serve.journal.replay" in out


# ---------------------------------------------------------------------------
# subprocess soaks (slow — the fuzz-crank RESPAWN arm cranks these)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # two daemon subprocesses = two jax imports; the
# RESPAWN arm cranks this kill→respawn→verify loop
def test_subprocess_sigkill_respawn_serves_journal(tmp_path):
    with env_override(DR_TPU_SERVE_PROBE_S="0.1"):
        fleet = serve.Router(str(tmp_path / "sk"), replicas=2,
                             cpu=True, spawn=True,
                             state_dir=str(tmp_path / "state")).start()
        try:
            rc = serve.RouterClient(fleet.paths(), tenant="kv",
                                    timeout=120.0, router=fleet)
            x = np.arange(1 << 12, dtype=np.float32)
            rc.put("feat", x)
            victim = rc.route("kv")
            vi = fleet.paths().index(victim)
            fleet._procs[vi].send_signal(signal.SIGKILL)
            fleet._procs[vi].wait(timeout=30)
            # the supervisor poll rides rc calls (router=fleet): the
            # traffic notices the death (re-hash), the poll respawns,
            # the breaker probe re-admits — then the journal serves
            # the tenant's resident BIT-EQUAL from the fresh process.
            # The ring can NOT be the wait signal alone: it still
            # lists the corpse until a request actually hits it.
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                try:
                    rc.reduce(np.ones(8, np.float32), tenant="kv")
                    if fleet.stats()["respawns"] >= 1 \
                            and victim in rc.live_replicas():
                        break
                except resilience.ResilienceError:
                    pass  # mid-churn classified: acceptable
                time.sleep(0.05)
            assert fleet.stats()["respawns"] >= 1, "never respawned"
            assert victim in rc.live_replicas(), "never re-admitted"
            np.testing.assert_array_equal(rc.get("feat", tenant="kv"),
                                          x)
            assert fleet.stats()["respawns"] >= 1
            assert env_int("_DR_TPU_SERVE_RESPAWNS", 0, floor=0) >= 1
            story = resilience.degradation_story()
            assert story is not None and \
                story["serve"]["respawns"] >= 1
            rc.close()
        finally:
            fleet.stop()


@pytest.mark.slow  # two daemon subprocesses; SIGTERM is the __main__
# drain path — the spawn-mode half of the rolling-restart acceptance
def test_subprocess_sigterm_drains_and_rolling_restart(tmp_path):
    with env_override(DR_TPU_SERVE_PROBE_S="0.1"):
        fleet = serve.Router(str(tmp_path / "rrs"), replicas=2,
                             cpu=True, spawn=True,
                             state_dir=str(tmp_path / "state")).start()
        try:
            rc = serve.RouterClient(fleet.paths(), tenant="alice",
                                    timeout=120.0, router=fleet)
            x = np.arange(256, dtype=np.float32)
            rc.put("feat", x)
            # SIGTERM = graceful drain (__main__): clean exit 0
            proc = fleet._procs[1]
            proc.terminate()
            assert proc.wait(timeout=60) == 0
            fleet._procs[1] = fleet._spawn(fleet.paths()[1],
                                           cpu=True)
            # full wire-drain rolling restart over both replicas
            restarted = fleet.rolling_restart()
            assert len(restarted) == 2
            deadline = time.monotonic() + 60.0
            while len(rc.live_replicas()) < 2 \
                    and time.monotonic() < deadline:
                try:
                    rc.reduce(np.ones(8, np.float32), tenant="bob")
                except resilience.ResilienceError:
                    pass
                time.sleep(0.05)
            np.testing.assert_array_equal(rc.get("feat"), x)
            rc.close()
        finally:
            fleet.stop()
