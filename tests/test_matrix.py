"""dense_matrix + partition tests (reference test/gtest/shp/containers.cpp
matrix sections, shp/containers/matrix_partition.hpp)."""

import numpy as np
import pytest

import dr_tpu


def test_factor():
    assert dr_tpu.factor(8) == (2, 4)
    assert dr_tpu.factor(4) == (2, 2)
    assert dr_tpu.factor(7) == (1, 7)
    assert dr_tpu.factor(1) == (1, 1)


def test_block_cyclic_tile_rank():
    part = dr_tpu.block_cyclic(grid=(2, 4))
    assert part.tile_rank(0, 0) == 0
    assert part.tile_rank(0, 3) == 3
    assert part.tile_rank(1, 0) == 4
    assert part.tile_rank(1, 3) == 7


def test_dense_matrix_roundtrip(oracle):
    src = np.arange(7 * 9, dtype=np.float32).reshape(7, 9)
    mat = dr_tpu.dense_matrix.from_array(src)
    np.testing.assert_array_equal(mat.materialize(), src)


def test_dense_matrix_segments_cover():
    m, n = 10, 12
    mat = dr_tpu.dense_matrix((m, n))
    segs = dr_tpu.segments(mat)
    total = sum((s.re - s.rb) * (s.ce - s.cb) for s in segs)
    assert total == m * n
    ranks = {dr_tpu.rank(s) for s in segs}
    assert ranks <= set(range(dr_tpu.nprocs()))


def test_dense_matrix_tile_materialize():
    src = np.random.default_rng(0).standard_normal((8, 8)).astype(np.float32)
    mat = dr_tpu.dense_matrix.from_array(src)
    for t in mat.tiles():
        np.testing.assert_array_equal(t.materialize(),
                                      src[t.rb:t.re, t.cb:t.ce])


def test_dense_matrix_local_tile():
    src = np.arange(64, dtype=np.float32).reshape(8, 8)
    mat = dr_tpu.dense_matrix.from_array(src)
    for t in mat.tiles():
        loc = dr_tpu.local(t)
        np.testing.assert_array_equal(np.asarray(loc),
                                      src[t.rb:t.re, t.cb:t.ce])


def test_dense_matrix_element_access():
    mat = dr_tpu.dense_matrix((5, 5))
    mat[2, 3] = 7.0
    assert mat[2, 3] == 7.0
    with pytest.raises(IndexError):
        mat[5, 0]


def test_dense_matrix_row_tiles_partition():
    part = dr_tpu.row_tiles()
    mat = dr_tpu.dense_matrix((16, 4), partition=part)
    assert mat.grid_shape == (dr_tpu.nprocs(), 1)


def test_dense_matrix_view_and_rows():
    src = np.arange(36, dtype=np.float32).reshape(6, 6)
    mat = dr_tpu.dense_matrix.from_array(src)
    v = mat[1:4, 2:5]
    np.testing.assert_array_equal(v.materialize(), src[1:4, 2:5])
    segs = dr_tpu.segments(v)
    assert sum((s.re - s.rb) * (s.ce - s.cb) for s in segs) == 9
    np.testing.assert_array_equal(v.row(0).materialize(), src[1, 2:5])
    np.testing.assert_array_equal(v.column(1).materialize(), src[1:4, 3])


def test_matrix_entry_iteration():
    src = np.arange(4, dtype=np.float32).reshape(2, 2)
    mat = dr_tpu.dense_matrix.from_array(
        src, partition=dr_tpu.block_cyclic(grid=(1, 1)))
    entries = list(mat.tiles()[0])
    assert [(e.index.i, e.index.j, float(e.value)) for e in entries] == \
        [(0, 0, 0.0), (0, 1, 1.0), (1, 0, 2.0), (1, 1, 3.0)]


def test_gemm():
    rng = np.random.default_rng(1)
    a = rng.standard_normal((16, 12)).astype(np.float32)
    b = rng.standard_normal((12, 8)).astype(np.float32)
    A = dr_tpu.dense_matrix.from_array(a)
    B = dr_tpu.dense_matrix.from_array(b)
    C = dr_tpu.gemm(A, B)
    np.testing.assert_allclose(C.materialize(), a @ b, rtol=1e-4,
                               atol=1e-5)


# ---------------------------------------------------------------- cyclic

def _cyclic_part(th, tw, grid=None):
    if grid is None:
        grid = dr_tpu.factor(dr_tpu.nprocs())
    return dr_tpu.block_cyclic(tile=(th, tw), grid=grid)


def test_cyclic_roundtrip():
    src = np.arange(24 * 20, dtype=np.float32).reshape(24, 20)
    mat = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    assert not mat.is_block
    np.testing.assert_array_equal(mat.materialize(), src)


def test_cyclic_tile_rank_round_robin():
    # round-robin parity with the reference's tile_rank
    # (matrix_partition.hpp:34-86)
    if dr_tpu.nprocs() < 4:
        pytest.skip("2x2 process grid needs four devices")
    part = _cyclic_part(4, 4, grid=(2, 2))
    src = np.arange(16 * 16, dtype=np.float32).reshape(16, 16)
    mat = dr_tpu.dense_matrix.from_array(src, part)
    nti, ntj = mat.grid_tiles
    assert (nti, ntj) == (4, 4)
    for t in mat.tiles():
        i, j = t.rb // 4, t.cb // 4
        assert dr_tpu.rank(t) == (i % 2) * 2 + (j % 2)


def test_cyclic_segments_cover_and_materialize():
    src = np.random.default_rng(3).standard_normal((24, 16)) \
        .astype(np.float32)
    mat = dr_tpu.dense_matrix.from_array(src, _cyclic_part(8, 4))
    segs = dr_tpu.segments(mat)
    total = sum((s.re - s.rb) * (s.ce - s.cb) for s in segs)
    assert total == 24 * 16
    for t in segs:
        np.testing.assert_array_equal(t.materialize(),
                                      src[t.rb:t.re, t.cb:t.ce])


def test_cyclic_local_tile():
    src = np.arange(16 * 16, dtype=np.float32).reshape(16, 16)
    mat = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    for t in mat.tiles():
        loc = dr_tpu.local(t)
        np.testing.assert_array_equal(np.asarray(loc),
                                      src[t.rb:t.re, t.cb:t.ce])


def test_cyclic_uneven_trim():
    # tiles that do not divide the shape: last row/col tiles are trimmed
    src = np.arange(10 * 7, dtype=np.float32).reshape(10, 7)
    mat = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    np.testing.assert_array_equal(mat.materialize(), src)
    total = sum(len(t) for t in mat.tiles())
    assert total == 70


def test_cyclic_element_and_batched_access():
    src = np.zeros((12, 12), dtype=np.float32)
    mat = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    mat[5, 7] = 3.0
    assert mat[5, 7] == 3.0
    mat.put([1, 9], [2, 11], [4.0, 5.0])
    got = np.asarray(mat.get([1, 9, 5], [2, 11, 7]))
    np.testing.assert_array_equal(got, [4.0, 5.0, 3.0])
    # the logical view agrees
    arr = mat.materialize()
    assert arr[1, 2] == 4.0 and arr[9, 11] == 5.0 and arr[5, 7] == 3.0


def test_cyclic_gemm_matches_block():
    rng = np.random.default_rng(7)
    a = rng.standard_normal((16, 12)).astype(np.float32)
    b = rng.standard_normal((12, 8)).astype(np.float32)
    A = dr_tpu.dense_matrix.from_array(a, _cyclic_part(4, 4))
    B = dr_tpu.dense_matrix.from_array(b, _cyclic_part(4, 4))
    C = dr_tpu.gemm(A, B)
    np.testing.assert_allclose(C.materialize(), a @ b, rtol=1e-5,
                               atol=1e-5)


def test_cyclic_stencil2d_matches_block():
    rng = np.random.default_rng(8)
    src = rng.standard_normal((16, 16)).astype(np.float32)
    w = dr_tpu.heat_step_weights(0.25)
    Ac = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    Bc = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    Ab = dr_tpu.dense_matrix.from_array(src)
    Bb = dr_tpu.dense_matrix.from_array(src)
    out_c = dr_tpu.stencil2d_iterate(Ac, Bc, w, steps=3)
    out_b = dr_tpu.stencil2d_iterate(Ab, Bb, w, steps=3)
    np.testing.assert_allclose(out_c.materialize(), out_b.materialize(),
                               rtol=1e-5, atol=1e-6)
    # single-step transform parity too
    Ac2 = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    Bc2 = dr_tpu.dense_matrix.from_array(src, _cyclic_part(4, 4))
    Ab2 = dr_tpu.dense_matrix.from_array(src)
    Bb2 = dr_tpu.dense_matrix.from_array(src)
    dr_tpu.stencil2d_transform(Ac2, Bc2, w)
    dr_tpu.stencil2d_transform(Ab2, Bb2, w)
    np.testing.assert_allclose(Bc2.materialize(), Bb2.materialize(),
                               rtol=1e-5, atol=1e-6)


def test_cyclic_mesh_sweep(mesh_size):
    """Cyclic placement across the rank sweep (VERDICT r1 item 5:
    mesh {1,2,3,4,8}): round-robin tile_rank parity, roundtrip, gemm,
    and the 2-D stencil on a cyclic layout."""
    rng = np.random.default_rng(30 + mesh_size)
    gp, gq = dr_tpu.factor(mesh_size)
    part = dr_tpu.block_cyclic(tile=(4, 4), grid=(gp, gq))
    src = rng.standard_normal((16, 16)).astype(np.float32)
    A = dr_tpu.dense_matrix.from_array(src, part)
    np.testing.assert_array_equal(A.materialize(), src)
    for t in A.tiles():
        i, j = t.rb // 4, t.cb // 4
        assert dr_tpu.rank(t) == (i % gp) * gq + (j % gq)
    B = dr_tpu.dense_matrix.from_array(src, part)
    C = dr_tpu.gemm(A, B)
    np.testing.assert_allclose(C.materialize(), src @ src, rtol=1e-4,
                               atol=1e-4)
    A2 = dr_tpu.dense_matrix.from_array(src, part)
    B2 = dr_tpu.dense_matrix.from_array(src, part)
    out = dr_tpu.stencil2d_iterate(A2, B2,
                                   dr_tpu.heat_step_weights(0.25),
                                   steps=2)
    Ab = dr_tpu.dense_matrix.from_array(src)
    Bb = dr_tpu.dense_matrix.from_array(src)
    ref = dr_tpu.stencil2d_iterate(Ab, Bb,
                                   dr_tpu.heat_step_weights(0.25),
                                   steps=2)
    np.testing.assert_allclose(out.materialize(), ref.materialize(),
                               rtol=1e-5, atol=1e-6)


def test_sparse_2d_mesh_sweep(mesh_size):
    rng = np.random.default_rng(40 + mesh_size)
    gp, gq = dr_tpu.factor(mesh_size)
    d = np.where(rng.random((20, 18)) < 0.4,
                 rng.standard_normal((20, 18)), 0).astype(np.float32)
    sp = dr_tpu.sparse_matrix.from_dense(
        d, partition=dr_tpu.block_cyclic(grid=(gp, gq)))
    b = np.linspace(-1, 1, 18).astype(np.float32)
    c = dr_tpu.distributed_vector(20)
    dr_tpu.fill(c, 0.0)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), d @ b, rtol=1e-4,
                               atol=1e-5)
