"""dense_matrix + partition tests (reference test/gtest/shp/containers.cpp
matrix sections, shp/containers/matrix_partition.hpp)."""

import numpy as np
import pytest

import dr_tpu


def test_factor():
    assert dr_tpu.factor(8) == (2, 4)
    assert dr_tpu.factor(4) == (2, 2)
    assert dr_tpu.factor(7) == (1, 7)
    assert dr_tpu.factor(1) == (1, 1)


def test_block_cyclic_tile_rank():
    part = dr_tpu.block_cyclic(grid=(2, 4))
    assert part.tile_rank(0, 0) == 0
    assert part.tile_rank(0, 3) == 3
    assert part.tile_rank(1, 0) == 4
    assert part.tile_rank(1, 3) == 7


def test_dense_matrix_roundtrip(oracle):
    src = np.arange(7 * 9, dtype=np.float32).reshape(7, 9)
    mat = dr_tpu.dense_matrix.from_array(src)
    np.testing.assert_array_equal(mat.materialize(), src)


def test_dense_matrix_segments_cover():
    m, n = 10, 12
    mat = dr_tpu.dense_matrix((m, n))
    segs = dr_tpu.segments(mat)
    total = sum((s.re - s.rb) * (s.ce - s.cb) for s in segs)
    assert total == m * n
    ranks = {dr_tpu.rank(s) for s in segs}
    assert ranks <= set(range(dr_tpu.nprocs()))


def test_dense_matrix_tile_materialize():
    src = np.random.default_rng(0).standard_normal((8, 8)).astype(np.float32)
    mat = dr_tpu.dense_matrix.from_array(src)
    for t in mat.tiles():
        np.testing.assert_array_equal(t.materialize(),
                                      src[t.rb:t.re, t.cb:t.ce])


def test_dense_matrix_local_tile():
    src = np.arange(64, dtype=np.float32).reshape(8, 8)
    mat = dr_tpu.dense_matrix.from_array(src)
    for t in mat.tiles():
        loc = dr_tpu.local(t)
        np.testing.assert_array_equal(np.asarray(loc),
                                      src[t.rb:t.re, t.cb:t.ce])


def test_dense_matrix_element_access():
    mat = dr_tpu.dense_matrix((5, 5))
    mat[2, 3] = 7.0
    assert mat[2, 3] == 7.0
    with pytest.raises(IndexError):
        mat[5, 0]


def test_dense_matrix_row_tiles_partition():
    part = dr_tpu.row_tiles()
    mat = dr_tpu.dense_matrix((16, 4), partition=part)
    assert mat.grid_shape == (dr_tpu.nprocs(), 1)


def test_dense_matrix_view_and_rows():
    src = np.arange(36, dtype=np.float32).reshape(6, 6)
    mat = dr_tpu.dense_matrix.from_array(src)
    v = mat[1:4, 2:5]
    np.testing.assert_array_equal(v.materialize(), src[1:4, 2:5])
    segs = dr_tpu.segments(v)
    assert sum((s.re - s.rb) * (s.ce - s.cb) for s in segs) == 9
    np.testing.assert_array_equal(v.row(0).materialize(), src[1, 2:5])
    np.testing.assert_array_equal(v.column(1).materialize(), src[1:4, 3])


def test_matrix_entry_iteration():
    src = np.arange(4, dtype=np.float32).reshape(2, 2)
    mat = dr_tpu.dense_matrix.from_array(
        src, partition=dr_tpu.block_cyclic(grid=(1, 1)))
    entries = list(mat.tiles()[0])
    assert [(e.index.i, e.index.j, float(e.value)) for e in entries] == \
        [(0, 0, 0.0), (0, 1, 1.0), (1, 0, 2.0), (1, 1, 3.0)]


def test_gemm():
    rng = np.random.default_rng(1)
    a = rng.standard_normal((16, 12)).astype(np.float32)
    b = rng.standard_normal((12, 8)).astype(np.float32)
    A = dr_tpu.dense_matrix.from_array(a)
    B = dr_tpu.dense_matrix.from_array(b)
    C = dr_tpu.gemm(A, B)
    np.testing.assert_allclose(C.materialize(), a @ b, rtol=1e-4,
                               atol=1e-5)
