"""MXU composed-operator stencil (ops/stencil_matmul.py) vs the step-by-
step oracle — same contract as the Pallas blocked kernel tests."""

import numpy as np
import pytest

import dr_tpu
from dr_tpu.algorithms.stencil import stencil_iterate, stencil_iterate_matmul
from dr_tpu.ops.stencil_matmul import composed_taps


def _serial_stencil(src, w, steps):
    r = (len(w) - 1) // 2
    x = src.astype(np.float64)
    for _ in range(steps):
        acc = np.zeros_like(x)
        for d in range(len(w)):
            acc += w[d] * np.roll(x, r - d)
        x = acc
    return x


def test_composed_taps():
    w = [0.25, 0.5, 0.25]
    c = composed_taps(w, 2)
    np.testing.assert_allclose(c, np.convolve(w, w))
    assert len(composed_taps(w, 5)) == 2 * 5 * 1 + 1


@pytest.mark.parametrize("steps,k", [(4, 4), (7, 4), (8, 8), (3, 8)])
def test_matmul_stencil_matches_serial(steps, k):
    n = dr_tpu.nprocs() * 1024
    rng = np.random.default_rng(5)
    src = rng.standard_normal(n).astype(np.float32)
    w = [0.05, 0.25, 0.4, 0.25, 0.05]
    hb = dr_tpu.halo_bounds(256, 256, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate_matmul(a, w, steps, k_block=k)
    ref = _serial_stencil(src, w, steps)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref,
                               rtol=2e-4, atol=2e-5)


def test_matmul_matches_xla_path():
    n = dr_tpu.nprocs() * 1024
    src = np.linspace(-1, 1, n).astype(np.float32)
    w = [0.25, 0.5, 0.25]
    hb = dr_tpu.halo_bounds(128, 128, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)
    m = dr_tpu.distributed_vector.from_array(src, halo=hb)
    xla = stencil_iterate(a, b, w, steps=6)
    mm = stencil_iterate_matmul(m, w, 6, k_block=3)
    np.testing.assert_allclose(dr_tpu.to_numpy(mm), dr_tpu.to_numpy(xla),
                               rtol=2e-4, atol=2e-5)


def test_matmul_stencil_asymmetric_weights():
    # asymmetric taps catch a flipped band orientation or swapped
    # ppermute direction that symmetric weights cannot see
    n = dr_tpu.nprocs() * 1024
    rng = np.random.default_rng(11)
    src = rng.standard_normal(n).astype(np.float32)
    w = [0.1, 0.2, 0.7]
    hb = dr_tpu.halo_bounds(128, 128, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate_matmul(a, w, 6, k_block=4)
    ref = _serial_stencil(src, w, 6)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref,
                               rtol=2e-4, atol=2e-5)


def test_pallas_apply_matches_xla_interpret():
    """The fused VMEM apply (interpret mode) against the XLA P-form."""
    import jax.numpy as jnp
    from dr_tpu.ops import stencil_matmul as sm

    rng = np.random.default_rng(5)
    seg, halo = 512, 128
    w = [0.05, 0.25, 0.4, 0.25, 0.05]
    k = 16
    row = jnp.asarray(rng.standard_normal(
        (1, 2 * halo + seg)).astype(np.float32))
    ref = np.asarray(sm.matmul_stencil_row(row, seg, halo, w, k))
    got = np.asarray(sm.matmul_stencil_row(row, seg, halo, w, k,
                                           impl="pallas_interpret"))
    # the kernel emulates HIGH via bf16x3 (~5e-6 scaled error); the
    # XLA reference on CPU computes full f32
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_pick_chunk_rows():
    from dr_tpu.ops import stencil_matmul as sm
    assert sm._pick_chunk_rows(4096) == 4096
    assert sm._pick_chunk_rows(4096 * 3) == 4096
    assert sm._pick_chunk_rows(512) == 512
    assert sm._pick_chunk_rows(384) == 128
    assert sm._pick_chunk_rows(100) == 4
    assert sm._pick_chunk_rows(7) == 1


@pytest.mark.parametrize("steps,k", [(96, 96), (200, 128)])
def test_matmul_stencil_wide_band(steps, k):
    """k*r spanning TWO lane columns each side (D=2): the multi-block
    P-form against the step-by-step oracle."""
    n = dr_tpu.nprocs() * 1024
    rng = np.random.default_rng(9)
    src = rng.standard_normal(n).astype(np.float32)
    w = [0.05, 0.25, 0.4, 0.25, 0.05]  # radius 2 -> k*r up to 256
    hb = dr_tpu.halo_bounds(256, 256, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate_matmul(a, w, steps, k_block=k)
    ref = _serial_stencil(src, w, steps)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref,
                               rtol=2e-4, atol=2e-5)


def test_pallas_apply_wide_band_interpret():
    """Fused VMEM apply at D=2 (interpret) against the XLA P-form."""
    import jax.numpy as jnp
    from dr_tpu.ops import stencil_matmul as sm

    rng = np.random.default_rng(11)
    seg, halo = 512, 256
    w = [0.05, 0.25, 0.4, 0.25, 0.05]
    k = 128  # k*r = 256 -> D = 2
    row = jnp.asarray(rng.standard_normal(
        (1, 2 * halo + seg)).astype(np.float32))
    ref = np.asarray(sm.matmul_stencil_row(row, seg, halo, w, k))
    got = np.asarray(sm.matmul_stencil_row(row, seg, halo, w, k,
                                           impl="pallas_interpret"))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_wide_band_chunked_paths():
    """D=2 with nch > 1: the chunk-boundary offsets (hc - D + c*cr,
    wrows = cr + 2D) in both the fused interpret kernel and the
    lax.map-chunked XLA path."""
    import jax.numpy as jnp
    from dr_tpu.ops import stencil_matmul as sm

    rng = np.random.default_rng(13)
    seg, halo = 1024, 256   # segc = 8
    w = [0.05, 0.25, 0.4, 0.25, 0.05]
    k = 128  # D = 2
    row = jnp.asarray(rng.standard_normal(
        (1, 2 * halo + seg)).astype(np.float32))
    ref = np.asarray(sm.matmul_stencil_row(row, seg, halo, w, k))

    # pallas interpret with cr=2 -> nch=4
    orig_pick = sm._pick_chunk_rows
    sm._pick_chunk_rows = lambda segc, cap=None: 2
    try:
        got = np.asarray(sm.matmul_stencil_row(
            row, seg, halo, w, k, impl="pallas_interpret"))
    finally:
        sm._pick_chunk_rows = orig_pick
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    # XLA chunked path with a 3-row chunk -> nch=2 plus remainder 2
    orig_rows = sm._CHUNK_ROWS
    sm._CHUNK_ROWS = 3
    try:
        got = np.asarray(sm.matmul_stencil_row(row, seg, halo, w, k))
    finally:
        sm._CHUNK_ROWS = orig_rows
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_dot_high_f32_emulation_accuracy():
    """The in-kernel bf16x3 HIGH emulation tracks the f64 product to
    ~f32 precision (far beyond one bf16 pass)."""
    import jax.numpy as jnp
    from dr_tpu.ops.stencil_matmul import _dot_high_f32

    rng = np.random.default_rng(3)
    a = rng.standard_normal((64, 128)).astype(np.float32)
    b = rng.standard_normal((128, 384)).astype(np.float32)
    ref = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    got = np.asarray(_dot_high_f32(jnp.asarray(a), jnp.asarray(b)))
    # scaled max error: one DEFAULT bf16 pass lands ~3e-3 on this
    # shape; the 3-pass emulation must land ~5e-6 like true HIGH
    err = np.abs(got - ref).max() / np.abs(ref).max()
    assert err < 5e-5, err


def test_matmul_stencil_band_cols_4(monkeypatch):
    """D=4 (k*r spanning four lane columns) via DR_TPU_MM_BAND_COLS."""
    monkeypatch.setenv("DR_TPU_MM_BAND_COLS", "4")
    n = dr_tpu.nprocs() * 1024
    rng = np.random.default_rng(17)
    src = rng.standard_normal(n).astype(np.float32)
    w = [0.05, 0.25, 0.4, 0.25, 0.05]  # radius 2, k=256 -> D=4
    hb = dr_tpu.halo_bounds(512, 512, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = stencil_iterate_matmul(a, w, 256, k_block=256)
    ref = _serial_stencil(src, w, 256)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref,
                               rtol=2e-4, atol=2e-5)
