"""plansan — footprint-soundness mutation battery + oracle acceptance
(docs/SPEC.md §23).

Every footprint family in ``plansan.FAMILY_NAMES`` gets ONE seeded
under-declaration the shadow verifier must classify as a
:class:`FootprintViolation` (drlint rule R9 closes the sweep against
the registry both ways); the conflict-serializability oracle catches
seeded reorders of conflicting work; and the gemv view-operand
footprint keeps the §21.2 ``flush_reads`` skip from worst-case
flushing.  The verifier and watcher are exercised DIRECTLY (they do
not require ``DR_TPU_SANITIZE=1`` arming) — the armed end-to-end
route rides ``make sanitize`` and the ``test_fuzz_plansan`` arm.
"""

import numpy as np
import pytest

import dr_tpu
from dr_tpu import plan as dr_plan
from dr_tpu import views
from dr_tpu.plan import interference, plansan
from dr_tpu.utils import sanitize


# module-level ops: program-cache keys pin callable identity
def _scale(x, c):
    return x * c


def _swap_sum(x, y):
    return (x + y, x - y)


def _double(x):
    return x * 2


def _runs(p):
    return [it for it in p._queue if isinstance(it, dr_plan._Run)]


def _assert_catches(run, op):
    """The declared footprint verifies clean; the seeded
    under-declaration is a classified FootprintViolation carrying the
    trace-tail postmortem; the restore verifies clean again."""
    plansan.verify_run(run)
    orig_r, orig_w = op.reads, op.writes
    if op.writes:
        op.writes = ()
    else:
        op.reads = ()
    try:
        with pytest.raises(plansan.FootprintViolation) as ei:
            plansan.verify_run(run)
    finally:
        op.reads, op.writes = orig_r, orig_w
    assert isinstance(ei.value, sanitize.SanitizeError)
    assert hasattr(ei.value, "trace_tail")
    assert "R9" in str(ei.value)
    plansan.verify_run(run)


def _fused_driver(record, opname=None):
    """Record inside a deferred region, under-declare one fused op,
    assert the verifier catches it, restore, and let the exit flush
    run the UNmutated plan to completion."""
    with dr_tpu.deferred() as p:
        record()
        run = _runs(p)[-1]
        op = run.ops[-1] if opname is None else \
            next(o for o in run.ops if o.name == opname)
        _assert_catches(run, op)


# ---------------------------------------------------------------------------
# one seeded under-declaration per footprint family
# ---------------------------------------------------------------------------

def _drive_generator():
    n = 8 * dr_tpu.nprocs()
    v = dr_tpu.distributed_vector(n, np.float32)
    _fused_driver(lambda: dr_tpu.fill(v, 2.0))


def _drive_transform():
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector(n, np.float32)
    _fused_driver(lambda: dr_tpu.transform(a, b, _scale, 1.5))


def _drive_zip_foreach():
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32) + 1)
    _fused_driver(lambda: dr_tpu.for_each(views.zip(a, b), _swap_sum))


def _drive_reduce():
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    _fused_driver(lambda: dr_tpu.reduce(a))


def _drive_splice():
    n = 8 * dr_tpu.nprocs()
    v = dr_tpu.distributed_vector(n, np.float32)
    src = np.arange(n, dtype=np.float32)
    _fused_driver(lambda: dr_tpu.copy(src, v))


def _drive_halo():
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    n = 8 * dr_tpu.nprocs()
    v = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    _fused_driver(lambda: dr_tpu.halo(v).exchange())


def _drive_stencil():
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    b = dr_tpu.distributed_vector.from_array(
        np.zeros(n, dtype=np.float32), halo=hb)
    _fused_driver(
        lambda: dr_tpu.stencil_transform(a, b, [0.25, 0.5, 0.25]))


def _drive_redistribute():
    P = dr_tpu.nprocs()
    n = 4 * P
    v = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    team = [n] + [0] * (P - 1)
    _fused_driver(lambda: dr_tpu.redistribute(v, team))


def _drive_histogram():
    n = 8 * dr_tpu.nprocs()
    vv = dr_tpu.distributed_vector.from_array(
        np.linspace(-2.0, 2.0, n, dtype=np.float32))
    out = dr_tpu.distributed_vector(9, np.int32)
    _fused_driver(lambda: dr_tpu.histogram(vv, out, -2.5, 2.5))


def _drive_top_k():
    n = 8 * dr_tpu.nprocs()
    vv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    tv = dr_tpu.distributed_vector(3, np.float32)
    ti = dr_tpu.distributed_vector(3, np.int32)
    _fused_driver(lambda: dr_tpu.top_k(vv, tv, ti))


def _drive_opaque():
    """The opaque half rides the container-access watcher instead of
    the abstract replay: under-declare the scan's write of ``out`` and
    run its thunk under ``plansan.watch``."""
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.inclusive_scan(a, b)
        [item] = [it for it in p._queue
                  if isinstance(it, dr_plan._Opaque)]
        orig_r, orig_w = item.reads, item.writes
        item.writes = ()   # under-declare (NOT None — that is the
        try:               # documented barrier opt-out)
            with pytest.raises(plansan.FootprintViolation) as ei:
                with plansan.watch(item):
                    item.thunk()
        finally:
            item.reads, item.writes = orig_r, orig_w
        assert hasattr(ei.value, "trace_tail")
        assert "R9" in str(ei.value)
        # the declared footprint passes the same watcher
        with plansan.watch(item):
            item.thunk()
    np.testing.assert_allclose(
        dr_tpu.to_numpy(b),
        np.cumsum(np.arange(n, dtype=np.float32)))


_DRIVERS = {
    "generator": _drive_generator,
    "transform": _drive_transform,
    "zip_foreach": _drive_zip_foreach,
    "reduce": _drive_reduce,
    "splice": _drive_splice,
    "halo": _drive_halo,
    "stencil": _drive_stencil,
    "redistribute": _drive_redistribute,
    "histogram": _drive_histogram,
    "top_k": _drive_top_k,
    "opaque": _drive_opaque,
}


def test_battery_covers_every_family():
    """The R9 closure contract: the battery sweeps the registry."""
    assert set(_DRIVERS) == set(plansan.FAMILY_NAMES)


@pytest.mark.parametrize("family", sorted(_DRIVERS))
def test_mutation_battery_catches_underdeclaration(family):
    _DRIVERS[family]()


def test_barrier_opaque_is_exempt_from_the_watcher():
    """A declared barrier (None footprint) already pays the worst case
    in every pass — the watcher must not second-guess it."""
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.inclusive_scan(a, b)
        [item] = list(p._queue)
        orig_r, orig_w = item.reads, item.writes
        item.reads = item.writes = None
        try:
            with plansan.watch(item):   # no violation
                item.thunk()
        finally:
            item.reads, item.writes = orig_r, orig_w


# ---------------------------------------------------------------------------
# conflict-serializability oracle
# ---------------------------------------------------------------------------

def test_oracle_catches_intra_run_reorder():
    """fill -> transform fuse into ONE run; reversing the op order
    inside it breaks the W->R dependency on the filled container."""
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 2.0)
        dr_tpu.transform(a, b, _scale, 3.0)
        [run] = _runs(p)
        snap = plansan.snapshot(p._queue)
        plansan.check_serializable(snap, list(p._queue))  # as recorded
        run.ops.reverse()
        try:
            with pytest.raises(plansan.SerializationViolation,
                               match="data") as ei:
                plansan.check_serializable(snap, list(p._queue))
        finally:
            run.ops.reverse()
        assert hasattr(ei.value, "trace_tail")
    np.testing.assert_allclose(dr_tpu.to_numpy(b), np.full(n, 6.0))


def test_oracle_catches_opaque_queue_reorder():
    """Two chained scans (W b -> R b) are opaque queue items; swapping
    them breaks the dependency."""
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector(n, np.float32)
    c = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.inclusive_scan(a, b)
        dr_tpu.inclusive_scan(b, c)
        snap = plansan.snapshot(p._queue)
        plansan.check_serializable(snap, list(p._queue))
        with pytest.raises(plansan.SerializationViolation, match="data"):
            plansan.check_serializable(snap, list(p._queue)[::-1])


def test_oracle_barrier_orders_against_everything():
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 1.0)
        p.record_opaque("mystery", lambda: None)   # None = barrier
        snap = plansan.snapshot(p._queue)
        plansan.check_serializable(snap, list(p._queue))
        with pytest.raises(plansan.SerializationViolation,
                           match="barrier"):
            plansan.check_serializable(snap, list(p._queue)[::-1])


def test_oracle_dropped_ops_are_unconstrained():
    """Dead-eliminated ops simply vanish from the executed queue — the
    oracle constrains ordering, not liveness (bit-identity owns that)."""
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 2.0)
        dr_tpu.transform(a, b, _scale, 3.0)
        snap = plansan.snapshot(p._queue)
        plansan.check_serializable(snap, [])       # everything dropped


# ---------------------------------------------------------------------------
# view-operand footprints (satellite: flush_reads stops worst-case
# flushing on opaque barriers it can now resolve)
# ---------------------------------------------------------------------------

def test_view_containers_resolves_chains_and_keeps_barriers():
    n = 8 * dr_tpu.nprocs()
    a = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    b = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32) + 1)
    got = interference.view_containers(views.take(a, 4))
    assert [id(x) for x in got] == [id(a)]
    got = interference.view_containers(
        views.transform(views.zip(a, b), _swap_sum))
    assert [id(x) for x in got] == [id(a), id(b)]
    assert interference.view_containers(object()) is None


def test_gemv_view_footprint_skips_unrelated_flush():
    """A gemv over a transform VIEW used to record a full barrier —
    every host touch paid the flush cliff.  The resolved base-chain
    footprint lets ``flush_reads`` skip unrelated containers and
    still flush for the view's base."""
    P = dr_tpu.nprocs()
    m = ncols = 4 * P
    rng = np.random.default_rng(5)
    nnz = 3 * m
    rows = rng.integers(0, m, size=nnz)
    cols = rng.integers(0, ncols, size=nnz)
    vals = rng.standard_normal(nnz).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo((m, ncols), rows, cols, vals)
    csrc = rng.standard_normal(m).astype(np.float32)
    bsrc = rng.standard_normal(ncols).astype(np.float32)
    c = dr_tpu.distributed_vector.from_array(csrc)
    b = dr_tpu.distributed_vector.from_array(bsrc)
    unrelated = dr_tpu.distributed_vector(4 * P, np.float32)
    tview = views.transform(b, _double)
    with dr_tpu.deferred() as p:
        dr_tpu.gemv(c, A, tview)
        [item] = list(p._queue)
        reads = interference.opaque_reads(item)
        assert reads is not None, "view operand must not be a barrier"
        assert id(b) in {id(x) for x in reads}
        dr_plan.flush_reads(cont=unrelated)
        assert len(p._queue) == 1      # provably untouched: skipped
        dr_plan.flush_reads(cont=b)
        assert len(p._queue) == 0      # the view's base flushes
    ref = csrc.astype(np.float64)
    np.add.at(ref, rows,
              vals.astype(np.float64) * (2.0 * bsrc.astype(np.float64))[cols])
    np.testing.assert_allclose(dr_tpu.to_numpy(c), ref,
                               rtol=1e-3, atol=1e-4)
