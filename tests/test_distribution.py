"""Distribution policies for distributed_vector.

The reference declares but never ships this: ``// TODO: support teams,
distributions`` (shp/distributed_vector.hpp:113) and the disabled
allocator/distribution test (test/gtest/mhp/distributed_vector.cpp:121-131).
Here uneven block sizes (and zero-size "team" blocks) are first-class.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import dr_tpu
from conftest import check_segments, equal


def test_even_sizes_helper():
    assert dr_tpu.even_sizes(10, 4) == (3, 3, 3, 1)
    assert dr_tpu.even_sizes(8, 4) == (2, 2, 2, 2)
    assert dr_tpu.even_sizes(2, 4) == (1, 1, 0, 0)


def test_even_distribution_is_default_layout():
    """An explicitly-even distribution must alias the default layout so
    the two are aligned() and share compiled programs."""
    a = dr_tpu.distributed_vector(100)
    b = dr_tpu.distributed_vector(
        100, distribution=dr_tpu.even_sizes(100, dr_tpu.nprocs()))
    assert a.layout == b.layout
    assert b.distribution is None


def test_uneven_sizes_validation():
    P = dr_tpu.nprocs()
    with pytest.raises(ValueError):
        dr_tpu.distributed_vector(10, distribution=[10] * (P + 1))
    with pytest.raises(ValueError):
        dr_tpu.distributed_vector(10, distribution=[1] * P)  # sums to P
    with pytest.raises(ValueError):
        dr_tpu.block_distribution([3, -1])


def test_halo_requires_uniform():
    P = dr_tpu.nprocs()
    sizes = [2] * P
    sizes[0] = 2 + P  # uneven but sums correctly with n below
    with pytest.raises(ValueError):
        dr_tpu.distributed_vector(sum(sizes), halo=dr_tpu.halo_bounds(1, 1),
                                  distribution=sizes)


def _uneven_sizes(n, P, seed=0):
    rng = np.random.default_rng(seed)
    cuts = np.sort(rng.integers(0, n + 1, size=P - 1))
    bounds = np.concatenate(([0], cuts, [n]))
    return tuple(int(b - a) for a, b in zip(bounds[:-1], bounds[1:]))


def test_segments_respect_distribution(oracle):
    P = dr_tpu.nprocs()
    n = 37
    sizes = _uneven_sizes(n, P, seed=1)
    src = np.arange(n, dtype=np.float32)
    dv = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
    segs = dr_tpu.segments(dv)
    # nonzero blocks appear in order with the declared sizes
    declared = [s for s in sizes if s]
    assert [len(s) for s in segs] == declared
    starts = np.concatenate(([0], np.cumsum(sizes)[:-1]))
    ranks = [r for r, s in enumerate(sizes) if s]
    assert [dr_tpu.rank(s) for s in segs] == ranks
    for s, r in zip(segs, ranks):
        assert s.begin == int(starts[r])
    oracle.check_segments(dv)
    oracle.equal(dv, src)


def test_team_zero_blocks(oracle):
    """Zero-size blocks = 'teams': data restricted to a rank subset."""
    P = dr_tpu.nprocs()
    n = 12
    sizes = [0] * P
    sizes[0] = n  # everything on rank 0
    dv = dr_tpu.distributed_vector(n, np.int32, distribution=sizes)
    dr_tpu.iota(dv, 5)
    segs = dr_tpu.segments(dv)
    assert len(segs) == 1 and dr_tpu.rank(segs[0]) == 0
    oracle.equal(dv, np.arange(5, 5 + n))


def test_elementwise_on_uneven(oracle):
    P = dr_tpu.nprocs()
    n = 29
    sizes = _uneven_sizes(n, P, seed=2)
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    b = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.iota(a, 0)
    dr_tpu.fill(b, 10.0)
    assert dr_tpu.aligned(a, b)
    out = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.transform(dr_tpu.views.zip(a, b), out, lambda x, y: x + y)
    oracle.equal(out, np.arange(n) + 10.0)
    dr_tpu.for_each(out, lambda x: x * 2)
    oracle.equal(out, 2 * (np.arange(n) + 10.0))


def test_uneven_vs_uniform_misaligned():
    P = dr_tpu.nprocs()
    if P == 1:
        pytest.skip("one shard: every distribution is the same")
    n = 24
    sizes = list(dr_tpu.even_sizes(n, P))
    sizes[0] += 1
    sizes[-1] -= 1
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    b = dr_tpu.distributed_vector(n, np.float32)
    assert not dr_tpu.aligned(a, b)
    # fallback path still computes the right answer
    dr_tpu.iota(a, 0)
    dr_tpu.transform(a, b, lambda x: x + 1)
    np.testing.assert_allclose(dr_tpu.to_numpy(b), np.arange(n) + 1)


def test_reduce_scan_on_uneven(oracle):
    P = dr_tpu.nprocs()
    n = 41
    sizes = _uneven_sizes(n, P, seed=3)
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.iota(a, 1)
    assert dr_tpu.reduce(a) == pytest.approx(n * (n + 1) / 2)
    s = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.inclusive_scan(a, s)
    oracle.equal(s, np.cumsum(np.arange(1, n + 1)))


def test_scan_variants_on_uneven(oracle):
    """The shard_map scan program on uneven layouts (round-3: no
    longer the logical-array fallback for classified ops): inclusive
    mul, exclusive with init, and a zero-size team shard."""
    P = dr_tpu.nprocs()
    n = 23
    sizes = _uneven_sizes(n, P, seed=5)
    src = np.random.default_rng(5).uniform(0.5, 1.5, n)\
        .astype(np.float32)
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    a.assign_array(src)
    s = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.inclusive_scan(a, s, op=jnp.multiply)
    np.testing.assert_allclose(dr_tpu.to_numpy(s), np.cumprod(src),
                               rtol=1e-4)
    ex = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.exclusive_scan(a, ex, init=10.0)
    ref = 10.0 + np.concatenate([[0.0], np.cumsum(src)[:-1]])
    np.testing.assert_allclose(dr_tpu.to_numpy(ex), ref, rtol=1e-4)
    if P >= 3:
        # an EMPTY shard in the middle: its total is the identity and
        # the local exclusive seeding must still chain the carry across
        tsizes = [5, 0] + list(dr_tpu.even_sizes(n - 5, P - 2))
        at = dr_tpu.distributed_vector(n, np.float32,
                                       distribution=tsizes)
        at.assign_array(src)
        st = dr_tpu.distributed_vector(n, np.float32,
                                       distribution=tsizes)
        dr_tpu.exclusive_scan(at, st, init=0.0)
        np.testing.assert_allclose(
            dr_tpu.to_numpy(st),
            np.concatenate([[0.0], np.cumsum(src)[:-1]]), rtol=1e-4)


def test_get_put_on_uneven():
    P = dr_tpu.nprocs()
    n = 19
    sizes = _uneven_sizes(n, P, seed=4)
    dv = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.fill(dv, 0.0)
    idx = np.array([0, n // 2, n - 1])
    dv.put(idx, np.array([1.0, 2.0, 3.0]))
    got = np.asarray(dv.get(idx))
    np.testing.assert_allclose(got, [1.0, 2.0, 3.0])
    assert dv[n - 1] == 3.0
    dv[0] = 7.0
    assert dv[0] == 7.0
    # untouched cells stayed zero
    np.testing.assert_allclose(
        np.delete(dr_tpu.to_numpy(dv), idx), 0.0)


def test_views_over_uneven(oracle):
    P = dr_tpu.nprocs()
    n = 33
    sizes = _uneven_sizes(n, P, seed=5)
    src = np.arange(n, dtype=np.float32)
    dv = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
    v = dv[5:20]
    oracle.equal(v, src[5:20])
    check = dr_tpu.views.transform(dv, lambda x: x * x)
    assert dr_tpu.reduce(check) == pytest.approx(float((src ** 2).sum()))
    oracle.check_segments(v)


def test_stencil_rejects_uneven():
    P = dr_tpu.nprocs()
    if P == 1:
        pytest.skip("one shard: every distribution is uniform")
    n = 16 * P
    sizes = list(dr_tpu.even_sizes(n, P))
    sizes[0] += 1
    sizes[-1] -= 1
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    b = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    with pytest.raises(AssertionError):
        dr_tpu.stencil_transform(a, b, [0.25, 0.5, 0.25], radius=0)


def test_gemv_rejects_uneven_fast_path(oracle):
    """Uneven c whose capacity happens to equal tile_rows must NOT take
    the rank-r-owns-rows-[r*th, r*th+th) fast path."""
    P = dr_tpu.nprocs()
    if P == 1:
        pytest.skip("one shard: every distribution is uniform")
    m = 2 * P - 1  # tile_rows = 2, last tile short
    d = np.random.default_rng(0).random((m, m)).astype(np.float32)
    d[d < 0.5] = 0.0
    sp = dr_tpu.sparse_matrix.from_dense(d)
    sizes = [2] * P
    sizes[-2] = 1  # uneven, but max(sizes) == tile_rows == 2
    sizes[-1] = m - sum(sizes[:-1])
    assert sum(sizes) == m and max(sizes) == 2
    c = dr_tpu.distributed_vector(m, np.float32, distribution=sizes)
    dr_tpu.fill(c, 0.0)
    bv = np.ones(m, np.float32)
    dr_tpu.gemv(c, sp, bv)
    oracle.equal(c, d @ bv)


def test_checkpoint_roundtrips_distribution(tmp_path):
    P = dr_tpu.nprocs()
    n = 23
    sizes = _uneven_sizes(n, P, seed=6)
    src = np.arange(n, dtype=np.float32)
    dv = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
    path = str(tmp_path / "dv_dist")
    dr_tpu.checkpoint.save(path, dv)
    back = dr_tpu.checkpoint.load(path)
    assert back.layout == dv.layout  # placement survives, not just values
    np.testing.assert_allclose(dr_tpu.to_numpy(back), src)


def _no_materialize(monkeypatch):
    """Arm: any to_array during the armed window fails the test."""
    def boom(self):
        raise AssertionError("materialize fallback taken on a native path")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)


def test_identityless_scan_on_uneven_is_native(monkeypatch, oracle):
    """Round-4: identityless custom ops run the shard_map scan program
    on uneven layouts too (real totals at local[valid-1], empty-shard-
    skipping fold) — no materialize (VERDICT r3 item 5)."""
    P = dr_tpu.nprocs()
    if P < 3:
        pytest.skip("needs a mesh with an empty team shard")
    op = lambda a, b: a + b + a * b * 0.25  # unclassified op, no identity
    sizes = [5, 0] + list(dr_tpu.even_sizes(18, P - 2))
    n = sum(sizes)
    src = np.random.default_rng(8).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    a.assign_array(src)
    out = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    ex = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    _no_materialize(monkeypatch)
    dr_tpu.inclusive_scan(a, out, op)
    dr_tpu.exclusive_scan(a, ex, init=None, op=op)
    monkeypatch.undo()
    ref = np.empty(n, np.float32)
    acc = src[0]
    ref[0] = acc
    for i in range(1, n):
        acc = acc + src[i] + acc * src[i] * 0.25
        ref[i] = acc
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=2e-4,
                               atol=1e-4)
    # exclusive (no init): global shift of the inclusive result with a
    # dtype zero at position 0 — across the EMPTY shard boundary too
    exref = np.concatenate([[0.0], ref[:-1]]).astype(np.float32)
    np.testing.assert_allclose(dr_tpu.to_numpy(ex), exref, rtol=2e-4,
                               atol=1e-4)


def test_native_paths_do_not_materialize(monkeypatch):
    """The advertised uneven-native surface (sort incl. windows,
    sort_by_key incl. MIXED distributions, is_sorted, scans, reduce,
    elementwise) must never call to_array — the remaining fallbacks
    are f64, windowed sort_by_key/scans, and mismatched shard counts
    (VERDICT r3 item 5)."""
    P = dr_tpu.nprocs()
    sizes = _uneven_sizes(21, P, seed=13)
    n = sum(sizes)
    src = np.random.default_rng(13).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    a.assign_array(src)
    k = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    k.assign_array(src)
    v = dr_tpu.distributed_vector(n, np.int32, distribution=sizes)
    v.assign_array(np.arange(n, dtype=np.int32))
    s = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    _no_materialize(monkeypatch)
    dr_tpu.sort(a)
    dr_tpu.is_sorted(a)
    dr_tpu.sort_by_key(k, v)
    dr_tpu.inclusive_scan(a, s)
    dr_tpu.exclusive_scan(a, s, init=1.0)
    dr_tpu.inclusive_scan(a, s, op=jnp.multiply)
    dr_tpu.reduce(a)
    dr_tpu.fill(s, 1.0)
    monkeypatch.undo()


def test_fallbacks_warn_once(monkeypatch):
    """Leaving a fast path announces itself once per (op, reason) —
    no silent perf cliffs (VERDICT r3 item 5)."""
    import warnings as w
    from dr_tpu.utils import fallback
    from dr_tpu.utils.fallback import MaterializeFallbackWarning
    monkeypatch.setattr(fallback, "_seen", set())
    monkeypatch.delenv("DR_TPU_SILENCE_FALLBACKS", raising=False)
    n = 24
    rng = np.random.default_rng(1)
    a = dr_tpu.distributed_vector.from_array(
        rng.standard_normal(n).astype(np.float32))
    out = dr_tpu.distributed_vector(n, np.float32)
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        # the LAST warned route: a scan over a non-distributed input
        # (every distributed shape is native after round 5)
        dr_tpu.inclusive_scan([1.0, 2.0, 3.0], out[0:3])
        dr_tpu.inclusive_scan([1.0, 2.0, 3.0], out[0:3])  # once only
    hits = [r for r in rec if issubclass(r.category,
                                         MaterializeFallbackWarning)]
    assert len(hits) == 1, [str(r.message) for r in rec]
    assert "multi-component or host" in str(hits[0].message).lower()


def _fuzz_unclassified(a, b):
    return a + b + a * b * np.float32(0.125)


def test_reduce_multicomponent_custom_op_warns(monkeypatch):
    """Round-6 satellite (ADVICE r5): a custom-op reduce over a
    MULTI-component distributed chain (transform over zip) still
    materializes — it must announce the cliff once, like the scan
    catch-all, and still produce the serial result."""
    import warnings as w
    from dr_tpu.utils import fallback
    from dr_tpu.utils.fallback import MaterializeFallbackWarning
    from dr_tpu.views import views
    monkeypatch.setattr(fallback, "_seen", set())
    monkeypatch.delenv("DR_TPU_SILENCE_FALLBACKS", raising=False)
    n = 20
    rng = np.random.default_rng(2)
    a_src = rng.uniform(0.5, 1.5, n).astype(np.float32)
    b_src = rng.uniform(0.5, 1.5, n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(a_src)
    b = dr_tpu.distributed_vector.from_array(b_src)
    z = views.transform(views.zip_view(a, b), lambda x, y: x * y)
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        got = dr_tpu.reduce(z, op=_fuzz_unclassified)
        dr_tpu.reduce(z, op=_fuzz_unclassified)  # once only
    hits = [r for r in rec if issubclass(r.category,
                                         MaterializeFallbackWarning)]
    assert len(hits) == 1, [str(r.message) for r in rec]
    assert "multi-component custom-op" in str(hits[0].message)
    acc = np.float32(a_src[0] * b_src[0])
    for x in (a_src[1:] * b_src[1:]):
        acc = _fuzz_unclassified(acc, np.float32(x))
    np.testing.assert_allclose(got, acc, rtol=1e-3)

    # the SINGLE-chain custom-op route stays native and silent
    monkeypatch.setattr(fallback, "_seen", set())
    with w.catch_warnings(record=True) as rec2:
        w.simplefilter("always")
        dr_tpu.reduce(a, op=_fuzz_unclassified)
    assert not [r for r in rec2 if issubclass(
        r.category, MaterializeFallbackWarning)]
