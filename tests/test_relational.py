"""Relational analytics layer (docs/SPEC.md §17): join / groupby /
unique / histogram / top_k vs pandas/numpy oracles — eager, deferred
(fusible AND opaque), elastic replay, serve wire round trip, and the
failure matrix."""

import os
import tempfile

import numpy as np
import pandas as pd
import pytest

import dr_tpu
from dr_tpu import views
from dr_tpu.utils import faults, resilience, sanitize
from dr_tpu.utils.env import env_override


def _mk(rng, n, dtype=np.float32, lo=0, hi=8, ints=False):
    if ints:
        src = rng.integers(lo, hi, n).astype(dtype)
    else:
        src = rng.standard_normal(n).astype(dtype)
    return src, dr_tpu.distributed_vector.from_array(src)


def _hist_oracle(x, bins, lo, hi):
    """The §17.1 bucket rule in numpy: floor((x-lo)*bins/(hi-lo)),
    right edge inclusive in the last bucket, out-of-range dropped."""
    x = np.asarray(x, np.float64)
    inr = (x >= lo) & (x <= hi)
    b = np.minimum(np.floor((x[inr] - lo) * bins / (hi - lo))
                   .astype(np.int64), bins - 1)
    return np.bincount(b, minlength=bins)


# ---------------------------------------------------------------- groupby

@pytest.mark.parametrize("agg", ["sum", "min", "max", "count", "mean"])
def test_groupby_aggregate_vs_pandas(agg):
    rng = np.random.default_rng(7)
    n = 57
    keys, kv = _mk(rng, n, ints=True, hi=9)
    vals, vv = _mk(rng, n)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    ng = dr_tpu.groupby_aggregate(kv, vv, ok, ov, agg=agg)
    ref = getattr(pd.DataFrame({"k": keys, "v": vals})
                  .groupby("k")["v"], agg)()
    assert ng == len(ref)
    np.testing.assert_array_equal(dr_tpu.to_numpy(ok)[:ng],
                                  ref.index.values.astype(np.float32))
    np.testing.assert_allclose(dr_tpu.to_numpy(ov)[:ng],
                               ref.values.astype(np.float32),
                               rtol=1e-5, atol=1e-6)
    # the tail contract: positions >= ngroups are ZERO
    assert not dr_tpu.to_numpy(ok)[ng:].any()
    assert not dr_tpu.to_numpy(ov)[ng:].any()


def test_groupby_count_without_values():
    rng = np.random.default_rng(8)
    n = 33
    keys, kv = _mk(rng, n, ints=True, hi=5)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.int32)
    ng = dr_tpu.groupby_aggregate(kv, None, ok, ov, agg="count")
    uk, uc = np.unique(keys, return_counts=True)
    assert ng == len(uk)
    np.testing.assert_array_equal(dr_tpu.to_numpy(ok)[:ng], uk)
    np.testing.assert_array_equal(dr_tpu.to_numpy(ov)[:ng], uc)


def test_groupby_all_equal_and_all_distinct_keys():
    rng = np.random.default_rng(9)
    n = 29
    vals, vv = _mk(rng, n)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    # all-equal: one group spanning every shard boundary
    keys = np.full(n, 3.5, np.float32)
    kv = dr_tpu.distributed_vector.from_array(keys)
    ng = dr_tpu.groupby_aggregate(kv, vv, ok, ov, agg="sum")
    assert ng == 1
    np.testing.assert_allclose(dr_tpu.to_numpy(ov)[0],
                               vals.astype(np.float64).sum(),
                               rtol=1e-5)
    # all-distinct: every element its own group
    keys2 = np.arange(n, dtype=np.float32)
    kv2 = dr_tpu.distributed_vector.from_array(keys2)
    ng = dr_tpu.groupby_aggregate(kv2, vv, ok, ov, agg="max")
    assert ng == n
    np.testing.assert_array_equal(dr_tpu.to_numpy(ov), vals)


def test_groupby_uneven_layouts_and_window_inputs():
    rng = np.random.default_rng(10)
    n = 41
    keys = rng.integers(0, 6, n).astype(np.float32)
    vals = rng.standard_normal(n).astype(np.float32)
    dist = [5, 0, 12, 3, 0, 9, 7, 5]
    kv = dr_tpu.distributed_vector.from_array(keys, distribution=dist)
    vv = dr_tpu.distributed_vector.from_array(vals, distribution=dist)
    ok = dr_tpu.distributed_vector(n, np.float32,
                                   distribution=[10, 0, 11, 20, 0, 0,
                                                 0, 0])
    ov = dr_tpu.distributed_vector(n, np.float32)
    ng = dr_tpu.groupby_aggregate(kv[5:30], vv[5:30], ok, ov,
                                  agg="mean")
    ref = pd.DataFrame({"k": keys[5:30], "v": vals[5:30]}) \
        .groupby("k")["v"].mean()
    assert ng == len(ref)
    np.testing.assert_array_equal(dr_tpu.to_numpy(ok)[:ng],
                                  ref.index.values.astype(np.float32))
    np.testing.assert_allclose(dr_tpu.to_numpy(ov)[:ng],
                               ref.values.astype(np.float32),
                               rtol=1e-5, atol=1e-6)


def test_unique_vs_numpy():
    rng = np.random.default_rng(11)
    n = 48
    keys, kv = _mk(rng, n, ints=True, hi=11)
    out = dr_tpu.distributed_vector(n, np.float32)
    nu = dr_tpu.unique(kv, out)
    ref = np.unique(keys)
    assert nu == len(ref)
    np.testing.assert_array_equal(dr_tpu.to_numpy(out)[:nu], ref)
    assert not dr_tpu.to_numpy(out)[nu:].any()


# ------------------------------------------------------------------- join

@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_join_vs_pandas(how):
    rng = np.random.default_rng(12)
    nl, nr = 31, 23
    lk = rng.integers(0, 7, nl).astype(np.float32)
    lv = rng.standard_normal(nl).astype(np.float32)
    rk = rng.integers(0, 7, nr).astype(np.float32)
    rv = rng.standard_normal(nr).astype(np.float32)
    cap = 512
    jk = dr_tpu.distributed_vector(cap, np.float32)
    jl = dr_tpu.distributed_vector(cap, np.float32)
    jr = dr_tpu.distributed_vector(cap, np.float32)
    m = dr_tpu.join(dr_tpu.distributed_vector.from_array(lk),
                    dr_tpu.distributed_vector.from_array(lv),
                    dr_tpu.distributed_vector.from_array(rk),
                    dr_tpu.distributed_vector.from_array(rv),
                    jk, jl, jr, how=how, fill=-9.0)
    ref = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                   pd.DataFrame({"k": rk, "rv": rv}),
                   on="k", how=how).fillna(-9.0)
    assert m == len(ref)
    got = pd.DataFrame({"k": dr_tpu.to_numpy(jk)[:m],
                        "lv": dr_tpu.to_numpy(jl)[:m],
                        "rv": dr_tpu.to_numpy(jr)[:m]})
    a = got.sort_values(["k", "lv", "rv"]).reset_index(drop=True)
    b = ref.sort_values(["k", "lv", "rv"]).reset_index(drop=True)
    np.testing.assert_allclose(a.values,
                               b.values.astype(np.float32),
                               rtol=1e-6)
    for o in (jk, jl, jr):
        assert not dr_tpu.to_numpy(o)[m:].any()


def test_join_many_to_many_duplicates():
    # duplicate keys on BOTH sides must expand multiplicatively
    lk = np.array([2, 2, 2, 5], np.int32)
    lv = np.array([1, 2, 3, 4], np.float32)
    rk = np.array([2, 2, 7], np.int32)
    rv = np.array([10, 20, 30], np.float32)
    jk = dr_tpu.distributed_vector(32, np.int32)
    jl = dr_tpu.distributed_vector(32, np.float32)
    jr = dr_tpu.distributed_vector(32, np.float32)
    m = dr_tpu.join(dr_tpu.distributed_vector.from_array(lk),
                    dr_tpu.distributed_vector.from_array(lv),
                    dr_tpu.distributed_vector.from_array(rk),
                    dr_tpu.distributed_vector.from_array(rv),
                    jk, jl, jr)
    assert m == 6  # 3 left twos x 2 right twos
    # rows ordered by (key, left pos, right pos)
    np.testing.assert_array_equal(dr_tpu.to_numpy(jl)[:m],
                                  [1, 1, 2, 2, 3, 3])
    np.testing.assert_array_equal(dr_tpu.to_numpy(jr)[:m],
                                  [10, 20, 10, 20, 10, 20])


def test_join_disjoint_and_empty_sides():
    rng = np.random.default_rng(13)
    lk = np.arange(10, dtype=np.float32)
    lv = rng.standard_normal(10).astype(np.float32)
    rk = np.arange(100, 105, dtype=np.float32)
    rv = rng.standard_normal(5).astype(np.float32)
    jk = dr_tpu.distributed_vector(16, np.float32)
    jl = dr_tpu.distributed_vector(16, np.float32)
    jr = dr_tpu.distributed_vector(16, np.float32)
    lkv = dr_tpu.distributed_vector.from_array(lk)
    lvv = dr_tpu.distributed_vector.from_array(lv)
    rkv = dr_tpu.distributed_vector.from_array(rk)
    rvv = dr_tpu.distributed_vector.from_array(rv)
    assert dr_tpu.join(lkv, lvv, rkv, rvv, jk, jl, jr) == 0
    assert not dr_tpu.to_numpy(jk).any()
    # left join against a disjoint right: every left row, filled
    m = dr_tpu.join(lkv, lvv, rkv, rvv, jk, jl, jr, how="left",
                    fill=-1.0)
    assert m == 10
    np.testing.assert_array_equal(dr_tpu.to_numpy(jr)[:m],
                                  np.full(10, -1.0, np.float32))
    # empty windows: zero rows, zeroed outputs
    assert dr_tpu.join(lkv[3:3], lvv[3:3], rkv, rvv, jk, jl, jr) == 0
    # left join against an EMPTY right side: every left row, filled
    m = dr_tpu.join(lkv, lvv, rkv[0:0], rvv[0:0], jk, jl, jr,
                    how="left", fill=-3.0)
    assert m == 10
    np.testing.assert_array_equal(dr_tpu.to_numpy(jr)[:m],
                                  np.full(10, -3.0, np.float32))


def test_join_outer_union_interleaves_by_key():
    """how="outer" (the data-plane round's satellite): unmatched rows
    of BOTH sides emit — fill on whichever value column is absent —
    interleaved in key order, matched keys expanding exactly as
    inner."""
    lk = np.array([1, 3, 3, 7], np.float32)
    lv = np.array([10, 30, 31, 70], np.float32)
    rk = np.array([0, 3, 5, 9], np.float32)
    rv = np.array([-0.5, -3.0, -5.0, -9.0], np.float32)
    jk = dr_tpu.distributed_vector(32, np.float32)
    jl = dr_tpu.distributed_vector(32, np.float32)
    jr = dr_tpu.distributed_vector(32, np.float32)
    m = dr_tpu.join(dr_tpu.distributed_vector.from_array(lk),
                    dr_tpu.distributed_vector.from_array(lv),
                    dr_tpu.distributed_vector.from_array(rk),
                    dr_tpu.distributed_vector.from_array(rv),
                    jk, jl, jr, how="outer", fill=-1.0)
    assert m == 7
    np.testing.assert_array_equal(dr_tpu.to_numpy(jk)[:m],
                                  [0, 1, 3, 3, 5, 7, 9])
    np.testing.assert_array_equal(dr_tpu.to_numpy(jl)[:m],
                                  [-1, 10, 30, 31, -1, 70, -1])
    np.testing.assert_array_equal(dr_tpu.to_numpy(jr)[:m],
                                  [-0.5, -1, -3, -3, -5, -1, -9])
    # an outer join with an EMPTY left emits every right row, filled
    lkv = dr_tpu.distributed_vector.from_array(lk)
    lvv = dr_tpu.distributed_vector.from_array(lv)
    m = dr_tpu.join(lkv[0:0], lvv[0:0],
                    dr_tpu.distributed_vector.from_array(rk),
                    dr_tpu.distributed_vector.from_array(rv),
                    jk, jl, jr, how="outer", fill=-2.0)
    assert m == 4
    np.testing.assert_array_equal(dr_tpu.to_numpy(jk)[:m], rk)
    np.testing.assert_array_equal(dr_tpu.to_numpy(jl)[:m],
                                  np.full(4, -2.0, np.float32))
    np.testing.assert_array_equal(dr_tpu.to_numpy(jr)[:m], rv)


# -------------------------------------------------------------- histogram

def test_histogram_vs_numpy():
    rng = np.random.default_rng(14)
    n = 77
    vals, vv = _mk(rng, n)
    out = dr_tpu.distributed_vector(9, np.int32)
    dr_tpu.histogram(vv, out, -2.5, 2.5)
    np.testing.assert_array_equal(dr_tpu.to_numpy(out),
                                  _hist_oracle(vals, 9, -2.5, 2.5))
    # integer-valued data sits away from bucket edges: the §17.1 rule
    # and np.histogram agree exactly there
    ints, iv = _mk(rng, n, ints=True, hi=10)
    out2 = dr_tpu.distributed_vector(5, np.int32)
    dr_tpu.histogram(iv, out2, -0.5, 9.5)
    ref, _ = np.histogram(ints, bins=5, range=(-0.5, 9.5))
    np.testing.assert_array_equal(dr_tpu.to_numpy(out2), ref)


def test_histogram_window_chain_and_program_reuse():
    rng = np.random.default_rng(15)
    n = 64
    vals, vv = _mk(rng, n)
    out = dr_tpu.distributed_vector(7, np.float32)
    tview = views.transform(vv[8:40], _double)
    dr_tpu.histogram(tview, out, -3.0, 3.0)
    np.testing.assert_array_equal(
        dr_tpu.to_numpy(out), _hist_oracle(vals[8:40] * 2, 7, -3, 3))
    # lo/hi are traced operands: a streamed range must reuse ONE
    # compiled program
    with sanitize.zero_recompile("histogram lo/hi stream"):
        for w in (1.0, 1.5, 2.0):
            dr_tpu.histogram(tview, out, -w, w)
    np.testing.assert_array_equal(
        dr_tpu.to_numpy(out), _hist_oracle(vals[8:40] * 2, 7, -2, 2))


def _double(x):
    return x * 2


# ------------------------------------------------------------------ top_k

def test_top_k_vs_numpy():
    rng = np.random.default_rng(16)
    n = 53
    vals, vv = _mk(rng, n)
    k = 7
    tv = dr_tpu.distributed_vector(k, np.float32)
    ti = dr_tpu.distributed_vector(k, np.int32)
    dr_tpu.top_k(vv, tv, ti)
    gv, gi = dr_tpu.to_numpy(tv), dr_tpu.to_numpy(ti)
    np.testing.assert_allclose(gv, np.sort(vals)[::-1][:k])
    np.testing.assert_array_equal(vals[gi], gv)
    assert len(set(gi.tolist())) == k
    # smallest-first
    dr_tpu.top_k(vv, tv, ti, largest=False)
    np.testing.assert_allclose(dr_tpu.to_numpy(tv),
                               np.sort(vals)[:k])


def test_top_k_ties_and_k_beyond_n():
    vals = np.array([1.0, 3.0, 3.0, 0.0, 3.0], np.float32)
    vv = dr_tpu.distributed_vector.from_array(vals)
    tv = dr_tpu.distributed_vector(8, np.float32)
    ti = dr_tpu.distributed_vector(8, np.int32)
    dr_tpu.top_k(vv, tv, ti)
    gi = dr_tpu.to_numpy(ti)
    # ties keep the smaller index first; k > n pads with the finite
    # worst value and INT32_MAX indices
    np.testing.assert_array_equal(gi[:5], [1, 2, 4, 0, 3])
    assert (gi[5:] == np.iinfo(np.int32).max).all()
    fin = dr_tpu.to_numpy(tv)
    assert np.isfinite(fin).all()
    assert (fin[5:] == np.finfo(np.float32).min).all()


def test_top_k_streaming_windows_matches_global():
    rng = np.random.default_rng(17)
    n = 90
    vals, vv = _mk(rng, n)
    k = 6
    tv = dr_tpu.distributed_vector(k, np.float32)
    ti = dr_tpu.distributed_vector(k, np.int32)
    dr_tpu.top_k(vv[0:30], tv, ti)
    dr_tpu.top_k(views.subrange(vv, 30, 60), tv, ti, merge=True)
    dr_tpu.top_k(views.subrange(vv, 60, n), tv, ti, merge=True)
    np.testing.assert_allclose(np.sort(dr_tpu.to_numpy(tv))[::-1],
                               np.sort(vals)[::-1][:k])


# ------------------------------------------------------- deferred plans

def test_deferred_fusible_histogram_top_k_bit_equal():
    rng = np.random.default_rng(18)
    n = 45
    vals, vv = _mk(rng, n)
    hb_e = dr_tpu.distributed_vector(6, np.int32)
    tv_e = dr_tpu.distributed_vector(5, np.float32)
    ti_e = dr_tpu.distributed_vector(5, np.int32)
    dr_tpu.histogram(vv, hb_e, -2.0, 2.0)
    dr_tpu.top_k(vv, tv_e, ti_e)

    hb = dr_tpu.distributed_vector(6, np.int32)
    tv = dr_tpu.distributed_vector(5, np.float32)
    ti = dr_tpu.distributed_vector(5, np.int32)
    with dr_tpu.deferred() as p:
        dr_tpu.histogram(vv, hb, -2.0, 2.0)
        dr_tpu.top_k(vv, tv, ti)
    st = p.stats()
    assert st["fused_runs"] == 1 and st["fused_ops"] == 2 \
        and st["opaque_ops"] == 0
    np.testing.assert_array_equal(dr_tpu.to_numpy(hb),
                                  dr_tpu.to_numpy(hb_e))
    np.testing.assert_array_equal(dr_tpu.to_numpy(tv),
                                  dr_tpu.to_numpy(tv_e))
    np.testing.assert_array_equal(dr_tpu.to_numpy(ti),
                                  dr_tpu.to_numpy(ti_e))
    # re-record with DIFFERENT lo/hi: traced operands, so the fused
    # program is a cache hit (zero recompile)
    with sanitize.zero_recompile("relational plan re-record"), \
            dr_tpu.deferred() as p2:
        dr_tpu.histogram(vv, hb, -1.0, 1.0)
        dr_tpu.top_k(vv, tv, ti)
    assert p2.stats()["cache_hits"] == 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(hb),
                                  _hist_oracle(vals, 6, -1, 1))


def test_deferred_opaque_groupby_join_order_and_counts():
    rng = np.random.default_rng(19)
    n = 40
    keys, kv = _mk(rng, n, ints=True, hi=5)
    vals, vv = _mk(rng, n)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    uo = dr_tpu.distributed_vector(n, np.float32)
    with dr_tpu.deferred() as p:
        # the fill BEFORE must land first (record order): groupby's
        # scratch copy reads vv's post-fill state
        dr_tpu.fill(vv, 1.0)
        ng = dr_tpu.groupby_aggregate(kv, vv, ok, ov, agg="sum")
        nu = dr_tpu.unique(kv, uo)
        assert isinstance(ng, dr_tpu.DeferredCount)
    uk, uc = np.unique(keys, return_counts=True)
    assert int(ng) == len(uk) and nu == len(uk)
    # values were all-ones at flush time -> per-group sums = counts
    np.testing.assert_allclose(dr_tpu.to_numpy(ov)[:int(ng)],
                               uc.astype(np.float32))
    names = [o for e in p.log for i in e["items"]
             for o in ([i["name"]] if i["kind"] == "opaque"
                       else i["ops"])]
    assert names == ["fill", "groupby_aggregate", "unique"]


def test_deferred_faulted_flush_breaks_count():
    rng = np.random.default_rng(20)
    n = 24
    _, kv = _mk(rng, n, ints=True, hi=4)
    _, vv = _mk(rng, n)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    with faults.injected("plan.flush", "transient", times=1):
        with pytest.raises(resilience.TransientBackendError):
            with dr_tpu.deferred():
                ng = dr_tpu.groupby_aggregate(kv, vv, ok, ov)
    with pytest.raises(RuntimeError):
        int(ng)


def test_elastic_replay_relational(tmp_path):
    """Device loss mid-flush with relational ops recorded: the plan
    re-records the suffix on the shrunken mesh — fusible histogram /
    top_k AND the opaque groupby replay, counts resolve, results match
    the full-mesh oracles (ISSUE 10 acceptance)."""
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    rng = np.random.default_rng(21)
    n = 4 * P
    keys, kv = _mk(rng, n, ints=True, hi=4)
    vals, vv = _mk(rng, n)
    hb = dr_tpu.distributed_vector(4, np.int32)
    tv = dr_tpu.distributed_vector(3, np.float32)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    for nm, c in (("kv", kv), ("vv", vv), ("hb", hb), ("tv", tv),
                  ("ok", ok), ("ov", ov)):
        dr_tpu.checkpoint.save(str(tmp_path / f"{nm}.npz"), c)
    ref_h = _hist_oracle(vals, 4, -2.0, 2.0)
    ref_t = np.sort(vals)[::-1][:3]
    refg = pd.DataFrame({"k": keys, "v": vals}).groupby("k")["v"].sum()
    with env_override(DR_TPU_ELASTIC="1"):
        with faults.injected("device.lost", "device_lost", times=1):
            with dr_tpu.deferred() as p:
                dr_tpu.histogram(vv, hb, -2.0, 2.0)
                dr_tpu.top_k(vv, tv)
                ng = dr_tpu.groupby_aggregate(kv, vv, ok, ov,
                                              agg="sum")
    assert dr_tpu.nprocs() == P - 1
    assert "elastic replay" in [e["reason"] for e in p.log]
    assert int(ng) == len(refg)
    np.testing.assert_array_equal(dr_tpu.to_numpy(hb), ref_h)
    np.testing.assert_allclose(dr_tpu.to_numpy(tv), ref_t)
    np.testing.assert_allclose(dr_tpu.to_numpy(ov)[:int(ng)],
                               refg.values.astype(np.float32),
                               rtol=1e-5)


# ---------------------------------------------------------- failure matrix

def test_relational_api_misuse_raises_at_call_site():
    rng = np.random.default_rng(22)
    n = 16
    _, kv = _mk(rng, n)
    _, vv = _mk(rng, n)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    with pytest.raises(ValueError, match="unknown agg"):
        dr_tpu.groupby_aggregate(kv, vv, ok, ov, agg="median")
    with pytest.raises(ValueError, match="needs values"):
        dr_tpu.groupby_aggregate(kv, None, ok, ov, agg="sum")
    with pytest.raises(ValueError, match="unknown how"):
        dr_tpu.join(kv, vv, kv, vv, ok, ov, ov, how="cross")
    with pytest.raises(TypeError, match="key dtypes"):
        ik = dr_tpu.distributed_vector(n, np.int32)
        dr_tpu.join(kv, vv, ik, vv, ok, ov, ov)
    with pytest.raises(ValueError, match="equal length"):
        dr_tpu.groupby_aggregate(kv[0:4], vv, ok, ov)
    with pytest.raises(TypeError, match="whole"):
        dr_tpu.unique(kv, ok[0:4])
    with pytest.raises(ValueError, match="hi > lo"):
        dr_tpu.histogram(kv, ok, 2.0, 2.0)
    with pytest.raises(TypeError, match="int32"):
        dr_tpu.top_k(kv, dr_tpu.distributed_vector(8, np.float32),
                     dr_tpu.distributed_vector(8, np.float32))
    # misuse inside a deferred region raises IMMEDIATELY (nothing
    # recorded) and the region still flushes clean
    with dr_tpu.deferred() as p:
        with pytest.raises(ValueError, match="unknown agg"):
            dr_tpu.groupby_aggregate(kv, vv, ok, ov, agg="nope")
    assert p.stats()["fused_ops"] == 0


def test_groupby_out_key_dtype_casts():
    """Review regression: out_keys of a DIFFERENT dtype decode through
    the KEY dtype and then cast (int out_keys used to receive raw
    encoding bits; float out_keys of int keys decoded to NaN)."""
    keys = np.array([3.0, 1.0, 3.0, 2.0, 1.0], np.float32)
    vals = np.ones(5, np.float32)
    kv = dr_tpu.distributed_vector.from_array(keys)
    vv = dr_tpu.distributed_vector.from_array(vals)
    oki = dr_tpu.distributed_vector(5, np.int32)
    ov = dr_tpu.distributed_vector(5, np.float32)
    ng = dr_tpu.groupby_aggregate(kv, vv, oki, ov)
    np.testing.assert_array_equal(dr_tpu.to_numpy(oki)[:ng],
                                  [1, 2, 3])
    ik = dr_tpu.distributed_vector.from_array(
        keys.astype(np.int32))
    okf = dr_tpu.distributed_vector(5, np.float32)
    ng = dr_tpu.groupby_aggregate(ik, vv, okf, ov)
    np.testing.assert_array_equal(dr_tpu.to_numpy(okf)[:ng],
                                  [1.0, 2.0, 3.0])


def test_groupby_unequal_out_capacities_rejected():
    """Review regression: a smaller out_values used to silently drop
    aggregates while ng claimed them all."""
    rng = np.random.default_rng(27)
    _, kv = _mk(rng, 16, ints=True, hi=12)
    _, vv = _mk(rng, 16)
    ok = dr_tpu.distributed_vector(32, np.float32)
    ov = dr_tpu.distributed_vector(8, np.float32)
    with pytest.raises(ValueError, match="share one capacity"):
        dr_tpu.groupby_aggregate(kv, vv, ok, ov)


def test_top_k_merge_needs_one_out_layout():
    """Review regression: merge pairs current values with indices BY
    SLOT — split out layouts used to mispair (or crash unclassified)."""
    rng = np.random.default_rng(28)
    _, vv = _mk(rng, 24)
    tv = dr_tpu.distributed_vector(4, np.float32)
    ti = dr_tpu.distributed_vector(4, np.int32,
                                   distribution=[4, 0, 0, 0, 0, 0, 0,
                                                 0])
    dr_tpu.top_k(vv, tv, ti)  # non-merge: independent layouts are fine
    with pytest.raises(TypeError, match="ONE layout"):
        dr_tpu.top_k(vv, tv, ti, merge=True)


def test_deferred_misuse_raises_before_recording():
    """Review regression (§17.5): join/groupby argument errors must
    raise AT the call site inside a deferred region — nothing records,
    the region flushes clean, no batchmate dies at flush."""
    rng = np.random.default_rng(29)
    _, kv = _mk(rng, 8)
    _, vv = _mk(rng, 8)
    _, short = _mk(rng, 6)
    ok = dr_tpu.distributed_vector(8, np.float32)
    ov = dr_tpu.distributed_vector(8, np.float32)
    small = dr_tpu.distributed_vector(4, np.float32)
    ik = dr_tpu.distributed_vector(8, np.int32)
    with dr_tpu.deferred() as p:
        with pytest.raises(ValueError, match="equal length"):
            dr_tpu.join(kv, short, kv, vv, ok, ov, ov)
        with pytest.raises(TypeError, match="key dtypes"):
            dr_tpu.join(kv, vv, ik, vv, ok, ov, ov)
        with pytest.raises(ValueError, match="share one capacity"):
            dr_tpu.join(kv, vv, kv, vv, ok, ov, small)
        with pytest.raises(ValueError, match="share one capacity"):
            dr_tpu.groupby_aggregate(kv, vv, ok, small)
        with pytest.raises(ValueError, match="equal length"):
            dr_tpu.groupby_aggregate(kv, short, ok, ov)
    assert p.stats()["fused_ops"] == 0 \
        and p.stats()["opaque_ops"] == 0


def test_relational_capacity_overflow_classified():
    rng = np.random.default_rng(23)
    n = 24
    _, kv = _mk(rng, n, ints=True, hi=12)
    _, vv = _mk(rng, n)
    s1 = dr_tpu.distributed_vector(2, np.float32)
    s2 = dr_tpu.distributed_vector(2, np.float32)
    with pytest.raises(resilience.ProgramError, match="rows"):
        dr_tpu.groupby_aggregate(kv, vv, s1, s2)
    with pytest.raises(resilience.ProgramError, match="rows"):
        dr_tpu.unique(kv, s1)
    ones = dr_tpu.distributed_vector.from_array(np.ones(16, np.float32))
    with pytest.raises(resilience.ProgramError, match="rows"):
        dr_tpu.join(ones, ones, ones, ones, s1, s1, s2)


# ----------------------------------------------------------------- serve

def test_serve_relational_round_trip(tmp_path):
    from dr_tpu import serve
    rng = np.random.default_rng(24)
    sock = os.path.join(str(tmp_path), "rel.sock")
    srv = serve.Server(sock, batch_window=0.0)
    srv.start()
    try:
        with serve.Client(sock, timeout=60.0) as c:
            lk = rng.integers(0, 6, 24).astype(np.float32)
            lv = rng.standard_normal(24).astype(np.float32)
            rk = rng.integers(0, 6, 18).astype(np.float32)
            rv = rng.standard_normal(18).astype(np.float32)
            jk, jl, jr = c.join(lk, lv, rk, rv)
            ref = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                           pd.DataFrame({"k": rk, "rv": rv}), on="k")
            assert len(jk) == len(ref)
            # the outer union serves over the SAME wire op (§17.3)
            ok_, ol_, or_ = c.join(lk, lv, rk, rv, how="outer",
                                   fill=-5.0)
            refo = pd.merge(pd.DataFrame({"k": lk, "lv": lv}),
                            pd.DataFrame({"k": rk, "rv": rv}),
                            on="k", how="outer").fillna(-5.0)
            assert len(ok_) == len(refo)
            got = pd.DataFrame({"k": ok_, "lv": ol_, "rv": or_}) \
                .sort_values(["k", "lv", "rv"]).reset_index(drop=True)
            refo = refo.sort_values(["k", "lv", "rv"]) \
                .reset_index(drop=True)
            np.testing.assert_allclose(
                got.values, refo.values.astype(np.float32), rtol=1e-5)
            gk, gv = c.groupby(lk, lv, agg="mean")
            refg = pd.DataFrame({"k": lk, "v": lv}) \
                .groupby("k")["v"].mean()
            np.testing.assert_allclose(gv,
                                       refg.values.astype(np.float32),
                                       rtol=1e-5)
            np.testing.assert_array_equal(c.unique(lk), np.unique(lk))
            tv, ti = c.top_k(lv, 4)
            np.testing.assert_allclose(tv, np.sort(lv)[::-1][:4])
            np.testing.assert_array_equal(lv[ti.astype(np.int64)], tv)
            h = c.histogram(lv, 6, -2.0, 2.0)
            np.testing.assert_array_equal(h,
                                          _hist_oracle(lv, 6, -2, 2))
            # classified errors cross the wire as the SAME class
            with pytest.raises(resilience.ProgramError):
                c.groupby(lk, lv, agg="median")
            with pytest.raises(resilience.ProgramError):
                ones = np.ones(64, np.float32)
                c.join(ones, ones, ones, ones, capacity=8)
            # the daemon survived both rejections
            assert c.ping()["pong"]
    finally:
        srv.stop()


def test_serve_topk_histogram_batch_into_one_flush(tmp_path):
    """The fusible relational ops join the shared deferred flush:
    held-queue topk + histogram + scale from one client dispatch as
    ONE batch (batched_requests counts them)."""
    from dr_tpu import serve
    import threading
    rng = np.random.default_rng(25)
    sock = os.path.join(str(tmp_path), "relb.sock")
    srv = serve.Server(sock, batch_window=0.05, batch_max=8)
    srv.start()
    try:
        x = rng.standard_normal(64).astype(np.float32)
        with serve.Client(sock, timeout=60.0) as c:
            c.top_k(x, 3)  # warm the programs outside the held batch
            c.histogram(x, 4, -2.0, 2.0)
        srv.hold()
        results = {}

        def go(name, fn):
            results[name] = fn()

        with serve.Client(sock, timeout=60.0) as c1, \
                serve.Client(sock, timeout=60.0) as c2, \
                serve.Client(sock, timeout=60.0) as c3:
            ts = [threading.Thread(target=go, args=("t", lambda:
                                                    c1.top_k(x, 3))),
                  threading.Thread(target=go, args=("h", lambda:
                                                    c2.histogram(
                                                        x, 4, -2.0,
                                                        2.0))),
                  threading.Thread(target=go, args=("s", lambda:
                                                    c3.scale(x,
                                                             a=2.0)))]
            for t in ts:
                t.start()
            import time
            time.sleep(0.3)  # let all three requests queue
            srv.release()
            for t in ts:
                t.join(timeout=30.0)
        st = srv.stats()
        assert st["batch_hw"] >= 3, st
        np.testing.assert_allclose(results["t"][0],
                                   np.sort(x)[::-1][:3])
        np.testing.assert_array_equal(results["h"],
                                      _hist_oracle(x, 4, -2, 2))
        np.testing.assert_allclose(results["s"], x * 2.0, rtol=1e-6)
    finally:
        srv.stop()


# ------------------------------------------------------------------- obs

def test_relational_obs_spans():
    from dr_tpu import obs
    rng = np.random.default_rng(26)
    n = 32
    _, kv = _mk(rng, n, ints=True, hi=5)
    _, vv = _mk(rng, n)
    ok = dr_tpu.distributed_vector(n, np.float32)
    ov = dr_tpu.distributed_vector(n, np.float32)
    jk = dr_tpu.distributed_vector(256, np.float32)
    obs.reset()
    obs.arm(True)
    try:
        dr_tpu.groupby_aggregate(kv, vv, ok, ov)
        dr_tpu.join(kv, vv, kv, vv, jk,
                    dr_tpu.distributed_vector(256, np.float32),
                    dr_tpu.distributed_vector(256, np.float32))
        dr_tpu.histogram(vv, dr_tpu.distributed_vector(4, np.int32),
                         -2.0, 2.0)
        dr_tpu.top_k(vv, dr_tpu.distributed_vector(3, np.float32))
        evs = obs.events()
    finally:
        obs.arm(False)
        obs.reset()
    names = {e.get("name") for e in evs}
    assert {"relational.groupby", "relational.join",
            "relational.histogram", "relational.top_k"} <= names
    phases = {e.get("args", {}).get("phase") for e in evs
              if e.get("name") == "relational.phase"}
    # the join's time splits into visible phases
    assert {"sort_left", "sort_right", "merge", "sort",
            "aggregate"} <= phases


# ------------------------------------------------- join repartition (§18.4)

def test_join_partition_bounds_memory_and_matches_broadcast():
    """ISSUE 12 acceptance: above the threshold the join merge runs
    the repartition exchange — the merge program's gathered channel is
    the rcap-bounded right partition, NOT a full-side all_gather — and
    its rows are bit-identical to the broadcast route and pandas."""
    from dr_tpu.algorithms import relational as rel
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("the repartition route needs >= 2 shards")
    rng = np.random.default_rng(33)
    nl, nr = 96, 64
    kl = rng.integers(0, 24, nl).astype(np.float32)   # uniform keys
    kr = rng.integers(0, 24, nr).astype(np.float32)
    vl = rng.standard_normal(nl).astype(np.float32)
    vr = rng.standard_normal(nr).astype(np.float32)
    cap = 4 * (nl + nr)

    def run(thresh):
        a = dr_tpu.distributed_vector.from_array(kl)
        b = dr_tpu.distributed_vector.from_array(vl)
        c = dr_tpu.distributed_vector.from_array(kr)
        d = dr_tpu.distributed_vector.from_array(vr)
        ok = dr_tpu.distributed_vector(cap)
        ol = dr_tpu.distributed_vector(cap)
        orr = dr_tpu.distributed_vector(cap)
        with env_override(DR_TPU_JOIN_BROADCAST_MAX=thresh):
            m = dr_tpu.join(a, b, c, d, ok, ol, orr)
        return (int(m), dr_tpu.to_numpy(ok), dr_tpu.to_numpy(ol),
                dr_tpu.to_numpy(orr), rel.last_join_route())

    mb, okb, olb, orb, rb = run("999999999")
    assert rb["impl"] == "broadcast"
    mp, okp, olp, orp, rp = run("0")
    assert rp["impl"] == "partition"
    # the ACCEPTANCE assertion: the merge program's gathered channel
    # (the right partition) stays under the full side, and the
    # per-device working set under the broadcast route's
    NR = rp["nshards"] * -(-nr // rp["nshards"])
    assert rp["rcap"] < NR, rp
    assert rp["gathered_rows_per_device"] \
        < rb["gathered_rows_per_device"], (rp, rb)
    assert mb == mp
    np.testing.assert_array_equal(okb, okp)
    np.testing.assert_array_equal(olb, olp)
    np.testing.assert_array_equal(orb, orp)
    ref = pd.merge(pd.DataFrame({"k": kl, "a": vl}),
                   pd.DataFrame({"k": kr, "b": vr}), on="k")
    assert mp == len(ref)


def test_join_partition_default_threshold_routes_small_broadcast():
    """The default DR_TPU_JOIN_BROADCAST_MAX keeps small joins on the
    broadcast fast path — the routing knob, not the data, decides."""
    from dr_tpu.algorithms import relational as rel
    rng = np.random.default_rng(34)
    n = 24
    keys, kv = _mk(rng, n, ints=True, hi=6)
    vals, vv = _mk(rng, n)
    cap = n * n
    ok = dr_tpu.distributed_vector(cap)
    ol = dr_tpu.distributed_vector(cap)
    orr = dr_tpu.distributed_vector(cap)
    dr_tpu.join(kv, vv, kv, vv, ok, ol, orr)
    assert rel.last_join_route()["impl"] == "broadcast"


def test_join_int_pad_sentinel_keys_match_pandas():
    """Round-16 fix: an INTEGER key equal to the dtype's max (the sort
    pad sentinel) must not count the pad rows as matches — both merge
    routes, vs pandas."""
    from dr_tpu.algorithms import relational as rel
    ik = np.array([0, 5, 2**31 - 1, 7, 2**31 - 1, -2**31], np.int32)
    jk = np.array([2**31 - 1, 5, -2**31, 9], np.int32)
    iv = np.arange(len(ik), dtype=np.int32)
    jv = np.arange(len(jk), dtype=np.int32)
    ref = pd.merge(pd.DataFrame({"k": ik, "a": iv}),
                   pd.DataFrame({"k": jk, "b": jv}), on="k")
    for thresh in ("999999999", "0"):
        if thresh == "0" and dr_tpu.nprocs() < 2:
            continue
        a = dr_tpu.distributed_vector.from_array(ik)
        b = dr_tpu.distributed_vector.from_array(iv)
        c = dr_tpu.distributed_vector.from_array(jk)
        d = dr_tpu.distributed_vector.from_array(jv)
        ok = dr_tpu.distributed_vector(32, np.int32)
        ol = dr_tpu.distributed_vector(32, np.int32)
        orr = dr_tpu.distributed_vector(32, np.int32)
        with env_override(DR_TPU_JOIN_BROADCAST_MAX=thresh):
            m = dr_tpu.join(a, b, c, d, ok, ol, orr)
        assert int(m) == len(ref), (thresh, int(m), len(ref))
        got = sorted(zip(dr_tpu.to_numpy(ok)[:m].tolist(),
                         dr_tpu.to_numpy(ol)[:m].tolist(),
                         dr_tpu.to_numpy(orr)[:m].tolist()))
        want = sorted(zip(ref["k"].tolist(), ref["a"].tolist(),
                          ref["b"].tolist()))
        assert got == want, thresh
