"""L0 vocabulary tests: CPOs, concepts, segment invariants.

Mirrors the static_assert-style concept checks in the reference tests
(``test/gtest/mhp/distributed_vector.cpp:12-24``, ``views.cpp:20-29``).
"""

import numpy as np
import pytest

import dr_tpu
from dr_tpu import views


def test_distributed_vector_is_distributed_range():
    dv = dr_tpu.distributed_vector(10)
    assert dr_tpu.is_distributed_range(dv)
    assert dr_tpu.is_distributed_contiguous_range(dv)


def test_segments_cover_and_ranks(mesh_size):
    n = 23
    dv = dr_tpu.distributed_vector(n)
    segs = dr_tpu.segments(dv)
    assert sum(len(s) for s in segs) == n
    ranks = [dr_tpu.rank(s) for s in segs]
    assert ranks == sorted(ranks)
    assert all(0 <= r < mesh_size for r in ranks)
    # each segment is a remote contiguous range
    for s in segs:
        assert dr_tpu.is_remote_range(s)
        assert dr_tpu.is_remote_contiguous_range(s)


def test_local_returns_shard_values():
    dv = dr_tpu.distributed_vector(16)
    dr_tpu.iota(dv, 0)
    for s in dr_tpu.segments(dv):
        loc = dr_tpu.local(s)
        np.testing.assert_array_equal(
            np.asarray(loc), np.arange(s.begin, s.end, dtype=np.float32))


def test_local_identity_fallback_for_host_objects():
    x = [1, 2, 3]
    assert dr_tpu.local(x) is x


def test_rank_raises_for_plain_objects():
    with pytest.raises(TypeError):
        dr_tpu.rank([1, 2, 3])


def test_segment_slicing_keeps_rank():
    dv = dr_tpu.distributed_vector(32)
    dr_tpu.iota(dv, 0)
    s = dr_tpu.segments(dv)[0]
    sub = s[1:3]
    assert dr_tpu.rank(sub) == dr_tpu.rank(s)
    assert len(sub) == 2
    np.testing.assert_array_equal(sub.materialize(),
                                  s.materialize()[1:3])


def test_check_segments_invariant(oracle):
    dv = dr_tpu.distributed_vector(41)
    dr_tpu.iota(dv, 7)
    oracle.check_segments(dv)
