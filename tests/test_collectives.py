"""communicator / rma_window / unstructured_halo / distributed_span tests
(reference details/communicator.hpp, details/halo.hpp:148-271,
shp/distributed_span.hpp)."""

import numpy as np
import pytest

import dr_tpu


def test_communicator_topology():
    comm = dr_tpu.default_comm()
    assert comm.size == dr_tpu.nprocs()
    assert comm.first() == 0 and comm.last() == comm.size - 1
    assert comm.next(comm.last()) == 0
    assert comm.prev(0) == comm.last()


def test_bcast_scatter_gather():
    comm = dr_tpu.default_comm()
    v = np.arange(comm.size * 4, dtype=np.float32)
    sharded = comm.scatter(v)
    np.testing.assert_array_equal(comm.gather(sharded), v)
    rep = comm.bcast(np.array([1.0, 2.0]))
    np.testing.assert_array_equal(np.asarray(rep), [1.0, 2.0])


def test_ring_shift():
    comm = dr_tpu.default_comm()
    P = comm.size
    arr = comm.scatter(np.arange(P, dtype=np.float32).reshape(P, 1)
                       .repeat(2, 1).reshape(P, 2)[:, :1])
    fwd = comm.shift_forward(arr, periodic=True)
    got = np.asarray(fwd).ravel()
    expect = np.roll(np.arange(P), 1)
    np.testing.assert_array_equal(got, expect)
    bwd = comm.shift_backward(arr, periodic=False)
    got = np.asarray(bwd).ravel()
    # non-periodic: last shard receives zeros
    expect = np.concatenate([np.arange(1, P), [0]])
    np.testing.assert_array_equal(got, expect)


def test_alltoall():
    comm = dr_tpu.default_comm()
    P = comm.size
    if P == 1:
        pytest.skip("needs >1 rank")
    mat = np.arange(P * P, dtype=np.float32).reshape(P, P, 1)
    sharded = comm.scatter(mat)
    out = np.asarray(comm.alltoall(sharded)).reshape(P, P)
    np.testing.assert_array_equal(out, mat.reshape(P, P).T)


def test_rma_window():
    dv = dr_tpu.distributed_vector(32, dtype=np.float32)
    win = dr_tpu.rma_window(dv)
    win.put(np.array([1, 17, 31]), np.array([5.0, 6.0, 7.0]))
    win.fence()
    got = np.asarray(win.get(np.array([1, 17, 31])))
    np.testing.assert_array_equal(got, [5.0, 6.0, 7.0])
    win.flush()


def test_unstructured_halo_exchange():
    n = 32
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    # rank 1 mirrors elements {0, 5}; rank 2 mirrors {31}
    uh = dr_tpu.unstructured_halo(dv, {1: [0, 5], 2: [31]})
    uh.exchange()
    np.testing.assert_array_equal(np.asarray(uh.ghost_values(1)), [0., 5.])
    np.testing.assert_array_equal(np.asarray(uh.ghost_values(2)), [31.])


def test_unstructured_halo_reduce():
    n = 16
    dv = dr_tpu.distributed_vector.from_array(np.zeros(n, np.float32))
    uh = dr_tpu.unstructured_halo(dv, {0: [3, 7], 1: [7]})
    uh.set_ghost_values(0, np.array([1.0, 2.0]))
    uh.set_ghost_values(1, np.array([10.0]))
    uh.reduce("plus")
    got = dr_tpu.to_numpy(dv)
    assert got[3] == 1.0
    assert got[7] == 12.0  # contributions from both ghost groups combine
    uh2 = dr_tpu.unstructured_halo(dv, {0: [3]})
    uh2.set_ghost_values(0, np.array([100.0]))
    uh2.reduce("max")
    assert dr_tpu.to_numpy(dv)[3] == 100.0


def test_distributed_span_reslicing():
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(40, dtype=np.float32))
    sp = dr_tpu.distributed_span.of(dv)
    assert len(sp) == 40
    sub = sp.subspan(7, 20)
    np.testing.assert_array_equal(sub.materialize(),
                                  np.arange(7, 27, dtype=np.float32))
    np.testing.assert_array_equal(sub.first(5).materialize(),
                                  np.arange(7, 12, dtype=np.float32))
    np.testing.assert_array_equal(sub.last(3).materialize(),
                                  np.arange(24, 27, dtype=np.float32))
    # ranks preserved through re-slicing
    for s in dr_tpu.segments(sub):
        assert 0 <= dr_tpu.rank(s) < dr_tpu.nprocs()


def test_logger(tmp_path):
    log = dr_tpu.drlog
    path = tmp_path / "dr.log"
    log.set_file(str(path))
    log.debug("hello {}", 42)
    log.close()
    text = path.read_text()
    assert "hello 42" in text
    assert "test_collectives.py" in text


def test_debug_printers(capsys):
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(10, dtype=np.float32))
    dr_tpu.print_range(dv, "v")
    out = capsys.readouterr().out
    assert "v:" in out and "rank=" in out
    mat = dr_tpu.dense_matrix.from_array(np.eye(4, dtype=np.float32))
    dr_tpu.print_matrix(mat, "m")
    out = capsys.readouterr().out
    assert "shape=(4, 4)" in out
