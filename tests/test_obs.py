"""Unified tracing & metrics layer (dr_tpu/obs — docs/SPEC.md §15).

The contract under test, in order of importance:

* tracing OFF is a true no-op: the hot-path hooks stay None and the
  event counter (``obs.events_recorded`` — the dispatch-count-style
  pin) does not move while real work dispatches;
* span nesting is correct across the serve daemon's threads — a
  client request's span tree links intake → queue-wait → the SHARED
  batch-flush span → reply;
* an injected fault (``DR_TPU_FAULT_SPEC`` included) appears IN the
  trace with the right site, and classified errors carry the last-N
  events as a postmortem;
* the ring buffer caps memory under a long chain;
* the Chrome exporter and tools/trace_view.py round-trip.
"""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import dr_tpu
from dr_tpu import obs, serve
from dr_tpu.utils import faults, resilience, spmd_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "trace_view", os.path.join(REPO, "tools", "trace_view.py"))
trace_view = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(trace_view)


@pytest.fixture
def traced():
    """Arm tracing for one test and leave the world disarmed+clean."""
    obs.arm(True)
    obs.reset()
    yield obs
    obs.arm(False)
    obs.reset()


def _vec(n=64):
    v = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(v, 1.0)
    return v


# ---------------------------------------------------------------------------
# the overhead contract: off = no-op
# ---------------------------------------------------------------------------

def test_tracing_off_is_true_noop():
    assert not obs.armed()
    # the hot-path hooks must be None (one `is not None` per dispatch)
    assert spmd_guard._obs_dispatch_hook is None
    assert spmd_guard._obs_compile_hook is None
    assert faults._obs_site_hook is None
    assert faults._obs_fault_hook is None
    e0 = obs.events_recorded()
    v = _vec()
    float(dr_tpu.reduce(v))
    with dr_tpu.deferred():
        dr_tpu.fill(v, 0.5)
    # dispatches happened…
    assert spmd_guard.dispatch_count() > 0
    # …but the event counter did not move and nothing was buffered
    assert obs.events_recorded() == e0
    assert obs.events() == []
    # the disarmed span is the shared null object — no per-call alloc
    assert obs.span("x") is obs.span("y")
    assert obs.begin("x") == 0
    assert obs.now() == 0


def test_span_ending_after_disarm_records_nothing():
    """A span begun while armed whose end lands after a disarm (an
    in-flight serve request across a fixture teardown) must not move
    the counter or the ring — the no-op pin holds mid-flight too."""
    obs.arm(True)
    obs.reset()
    sid = obs.begin("straggler")
    with obs.span("cm-straggler") as sp:
        obs.arm(False)
        r0 = obs.events_recorded()
    obs.end(sid)
    assert obs.events_recorded() == r0
    assert obs.events() == []
    assert sp is not None  # it WAS an armed span when entered
    obs.reset()


def test_off_classified_errors_carry_no_tail():
    err = resilience.TransientBackendError("x", site="s")
    assert err.trace_tail is None


# ---------------------------------------------------------------------------
# recording basics
# ---------------------------------------------------------------------------

def test_span_nesting_and_events(traced):
    with obs.span("outer", cat="t") as sp:
        assert obs.current() == sp.sid
        with obs.span("inner", cat="t"):
            obs.event("tick", cat="t", k=1)
        sp.set(extra=2)
    evs = obs.events()
    names = [e["name"] for e in evs]
    # inner closes (and records) before outer
    assert names.index("inner") < names.index("outer")
    outer = next(e for e in evs if e["name"] == "outer")
    inner = next(e for e in evs if e["name"] == "inner")
    assert inner["args"]["parent"] == outer["id"]
    assert outer["args"]["extra"] == 2
    tick = next(e for e in evs if e["name"] == "tick")
    assert tick["ph"] == "i" and tick["args"]["k"] == 1
    # spans nest in time
    assert (outer["ts"] <= inner["ts"] and
            inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"])


def test_dispatch_and_compile_events_ride_the_tap(traced):
    d0 = spmd_guard.dispatch_count()
    v = _vec(128)
    float(dr_tpu.reduce(v))
    grew = spmd_guard.dispatch_count() - d0
    assert grew > 0
    evs = obs.events()
    assert sum(1 for e in evs if e["name"] == "dispatch") == grew
    # key labels are the structural tag, not a repr dump
    labels = {e["args"]["key"] for e in evs if e["name"] == "dispatch"}
    assert all(len(lbl) < 100 for lbl in labels)


def test_plan_flush_span_with_runs(traced):
    v = _vec()
    with dr_tpu.deferred():
        dr_tpu.fill(v, 0.5)
        s = dr_tpu.reduce(v)
    assert float(s) == pytest.approx(0.5 * len(v))
    evs = obs.events()
    # the span (ph=X) — distinct from the plan.flush SITE event the
    # fault-registry hook also records (ph=i, cat=site)
    flush = [e for e in evs if e["name"] == "plan.flush"
             and e["ph"] == "X"]
    assert flush and flush[0]["args"]["reason"] in ("region exit",
                                                    "scalar read")
    assert any(e["name"] == "plan.flush" and e["cat"] == "site"
               for e in evs)
    runs = [e for e in evs if e["name"] == "plan.run"]
    assert runs and runs[0]["args"]["parent"] == flush[0]["id"]
    assert runs[0]["args"]["ops"] == 2
    snap = obs.snapshot()
    assert snap["counters"]["plan.flushes"] >= 1
    assert snap["counters"]["plan.fused_ops"] >= 2


def test_log_debug_mirrors_into_trace(traced):
    from dr_tpu.utils.logging import Logger
    lg = Logger()  # sink disabled (no DR_TPU_LOG) — trace still gets it
    lg.debug("hello {}", 41 + 1)
    evs = obs.events()
    hits = [e for e in evs if e["name"] == "log.debug"]
    assert hits and "hello 42" in hits[0]["args"]["msg"]
    assert hits[0]["args"]["loc"].startswith("test_obs.py:")


# ---------------------------------------------------------------------------
# faults in the trace + postmortems
# ---------------------------------------------------------------------------

def test_injected_fault_appears_in_trace_with_site(traced):
    v = _vec(64)
    with faults.injected("dispatch.cache", "transient"):
        with pytest.raises(resilience.TransientBackendError) as ei:
            dr_tpu.fill(v, 2.0)
    evs = obs.events()
    hit = [e for e in evs if e["name"] == "fault"]
    assert hit and hit[0]["args"] == {"site": "dispatch.cache",
                                      "kind": "transient"}
    # the classified error carries the last-N events as a postmortem,
    # and the injected fault is in it
    tail = ei.value.trace_tail
    assert tail and any(e["name"] == "fault" for e in tail)


def test_fault_spec_env_injection_traced(traced, monkeypatch):
    monkeypatch.setenv("DR_TPU_FAULT_SPEC", "halo.exchange:transient")
    faults.reload_env()
    try:
        hb = dr_tpu.halo_bounds(1, 1, periodic=True)
        v = dr_tpu.distributed_vector(64, np.float32, halo=hb)
        dr_tpu.fill(v, 1.0)
        with pytest.raises(resilience.TransientBackendError):
            v.halo().exchange()
    finally:
        monkeypatch.delenv("DR_TPU_FAULT_SPEC")
        faults.reload_env()
    evs = obs.events()
    assert any(e["name"] == "fault" and
               e["args"]["site"] == "halo.exchange" for e in evs)
    # the clean site visits are on the trace too
    assert any(e["name"] == "halo.exchange" and e["cat"] == "site"
               for e in evs)


def test_retry_events_and_counter(traced):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise resilience.TransientBackendError("UNAVAILABLE: x")
        return 7

    assert resilience.retry(flaky, attempts=3, base=0.0,
                            sleep=lambda s: None) == 7
    evs = obs.events()
    retries = [e for e in evs if e["name"] == "retry"]
    assert len(retries) == 2
    assert retries[0]["args"]["error"] == "TransientBackendError"
    assert obs.snapshot()["counters"]["resilience.retries"] == 2


# ---------------------------------------------------------------------------
# ring buffer bound
# ---------------------------------------------------------------------------

def test_ring_buffer_caps_memory(monkeypatch):
    monkeypatch.setenv("DR_TPU_TRACE_BUF", "128")
    obs.arm(True)  # re-reads the cap
    try:
        obs.reset()
        r0 = obs.events_recorded()
        for i in range(1000):
            obs.event("spin", i=i)
        assert obs.events_recorded() - r0 == 1000
        evs = obs.events()
        assert len(evs) == 128
        # the ring keeps the TAIL (postmortems want the latest events)
        assert evs[-1]["args"]["i"] == 999
        assert obs.tail(5)[-1]["args"]["i"] == 999
    finally:
        obs.arm(False)
        obs.reset()
        monkeypatch.delenv("DR_TPU_TRACE_BUF")
        obs.arm(True)  # restore the default cap in the module deque
        obs.arm(False)


# ---------------------------------------------------------------------------
# serve: cross-thread span tree + stats wire op
# ---------------------------------------------------------------------------

def test_serve_span_tree_links_across_threads(traced, tmp_path):
    srv = serve.Server(str(tmp_path / "d.sock"))
    srv.start()
    try:
        with serve.Client(srv.path, timeout=60.0) as c:
            x = np.arange(48, dtype=np.float32)
            np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0)
            st = c.stats()
    finally:
        srv.stop()
    evs = obs.events()
    reqs = [e for e in evs if e["name"] == "serve.request"
            and e["ph"] == "X"]
    assert reqs, "request span missing"
    rid = reqs[0]["id"]
    # queue-wait child under the request span (recorded on the
    # DISPATCH thread, parented across threads by explicit id)
    qw = [e for e in evs if e["name"] == "serve.queue_wait"]
    assert any(e["args"].get("parent") == rid for e in qw)
    # the shared batch-flush span links back to the request
    bf = [e for e in evs if e["name"] == "serve.batch_flush"]
    assert any(rid in e["args"].get("links", ()) for e in bf)
    # request/flush spans live on different threads (reader vs
    # dispatcher), and flow start/finish events pair up per request
    assert any(e["tid"] != reqs[0]["tid"] for e in bf)
    assert any(e["ph"] == "s" and e["id"] == rid for e in evs)
    assert any(e["ph"] == "f" and e["id"] == rid for e in evs)
    # reply instant closes the tree
    assert any(e["name"] == "serve.reply" and
               e["args"].get("parent") == rid for e in evs)
    # accept is on the trace through the fault-site hook
    assert any(e["name"] == "serve.accept" and e["cat"] == "site"
               for e in evs)
    # the extended stats wire op carries the daemon-side histograms
    hists = st["obs"]["histograms"]
    for key in ("serve.queue_wait_ms", "serve.service_ms",
                "serve.flush_ms"):
        assert hists[key]["count"] >= 1
        assert hists[key]["p50"] is not None


def test_serve_cancelled_request_closes_its_span(traced, tmp_path):
    """A client that vanishes before dispatch must not leak its open
    request span — a traced daemon with client churn would otherwise
    grow the open-span table without bound."""
    from dr_tpu.obs import recorder
    srv = serve.Server(str(tmp_path / "d.sock"))
    srv.start()
    srv.hold()  # park the dispatcher so the request queues
    try:
        c = serve.Client(srv.path, timeout=60.0)
        c._sock.sendall(b"")  # ensure connected
        import dr_tpu.serve.protocol as proto
        proto.send_frame(c._sock, {"op": "fill", "params": {"n": 8},
                                   "tenant": "ghost", "id": 1})
        # wait until the daemon has admitted it (span opened at intake)
        deadline = 50
        while not recorder._open and deadline:
            threading.Event().wait(0.02)
            deadline -= 1
        assert recorder._open, "request span never opened"
        c.close()  # vanish before dispatch → cancelled
        # give the reader thread a beat to mark it cancelled
        threading.Event().wait(0.1)
        srv.release()
        deadline = 100
        while recorder._open and deadline:
            threading.Event().wait(0.02)
            deadline -= 1
    finally:
        srv.stop()
    assert recorder._open == {}, "cancelled request leaked its span"


def test_serve_daemon_samples_untraced(tmp_path):
    """The daemon-side latency histograms are ALWAYS live (bench
    --serve reports them on every run) — tracing adds spans, not the
    numbers."""
    from dr_tpu.obs import metrics as om
    h = om.histogram("serve.queue_wait_ms")
    c0 = h.count
    srv = serve.Server(str(tmp_path / "d.sock"))
    srv.start()
    try:
        with serve.Client(srv.path, timeout=60.0) as c:
            c.fill(16, 1.0)
            m = c.metrics()
    finally:
        srv.stop()
    assert h.count > c0
    assert m["histograms"]["serve.queue_wait_ms"]["count"] >= 1
    assert not m["trace_armed"]
    # …and no trace events leaked while disarmed
    assert obs.events() == []


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_metrics_registry_shapes(traced):
    from dr_tpu.obs import metrics as om
    om.counter("t.c").add(3)
    om.gauge("t.g").set(1.5)
    h = om.histogram("t.h")
    for v in (0.02, 0.2, 2.0, 20.0, 200.0):
        h.observe(v)
    snap = obs.snapshot()
    assert snap["counters"]["t.c"] == 3
    assert snap["gauges"]["t.g"] == 1.5
    hs = snap["histograms"]["t.h"]
    assert hs["count"] == 5 and hs["min"] == 0.02 and hs["max"] == 200.0
    assert sum(hs["buckets"].values()) == 5
    assert hs["p50"] == 2.0
    # reset zeroes in place without orphaning module-held handles
    obs.reset()
    h.observe(1.0)
    assert obs.snapshot()["histograms"]["t.h"]["count"] == 1


# ---------------------------------------------------------------------------
# exporter + trace_view
# ---------------------------------------------------------------------------

def test_chrome_export_and_trace_view_smoke(traced, tmp_path, capsys):
    v = _vec()
    with dr_tpu.deferred():
        dr_tpu.fill(v, 0.25)
        dr_tpu.reduce(v)
    srv = serve.Server(str(tmp_path / "d.sock"))
    srv.start()
    try:
        with serve.Client(srv.path, timeout=60.0) as c:
            c.dot(np.ones(8, np.float32), np.ones(8, np.float32))
    finally:
        srv.stop()
    path = obs.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    evs = doc["traceEvents"]
    assert any(e["ph"] == "X" for e in evs)
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in evs)
    assert all("pid" in e for e in evs)
    # the CLI summarizer parses it and prints every section
    assert trace_view.main([path]) == 0
    out = capsys.readouterr().out
    assert "spans by self-time" in out
    assert "events by site" in out
    assert "serve: 1 request(s)" in out
    assert "queue-wait" in out


def test_trace_view_rejects_garbage(tmp_path, capsys):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert trace_view.main([str(bad)]) == 2


def test_trace_dir_env(monkeypatch, tmp_path, traced):
    monkeypatch.setenv("DR_TPU_TRACE_DIR", str(tmp_path))
    obs.event("x")
    path = obs.export_chrome_trace()
    assert os.path.dirname(path) == str(tmp_path)
    assert os.path.exists(path)


# ---------------------------------------------------------------------------
# deadline postmortem generalization
# ---------------------------------------------------------------------------

def test_with_deadline_dumps_obs_tail(traced, capsys):
    obs.event("marker", k="tail-me")
    ev = threading.Event()
    with pytest.raises(resilience.DeadlineExpired) as ei:
        resilience.with_deadline(ev.wait, 0.05, site="test.hang")
    ev.set()
    err = capsys.readouterr().err
    assert "obs trace event" in err
    assert ei.value.trace_tail is not None
    assert any(e["name"] == "deadline.expired"
               for e in ei.value.trace_tail)


# ---------------------------------------------------------------------------
# collective redistribution spans (round 16, docs/SPEC.md §18)
# ---------------------------------------------------------------------------

def test_redistribute_span_phases_and_bytes_counter(traced):
    """The engine's obs contract: a ``redistribute`` span with
    plan/exchange/rebind phase children, a bytes-moved counter that
    actually counts off-shard traffic, and a classified mid-exchange
    error carrying the trace tail like every resilience path."""
    P = dr_tpu.nprocs()
    n = 4 * P
    v = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32))
    dr_tpu.redistribute(v, [n] + [0] * (P - 1))   # collective, moves
    evs = obs.events()
    names = [e.get("name") for e in evs]
    assert "redistribute" in names
    spans = [e for e in evs if e.get("name") == "redistribute"]
    assert any(s.get("args", {}).get("impl") == "collective"
               for s in spans)
    phases = {e.get("args", {}).get("phase") for e in evs
              if e.get("name") == "redistribute.phase"}
    assert {"plan", "exchange", "rebind"} <= phases, phases
    if P > 1:
        moved = obs.metrics.counter("redistribute.bytes_moved").value
        # everything but rank 0's original block crossed shards
        assert moved >= (n - -(-n // P)) * 4, moved
    # classified mid-exchange errors carry the §15.4 trace tail
    with faults.injected("redistribute.exchange", "program", times=1):
        try:
            dr_tpu.redistribute(v, None)
            raise AssertionError("injected fault did not surface")
        except resilience.ProgramError as e:
            assert e.trace_tail, "no trace tail on the classified error"
