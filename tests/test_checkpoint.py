"""Checkpoint round-trip tests (capability beyond the reference, which
has no serialization — SURVEY.md §5)."""

import numpy as np

import dr_tpu
from dr_tpu.utils import checkpoint


def test_vector_roundtrip(tmp_path):
    src = np.random.default_rng(0).standard_normal(37).astype(np.float32)
    dv = dr_tpu.distributed_vector.from_array(
        src, halo=dr_tpu.halo_bounds(1, 1))
    p = tmp_path / "vec.npz"
    checkpoint.save(str(p), dv)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.materialize(), src)
    assert back.halo_bounds == dv.halo_bounds


def test_dense_matrix_roundtrip(tmp_path):
    src = np.random.default_rng(1).standard_normal((9, 7))\
        .astype(np.float32)
    mat = dr_tpu.dense_matrix.from_array(src)
    p = tmp_path / "mat.npz"
    checkpoint.save(str(p), mat)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.materialize(), src)


def test_sparse_roundtrip(tmp_path):
    d = np.zeros((16, 8), np.float32)
    d[3, 2] = 1.5
    d[15, 7] = -2.0
    sp = dr_tpu.sparse_matrix.from_dense(d)
    p = tmp_path / "sp.npz"
    checkpoint.save(str(p), sp)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.to_dense(), d)


def test_mdarray_roundtrip(tmp_path):
    src = np.random.default_rng(2).standard_normal((4, 5, 3))\
        .astype(np.float32)
    md = dr_tpu.distributed_mdarray.from_array(src)
    p = tmp_path / "md.npz"
    checkpoint.save(str(p), md)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.materialize(), src)


def test_cyclic_dense_partition_roundtrip(tmp_path):
    part = dr_tpu.block_cyclic(tile=(4, 4), grid=dr_tpu.factor(
        dr_tpu.nprocs()))
    src = np.arange(16 * 16, dtype=np.float32).reshape(16, 16)
    mat = dr_tpu.dense_matrix.from_array(src, part)
    p = str(tmp_path / "cyc")
    checkpoint.save(p, mat)
    back = checkpoint.load(p)
    assert not back.is_block
    assert back.partition.tile == (4, 4)
    assert back.grid_shape == part.grid
    np.testing.assert_array_equal(back.materialize(), src)


def test_sparse_2d_partition_roundtrip(tmp_path):
    part = dr_tpu.block_cyclic(grid=dr_tpu.factor(dr_tpu.nprocs()))
    d = np.zeros((12, 12), dtype=np.float32)
    d[3, 4] = 2.0
    d[11, 1] = -1.0
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    p = str(tmp_path / "sp2d")
    checkpoint.save(p, sp)
    back = checkpoint.load(p)
    assert back.grid_shape == part.grid_for(dr_tpu.nprocs())
    np.testing.assert_array_equal(back.to_dense(), d)
