"""Checkpoint round-trip tests (capability beyond the reference, which
has no serialization — SURVEY.md §5), plus the round-7 failure model:
atomic writes, format versioning, and classified corrupt-file errors
under fault injection."""

import json
import os

import numpy as np
import pytest

import dr_tpu
from dr_tpu.utils import checkpoint, faults
from dr_tpu.utils.resilience import (CheckpointCorruptError,
                                     TransientBackendError)


def test_vector_roundtrip(tmp_path):
    src = np.random.default_rng(0).standard_normal(37).astype(np.float32)
    dv = dr_tpu.distributed_vector.from_array(
        src, halo=dr_tpu.halo_bounds(1, 1))
    p = tmp_path / "vec.npz"
    checkpoint.save(str(p), dv)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.materialize(), src)
    assert back.halo_bounds == dv.halo_bounds


def test_dense_matrix_roundtrip(tmp_path):
    src = np.random.default_rng(1).standard_normal((9, 7))\
        .astype(np.float32)
    mat = dr_tpu.dense_matrix.from_array(src)
    p = tmp_path / "mat.npz"
    checkpoint.save(str(p), mat)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.materialize(), src)


def test_sparse_roundtrip(tmp_path):
    d = np.zeros((16, 8), np.float32)
    d[3, 2] = 1.5
    d[15, 7] = -2.0
    sp = dr_tpu.sparse_matrix.from_dense(d)
    p = tmp_path / "sp.npz"
    checkpoint.save(str(p), sp)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.to_dense(), d)


def test_mdarray_roundtrip(tmp_path):
    src = np.random.default_rng(2).standard_normal((4, 5, 3))\
        .astype(np.float32)
    md = dr_tpu.distributed_mdarray.from_array(src)
    p = tmp_path / "md.npz"
    checkpoint.save(str(p), md)
    back = checkpoint.load(str(p))
    np.testing.assert_allclose(back.materialize(), src)


def test_cyclic_dense_partition_roundtrip(tmp_path):
    part = dr_tpu.block_cyclic(tile=(4, 4), grid=dr_tpu.factor(
        dr_tpu.nprocs()))
    src = np.arange(16 * 16, dtype=np.float32).reshape(16, 16)
    mat = dr_tpu.dense_matrix.from_array(src, part)
    p = str(tmp_path / "cyc")
    checkpoint.save(p, mat)
    back = checkpoint.load(p)
    assert not back.is_block
    assert back.partition.tile == (4, 4)
    assert back.grid_shape == part.grid
    np.testing.assert_array_equal(back.materialize(), src)


def test_sparse_2d_partition_roundtrip(tmp_path):
    part = dr_tpu.block_cyclic(grid=dr_tpu.factor(dr_tpu.nprocs()))
    d = np.zeros((12, 12), dtype=np.float32)
    d[3, 4] = 2.0
    d[11, 1] = -1.0
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    p = str(tmp_path / "sp2d")
    checkpoint.save(p, sp)
    back = checkpoint.load(p)
    assert back.grid_shape == part.grid_for(dr_tpu.nprocs())
    np.testing.assert_array_equal(back.to_dense(), d)


# ---------------------------------------------------------------------------
# failure model (round 7): atomic writes, versioning, classified errors
# ---------------------------------------------------------------------------

def _save_vec(path, values):
    checkpoint.save(str(path),
                    dr_tpu.distributed_vector.from_array(values))


def test_save_is_atomic_under_midwrite_kill(tmp_path):
    """A write killed mid-stream (injected fault between the temp-file
    write and the rename) must leave the PREVIOUS checkpoint intact and
    loadable — the torn-file regression the non-atomic round-6 save()
    could not pass — and no temp debris behind."""
    p = tmp_path / "vec.npz"
    old = np.arange(10, dtype=np.float32)
    _save_vec(p, old)
    with faults.injected("checkpoint.write", "transient"):
        with pytest.raises(TransientBackendError):
            _save_vec(p, old * 7)
    back = checkpoint.load(str(p))
    np.testing.assert_array_equal(back.materialize(), old)
    assert [f for f in os.listdir(tmp_path) if ".tmp" in f] == []


def test_truncated_checkpoint_raises_classified(tmp_path):
    """The injected 'truncate' kind installs the torn file a mid-stream
    kill leaves a NON-atomic writer in; load() must answer with the
    classified error, not a raw zipfile traceback."""
    p = tmp_path / "vec.npz"
    with faults.injected("checkpoint.write", "truncate") as sp:
        _save_vec(p, np.arange(32, dtype=np.float32))
        assert sp.fired == 1
    with pytest.raises(CheckpointCorruptError):
        checkpoint.load(str(p))


def test_corrupt_bytes_raise_classified(tmp_path):
    p = tmp_path / "garbage.npz"
    p.write_bytes(b"not a zip archive at all")
    with pytest.raises(CheckpointCorruptError):
        checkpoint.load(str(p))
    # a MISSING file is not corruption: the original error class stays
    with pytest.raises(FileNotFoundError):
        checkpoint.load(str(tmp_path / "never_written.npz"))


def test_corrupt_member_raises_classified(tmp_path):
    """A zip-INTACT archive whose .npy member bytes were overwritten
    (bit rot / partial overwrite, not tail truncation) must classify
    too — np.lib.format raises ValueError at the member read."""
    import io
    import zipfile as zf
    meta = io.BytesIO()
    np.save(meta, np.array(json.dumps(
        {"kind": "vector", "halo": [0, 0, False], "format_version": 1})))
    p = tmp_path / "member.npz"
    with zf.ZipFile(p, "w") as z:
        z.writestr("meta.npy", meta.getvalue())
        z.writestr("data.npy", b"\x93NUMPY garbage, not a real header")
    with pytest.raises(CheckpointCorruptError, match="member"):
        checkpoint.load(str(p))


def test_format_version_recorded_and_future_rejected(tmp_path):
    p = tmp_path / "vec.npz"
    _save_vec(p, np.arange(8, dtype=np.float32))
    with np.load(str(p), allow_pickle=False) as f:
        meta = json.loads(str(f["meta"]))
    assert meta["format_version"] == checkpoint.FORMAT_VERSION
    # a file from a NEWER dr_tpu must fail closed, not misparse
    meta["format_version"] = checkpoint.FORMAT_VERSION + 1
    with open(tmp_path / "future.npz", "wb") as fh:
        np.savez(fh, meta=json.dumps(meta),
                 data=np.arange(8, dtype=np.float32))
    with pytest.raises(CheckpointCorruptError, match="newer"):
        checkpoint.load(str(tmp_path / "future.npz"))


def test_legacy_unversioned_checkpoint_loads(tmp_path):
    """Round-6 files carry no format_version: they read as version 0
    and keep loading."""
    legacy = {"kind": "vector", "halo": [0, 0, False]}
    with open(tmp_path / "legacy.npz", "wb") as fh:
        np.savez(fh, meta=json.dumps(legacy),
                 data=np.arange(12, dtype=np.float32))
    back = checkpoint.load(str(tmp_path / "legacy.npz"))
    np.testing.assert_array_equal(back.materialize(),
                                  np.arange(12, dtype=np.float32))


def test_checkpoint_read_site_classified(tmp_path):
    p = tmp_path / "vec.npz"
    _save_vec(p, np.arange(8, dtype=np.float32))
    with faults.injected("checkpoint.read", "transient"):
        with pytest.raises(TransientBackendError):
            checkpoint.load(str(p))
    # clean afterwards
    checkpoint.load(str(p))
