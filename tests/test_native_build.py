"""Build and run the native C++ layer (vocabulary, host executor, bridge).

The reference is a C++20 library; this keeps our native surface compiled
and tested alongside the Python suite.
"""

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "native"


requires_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                                  reason="g++ not available")


@requires_gxx
def test_native_vocabulary_and_executor():
    subprocess.run(["make", "build/test_native"], cwd=NATIVE, check=True,
                   capture_output=True)
    out = subprocess.run([str(NATIVE / "build" / "test_native")],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "PASSED" in out.stdout


@requires_gxx
def test_native_bridge_drives_backend():
    r = subprocess.run(["make", "build/bridge_demo"], cwd=NATIVE,
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"bridge build unavailable: {r.stderr[-200:]}")
    import os
    env = dict(os.environ)
    repo = str(NATIVE.parent)
    env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
    out = subprocess.run([str(NATIVE / "build" / "bridge_demo"), "4"],
                         capture_output=True, text=True, env=env,
                         timeout=600)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "PASSED" in out.stdout
