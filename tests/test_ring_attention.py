"""Ring attention vs dense single-device attention oracle."""

import numpy as np
import pytest

import dr_tpu
from dr_tpu.ops.ring_attention import ring_attention


def _dense_attention(q, k, v, causal=False):
    B, S, h, d = q.shape
    logits = np.einsum("bqhd,bkhd->bhqk", q, k).astype(np.float64)
    logits /= np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((S, S), bool))
        logits = np.where(mask[None, None], logits, -np.inf)
    logits -= logits.max(-1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bkhd->bhqd", p, v)
    return np.einsum("bhqd->bqhd", out)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    rng = np.random.default_rng(0)
    B, S, h, d = 2, 8 * dr_tpu.nprocs(), 2, 16
    q = rng.standard_normal((B, S, h, d)).astype(np.float32)
    k = rng.standard_normal((B, S, h, d)).astype(np.float32)
    v = rng.standard_normal((B, S, h, d)).astype(np.float32)
    got = np.asarray(ring_attention(q, k, v, causal=causal))
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_ring_attention_long_sequence_constant_local_memory():
    # the per-shard working set is O(S/P): just exercise a longer ring
    rng = np.random.default_rng(1)
    B, S, h, d = 1, 32 * dr_tpu.nprocs(), 1, 8
    q = rng.standard_normal((B, S, h, d)).astype(np.float32)
    got = np.asarray(ring_attention(q, q, q, causal=True))
    ref = _dense_attention(q, q, q, causal=True)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_ring_attention_q_chunked_matches_unchunked():
    rng = np.random.default_rng(9)
    B, S, h, d = 2, 8 * dr_tpu.nprocs(), 2, 16
    q, k, v = (rng.standard_normal((B, S, h, d)).astype(np.float32)
               for _ in range(3))
    full = np.asarray(dr_tpu.ring_attention(q, k, v, causal=True))
    chunked = np.asarray(dr_tpu.ring_attention(q, k, v, causal=True,
                                               q_chunk=4))
    np.testing.assert_allclose(chunked, full, rtol=2e-4, atol=2e-5)


def test_ring_attention_q_chunked_non_causal():
    rng = np.random.default_rng(10)
    B, S, h, d = 1, 16 * dr_tpu.nprocs(), 2, 8
    q, k, v = (rng.standard_normal((B, S, h, d)).astype(np.float32)
               for _ in range(3))
    full = np.asarray(dr_tpu.ring_attention(q, k, v))
    chunked = np.asarray(dr_tpu.ring_attention(q, k, v, q_chunk=8))
    np.testing.assert_allclose(chunked, full, rtol=2e-4, atol=2e-5)


def test_pick_q_chunk_floor_holds_for_non_power_of_two():
    from dr_tpu.ops.ring_attention import _pick_q_chunk
    # tiny budget forces maximal halving; the floor must still hold
    for s in (192, 384, 8192, 131072):
        qc = _pick_q_chunk(B=8, s=s, h=32, budget_bytes=1)
        assert qc >= 128, (s, qc)
        # and the caller's divisor walk starts from a sane value
        assert qc <= s


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_interpret_matches_dense(causal):
    """The Pallas flash block kernel (interpret mode) against the dense
    oracle — the TPU path's math, validated on CPU."""
    import jax.numpy as jnp
    from dr_tpu.ops import flash_attention as fa

    rng = np.random.default_rng(4)
    BH, s, d = 2, 256, 128
    q, k, v = (rng.standard_normal((BH, s, d)).astype(np.float32)
               for _ in range(3))
    blocks = fa.pick_blocks(s, s, d)
    assert blocks is not None
    bq, bk = blocks
    qb, kb, vb = (jnp.asarray(x, jnp.bfloat16) for x in (q, k, v))
    m = jnp.full((BH, s, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((BH, s, 1), jnp.float32)
    acc = jnp.zeros((BH, s, d), jnp.float32)
    # two chained updates against the two halves emulate two ring steps
    half = s // 2
    m, l, acc = fa.flash_update(qb, kb[:, :half], vb[:, :half], m, l, acc,
                                0, 0, causal=causal, bq=bq,
                                bk=min(bk, half), interpret=True)
    m, l, acc = fa.flash_update(qb, kb[:, half:], vb[:, half:], m, l, acc,
                                0, half, causal=causal, bq=bq,
                                bk=min(bk, half), interpret=True)
    out = np.asarray(acc / np.where(np.asarray(l) > 0, np.asarray(l),
                                    1.0))
    qf, kf, vf = (np.asarray(np.asarray(x, np.float32), np.float64)
                  for x in (qb, kb, vb))
    logits = np.einsum("bqd,bkd->bqk", qf, kf) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        logits = np.where(mask[None], logits, -np.inf)
    logits -= logits.max(-1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bqk,bkd->bqd", p, vf)
    np.testing.assert_allclose(out, ref, rtol=5e-2, atol=5e-3)


def test_pick_blocks_gates(monkeypatch):
    from dr_tpu.ops import flash_attention as fa
    assert fa.pick_blocks(8192, 8192, 128) == (2048, 1024)
    assert fa.pick_blocks(8192, 8192, 100) is None   # lane-unaligned d
    assert fa.pick_blocks(100, 8192, 128) is None    # no q tile divisor
    # beyond the resident VMEM budget the STREAMING kernel takes over
    assert not fa.resident_fits(1 << 20, 128)
    assert fa.pick_blocks(1 << 20, 1 << 20, 128) == (2048, 1024)
    assert fa.use_streaming(1 << 20, 128)
    assert not fa.use_streaming(8192, 128)
    # explicit opt-out restores the hard gate
    monkeypatch.setenv("DR_TPU_FLASH_STREAM", "0")
    assert fa.pick_blocks(1 << 20, 1 << 20, 128) is None


@pytest.mark.parametrize("causal", [False, True])
def test_flash_streaming_matches_resident_interpret(causal, monkeypatch):
    """The streaming kernel (K-block grid dimension, state in revisited
    output blocks) must match the resident kernel exactly on the same
    inputs (interpret mode)."""
    import jax.numpy as jnp

    from dr_tpu.ops import flash_attention as fa
    rng = np.random.default_rng(21)
    BH, s, d = 4, 256, 128
    bq, bk = 64, 128
    q, k, v = (jnp.asarray(rng.standard_normal((BH, s, d)),
                           jnp.bfloat16) for _ in range(3))
    m = jnp.full((BH, s, 1), -np.inf, jnp.float32)
    l = jnp.zeros((BH, s, 1), jnp.float32)
    acc = jnp.zeros((BH, s, d), jnp.float32)
    monkeypatch.setenv("DR_TPU_FLASH_STREAM", "0")
    ref = fa.flash_update(q, k, v, m, l, acc, 0, 0, causal=causal,
                          bq=bq, bk=bk, interpret=True)
    monkeypatch.setenv("DR_TPU_FLASH_STREAM", "1")
    got = fa.flash_update(q, k, v, m, l, acc, 0, 0, causal=causal,
                          bq=bq, bk=bk, interpret=True)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
    # nonzero offsets (ring-step positions) must agree too
    monkeypatch.setenv("DR_TPU_FLASH_STREAM", "0")
    ref = fa.flash_update(q, k, v, m, l, acc, s, 2 * s, causal=causal,
                          bq=bq, bk=bk, interpret=True)
    monkeypatch.setenv("DR_TPU_FLASH_STREAM", "1")
    got = fa.flash_update(q, k, v, m, l, acc, s, 2 * s, causal=causal,
                          bq=bq, bk=bk, interpret=True)
    for a, b in zip(got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_multishard_interpret(causal):
    """The FULL flash ring schedule (per-step kernel + ppermute K/V
    rotation + (m, l, acc) carries) over the multi-device mesh, kernel
    interpreted — validates the global q_off/k_off bookkeeping that a
    single-chip run never exercises."""
    import jax.numpy as jnp
    from dr_tpu.ops import ring_attention as ra
    from dr_tpu.parallel import runtime as _rt

    rt = _rt.runtime()
    P = rt.nprocs
    B, h, d = 1, 2, 128
    s = 128                       # per-shard block (pick_blocks floor)
    S = P * s
    rng = np.random.default_rng(11)
    q, k, v = (rng.standard_normal((B, S, h, d)).astype(np.float32)
               for _ in range(3))
    prog = ra._build_flash(rt.mesh, rt.axis, P, (B, s, h, d), causal,
                           jnp.dtype(jnp.float32), interpret=True)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    sh = NamedSharding(rt.mesh, PartitionSpec(None, rt.axis))
    got = np.asarray(prog(*(jax.device_put(x, sh) for x in (q, k, v))))
    qb, kb, vb = (np.asarray(
        jnp.asarray(x, jnp.bfloat16).astype(jnp.float32), np.float64)
        for x in (q, k, v))
    ref = _dense_attention(qb, kb, vb, causal=causal)
    np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_ring_attention_matches_repeated_kv(causal):
    """Grouped-query attention: q with h heads against hkv < h shared
    K/V heads equals full attention with the K/V heads repeated."""
    rng = np.random.default_rng(13)
    B, S, h, hkv, d = 1, 8 * dr_tpu.nprocs(), 4, 2, 16
    q = rng.standard_normal((B, S, h, d)).astype(np.float32)
    k = rng.standard_normal((B, S, hkv, d)).astype(np.float32)
    v = rng.standard_normal((B, S, hkv, d)).astype(np.float32)
    got = np.asarray(dr_tpu.ring_attention(q, k, v, causal=causal))
    kr = np.repeat(k, h // hkv, axis=2)
    vr = np.repeat(v, h // hkv, axis=2)
    ref = _dense_attention(q, kr, vr, causal=causal)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa_flash_multishard_interpret(causal):
    """GQA through the flash kernel (interpret) over the mesh: the
    kernel's b//group K/V index map against the dense oracle."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec
    from dr_tpu.ops import ring_attention as ra
    from dr_tpu.parallel import runtime as _rt

    rt = _rt.runtime()
    P = rt.nprocs
    B, h, hkv, d = 1, 4, 2, 128
    s = 128
    S = P * s
    rng = np.random.default_rng(14)
    q = rng.standard_normal((B, S, h, d)).astype(np.float32)
    k = rng.standard_normal((B, S, hkv, d)).astype(np.float32)
    v = rng.standard_normal((B, S, hkv, d)).astype(np.float32)
    prog = ra._build_flash(rt.mesh, rt.axis, P, (B, s, h, d), causal,
                           jnp.dtype(jnp.float32), interpret=True,
                           hkv=hkv)
    sh = NamedSharding(rt.mesh, PartitionSpec(None, rt.axis))
    got = np.asarray(prog(*(jax.device_put(x, sh) for x in (q, k, v))))
    to_f = lambda x: np.asarray(
        jnp.asarray(x, jnp.bfloat16).astype(jnp.float32), np.float64)
    kr = np.repeat(to_f(k), h // hkv, axis=2)
    vr = np.repeat(to_f(v), h // hkv, axis=2)
    ref = _dense_attention(to_f(q), kr, vr, causal=causal)
    np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_streaming_multishard_interpret(causal, monkeypatch):
    """The full flash ring with the STREAMING kernel forced — the
    long-context configuration (K/V beyond the resident VMEM budget)
    exercised end-to-end on the multi-shard mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec
    from dr_tpu.ops import ring_attention as ra
    from dr_tpu.parallel import runtime as _rt

    monkeypatch.setenv("DR_TPU_FLASH_STREAM", "1")
    rt = _rt.runtime()
    P = rt.nprocs
    B, h, d = 1, 2, 128
    s = 256
    S = P * s
    rng = np.random.default_rng(23)
    q, k, v = (rng.standard_normal((B, S, h, d)).astype(np.float32)
               for _ in range(3))
    prog = ra._build_flash(rt.mesh, rt.axis, P, (B, s, h, d), causal,
                           jnp.dtype(jnp.float32), interpret=True)
    sh = NamedSharding(rt.mesh, PartitionSpec(None, rt.axis))
    got = np.asarray(prog(*(jax.device_put(x, sh) for x in (q, k, v))))
    qb, kb, vb = (np.asarray(
        jnp.asarray(x, jnp.bfloat16).astype(jnp.float32), np.float64)
        for x in (q, k, v))
    ref = _dense_attention(qb, kb, vb, causal=causal)
    np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-3)


def test_causal_computed_flops_exact():
    """The block-granular flop counter matches a brute-force walk of the
    kernels' shared skip rule, and never undercounts the ideal causal
    triangle (so effective <= actual rate <= peak on honest timings)."""
    from dr_tpu.ops.flash_attention import causal_computed_flops
    for (s, skv, d, bq, bk, q_off, k_off) in [
            (8192, 8192, 128, 2048, 1024, 0, 0),
            (8192, 8192, 128, 1024, 2048, 0, 0),
            (1024, 2048, 128, 256, 128, 2048, 0),   # ring: later q shard
            (1024, 2048, 128, 256, 128, 0, 2048),   # future K block: 0
            (512, 512, 128, 512, 512, 0, 0)]:
        got = causal_computed_flops(s, skv, d, bq, bk, q_off, k_off)
        cells = sum(
            1
            for iq in range(s // bq)
            for ik in range(skv // bk)
            if k_off + ik * bk <= q_off + iq * bq + bq - 1)
        assert got == cells * 2 * 2 * bq * bk * d, (s, skv, bq, bk)
        # ideal triangle (pairs with q_pos >= k_pos) is a lower bound
        tri = 2 * 2 * sum(
            min(max(q_off + i - k_off + 1, 0), skv)
            for i in range(s)) * d
        assert got >= tri, (got, tri)
