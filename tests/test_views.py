"""View tests: segment recomputation, zip alignment, transform laziness
(reference test/gtest/mhp/views.cpp, test/gtest/shp/views.cpp,
test/gtest/mhp/alignment.cpp)."""

import numpy as np
import pytest

import dr_tpu
from dr_tpu import views


@pytest.fixture
def dv():
    v = dr_tpu.distributed_vector(24, dtype=np.int32)
    dr_tpu.iota(v, 0)
    return v


def test_take(dv, oracle):
    t = views.take(dv, 10)
    assert len(t) == 10
    oracle.equal(t, np.arange(10))
    oracle.check_segments(t)


def test_drop(dv, oracle):
    d = views.drop(dv, 15)
    assert len(d) == 9
    oracle.equal(d, np.arange(15, 24))
    oracle.check_segments(d)


def test_subrange_collapses(dv, oracle):
    s = views.subrange(views.subrange(dv, 4, 20), 2, 10)
    assert s.base is dv
    assert (s.start, s.stop) == (6, 14)
    oracle.equal(s, np.arange(6, 14))
    oracle.check_segments(s)


def test_pipe_syntax(dv, oracle):
    r = dv | views.take(20) | views.drop(5)
    oracle.equal(r, np.arange(5, 20))
    r2 = dv | views.slice_view((3, 9))
    oracle.equal(r2, np.arange(3, 9))


def test_take_segments_trim(dv):
    segs = dr_tpu.segments(views.take(dv, 7))
    assert sum(len(s) for s in segs) == 7
    # ranks preserved from the base
    base_segs = dr_tpu.segments(dv)
    assert dr_tpu.rank(segs[0]) == dr_tpu.rank(base_segs[0])


def test_transform_lazy(dv, oracle):
    t = views.transform(dv, lambda x: x * 3)
    assert len(t) == len(dv)
    oracle.equal(t, np.arange(24) * 3)
    oracle.check_segments(t)
    # segments keep rank
    for s, b in zip(dr_tpu.segments(t), dr_tpu.segments(dv)):
        assert dr_tpu.rank(s) == dr_tpu.rank(b)


def test_transform_pipe(dv, oracle):
    t = dv | views.transform(lambda x: x + 100)
    oracle.equal(t, np.arange(24) + 100)


def test_zip_aligned(dv, oracle):
    other = dr_tpu.distributed_vector(24, dtype=np.int32)
    dr_tpu.iota(other, 100)
    z = views.zip_view(dv, other)
    assert dr_tpu.aligned(dv, other)
    segs = dr_tpu.segments(z)
    assert segs, "aligned zip must produce segments"
    assert sum(len(s) for s in segs) == 24
    a, b = z.to_array()
    np.testing.assert_array_equal(np.asarray(a), np.arange(24))
    np.testing.assert_array_equal(np.asarray(b), np.arange(100, 124))


def test_zip_misaligned_empty_segments(dv):
    # different segment sizes -> misaligned -> empty segment list
    # (segments_tools.hpp:117-121)
    other = dr_tpu.distributed_vector(100, dtype=np.int32)
    z = views.zip_view(dv, other)
    assert dr_tpu.segments(z) == []
    assert not dr_tpu.aligned(dv, other)


def test_zip_common_prefix_aligns(dv):
    # same segment size, shorter vector: zip trims both lists to the common
    # prefix and stays aligned (an improvement over the reference, which
    # only compares full segment lists)
    # one shorter than dv keeps ceil(n/P) equal at every mesh size
    # (17 vs 24 diverges at P=3: seg 6 vs 8 -> correctly misaligned)
    n_other = len(dv) - 1
    other = dr_tpu.distributed_vector(n_other, dtype=np.int32)
    dr_tpu.iota(other, 0)
    z = views.zip_view(dv, other)
    segs = dr_tpu.segments(z)
    assert segs and sum(len(s) for s in segs) == n_other


def test_zip_shifted_misaligned(dv):
    assert not dr_tpu.aligned(dv[1:], dv[:-1])


def test_enumerate(dv):
    e = views.enumerate_view(dv)
    segs = dr_tpu.segments(e)
    assert segs
    pairs = list(e)
    assert pairs[:3] == [(0, 0), (1, 1), (2, 2)]


def test_zip_segment_iteration(dv):
    other = dr_tpu.distributed_vector(24, dtype=np.int32)
    dr_tpu.iota(other, 50)
    z = views.zip_view(dv, other)
    seg0 = dr_tpu.segments(z)[0]
    vals = list(seg0)
    assert vals[0] == (0, 50)


def test_ranked_view(dv):
    rv = views.ranked_view(dv)
    pairs = list(rv)
    # rank of the first element is 0
    assert pairs[0][0] == 0
    # ranks match the segment owner for every element
    for s in dr_tpu.segments(dv):
        for i in range(s.begin, s.end):
            assert pairs[i][0] == dr_tpu.rank(s)


def test_local_segments(dv):
    locs = dr_tpu.local_segments(dv)
    flat = np.concatenate([np.asarray(l) for l in locs])
    np.testing.assert_array_equal(flat, np.arange(24))


def test_transform_over_subrange(dv, oracle):
    t = views.transform(views.subrange(dv, 5, 15), lambda x: -x)
    oracle.equal(t, -np.arange(5, 15))
    oracle.check_segments(t)


def test_iota_view_standalone(oracle):
    iv = views.iota_view(5, 10)
    oracle.equal(iv, np.arange(5, 15))


def test_segment_range(dv, mesh_size):
    # shp/range.hpp:97-130: per-segment id ranges with global offsets
    srs = views.segment_ranges(dv)
    segs = dr_tpu.segments(dv)
    assert len(srs) == len(segs)
    pos = 0
    for i, (sr, s) in enumerate(zip(srs, segs)):
        assert len(sr) == len(s)
        assert sr.rank() == 0  # reference contract
        first, last = sr[0], sr[-1]
        assert first.segment == i and first.local_id == 0
        assert int(first) == pos
        assert last.global_id == pos + len(s) - 1
        pos += len(s)
    # iteration yields every global index exactly once, in order
    flat = [int(x) for sr in srs for x in sr]
    assert flat == list(range(len(dv)))
    # id protocol: usable anywhere an index is (e.g. container indexing)
    assert dv[srs[0][1]] == dr_tpu.to_numpy(dv)[1]


def test_segment_range_standalone():
    sr = views.segment_range(3, 4, 100)
    assert [x.global_id for x in sr] == [100, 101, 102, 103]
    assert sr[2] == 102 and sr[2].segment == 3 and sr[2].local_id == 2
    import pytest
    with pytest.raises(IndexError):
        sr[4]


def test_bound_op_materialization_matches_fused():
    """views.transform with bound scalars: the lazy materialization
    (to_array / segments) and the fused reduce agree."""
    import numpy as np

    def scaled(x, c):
        return x * c

    n = 300
    src = np.linspace(0.1, 2, n).astype(np.float32)
    dv = dr_tpu.distributed_vector.from_array(src)
    v = dr_tpu.views.transform(dv, scaled, 3.0)
    np.testing.assert_allclose(np.asarray(v.to_array()), src * 3.0,
                               rtol=1e-6)
    got = dr_tpu.reduce(v)
    assert got == pytest.approx(float((src * 3.0).sum()), rel=1e-4)
    # segments materialize through the bound op too
    segs = dr_tpu.segments(v)
    joined = np.concatenate([s.materialize() for s in segs])
    np.testing.assert_allclose(joined[:n], src * 3.0, rtol=1e-6)
