"""Plan optimizer pass pipeline (docs/SPEC.md §21): targeted units.

The bit-identity battery lives in tests/test_fuzz.py::test_fuzz_plan_opt
(random chains, ``DR_TPU_PLAN_OPT=all`` vs ``=0``); this file pins the
individual pass semantics — merge coalesces independent runs split by
recording order, dce eliminates overwritten-before-read writes,
pushdown re-homes a projection into the relational scratch copy,
capinfer sizes auto outputs from probes/hints, joinroute reads the
tuning DB — plus the knob surface (mode / per-pass disable) and the
never-take-a-flush-down failure posture.
"""

import numpy as np
import pytest

import dr_tpu
from dr_tpu import tuning
from dr_tpu.plan import opt as plan_opt
from dr_tpu.utils.env import env_override
from dr_tpu.utils.spmd_guard import dispatch_count


def _scale(x, c):
    return x * c


def _shift(x, c):
    return x + c


def _mkvec(src):
    return dr_tpu.distributed_vector.from_array(np.asarray(src))


# ---------------------------------------------------------------------------
# mode / knob surface
# ---------------------------------------------------------------------------

def test_mode_parsing_and_per_pass_disable():
    with env_override(DR_TPU_PLAN_OPT=None, DR_TPU_PLAN_OPT_DISABLE=None):
        assert plan_opt.mode() == "auto"
        assert plan_opt.enabled("merge")
        assert plan_opt.enabled("dce")
        # auto leaves the probe/rewrite passes off; all arms them
        assert not plan_opt.enabled("capinfer")
        assert not plan_opt.enabled("pushdown")
    with env_override(DR_TPU_PLAN_OPT="all"):
        for name in plan_opt.PASS_NAMES:
            assert plan_opt.enabled(name), name
    with env_override(DR_TPU_PLAN_OPT="0"):
        for name in plan_opt.PASS_NAMES:
            assert not plan_opt.enabled(name), name
    with env_override(DR_TPU_PLAN_OPT="all",
                      DR_TPU_PLAN_OPT_DISABLE="merge, capinfer"):
        assert not plan_opt.enabled("merge")
        assert not plan_opt.enabled("capinfer")
        assert plan_opt.enabled("dce")


def test_every_pass_is_registered_with_an_impl_or_config_contract():
    """The §21 registry shape drlint R7 keys on: queue-rewrite passes
    carry a callable, config-level passes register with None — and
    every name answers :func:`plan_opt.enabled`."""
    names = set()
    for name, fn in plan_opt.PASSES:
        assert fn is None or callable(fn)
        names.add(name)
        with env_override(DR_TPU_PLAN_OPT="all",
                          DR_TPU_PLAN_OPT_DISABLE=name):
            assert not plan_opt.enabled(name)
    assert names == set(plan_opt.PASS_NAMES)


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------

def test_merge_coalesces_runs_split_by_independent_opaque():
    """fill(a) | scan(c->d) | for_each(b): the two fusible runs touch
    containers disjoint from the scan's footprint, so they merge into
    ONE dispatch — and the flush result is bit-equal to opt=0."""
    n = 32
    src_c = np.arange(n, dtype=np.float32)

    def run():
        a = dr_tpu.distributed_vector(n, np.float32)
        b = _mkvec(np.full(n, 3.0, np.float32))
        c = _mkvec(src_c)
        d = dr_tpu.distributed_vector(n, np.float32)
        d0 = dispatch_count()
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)
            dr_tpu.inclusive_scan(c, d)
            dr_tpu.for_each(b, _scale, 2.0)
        used = dispatch_count() - d0
        return ([dr_tpu.to_numpy(x) for x in (a, b, c, d)],
                used, p.stats())

    with env_override(DR_TPU_PLAN_OPT="0"):
        base, base_used, base_stats = run()
    with env_override(DR_TPU_PLAN_OPT="auto"):
        got, used, stats = run()
    for w, g in zip(base, got):
        np.testing.assert_array_equal(w, g)
    assert base_stats["opt"] == {"merged_runs": 0, "dce_ops": 0,
                                 "pushdowns": 0}
    assert stats["opt"]["merged_runs"] == 1
    assert used < base_used
    assert stats["fused_runs"] == base_stats["fused_runs"] - 1


def test_merge_blocked_by_footprint_overlap():
    """fill(a,1) | scan(a->d) | fill(a,2): the second run writes a
    container the opaque reads, so recording order must hold."""
    n = 16
    a = _mkvec(np.zeros(n, np.float32))
    d = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)
            dr_tpu.inclusive_scan(a, d)
            dr_tpu.fill(a, 2.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 2.0, np.float32))
    np.testing.assert_array_equal(dr_tpu.to_numpy(d),
                                  np.cumsum(np.ones(n, np.float32)))
    assert p.stats()["opt"]["merged_runs"] == 0


def test_merge_blocked_by_scalar_dependency():
    """A run consuming an earlier run's reduce handle cannot merge
    backward past it — the handle resolves only after the producer
    dispatches."""
    n = 16
    a = _mkvec(np.full(n, 2.0, np.float32))
    c = _mkvec(np.arange(n, dtype=np.float32))
    d = dr_tpu.distributed_vector(n, np.float32)
    x = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            s = dr_tpu.reduce(a)          # run 1: handle producer
            dr_tpu.inclusive_scan(c, d)   # opaque splitter
            dr_tpu.fill(x, s)             # run 2: consumes the handle
    assert float(s) == 2.0 * n
    np.testing.assert_array_equal(dr_tpu.to_numpy(x),
                                  np.full(n, 2.0 * n, np.float32))
    assert p.stats()["opt"]["merged_runs"] == 0


def test_merge_preserves_within_run_interleaving():
    """Cross-container read-after-write THROUGH the merge: run 2's
    transform reads what run 1 wrote — footprints intersect, so run 2
    stays put and the merged threading still sees run 1's value."""
    n = 24
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    c = _mkvec(np.arange(n, dtype=np.float32))
    d = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred():
            dr_tpu.fill(a, 5.0)
            dr_tpu.inclusive_scan(c, d)
            dr_tpu.transform(a, b, _shift, 1.0)  # reads a: no reorder
    np.testing.assert_array_equal(dr_tpu.to_numpy(b),
                                  np.full(n, 6.0, np.float32))


# ---------------------------------------------------------------------------
# dce
# ---------------------------------------------------------------------------

def test_dce_eliminates_overwritten_fill():
    n = 32
    a = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)   # dead: same window overwritten
            dr_tpu.fill(a, 2.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 2.0, np.float32))
    assert p.stats()["opt"]["dce_ops"] == 1


def test_dce_keeps_partially_overwritten_and_read_first_writes():
    n = 32
    a = dr_tpu.distributed_vector(n, np.float32)
    b = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)             # read by the transform
            dr_tpu.transform(a, b, _scale, 3.0)
            dr_tpu.fill(a[0:n // 2], 7.0)   # partial: tail survives
    want = np.full(n, 1.0, np.float32)
    want[:n // 2] = 7.0
    np.testing.assert_array_equal(dr_tpu.to_numpy(a), want)
    np.testing.assert_array_equal(dr_tpu.to_numpy(b),
                                  np.full(n, 3.0, np.float32))
    assert p.stats()["opt"]["dce_ops"] == 0


def test_dce_window_coverage_composes():
    """Two half-window fills do NOT retire a whole-row victim (the
    interval walk needs full coverage BY KEPT ops), but a whole-row
    fill retires both earlier halves."""
    n = 32
    a = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a[0:n // 2], 1.0)   # dead under the full fill
            dr_tpu.fill(a[n // 2:n], 2.0)   # dead under the full fill
            dr_tpu.fill(a, 9.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 9.0, np.float32))
    assert p.stats()["opt"]["dce_ops"] == 2


def test_dce_empty_run_disappears():
    """A run whose every op died (and that owes no scalar handles)
    drops out of the executed queue entirely."""
    n = 16
    a = dr_tpu.distributed_vector(n, np.float32)
    c = _mkvec(np.arange(n, dtype=np.float32))
    d = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto",
                      DR_TPU_PLAN_OPT_DISABLE="merge"):
        d0 = dispatch_count()
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)           # the whole run dies
            dr_tpu.inclusive_scan(c, d)
            dr_tpu.fill(a, 2.0)
        used = dispatch_count() - d0
    assert p.stats()["opt"]["dce_ops"] == 1
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 2.0, np.float32))
    # the dead first run never dispatched: scan + one fused run only
    items = p.log[-1]["items"]
    assert sum(1 for i in items if i["kind"] == "fused") == 1
    assert used >= 1


def test_per_pass_disable_bisects():
    n = 32
    with env_override(DR_TPU_PLAN_OPT="auto",
                      DR_TPU_PLAN_OPT_DISABLE="dce"):
        a = dr_tpu.distributed_vector(n, np.float32)
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)
            dr_tpu.fill(a, 2.0)
        assert p.stats()["opt"]["dce_ops"] == 0
        assert "dce" not in p.log[-1]["opt"]["passes"]
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 2.0, np.float32))


# ---------------------------------------------------------------------------
# pushdown
# ---------------------------------------------------------------------------

def test_pushdown_rehomes_projection_into_relational_scratch():
    """transform(a -> tmp) feeding ONLY a relational op, with tmp
    overwritten afterwards: the projection re-homes into the scratch
    sort copy and the materializing transform dies — same rows out."""
    rng = np.random.default_rng(21)
    n = 48
    src = rng.integers(0, 8, n).astype(np.float32)
    want = np.unique(src * 2.0)

    a = _mkvec(src)
    tmp = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="all"):
        with dr_tpu.deferred() as p:
            dr_tpu.transform(a, tmp, _scale, 2.0)
            r = dr_tpu.unique_auto(tmp)
            dr_tpu.copy(np.zeros(n, np.float32), tmp)  # tmp dies
        o = p.stats()["opt"]
    np.testing.assert_array_equal(r.arrays()[0], want)
    np.testing.assert_array_equal(dr_tpu.to_numpy(tmp),
                                  np.zeros(n, np.float32))
    assert o["pushdowns"] == 1
    assert o["dce_ops"] >= 1   # the re-homed transform died


def test_pushdown_declines_when_intermediate_is_live():
    """tmp read after the relational op: eliminating the transform
    would be observable, so the pushdown must not fire."""
    rng = np.random.default_rng(22)
    n = 32
    src = rng.integers(0, 6, n).astype(np.float32)
    a = _mkvec(src)
    tmp = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="all"):
        with dr_tpu.deferred() as p:
            dr_tpu.transform(a, tmp, _scale, 2.0)
            r = dr_tpu.unique_auto(tmp)
        o = p.stats()["opt"]
    assert o["pushdowns"] == 0
    np.testing.assert_array_equal(r.arrays()[0], np.unique(src * 2.0))
    np.testing.assert_array_equal(dr_tpu.to_numpy(tmp), src * 2.0)


# ---------------------------------------------------------------------------
# capinfer / auto-capacity API
# ---------------------------------------------------------------------------

def _join_oracle(lk, lv, rk, rv, how="inner", fill=0.0):
    import pandas as pd
    left = pd.DataFrame({"k": lk, "l": lv})
    right = pd.DataFrame({"k": rk, "r": rv})
    m = left.merge(right, on="k", how=how if how != "outer" else "outer")
    m = m.fillna(fill)
    return m.sort_values(["k", "l", "r"], kind="stable")


def test_join_auto_matches_explicit_join():
    rng = np.random.default_rng(23)
    nl, nr = 40, 24
    lk = rng.integers(0, 10, nl).astype(np.float32)
    lv = rng.standard_normal(nl).astype(np.float32)
    rk = rng.integers(0, 10, nr).astype(np.float32)
    rv = rng.standard_normal(nr).astype(np.float32)

    lkc, lvc = _mkvec(lk), _mkvec(lv)
    rkc, rvc = _mkvec(rk), _mkvec(rv)
    cap = 4 * (nl + nr)
    ok = dr_tpu.distributed_vector(cap, np.float32)
    ol = dr_tpu.distributed_vector(cap, np.float32)
    orr = dr_tpu.distributed_vector(cap, np.float32)
    m_exp = int(dr_tpu.join(lkc, lvc, rkc, rvc, ok, ol, orr))

    with env_override(DR_TPU_PLAN_OPT="all"):
        r = dr_tpu.join_auto(lkc, lvc, rkc, rvc)
        assert r.count == m_exp
        got_k, got_l, got_r = r.arrays()
    np.testing.assert_array_equal(got_k, dr_tpu.to_numpy(ok)[:m_exp])
    np.testing.assert_array_equal(got_l, dr_tpu.to_numpy(ol)[:m_exp])
    np.testing.assert_array_equal(got_r, dr_tpu.to_numpy(orr)[:m_exp])


def test_capinfer_hint_skips_probe_and_survives_undershoot():
    """A session-noted ratio replaces the count probe; a deliberately
    TINY hint undershoots, and the auto path re-merges at the exact
    count instead of raising the capacity error."""
    rng = np.random.default_rng(24)
    n = 40
    keys = rng.integers(0, 4, n).astype(np.float32)   # heavy dup keys
    vals = np.ones(n, np.float32)
    kc, vc = _mkvec(keys), _mkvec(vals)
    with env_override(DR_TPU_PLAN_OPT="all"):
        tuning.clear_session()
        tuning.note("relational", "cap_ratio_join_inner", 1e-3)
        r = dr_tpu.join_auto(kc, vc, kc, vc)
        # many-to-many expansion: sum over keys of cnt^2
        _u, cnt = np.unique(keys, return_counts=True)
        assert r.count == int((cnt.astype(np.int64) ** 2).sum())
        # the observed ratio overwrote the bogus note
        key = tuning.context_key("relational", "cap_ratio_join_inner")
        assert tuning._session[key] > 1e-3


def test_groupby_auto_and_unique_auto_deferred():
    rng = np.random.default_rng(25)
    n = 36
    keys = rng.integers(0, 7, n).astype(np.float32)
    vals = rng.standard_normal(n).astype(np.float32)
    kc, vc = _mkvec(keys), _mkvec(vals)
    with env_override(DR_TPU_PLAN_OPT="all"):
        with dr_tpu.deferred():
            g = dr_tpu.groupby_auto(kc, vc, agg="sum")
            u = dr_tpu.unique_auto(kc)
        gk, gv = g.arrays()
        (uk,) = u.arrays()
    want_k = np.unique(keys)
    np.testing.assert_array_equal(gk, want_k)
    np.testing.assert_array_equal(uk, want_k)
    want_v = np.array([vals[keys == k].sum() for k in want_k],
                      np.float64)
    np.testing.assert_allclose(gv, want_v, rtol=1e-5, atol=1e-6)
    assert g.count == len(want_k) and u.count == len(want_k)


def test_auto_result_discarded_raises():
    n = 16
    kc = _mkvec(np.arange(n, dtype=np.float32))
    with env_override(DR_TPU_PLAN_OPT="all"):
        p = dr_tpu.plan.Plan()
        with pytest.raises(RuntimeError, match="boom"):
            with p.record():
                u = dr_tpu.unique_auto(kc)
                raise RuntimeError("boom")
        with pytest.raises(RuntimeError, match="discarded"):
            u.count


def test_capinfer_disabled_uses_caller_guess_shape():
    """With the pass off the auto API still works — capacity falls
    back to the pre-§21 worst-case guess (no probe dispatch)."""
    rng = np.random.default_rng(26)
    n = 30
    keys = rng.integers(0, 5, n).astype(np.float32)
    kc = _mkvec(keys)
    with env_override(DR_TPU_PLAN_OPT="0"):
        u = dr_tpu.unique_auto(kc)
        np.testing.assert_array_equal(u.arrays()[0], np.unique(keys))


# ---------------------------------------------------------------------------
# joinroute (tuning-DB route selection)
# ---------------------------------------------------------------------------

def test_joinroute_reads_tuning_db_and_env_pin_wins():
    from dr_tpu.algorithms import relational as rel
    rng = np.random.default_rng(27)
    n = 32
    k = rng.integers(0, 9, n).astype(np.float32)
    v = rng.standard_normal(n).astype(np.float32)
    kc, vc = _mkvec(k), _mkvec(v)
    cap = 8 * n
    outs = [dr_tpu.distributed_vector(cap, np.float32)
            for _ in range(3)]

    def route_of():
        dr_tpu.join(kc, vc, kc, vc, *outs)
        return rel.last_join_route()["impl"]

    with env_override(DR_TPU_PLAN_OPT="all",
                      DR_TPU_JOIN_BROADCAST_MAX=None):
        assert route_of() == "broadcast"        # code default: 2^18
        tuning.note("join", "broadcast_max", 0)  # measured: repartition
        if dr_tpu.nprocs() > 1:
            assert route_of() == "partition"
        # the operator's env pin beats the DB
        with env_override(DR_TPU_JOIN_BROADCAST_MAX=str(1 << 18)):
            assert route_of() == "broadcast"
        # the pass disabled: the DB entry is ignored
        with env_override(DR_TPU_PLAN_OPT_DISABLE="joinroute"):
            assert route_of() == "broadcast"


# ---------------------------------------------------------------------------
# failure posture / introspection
# ---------------------------------------------------------------------------

def test_optimizer_pass_failure_never_takes_the_flush_down(monkeypatch):
    """A crashing pass is announced and the RECORDED queue executes —
    correct results, `error` note in the flush entry."""
    def boom(_q):
        raise RuntimeError("synthetic pass bug")

    monkeypatch.setattr(plan_opt, "PASSES",
                        (("dce", boom),) + tuple(
                            p for p in plan_opt.PASSES if p[0] != "dce"))
    n = 16
    a = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)
            dr_tpu.fill(a, 4.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 4.0, np.float32))
    assert "synthetic pass bug" in p.log[-1]["opt"]["error"]


def test_explain_carries_the_opt_note():
    n = 16
    a = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)
            dr_tpu.fill(a, 2.0)
    text = p.explain()
    assert "opt [" in text and "dead op(s) eliminated" in text


# ---------------------------------------------------------------------------
# tuning database (dr_tpu/tuning.py)
# ---------------------------------------------------------------------------

def test_tuning_record_lookup_roundtrip(tmp_path):
    db = str(tmp_path / "tuning_db.json")
    with env_override(DR_TPU_TUNING_DB=db):
        assert tuning.enabled()
        assert tuning.lookup("spmv", "format", "csr") == "csr"
        key = tuning.record("spmv", "format", "ell", source="unit")
        assert key is not None and "spmv.format@backend=" in key
        tuning.clear_session()
        tuning.reload()
        assert tuning.lookup("spmv", "format") == "ell"
    # a different store path: the entry does not leak
    with env_override(DR_TPU_TUNING_DB=str(tmp_path / "other.json")):
        tuning.clear_session()
        tuning.reload()
        assert tuning.lookup("spmv", "format") is None


def test_tuning_context_isolation(tmp_path):
    """A CPU-mesh record can never poison a TPU-context lookup (and
    vice versa): the backend/nshards tag is part of the key."""
    db = str(tmp_path / "tuning_db.json")
    tpu_ctx = {"backend": "tpu", "nshards": 4, "x64": False}
    with env_override(DR_TPU_TUNING_DB=db):
        tuning.record("scan", "chunk", 256)          # live (cpu) ctx
        tuning.record("scan", "chunk", 4096, ctx=tpu_ctx)
        tuning.clear_session()
        tuning.reload()
        assert tuning.lookup("scan", "chunk") == 256
        assert tuning.lookup("scan", "chunk", ctx=tpu_ctx) == 4096
        # a third context matches nothing: code default
        assert tuning.lookup(
            "scan", "chunk", 8192,
            ctx={"backend": "cpu", "nshards": 2, "x64": False}) == 8192


def test_tuning_corrupt_db_single_warn_and_defaults(tmp_path,
                                                    monkeypatch):
    import warnings
    from dr_tpu.utils import fallback
    db = tmp_path / "tuning_db.json"
    db.write_text("{ not json !!", encoding="utf-8")
    monkeypatch.setenv("DR_TPU_SILENCE_FALLBACKS", "0")
    fallback.reset()
    with env_override(DR_TPU_TUNING_DB=str(db)):
        tuning.reload()
        monkeypatch.setattr(tuning, "_warned_paths", set())
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            assert tuning.lookup("scan", "chunk", 8192) == 8192
            assert tuning.lookup("spmv", "format", "csr") == "csr"
        warns = [w for w in rec if "tuning DB" in str(w.message)]
        assert len(warns) == 1
        # a later RECORD rebuilds the store from scratch
        tuning.record("scan", "chunk", 512)
        tuning.clear_session()
        tuning.reload()
        assert tuning.lookup("scan", "chunk") == 512


def test_tuning_pickers_consult_db_between_env_and_default(tmp_path):
    """The dispatch-time integration sites: scan chunk + spmv format
    flip with a DB entry, and an explicit env pin still wins."""
    import importlib
    _gemv = importlib.import_module("dr_tpu.algorithms.gemv")
    from dr_tpu.ops import scan_pallas
    db = str(tmp_path / "tuning_db.json")
    with env_override(DR_TPU_TUNING_DB=db, DR_TPU_SCAN_CHUNK=None,
                      DR_TPU_SPMV_FORMAT=None):
        default_chunk = scan_pallas.chunk_cap()
        tuning.record("scan", "chunk", 256)
        assert scan_pallas.chunk_cap() == 256
        assert default_chunk != 256
        with env_override(DR_TPU_SCAN_CHUNK="1024"):
            assert scan_pallas.chunk_cap() == 1024

        m = 64
        rows = np.arange(m)
        A = dr_tpu.sparse_matrix.from_coo(
            (m, m), rows, rows, np.ones(m, np.float32))
        base_fmt = _gemv._pick_format(A)
        other = "csr" if base_fmt != "csr" else "ell"
        tuning.record("spmv", "format", other)
        assert _gemv._pick_format(A) == other
        with env_override(DR_TPU_SPMV_FORMAT=base_fmt):
            assert _gemv._pick_format(A) == base_fmt
        # a junk recorded value is ignored, not crashed on
        tuning.record("spmv", "format", 123)
        assert _gemv._pick_format(A) == base_fmt


def test_tuning_db_fresh_process_roundtrip(tmp_path):
    """The acceptance round trip: a recorded winner changes the picked
    config in a FRESH process with zero code edits, and without the
    DB the fresh process keeps the code default."""
    import subprocess
    import sys
    db = str(tmp_path / "tuning_db.json")
    with env_override(DR_TPU_TUNING_DB=db):
        tuning.record("scan", "chunk", 256, source="unit-roundtrip")
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import dr_tpu\n"
        "dr_tpu.init()\n"
        "from dr_tpu.ops import scan_pallas\n"
        "print('chunk=%d' % scan_pallas.chunk_cap())\n"
    )

    def run(extra_env):
        import os
        env = dict(os.environ)
        env.pop("DR_TPU_SCAN_CHUNK", None)
        env.pop("DR_TPU_TUNING_DB", None)
        env.update(extra_env)
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=300)
        assert out.returncode == 0, out.stderr
        return out.stdout

    assert "chunk=256" in run({"DR_TPU_TUNING_DB": db})
    assert "chunk=8192" in run({})


# ---------------------------------------------------------------------------
# footprint-gated host writes (§21.2 flush gating)
# ---------------------------------------------------------------------------

def test_assign_array_footprint_gated_flush():
    """A host write into a container the queue never touches (the
    serve daemon building a batched request's fresh operand) records
    WITHOUT the flush cliff; a write into a touched container still
    flushes the pending ops first."""
    n = 16
    a = dr_tpu.distributed_vector(n, np.float32)
    src = np.arange(n, dtype=np.float32)
    with dr_tpu.deferred() as p:
        dr_tpu.fill(a, 1.0)
        fresh = dr_tpu.distributed_vector.from_array(src)
        assert p.stats()["flushes"] == 0     # untouched: no cliff
        a.assign_array(np.full(n, 5.0, np.float32))
        assert p.stats()["flushes"] == 1     # touched: ordered flush
    np.testing.assert_array_equal(dr_tpu.to_numpy(fresh), src)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.full(n, 5.0, np.float32))


# ---------------------------------------------------------------------------
# the bench --plan acceptance bar
# ---------------------------------------------------------------------------

def test_bench_plan_relational_ab_strictly_fewer_dispatches():
    """The ISSUE 15 acceptance criterion, pinned in CI: the deferred
    relational pipeline (join_auto -> groupby_auto -> histogram/top_k
    with interleaved elementwise runs) flushes with STRICTLY fewer
    dispatches under DR_TPU_PLAN_OPT=all than =0 — and the same
    relational row counts."""
    import bench
    n_fact, ncard = 2 ** 10, 64
    used = {}
    res = {}
    for mode in ("0", "all"):
        bench._plan_opt_chain(n_fact, ncard, mode)   # warm compiles
        used[mode], _wall, note, res[mode] = \
            bench._plan_opt_chain(n_fact, ncard, mode)
    assert used["all"] < used["0"], (used, note)
    assert note.get("merged_runs", 0) >= 1
    assert res["0"] == res["all"]


# ---------------------------------------------------------------------------
# review-fix regressions
# ---------------------------------------------------------------------------

def test_gemv_view_operand_is_a_full_barrier():
    """A deferred gemv whose b operand is a VIEW (subrange/transform —
    a container the footprint cannot name) must record as a full
    barrier: dce may not retire a producer of the view's base across
    it, and merge may not reorder one past it."""
    n = 16
    rows = np.arange(n)
    A = dr_tpu.sparse_matrix.from_coo(
        (n, n), rows, rows, np.ones(n, np.float32))
    b = dr_tpu.distributed_vector(n, np.float32)
    c = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(c, 0.0)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred():
            dr_tpu.fill(b, 1.0)          # must NOT be dce'd
            dr_tpu.gemv(c, A, b[0:n])    # barrier: reads b via a view
            dr_tpu.fill(b, 2.0)
    np.testing.assert_array_equal(dr_tpu.to_numpy(c),
                                  np.ones(n, np.float32))
    np.testing.assert_array_equal(dr_tpu.to_numpy(b),
                                  np.full(n, 2.0, np.float32))


def test_pass_failure_after_merge_keeps_recorded_queue_executable(
        monkeypatch):
    """The never-take-a-flush-down fallback must re-execute a WHOLE
    recorded queue even when a pass fails AFTER merge wrapped ops —
    the source ops' operand values may only drop once the full
    pipeline succeeded."""
    def boom(_q):
        raise RuntimeError("post-merge pass bug")

    monkeypatch.setattr(plan_opt, "PASSES",
                        tuple(plan_opt.PASSES) + (("post", boom),))
    n = 16
    a = dr_tpu.distributed_vector(n, np.float32)
    b = _mkvec(np.full(n, 3.0, np.float32))
    c = _mkvec(np.arange(n, dtype=np.float32))
    d = dr_tpu.distributed_vector(n, np.float32)
    with env_override(DR_TPU_PLAN_OPT="auto"):
        with dr_tpu.deferred() as p:
            dr_tpu.fill(a, 1.0)           # run 1
            dr_tpu.inclusive_scan(c, d)   # splitter
            dr_tpu.for_each(b, _scale, 2.0)  # run 2: wrapped by merge
    assert "post-merge pass bug" in p.log[-1]["opt"]["error"]
    np.testing.assert_array_equal(dr_tpu.to_numpy(a),
                                  np.ones(n, np.float32))
    np.testing.assert_array_equal(dr_tpu.to_numpy(b),
                                  np.full(n, 6.0, np.float32))
    np.testing.assert_array_equal(
        dr_tpu.to_numpy(d),
        np.cumsum(np.arange(n, dtype=np.float32)))


def test_groupby_auto_validates_at_the_call_site():
    """API misuse raises NOW, not inside the deferred flush where it
    would classify away the whole batch (§17.5 discipline)."""
    kc = _mkvec(np.arange(8, dtype=np.float32))
    vc = _mkvec(np.arange(6, dtype=np.float32))
    with dr_tpu.deferred():
        with pytest.raises(ValueError, match="equal"):
            dr_tpu.groupby_auto(kc, vc)
