"""distributed_mdarray / mdspan tests (reference spec pages,
doc/spec/source/containers/distributed_mdarray.rst; transpose example)."""

import numpy as np
import pytest

import dr_tpu
from dr_tpu.containers.mdarray import (distributed_mdarray,
                                       distributed_mdspan, transpose)


def test_1d_roundtrip():
    src = np.arange(23, dtype=np.float32)
    md = distributed_mdarray.from_array(src)
    np.testing.assert_array_equal(md.materialize(), src)
    segs = dr_tpu.segments(md)
    assert sum(len(s) for s in segs) == 23


def test_2d_roundtrip_and_tiles():
    src = np.arange(7 * 10, dtype=np.float32).reshape(7, 10)
    md = distributed_mdarray.from_array(src)
    np.testing.assert_array_equal(md.materialize(), src)
    total = sum(len(s) for s in dr_tpu.segments(md))
    assert total == 70
    for s in dr_tpu.segments(md):
        np.testing.assert_array_equal(
            s.materialize(),
            src[s.box[0][0]:s.box[0][1], s.box[1][0]:s.box[1][1]])


def test_3d_array():
    src = np.arange(4 * 6 * 5, dtype=np.float32).reshape(4, 6, 5)
    md = distributed_mdarray.from_array(src)
    np.testing.assert_array_equal(md.materialize(), src)
    segs = dr_tpu.segments(md)
    assert sum(len(s) for s in segs) == 120
    # trailing dims are not distributed
    for s in segs:
        assert s.box[2] == (0, 5)


def test_local_tile_values():
    src = np.arange(8 * 8, dtype=np.float32).reshape(8, 8)
    md = distributed_mdarray.from_array(src)
    for s in dr_tpu.segments(md):
        loc = np.asarray(dr_tpu.local(s))
        np.testing.assert_array_equal(loc, s.materialize())


def test_submdspan():
    src = np.arange(12 * 9, dtype=np.float32).reshape(12, 9)
    md = distributed_mdarray.from_array(src)
    v = md.submdspan(slice(2, 9), slice(1, 6))
    assert v.shape == (7, 5)
    np.testing.assert_array_equal(v.materialize(), src[2:9, 1:6])
    vv = v.submdspan(slice(1, 4), slice(0, 2))
    np.testing.assert_array_equal(vv.materialize(), src[3:6, 1:3])
    segs = dr_tpu.segments(vv)
    assert sum(len(s) for s in segs) == 6


def test_getitem_slicing_and_elements():
    src = np.arange(6 * 6, dtype=np.float32).reshape(6, 6)
    md = distributed_mdarray.from_array(src)
    assert md[2, 3] == src[2, 3]
    md[2, 3] = -1.0
    assert md[2, 3] == -1.0
    v = md[1:4, 2:5]
    assert isinstance(v, distributed_mdspan)
    with pytest.raises(IndexError):
        md[6, 0]


def test_transpose():
    src = np.arange(8 * 12, dtype=np.float32).reshape(8, 12)
    a = distributed_mdarray.from_array(src)
    b = distributed_mdarray((12, 8), np.float32)
    transpose(b, a)
    np.testing.assert_array_equal(b.materialize(), src.T)


def test_transpose_nd_axes():
    """N-D axis permutations (the 2-D .T is the axes=None case)."""
    rng = np.random.default_rng(20)
    src = rng.standard_normal((6, 10, 4)).astype(np.float32)
    M = dr_tpu.distributed_mdarray.from_array(src)
    # default: full reversal
    T = dr_tpu.distributed_mdarray((4, 10, 6))
    dr_tpu.transpose(T, M)
    np.testing.assert_array_equal(T.materialize(), src.transpose())
    # explicit permutation (cycle)
    P = dr_tpu.distributed_mdarray((10, 4, 6))
    dr_tpu.transpose(P, M, axes=(1, 2, 0))
    np.testing.assert_array_equal(P.materialize(),
                                  src.transpose(1, 2, 0))
    # negative axes normalize
    Q = dr_tpu.distributed_mdarray((10, 4, 6))
    dr_tpu.transpose(Q, M, axes=(-2, -1, 0))
    np.testing.assert_array_equal(Q.materialize(),
                                  src.transpose(1, 2, 0))
    # invalid permutation rejected
    import pytest
    with pytest.raises(AssertionError):
        dr_tpu.transpose(P, M, axes=(0, 0, 1))
