"""sparse_matrix + gemv tests (reference test/gtest/shp containers/gemv,
examples/shp/gemv_example.cpp:18-41)."""

import numpy as np
import pytest

import dr_tpu


def _random_dense(m, n, density, seed=0):
    rng = np.random.default_rng(seed)
    d = rng.standard_normal((m, n)).astype(np.float32)
    mask = rng.random((m, n)) < density
    return np.where(mask, d, 0.0).astype(np.float32)


def test_from_dense_roundtrip():
    d = _random_dense(20, 16, 0.2)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    assert sp.nnz == int(np.count_nonzero(d))
    np.testing.assert_allclose(sp.to_dense(), d)


def test_from_csr():
    d = _random_dense(10, 10, 0.3, seed=1)
    rowptr = np.zeros(11, dtype=np.int64)
    rows, cols = np.nonzero(d)
    np.add.at(rowptr[1:], rows, 1)
    rowptr = np.cumsum(rowptr)
    sp = dr_tpu.sparse_matrix.from_csr((10, 10), rowptr, cols, d[rows, cols])
    np.testing.assert_allclose(sp.to_dense(), d)


def test_segments_ranks_and_rows():
    d = _random_dense(24, 8, 0.4, seed=2)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    segs = dr_tpu.segments(sp)
    assert segs
    covered = np.zeros((24, 8), dtype=np.float32)
    for s in segs:
        r, c, v = s.triples()
        assert (r >= s.rb).all() and (r < s.re).all()
        np.add.at(covered, (r, c), v)
    np.testing.assert_allclose(covered, d)


def test_tile_csr_view():
    d = _random_dense(16, 6, 0.5, seed=3)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    t = sp.tile((0, 0))
    rowptr, cols, vals = t.csr()
    assert rowptr[-1] == t.nnz
    # rebuild the tile densely from CSR
    m = t.re - t.rb
    dd = np.zeros((m, 6), dtype=np.float32)
    for i in range(m):
        for k in range(rowptr[i], rowptr[i + 1]):
            dd[i, cols[k]] += vals[k]
    np.testing.assert_allclose(dd, d[t.rb:t.re])


def test_gemv_fast_path(mesh_size):
    m, n = 8 * mesh_size, 24
    d = _random_dense(m, n, 0.3, seed=4)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    b = np.random.default_rng(5).standard_normal(n).astype(np.float32)
    bv = dr_tpu.distributed_vector.from_array(b)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.gemv(c, sp, bv)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), d @ b, rtol=1e-4,
                               atol=1e-5)


def test_gemv_accumulates():
    m, n = 16, 8
    d = _random_dense(m, n, 0.4, seed=6)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    b = np.ones(n, dtype=np.float32)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 1.0)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), 1.0 + d @ b,
                               rtol=1e-4, atol=1e-5)


def test_gemv_host_b():
    m, n = 12, 5
    d = _random_dense(m, n, 0.6, seed=7)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    b = np.arange(n, dtype=np.float32)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), d @ b, rtol=1e-4,
                               atol=1e-5)


def test_random_sparse_matrix():
    sp = dr_tpu.random_sparse_matrix((32, 32), density=0.1, seed=8)
    assert sp.nnz == int(0.1 * 32 * 32)
    assert sp.shape == (32, 32)
    b = np.ones(32, dtype=np.float32)
    y = np.asarray(dr_tpu.flat_gemv(sp, b))
    np.testing.assert_allclose(y, sp.to_dense() @ b, rtol=1e-4, atol=1e-5)


def test_empty_rows_tile():
    # matrix with an entirely empty row stripe still works
    d = np.zeros((16, 4), dtype=np.float32)
    d[0, 1] = 3.0
    sp = dr_tpu.sparse_matrix.from_dense(d)
    c = dr_tpu.distributed_vector(16)
    dr_tpu.gemv(c, sp, np.ones(4, dtype=np.float32))
    ref = d @ np.ones(4, dtype=np.float32)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), ref)


# --------------------------------------------------------- 2-D partition

def _grid2d():
    return dr_tpu.factor(dr_tpu.nprocs())


def test_sparse_2d_construction_and_dense_roundtrip():
    d = _random_dense(20, 18, 0.4, seed=11)
    part = dr_tpu.block_cyclic(grid=_grid2d())
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    assert sp.grid_shape == _grid2d()
    np.testing.assert_allclose(sp.to_dense(), d)


def test_sparse_2d_segments_cover_nnz():
    d = _random_dense(16, 16, 0.3, seed=12)
    part = dr_tpu.block_cyclic(grid=_grid2d())
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    total = sum(len(t) for t in sp.tiles())
    assert total == sp.nnz
    for t in sp.tiles():
        rows, cols, vals = t.triples()
        assert (rows >= t.rb).all() and (rows < t.re).all()
        assert (cols >= t.cb).all() and (cols < t.ce).all()
        np.testing.assert_allclose(vals, d[rows, cols])


def test_sparse_2d_gemv_matches_dense():
    m, n = 24, 20
    d = _random_dense(m, n, 0.35, seed=13)
    part = dr_tpu.block_cyclic(grid=_grid2d())
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    b = np.linspace(-1, 1, n).astype(np.float32)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 1.0)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), 1.0 + d @ b,
                               rtol=1e-4, atol=1e-5)


def test_sparse_2d_flat_gemv():
    d = _random_dense(17, 9, 0.5, seed=14)   # uneven tile trim
    part = dr_tpu.block_cyclic(grid=_grid2d())
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    b = np.arange(9, dtype=np.float32)
    y = np.asarray(dr_tpu.flat_gemv(sp, b))
    np.testing.assert_allclose(y, d @ b, rtol=1e-4, atol=1e-5)


def test_sparse_2d_random_and_repr():
    part = dr_tpu.block_cyclic(grid=_grid2d())
    sp = dr_tpu.random_sparse_matrix((32, 32), density=0.1, seed=15,
                                     partition=part)
    gp, gq = _grid2d()
    assert f"{gp}x{gq}" in repr(sp)
    b = np.ones(32, dtype=np.float32)
    c = dr_tpu.distributed_vector(32)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), sp.to_dense() @ b,
                               rtol=1e-4, atol=1e-5)


def test_gemv_n_matches_repeated_gemv():
    from dr_tpu.algorithms.gemv import gemv_n
    m = 16 * dr_tpu.nprocs()
    d = _random_dense(m, 24, 0.5, seed=21)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    b = np.linspace(0, 1, 24).astype(np.float32)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.0)
    gemv_n(c, sp, b, 3)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), 3 * (d @ b),
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------------ BCSR

def test_bcsr_banded_matches_dense():
    """Block-banded matrix takes the BCSR dense-tile path and matches
    the dense oracle (VERDICT r1 item 6)."""
    m, half = 64, 4
    rng = np.random.default_rng(50)
    d = np.zeros((m, m), dtype=np.float32)
    for i in range(m):
        lo, hi = max(0, i - half), min(m, i + half + 1)
        d[i, lo:hi] = rng.standard_normal(hi - lo)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    assert sp.ensure_bcsr()
    b = np.linspace(-1, 1, m).astype(np.float32)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.5)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), 0.5 + d @ b,
                               rtol=1e-4, atol=1e-5)


def test_bcsr_rejected_for_unstructured():
    m = 256
    rng = np.random.default_rng(51)
    rows = np.arange(m, dtype=np.int64)
    cols = rng.integers(0, m, size=m)
    vals = np.ones(m, dtype=np.float32)
    sp = dr_tpu.sparse_matrix.from_coo((m, m), rows, cols, vals)
    assert not sp.ensure_bcsr()       # ~1 nnz per (8,128) tile
    assert sp._bcsr_state == "no"     # remembered, not retried


def test_bcsr_gemv_n_matches_repeated():
    from dr_tpu.algorithms.gemv import gemv_n
    m, half = 64, 6
    rng = np.random.default_rng(52)
    d = np.zeros((m, m), dtype=np.float32)
    for i in range(m):
        lo, hi = max(0, i - half), min(m, i + half + 1)
        d[i, lo:hi] = rng.standard_normal(hi - lo)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    b = np.arange(m, dtype=np.float32) / m
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.0)
    gemv_n(c, sp, b, 3)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), 3 * (d @ b),
                               rtol=1e-4, atol=1e-4)


def test_bcsr_duplicates_and_partial_tiles():
    """Duplicate COO entries must accumulate inside the dense tiles,
    and partially-filled tiles must contribute exactly their nnz."""
    m, n = 16 * dr_tpu.nprocs(), 16
    rng = np.random.default_rng(53)
    # dense first 8-row stripe (one well-filled tile) + a sprinkle, so
    # the fill gate genuinely admits the layout
    rows = np.repeat(np.arange(8), n)
    cols = np.tile(np.arange(n), 8)
    vals = rng.standard_normal(8 * n).astype(np.float32)
    rows = np.concatenate([rows, [0, 0, m - 1]])
    cols = np.concatenate([cols, [0, 0, 2]])
    vals = np.concatenate([vals, [1.0, 2.0, 8.0]]).astype(np.float32)
    sp = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    assert sp.ensure_bcsr(), "the dense stripe must admit BCSR"
    d = sp.to_dense()
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.0)
    b = np.linspace(1, 2, n).astype(np.float32)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), d @ b,
                               rtol=1e-4, atol=1e-4)


def test_bcsr_skew_guard():
    # one fully dense block-row next to many single-tile block-rows:
    # fill passes but the allocation would balloon (kb = whole width)
    m, n = 8 * max(dr_tpu.nprocs(), 2) * 4, 128 * 32
    rows = [np.repeat(np.arange(8), 32 * 128)]
    cols = [np.tile(np.arange(32 * 128), 8)]
    for br in range(1, m // 8):
        rows.append(np.repeat(np.arange(br * 8, br * 8 + 8), 128))
        cols.append(np.tile(np.arange(128), 8))
    rows = np.concatenate(rows)
    cols = np.concatenate(cols)
    vals = np.ones(len(rows), dtype=np.float32)
    sp = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    assert not sp.ensure_bcsr()
    assert sp._bcsr_state == "no"


def test_bcsr_unaligned_tile_height():
    """th % 8 != 0 (remainder block-row zero-padded): the BCSR path
    stays eligible and matches the dense oracle.  m is derived so the
    tile height is unaligned at ANY mesh size (6P-2 -> th in {5, 6},
    never a multiple of 8)."""
    P = dr_tpu.nprocs()
    m = max(6 * P - 2, 12)
    rng = np.random.default_rng(60)
    d = np.zeros((m, m), dtype=np.float32)
    half = 5
    for i in range(m):
        lo, hi = max(0, i - half), min(m, i + half + 1)
        d[i, lo:hi] = rng.standard_normal(hi - lo)
    sp = dr_tpu.sparse_matrix.from_dense(d)
    assert sp._th % sp._BCSR_BH != 0  # premise: unaligned tile height
    assert sp.ensure_bcsr()
    b = rng.standard_normal(m).astype(np.float32)
    c = dr_tpu.distributed_vector(m, np.float32)
    dr_tpu.fill(c, 0.0)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), d @ b,
                               rtol=1e-4, atol=1e-4)
    # the fused measurement loop shares the layout
    from dr_tpu.algorithms.gemv import gemv_n
    dr_tpu.fill(c, 0.0)
    gemv_n(c, sp, dr_tpu.distributed_vector.from_array(b), 2)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), 2 * (d @ b),
                               rtol=1e-3, atol=1e-3)


def test_bcsr_2d_grid_matches_dense():
    """Dense-banded matrix on a 2-D tile grid takes the BCSR MXU path
    (per-tile dense-tile contraction + psum over mesh columns) — the
    layout/grid combination the reference's ``grid_shape[1]==1`` assert
    forbids (gemv.hpp:21).  VERDICT r2 item 5."""
    part = dr_tpu.block_cyclic(grid=_grid2d())
    m, half = 96, 6
    rng = np.random.default_rng(51)
    d = np.zeros((m, m), dtype=np.float32)
    for i in range(m):
        lo, hi = max(0, i - half), min(m, i + half + 1)
        d[i, lo:hi] = rng.standard_normal(hi - lo)
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    assert sp.grid_shape == _grid2d()
    assert sp.ensure_bcsr(), "band must pass the fill gate on 2-D grids"
    b = np.linspace(-1, 1, m).astype(np.float32)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.25)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), 0.25 + d @ b,
                               rtol=1e-4, atol=1e-4)


def test_bcsr_2d_fill_gate_uses_tile_width():
    """The fill gate must count occupiable cells per TILE width: a 2-D
    grid tile narrower than the matrix must not deflate the ratio
    (round-2 advisor finding)."""
    gp, gq = _grid2d()
    if gq == 1:
        import pytest
        pytest.skip("needs a 2-D grid")
    m = 8 * gp
    n = 128 * gq        # each tile exactly one 128-wide block column
    d = np.zeros((m, n), dtype=np.float32)
    d[:, :] = 1.0       # fully dense: fill ratio must compute to ~1
    part = dr_tpu.block_cyclic(grid=(gp, gq))
    sp = dr_tpu.sparse_matrix.from_dense(d, partition=part)
    assert sp.ensure_bcsr()
    b = np.ones(n, dtype=np.float32)
    c = dr_tpu.distributed_vector(m)
    dr_tpu.fill(c, 0.0)
    dr_tpu.gemv(c, sp, b)
    np.testing.assert_allclose(dr_tpu.to_numpy(c), d @ b, rtol=1e-4)


def _rand_coo(m, n, k, seed=0):
    rng = np.random.default_rng(seed)
    rows = np.repeat(np.arange(m), k)
    cols = rng.integers(0, n, size=m * k)
    vals = rng.standard_normal(m * k).astype(np.float32)
    return rows, cols, vals


def test_spmm_random_matches_dense(mesh_size):
    """Multi-vector SpMM on the random (ELL) path vs the dense oracle —
    the gather-amortization surface (docs/PERF.md SpMV roofline)."""
    m = n = 64
    rows, cols, vals = _rand_coo(m, n, 4, seed=3)
    A = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    rng = np.random.default_rng(7)
    B = rng.standard_normal((n, 5)).astype(np.float32)
    dense = np.zeros((m, n), np.float32)
    np.add.at(dense, (rows, cols), vals)
    got = np.asarray(dr_tpu.spmm(A, B))
    np.testing.assert_allclose(got, dense @ B, rtol=2e-5, atol=1e-5)


def test_spmm_bcsr_banded_matches_dense():
    m, half = 64, 4
    rng = np.random.default_rng(50)
    dense = np.zeros((m, m), dtype=np.float32)
    for i in range(m):
        lo, hi = max(0, i - half), min(m, i + half + 1)
        dense[i, lo:hi] = rng.standard_normal(hi - lo)
    A = dr_tpu.sparse_matrix.from_dense(dense)
    assert A.ensure_bcsr()
    B = np.random.default_rng(2).standard_normal((m, 3)).astype(np.float32)
    got = np.asarray(dr_tpu.spmm(A, B))
    np.testing.assert_allclose(got, dense @ B, rtol=2e-5, atol=1e-4)


def test_spmm_single_column_matches_gemv():
    m = n = 96
    rows, cols, vals = _rand_coo(m, n, 3, seed=9)
    A = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    b = np.random.default_rng(4).standard_normal(n).astype(np.float32)
    got = np.asarray(dr_tpu.spmm(A, b[:, None]))[:, 0]
    c = dr_tpu.distributed_vector(m, np.float32)
    dr_tpu.fill(c, 0.0)
    dr_tpu.gemv(c, A, b)
    np.testing.assert_allclose(got, dr_tpu.to_numpy(c), rtol=2e-5,
                               atol=1e-5)


def test_spmm_2d_grid_native():
    """2-D tile grids run the per-tile partial + psum program (round
    4), not the per-column flat fallback."""
    m = n = 64
    rows, cols, vals = _rand_coo(m, n, 2, seed=11)
    A = dr_tpu.sparse_matrix.from_coo(
        (m, n), rows, cols, vals,
        partition=dr_tpu.block_cyclic(
            grid=dr_tpu.factor(dr_tpu.nprocs())))
    B = np.random.default_rng(5).standard_normal((n, 3)).astype(np.float32)
    dense = np.zeros((m, n), np.float32)
    np.add.at(dense, (rows, cols), vals)
    # pin THIS call to the native program: a fall-through to the flat
    # per-column path would call flat_gemv
    import importlib
    gemv_mod = importlib.import_module("dr_tpu.algorithms.gemv")

    def no_flat(*a, **kw):
        raise AssertionError("2-D spmm fell back to flat_gemv")
    real = gemv_mod.flat_gemv
    gemv_mod.flat_gemv = no_flat
    try:
        got = np.asarray(dr_tpu.spmm(A, B))
    finally:
        gemv_mod.flat_gemv = real
    np.testing.assert_allclose(got, dense @ B, rtol=2e-5, atol=1e-5)


def test_spmm_2d_skewed_flat_fallback():
    """A skewed 2-D matrix (one huge row defeats the ELL pad budget)
    takes the per-column flat path and stays correct."""
    m = n = 64
    rows = np.concatenate([np.zeros(n, np.int64), np.arange(m)])
    cols = np.concatenate([np.arange(n), np.zeros(m, np.int64)])
    vals = np.random.default_rng(3).standard_normal(
        len(rows)).astype(np.float32)
    A = dr_tpu.sparse_matrix.from_coo(
        (m, n), rows, cols, vals,
        partition=dr_tpu.block_cyclic(
            grid=dr_tpu.factor(dr_tpu.nprocs())))
    B = np.random.default_rng(4).standard_normal((n, 2)).astype(
        np.float32)
    dense = np.zeros((m, n), np.float32)
    np.add.at(dense, (rows, cols), vals)
    got = np.asarray(dr_tpu.spmm(A, B))
    np.testing.assert_allclose(got, dense @ B, rtol=2e-4, atol=2e-4)


def test_spmm_rejects_bad_shapes():
    m = n = 32
    rows, cols, vals = _rand_coo(m, n, 2)
    A = dr_tpu.sparse_matrix.from_coo((m, n), rows, cols, vals)
    with pytest.raises(AssertionError):
        dr_tpu.spmm(A, np.zeros((n + 1, 2), np.float32))
    with pytest.raises(AssertionError):
        dr_tpu.spmm(A, np.zeros((n,), np.float32))
