"""Device-init hardening of the driver benchmark (bench.py).

A wedged tunnel relay makes the first backend touch hang or fail; the
bench must (1) retry ONCE in a fresh process after a cool-down — round-3
probe tallies showed single claims failing where a later one landed
instantly — and (2) fall back to a tagged CPU run only after the retry
also fails, so the driver always records a number.  The re-execs are
``os.execve`` (a hung probe thread blocks the singleton PJRT init lock,
so an in-process retry would just join the hang); here they are
monkeypatched so the chain is testable in-process on CPU.
"""

import importlib.util
import os
import sys

import pytest


@pytest.fixture
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod.time, "sleep", lambda s: None)
    return mod


class _Exec(Exception):
    def __init__(self, argv, env):
        self.argv, self.env = argv, env


def _arm(monkeypatch, bench, probe_result):
    from dr_tpu.parallel import runtime
    monkeypatch.setattr(runtime, "probe_devices",
                        lambda t: probe_result)

    def fake_execve(path, argv, env):
        raise _Exec(argv, env)
    monkeypatch.setattr(bench.os, "execve", fake_execve)


def test_probe_success_no_exec(monkeypatch, bench):
    _arm(monkeypatch, bench, (["dev0"], None))
    assert bench._devices_or_die(1.0) == ["dev0"]


def test_first_failure_re_execs_with_retry_flag(monkeypatch, bench):
    monkeypatch.delenv("_DR_TPU_BENCH_RETRY", raising=False)
    monkeypatch.delenv("_DR_TPU_BENCH_CPU_FALLBACK", raising=False)
    monkeypatch.setattr(bench, "_relay_listening", lambda: True)
    _arm(monkeypatch, bench, (None, "UNAVAILABLE: boom"))
    with pytest.raises(_Exec) as ei:
        bench._devices_or_die(1.0)
    env = ei.value.env
    assert env["_DR_TPU_BENCH_RETRY"] == "1"
    assert env["_DR_TPU_BENCH_FIRST_ERR"] == "UNAVAILABLE: boom"
    # still aimed at the TPU: no CPU fallback markers yet
    assert "_DR_TPU_BENCH_CPU_FALLBACK" not in env
    assert "_DR_TPU_BENCH_DEGRADED" not in env


def test_relay_down_skips_retry(monkeypatch, bench):
    """A dead relay (TCP connect refused) cannot serve a second claim:
    go straight to the CPU fallback instead of paying the cool-down +
    retry tax during an outage."""
    monkeypatch.delenv("_DR_TPU_BENCH_RETRY", raising=False)
    monkeypatch.delenv("_DR_TPU_BENCH_CPU_FALLBACK", raising=False)
    monkeypatch.setattr(bench, "_relay_listening", lambda: False)
    _arm(monkeypatch, bench, (None, "UNAVAILABLE: boom"))
    with pytest.raises(_Exec) as ei:
        bench._devices_or_die(1.0)
    env = ei.value.env
    assert env["_DR_TPU_BENCH_CPU_FALLBACK"] == "1"
    assert "_DR_TPU_BENCH_RETRY" not in env
    assert "retry skipped" in env["_DR_TPU_BENCH_DEGRADED"]


def test_retry_failure_falls_back_to_cpu(monkeypatch, bench):
    monkeypatch.setenv("_DR_TPU_BENCH_RETRY", "1")
    monkeypatch.setenv("_DR_TPU_BENCH_FIRST_ERR", "UNAVAILABLE: first")
    monkeypatch.delenv("_DR_TPU_BENCH_CPU_FALLBACK", raising=False)
    _arm(monkeypatch, bench, (None, "UNAVAILABLE: second"))
    with pytest.raises(_Exec) as ei:
        bench._devices_or_die(1.0)
    env = ei.value.env
    assert env["_DR_TPU_BENCH_CPU_FALLBACK"] == "1"
    assert env["JAX_PLATFORMS"] == "cpu"
    # degraded message keeps both causes for the artifact
    assert "UNAVAILABLE: second" in env["_DR_TPU_BENCH_DEGRADED"]
    assert "UNAVAILABLE: first" in env["_DR_TPU_BENCH_DEGRADED"]
    # ... and the rest of the degradation story (round 7): retry count
    # and probe wall time ride the env into the tagged CPU child
    assert env["_DR_TPU_BENCH_RETRIES"] == "1"
    assert float(env["_DR_TPU_BENCH_PROBE_S"]) >= 0.0


def test_degradation_story_reaches_json_detail(monkeypatch, bench,
                                               capsys):
    """The degradation story (fallback reason, original probe error,
    retry count, probe wall time) must survive into bench's JSON
    artifact, not just stderr — exercised through bench's REAL
    report path (the CPU child's zero-report leg builds the same
    detail.degraded object main() emits)."""
    import json as _json
    monkeypatch.setenv("_DR_TPU_BENCH_CPU_FALLBACK", "1")
    monkeypatch.setenv("_DR_TPU_BENCH_DEGRADED", "retry failed: boom")
    monkeypatch.setenv("_DR_TPU_BENCH_FIRST_ERR", "UNAVAILABLE: first")
    monkeypatch.setenv("_DR_TPU_BENCH_RETRIES", "1")
    monkeypatch.setenv("_DR_TPU_BENCH_PROBE_S", "3.25")
    _arm(monkeypatch, bench, (None, "cpu probe also failed"))

    class _Exit(Exception):
        pass

    monkeypatch.setattr(bench.os, "_exit",
                        lambda code: (_ for _ in ()).throw(_Exit()))
    with pytest.raises(_Exit):
        bench._devices_or_die(1.0)
    rec = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 0.0
    assert rec["detail"]["error"] == "cpu probe also failed"
    assert rec["detail"]["degraded"] == {
        "reason": "retry failed: boom",
        "first_error": "UNAVAILABLE: first",
        "retries": 1, "probe_wall_s": 3.25}


def test_retry_success_returns_devices(monkeypatch, bench):
    monkeypatch.setenv("_DR_TPU_BENCH_RETRY", "1")
    monkeypatch.setenv("DR_TPU_BENCH_RETRY_TIMEOUT", "33")
    seen = {}
    from dr_tpu.parallel import runtime

    def probe(t):
        seen["timeout"] = t
        return ["dev0"], None
    monkeypatch.setattr(runtime, "probe_devices", probe)
    assert bench._devices_or_die(420.0) == ["dev0"]
    # the retry leg honors its own (shorter) timeout budget
    assert seen["timeout"] == 33.0


def test_dead_relay_skips_probe_entirely(monkeypatch, bench):
    """A dead relay (axon platform, port refusing) execs straight to
    the CPU fallback WITHOUT spending the probe timeout."""
    monkeypatch.delenv("_DR_TPU_BENCH_RETRY", raising=False)
    monkeypatch.delenv("_DR_TPU_BENCH_CPU_FALLBACK", raising=False)
    monkeypatch.setattr(bench, "_dead_relay", lambda: True)
    from dr_tpu.parallel import runtime

    def no_probe(t):
        raise AssertionError("probe must not run with a dead relay")
    monkeypatch.setattr(runtime, "probe_devices", no_probe)

    def fake_execve(path, argv, env):
        raise _Exec(argv, env)
    monkeypatch.setattr(bench.os, "execve", fake_execve)
    with pytest.raises(_Exec) as ei:
        bench._devices_or_die(420.0)
    env = ei.value.env
    assert env["_DR_TPU_BENCH_CPU_FALLBACK"] == "1"
    assert "probe skipped" in env["_DR_TPU_BENCH_DEGRADED"]


@pytest.mark.parametrize("flag", ["--phases", "--pipeline", "--spmv"])
def test_cli_flags_survive_both_re_execs(monkeypatch, bench, flag):
    """--phases/--pipeline/--spmv must ride sys.argv through BOTH exec
    legs (retry-in-fresh-process and CPU fallback), or a degraded run
    would silently drop the ladder the operator asked for (round 6
    lesson, extended to the round-8 pipeline and round-9 spmv flags)."""
    monkeypatch.setattr(bench.sys, "argv", ["bench.py", flag])
    # leg 1: first failure -> retry exec
    monkeypatch.delenv("_DR_TPU_BENCH_RETRY", raising=False)
    monkeypatch.delenv("_DR_TPU_BENCH_CPU_FALLBACK", raising=False)
    monkeypatch.setattr(bench, "_relay_listening", lambda: True)
    _arm(monkeypatch, bench, (None, "UNAVAILABLE: boom"))
    with pytest.raises(_Exec) as ei:
        bench._devices_or_die(1.0)
    assert flag in ei.value.argv
    # leg 2: retry failure -> CPU-fallback exec
    monkeypatch.setenv("_DR_TPU_BENCH_RETRY", "1")
    with pytest.raises(_Exec) as ei:
        bench._devices_or_die(1.0)
    assert ei.value.env["_DR_TPU_BENCH_CPU_FALLBACK"] == "1"
    assert flag in ei.value.argv
