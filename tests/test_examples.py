"""Integration coverage through the examples' composition layer (the
reference ships its examples as tests too: test/gtest reuses the same
workloads its examples/ demonstrate)."""

import os
import sys

import numpy as np
import pytest

import dr_tpu

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "examples"))


def test_conjugate_gradient_converges():
    """CG composes gemv + dot + fused zip|transform: the solution must
    match the dense solve (SPD Laplacian system)."""
    from conjugate_gradient import build_laplacian, cg

    n = 256
    ii, jj, vv = build_laplacian(n)
    A = dr_tpu.sparse_matrix.from_coo((n, n), ii, jj, vv)
    rng = np.random.default_rng(1)
    b = rng.standard_normal(n).astype(np.float32)
    x, resid, its = cg(A, b, iters=100)
    assert resid < 1e-3 and its < 60
    Ad = np.zeros((n, n), dtype=np.float64)
    Ad[ii, jj] = vv
    ref = np.linalg.solve(Ad, b.astype(np.float64))
    np.testing.assert_allclose(dr_tpu.to_numpy(x), ref,
                               rtol=1e-3, atol=1e-3)


def _load_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeOp:
    """run_sync stub: deterministic per-op cost, records loop counts."""

    def __init__(self, per_op, constant=0.0):
        self.per_op = per_op
        self.constant = constant
        self.calls = []
        self.clock = [0.0]

    def __call__(self, r):
        self.calls.append(r)
        self.clock[0] += self.constant + self.per_op * r


def test_marginal_widens_fast_ops(monkeypatch):
    """An op far below the spread threshold must widen its loop count
    instead of reporting noise."""
    bench = _load_bench()
    op = _FakeOp(per_op=1e-4)
    monkeypatch.setattr(bench.time, "perf_counter",
                        lambda: op.clock[0])
    dt = bench._marginal(op, r1=4, r2=36, samples=3, min_spread=0.3,
                         rmax=4096)
    assert dt == pytest.approx(1e-4, rel=1e-6)
    assert max(op.calls) > 36  # widened beyond the pilot loop count


def test_marginal_raises_on_pure_noise(monkeypatch):
    """Zero marginal cost (measurement drowned) raises the typed error
    instead of returning a non-positive rate."""
    bench = _load_bench()
    op = _FakeOp(per_op=0.0, constant=0.01)
    monkeypatch.setattr(bench.time, "perf_counter",
                        lambda: op.clock[0])
    with pytest.raises(bench._JitterError):
        bench._marginal(op, r1=4, r2=36, samples=3, min_spread=0.3,
                        rmax=4096)


def test_marginal_fast_path_no_widening(monkeypatch):
    """An op already above the spread threshold keeps the pilot count
    (no extra compile)."""
    bench = _load_bench()
    op = _FakeOp(per_op=0.05)
    monkeypatch.setattr(bench.time, "perf_counter",
                        lambda: op.clock[0])
    dt = bench._marginal(op, r1=4, r2=36, samples=3, min_spread=0.3,
                         rmax=4096)
    assert dt == pytest.approx(0.05, rel=1e-6)
    assert max(op.calls) == 36


@pytest.mark.parametrize("mod,argv", [
    ("vector_add", ["-n", "4096"]),
    ("dot_product", ["-n", "4096"]),
    ("inclusive_scan_example", ["-n", "4096"]),
    ("spmm_example", ["-m", "512", "-k", "4", "--nv", "3"]),
    ("sort_example", ["-n", "4096"]),
    ("sort_example", ["-n", "4097", "--descending"]),
    ("windows_example", ["-n", "4096"]),
    ("top_k", ["-n", "4099", "-k", "5"]),
    ("views_example", []),
])
def test_example_smoke(mod, argv, monkeypatch, capsys):
    """Examples double as integration tests (the reference pattern:
    examples/mhp/stencil-1d.cpp:21-45 ships its own check()); each main
    returns 0 only when its built-in oracle passes."""
    import importlib
    m = importlib.import_module(mod)
    monkeypatch.setattr(sys, "argv", [mod] + argv)
    assert m.main() in (0, None)


def test_env_knob_tolerant_parsing(monkeypatch):
    """Malformed tuning env values fall back to defaults instead of
    raising at trace time."""
    from dr_tpu.utils.env import env_int, env_pow2

    monkeypatch.setenv("DR_TPU_TEST_KNOB", "2k")
    assert env_int("DR_TPU_TEST_KNOB", 7) == 7
    assert env_pow2("DR_TPU_TEST_KNOB", 512) == 512
    monkeypatch.setenv("DR_TPU_TEST_KNOB", "3000")
    assert env_pow2("DR_TPU_TEST_KNOB", 512) == 2048
    monkeypatch.setenv("DR_TPU_TEST_KNOB", "-4")
    assert env_int("DR_TPU_TEST_KNOB", 7, floor=2) == 2
    # floor=0 keeps an explicit 0 expressible (FUZZ_ITERS/CHAOS_ROUNDS
    # use it to mean "skip the arms"); the default floor clamps to 1
    monkeypatch.setenv("DR_TPU_TEST_KNOB", "0")
    assert env_int("DR_TPU_TEST_KNOB", 7, floor=0) == 0
    assert env_int("DR_TPU_TEST_KNOB", 7) == 1

    # the kernels survive a typo'd knob end-to-end
    from dr_tpu.ops import scan_pallas, stencil_matmul
    monkeypatch.setenv("DR_TPU_SCAN_CHUNK", "oops")
    assert scan_pallas.chunk_cap() == scan_pallas._MAX_ROWS
    monkeypatch.setenv("DR_TPU_MM_BAND_COLS", "wide")
    assert stencil_matmul.max_ksteps(2) == 256  # 4-column default
