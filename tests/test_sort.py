"""Distributed sample-sort tests (beyond-parity surface; the reference
snapshot has no sort — algorithms/sort.py docstring).  Oracle pattern:
distributed result vs numpy's sort, per SURVEY.md §4."""

import jax
import numpy as np
import pytest

import dr_tpu


def _roundtrip(src, **kw):
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort(v, **kw)
    return dr_tpu.to_numpy(v)


@pytest.mark.parametrize("n", [1, 2, 7, 57, 256, 1000])
def test_sort_random_f32(n):
    src = np.random.default_rng(n).standard_normal(n).astype(np.float32)
    np.testing.assert_array_equal(_roundtrip(src), np.sort(src))


def test_sort_rank_sweep(mesh_size, oracle):
    """The reference-style rank sweep (mpiexec -n {1..4} analog): the
    fast path at every shard count, including the p == 1 degenerate
    program, with uneven tails (n % p != 0)."""
    n = 4 * mesh_size + 3
    src = np.random.default_rng(mesh_size).standard_normal(n) \
        .astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort(v)
    oracle.equal(v, np.sort(src))
    dr_tpu.sort(v, descending=True)
    oracle.equal(v, np.sort(src)[::-1])


@pytest.mark.parametrize("n", [5, 64, 333])
def test_sort_descending(n):
    src = np.random.default_rng(n).standard_normal(n).astype(np.float32)
    np.testing.assert_array_equal(_roundtrip(src, descending=True),
                                  np.sort(src)[::-1])


def test_sort_int32():
    src = np.random.default_rng(3).integers(-50, 50, 200).astype(np.int32)
    np.testing.assert_array_equal(_roundtrip(src), np.sort(src))


def test_sort_duplicates_and_max_sentinel():
    """Values equal to the padding sentinel (dtype max / +inf) must
    survive: ties with the pad cannot change the sorted output."""
    src = np.array([5, np.inf, -1, np.inf, 3, 3, -np.inf, 0],
                   dtype=np.float32)
    np.testing.assert_array_equal(_roundtrip(src), np.sort(src))
    imax = np.iinfo(np.int32).max
    srci = np.array([imax, 0, imax, -7, imax], dtype=np.int32)
    np.testing.assert_array_equal(_roundtrip(srci), np.sort(srci))


def test_sort_nan_and_negzero():
    """NaNs must survive the fast path and land LAST (numpy order):
    the key encoding canonicalizes them after +inf but strictly before
    the pad sentinel, so the validity mask cannot drop them."""
    src = np.array([1.0, np.nan, -np.inf, np.inf, np.nan, -0.0, 0.5],
                   dtype=np.float32)
    got = _roundtrip(src)
    ref = np.sort(src)
    np.testing.assert_array_equal(got, ref)  # NaN == NaN positionally
    got_d = _roundtrip(src, descending=True)
    np.testing.assert_array_equal(got_d, ref[::-1])


def test_sort_is_bit_exact_permutation():
    """Keys-only sort() preserves VALUES bit-exactly: -0.0 stays -0.0
    (distinct zero keys in the encoding — advisor r3), so 1/x on a
    sorted zero keeps its sign.  Both zeros compare equal, so the only
    valid placement question is the zeros' order: -0.0 first."""
    src = np.array([0.0, 3.0, -0.0, -1.0, 0.0, -0.0], dtype=np.float32)
    got = _roundtrip(src)
    np.testing.assert_array_equal(got, np.sort(src))  # IEEE-equal view
    # bit-level: [-1.0, -0.0, -0.0, 0.0, 0.0, 3.0] — the two -0.0s
    # survived, ordered before the +0.0s
    assert np.array_equal(np.signbit(got),
                          [True, True, True, False, False, False])


def test_sort_adversarial_distributions():
    """Skew that breaks naive splitter choices: constant arrays, already
    sorted, reverse sorted, one-hot — balance may suffer, correctness
    must not (the (p, seg) bucket matrix is overflow-free)."""
    n = 300
    for src in (np.zeros(n, np.float32),
                np.arange(n, dtype=np.float32),
                np.arange(n, 0, -1).astype(np.float32),
                np.concatenate([np.zeros(n - 1, np.float32),
                                [-1.0]]).astype(np.float32)):
        np.testing.assert_array_equal(_roundtrip(src), np.sort(src))


def test_sort_bf16():
    import jax.numpy as jnp
    src = np.random.default_rng(9).standard_normal(128).astype(np.float32)
    v = dr_tpu.distributed_vector(128, dtype=jnp.bfloat16)
    v.assign_array(src.astype(jnp.bfloat16))
    dr_tpu.sort(v)
    got = dr_tpu.to_numpy(v).astype(np.float32)
    np.testing.assert_array_equal(got,
                                  np.sort(src.astype(jnp.bfloat16)
                                          .astype(np.float32)))


def test_sort_window_fallback():
    """Sorting a subrange must only reorder the window."""
    src = np.array([9, 8, 7, 6, 5, 4, 3, 2, 1, 0], dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort(v[2:7])
    got = dr_tpu.to_numpy(v)
    ref = src.copy()
    ref[2:7] = np.sort(ref[2:7])
    np.testing.assert_array_equal(got, ref)


def test_sort_uneven_distribution(mesh_size):
    """Uneven block_distribution layouts run the SAME sample-sort
    program (per-shard starts/sizes are static geometry)."""
    if mesh_size < 2:
        pytest.skip("needs >= 2 shards for an uneven split")
    sizes = [7] + [3] * (mesh_size - 1)
    n = sum(sizes)
    src = np.random.default_rng(4).standard_normal(n).astype(np.float32)
    v = dr_tpu.distributed_vector(
        n, np.float32, distribution=dr_tpu.block_distribution(sizes))
    v.assign_array(src)
    assert not dr_tpu.is_sorted(v)
    dr_tpu.sort(v)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src))
    assert dr_tpu.is_sorted(v)
    dr_tpu.sort(v, descending=True)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src)[::-1])


def test_sort_uneven_with_teams(mesh_size):
    """Zero-size shards (teams) in the distribution: empty shards
    contribute nothing, sample nothing, and receive exactly their
    (empty) windows."""
    if mesh_size < 3:
        pytest.skip("needs >= 3 shards for a zero-size middle shard")
    sizes = [5, 0] + [4] * (mesh_size - 2)
    n = sum(sizes)
    rng = np.random.default_rng(6)
    src = rng.integers(0, 50, n).astype(np.int32)
    dist = dr_tpu.block_distribution(sizes)
    v = dr_tpu.distributed_vector(n, np.int32, distribution=dist)
    v.assign_array(src)
    dr_tpu.sort(v)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src))
    assert dr_tpu.is_sorted(v)
    # stable key-value over the same uneven distribution
    k = rng.integers(0, 5, n).astype(np.float32)
    pay = np.arange(n, dtype=np.float32)
    kd = dr_tpu.distributed_vector(n, np.float32, distribution=dist)
    kd.assign_array(k)
    pd = dr_tpu.distributed_vector(n, np.float32, distribution=dist)
    pd.assign_array(pay)
    dr_tpu.sort_by_key(kd, pd)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(pd), pay[order])


def test_is_sorted_uneven_boundary(mesh_size):
    """A violation visible only at an uneven shard boundary, with an
    empty shard between the two conflicting shards."""
    if mesh_size < 3:
        pytest.skip("needs >= 3 shards")
    sizes = [4, 0] + [4] * (mesh_size - 2)
    n = sum(sizes)
    # shard 0 ascending but ABOVE shard 2's values; shards 2+ ascending
    src = np.concatenate([
        1000.0 + np.arange(4),
        np.arange(n - 4, dtype=np.float64) * 1.0,
    ]).astype(np.float32)
    dist = dr_tpu.block_distribution(sizes)
    v = dr_tpu.distributed_vector(n, np.float32, distribution=dist)
    v.assign_array(src)
    assert not dr_tpu.is_sorted(v)


def test_sort_by_key_random():
    n = 777
    rng = np.random.default_rng(11)
    k = rng.standard_normal(n).astype(np.float32)
    v = np.arange(n, dtype=np.int32)
    kd = dr_tpu.distributed_vector.from_array(k)
    vd = dr_tpu.distributed_vector.from_array(v)
    dr_tpu.sort_by_key(kd, vd)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), v[order])


def test_sort_by_key_stability():
    """Duplicate keys everywhere: the payload must come out in original
    global order within each tie group (stable), and descending must be
    the exact reverse of the ascending result."""
    n = 500
    rng = np.random.default_rng(12)
    k = rng.integers(0, 7, n).astype(np.int32)   # heavy duplication
    v = np.arange(n, dtype=np.float32)
    kd = dr_tpu.distributed_vector.from_array(k)
    vd = dr_tpu.distributed_vector.from_array(v)
    dr_tpu.sort_by_key(kd, vd)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), v[order])

    kd2 = dr_tpu.distributed_vector.from_array(k)
    vd2 = dr_tpu.distributed_vector.from_array(v)
    dr_tpu.sort_by_key(kd2, vd2, descending=True)
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd2), k[order][::-1])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd2), v[order][::-1])


def test_sort_by_key_rank_sweep(mesh_size, oracle):
    n = 6 * mesh_size + 5
    rng = np.random.default_rng(mesh_size + 50)
    k = rng.integers(0, 4, n).astype(np.float32)
    v = rng.standard_normal(n).astype(np.float32)
    kd = dr_tpu.distributed_vector.from_array(k)
    vd = dr_tpu.distributed_vector.from_array(v)
    dr_tpu.sort_by_key(kd, vd)
    order = np.argsort(k, kind="stable")
    oracle.equal(kd, k[order])
    oracle.equal(vd, v[order])


def test_sort_by_key_mixed_halo_layouts():
    """Key and payload containers with different halo widths still share
    the (nshards, seg, n) geometry, so the fast path must handle the
    differing physical row offsets."""
    n = 200
    rng = np.random.default_rng(13)
    k = rng.standard_normal(n).astype(np.float32)
    v = rng.standard_normal(n).astype(np.float32)
    kd = dr_tpu.distributed_vector.from_array(k)
    vd = dr_tpu.distributed_vector.from_array(
        v, halo=dr_tpu.halo_bounds(2, 2))
    dr_tpu.sort_by_key(kd, vd)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), v[order])


def test_sort_by_key_intmax_keys():
    """Integer keys EQUAL to the pad sentinel (dtype max) must keep
    their payloads: the global-index secondary key orders real elements
    before pad slots in the merge."""
    imax = np.iinfo(np.int32).max
    k = np.array([5, imax, 1, 2, 3, 4, 6, 7], dtype=np.int32)
    v = np.arange(8, dtype=np.float32)
    kd = dr_tpu.distributed_vector.from_array(k)
    vd = dr_tpu.distributed_vector.from_array(v)
    dr_tpu.sort_by_key(kd, vd)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), v[order])


def test_sort_by_key_signed_zero_ties():
    """-0.0 and +0.0 are IEEE-equal: numpy-stable tie order for the
    payload (the zero's sign itself is canonicalized to +0.0, like a
    NaN's payload)."""
    k = np.array([0.0, -0.0, 1.0, -0.0, 0.0], dtype=np.float32)
    v = np.array([10, 20, 30, 40, 50], dtype=np.float32)
    kd = dr_tpu.distributed_vector.from_array(k)
    vd = dr_tpu.distributed_vector.from_array(v)
    dr_tpu.sort_by_key(kd, vd)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), v[order])


def test_sort_by_key_length_mismatch():
    a = dr_tpu.distributed_vector.from_array(
        np.arange(4, dtype=np.float32))
    b = dr_tpu.distributed_vector.from_array(
        np.arange(5, dtype=np.float32))
    with pytest.raises(ValueError):
        dr_tpu.sort_by_key(a, b)


def test_argsort():
    rng = np.random.default_rng(21)
    src = rng.integers(0, 9, 300).astype(np.float32)  # many ties
    v = dr_tpu.distributed_vector.from_array(src)
    idx = dr_tpu.argsort(v)
    np.testing.assert_array_equal(dr_tpu.to_numpy(idx),
                                  np.argsort(src, kind="stable"))
    # the input is untouched
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)
    idx_d = dr_tpu.argsort(v, descending=True)
    np.testing.assert_array_equal(dr_tpu.to_numpy(idx_d),
                                  np.argsort(src, kind="stable")[::-1])


def test_is_sorted(mesh_size):
    p = mesh_size
    n = 5 * p + 2
    src = np.arange(n, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    assert dr_tpu.is_sorted(v)
    # a violation only a LOCAL compare can see
    bad = src.copy()
    bad[0] = 1e9
    assert not dr_tpu.is_sorted(dr_tpu.distributed_vector.from_array(bad))
    if p > 1:
        # a violation ONLY the cross-shard boundary check can see:
        # every shard internally ascending, shard r's values all above
        # shard r+1's (seg = ceil(n/p) with the exact n = p*seg)
        seg = 6
        cross = np.concatenate([
            (p - r) * 1000.0 + np.arange(seg) for r in range(p)
        ]).astype(np.float32)
        vc = dr_tpu.distributed_vector.from_array(cross)
        assert not dr_tpu.is_sorted(vc)
    # equal runs are sorted; NaNs count as largest (numpy order)
    ve = dr_tpu.distributed_vector.from_array(np.zeros(n, np.float32))
    assert dr_tpu.is_sorted(ve)
    wn = np.sort(np.r_[src[: n - 1], [np.nan]])
    vn = dr_tpu.distributed_vector.from_array(wn.astype(np.float32))
    assert dr_tpu.is_sorted(vn)
    nan_first = np.r_[[np.nan], src[: n - 1]].astype(np.float32)
    assert not dr_tpu.is_sorted(
        dr_tpu.distributed_vector.from_array(nan_first))


def test_is_sorted_window():
    src = np.array([9, 1, 2, 3, 0], dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    assert not dr_tpu.is_sorted(v)
    assert dr_tpu.is_sorted(v[1:4])


def test_is_sorted_and_argsort_accept_views():
    """Both are READ-ONLY: transform views are legal inputs (reduce's
    convention), and the view chain fuses into argsort's scratch copy."""
    from dr_tpu.views import views
    src = np.array([3.0, 1.0, 2.0], dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    tv = views.transform(v, lambda x: -x)
    assert not dr_tpu.is_sorted(tv)
    assert dr_tpu.is_sorted(views.transform(
        dr_tpu.distributed_vector.from_array(np.sort(src)),
        lambda x: x * 2.0))
    idx = dr_tpu.argsort(tv)
    np.testing.assert_array_equal(dr_tpu.to_numpy(idx),
                                  np.argsort(-src, kind="stable"))


def test_is_sorted_f64_exact():
    """f64 pairs closer than an f32 ulp must compare exactly (the
    fallback must NOT round through the f32 key encoding)."""
    import jax
    if not jax.config.jax_enable_x64:
        a = np.array([1.0, 1.0 - 2 ** -53], dtype=np.float64)
        # without x64 the container itself downcasts; assert the
        # fallback path at least agrees with the stored values
        v = dr_tpu.distributed_vector.from_array(
            a.astype(np.float32))
        assert dr_tpu.is_sorted(v)  # equal after f32 rounding
    else:  # pragma: no cover - x64-enabled environments
        v = dr_tpu.distributed_vector.from_array(
            np.array([1.0, 1.0 - 2 ** -53], dtype=np.float64))
        assert not dr_tpu.is_sorted(v)


def test_sort_then_is_sorted_composes():
    rng = np.random.default_rng(22)
    src = rng.standard_normal(513).astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    assert not dr_tpu.is_sorted(v)
    dr_tpu.sort(v)
    assert dr_tpu.is_sorted(v)


def test_sort_rejects_transform_views():
    src = np.arange(8, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    from dr_tpu.views import views
    with pytest.raises(TypeError):
        dr_tpu.sort(views.transform(v, lambda x: x * 2))


@pytest.mark.parametrize("descending", [False, True])
def test_sort_window_native_uneven(mesh_size, descending):
    """Round 4: subrange windows run the sample-sort program in
    window-relative coordinates — including over uneven distributions
    with empty team shards; cells outside the window are untouched
    bit-exactly."""
    P = dr_tpu.nprocs()
    if P < 3:
        pytest.skip("needs a team-bearing distribution")
    sizes = [5, 0] + [4] * (P - 2)
    n = sum(sizes)
    src = np.random.default_rng(n).standard_normal(n).astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
    b, e = 2, n - 3
    dr_tpu.sort(v[b:e], descending=descending)
    ref = src.copy()
    w = np.sort(ref[b:e])
    ref[b:e] = w[::-1] if descending else w
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), ref)


def test_sort_window_native_no_materialize(monkeypatch):
    v = dr_tpu.distributed_vector.from_array(
        np.random.default_rng(5).standard_normal(64).astype(np.float32))

    def boom(self):
        raise AssertionError("window sort materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort(v[7:41])
    monkeypatch.undo()
    got = dr_tpu.to_numpy(v)
    assert dr_tpu.is_sorted(v[7:41])
    assert len(got) == 64


def test_sort_window_signed_zero_bit_exact():
    src = np.array([1.0, -0.0, 0.0, -1.0, -0.0, 2.0], dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort(v[1:5])
    got = dr_tpu.to_numpy(v)
    np.testing.assert_array_equal(got, [1.0, -1.0, -0.0, -0.0, 0.0, 2.0])
    assert list(np.signbit(got)) == [False, True, True, True, False,
                                     False]


def test_is_sorted_window_native(monkeypatch):
    """Round 4: is_sorted on subrange windows runs the fused program
    (window coordinates) — no materialize."""
    src = np.array([9.0, 1.0, 2.0, 3.0, -5.0], dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)

    def boom(self):
        raise AssertionError("is_sorted window materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    assert dr_tpu.is_sorted(v[1:4])
    assert not dr_tpu.is_sorted(v[0:3])
    assert not dr_tpu.is_sorted(v[2:5])
    assert dr_tpu.is_sorted(v[3:3])  # empty window
    monkeypatch.undo()


def test_is_sorted_window_uneven(mesh_size):
    if mesh_size < 3:
        pytest.skip("needs a team-bearing distribution")
    sizes = [5, 0] + [4] * (mesh_size - 2)
    n = sum(sizes)
    src = np.arange(n, dtype=np.float32)
    src[0] = 99.0  # violation OUTSIDE the window only
    v = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
    assert not dr_tpu.is_sorted(v)
    assert dr_tpu.is_sorted(v[1:n])
    assert not dr_tpu.is_sorted(v[0:4])


def test_sort_by_key_mixed_distributions_native(mesh_size, monkeypatch):
    """Round 4: keys and values may carry DIFFERENT block
    distributions — the payload realigns to key coordinates on entry
    (one static masked all_to_all) and rebalances into its own windows
    on exit.  No materialize; stable ties; empty team shards included."""
    if mesh_size < 3:
        pytest.skip("needs a team-bearing distribution")
    P = mesh_size
    ksizes = [5, 0] + [4] * (P - 2)
    n = sum(ksizes)
    vsizes = list(dr_tpu.even_sizes(n, P))
    rng = np.random.default_rng(n)
    k = rng.integers(0, 5, n).astype(np.float32)   # heavy ties
    pay = np.arange(n, dtype=np.float32)
    kd = dr_tpu.distributed_vector.from_array(
        k, distribution=dr_tpu.block_distribution(ksizes))
    pd = dr_tpu.distributed_vector.from_array(pay, distribution=vsizes)

    def boom(self):
        raise AssertionError("mixed-distribution sort_by_key "
                             "materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort_by_key(kd, pd)
    monkeypatch.undo()
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(pd), pay[order])
    # descending too (whole order reversed, ties included)
    kd2 = dr_tpu.distributed_vector.from_array(
        k, distribution=dr_tpu.block_distribution(ksizes))
    pd2 = dr_tpu.distributed_vector.from_array(pay,
                                               distribution=vsizes)
    dr_tpu.sort_by_key(kd2, pd2, descending=True)
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd2), k[order][::-1])
    np.testing.assert_array_equal(dr_tpu.to_numpy(pd2),
                                  pay[order][::-1])


def test_sort_by_key_window_native(mesh_size, monkeypatch):
    """Round 4: windowed sort_by_key runs the sample-sort program —
    key and value windows may sit at DIFFERENT offsets and carry
    different distributions; cells outside both windows are untouched."""
    if mesh_size < 3:
        pytest.skip("needs a team-bearing distribution")
    P = mesh_size
    ksizes = [5, 0] + [4] * (P - 2)
    n = sum(ksizes)
    vsizes = list(dr_tpu.even_sizes(n, P))
    rng = np.random.default_rng(n + 1)
    k = rng.integers(0, 4, n).astype(np.float32)
    pay = np.arange(n, dtype=np.float32)
    kd = dr_tpu.distributed_vector.from_array(
        k, distribution=dr_tpu.block_distribution(ksizes))
    pd = dr_tpu.distributed_vector.from_array(pay, distribution=vsizes)
    kb, ke = 2, n - 3
    vb = 1
    wn = ke - kb

    def boom(self):
        raise AssertionError("windowed sort_by_key materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort_by_key(kd[kb:ke], pd[vb:vb + wn])
    monkeypatch.undo()
    kref = k.copy()
    pref = pay.copy()
    order = np.argsort(k[kb:ke], kind="stable")
    kref[kb:ke] = k[kb:ke][order]
    pref[vb:vb + wn] = pay[vb:vb + wn][order]
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), kref)
    np.testing.assert_array_equal(dr_tpu.to_numpy(pd), pref)


def test_sort_by_key_same_container_disjoint_windows_native(monkeypatch):
    """DISJOINT windows of ONE container run the aliased single-row
    program (round 5 — this shape used to take the sequential
    fallback): both blends land in one donated buffer, no
    materialize."""
    n = 20
    src = np.random.default_rng(2).standard_normal(n).astype(np.float32)
    x = dr_tpu.distributed_vector.from_array(src)

    def boom(self):
        raise AssertionError("aliased sort_by_key materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort_by_key(x[0:8], x[10:18])
    monkeypatch.undo()
    ref = src.copy()
    order = np.argsort(src[0:8], kind="stable")
    ref[0:8] = src[0:8][order]
    ref[10:18] = src[10:18][order]
    np.testing.assert_array_equal(dr_tpu.to_numpy(x), ref)
    # value window BEFORE the key window, uneven split point
    src2 = np.random.default_rng(3).standard_normal(n).astype(np.float32)
    y = dr_tpu.distributed_vector.from_array(src2)
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort_by_key(y[11:18], y[2:9], descending=True)
    monkeypatch.undo()
    ref2 = src2.copy()
    order2 = np.argsort(src2[11:18], kind="stable")[::-1]
    ref2[11:18] = src2[11:18][order2]
    ref2[2:9] = src2[2:9][order2]
    np.testing.assert_array_equal(dr_tpu.to_numpy(y), ref2)


def test_sort_by_key_same_container_overlap_native(monkeypatch):
    """OVERLAPPING windows of one container are native too (round 5):
    both slices read the original row, blends compose payload-last —
    byte-for-byte the old sequential fallback's write order."""
    n = 20
    src = np.random.default_rng(4).standard_normal(n).astype(np.float32)
    x = dr_tpu.distributed_vector.from_array(src)

    def boom(self):
        raise AssertionError("overlapping sort_by_key materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort_by_key(x[0:8], x[5:13])
    monkeypatch.undo()
    ref = src.copy()
    order = np.argsort(src[0:8], kind="stable")
    ref[0:8] = src[0:8][order]
    ref[5:13] = src[5:13][order]
    np.testing.assert_array_equal(dr_tpu.to_numpy(x), ref)
    # value window first, partial overlap the other direction
    y = dr_tpu.distributed_vector.from_array(src)
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort_by_key(y[9:17], y[4:12])
    monkeypatch.undo()
    ref2 = src.copy()
    o2 = np.argsort(src[9:17], kind="stable")
    ref2[9:17] = src[9:17][o2]
    ref2[4:12] = src[4:12][o2]
    np.testing.assert_array_equal(dr_tpu.to_numpy(y), ref2)


def test_sort_by_key_keys_are_values():
    """sort_by_key(x, x) (and equal windows of one container) is plain
    sort — no double donation of one buffer."""
    n = 33
    src = np.random.default_rng(6).standard_normal(n).astype(np.float32)
    x = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort_by_key(x, x)
    np.testing.assert_array_equal(dr_tpu.to_numpy(x), np.sort(src))
    y = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort_by_key(y[3:17], y[3:17])
    ref = src.copy()
    ref[3:17] = np.sort(src[3:17])
    np.testing.assert_array_equal(dr_tpu.to_numpy(y), ref)


def test_sort_by_key_empty_window_noop():
    n = 12
    src = np.arange(n, dtype=np.float32)[::-1].copy()
    k = dr_tpu.distributed_vector.from_array(src)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort_by_key(k[3:3], v[5:5])
    np.testing.assert_array_equal(dr_tpu.to_numpy(k), src)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), src)


def test_f64_sort_native_under_x64_subprocess():
    """Real f64 keys (x64-enabled mesh) run the NATIVE sample-sort /
    is_sorted programs through the 64-bit sign-flip encoding — no
    materialize, and pairs closer than an f32 ulp order exactly
    (round 5; the old fallback is gone)."""
    import subprocess
    import sys
    from pathlib import Path
    import os
    repo = Path(__file__).resolve().parent.parent
    code = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import dr_tpu

dr_tpu.init()
# any to_array during an armed window => the native path was left
import contextlib

@contextlib.contextmanager
def armed():
    real = dr_tpu.distributed_vector.to_array
    def boom(self):
        raise AssertionError("f64 path materialized")
    dr_tpu.distributed_vector.to_array = boom
    try:
        yield
    finally:
        dr_tpu.distributed_vector.to_array = real

n = 97
rng = np.random.default_rng(5)
base = rng.standard_normal(n)
# adjacent pairs closer than an f32 ulp: f32 rounding would tie them
src = (base + rng.uniform(-2**-40, 2**-40, n)).astype(np.float64)
v = dr_tpu.distributed_vector(n, np.float64)
v.assign_array(src)
assert v._data.dtype == np.float64, v._data.dtype  # real f64 buffer
with armed():
    dr_tpu.sort(v)
got = np.asarray(dr_tpu.to_numpy(v))
assert got.dtype == np.float64
np.testing.assert_array_equal(got, np.sort(src))
with armed():
    assert dr_tpu.is_sorted(v)

# is_sorted must see sub-f32-ulp inversions exactly
w = dr_tpu.distributed_vector(2, np.float64)
w.assign_array(np.array([1.0, 1.0 - 2**-53], dtype=np.float64))
with armed():
    assert not dr_tpu.is_sorted(w)

# f64 keys + f64 payload, stable, descending too
k = rng.standard_normal(n)
k[13] = k[31]  # a tie
pay = np.arange(n, dtype=np.float64)
kd = dr_tpu.distributed_vector(n, np.float64); kd.assign_array(k)
pd = dr_tpu.distributed_vector(n, np.float64); pd.assign_array(pay)
with armed():
    dr_tpu.sort_by_key(kd, pd)
order = np.argsort(k, kind="stable")
np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
np.testing.assert_array_equal(dr_tpu.to_numpy(pd), pay[order])

# NaNs last, -0.0/+0.0 handling on the 64-bit path
z = np.array([np.nan, -0.0, 1.5, 0.0, -1.5, np.nan], dtype=np.float64)
zd = dr_tpu.distributed_vector(len(z), np.float64)
zd.assign_array(z)
with armed():
    dr_tpu.sort(zd)
zg = np.asarray(dr_tpu.to_numpy(zd))
np.testing.assert_array_equal(zg, np.sort(z))
assert np.signbit(zg[1]) and not np.signbit(zg[2])  # -0.0 before +0.0
print("X64-SORT-OK")
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    out = subprocess.run([sys.executable, "-c", code], cwd=repo,
                         env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stderr[-3000:]
    assert "X64-SORT-OK" in out.stdout


def test_sort_by_key_mismatched_shard_counts_native():
    """Keys and values on DIFFERENT runtimes (shard counts) take the
    reshard route (round 5 — this used to be the argsort materialize):
    payload reshards onto the key runtime, the sample-sort runs
    natively there, result reshards back.  No MaterializeFallback
    warning fires."""
    import warnings
    from dr_tpu.parallel.runtime import Runtime
    from dr_tpu.utils.fallback import MaterializeFallbackWarning
    from jax.sharding import Mesh
    ndev = len(jax.devices())
    if ndev < 2:
        pytest.skip("needs >= 2 devices for distinct shard counts")
    rt_small = Runtime(mesh=Mesh(np.asarray(jax.devices()[:ndev // 2]),
                                 ("x",)))
    n = 101
    rng = np.random.default_rng(7)
    k = rng.standard_normal(n).astype(np.float32)
    pay = np.arange(n, dtype=np.int32)
    kd = dr_tpu.distributed_vector(n, np.float32)
    kd.assign_array(k)
    vd = dr_tpu.distributed_vector(n, np.int32, runtime=rt_small)
    vd.assign_array(pay)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        dr_tpu.sort_by_key(kd, vd)
    assert not [r for r in rec
                if issubclass(r.category, MaterializeFallbackWarning)], \
        [str(r.message) for r in rec]
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), pay[order])

    # windows on both sides, descending, int payload
    kd2 = dr_tpu.distributed_vector(n, np.float32)
    kd2.assign_array(k)
    vd2 = dr_tpu.distributed_vector(n, np.int32, runtime=rt_small)
    vd2.assign_array(pay)
    dr_tpu.sort_by_key(kd2[5:60], vd2[10:65], descending=True)
    kref = k.copy()
    pref = pay.copy()
    o = np.argsort(k[5:60], kind="stable")[::-1]
    kref[5:60] = k[5:60][o]
    pref[10:65] = pay[10:65][o]
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd2), kref)
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd2), pref)


def test_sort_by_key_equal_counts_different_devices_native():
    """EQUAL shard counts over DIFFERENT device sets must also take
    the reshard route — mesh identity, not shard count, is the
    dispatch (round-5 review finding)."""
    from dr_tpu.parallel.runtime import Runtime
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices for two disjoint 4-device meshes")
    rt_a = Runtime(mesh=Mesh(np.asarray(devs[:4]), ("x",)))
    rt_b = Runtime(mesh=Mesh(np.asarray(devs[4:8]), ("x",)))
    n = 57
    rng = np.random.default_rng(8)
    k = rng.standard_normal(n).astype(np.float32)
    pay = np.arange(n, dtype=np.int32)
    kd = dr_tpu.distributed_vector(n, np.float32, runtime=rt_a)
    kd.assign_array(k)
    vd = dr_tpu.distributed_vector(n, np.int32, runtime=rt_b)
    vd.assign_array(pay)
    dr_tpu.sort_by_key(kd, vd)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), pay[order])


def test_sort_n_fused_loop():
    """sort_n / sort_by_key_n (bench helpers): chained in-program
    rounds leave the same result as one sort."""
    from dr_tpu.algorithms.sort import sort_by_key_n, sort_n
    n = 200
    src = np.random.default_rng(9).standard_normal(n).astype(np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    sort_n(v, 3)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src))
    k = np.random.default_rng(10).standard_normal(n).astype(np.float32)
    kd = dr_tpu.distributed_vector.from_array(k)
    pd = dr_tpu.distributed_vector(n, np.int32)
    dr_tpu.iota(pd, 0)
    sort_by_key_n(kd, pd, 2)
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), np.sort(k))
    # after round 1 keys are sorted, so round 2's stable order is the
    # identity over round 1's payload — i.e. the single-sort payload
    np.testing.assert_array_equal(dr_tpu.to_numpy(pd),
                                  np.argsort(k, kind="stable"))


def test_is_sorted_view_chain_native(monkeypatch):
    """is_sorted over transform-view chains fuses the op stack into
    the program (round 5 — views used to materialize)."""
    from dr_tpu.views import views
    src = np.arange(40, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)

    def boom(self):
        raise AssertionError("is_sorted view chain materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    assert dr_tpu.is_sorted(views.transform(v, lambda x: x * 2.0))
    assert not dr_tpu.is_sorted(views.transform(v, lambda x: -x))
    # windowed chain: monotone op keeps the window sorted...
    assert dr_tpu.is_sorted(views.transform(v[5:30], lambda x: x + 3.0))
    # ...and a violation INSIDE the window that only appears after the
    # op is applied (negation flips the ascending run) must be seen by
    # the windowed boundary compare too
    assert not dr_tpu.is_sorted(views.transform(v[5:30], lambda x: -x))
    # boundary-only violation: data sorted within every shard, one
    # inversion exactly at a shard boundary, visible through the chain
    P = dr_tpu.nprocs()
    if P >= 2:
        seg = -(-32 // P)
        w = np.arange(32, dtype=np.float32)
        w[seg] = -50.0  # first element of shard 1 undercuts shard 0
        wv = dr_tpu.distributed_vector.from_array(w)
        assert not dr_tpu.is_sorted(views.transform(wv, lambda x: x * 2.0))
    monkeypatch.undo()


def _shift_op(x, mu):
    return x + mu


def test_is_sorted_streamed_boundop_zero_recompile():
    """Round-6 compile-churn fix (the scan twin): is_sorted over a
    BoundOp transform chain keys on op identity + scalar count and
    feeds the coefficient traced — a streamed-coefficient loop builds
    ZERO new programs after the first call."""
    from dr_tpu.algorithms.elementwise import _prog_cache
    from dr_tpu.views import views
    src = np.arange(40, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    assert dr_tpu.is_sorted(views.transform(v, _shift_op, 0.5))
    n_progs = len(_prog_cache)
    for mu in (0.25, -1.5, 3.0, 7.25):
        assert dr_tpu.is_sorted(views.transform(v, _shift_op, mu))
    assert len(_prog_cache) == n_progs, \
        "streamed BoundOp coefficients recompiled is_sorted"


def test_sort_phase_truncations_chain_and_complete():
    """Round-6 profiling surface: every stop_after prefix of the
    keys-only program builds, runs as a fused loop, and keeps the
    container shape; the full prefix (the last phase name) IS the
    real sort."""
    from dr_tpu.algorithms.sort import (SORT_PHASES, sort_phases_n)
    n = 96
    src = np.random.default_rng(5).standard_normal(n).astype(np.float32)
    for phase in SORT_PHASES[:-1]:
        v = dr_tpu.distributed_vector.from_array(src)
        sort_phases_n(v, phase, 2)
        got = dr_tpu.to_numpy(v)
        assert got.shape == (n,) and got.dtype == np.float32
    v = dr_tpu.distributed_vector.from_array(src)
    sort_phases_n(v, SORT_PHASES[-1], 2)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src))


def test_sortkv_phase_truncations_leave_payload_untouched():
    """Truncations before the "payload" phase must leave the payload
    container bit-identical — the single-exchange plan's accounting
    claim (no earlier phase reads or moves the payload) made
    testable."""
    from dr_tpu.algorithms.sort import (SORTKV_PHASES,
                                        sort_by_key_phases_n)
    n = 80
    rng = np.random.default_rng(6)
    k = rng.standard_normal(n).astype(np.float32)
    pay = rng.standard_normal(n).astype(np.float32)
    for phase in SORTKV_PHASES[:-1]:
        kd = dr_tpu.distributed_vector.from_array(k)
        vd = dr_tpu.distributed_vector.from_array(pay)
        sort_by_key_phases_n(kd, vd, phase, 2)
        np.testing.assert_array_equal(dr_tpu.to_numpy(vd), pay,
                                      err_msg=f"phase={phase}")
    kd = dr_tpu.distributed_vector.from_array(k)
    vd = dr_tpu.distributed_vector.from_array(pay)
    sort_by_key_phases_n(kd, vd, SORTKV_PHASES[-1], 2)
    order = np.argsort(k, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(kd), k[order])
    np.testing.assert_array_equal(dr_tpu.to_numpy(vd), pay[order])


def test_sort_stable_override_env(monkeypatch):
    """DR_TPU_SORT_STABLE=1 (the tune A/B knob) still sorts correctly
    and builds its own cached programs."""
    monkeypatch.setenv("DR_TPU_SORT_STABLE", "1")
    n = 120
    rng = np.random.default_rng(8)
    src = rng.integers(0, 6, n).astype(np.float32)
    pay = np.arange(n, dtype=np.int32)
    v = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.sort(v)
    np.testing.assert_array_equal(dr_tpu.to_numpy(v), np.sort(src))
    kd = dr_tpu.distributed_vector.from_array(src)
    pd = dr_tpu.distributed_vector.from_array(pay)
    dr_tpu.sort_by_key(kd, pd)
    order = np.argsort(src, kind="stable")
    np.testing.assert_array_equal(dr_tpu.to_numpy(pd), pay[order])
