"""The native bridge's expression DSL compiler (dr_tpu/utils/expr.py):
grammar validation, numeric parity with numpy, and the identity-caching
contract the algorithm-layer program caches rely on."""

import numpy as np
import pytest

from dr_tpu.utils.expr import op_from_expr


def test_arithmetic_matches_numpy():
    f = op_from_expr("(x0 * 2.0 + 1.0)", 1)
    x = np.linspace(-2, 2, 64).astype(np.float32)
    np.testing.assert_allclose(np.asarray(f(x)), x * 2.0 + 1.0,
                               rtol=1e-6)


def test_binary_and_functions():
    f = op_from_expr("maximum(sqrt(abs(x0)), tanh(x1))", 2)
    a = np.linspace(-4, 4, 32).astype(np.float32)
    b = np.linspace(-1, 1, 32).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(f(a, b)), np.maximum(np.sqrt(np.abs(a)), np.tanh(b)),
        rtol=1e-6)


def test_identity_caching():
    # equal strings MUST return the same function object: the program
    # caches key user ops by identity (core/pinning.pinned_id)
    assert op_from_expr("(x0 + x1)", 2) is op_from_expr("(x0 + x1)", 2)
    assert op_from_expr("(x0 + x1)", 2) is not op_from_expr("(x0 - x1)", 2)


def test_rejects_non_dsl_names():
    for bad in ("__import__('os')", "open('x')", "x9", "foo(x0)",
                "x0.__class__", "lambda: 1", "x0; x0"):
        with pytest.raises(ValueError):
            op_from_expr(bad, 2)


def test_scientific_literals_ok():
    f = op_from_expr("(x0 * 1e-3 + 2.5e2)", 1)
    x = np.ones(8, np.float32)
    np.testing.assert_allclose(np.asarray(f(x)), x * 1e-3 + 250.0,
                               rtol=1e-6)
