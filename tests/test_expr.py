"""The native bridge's expression DSL compiler (dr_tpu/utils/expr.py):
grammar validation, numeric parity with numpy, and the identity-caching
contract the algorithm-layer program caches rely on."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dr_tpu
from dr_tpu.utils.expr import op_from_expr


def test_arithmetic_matches_numpy():
    f = op_from_expr("(x0 * 2.0 + 1.0)", 1)
    x = np.linspace(-2, 2, 64).astype(np.float32)
    np.testing.assert_allclose(np.asarray(f(x)), x * 2.0 + 1.0,
                               rtol=1e-6)


def test_binary_and_functions():
    f = op_from_expr("maximum(sqrt(abs(x0)), tanh(x1))", 2)
    a = np.linspace(-4, 4, 32).astype(np.float32)
    b = np.linspace(-1, 1, 32).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(f(a, b)), np.maximum(np.sqrt(np.abs(a)), np.tanh(b)),
        rtol=1e-6)


def test_identity_caching():
    # equal strings MUST return the same function object: the program
    # caches key user ops by identity (core/pinning.pinned_id)
    assert op_from_expr("(x0 + x1)", 2) is op_from_expr("(x0 + x1)", 2)
    assert op_from_expr("(x0 + x1)", 2) is not op_from_expr("(x0 - x1)", 2)


def test_rejects_non_dsl_names():
    for bad in ("__import__('os')", "open('x')", "x9", "foo(x0)",
                "x0.__class__", "lambda: 1", "x0; x0"):
        with pytest.raises(ValueError):
            op_from_expr(bad, 2)


def test_scientific_literals_ok():
    f = op_from_expr("(x0 * 1e-3 + 2.5e2)", 1)
    x = np.ones(8, np.float32)
    np.testing.assert_allclose(np.asarray(f(x)), x * 1e-3 + 250.0,
                               rtol=1e-6)


def test_op_from_source_escape_hatch():
    """Full-Python custom ops (SURVEY §7 hard-part 2 option b): jax-
    traceable source the arithmetic DSL cannot express, cached by
    (source, nargs) so identity-keyed program caches stay warm."""
    from dr_tpu.utils.expr import op_from_source
    src = "lambda x0: jnp.where(x0 > 0, x0, 0.01 * x0)"
    fn = op_from_source(src, 1)
    assert fn is op_from_source(src, 1)  # identity-stable
    x = jnp.asarray([-2.0, 3.0], jnp.float32)
    np.testing.assert_allclose(np.asarray(fn(x)), [-0.02, 3.0])
    # traceable under jit (compile once, then call — R6 discipline)
    jfn = jax.jit(fn)
    np.testing.assert_allclose(np.asarray(jfn(x)), [-0.02, 3.0])
    # arity mismatch is a loud error
    with pytest.raises(ValueError):
        op_from_source("lambda x0, x1: x0 + x1", 1)
    with pytest.raises(TypeError):
        op_from_source("42", 1)
    # compatible signatures are NOT rejected: defaulted extras, *args,
    # and signatureless ufuncs all accept nargs positionals
    f2 = op_from_source(
        "lambda x0, alpha=0.5: jnp.where(x0 > 0, x0, alpha * x0)", 1)
    np.testing.assert_allclose(np.asarray(f2(jnp.asarray([-2.0]))),
                               [-1.0])
    f3 = op_from_source("lambda *xs: xs[0] + xs[1]", 2)
    assert float(f3(jnp.asarray(1.0), jnp.asarray(2.0))) == 3.0
    f4 = op_from_source("jnp.abs", 1)  # read-only __name__: no crash
    assert float(f4(jnp.asarray(-3.0))) == 3.0


def test_op_from_source_drives_algorithms():
    src_clip = "lambda x0: jnp.clip(x0, 0.0, 6.0)"
    from dr_tpu.utils.expr import op_from_source
    v = dr_tpu.distributed_vector(32)
    dr_tpu.iota(v, -16)
    out = dr_tpu.distributed_vector(32)
    dr_tpu.transform(v, out, op_from_source(src_clip, 1))
    ref = np.clip(np.arange(-16, 16, dtype=np.float32), 0.0, 6.0)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref)


def test_expr_arity_validated_at_boundary():
    """Wrong-arity DSL calls fail in the VALIDATOR (ValueError), not as
    a TypeError when the op first runs inside a jitted algorithm
    (round-5 review finding)."""
    import pytest

    from dr_tpu.utils.expr import op_from_expr
    for bad in ("abs(x0, x1)", "minimum(x0)", "sqrt()", "power(x0)",
                "maximum(x0, x1, x0)"):
        with pytest.raises(ValueError):
            op_from_expr(bad, 2)
    # the boundary cases still pass
    assert callable(op_from_expr("minimum(x0, x1)", 2))
    assert callable(op_from_expr("abs(x0)", 1))
