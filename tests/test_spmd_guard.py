"""SPMD dispatch-order guard (utils/spmd_guard.py): recording through
the shared program cache, canonicalization of process-local ids, and
digest agreement for identical dispatch sequences.  The cross-process
verify() path runs in tests/multihost_worker.py."""

import numpy as np

import dr_tpu
from dr_tpu.utils import spmd_guard


def _workload(n):
    a = dr_tpu.distributed_vector(n)
    b = dr_tpu.distributed_vector(n)
    dr_tpu.iota(a, 0)
    dr_tpu.fill(b, 2.0)
    dr_tpu.dot(a, b)
    out = dr_tpu.distributed_vector(n)
    dr_tpu.inclusive_scan(a, out)


def test_guard_records_dispatches():
    with spmd_guard.guard() as g:
        _workload(256)
    assert len(g.trace) >= 4  # iota, fill, dot, scan at minimum
    # verify() is a no-op single-process but must not raise
    g.verify()


def test_identical_sequences_share_digest():
    with spmd_guard.guard() as g1:
        _workload(256)
    with spmd_guard.guard() as g2:
        _workload(256)
    assert g1.digest() == g2.digest()
    with spmd_guard.guard() as g3:
        _workload(512)  # different layout -> different trace
    assert g1.digest() != g3.digest()


def test_canonicalization_hides_object_ids():
    # pinned ids are object identities (typed PinnedId): legitimately
    # different across processes, so they canonicalize to a placeholder
    from dr_tpu.core.pinning import pinned_id
    key1 = ("dot", pinned_id(object()), (8, 32, 0, 0, 256))
    key2 = ("dot", pinned_id(object()), (8, 32, 0, 0, 256))
    assert spmd_guard._canon(key1) == spmd_guard._canon(key2)
    assert "ptr" in spmd_guard._canon(key1)
    # structural ints — however large — must survive verbatim: a
    # billion-element n differing across processes IS a divergence
    big1 = ("scan", (8, 1 << 33, 0, 0, (1 << 36) + 8))
    big2 = ("scan", (8, 1 << 33, 0, 0, (1 << 36) + 16))
    assert spmd_guard._canon(big1) != spmd_guard._canon(big2)


def test_divergence_detection_logic():
    # exercise the comparison logic directly (two processes can't run
    # inside one pytest process; the live path runs in the multihost
    # worker)
    g = spmd_guard.SpmdGuard()
    g.record(("fill", 1))
    g.record(("dot", 2))
    h = spmd_guard.SpmdGuard()
    h.record(("fill", 1))
    h.record(("scan", 2))
    assert g.digest() != h.digest()
    assert g.trace[0] == h.trace[0] and g.trace[1] != h.trace[1]


def test_guard_nesting_restores():
    assert spmd_guard.active() is None
    with spmd_guard.guard() as outer:
        with spmd_guard.guard() as inner:
            dr_tpu.fill(dr_tpu.distributed_vector(64), 1.0)
            assert spmd_guard.active() is inner
        assert spmd_guard.active() is outer
    assert spmd_guard.active() is None


def test_all_module_caches_are_tapped():
    """Halo, collectives, matrix, mdarray and attention dispatches must
    land on the trace too — the collective-heaviest paths are exactly
    where divergence deadlocks live."""
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(32, dtype=np.float32), halo=hb)
    with spmd_guard.guard() as g:
        dr_tpu.halo(dv).exchange()
        n0 = len(g.trace)
        assert n0 >= 1, "halo exchange not recorded"
        comm = dr_tpu.default_comm()
        comm.shift_forward(dv._data, periodic=True)
        assert len(g.trace) > n0, "communicator shift not recorded"
        n1 = len(g.trace)
        M = dr_tpu.distributed_mdarray.from_array(
            np.zeros((8, 8), np.float32))
        T = dr_tpu.distributed_mdarray((8, 8))
        dr_tpu.transpose(T, M)
        assert len(g.trace) > n1, "mdarray transpose not recorded"
        n2 = len(g.trace)
        A = dr_tpu.dense_matrix.from_array(np.ones((8, 8), np.float32))
        dr_tpu.gemm(A, A)
        assert len(g.trace) > n2, "dense matrix dispatch not recorded"
        n3 = len(g.trace)
        S = 4 * dr_tpu.nprocs()
        q = np.zeros((1, S, 1, 8), np.float32)
        dr_tpu.ring_attention(q, q, q, causal=True)
        assert len(g.trace) > n3, "ring attention not recorded"


def test_op_identity_survives_canonicalization():
    """Same geometry + DIFFERENT user op must diverge: pinned callables
    canonicalize to their qualname, not to the 'ptr' placeholder."""

    def op_a(x):
        return x * 2

    def op_b(x):
        return x * 3

    src = np.ones(64, np.float32)
    out = dr_tpu.distributed_vector(64)
    with spmd_guard.guard() as ga:
        dr_tpu.transform(dr_tpu.distributed_vector.from_array(src), out,
                         op_a)
    with spmd_guard.guard() as gb:
        dr_tpu.transform(dr_tpu.distributed_vector.from_array(src), out,
                         op_b)
    assert ga.digest() != gb.digest()
    assert any("op_a" in t for t in ga.trace)


def test_lambda_code_identity_diverges():
    """Two different lambdas share __qualname__ '<lambda>'; the code
    hash keeps a rank-dependent op choice visible in the trace."""
    from dr_tpu.core.pinning import pinned_id
    f = lambda x: x * 2  # noqa: E731
    g = lambda x: x * 3  # noqa: E731
    cf = spmd_guard._canon(("t", pinned_id(f)))
    cg = spmd_guard._canon(("t", pinned_id(g)))
    assert cf != cg
    # while EQUAL source in the same position canonicalizes stably
    h1 = lambda x: x * 2  # noqa: E731
    assert spmd_guard._canon(("t", pinned_id(h1))) == cf


def test_tapped_cache_lru_bound(monkeypatch):
    """The program caches are bounded LRUs: inserts beyond the cap
    evict the OLDEST entry, and a get refreshes recency (a hot program
    survives a stream of one-shot layouts)."""
    from dr_tpu.utils.spmd_guard import TappedCache

    monkeypatch.setenv("DR_TPU_PROG_CACHE_CAP", "8")
    c = TappedCache()
    for i in range(8):
        c[("k", i)] = i
    assert len(c) == 8
    # touch the oldest: it must survive the next insert
    assert c.get(("k", 0)) == 0
    c[("k", 8)] = 8
    assert len(c) == 8
    assert c.get(("k", 0)) == 0          # refreshed -> kept
    assert c.get(("k", 1)) is None       # the true oldest -> evicted
    # setdefault counts as a touch too
    c.setdefault(("k", 2), None)
    for i in range(9, 15):
        c[("k", i)] = i
    assert c.get(("k", 2)) is not None
    # malformed cap falls back to the default 512 (tolerant knob
    # parsing): the 9th insert must NOT evict under the fallback
    monkeypatch.setenv("DR_TPU_PROG_CACHE_CAP", "lots")
    before = len(c)
    c[("k", 99)] = 99
    assert len(c) == before + 1


def test_pin_eviction_purges_cache_entries(monkeypatch):
    """Pins are a bounded LRU; evicting a pin purges every cache entry
    whose key references that identity, so a later id reuse can never
    alias a stale program (core/pinning.py docstring)."""
    from collections import OrderedDict

    from dr_tpu.core import pinning
    from dr_tpu.utils.spmd_guard import TappedCache

    # isolate: fresh pin table so ambient pins are untouched (their
    # objects stay alive, so their ids cannot collide with ours)
    monkeypatch.setattr(pinning, "_pins", OrderedDict())
    monkeypatch.setenv("DR_TPU_PIN_CAP", "1024")

    c = TappedCache()
    # eviction is amortized: the table may overshoot the cap by 25%
    # before a batch eviction fires, so cross the margin, not the cap
    keep = [object() for _ in range(1024 + 256 + 1)]
    pid0 = pinning.pinned_id(keep[0])
    c[("prog", pid0, 7)] = "compiled"
    c[("prog", "no-pin", 8)] = "other"
    assert c.get(("prog", pid0, 7)) == "compiled"
    for o in keep[1:]:
        pinning.pinned_id(o)
    # keep[0]'s pin was the oldest -> evicted -> its entry purged;
    # unrelated entries survive
    assert ("prog", pid0, 7) not in c
    assert c.get(("prog", "no-pin", 8)) == "other"
    # re-pinning the SAME object compiles fresh (no stale alias)
    pid0b = pinning.pinned_id(keep[0])
    assert int(pid0b) == int(pid0)
    assert c.get(("prog", pid0b, 7)) is None
