"""SPMD dispatch-order guard (utils/spmd_guard.py): recording through
the shared program cache, canonicalization of process-local ids, and
digest agreement for identical dispatch sequences.  The cross-process
verify() path runs in tests/multihost_worker.py."""

import numpy as np

import dr_tpu
from dr_tpu.utils import spmd_guard


def _workload(n):
    a = dr_tpu.distributed_vector(n)
    b = dr_tpu.distributed_vector(n)
    dr_tpu.iota(a, 0)
    dr_tpu.fill(b, 2.0)
    dr_tpu.dot(a, b)
    out = dr_tpu.distributed_vector(n)
    dr_tpu.inclusive_scan(a, out)


def test_guard_records_dispatches():
    with spmd_guard.guard() as g:
        _workload(256)
    assert len(g.trace) >= 4  # iota, fill, dot, scan at minimum
    # verify() is a no-op single-process but must not raise
    g.verify()


def test_identical_sequences_share_digest():
    with spmd_guard.guard() as g1:
        _workload(256)
    with spmd_guard.guard() as g2:
        _workload(256)
    assert g1.digest() == g2.digest()
    with spmd_guard.guard() as g3:
        _workload(512)  # different layout -> different trace
    assert g1.digest() != g3.digest()


def test_canonicalization_hides_object_ids():
    # pinned ids are object identities (typed PinnedId): legitimately
    # different across processes, so they canonicalize to a placeholder
    from dr_tpu.core.pinning import pinned_id
    key1 = ("dot", pinned_id(object()), (8, 32, 0, 0, 256))
    key2 = ("dot", pinned_id(object()), (8, 32, 0, 0, 256))
    assert spmd_guard._canon(key1) == spmd_guard._canon(key2)
    assert "ptr" in spmd_guard._canon(key1)
    # structural ints — however large — must survive verbatim: a
    # billion-element n differing across processes IS a divergence
    big1 = ("scan", (8, 1 << 33, 0, 0, (1 << 36) + 8))
    big2 = ("scan", (8, 1 << 33, 0, 0, (1 << 36) + 16))
    assert spmd_guard._canon(big1) != spmd_guard._canon(big2)


def test_divergence_detection_logic():
    # exercise the comparison logic directly (two processes can't run
    # inside one pytest process; the live path runs in the multihost
    # worker)
    g = spmd_guard.SpmdGuard()
    g.record(("fill", 1))
    g.record(("dot", 2))
    h = spmd_guard.SpmdGuard()
    h.record(("fill", 1))
    h.record(("scan", 2))
    assert g.digest() != h.digest()
    assert g.trace[0] == h.trace[0] and g.trace[1] != h.trace[1]


def test_guard_nesting_restores():
    assert spmd_guard.active() is None
    with spmd_guard.guard() as outer:
        with spmd_guard.guard() as inner:
            dr_tpu.fill(dr_tpu.distributed_vector(64), 1.0)
            assert spmd_guard.active() is inner
        assert spmd_guard.active() is outer
    assert spmd_guard.active() is None
