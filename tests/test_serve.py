"""Serving daemon (dr_tpu/serve): lifecycle edges, admission control,
batching, and the classified failure matrix (docs/SPEC.md §14).

Everything runs on the 8-device virtual CPU mesh.  In-process servers
bind sockets under tmp_path (Unix-domain paths cap near 107 bytes —
pytest tmp dirs stay short enough); the subprocess tests drive the
``python -m dr_tpu.serve`` entry the fuzz-crank serve arm cranks.
"""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import dr_tpu
from dr_tpu import serve
from dr_tpu.serve import protocol
from dr_tpu.utils import faults, resilience
from dr_tpu.utils.env import env_int

X = np.arange(48, dtype=np.float32)


@pytest.fixture
def server(tmp_path):
    srv = serve.Server(str(tmp_path / "d.sock"))
    srv.start()
    yield srv
    srv.stop()


def _client(srv, **kw):
    kw.setdefault("timeout", 60.0)
    return serve.Client(srv.path, **kw)


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

def test_protocol_roundtrip():
    a, b = socket.socketpair()
    try:
        arrays = [np.arange(5, dtype=np.float32),
                  np.ones((2, 3), np.int32)]
        protocol.send_frame(a, {"op": "x", "params": {"k": 1}}, arrays)
        hdr, got = protocol.recv_frame(b)
        assert hdr["op"] == "x" and hdr["params"] == {"k": 1}
        for want, have in zip(arrays, got):
            np.testing.assert_array_equal(want, have)
            assert want.dtype == have.dtype
        # clean EOF between frames is a normal disconnect
        a.close()
        assert protocol.recv_frame(b) == (None, None)
    finally:
        b.close()


def test_protocol_torn_frame_classified():
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x00\x00\x00\x10partial")  # 16-byte header, 7 sent
        a.close()
        with pytest.raises(resilience.TransientBackendError,
                           match="torn"):
            protocol.recv_frame(b)
    finally:
        b.close()


def test_protocol_malformed_and_oversized_classified():
    a, b = socket.socketpair()
    try:
        a.sendall(b"\xff\xff\xff\xff")  # absurd header length
        with pytest.raises(resilience.ProgramError):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        bad = b"not json!"
        import struct
        a.sendall(struct.pack(">I", len(bad)) + bad)
        with pytest.raises(resilience.ProgramError, match="malformed"):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_protocol_error_header_roundtrip():
    hdr = protocol.error_header(
        resilience.ServerOverloaded("queue full", site="serve.request"))
    assert hdr["ok"] is False
    with pytest.raises(resilience.ServerOverloaded, match="queue full"):
        protocol.raise_error(hdr)
    # unknown class name degrades to the deterministic bucket
    with pytest.raises(resilience.ProgramError):
        protocol.raise_error({"error": {"cls": "NoSuchClass",
                                        "message": "m"}})


# ---------------------------------------------------------------------------
# request/reply correctness
# ---------------------------------------------------------------------------

def test_serve_ops_roundtrip(server):
    rng = np.random.default_rng(0)
    x = rng.standard_normal(33).astype(np.float32)
    y = rng.standard_normal(33).astype(np.float32)
    with _client(server) as c:
        assert c.ping()["pong"] is True
        np.testing.assert_allclose(c.scale(x, a=2.0, b=-1.0),
                                   x * 2.0 - 1.0, rtol=1e-6)
        assert abs(c.reduce(x) - x.astype(np.float64).sum()) < 1e-3
        assert abs(c.dot(x, y) - (x.astype(np.float64)
                                  * y).sum()) < 1e-2
        np.testing.assert_allclose(c.scan(x),
                                   np.cumsum(x, dtype=np.float32),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_array_equal(c.sort(x), np.sort(x))
        np.testing.assert_allclose(c.fill(16, 2.5),
                                   np.full(16, 2.5, np.float32))
        st = c.stats()
        assert st["requests"] >= 6 and st["errors"] == 0


def test_serve_request_errors_classified_daemon_survives(server):
    with _client(server) as c:
        with pytest.raises(resilience.ProgramError, match="unknown op"):
            c.request("no_such_op")
        with pytest.raises(resilience.ProgramError, match="array"):
            c.request("reduce")  # missing operand
        with pytest.raises(resilience.ProgramError, match="params.n"):
            c.fill(0)
        with pytest.raises(resilience.ProgramError, match="share a"):
            c.dot(X, X[:5])
        # the SAME connection keeps working after every rejection
        assert abs(c.reduce(X) - X.sum()) < 1e-3
    assert server.stats()["errors"] == 4


# ---------------------------------------------------------------------------
# batching
# ---------------------------------------------------------------------------

def test_serve_batching_coalesces_one_flush(server):
    with _client(server) as c:
        c.scale(X, a=1.0)  # compile the fused program
    f0 = server.stats()["flushes"]
    server.hold()
    results = {}

    def worker(i):
        with _client(server) as c:
            results[i] = c.scale(X, a=float(i + 1))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10.0
    while len(server._queue) < 4 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(server._queue) == 4, "requests did not queue under hold"
    server.release()
    for t in threads:
        t.join()
    for i in range(4):
        np.testing.assert_allclose(results[i], X * (i + 1), rtol=1e-6)
    st = server.stats()
    # all four concurrent requests coalesced into ONE fused-plan flush
    assert st["flushes"] == f0 + 1
    assert st["batch_hw"] == 4


def test_serve_nonfusible_runs_solo_in_batch(server):
    """sort is non-fusible: batched alongside fusible ops it executes
    alone (after the fused group), and every result stays correct."""
    server.hold()
    rng = np.random.default_rng(3)
    src = rng.standard_normal(40).astype(np.float32)
    results = {}

    def w_sort():
        with _client(server) as c:
            results["sort"] = c.sort(src)

    def w_scale():
        with _client(server) as c:
            results["scale"] = c.scale(src, a=3.0)

    threads = [threading.Thread(target=w) for w in (w_sort, w_scale)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10.0
    while len(server._queue) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    server.release()
    for t in threads:
        t.join()
    np.testing.assert_array_equal(results["sort"], np.sort(src))
    np.testing.assert_allclose(results["scale"], src * 3.0, rtol=1e-6)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_serve_overload_rejected_classified(tmp_path):
    srv = serve.Server(str(tmp_path / "o.sock"), queue_depth=2,
                       tenant_cap=8).start()
    try:
        srv.hold()
        errs, oks = [], []

        def worker(i):
            try:
                with serve.Client(srv.path, timeout=30.0,
                                  tenant=f"t{i}") as c:
                    oks.append(c.reduce(X))
            except resilience.ResilienceError as e:
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10.0
        while len(errs) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        srv.release()
        for t in threads:
            t.join()
        # two requests queue, two are REJECTED (classified, immediate)
        assert len(errs) == 2 and len(oks) == 2
        assert all(isinstance(e, resilience.ServerOverloaded)
                   for e in errs), errs
        assert srv.stats()["rejected"] == 2
    finally:
        srv.stop()


def test_serve_tenant_cap_isolates_tenants(tmp_path):
    srv = serve.Server(str(tmp_path / "t.sock"), queue_depth=16,
                       tenant_cap=1).start()
    try:
        srv.hold()
        errs, oks = [], []

        def worker(i, tenant):
            try:
                with serve.Client(srv.path, timeout=30.0,
                                  tenant=tenant) as c:
                    oks.append(c.reduce(X))
            except resilience.ServerOverloaded as e:
                errs.append(e)

        # tenant "hog" submits twice (cap 1): exactly one is rejected;
        # tenant "other" stays admitted regardless
        threads = [threading.Thread(target=worker, args=(i, t))
                   for i, t in ((0, "hog"), (1, "hog"), (2, "other"))]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10.0
        while len(errs) + len(server_queued(srv)) < 3 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        srv.release()
        for t in threads:
            t.join()
        assert len(errs) == 1 and "hog" in str(errs[0])
        assert len(oks) == 2
    finally:
        srv.stop()


def server_queued(srv):
    return range(len(srv._queue))


def test_serve_deadline_expired_requests_shed(server):
    server.hold()
    box = {}

    def worker():
        try:
            with _client(server, timeout=30.0) as c:
                box["r"] = c.reduce(X, deadline_s=0.05)
        except resilience.ResilienceError as e:
            box["e"] = e

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.4)  # let the deadline lapse while queued
    server.release()
    t.join()
    # shed BEFORE dispatch: classified DeadlineExpired, not a result
    assert isinstance(box.get("e"), resilience.DeadlineExpired), box
    assert "shed" in str(box["e"])
    assert server.stats()["shed"] == 1


# ---------------------------------------------------------------------------
# daemon lifecycle edges
# ---------------------------------------------------------------------------

def test_serve_stale_socket_takeover(tmp_path):
    path = str(tmp_path / "stale.sock")
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.bind(path)  # a daemon died here without unlinking
    s.close()
    assert os.path.exists(path)
    srv = serve.Server(path).start()
    try:
        with serve.Client(path, timeout=30.0) as c:
            assert c.ping()["pong"] is True
    finally:
        srv.stop()


def test_serve_double_daemon_refused_classified(server):
    newcomer = serve.Server(server.path)
    with pytest.raises(resilience.ProgramError,
                       match="already serving"):
        newcomer.start()
    # the bench/tests try/finally shape stops the refused newcomer —
    # that stop must NOT unlink the LIVE incumbent's socket (review
    # fix: only the daemon that bound the socket may delete it)
    newcomer.stop()
    assert os.path.exists(server.path)
    with _client(server) as c:
        assert abs(c.reduce(X) - X.sum()) < 1e-3


def test_serve_client_crash_mid_request_cancels_cleanly(server):
    server.hold()
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.connect(server.path)
    protocol.send_frame(raw, {"op": "reduce", "params": {},
                              "tenant": "crash"}, [X])
    deadline = time.monotonic() + 10.0
    while len(server._queue) < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    raw.close()  # crash before the reply
    time.sleep(0.1)
    server.release()
    # the daemon sheds the dead client's work and keeps serving —
    # the resident claim is not poisoned
    with _client(server) as c:
        assert abs(c.reduce(X) - X.sum()) < 1e-3
    assert server.stats()["cancelled"] == 1


def test_serve_truncated_frame_drops_connection_only(server):
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.connect(server.path)
    raw.sendall(b"\x00\x00\x01\x00only-a-few-bytes")
    raw.close()
    time.sleep(0.2)
    with _client(server) as c:  # the daemon survived the torn frame
        assert c.ping()["pong"] is True


# ---------------------------------------------------------------------------
# failure injection (the serve.* sites)
# ---------------------------------------------------------------------------

def test_serve_flush_transient_recovers_in_process(server):
    with faults.injected("serve.flush", "transient") as sp:
        with _client(server) as c:
            assert abs(c.reduce(X) - X.sum()) < 1e-3
        assert sp.fired == 1  # retried in process, request succeeded


def test_serve_flush_program_fault_isolated_per_request(server):
    """A deterministic batch failure re-executes each request alone
    (poison-pill isolation): with the fault exhausted by the batch
    attempt, BOTH clients still get their results."""
    with _client(server) as c:
        c.scale(X, a=1.0)
    server.hold()
    results, errs = {}, []

    def worker(i):
        try:
            with _client(server) as c:
                results[i] = c.scale(X, a=float(i + 2))
        except resilience.ResilienceError as e:
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10.0
    while len(server._queue) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    with faults.injected("serve.flush", "program") as sp:
        server.release()
        for t in threads:
            t.join()
        assert sp.fired == 1
    assert not errs, errs
    for i in range(2):
        np.testing.assert_allclose(results[i], X * (i + 2), rtol=1e-6)


def test_serve_relay_death_degrades_to_cpu_route(server):
    """relay_down at the flush boundary: the watchdog re-routes the
    resident claim through route_first_touch onto the CPU mesh, the
    batch replays there, and the request still SUCCEEDS — with the
    serve chapter published into the degradation story."""
    with faults.injected("serve.flush", "relay_down") as sp:
        with _client(server) as c:
            assert abs(c.reduce(X) - X.sum()) < 1e-3
        assert sp.fired == 1
    st = server.stats()
    assert st["restarts"] == 1
    assert "CPU route" in st["degraded"]
    story = resilience.degradation_story()
    assert story is not None and story["serve"]["restarts"] == 1
    # conftest's autouse fixture resets this between tests; reset()
    # here proves the hook clears the markers
    serve.reset()
    assert resilience.degradation_story() is None


def test_serve_accept_fault_drops_connection_keeps_serving(server):
    with faults.injected("serve.accept", "transient") as sp:
        with pytest.raises(resilience.ResilienceError):
            with _client(server) as c:
                c.ping()
        assert sp.fired == 1
    assert server.stats()["accept_drops"] == 1
    with _client(server) as c:  # the NEXT connection serves normally
        assert c.ping()["pong"] is True


def test_serve_request_fault_serialized_back(server):
    with _client(server) as c:
        with faults.injected("serve.request", "oom") as sp:
            with pytest.raises(resilience.DeviceOOM):
                c.reduce(X)
            assert sp.fired == 1
        # the classified reply did not kill the daemon OR the conn
        assert abs(c.reduce(X) - X.sum()) < 1e-3


# ---------------------------------------------------------------------------
# concurrency with the host thread's own plans
# ---------------------------------------------------------------------------

def _tl_scale(x, c):
    return x * c


def test_serve_plans_are_thread_local(server):
    """The daemon records batched requests into deferred plans on ITS
    dispatch thread; a region OPEN on the host thread must neither
    absorb the daemon's ops nor leak its own into the daemon's flush."""
    src = np.arange(64, dtype=np.float32)
    v = dr_tpu.distributed_vector.from_array(src)
    box = {}

    def worker():
        with _client(server) as c:
            box["r"] = c.scale(X, a=5.0)

    with dr_tpu.deferred() as p:
        dr_tpu.fill(v, 2.0)
        t = threading.Thread(target=worker)
        t.start()
        t.join()  # the daemon flushed ITS plan while ours is open
        dr_tpu.for_each(v, _tl_scale, 3.0)
        tot = dr_tpu.reduce(v)
    assert float(tot) == pytest.approx(64 * 6.0)
    np.testing.assert_allclose(box["r"], X * 5.0, rtol=1e-6)
    # the host plan held exactly its own three ops, in one fused run
    st = p.stats()
    assert st["fused_ops"] == 3 and st["fused_runs"] == 1


# ---------------------------------------------------------------------------
# subprocess daemon (the fuzz-crank serve arm cranks these)
# ---------------------------------------------------------------------------

def _spawn_daemon(path, fault_spec=None, timeout=120.0):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # frozen by sitecustomize: use --cpu
    if fault_spec is not None:
        env["DR_TPU_FAULT_SPEC"] = fault_spec
    else:
        env.pop("DR_TPU_FAULT_SPEC", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dr_tpu.serve", "--socket", path, "--cpu"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    import json
    line = proc.stdout.readline()
    try:
        ready = json.loads(line) if line.strip() else {}
    except ValueError:
        ready = {}
    if ready.get("serving") != path:
        proc.kill()
        proc.wait(timeout=30)
        raise AssertionError(f"daemon failed to start: {line!r}")
    return proc


def test_serve_subprocess_lifecycle(tmp_path):
    path = str(tmp_path / "sub.sock")
    proc = _spawn_daemon(path)
    try:
        with serve.Client(path, timeout=120.0) as c:
            np.testing.assert_allclose(c.scale(X, a=2.0), X * 2.0,
                                       rtol=1e-6)
            c.shutdown()
        assert proc.wait(timeout=60) == 0
        assert not os.path.exists(path), "socket not cleaned up"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def _serve_chaos_combos():
    """Tier-1 runs the two richest subprocess combos (a daemon start
    costs a jax import); the fuzz-crank serve arm (DR_TPU_CHAOS_ROUNDS
    > 1) sweeps every serve site x kind against a live daemon."""
    if env_int("DR_TPU_CHAOS_ROUNDS", 1, floor=0) > 1:
        return [(s, k) for s, kinds in sorted(faults.sites().items())
                if s.startswith("serve.") for k in kinds]
    return [("serve.flush", "relay_down"), ("serve.request", "program")]


@pytest.mark.slow  # each combo pays a daemon-subprocess jax import;
# tier-1 (-m 'not slow') keeps the IN-process serve.* sweep
# (test_chaos) and the subprocess lifecycle test above — the
# fuzz-crank serve arm runs this sweep unfiltered
@pytest.mark.parametrize("site,kind", _serve_chaos_combos())
def test_serve_subprocess_chaos(tmp_path, site, kind):
    """Chaos against a LIVE daemon subprocess: with `site:kind` armed
    in the daemon's environment, every client request must end in a
    classified error or a correct result — the daemon never dies
    uncleanly and never hangs the client past its timeout."""
    path = str(tmp_path / "chaos.sock")
    proc = _spawn_daemon(path, fault_spec=f"{site}:{kind}")
    try:
        outcomes = []
        for attempt in range(3):
            try:
                with serve.Client(path, timeout=120.0) as c:
                    got = c.scale(X, a=2.0)
                    np.testing.assert_allclose(got, X * 2.0, rtol=1e-6)
                    outcomes.append("ok")
            except resilience.ResilienceError as e:
                outcomes.append(type(e).__name__)
            # (any OTHER exception propagates = unclassified = failure)
        # the injection fires once; afterwards the daemon must serve
        assert outcomes[-1] == "ok", outcomes
        with serve.Client(path, timeout=120.0) as c:
            if site == "serve.flush" and kind == "relay_down":
                st = c.stats()
                assert st["restarts"] == 1, st
            c.shutdown()
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


# ---------------------------------------------------------------------------
# client retry policy (round 13, SPEC §14.6)
# ---------------------------------------------------------------------------

def test_client_retry_recovers_transient_intake_fault(server):
    """retries>1: a transient at request intake resubmits through the
    seeded-backoff resilience.retry (reconnecting first — the failed
    exchange invalidated the connection) and the request lands."""
    with _client(server, retries=3) as c:
        faults.inject("serve.request", "transient", times=1)
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(c.scale(x, a=2.0), x * 2.0,
                                   rtol=1e-6)


def test_client_retry_recovers_overload(tmp_path):
    """retries>1: a ServerOverloaded rejection backs off and
    resubmits; once the dispatcher drains the queue the retry lands —
    the client-side remainder of ROADMAP item 1."""
    srv = serve.Server(str(tmp_path / "r.sock"), queue_depth=1,
                       batch_window=0.0).start()
    try:
        srv.hold()
        filler_err = []

        def filler():
            try:
                with serve.Client(srv.path, timeout=30.0,
                                  tenant="filler") as c0:
                    c0.reduce(X)
            except resilience.ResilienceError as e:  # pragma: no cover
                filler_err.append(e)

        t = threading.Thread(target=filler)
        t.start()
        deadline = time.monotonic() + 10.0
        while len(srv._queue) < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        threading.Timer(0.3, srv.release).start()
        # the queue is full: a single-attempt client is rejected, a
        # retrying one outlasts the hold
        with serve.Client(srv.path, timeout=30.0, retries=1) as c1:
            with pytest.raises(resilience.ServerOverloaded):
                c1.reduce(X)
        with serve.Client(srv.path, timeout=30.0, retries=5) as c2:
            assert abs(c2.reduce(np.ones(8, np.float32)) - 8.0) < 1e-4
        t.join(timeout=10)
        assert not filler_err
        assert srv.stats()["rejected"] >= 1
    finally:
        srv.stop()


def test_client_retry_deadline_aware():
    """A retry whose backoff delay would land past the request's
    deadline_s is NOT taken — the classified error surfaces instead of
    a resubmission nobody is waiting on."""
    calls = []

    def always_overloaded():
        calls.append(1)
        raise resilience.ServerOverloaded("full", site="serve.request")

    with pytest.raises(resilience.ServerOverloaded):
        resilience.retry(always_overloaded, attempts=5, base=10.0,
                         retry_on=(resilience.ServerOverloaded,),
                         deadline_s=0.5)
    assert len(calls) == 1  # the 10 s backoff would blow the budget


def test_client_default_single_attempt_unchanged(server):
    """The default stays ONE attempt: an intake fault surfaces
    classified immediately (overload rejections are information)."""
    with _client(server) as c:
        faults.inject("serve.request", "transient", times=1)
        with pytest.raises(resilience.TransientBackendError):
            c.scale(np.arange(4, dtype=np.float32))
