"""R4 clean twin: the branch condition is mesh-static (shape/rank
arithmetic), identical on every rank."""
from jax import lax


def exchange(nshards, blk):
    if nshards > 1:                              # mesh-static
        blk = lax.ppermute(blk, "i", [(0, 1)])
    return blk
