"""R2 clean twin: a documented var through the registry helpers,
None-vs-set through env_raw."""
from dr_tpu.utils.env import env_raw, env_str


def knob():
    return env_str("DR_TPU_LOG")


def pinned():
    return env_raw("DR_TPU_SANITIZE") is not None
