"""R9 bad twin: plan-item record sites without derived footprints —
a _FusedOp with no declaration at all, one whose slots are NOT derived
from the run's operands, and a record_opaque missing its writes."""
# drlint: scope=package — R9 only applies inside dr_tpu/; judge this
# fixture as package code under a direct CLI scan too


def record_fill(run, cont, value):
    slot = run.slot(cont)
    run.ops.append(_FusedOp("fill", ("fill",), None, ("t",), (value,)))


def record_axpy(run, cont, alpha):
    idx = alpha + 1    # an operand value, not a slot
    run.ops.append(_FusedOp("axpy", ("axpy",), None, reads=(idx,),
                            writes=((idx, 0, 4, False),)))


def record_scan(plan, cont):
    plan.record_opaque("scan", lambda: None, reads=(cont,))
