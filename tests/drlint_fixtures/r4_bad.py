"""R4 bad twin: a collective under a data-dependent branch — ranks can
diverge in dispatch order (the class spmd_guard only names at runtime,
after the hang)."""
from jax import lax


def exchange(x, blk):
    if x.sum() > 0:                              # reads runtime DATA
        blk = lax.ppermute(blk, "i", [(0, 1)])   # divergent dispatch
    return blk
