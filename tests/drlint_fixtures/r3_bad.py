"""R3 bad twin: a fault site name outside faults.SITES — a chaos sweep
can never reach it."""
from dr_tpu.utils import faults


def risky():
    faults.fire("fixture.unregistered.site")
