"""R6 clean twin: the cache is a TappedCache, programs compiled once."""
import jax

from dr_tpu.utils.spmd_guard import TappedCache

_prog_cache = TappedCache()


def run(f, x):
    prog = _prog_cache.get(("run",))
    if prog is None:
        prog = jax.jit(f)
        _prog_cache[("run",)] = prog
    return prog(x)
