"""R1 bad twin: a runtime scalar value-keyed into a program cache and
closed over by the jitted body — the recompile-storm shape."""
import jax

_prog_cache = {}


def build(x):
    v = x[0]
    scale = v.item()            # runtime scalar pulled to host
    key = ("prog", scale)       # value-keyed: every new value recompiles
    prog = _prog_cache.get(key)
    if prog is None:
        def body(a):
            return a * scale    # and baked into the compiled body
        prog = jax.jit(body)
        _prog_cache[key] = prog
    return prog
