"""R9 clean twin: every footprint slot chases to a run.slot(…) result
(through tuple-unpacking and conditionals), opaque footprints name
their containers, and the barrier opt-in is EXPLICIT."""
# drlint: scope=package — same scope as the bad twin, so cleanliness
# is proven under the package-scoped rules


def record_fill(run, cont, value, n):
    slot = run.slot(cont)
    run.ops.append(_FusedOp("fill", ("fill",), None, ("t",), (value,),
                            writes=((slot, 0, n, False),), pure=True))


def record_dot(run, a, b, maybe):
    sa, sb = run.slot(a), run.slot(b)
    sm = run.slot(maybe) if maybe is not None else None
    run.ops.append(_FusedOp("dot", ("dot",), None,
                            reads=(sa, sb) + ((sm,) if sm is not None
                                              else ())))


def record_foreach(run, outs):
    out_slots = tuple(run.slot(c) for c in outs)
    run.ops.append(_FusedOp(
        "foreach", ("foreach",), None, reads=out_slots,
        writes=tuple((s, 0, 4, False) for s in out_slots)))


def record_scan(plan, in_cont, out):
    plan.record_opaque("scan", lambda: None, reads=(in_cont, out),
                       writes=((out, False),))


def record_mystery(plan, thunk):
    # the documented barrier opt-in: UNKNOWN footprints, declared so
    plan.record_opaque("mystery", thunk, reads=None, writes=None)
