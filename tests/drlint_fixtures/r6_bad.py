"""R6 bad twin (scanned with a faked dr_tpu/ relpath): a plain-dict
program cache plus an immediately-invoked jit — compiles off the
spmd_guard tap, one of them per call."""
import jax

_prog_cache = {}


def run(f, x):
    prog = _prog_cache.get(("run",))
    if prog is None:
        prog = jax.jit(f)
        _prog_cache[("run",)] = prog
    return jax.jit(f)(x)
