"""R3 clean twin: a registered site."""
from dr_tpu.utils import faults


def risky():
    faults.fire("halo.exchange")
