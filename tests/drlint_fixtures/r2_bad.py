"""R2 bad twin: raw environment read of an undocumented DR_TPU var,
plus the raw membership-test shape (a read too)."""
import os


def knob():
    return os.environ.get("DR_TPU_FIXTURE_ONLY_KNOB", "1")


def pinned():
    return "DR_TPU_SANITIZE" in os.environ
