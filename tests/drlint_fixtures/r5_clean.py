"""R5 clean twin: the degradation announces through the chaos-countable
registry."""
# drlint: scope=package — same scope as the bad twin, so cleanliness
# is proven under the package-scoped rules
from dr_tpu.utils.fallback import warn_fallback


def degrade(run):
    try:
        return run()
    except ValueError as e:
        warn_fallback("fixture", f"slow path: {e}")
    return None
