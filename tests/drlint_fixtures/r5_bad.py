"""R5 bad twin: a silent degradation — bare warnings.warn plus a broad
swallowed except."""
# drlint: scope=package — R5 only applies inside dr_tpu/; judge this
# fixture as package code under a direct CLI scan too
import warnings


def degrade(run):
    try:
        return run()
    except Exception:
        pass
    warnings.warn("falling back to the slow path")
    return None
