"""R0 twin: a suppression with NO reason is itself a finding, and does
not waive the underlying one."""
import os


def knob():
    return os.environ.get("DR_TPU_FIXTURE_ONLY_KNOB")  # drlint: ok[R2]
