"""R1 clean twin: the scalar rides as a traced operand — one program,
any value."""
import jax

_prog_cache = {}


def build(x):
    v = x[0]
    key = ("prog", "scaled")    # structural key only
    prog = _prog_cache.get(key)
    if prog is None:
        def body(a, s):
            return a * s        # scalar is a parameter, not a constant
        prog = jax.jit(body)
        _prog_cache[key] = prog
    return prog, v
