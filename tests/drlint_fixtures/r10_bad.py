"""R10 bad twin: a would-be optimizer pass hand-rolls its own aliasing
logic by reading item footprints directly."""
# drlint: scope=dr_tpu/plan/r10_fixture.py — judge this fixture under
# the dr_tpu/plan/ serialization-dependency discipline


def pass_swap(q):
    a, b = q
    if not (set(a.writes) & set(b.reads)):
        return [b, a]
    return q
