"""R10 clean twin: the same reorder decision routed through the one
interference-graph helper."""
# drlint: scope=dr_tpu/plan/r10_fixture.py — same effective relpath as
# the bad twin, so cleanliness is proven under the same discipline

from . import interference as _interf


def pass_swap(q):
    a, b = q
    ta, tb = _interf.item_touch(a), _interf.item_touch(b)
    if ta is not None and tb is not None and not (ta & tb):
        return [b, a]
    return q
