"""Suppression twin: the R2 finding is waived WITH a reason — the
same-line, line-above, and STACKED line-above forms."""
import jax
import os


def knob():
    # drlint: ok[R2] fixture exercising the line-above suppression form
    a = os.environ.get("DR_TPU_FIXTURE_ONLY_KNOB")
    b = os.environ.get("DR_TPU_FIXTURE_ONLY_KNOB")  # drlint: ok[R2] same-line form
    return a, b


def stacked():
    # drlint: ok[R2] stacked waivers: the raw read is deliberate here
    # drlint: ok[R6] stacked waivers: compile-per-call is deliberate too
    return jax.jit(lambda: 0)(), os.environ["DR_TPU_FIXTURE_ONLY_KNOB"]
