"""Halo (ghost-cell) exchange tests (reference test/gtest/mhp/stencil.cpp,
halo semantics from include/dr/details/halo.hpp)."""

import numpy as np
import pytest

import dr_tpu


def _shard_rows(dv):
    """Raw (nshards, width) host copy of the padded shard rows."""
    return np.asarray(dv._data)


def test_exchange_fills_ghosts(mesh_size):
    if mesh_size == 1:
        pytest.skip("no neighbors at 1 rank")
    n = mesh_size * 4
    hb = dr_tpu.halo_bounds(1, 1)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    dr_tpu.halo(dv).exchange()
    rows = _shard_rows(dv)
    seg = dv.segment_size
    for r in range(1, dv.nshards):
        assert rows[r, 0] == r * seg - 1, "ghost_prev wrong"
    for r in range(dv.nshards - 1):
        assert rows[r, 1 + seg] == (r + 1) * seg, "ghost_next wrong"


def test_exchange_nonperiodic_edges_untouched():
    n = 32
    hb = dr_tpu.halo_bounds(1, 1)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    dr_tpu.halo(dv).exchange()
    rows = _shard_rows(dv)
    # first rank's ghost_prev and last rank's ghost_next keep initial zeros
    assert rows[0, 0] == 0.0
    assert rows[-1, -1] == 0.0


def test_exchange_periodic_wraparound():
    n = 32  # divisible: every shard full
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    dr_tpu.halo(dv).exchange()
    rows = _shard_rows(dv)
    seg = dv.segment_size
    assert rows[0, 0] == n - 1, "ring ghost_prev of rank 0"
    assert rows[-1, 1 + seg] == 0.0 or rows[-1, 1 + dv.segment_size] == 0.0


def test_exchange_periodic_short_last_shard():
    """Regression: periodic wrap must ship the logical last element, not
    the last shard's padding."""
    n = 29  # 8 shards * seg 4 = 32 > 29: last shard holds 1 element
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    dr_tpu.halo(dv).exchange()
    rows = _shard_rows(dv)
    assert rows[0, 0] == n - 1, \
        f"rank 0 ghost_prev must be element {n-1}, got {rows[0, 0]}"


def test_halo_reduce_plus(mesh_size):
    if mesh_size == 1:
        pytest.skip("no neighbors at 1 rank")
    n = mesh_size * 4
    hb = dr_tpu.halo_bounds(1, 1)
    dv = dr_tpu.distributed_vector.from_array(
        np.ones(n, dtype=np.float32), halo=hb)
    dr_tpu.halo(dv).exchange()
    dr_tpu.halo(dv).reduce_plus()
    arr = dr_tpu.to_numpy(dv)
    seg = dv.segment_size
    ref = np.ones(n, dtype=np.float32)
    for r in range(dv.nshards):
        lo, hi = r * seg, min((r + 1) * seg, n)
        if r > 0:
            ref[lo] += 1  # folded from my ghost... owner got neighbor ghost
        if r < dv.nshards - 1:
            ref[hi - 1] += 1
    np.testing.assert_array_equal(arr, ref)


def test_halo_reduce_ops():
    n = 32
    hb = dr_tpu.halo_bounds(1, 1)
    dv = dr_tpu.distributed_vector.from_array(
        np.full(n, 2.0, dtype=np.float32), halo=hb)
    dr_tpu.halo(dv).exchange()
    dr_tpu.halo(dv).reduce_multiplies()
    arr = dr_tpu.to_numpy(dv)
    seg = dv.segment_size
    # boundary owned cells got *=2 from each neighbor ghost
    assert arr[seg - 1] == 8.0 or arr[seg - 1] == 4.0  # interior boundary
    assert arr[0] == 2.0  # global edge untouched


def test_halo_second_op_overwrites():
    n = 32
    hb = dr_tpu.halo_bounds(1, 1)
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    dr_tpu.halo(dv).exchange()
    dr_tpu.halo(dv).reduce(dr_tpu.halo_ops.second)
    # 'second' overwrites the owner with the ghost copy: after a plain
    # exchange the ghost equals the owner's value, so nothing changes
    np.testing.assert_array_equal(dr_tpu.to_numpy(dv),
                                  np.arange(n, dtype=np.float32))


def test_halo_too_small_raises():
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("min-size rules need at least two shards")
    with pytest.raises(ValueError):
        # halo grows seg to 2 -> trailing shards own nothing
        dr_tpu.distributed_vector(P - 1, halo=dr_tpu.halo_bounds(2, 2))
    with pytest.raises(ValueError):
        # periodic ring: last shard owns 1 element < radius 2
        dr_tpu.distributed_vector(
            2 * P - 1, halo=dr_tpu.halo_bounds(2, 2, periodic=True))


def test_halo_of_view():
    hb = dr_tpu.halo_bounds(1, 1)
    dv = dr_tpu.distributed_vector(32, halo=hb)
    v = dv[1:31]
    h = dr_tpu.halo(v)  # walks back to the container (mhp dv.hpp:240-248)
    assert h is dv.halo()


def test_exchange_begin_finalize():
    hb = dr_tpu.halo_bounds(1, 1)
    # every shard must own elements at ANY mesh size (16 over 5 shards
    # leaves the last shard empty under the ceil layout)
    n = 4 * dr_tpu.nprocs()
    dv = dr_tpu.distributed_vector.from_array(
        np.arange(n, dtype=np.float32), halo=hb)
    h = dr_tpu.halo(dv)
    h.exchange_begin()
    h.exchange_finalize()
    rows = _shard_rows(dv)
    assert rows[1, 0] == dv.segment_size - 1


def test_exchange_n_matches_repeated_exchange():
    import numpy as np
    n = 64
    src = np.arange(n, dtype=np.float32)
    hb = dr_tpu.halo_bounds(2, 2, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb)
    for _ in range(3):
        a.halo().exchange()
    b.halo().exchange_n(3)
    np.testing.assert_array_equal(np.asarray(a._data), np.asarray(b._data))


@pytest.mark.parametrize("shape", [
    # (n, prev, nxt, periodic): uniform, ragged tail, one-sided, both
    (64, 2, 2, True), (61, 2, 3, False), (30, 0, 2, False),
    (30, 2, 0, True), (29, 2, 2, False)])
def test_exchange_n_carry_modes_agree(monkeypatch, shape):
    """The ghost-carry fused loop (round-4 default: O(width) per round)
    and the row-carry variant must produce identical rows — exchange
    never writes owned cells, so carrying only the ghosts is exact."""
    import numpy as np
    n, prev, nxt, periodic = shape
    hb = dr_tpu.halo_bounds(prev, nxt, periodic=periodic)
    src = np.arange(n, dtype=np.float32) + 1
    outs = {}
    for carry in ("ghost", "row"):
        monkeypatch.setenv("DR_TPU_HALO_NCARRY", carry)
        v = dr_tpu.distributed_vector.from_array(src, halo=hb)
        v.halo().exchange_n(4)
        outs[carry] = np.asarray(v._data)
    np.testing.assert_array_equal(outs["ghost"], outs["row"])
