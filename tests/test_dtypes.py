"""Dtype robustness: the reference is dtype-generic (templates); the
containers and algorithm set must hold up beyond float32."""

import jax.numpy as jnp
import numpy as np

import dr_tpu


def test_int32_iota_reduce_scan():
    a = dr_tpu.distributed_vector(50, np.int32)
    dr_tpu.iota(a, 3)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a), np.arange(3, 53))
    assert dr_tpu.reduce(a) == np.arange(3, 53).sum()
    assert dr_tpu.reduce(a, op=max) == 52
    s = dr_tpu.distributed_vector(50, np.int32)
    dr_tpu.inclusive_scan(a, s)
    np.testing.assert_array_equal(dr_tpu.to_numpy(s),
                                  np.cumsum(np.arange(3, 53)))


def test_int32_blocked_scan_stays_exact():
    # large enough for the blocked path; ints must NOT take the float
    # matmul-cumsum formulation
    n = 40000
    a = dr_tpu.distributed_vector(n, np.int32)
    dr_tpu.fill(a, 1)
    s = dr_tpu.distributed_vector(n, np.int32)
    dr_tpu.inclusive_scan(a, s)
    np.testing.assert_array_equal(dr_tpu.to_numpy(s), np.arange(1, n + 1))


def test_bfloat16_fill_reduce_dot():
    a = dr_tpu.distributed_vector(64, jnp.bfloat16)
    b = dr_tpu.distributed_vector(64, jnp.bfloat16)
    dr_tpu.fill(a, 1.5)
    dr_tpu.fill(b, 2.0)
    assert abs(float(dr_tpu.reduce(a)) - 96.0) < 1.0
    assert abs(float(dr_tpu.dot(a, b)) - 192.0) < 2.0


def test_int32_stencil_callable_op():
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    src = np.arange(64, dtype=np.int32)
    v = dr_tpu.distributed_vector.from_array(src, halo=hb)
    w = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = dr_tpu.stencil_iterate(v, w, lambda l, c, r: l + c + r, steps=1)
    ref = np.roll(src, 1) + src + np.roll(src, -1)
    np.testing.assert_array_equal(dr_tpu.to_numpy(out), ref)


def test_round5_window_shapes_across_dtypes(monkeypatch):
    """The round-5 native shapes (windowed sort, mismatched-window
    scan, overlapping same-container sort_by_key) across i32 and
    bfloat16 — the key-encode and realign paths differ per dtype, and
    none may materialize."""
    def boom(self):
        raise AssertionError("dtype window shape materialized")

    n = 96
    # i32: integers are their own sort keys (pad sentinel = dtype max)
    isrc = np.random.default_rng(21).integers(-1000, 1000, n) \
        .astype(np.int32)
    iv = dr_tpu.distributed_vector(n, np.int32)
    iv.assign_array(isrc)
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort(iv[7:80])
    monkeypatch.undo()
    iref = isrc.copy()
    iref[7:80] = np.sort(isrc[7:80])
    np.testing.assert_array_equal(dr_tpu.to_numpy(iv), iref)
    # i32 mismatched-window scan stays exact
    iout = dr_tpu.distributed_vector(n, np.int32)
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.inclusive_scan(iv[0:40], iout[5:45])
    monkeypatch.undo()
    np.testing.assert_array_equal(dr_tpu.to_numpy(iout)[5:45],
                                  np.cumsum(iref[0:40]))
    # i32 overlapping same-container kv windows
    iw = dr_tpu.distributed_vector(n, np.int32)
    iw.assign_array(isrc)
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort_by_key(iw[0:30], iw[15:45])
    monkeypatch.undo()
    iwref = isrc.copy()
    order = np.argsort(isrc[0:30], kind="stable")
    iwref[0:30] = isrc[0:30][order]
    iwref[15:45] = isrc[15:45][order]
    np.testing.assert_array_equal(dr_tpu.to_numpy(iw), iwref)

    # bfloat16: keys upcast exactly through f32 before the sign-flip
    bsrc = np.random.default_rng(22).standard_normal(n).astype(
        jnp.bfloat16)
    bv = dr_tpu.distributed_vector(n, jnp.bfloat16)
    bv.assign_array(bsrc)
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    dr_tpu.sort(bv[3:90])
    monkeypatch.undo()
    bref = np.asarray(bsrc, dtype=np.float32).copy()
    bref[3:90] = np.sort(bref[3:90])
    np.testing.assert_array_equal(
        np.asarray(dr_tpu.to_numpy(bv), dtype=np.float32), bref)
    assert dr_tpu.is_sorted(bv[3:90])
