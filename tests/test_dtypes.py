"""Dtype robustness: the reference is dtype-generic (templates); the
containers and algorithm set must hold up beyond float32."""

import jax.numpy as jnp
import numpy as np

import dr_tpu


def test_int32_iota_reduce_scan():
    a = dr_tpu.distributed_vector(50, np.int32)
    dr_tpu.iota(a, 3)
    np.testing.assert_array_equal(dr_tpu.to_numpy(a), np.arange(3, 53))
    assert dr_tpu.reduce(a) == np.arange(3, 53).sum()
    assert dr_tpu.reduce(a, op=max) == 52
    s = dr_tpu.distributed_vector(50, np.int32)
    dr_tpu.inclusive_scan(a, s)
    np.testing.assert_array_equal(dr_tpu.to_numpy(s),
                                  np.cumsum(np.arange(3, 53)))


def test_int32_blocked_scan_stays_exact():
    # large enough for the blocked path; ints must NOT take the float
    # matmul-cumsum formulation
    n = 40000
    a = dr_tpu.distributed_vector(n, np.int32)
    dr_tpu.fill(a, 1)
    s = dr_tpu.distributed_vector(n, np.int32)
    dr_tpu.inclusive_scan(a, s)
    np.testing.assert_array_equal(dr_tpu.to_numpy(s), np.arange(1, n + 1))


def test_bfloat16_fill_reduce_dot():
    a = dr_tpu.distributed_vector(64, jnp.bfloat16)
    b = dr_tpu.distributed_vector(64, jnp.bfloat16)
    dr_tpu.fill(a, 1.5)
    dr_tpu.fill(b, 2.0)
    assert abs(float(dr_tpu.reduce(a)) - 96.0) < 1.0
    assert abs(float(dr_tpu.dot(a, b)) - 192.0) < 2.0


def test_int32_stencil_callable_op():
    hb = dr_tpu.halo_bounds(1, 1, periodic=True)
    src = np.arange(64, dtype=np.int32)
    v = dr_tpu.distributed_vector.from_array(src, halo=hb)
    w = dr_tpu.distributed_vector.from_array(src, halo=hb)
    out = dr_tpu.stencil_iterate(v, w, lambda l, c, r: l + c + r, steps=1)
    ref = np.roll(src, 1) + src + np.roll(src, -1)
    np.testing.assert_array_equal(dr_tpu.to_numpy(out), ref)
