"""inclusive/exclusive scan tests (reference test/gtest/shp/algorithms.cpp
:61-149, examples/shp/inclusive_scan_example.cpp)."""

import operator

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dr_tpu


def test_inclusive_scan_sum(mesh_size, oracle):
    n = 57
    src = np.random.default_rng(1).integers(0, 10, n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n)
    dr_tpu.inclusive_scan(a, out)
    oracle.equal(out, np.cumsum(src))


def test_inclusive_scan_mul():
    src = np.full(16, 1.1, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(16)
    dr_tpu.inclusive_scan(a, out, op=jnp.multiply)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), np.cumprod(src),
                               rtol=1e-5)


def test_inclusive_scan_max():
    src = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5], dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(len(src))
    dr_tpu.inclusive_scan(a, out, op=jnp.maximum)
    np.testing.assert_array_equal(dr_tpu.to_numpy(out),
                                  np.maximum.accumulate(src))


def test_inclusive_scan_init():
    src = np.arange(1, 9, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(8)
    dr_tpu.inclusive_scan(a, out, op=operator.add, init=100.0)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), np.cumsum(src) + 100)


def test_inclusive_scan_in_place():
    src = np.arange(20, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    dr_tpu.inclusive_scan(a, a)
    np.testing.assert_allclose(dr_tpu.to_numpy(a), np.cumsum(src))


def test_exclusive_scan():
    src = np.arange(1, 13, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(12)
    dr_tpu.exclusive_scan(a, out, init=0.0)
    ref = np.concatenate([[0], np.cumsum(src)[:-1]])
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref)


def test_exclusive_scan_mul_init():
    """Classified non-add op with non-zero init: position 0 must be
    exactly ``init``; later positions fold it into the shifted prefixes
    (std::exclusive_scan semantics)."""
    src = np.array([2.0, 3.0, 4.0, 5.0, 6.0, 7.0], dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(len(src))
    dr_tpu.exclusive_scan(a, out, init=10.0, op=jnp.multiply)
    ref = 10.0 * np.concatenate([[1.0], np.cumprod(src)[:-1]])
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-5)


def test_exclusive_scan_unclassified_op_init():
    """UNCLASSIFIED associative op (a user lambda the kind-classifier
    can't name): the scan program seeds position 0 with a pseudo-identity
    zero, so the init fold must overwrite it with ``init`` exactly —
    ``op(init, 0)`` would be 0 here.  Also covers init=0, which for an
    unclassified op still has to be applied."""
    src = np.array([2.0, 3.0, 4.0, 5.0], dtype=np.float32)

    def op(x, y):
        return x * y  # associative, but a lambda-like fn: kind is None

    for init in (10.0, 0.0):
        a = dr_tpu.distributed_vector.from_array(src)
        out = dr_tpu.distributed_vector(len(src))
        dr_tpu.exclusive_scan(a, out, init=init, op=op)
        ref = np.empty_like(src)
        acc = init
        for i, v in enumerate(src):
            ref[i] = acc
            acc = acc * v
        np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-5)


def test_scan_into_subrange_preserves_rest():
    """Regression: the fast path must not clobber output cells outside the
    requested window."""
    src = np.arange(1, 5, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(10)
    dr_tpu.fill(out, 7.0)
    dr_tpu.inclusive_scan(a, out[0:4])
    got = dr_tpu.to_numpy(out)
    np.testing.assert_allclose(got[:4], np.cumsum(src))
    np.testing.assert_allclose(got[4:], np.full(6, 7.0))


def test_scan_generic_op():
    src = np.arange(1, 9, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(8)
    dr_tpu.inclusive_scan(a, out, op=lambda x, y: x + y + 1)
    ref = np.empty(8, dtype=np.float32)
    acc = src[0]
    ref[0] = acc
    for i in range(1, 8):
        acc = acc + src[i] + 1
        ref[i] = acc
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref)


def test_blocked_scan_large(oracle):
    # big enough that each of the 8 mesh shards' LOCAL scan exceeds the
    # 2 * 1024 flat-path cutoff and takes the blocked recursion
    n = 2 ** 15 + 37
    src = np.random.default_rng(7).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n)
    dr_tpu.inclusive_scan(a, out)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), np.cumsum(src),
                               rtol=1e-3, atol=1e-3)


def test_blocked_scan_helper_matches_flat():
    from dr_tpu.algorithms.scan import _blocked_scan
    import jax.numpy as jnp
    x = jnp.asarray(np.random.default_rng(3).standard_normal(4097),
                    dtype=jnp.float32)
    got = _blocked_scan(jnp.add, x, jnp.zeros((), jnp.float32))
    np.testing.assert_allclose(np.asarray(got),
                               np.cumsum(np.asarray(x)), rtol=1e-3,
                               atol=1e-3)
    # max monoid with -inf identity
    got = _blocked_scan(jnp.maximum, x,
                        jnp.array(-np.inf, jnp.float32))
    np.testing.assert_allclose(np.asarray(got),
                               np.maximum.accumulate(np.asarray(x)))


def test_chunked_cumsum_kernel_interpret():
    """Single-pass Pallas scan kernel (interpret mode) vs numpy."""
    from dr_tpu.ops import scan_pallas
    rng = np.random.default_rng(6)
    for n in (128 * 128, 128 * 128 * 4 + 0):
        R = scan_pallas.pick_chunk(n)
        assert R is not None
        x = jnp.asarray(rng.standard_normal(n).astype(np.float32))
        got = np.asarray(scan_pallas.chunked_cumsum(x, interpret=True))
        ref = np.cumsum(np.asarray(x, np.float64))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-3)


def test_scan_kernel_chunk_gates():
    from dr_tpu.ops import scan_pallas
    assert scan_pallas.pick_chunk(2 ** 27) == scan_pallas._MAX_ROWS
    assert scan_pallas.pick_chunk(128 * 128) == 128
    assert scan_pallas.pick_chunk(130) is None      # not lane-aligned
    assert scan_pallas.pick_chunk(128 * 100) is None  # rows % 2^k != 0


def test_distributed_scan_with_kernel_interpret(monkeypatch):
    """The full shard_map scan program with the Pallas kernel as the
    local scan (interpret mode) on the multi-device mesh — validates
    the kernel's interaction with masking, the all_gather carry
    exchange, and the exclusive shift."""
    from dr_tpu.algorithms import scan as scan_mod
    from dr_tpu.ops import kernels

    # a forced interpret-mode Decision (§22): the program threads
    # interpret=True into chunked_cumsum itself now
    monkeypatch.setattr(scan_mod, "_use_scan_kernel",
                        lambda *a, **k: kernels.Decision(True, True))
    P = dr_tpu.nprocs()
    # seg stays 128*128 (lane-chunkable) but n is NOT P*seg: the last
    # shard's tail is pad, exercising the gid<n mask ahead of the
    # kernel.  The shortfall must stay < P so ceil(n/P) == 128*128 at
    # EVERY mesh size (a fixed -3 made 3 | n at P=3, shrinking seg to a
    # non-chunkable 16383).  At P=1 there is no pad tail — the mask
    # path is then covered by the multi-device runs.
    n = 128 * 128 * P - max(P - 1, 0)
    rng = np.random.default_rng(12)
    src = rng.standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n)
    dr_tpu.inclusive_scan(a, out)
    np.testing.assert_allclose(dr_tpu.to_numpy(out),
                               np.cumsum(src.astype(np.float64)),
                               rtol=1e-4, atol=1e-3)
    ex = dr_tpu.distributed_vector(n)
    dr_tpu.exclusive_scan(a, ex)
    ref = np.concatenate(
        [[0.0], np.cumsum(src.astype(np.float64))[:-1]])
    np.testing.assert_allclose(dr_tpu.to_numpy(ex), ref,
                               rtol=1e-4, atol=1e-3)


def test_chunked_cumsum_kernel_bf16_interpret():
    from dr_tpu.ops import scan_pallas
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    n = 128 * 128
    x = jnp.asarray(rng.standard_normal(n), jnp.bfloat16)
    got = np.asarray(scan_pallas.chunked_cumsum(x, interpret=True)
                     .astype(jnp.float32))
    ref = np.cumsum(np.asarray(x.astype(jnp.float32), np.float64))
    # bf16 storage rounds each output; tolerance reflects that
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=1.0)


def test_chunked_cumsum_vpu_variant_interpret(monkeypatch):
    """The vector-unit in-chunk prefix (DR_TPU_SCAN_KERNEL=vpu) matches
    the MXU form and numpy."""
    from dr_tpu.ops import scan_pallas
    monkeypatch.setenv("DR_TPU_SCAN_KERNEL", "vpu")
    rng = np.random.default_rng(8)
    n = 128 * 128 * 4
    x = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    got = np.asarray(scan_pallas.chunked_cumsum(x, interpret=True))
    ref = np.cumsum(np.asarray(x, np.float64))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-3)


def test_scan_chunk_cap_env(monkeypatch):
    """DR_TPU_SCAN_CHUNK tunes pick_chunk (pow2-rounded) and the kernel
    still matches numpy at a non-default chunk."""
    from dr_tpu.ops import scan_pallas
    monkeypatch.setenv("DR_TPU_SCAN_CHUNK", "3000")  # rounds to 2048
    assert scan_pallas.chunk_cap() == 2048
    n = 128 * 2048
    assert scan_pallas.pick_chunk(n) == 2048
    rng = np.random.default_rng(14)
    x = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    got = np.asarray(scan_pallas.chunked_cumsum(x, interpret=True))
    np.testing.assert_allclose(got, np.cumsum(np.asarray(x, np.float64)),
                               rtol=1e-5, atol=1e-2)


def test_chunked_cumsum_pipe_and_passes_variants(monkeypatch):
    """Both DMA pipelines (auto-grid / manual) and every precision
    split depth produce ~f32-exact prefixes (interpret mode)."""
    import jax.numpy as jnp
    from dr_tpu.ops import scan_pallas
    rng = np.random.default_rng(7)
    n = 128 * 1024
    x = rng.standard_normal(n).astype(np.float32)
    ref = np.cumsum(x.astype(np.float64))
    scale = np.abs(ref).max() + 1
    for pipe in ("grid", "manual"):
        for passes in ("0", "2", "3"):
            monkeypatch.setenv("DR_TPU_SCAN_PIPE", pipe)
            monkeypatch.setenv("DR_TPU_SCAN_PASSES", passes)
            monkeypatch.setenv("DR_TPU_SCAN_CHUNK", "512")
            got = np.asarray(scan_pallas.chunked_cumsum(
                jnp.asarray(x), interpret=True))
            err = np.abs(got - ref).max() / scale
            tol = 3e-5 if passes == "2" else 3e-6
            assert err < tol, (pipe, passes, err)


def test_chunked_dot_kernel_interpret(monkeypatch):
    """Streamed dot kernel (interpret mode) vs numpy, incl. the in-
    kernel salt the dot_n measurement loop uses."""
    import jax.numpy as jnp
    from dr_tpu.ops import reduce_pallas
    rng = np.random.default_rng(11)
    monkeypatch.setenv("DR_TPU_SCAN_CHUNK", "512")
    n = 128 * 1024
    x = rng.standard_normal(n).astype(np.float32)
    y = rng.standard_normal(n).astype(np.float32)
    got = float(reduce_pallas.chunked_dot(jnp.asarray(x), jnp.asarray(y),
                                          interpret=True))
    ref = float(x.astype(np.float64) @ y.astype(np.float64))
    assert abs(got - ref) < 1e-4 * abs(ref) + 1e-3
    got_s = float(reduce_pallas.chunked_dot(
        jnp.asarray(x), jnp.asarray(y), salt=0.25, interpret=True))
    ref_s = float(x.astype(np.float64) @ (y.astype(np.float64) + 0.25))
    assert abs(got_s - ref_s) < 1e-4 * abs(ref_s) + 1e-3


def test_chunked_dot_bf16_interpret(monkeypatch):
    import jax.numpy as jnp
    from dr_tpu.ops import reduce_pallas
    rng = np.random.default_rng(14)
    monkeypatch.setenv("DR_TPU_SCAN_CHUNK", "256")
    n = 128 * 512
    x = rng.standard_normal(n).astype(np.float32)
    y = rng.standard_normal(n).astype(np.float32)
    got = float(reduce_pallas.chunked_dot(
        jnp.asarray(x, jnp.bfloat16), jnp.asarray(y, jnp.bfloat16),
        interpret=True))
    ref = float(x.astype(np.float64) @ y.astype(np.float64))
    # bf16 inputs round each operand; f32 accumulation keeps the rest
    assert abs(got - ref) < 2e-2 * (abs(ref) + 1)


@pytest.mark.parametrize("exclusive", [False, True])
def test_scan_window_native(monkeypatch, exclusive):
    """Round 4: aligned subrange windows with an identity op run the
    fused program over an identity-masked input — no materialize; cells
    outside the window keep the OUT container's original content."""
    n = 40
    src = np.random.default_rng(11).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(out, -7.0)
    b, e = 5, 31

    def boom(self):
        raise AssertionError("windowed scan materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    if exclusive:
        dr_tpu.exclusive_scan(a[b:e], out[b:e], init=None)
    else:
        dr_tpu.inclusive_scan(a[b:e], out[b:e])
    monkeypatch.undo()
    ref = np.full(n, -7.0, np.float32)
    w = np.cumsum(src[b:e], dtype=np.float32)
    ref[b:e] = np.concatenate([[0.0], w[:-1]]) if exclusive else w
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-4,
                               atol=1e-5)


def test_scan_window_native_uneven_mul(mesh_size):
    if mesh_size < 3:
        pytest.skip("needs a team-bearing distribution")
    sizes = [5, 0] + [4] * (mesh_size - 2)
    n = sum(sizes)
    src = np.random.default_rng(n).uniform(0.5, 1.5, n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
    out = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    dr_tpu.fill(out, 3.0)
    b, e = 2, n - 2
    dr_tpu.inclusive_scan(a[b:e], out[b:e], op=jnp.multiply)
    ref = np.full(n, 3.0, np.float32)
    ref[b:e] = np.cumprod(src[b:e]).astype(np.float32)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=2e-4)


@pytest.mark.parametrize("exclusive", [False, True])
def test_scan_window_identityless_native(monkeypatch, mesh_size,
                                         exclusive):
    """Round 4: identityless custom ops on aligned subrange windows run
    the fused program in WINDOW coordinates (the sort family's static
    window geometry + the identityless empty-shard-skipping fold) —
    no materialize, including the in-place aliased form."""
    if mesh_size < 3:
        pytest.skip("needs a team-bearing distribution")
    op = lambda a, b: a + b + a * b * 0.25
    sizes = [5, 0] + [4] * (mesh_size - 2)
    n = sum(sizes)
    src = np.random.default_rng(n).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src, distribution=sizes)
    b, e = 2, n - 3

    def boom(self):
        raise AssertionError("identityless windowed scan materialized")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)
    if exclusive:
        dr_tpu.exclusive_scan(a[b:e], a[b:e], init=None, op=op)
    else:
        dr_tpu.inclusive_scan(a[b:e], a[b:e], op=op)
    monkeypatch.undo()
    ref = src.copy()
    acc = src[b]
    w = np.empty(e - b, np.float32)
    w[0] = acc
    for i in range(b + 1, e):
        acc = np.float32(acc + src[i] + acc * src[i] * np.float32(0.25))
        w[i - b] = acc
    ref[b:e] = np.concatenate([[np.float32(0.0)], w[:-1]]) \
        if exclusive else w
    np.testing.assert_allclose(dr_tpu.to_numpy(a), ref, rtol=2e-3,
                               atol=2e-3)


# ---------------------------------------------------------------------------
# round 5: view chains, mismatched windows/layouts, cross-mesh — native
# ---------------------------------------------------------------------------

def _arm_no_materialize(monkeypatch):
    def boom(self):
        raise AssertionError("scan materialized on a native path")
    monkeypatch.setattr(dr_tpu.distributed_vector, "to_array", boom)


def test_scan_view_chain_native(monkeypatch):
    """Scans over transform-view chains fuse the op stack into the
    program (round 5 — used to materialize)."""
    from dr_tpu import views
    n = 101
    src = np.random.default_rng(31).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n, np.float32)
    _arm_no_materialize(monkeypatch)
    dr_tpu.inclusive_scan(views.transform(a, lambda x: x * 2.0), out)
    monkeypatch.undo()
    np.testing.assert_allclose(dr_tpu.to_numpy(out),
                               np.cumsum(src * 2.0), rtol=1e-4,
                               atol=1e-5)
    # stacked chain, exclusive, custom identityless op
    tv = views.transform(views.transform(a, lambda x: x + 1.0),
                         lambda x: x * x)
    out2 = dr_tpu.distributed_vector(n, np.float32)
    _arm_no_materialize(monkeypatch)
    dr_tpu.exclusive_scan(tv, out2, op=lambda p, q: p + q + 0.0 * p * q)
    monkeypatch.undo()
    vals = (src + 1.0) ** 2
    ref = np.concatenate([[0.0], np.cumsum(vals)[:-1]]).astype(np.float32)
    np.testing.assert_allclose(dr_tpu.to_numpy(out2), ref, rtol=1e-4,
                               atol=1e-4)
    # chain over a WINDOW of the container
    out3 = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(out3, -1.0)
    _arm_no_materialize(monkeypatch)
    dr_tpu.inclusive_scan(views.transform(a[10:60], lambda x: -x),
                          out3[10:60])
    monkeypatch.undo()
    ref3 = np.full(n, -1.0, np.float32)
    ref3[10:60] = np.cumsum(-src[10:60])
    np.testing.assert_allclose(dr_tpu.to_numpy(out3), ref3, rtol=1e-4,
                               atol=1e-5)


def test_scan_mismatched_windows_native(monkeypatch):
    """Mismatched in/out window offsets run the window-coordinate
    program with a realign into the destination geometry (round 5 —
    used to warn and materialize)."""
    import warnings
    from dr_tpu.utils.fallback import MaterializeFallbackWarning
    n = 64
    src = np.random.default_rng(32).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n, np.float32)
    dr_tpu.fill(out, 7.0)
    _arm_no_materialize(monkeypatch)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        dr_tpu.inclusive_scan(a[0:8], out[1:9])
    monkeypatch.undo()
    assert not [r for r in rec
                if issubclass(r.category, MaterializeFallbackWarning)]
    ref = np.full(n, 7.0, np.float32)
    ref[1:9] = np.cumsum(src[0:8])
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-4,
                               atol=1e-5)
    # wide windows crossing several shard boundaries, exclusive, init
    out2 = dr_tpu.distributed_vector(n, np.float32)
    _arm_no_materialize(monkeypatch)
    dr_tpu.exclusive_scan(a[5:55], out2[9:59], init=2.0)
    monkeypatch.undo()
    ref2 = np.zeros(n, np.float32)
    ref2[9:59] = 2.0 + np.concatenate([[0.0], np.cumsum(src[5:54])])
    np.testing.assert_allclose(dr_tpu.to_numpy(out2), ref2, rtol=1e-4,
                               atol=1e-5)
    # same-container aliased mismatched windows
    b = dr_tpu.distributed_vector.from_array(src)
    _arm_no_materialize(monkeypatch)
    dr_tpu.inclusive_scan(b[0:20], b[30:50])
    monkeypatch.undo()
    ref3 = src.copy()
    ref3[30:50] = np.cumsum(src[0:20])
    np.testing.assert_allclose(dr_tpu.to_numpy(b), ref3, rtol=1e-4,
                               atol=1e-5)


def test_scan_mismatched_layouts_native(monkeypatch, mesh_size):
    """Different block distributions of in and out (same mesh) run the
    realign program over whole containers (round 5)."""
    if mesh_size < 3:
        pytest.skip("needs >= 3 shards for an interesting uneven split")
    n = 41
    src = np.random.default_rng(33).standard_normal(n).astype(np.float32)
    sizes = [n - 20 - (mesh_size - 2) * 2, 20] + [2] * (mesh_size - 2)
    assert sum(sizes) == n and all(s >= 0 for s in sizes)
    a = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    a.assign_array(src)
    out = dr_tpu.distributed_vector(n, np.float32)  # uniform layout
    _arm_no_materialize(monkeypatch)
    dr_tpu.inclusive_scan(a, out)
    monkeypatch.undo()
    np.testing.assert_allclose(dr_tpu.to_numpy(out), np.cumsum(src),
                               rtol=1e-4, atol=1e-5)
    # multiplies (identity op) the other direction: uniform -> uneven
    b = dr_tpu.distributed_vector.from_array(
        np.abs(src) * 0.2 + 0.9)
    out2 = dr_tpu.distributed_vector(n, np.float32, distribution=sizes)
    _arm_no_materialize(monkeypatch)
    dr_tpu.inclusive_scan(b, out2, op=jnp.multiply)
    monkeypatch.undo()
    np.testing.assert_allclose(
        dr_tpu.to_numpy(out2),
        np.cumprod(np.abs(src) * 0.2 + 0.9), rtol=2e-4, atol=1e-5)


def test_scan_cross_mesh_reshard():
    """Scan into a container on a DIFFERENT runtime: native scan on the
    input mesh + reshard of the result (round 5 — no warning)."""
    import warnings
    from dr_tpu.parallel.runtime import Runtime
    from dr_tpu.utils.fallback import MaterializeFallbackWarning
    from jax.sharding import Mesh
    ndev = len(jax.devices())
    if ndev < 2:
        pytest.skip("needs >= 2 devices")
    rt_small = Runtime(mesh=Mesh(np.asarray(jax.devices()[:ndev // 2]),
                                 ("x",)))
    n = 77
    src = np.random.default_rng(34).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n, np.float32, runtime=rt_small)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        dr_tpu.inclusive_scan(a, out)
    assert not [r for r in rec
                if issubclass(r.category, MaterializeFallbackWarning)]
    np.testing.assert_allclose(dr_tpu.to_numpy(out), np.cumsum(src),
                               rtol=1e-4, atol=1e-5)


def test_scan_length_mismatch_is_clear():
    """In/out length mismatches follow transform's convention: larger
    out windows narrow to the input length; smaller ones raise a clear
    ValueError instead of a broadcast crash (round-5 review finding)."""
    src = np.arange(8, dtype=np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(20, np.float32)
    dr_tpu.fill(out, -1.0)
    dr_tpu.inclusive_scan(a, out)  # narrows: writes [0:8) only
    got = dr_tpu.to_numpy(out)
    np.testing.assert_allclose(got[:8], np.cumsum(src))
    np.testing.assert_array_equal(got[8:], np.full(12, -1.0, np.float32))
    with pytest.raises(ValueError, match="too small"):
        dr_tpu.inclusive_scan(a, out[0:4])


def test_scan_mismatched_window_never_takes_kernel(monkeypatch):
    """ADVICE r5 HIGH regression: the mismatched-window route forces
    window-coordinate geometry whose per-shard slice length is not
    lane-aligned — the Pallas chunked_cumsum would assert at trace
    time.  Even with the kernel gate claiming eligibility (as it does
    on TPU for an add-monoid f32 uniform container), the mis_ok route
    must build the XLA program."""
    import dr_tpu.algorithms.scan as scan_mod
    from dr_tpu.ops import kernels, scan_pallas

    def boom(*a, **k):
        raise AssertionError("Pallas kernel taken on the "
                             "mismatched-window scan route")
    monkeypatch.setattr(scan_mod, "_use_scan_kernel",
                        lambda *a, **k: kernels.Decision(True, True))
    monkeypatch.setattr(scan_pallas, "chunked_cumsum", boom)
    n = 61
    src = np.random.default_rng(61).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector.from_array(0.0 * src)
    wn = 40
    dr_tpu.inclusive_scan(a[3:3 + wn], out[9:9 + wn])  # olo != ilo
    ref = 0.0 * src
    ref[9:9 + wn] = np.cumsum(src[3:3 + wn], dtype=np.float64)
    np.testing.assert_allclose(dr_tpu.to_numpy(out), ref, rtol=1e-4,
                               atol=1e-5)


def test_scan_streamed_boundop_zero_recompile():
    """Round-6 compile-churn fix: fused view-chain BoundOps key on op
    identity + scalar COUNT and feed values as traced operands, so a
    loop streaming coefficients through a scan pipeline reuses ONE
    compiled program (the _custom_reduce_program convention)."""
    from dr_tpu.algorithms.elementwise import _prog_cache
    from dr_tpu import views
    n = 48
    src = np.random.default_rng(7).standard_normal(n).astype(np.float32)
    a = dr_tpu.distributed_vector.from_array(src)
    out = dr_tpu.distributed_vector(n, np.float32)

    def run(mu):
        dr_tpu.inclusive_scan(
            views.transform(a, _scaled_shift, mu), out)
        return dr_tpu.to_numpy(out)

    first = run(0.5)
    np.testing.assert_allclose(
        first, np.cumsum(src + np.float32(0.5), dtype=np.float64),
        rtol=1e-4, atol=1e-5)
    n_progs = len(_prog_cache)
    for mu in (0.25, -1.5, 3.0):
        got = run(mu)
        np.testing.assert_allclose(
            got, np.cumsum(src + np.float32(mu), dtype=np.float64),
            rtol=1e-4, atol=1e-5)
    assert len(_prog_cache) == n_progs, \
        "streamed BoundOp coefficients recompiled the scan program"


def _scaled_shift(x, mu):
    return x + mu
