"""Unit tests for the §22 kernel-arm registry (dr_tpu/ops/kernels.py):
selection precedence (env pin > tuning-DB winner > ``auto``), the
forced-pin interpret semantics off-TPU, the eligibility veto, the
``kernel.build`` fault-site degrade, and the Decision truthiness
contract every dispatch seam keys on.  The end-to-end halves (parity,
recording) live in test_fuzz.py and the tune ladder; this file pins
the decision function itself."""

import pytest

from dr_tpu import tuning
from dr_tpu.ops import kernels
from dr_tpu.utils import faults
from dr_tpu.utils.env import env_override


def test_decision_truthiness_contract():
    # a NamedTuple is ALWAYS truthy — seams must branch on .use and
    # key program caches on tuple(decision), never `if kern:`
    assert kernels.NO_KERNEL
    assert not kernels.NO_KERNEL.use
    assert tuple(kernels.Decision(True, True)) != tuple(kernels.NO_KERNEL)


def test_registry_shape():
    assert set(kernels.ARM_NAMES) == {"sort_local", "segred", "hist",
                                      "scan"}
    for arm, env, mod, fallback, site in kernels.ARMS:
        assert env.startswith("DR_TPU_")
        assert fallback
        assert site == "kernel.build"


def test_auto_resolves_by_platform():
    assert kernels.use_kernel("hist", "cpu") == kernels.NO_KERNEL
    assert kernels.use_kernel("hist", "tpu") == kernels.Decision(True,
                                                                 False)


def test_pallas_pin_forced_interpret_off_tpu():
    with env_override(DR_TPU_HIST_IMPL="pallas"):
        assert kernels.use_kernel("hist", "cpu") \
            == kernels.Decision(True, True)
        assert kernels.use_kernel("hist", "tpu") \
            == kernels.Decision(True, False)


def test_xla_pin_wins_even_on_tpu():
    with env_override(DR_TPU_SEGRED_IMPL="xla"):
        assert kernels.use_kernel("segred", "tpu") == kernels.NO_KERNEL


def test_tuning_db_between_pin_and_default():
    tuning.note("kernels", "hist", "pallas")
    try:
        # a recorded winner applies with no pin (interpret here: cpu)...
        assert kernels.use_kernel("hist", "cpu") \
            == kernels.Decision(True, True)
        # ...and an explicit env pin still beats it
        with env_override(DR_TPU_HIST_IMPL="xla"):
            assert kernels.use_kernel("hist", "cpu") == kernels.NO_KERNEL
    finally:
        tuning.clear_session()


def test_junk_pin_and_junk_db_mean_auto():
    tuning.note("kernels", "hist", "warp9")
    try:
        assert kernels.use_kernel("hist", "cpu") == kernels.NO_KERNEL
        with env_override(DR_TPU_HIST_IMPL="mystery"):
            assert kernels.use_kernel("hist", "tpu") \
                == kernels.Decision(True, False)  # junk pin = auto
    finally:
        tuning.clear_session()


def test_ineligible_beats_every_mode():
    with env_override(DR_TPU_SORT_LOCAL="pallas"):
        assert kernels.use_kernel("sort_local", "tpu", eligible=False) \
            == kernels.NO_KERNEL


def test_kernel_build_fault_degrades_to_xla(recwarn):
    with env_override(DR_TPU_HIST_IMPL="pallas"):
        try:
            with faults.injected("kernel.build", "transient", times=1):
                assert kernels.use_kernel("hist", "cpu") \
                    == kernels.NO_KERNEL
            # the fault was one-shot: the next decision is the pin again
            assert kernels.use_kernel("hist", "cpu") \
                == kernels.Decision(True, True)
        finally:
            faults.clear()


def test_unregistered_arm_asserts():
    with pytest.raises(AssertionError):
        kernels.use_kernel("warp", "cpu")
