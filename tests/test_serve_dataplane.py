"""Serving data plane (docs/SPEC.md §19): shared-memory tensor arena,
per-tenant resident containers, replica router, weighted-fair
admission.

Everything runs on the 8-device virtual CPU mesh with in-process
daemons under tmp_path sockets (the test_serve.py conventions); the
multi-tenant contention and arena concurrent-stress tests are the
ISSUE 13 satellite regressions.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

import dr_tpu
from dr_tpu import serve
from dr_tpu.serve import arena as arena_mod
from dr_tpu.serve import protocol
from dr_tpu.serve.queue import AdmissionQueue, Request, parse_weights
from dr_tpu.utils import faults, resilience
from dr_tpu.utils.env import env_override

X = np.arange(48, dtype=np.float32)
#: comfortably above the default DR_TPU_SERVE_ARENA_MIN_BYTES floor
BIG = np.arange(1 << 15, dtype=np.float32)


@pytest.fixture
def server(tmp_path):
    srv = serve.Server(str(tmp_path / "dp.sock"))
    srv.start()
    yield srv
    srv.stop()


def _client(srv, **kw):
    kw.setdefault("timeout", 60.0)
    return serve.Client(srv.path, **kw)


# ---------------------------------------------------------------------------
# arena unit behavior (no daemon)
# ---------------------------------------------------------------------------

def test_arena_alloc_release_recycles_and_coalesces():
    ar = serve.Arena(nbytes=1 << 16)
    try:
        a = ar.alloc(1000)
        b = ar.alloc(2000)
        c = ar.alloc(3000)
        assert ar.stats()["slots"] == 3
        ar.release(b)
        ar.release(a)  # adjacent frees coalesce back into one range
        d = ar.alloc(2900)  # fits the coalesced a+b hole
        assert d["offset"] == a["offset"]
        ar.release(c)
        ar.release(d)
        st = ar.stats()
        assert st["in_use"] == 0 and st["slots"] == 0
        # the whole segment is one hole again
        e = ar.alloc((1 << 16) - arena_mod.ALIGN)
        ar.release(e)
    finally:
        ar.destroy()


def test_arena_generation_tag_rejects_stale_handles():
    ar = serve.Arena(nbytes=1 << 16)
    try:
        h1 = ar.put(arena_mod.npy_bytes(X))
        ar.release(h1)
        # the slot id is gone; a recycled-id handle must NOT alias
        h2 = ar.put(arena_mod.npy_bytes(X * 2))
        with pytest.raises(resilience.ProgramError, match="stale"):
            ar.map(h1)
        with pytest.raises(resilience.ProgramError, match="stale"):
            ar.release(h1)  # double release is the same classified bug
        np.testing.assert_array_equal(ar.map(h2), X * 2)
        # refcounts: retain keeps the slot live across one release
        ar.retain(h2)
        ar.release(h2)
        np.testing.assert_array_equal(ar.map(h2), X * 2)
        ar.release(h2)
        assert ar.stats()["in_use"] == 0
    finally:
        ar.destroy()


def test_arena_exhaustion_is_classified_transient():
    ar = serve.Arena(nbytes=1 << 12)
    try:
        ar.alloc(3 << 10)
        with pytest.raises(resilience.TransientBackendError,
                           match="exhausted"):
            ar.alloc(3 << 10)
        assert ar.stats()["exhaustions"] == 1
    finally:
        ar.destroy()


def test_arena_release_owner_frees_wholesale():
    ar = serve.Arena(nbytes=1 << 16)
    try:
        owner = object()
        for _ in range(4):
            ar.alloc(512, owner=owner)
        keep = ar.alloc(512, owner=object())
        assert ar.release_owner(owner) == 4
        st = ar.stats()
        assert st["slots"] == 1
        ar.release(keep)
    finally:
        ar.destroy()


# ---------------------------------------------------------------------------
# arena over the wire
# ---------------------------------------------------------------------------

def test_arena_wire_roundtrip_and_reply_path(server):
    with _client(server) as c:
        got = c.scale(BIG, a=2.0, b=-1.0)
        np.testing.assert_allclose(got, BIG * 2.0 - 1.0, rtol=1e-6)
        assert c.arena_active(), "big payload should attach the arena"
        st = c.stats()
        # the request payload AND the same-size reply both mapped
        assert st["arena"]["allocs"] >= 2
        assert st["obs"]["counters"]["serve.arena.maps"] >= 1
        # multi-array op: both big operands ride the arena
        s = c.dot(BIG, BIG)
        assert abs(s - float((BIG.astype(np.float64) ** 2).sum())) \
            < abs(s) * 1e-5 + 1.0
        # mixed: small payloads stay inline on the same connection
        np.testing.assert_allclose(c.scale(X, a=3.0), X * 3.0,
                                   rtol=1e-6)
    # reply slots the client still owed free at disconnect teardown
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if server._arena.stats()["in_use"] == 0:
            break
        time.sleep(0.02)
    assert server._arena.stats()["in_use"] == 0


def test_arena_lease_cache_reuses_slots_across_requests(server):
    """ISSUE 15 satellite: same-shape payloads reuse a granted slot
    lease (the ``keep`` wire marker) — the per-request ``arena_alloc``
    round trip disappears after the first, the daemon's alloc count
    stays flat, and every reply is still correct."""
    with _client(server) as c:
        for r in range(6):
            got = c.scale(BIG, a=1.0 + r)
            np.testing.assert_allclose(got, BIG * (1.0 + r), rtol=1e-6)
        assert c.arena_active()
        assert c.lease_hits >= 5, (c.lease_hits, c.lease_misses)
        assert c.lease_misses == 1
        # the held lease is ONE live slot beyond the reply traffic —
        # request-side allocs stopped after the first request
        allocs_now = server._arena.stats()["allocs"]
        c.scale(BIG, a=9.0)
        # one more round trip costs exactly the REPLY slot, never a
        # fresh request lease
        assert server._arena.stats()["allocs"] == allocs_now + 1
    # disconnect teardown reaps the held lease wholesale
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if server._arena.stats()["in_use"] == 0:
            break
        time.sleep(0.02)
    assert server._arena.stats()["in_use"] == 0


def test_arena_lease_cache_differs_by_shape_and_caps(server):
    """Different payload sizes take different leases; the cache stays
    bounded (excess leases release by piggyback, never leak)."""
    small_big = np.arange(1 << 14, dtype=np.float32)  # still >= floor
    with _client(server) as c:
        c.scale(BIG, a=1.0)
        c.scale(small_big, a=1.0)
        hits0 = c.lease_hits
        c.scale(BIG, a=2.0)
        c.scale(small_big, a=2.0)
        assert c.lease_hits == hits0 + 2
        assert c.lease_misses == 2  # one per distinct capacity


def test_arena_lease_cache_disabled_by_env(tmp_path):
    with env_override(DR_TPU_SERVE_LEASE_CACHE="0"):
        srv = serve.Server(str(tmp_path / "nolease.sock")).start()
        try:
            with _client(srv) as c:
                for r in range(3):
                    np.testing.assert_allclose(
                        c.scale(BIG, a=1.0 + r), BIG * (1.0 + r),
                        rtol=1e-6)
                assert c.lease_hits == 0
                assert c.lease_misses == 3
        finally:
            srv.stop()


def test_arena_lease_cache_drops_on_reconnect(tmp_path):
    """A reconnect invalidates every held lease (the daemon teardown
    freed them; a recycled slot's generation bumped) — the fresh
    connection re-leases instead of offering a stale handle."""
    srv = serve.Server(str(tmp_path / "relse.sock")).start()
    try:
        with _client(srv, retries=3) as c:
            c.scale(BIG, a=1.0)
            c.scale(BIG, a=2.0)
            assert c.lease_hits == 1
            # force a desync the retry path heals with a reconnect
            c._invalidate("test: simulated desync")
            assert c._lease_cache == {}
            np.testing.assert_allclose(c.scale(BIG, a=3.0), BIG * 3.0,
                                       rtol=1e-6)
            assert c.lease_misses >= 2  # re-leased after the drop
    finally:
        srv.stop()


def test_arena_disabled_daemon_serves_inline(tmp_path):
    with env_override(DR_TPU_SERVE_ARENA="0"):
        srv = serve.Server(str(tmp_path / "noar.sock")).start()
    try:
        with serve.Client(srv.path, timeout=60.0) as c:
            assert "arena" not in c.ping()
            np.testing.assert_allclose(c.scale(BIG, a=2.0), BIG * 2.0,
                                       rtol=1e-6)
            assert not c.arena_active()
    finally:
        srv.stop()


def test_arena_exhausted_falls_back_to_inline_wire(tmp_path):
    """An arena too small for the payload: the client's lease fails
    with the classified transient and the request silently takes the
    inline wire — full function, counted fallback."""
    with env_override(DR_TPU_SERVE_ARENA_BYTES=str(1 << 12)):
        srv = serve.Server(str(tmp_path / "tiny.sock")).start()
    try:
        with serve.Client(srv.path, timeout=60.0) as c:
            np.testing.assert_allclose(c.scale(BIG, a=2.0), BIG * 2.0,
                                       rtol=1e-6)
            st = c.stats()
            assert st["arena"]["exhaustions"] >= 1
            assert st["obs"]["counters"].get("serve.arena.fallbacks",
                                             0) >= 1
    finally:
        srv.stop()


def test_arena_stale_wire_handle_classified(server):
    """A handle the daemon never leased (or already recycled) is the
    client's deterministic bug: classified ProgramError, site
    arena.map, connection keeps serving."""
    with _client(server) as c:
        c.scale(BIG, a=1.0)  # attach + prove the arena works
        raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        raw.connect(server.path)
        try:
            protocol.send_frame(
                raw, {"op": "scale", "params": {"a": 1.0}, "id": 1,
                      "arena": [{"slot": 999999, "generation": 3,
                                 "len": 64}]})
            hdr, _ = protocol.recv_frame(raw)
            assert hdr["ok"] is False
            assert hdr["error"]["cls"] == "ProgramError"
            assert hdr["error"]["site"] == "arena.map"
        finally:
            raw.close()
        assert abs(c.reduce(X) - X.sum()) < 1e-3


def test_arena_fault_sites_drive_classified_or_fallback(server):
    with _client(server) as c:
        c.scale(BIG, a=1.0)  # attach
        # a transient at the lease: the client falls back inline and
        # the request still succeeds
        with faults.injected("arena.map", "transient") as sp:
            np.testing.assert_allclose(c.scale(BIG, a=2.0), BIG * 2.0,
                                       rtol=1e-6)
            assert sp.fired == 1
        # a program fault surfaces classified (no fallback for
        # deterministic bugs)
        with faults.injected("arena.map", "program") as sp:
            with pytest.raises(resilience.ProgramError):
                c.scale(BIG, a=2.0)
            assert sp.fired == 1
        # the daemon survived both
        np.testing.assert_allclose(c.scale(BIG, a=4.0), BIG * 4.0,
                                   rtol=1e-6)


def test_arena_concurrent_stress_slot_recycling(tmp_path):
    """ISSUE 13 satellite: parallel clients hammer a SMALL arena —
    slot recycling under contention, exhaustion fallbacks interleaved
    with arena traffic, every result exact, and the arena drains to
    zero once the clients disconnect."""
    with env_override(DR_TPU_SERVE_ARENA_BYTES=str(1 << 20)):
        srv = serve.Server(str(tmp_path / "stress.sock"),
                           queue_depth=256, tenant_cap=64).start()
    errs = []
    try:
        with serve.Client(srv.path, timeout=120.0) as c:
            c.scale(BIG, a=1.0)  # compile once

        def worker(i):
            try:
                rng = np.random.default_rng(i)
                with serve.Client(srv.path, timeout=120.0,
                                  tenant=f"w{i}") as c:
                    for r in range(6):
                        x = rng.standard_normal(1 << 15) \
                            .astype(np.float32)
                        got = c.scale(x, a=2.0, b=float(r))
                        np.testing.assert_allclose(got, x * 2.0 + r,
                                                   rtol=1e-6)
            except Exception as e:  # pragma: no cover - failure path
                errs.append(repr(e))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert not errs, errs[:3]
        st = srv._arena.stats()
        assert st["allocs"] >= 6  # arena traffic actually happened
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and srv._arena.stats()["in_use"]:
            time.sleep(0.02)
        assert srv._arena.stats()["in_use"] == 0, \
            "slots leaked after client disconnects"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# resident container cache
# ---------------------------------------------------------------------------

def test_resident_put_ref_get_drop_roundtrip(server):
    with _client(server) as c:
        r = c.put("feat", X)
        assert r["bytes"] == X.nbytes and r["cached"] is False
        # repeated ops by reference: zero payload, no rebuild
        assert abs(c.reduce(serve.Ref("feat")) - X.sum()) < 1e-3
        np.testing.assert_allclose(c.scale(serve.Ref("feat"), a=2.0),
                                   X * 2.0, rtol=1e-6)
        # the mutating op ran on a scratch copy — the resident value
        # is untouched
        np.testing.assert_array_equal(c.get("feat"), X)
        # ref + inline operand mix (dot takes one of each)
        assert abs(c.dot(serve.Ref("feat"), X)
                   - float((X.astype(np.float64) ** 2).sum())) < 1e-2
        # identical re-put is a content-tag HIT (no rebuild)
        assert c.put("feat", X)["cached"] is True
        # different content replaces
        r3 = c.put("feat", X * 3)
        assert r3["cached"] is False
        np.testing.assert_allclose(c.get("feat"), X * 3, rtol=1e-6)
        assert c.drop("feat")["dropped"] is True
        assert c.drop("feat")["dropped"] is False
        with pytest.raises(resilience.ProgramError,
                           match="no resident"):
            c.reduce(serve.Ref("feat"))
        st = c.stats()["resident"]
        assert st["puts"] == 2 and st["put_hits"] == 1


def test_resident_is_tenant_scoped(server):
    with _client(server, tenant="alice") as a, \
            _client(server, tenant="bob") as b:
        a.put("secret", X)
        with pytest.raises(resilience.ProgramError,
                           match="no resident"):
            b.get("secret")
        # bob's same-name put shadows nothing of alice's
        b.put("secret", X * 2)
        np.testing.assert_array_equal(a.get("secret"), X)
        np.testing.assert_allclose(b.get("secret"), X * 2, rtol=1e-6)


def test_resident_lru_bytes_budget_evicts(tmp_path):
    n = 1 << 10  # 4 KiB per value
    with env_override(DR_TPU_SERVE_RESIDENT_BYTES=str(3 * n * 4)):
        srv = serve.Server(str(tmp_path / "lru.sock")).start()
    try:
        with serve.Client(srv.path, timeout=60.0) as c:
            vals = {}
            for i in range(4):
                vals[i] = np.full(n, float(i), np.float32)
                c.put(f"v{i}", vals[i])
            # 4 puts against a 3-value budget: v0 (LRU) evicted
            with pytest.raises(resilience.ProgramError,
                               match="no resident"):
                c.get("v0")
            np.testing.assert_array_equal(c.get("v3"), vals[3])
            st = c.stats()["resident"]
            assert st["evictions"] == 1 and st["entries"] == 3
            assert st["bytes"] <= 3 * n * 4
            # touching v1 re-freshens it: the NEXT eviction takes v2
            c.get("v1")
            c.put("v4", np.full(n, 9.0, np.float32))
            np.testing.assert_array_equal(c.get("v1"), vals[1])
            with pytest.raises(resilience.ProgramError,
                               match="no resident"):
                c.get("v2")
            # a single value past the whole budget is a classified
            # rejection, not a cache wipe
            with pytest.raises(resilience.ProgramError,
                               match="budget"):
                c.put("huge", np.zeros(4 * n, np.float32))
            np.testing.assert_array_equal(c.get("v1"), vals[1])
    finally:
        srv.stop()


def test_resident_rides_elastic_shrink_poison_classified(server):
    """§19.2 x §16: a resident container the shrink cannot rescue is
    POISONED — later uses raise the classified DeviceLostError to the
    client (never a silent wrong answer) — and a re-put on the
    shrunken mesh serves again.  The session grows back afterwards so
    later tests see the full mesh."""
    from dr_tpu.utils import elastic
    P = dr_tpu.nprocs()
    if P < 2:
        pytest.skip("shrink needs >= 2 devices")
    with _client(server) as c:
        c.put("state", X)
        assert abs(c.reduce(serve.Ref("state")) - X.sum()) < 1e-3
        try:
            elastic.rescue_session(
                resilience.DeviceLostError(
                    "dataplane: simulated device loss", rank=P - 1))
            # the full-span uncheckpointed resident is LOST: poisoned,
            # classified on use — for get and ref-ops alike
            with pytest.raises(resilience.DeviceLostError):
                c.get("state")
            with pytest.raises(resilience.DeviceLostError):
                c.reduce(serve.Ref("state"))
            # a fresh put on the shrunken mesh serves again
            c.put("state", X * 2)
            assert abs(c.reduce(serve.Ref("state")) - 2 * X.sum()) \
                < 1e-3
        finally:
            elastic.grow_session(reason="dataplane test: restore mesh")
        # after grow-back the re-put value still answers (the §16.6
        # container walk re-admitted it)
        assert abs(c.reduce(serve.Ref("state")) - 2 * X.sum()) < 1e-3


# ---------------------------------------------------------------------------
# weighted-fair admission (DRR)
# ---------------------------------------------------------------------------

def _reqs(tenant, n):
    return [Request("scale", {}, [X], tenant=tenant) for _ in range(n)]


def test_drr_interleaves_tenants_fifo_within():
    q = AdmissionQueue(64, 64, weights={})
    heavy = _reqs("heavy", 6)
    light = _reqs("light", 2)
    for r in heavy + light:  # heavy's burst queues FIRST
        q.submit(r)
    live, dropped = q.take_batch(8, 0.0)
    assert not dropped
    order = [r.tenant for r in live]
    # equal weights: strict alternation until light drains, FIFO
    # within each tenant — light's requests land at positions 1 and 3
    # instead of 6 and 7 (the FIFO starvation this queue replaces)
    assert order[:4] == ["heavy", "light", "heavy", "light"]
    assert order[4:] == ["heavy"] * 4
    assert [r is h for r, h in zip(
        [x for x in live if x.tenant == "heavy"], heavy)] == [True] * 6


def test_drr_weights_shift_the_share():
    q = AdmissionQueue(64, 64, weights={"gold": 3.0})
    for r in _reqs("free", 6) + _reqs("gold", 6):
        q.submit(r)
    live, _ = q.take_batch(8, 0.0)
    order = [r.tenant for r in live]
    # free banked 1 credit/turn, gold 3: gold takes 3 of every 4
    assert order.count("gold") == 6
    assert order[:4].count("free") == 1
    live2, _ = q.take_batch(8, 0.0)
    assert [r.tenant for r in live2] == ["free"] * 4


def test_drr_fractional_weights_bank_across_turns():
    q = AdmissionQueue(64, 64, weights={"slow": 0.5})
    for r in _reqs("slow", 2) + _reqs("fast", 2):
        q.submit(r)
    live, _ = q.take_batch(10, 0.0)
    order = [r.tenant for r in live]
    # slow's half-credit banks: it pops on every SECOND ring turn but
    # still drains completely (no starvation, no infinite loop)
    assert order.count("slow") == 2 and order.count("fast") == 2
    assert order[0] == "fast" or order[1] == "fast"


def test_parse_weights_tolerant():
    assert parse_weights("a:2,b:0.5") == {"a": 2.0, "b": 0.5}
    assert parse_weights(" gold : 4 ; free : 1 ") == \
        {"gold": 4.0, "free": 1.0}
    # malformed entries skip; zero/negative weights floor positive
    w = parse_weights("bad,x:oops,ok:3,z:-1")
    assert w["ok"] == 3.0 and w["z"] == pytest.approx(1e-3)
    assert "bad" not in w and "x" not in w
    assert parse_weights("") == {}


def test_starvation_regression_light_tenant_bounded(tmp_path):
    """ISSUE 13 acceptance: a heavy tenant's burst must not starve a
    light tenant.  Heavy floods 10 requests before light's single
    request even queues; with the DRR pop the light request rides the
    FIRST batch, so its queue-wait stays near the minimum while
    heavy's tail pays for its own burst."""
    srv = serve.Server(str(tmp_path / "fair.sock"), batch_max=2,
                       tenant_cap=16, batch_window=0.0).start()
    try:
        with serve.Client(srv.path, timeout=60.0) as c:
            c.scale(X, a=1.0)  # compile once
        srv.hold()
        done = []

        def worker(tenant):
            with serve.Client(srv.path, timeout=60.0,
                              tenant=tenant) as c:
                c.scale(X, a=2.0)
                done.append(tenant)

        threads = [threading.Thread(target=worker, args=("heavy",))
                   for _ in range(10)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10.0
        while len(srv._queue) < 10 and time.monotonic() < deadline:
            time.sleep(0.01)
        lt = threading.Thread(target=worker, args=("light",))
        lt.start()  # the light request queues LAST
        while len(srv._queue) < 11 and time.monotonic() < deadline:
            time.sleep(0.01)
        srv.release()
        for t in threads + [lt]:
            t.join(timeout=60.0)
        assert len(done) == 11
        hists = srv.stats()["obs"]["histograms"]
        light = hists["serve.queue_wait_ms.t.light"]
        heavy = hists["serve.queue_wait_ms.t.heavy"]
        assert light["count"] == 1 and heavy["count"] == 10
        # the light request popped in the first DRR round: its wait is
        # bounded by the FIRST batch, not the heavy backlog's tail
        assert light["max"] < heavy["max"], (light, heavy)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# replica router
# ---------------------------------------------------------------------------

def test_hash_ring_stable_and_bounded_rehash():
    paths = [f"/tmp/r{i}.sock" for i in range(4)]
    ring1 = serve.HashRing(paths)
    ring2 = serve.HashRing(list(paths))
    tenants = [f"tenant{i}" for i in range(64)]
    before = {t: ring1.lookup(t) for t in tenants}
    # placement is process-independent (sha1, not salted hash())
    assert before == {t: ring2.lookup(t) for t in tenants}
    assert len(set(before.values())) > 1, "all tenants on one replica"
    ring1.remove(paths[0])
    moved = [t for t in tenants if ring1.lookup(t) != before[t]]
    # ONLY the dead replica's tenants moved (consistent hashing)
    assert all(before[t] == paths[0] for t in moved)
    assert all(ring1.lookup(t) == before[t] for t in tenants
               if before[t] != paths[0])


def test_router_fleet_tenant_affinity_and_stats(tmp_path):
    fleet = serve.Router(str(tmp_path / "f"), replicas=2, cpu=True,
                         batch_window=0.0).start()
    try:
        with serve.RouterClient(fleet.paths(), tenant="alice",
                                timeout=60.0) as rc:
            assert abs(rc.reduce(np.ones(64, np.float32)) - 64.0) \
                < 1e-3
            # resident state follows tenant affinity: put and ref land
            # on the SAME replica without the caller knowing which
            rc.put("feat", X)
            assert abs(rc.reduce(serve.Ref("feat")) - X.sum()) < 1e-3
            # a second tenant routes independently (possibly the other
            # replica) and its ops work through the same front
            assert abs(rc.reduce(np.ones(8, np.float32),
                                 tenant="bob") - 8.0) < 1e-3
            st = rc.stats()
            assert len(st) == 2
            assert sum(s["requests"] for s in st.values()) >= 3
    finally:
        fleet.stop()


def test_router_dead_replica_rehash_with_story_marker(tmp_path):
    fleet = serve.Router(str(tmp_path / "k"), replicas=2, cpu=True,
                         batch_window=0.0).start()
    try:
        with serve.RouterClient(fleet.paths(), tenant="alice",
                                timeout=60.0) as rc:
            assert abs(rc.reduce(np.ones(32, np.float32)) - 32.0) \
                < 1e-3
            victim = rc.route("alice")
            next(s for s in fleet._servers if s.path == victim).stop()
            # the next op re-hashes onto the survivor and SUCCEEDS
            assert abs(rc.reduce(np.ones(16, np.float32)) - 16.0) \
                < 1e-3
            assert rc.rehashes == 1
            assert rc.live_replicas() == \
                [p for p in fleet.paths() if p != victim]
            story = resilience.degradation_story()
            assert story is not None
            assert story["serve"]["router_dead"] == 1
            assert "re-hashed" in story["serve"]["router_reason"]
            # killing the LAST replica surfaces the degrade signal
            next(s for s in fleet._servers
                 if s.path != victim).stop()
            with pytest.raises(resilience.RelayDownError):
                rc.reduce(np.ones(8, np.float32))
    finally:
        fleet.stop()
        serve.reset()


def test_router_route_fault_site_classified(tmp_path):
    fleet = serve.Router(str(tmp_path / "rf"), replicas=1, cpu=True,
                         batch_window=0.0).start()
    try:
        with serve.RouterClient(fleet.paths(), timeout=60.0) as rc:
            with faults.injected("router.route", "program") as sp:
                with pytest.raises(resilience.ProgramError):
                    rc.reduce(X)
                assert sp.fired == 1
            # a transient from a LIVE replica re-raises (no rehash)
            with faults.injected("router.route", "transient") as sp:
                with pytest.raises(resilience.TransientBackendError):
                    rc.reduce(X)
                assert sp.fired == 1
            assert rc.rehashes == 0
            assert abs(rc.reduce(np.ones(8, np.float32)) - 8.0) < 1e-3
    finally:
        fleet.stop()


# ---------------------------------------------------------------------------
# trace_view per-tenant rollup
# ---------------------------------------------------------------------------

def test_trace_view_per_tenant_rollup(capsys):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "trace_view", os.path.join(repo, "tools", "trace_view.py"))
    tv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tv)
    events = []
    sid = 0
    for tenant, qw_us, total_us, n in (("heavy", 5000, 9000, 3),
                                       ("light", 100, 1200, 2)):
        for i in range(n):
            sid += 1
            events.append({"ph": "X", "name": "serve.request",
                           "id": sid, "ts": sid * 100000,
                           "dur": total_us,
                           "args": {"op": "reduce", "tenant": tenant,
                                    "rid": str(sid)}})
            events.append({"ph": "X", "name": "serve.queue_wait",
                           "ts": sid * 100000, "dur": qw_us,
                           "args": {"parent": sid}})
    tv.summarize(events)
    out = capsys.readouterr().out
    assert "per-tenant rollup" in out
    heavy = next(l for l in out.splitlines()
                 if l.strip().startswith("heavy"))
    light = next(l for l in out.splitlines()
                 if l.strip().startswith("light"))
    assert " 3 " in heavy and "5.000 ms" in heavy  # qw p50
    assert " 2 " in light and "100 us" in light
    # service = span remainder after queue-wait
    assert "4.000 ms" in heavy and "1.100 ms" in light


@pytest.mark.slow  # two daemon subprocesses = two jax imports; the
# fuzz-crank arena arm runs this (client churn x arena exhaustion x
# replica kill under DR_TPU_CHAOS_ROUNDS)
def test_router_subprocess_fleet_churn_and_kill(tmp_path):
    import subprocess  # noqa: F401  (documents the spawn mode)
    fleet = serve.Router(str(tmp_path / "sub"), replicas=2, cpu=True,
                         spawn=True).start()
    try:
        errs = []

        def churn(i):
            try:
                rng = np.random.default_rng(i)
                for r in range(4):
                    with serve.RouterClient(
                            fleet.paths(), tenant=f"t{i}",
                            timeout=120.0) as rc:
                        x = rng.standard_normal(1 << 15) \
                            .astype(np.float32)
                        got = rc.scale(x, a=2.0)
                        np.testing.assert_allclose(got, x * 2.0,
                                                   rtol=1e-6)
            except resilience.ResilienceError:
                pass  # classified is an acceptable churn outcome
            except Exception as e:  # pragma: no cover
                errs.append(repr(e))

        threads = [threading.Thread(target=churn, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240.0)
        assert not errs, errs[:3]
        # kill replica 0 mid-fleet: a fresh RouterClient re-hashes
        # onto the survivor and still serves
        fleet._procs[0].kill()
        fleet._procs[0].wait(timeout=30)
        with serve.RouterClient(fleet.paths(), tenant="after",
                                timeout=120.0) as rc:
            assert abs(rc.reduce(np.ones(64, np.float32)) - 64.0) \
                < 1e-3
    finally:
        fleet.stop()
        serve.reset()
