"""Worker for the elastic multihost leg (docs/SPEC.md §16.5): two
processes join a jax.distributed mesh, worker 1 is killed mid-session,
and worker 0 must downgrade the mesh instead of dying with the job —
attribute the collective failure to the dead rank
(``elastic.attribute``), shrink onto its local devices
(``elastic.rescue_session``), restore the checkpointed vector, and
finish.  Usage: python elastic_worker.py <pid> <nproc> <port> <ckpt>
"""

import os
import sys

pid, nproc, port, ck = (int(sys.argv[1]), int(sys.argv[2]),
                        sys.argv[3], sys.argv[4])

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import dr_tpu  # noqa: E402
from dr_tpu.utils import elastic, resilience  # noqa: E402

dr_tpu.init_distributed(f"localhost:{port}", nproc, pid)
assert dr_tpu.nprocs() == nproc

n = 4 * nproc
dv = dr_tpu.distributed_vector(n, dtype=np.float32)
dr_tpu.iota(dv, 1)
total = float(dr_tpu.reduce(dv))
assert total == n * (n + 1) / 2, total

# checkpoint while every rank is alive (collective: materialization
# gathers, rank 0 writes) — the restore source for the dead segment
dr_tpu.checkpoint.save(ck, dv)
dr_tpu.barrier()

if pid != 0:
    # simulate a host loss: die without a word, mid-session
    os._exit(17)

# worker 0: the next collective against the dead peer fails (or
# hangs — the watchdog bounds it either way); attribute the failure to
# the dead rank and SHRINK instead of dying with it
try:
    resilience.with_deadline(lambda: float(dr_tpu.reduce(dv)), 60.0,
                             site="elastic.multihost", dump=False)
    raise SystemExit("peer death went unnoticed by the collective")
except resilience.ResilienceError as e:
    loss = elastic.attribute(e, 1)

report = elastic.rescue_session(loss)
assert dr_tpu.nprocs() == 1, dr_tpu.nprocs()
assert report.restored == 1, report

# the rank-0 half is the survivors' live state, the dead rank's half
# restored from the checkpoint — here both equal the iota
np.testing.assert_allclose(dr_tpu.to_numpy(dv),
                           np.arange(1, n + 1, dtype=np.float32))
assert float(dr_tpu.reduce(dv)) == total

print(f"ELASTIC-MULTIHOST-OK lost_rank={loss.rank} "
      f"nprocs={dr_tpu.nprocs()}", flush=True)
