"""Temporally-blocked Pallas stencil vs the serial oracle (interpret mode
on CPU; the same kernel compiles natively on TPU)."""

import numpy as np
import pytest

import dr_tpu
from dr_tpu.algorithms.stencil import stencil_iterate_blocked
from dr_tpu.ops import stencil_pallas


pytestmark = pytest.mark.skipif(not stencil_pallas.supported(),
                                reason="pallas TPU namespace unavailable")


def _serial_periodic(x, w, steps):
    r = (len(w) - 1) // 2
    x = x.astype(np.float64).copy()
    for _ in range(steps):
        acc = np.zeros_like(x)
        for d in range(-r, r + 1):
            acc += np.roll(x, -d) * w[d + r]
        x = acc
    return x


# kernel geometry: seg and halo are whole (8, 128) f32 tiles
ALIGN = stencil_pallas.ROW_ALIGN


@pytest.mark.parametrize("steps", [4, 8, 11])
def test_blocked_matches_oracle(steps):
    P = dr_tpu.nprocs()
    seg = ALIGN
    n = P * seg
    w = [0.25, 0.5, 0.25]
    src = np.random.default_rng(0).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(ALIGN, ALIGN, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(src, halo=hb)
    stencil_iterate_blocked(dv, w, steps, time_block=4)
    ref = _serial_periodic(src, w, steps)
    np.testing.assert_allclose(dr_tpu.to_numpy(dv), ref, rtol=1e-4,
                               atol=1e-5)


def test_blocked_5pt():
    P = dr_tpu.nprocs()
    seg = ALIGN
    n = P * seg
    w = [0.05, 0.25, 0.4, 0.25, 0.05]
    src = np.random.default_rng(1).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(ALIGN, ALIGN, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(src, halo=hb)
    stencil_iterate_blocked(dv, w, 8, time_block=4)
    ref = _serial_periodic(src, w, 8)
    np.testing.assert_allclose(dr_tpu.to_numpy(dv), ref, rtol=1e-4,
                               atol=1e-5)


def test_blocked_multichunk():
    """seg spanning several DMA chunks exercises the double-buffer loop."""
    P = dr_tpu.nprocs()
    seg = 4 * ALIGN
    n = P * seg
    w = [0.25, 0.5, 0.25]
    src = np.random.default_rng(3).standard_normal(n).astype(np.float32)
    hb = dr_tpu.halo_bounds(ALIGN, ALIGN, periodic=True)
    dv = dr_tpu.distributed_vector.from_array(src, halo=hb)
    stencil_iterate_blocked(dv, w, 6, time_block=6, chunk=ALIGN)
    ref = _serial_periodic(src, w, 6)
    np.testing.assert_allclose(dr_tpu.to_numpy(dv), ref, rtol=1e-4,
                               atol=1e-5)


def test_blocked_matches_unblocked():
    P = dr_tpu.nprocs()
    seg = ALIGN
    n = P * seg
    w = [1 / 3, 1 / 3, 1 / 3]
    src = np.random.default_rng(2).standard_normal(n).astype(np.float32)
    hb1 = dr_tpu.halo_bounds(1, 1, periodic=True)
    a = dr_tpu.distributed_vector.from_array(src, halo=hb1)
    b = dr_tpu.distributed_vector.from_array(src, halo=hb1)
    ref_dv = dr_tpu.stencil_iterate(a, b, w, steps=6)
    hb2 = dr_tpu.halo_bounds(ALIGN, ALIGN, periodic=True)
    blk = dr_tpu.distributed_vector.from_array(src, halo=hb2)
    stencil_iterate_blocked(blk, w, 6, time_block=3)
    np.testing.assert_allclose(dr_tpu.to_numpy(blk),
                               dr_tpu.to_numpy(ref_dv), rtol=1e-4,
                               atol=1e-5)
