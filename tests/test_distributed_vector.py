"""distributed_vector tests (reference test/gtest/mhp/distributed_vector.cpp,
test/gtest/shp/containers.cpp)."""

import numpy as np
import pytest

import dr_tpu


def test_zero_initialized(mesh_size):
    dv = dr_tpu.distributed_vector(17)
    np.testing.assert_array_equal(dr_tpu.to_numpy(dv), np.zeros(17))


def test_segment_sizing_matches_reference_rule(mesh_size):
    # segment_size = max(ceil(n/p), prev, next)  (mhp dv.hpp:190-193)
    n = 23
    hb = dr_tpu.halo_bounds(2, 3)
    dv = dr_tpu.distributed_vector(n, halo=hb)
    assert dv.segment_size == max(-(-n // mesh_size), 2, 3)
    assert dv.block_width == dv.segment_size + 5


def test_element_read_write(mesh_size):
    dv = dr_tpu.distributed_vector(13)
    dv[3] = 42.0
    dv[12] = -1.0
    assert dv[3] == 42.0
    assert dv[12] == -1.0
    assert dv[-1] == -1.0
    with pytest.raises(IndexError):
        dv[13]


def test_batched_get_put(mesh_size):
    dv = dr_tpu.distributed_vector(20, dtype=np.int32)
    idx = np.array([0, 5, 7, 19, 11])
    vals = np.array([1, 2, 3, 4, 5], dtype=np.int32)
    dv.put(idx, vals)
    got = np.asarray(dv.get(idx))
    np.testing.assert_array_equal(got, vals)
    # untouched elements remain zero
    assert dv[1] == 0


def test_from_array_roundtrip(mesh_size, oracle):
    ref = np.arange(29, dtype=np.float32) * 1.5
    dv = dr_tpu.distributed_vector.from_array(ref)
    oracle.equal(dv, ref)
    oracle.check_segments(dv)


def test_from_array_with_halo(oracle):
    ref = np.arange(50, dtype=np.float32)
    dv = dr_tpu.distributed_vector.from_array(
        ref, halo=dr_tpu.halo_bounds(1, 1))
    oracle.equal(dv, ref)


def test_slice_returns_view(oracle):
    dv = dr_tpu.distributed_vector(30)
    dr_tpu.iota(dv, 0)
    v = dv[5:15]
    assert len(v) == 10
    oracle.equal(v, np.arange(5, 15, dtype=np.float32))


def test_slice_assignment():
    dv = dr_tpu.distributed_vector(10)
    dv[2:5] = np.array([7.0, 8.0, 9.0])
    np.testing.assert_array_equal(
        dr_tpu.to_numpy(dv),
        [0, 0, 7, 8, 9, 0, 0, 0, 0, 0])


def test_small_vector_many_shards():
    # n < nprocs: trailing shards hold no logical elements
    dv = dr_tpu.distributed_vector(3)
    segs = dr_tpu.segments(dv)
    assert sum(len(s) for s in segs) == 3
    dr_tpu.iota(dv, 1)
    np.testing.assert_array_equal(dr_tpu.to_numpy(dv), [1, 2, 3])


def test_int_dtype(oracle):
    dv = dr_tpu.distributed_vector(12, dtype=int)
    dr_tpu.iota(dv, 0)
    assert dr_tpu.to_numpy(dv).dtype == np.int32
    oracle.check_segments(dv)


def test_get_put_reject_out_of_range(mesh_size):
    import pytest
    v = dr_tpu.distributed_vector(10, np.float32)
    dr_tpu.iota(v, 0)
    # numpy-convention negatives are fine
    np.testing.assert_allclose(np.asarray(v.get([-1, -10])), [9.0, 0.0])
    # out-of-range must raise, not wrap (round-1 wrapped % n silently)
    with pytest.raises(IndexError):
        v.get([10])
    with pytest.raises(IndexError):
        v.get([0, 5, -11])
    with pytest.raises(IndexError):
        v.put([12], [1.0])
    # state unchanged after the failed put
    np.testing.assert_allclose(dr_tpu.to_numpy(v), np.arange(10.0))
